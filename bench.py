"""Benchmark: the integrated device mutation pipeline vs CPU baseline.

The flagship number is the INTEGRATED rate: corpus tensors resident on
device -> batched mutation kernel -> sparse-delta transfer -> vectorized
host assembly -> executor-ready exec wire bytes (ops/pipeline.py — the
path fuzzer/proc.py actually drains).  The CPU baseline is the
reference-equivalent loop: clone + weighted-op mutate + serialize to the
same exec wire format (the tools/syz-mutate analog, BASELINE.md config
#1), implemented in this repo's models/ — there is no Go toolchain in
the image, so the divisor is our own CPU reference implementation, not
the reference's Go binary (see "note" in the output).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Modes:
  python bench.py            # flagship (pipeline + kernel + CPU baseline)
  python bench.py --ab 20    # A/B: new edges on sim kernel, engine on/off
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

# Measurement journal: every successful bench appends one line here so
# "last healthy" claims are always backed by a recorded artifact
# (reference analog: syz-manager -bench snapshot files,
# /root/reference/syz-manager/manager.go:299-333).
JOURNAL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_HISTORY.jsonl")


def _git_rev() -> str:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, timeout=10,
                             cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def journal_append(entry: dict) -> None:
    entry = dict(entry)
    entry.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%S%z"))
    entry.setdefault("git_rev", _git_rev())
    try:
        with open(JOURNAL, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass  # journaling must never fail the bench itself


def journal_last_healthy() -> Optional[dict]:
    """Most recent journal entry with a positive flagship value."""
    try:
        with open(JOURNAL) as f:
            lines = f.readlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            e = json.loads(line)
        except ValueError:
            continue
        if e.get("metric") == "exec_ready_mutants_per_sec_per_chip" \
                and e.get("value", 0) > 0 and not e.get("platform"):
            # platform-pinned (CPU) runs are not accelerator numbers
            return e
    return None


def _seed_programs(target, n, length=8, seed0=42):
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    return [generate_prog(target, RandGen(target, seed0 + i), length)
            for i in range(n)]


def bench_pipeline(batch_size=2048, seconds=8.0, capacity=1024,
                   seeds=64) -> float:
    """End-to-end exec-ready mutants/sec off the DevicePipeline."""
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity, batch_size=batch_size,
                        seed=0)
    added, i = 0, 0
    while added < seeds and i < seeds * 8:
        if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
            added += 1
        i += 1
    assert added > 0, "no seed programs tensorized"
    try:
        # Warmup: compile + both carried signatures.
        pl.next_batch(timeout=600)
        pl.next_batch(timeout=600)
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            n += len(pl.next_batch(timeout=600))
        dt = time.time() - t0
    finally:
        pl.stop()
    return n / dt


def bench_device_kernel(batch_size=512, edges_per_prog=128,
                        steps=20) -> float:
    """The fused mutate+triage kernel alone (device steady state)."""
    import jax
    import jax.numpy as jnp
    from jax import random

    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.ops.mutate import _mutate_one
    from syzkaller_tpu.ops.tensor import (
        FlagTables, TensorConfig, encode_prog, stack_batch)

    target = get_target("test", "64")
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = []
    i = 0
    while len(tensors) < batch_size:
        p = _seed_programs(target, 1, seed0=42 + i)[0]
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    batch = {k: jnp.asarray(v) for k, v in stack_batch(tensors).items()}
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    plane = dsig.new_plane()

    def step(batch, plane, key):
        b = batch["kind"].shape[0]
        k1, k2 = random.split(key)
        keys = random.split(k1, b)
        mutated = jax.vmap(
            lambda st, k: _mutate_one(st, k, fv, fc, 4))(batch, keys)
        edges = random.bits(k2, (b, edges_per_prog), dtype=jnp.uint32)
        nedges = jnp.full((b,), edges_per_prog, dtype=jnp.int32)
        prios = jnp.full((b,), 2, dtype=jnp.uint8)
        new_mask, counts = dsig.diff_batch(plane, edges, nedges, prios)
        plane = dsig.merge(plane, edges, nedges, prios, counts > 0)
        # Strip the keys _mutate_one adds so the carried batch keeps a
        # stable jit signature (r2's 30x "regression" was exactly this:
        # 'touched' leaked into step 2's input and recompiled inside
        # the timed loop).
        mutated.pop("preserve_sizes", None)
        mutated.pop("touched", None)
        return mutated, plane, counts

    step = jax.jit(step)
    key = random.key(0)
    # Warm BOTH call signatures: the fresh batch and the carried one.
    for _ in range(2):
        key, sub = random.split(key)
        batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    t0 = time.time()
    for _ in range(steps):
        key, sub = random.split(key)
        batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    dt = time.time() - t0
    return batch_size * steps / dt


def bench_cpu(seconds=3.0) -> float:
    """Reference-equivalent CPU loop: clone + weighted-op mutate +
    exec-wire serialization per mutant (tools/syz-mutate analog;
    reference: syz-fuzzer/proc.go:92-95 + prog/encodingexec.go:57)."""
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.mutation import mutate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target

    target = get_target("test", "64")
    rng = RandGen(target, 7)
    corpus = _seed_programs(target, 16, seed0=0)
    n = 0
    t0 = time.time()
    while time.time() - t0 < seconds:
        p = corpus[n % len(corpus)].clone()
        mutate_prog(p, rng, 30, corpus=corpus)
        try:
            serialize_for_exec(p)
        except Exception:
            pass  # oversized mutants count as attempted work
        n += 1
    return n / (time.time() - t0)


def bench_ab_edges(seconds=20.0) -> dict:
    """A/B per BASELINE.md metric #2: new-coverage edges discovered on
    the sim-kernel executor in equal wall time, device engine on vs
    off (single proc, same seed corpus)."""
    import threading

    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, Proc, WorkQueue
    from syzkaller_tpu.ipc.env import make_env
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    def run(engine_on: bool) -> tuple[int, int]:
        target = get_target("test", "64")
        cfg = FuzzerConfig(program_length=8, generate_period=100,
                           smash_mutants=5, fault_nth_max=3,
                           minimize_attempts=1)
        fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
        for i, p in enumerate(_seed_programs(target, 16, length=6)):
            fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
        mutator = None
        pl = None
        if engine_on:
            from syzkaller_tpu.fuzzer.proc import PipelineMutator
            from syzkaller_tpu.ops.pipeline import DevicePipeline

            pl = DevicePipeline(target, capacity=256, batch_size=256)
            mutator = PipelineMutator(pl, drain_timeout=120.0)
            mutator._sync_corpus(fuzzer)
            # Warm up compile + caches OUTSIDE the timed window.
            pl.next_batch(timeout=600)
            pl.next_batch(timeout=600)
        env = make_env(pid=0, sim=True, signal=True)
        proc = Proc(fuzzer, pid=0, env=env, mutator=mutator)
        stop = threading.Event()
        t = threading.Thread(target=proc.loop, args=(1 << 62,),
                             kwargs={"stop": stop}, daemon=True)
        t.start()
        time.sleep(seconds)
        stop.set()
        if pl is not None:
            pl.stop()  # wakes a proc blocked in pipeline.next()
        t.join(timeout=60)
        assert not t.is_alive(), "A/B proc thread leaked into next run"
        env.close()
        return len(fuzzer.max_signal), fuzzer.exec_count()

    edges_on, execs_on = run(True)
    edges_off, execs_off = run(False)
    return {"seconds": seconds,
            "engine_on": {"edges": edges_on, "execs": execs_on},
            "engine_off": {"edges": edges_off, "execs": execs_off}}


def device_preflight(timeout_s: float = 180.0, attempts: int = 2,
                     backoff_s: float = 20.0) -> Optional[str]:
    """Probe the accelerator in a SUBPROCESS with a hard timeout.

    The tunneled TPU backend can wedge in a state where every jax op
    (even jnp.ones) blocks forever; probing in-process would hang the
    whole bench.  Each attempt re-initializes the backend in a fresh
    subprocess (the wedge is per-process in the common case), so the
    retry doubles as a recovery attempt.  Returns None if healthy,
    else the reason string of the last failed attempt."""
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((64, 64));"
            "print('OK', float((x @ x).sum()))")
    reason = "no probe attempts made"
    for i in range(max(1, attempts)):
        if i:
            time.sleep(backoff_s)
        try:
            res = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired:
            reason = (f"device probe timed out after {timeout_s:.0f}s "
                      f"on attempt {i + 1}/{attempts} "
                      f"(tunneled backend wedged?)")
            continue
        if res.returncode != 0 or "OK" not in res.stdout:
            reason = (f"device probe failed (attempt {i + 1}/{attempts}): "
                      f"{res.stderr.strip()[-300:]}")
            continue
        return None
    return reason


def main() -> None:
    argv = sys.argv[1:]
    # TZ_BENCH_PLATFORM (or the shared TZ_JAX_PLATFORM) pins jax to a
    # working backend — used to record functional A/B artifacts while
    # the tunneled device is wedged.  Results are labeled with the
    # platform.
    from syzkaller_tpu.utils.jaxenv import pin_jax_platform

    platform = pin_jax_platform(os.environ.get("TZ_BENCH_PLATFORM", ""))
    if platform:
        # a pinned platform states the intent explicitly — probing the
        # (possibly wedged) default accelerator would be wrong and slow
        if "--no-preflight" not in argv:
            argv.insert(0, "--no-preflight")
    if "--no-preflight" not in argv:
        reason = device_preflight(
            timeout_s=float(os.environ.get("TZ_BENCH_PREFLIGHT_TIMEOUT",
                                           "180")),
            attempts=int(os.environ.get("TZ_BENCH_PREFLIGHT_ATTEMPTS", "2")))
        if reason is not None:
            result = {
                "metric": "exec_ready_mutants_per_sec_per_chip",
                "value": 0,
                "unit": "mutants/sec",
                "vs_baseline": 0,
                "error": reason,
            }
            last = journal_last_healthy()
            if last is not None:
                result["last_healthy"] = {
                    "ts": last.get("ts"), "git_rev": last.get("git_rev"),
                    "value": last.get("value"),
                    "vs_baseline": last.get("vs_baseline"),
                    "sub": last.get("sub"),
                }
                result["note"] = ("accelerator unreachable at bench time; "
                                  "last_healthy is read from "
                                  "BENCH_HISTORY.jsonl (recorded artifact)")
            else:
                result["note"] = ("accelerator unreachable at bench time; "
                                  "no recorded healthy measurement in "
                                  "BENCH_HISTORY.jsonl")
            print(json.dumps(result))
            return
    if "--ab" in argv:
        secs = float(argv[argv.index("--ab") + 1]) \
            if len(argv) > argv.index("--ab") + 1 else 20.0
        res = bench_ab_edges(secs)
        res["metric"] = "new_edges_sim_kernel_ab"
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    batch = int(argv[argv.index("--batch") + 1]) \
        if "--batch" in argv else 2048
    secs = float(argv[argv.index("--seconds") + 1]) \
        if "--seconds" in argv else 8.0
    pipe_rate = bench_pipeline(batch_size=batch, seconds=secs)
    kernel_rate = bench_device_kernel()
    cpu_rate = bench_cpu()
    result = {
        "metric": "exec_ready_mutants_per_sec_per_chip",
        "value": round(pipe_rate, 1),
        "unit": "mutants/sec",
        "vs_baseline": round(pipe_rate / cpu_rate, 2),
        "sub": {
            "device_kernel_mutations_per_sec": round(kernel_rate, 1),
            "cpu_baseline_mutants_per_sec": round(cpu_rate, 1),
            "pipeline_batch": batch,
        },
        "note": ("value = integrated corpus-tensor->exec-bytes rate off "
                 "ops/pipeline.DevicePipeline (the path fuzzer/proc.py "
                 "drains). baseline divisor = this repo's CPU reference "
                 "loop (clone+mutate+serialize_for_exec); no Go "
                 "toolchain in the image to run the reference's own "
                 "tools/syz-mutate."),
    }
    if platform:
        result["platform"] = platform
    journal_append(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
