"""Benchmark: the integrated device mutation pipeline vs CPU baseline.

The flagship number is the INTEGRATED rate: corpus tensors resident on
device -> batched mutation kernel -> sparse-delta transfer -> vectorized
host assembly -> executor-ready exec wire bytes (ops/pipeline.py — the
path fuzzer/proc.py actually drains).  The CPU baseline is the
reference-equivalent loop: clone + weighted-op mutate + serialize to the
same exec wire format (the tools/syz-mutate analog, BASELINE.md config
#1), implemented in this repo's models/ — there is no Go toolchain in
the image, so the divisor is our own CPU reference implementation, not
the reference's Go binary (see "note" in the output).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Modes:
  python bench.py                  # flagship (pipeline + kernel + CPU
                                   # baseline + host-assembly sub-bench)
  python bench.py --ab 20          # A/B: new edges on sim kernel,
                                   # engine on/off
  python bench.py --host-assembly  # drain->exec-ready stage only:
                                   # pooled arena path vs single-thread
                                   # per-mutant reference
  python bench.py --triage         # batched device-plane novelty
                                   # triage vs the CPU Signal path
  python bench.py --profile        # per-kernel device ms/batch at the
                                   # flagship shape (mutate,
                                   # emit-compact, novel_any) — the
                                   # Pallas-rewrite baseline
  python bench.py --coverage       # coverage-intelligence analytics:
                                   # occupancy popcount + heat map +
                                   # drift audit cost at the full
                                   # plane shape, novelty-rate EWMA
  python bench.py --serve          # serving-plane composer overhead
                                   # (host-only): ms/batch scheduling
                                   # tax, tenants-per-chip break-even,
                                   # per-tenant novelty share
  python bench.py --accounting     # accounting & SLO plane (host-only):
                                   # device-time ledger metering tax
                                   # us/batch, conservation error, SLO
                                   # burn-evaluation us/tick
  python bench.py --device         # device residency observatory:
                                   # HBM-ledger handle-update tax
                                   # us/batch, full reconcile ms at the
                                   # 64 MB plane shape, headroom
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

# Measurement journal: every successful bench appends one line here so
# "last healthy" claims are always backed by a recorded artifact
# (reference analog: syz-manager -bench snapshot files,
# /root/reference/syz-manager/manager.go:299-333).
JOURNAL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_HISTORY.jsonl")


def dump_telemetry() -> None:
    """Write the process telemetry snapshot where TZ_TELEMETRY_SNAPSHOT
    points (set by tools/bench_watch): per-phase latency percentiles +
    breaker/watchdog transition timelines for its wedge diagnostics.
    Called after each warmup batch, not just at exit — a wedged attempt
    is killed by the watcher's outer timeout, and the last mid-run dump
    is exactly the evidence the diagnosis needs."""
    path = os.environ.get("TZ_TELEMETRY_SNAPSHOT")
    if not path:
        return
    try:
        from syzkaller_tpu import telemetry

        telemetry.dump_snapshot(path)
    except Exception:
        pass  # diagnostics must never fail a measurement


def _git_rev() -> str:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, timeout=10,
                             cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def journal_append(entry: dict) -> None:
    entry = dict(entry)
    entry.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%S%z"))
    entry.setdefault("git_rev", _git_rev())
    try:
        with open(JOURNAL, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass  # journaling must never fail the bench itself


#: Flagship metric names across rounds.  r1/r2 recorded under the old
#: name; the journal holds those as reconstructed entries (ask r4#10).
FLAGSHIP_METRICS = ("exec_ready_mutants_per_sec_per_chip",
                    "mutations_triaged_per_sec_per_chip")


def journal_last_healthy() -> Optional[dict]:
    """Most recent on-chip journal entry with a positive flagship value.

    Excludes platform-pinned (CPU) runs and entries flagged as harness
    artifacts; reconstructed entries ARE eligible (they carry their
    'reconstructed'/'provenance' flags through to the caller so the
    wedge note can label them) — the journal is the single perf
    history, never a constant in this file.
    """
    try:
        with open(JOURNAL) as f:
            lines = f.readlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            e = json.loads(line)
        except ValueError:
            continue
        if e.get("metric") in FLAGSHIP_METRICS \
                and e.get("value", 0) > 0 and not e.get("platform") \
                and not e.get("harness_artifact"):
            # platform-pinned (CPU) runs are not accelerator numbers
            return e
    return None


def _seed_programs(target, n, length=8, seed0=42):
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    return [generate_prog(target, RandGen(target, seed0 + i), length)
            for i in range(n)]



#: Shared pipeline shape for the flagship bench AND the A/B engine:
#: the jit signature (ring capacity x batch) must be identical so the
#: A/B can load the flagship's persistently-cached executable when the
#: tunnel's remote-compile service is down (r5 failure mode:
#: UNAVAILABLE on fresh compiles only).
PIPE_CAPACITY = 1024
# 4096 (was 2048): the Pallas mutation core + fused plane drain
# (ISSUE 10) moved the per-mutant device cost enough that the larger
# batch amortizes dispatch without starving the assembly pool — the
# DepthController ceiling and staging-arena buckets scale with it
# (ops/pipeline, ops/staging).  TZ_PIPELINE_BATCH overrides at run
# time without re-editing the flagship shape.
PIPE_BATCH = 4096

def bench_pipeline(batch_size=PIPE_BATCH, seconds=8.0,
                   capacity=PIPE_CAPACITY,
                   seeds=64, sub_out: Optional[dict] = None) -> float:
    """End-to-end exec-ready mutants/sec off the DevicePipeline.

    When `sub_out` is a dict, drops the run's transfer sub-metrics
    into it (d2h_bytes_per_batch — the compacted device->host cost
    the wedge diagnostics track)."""
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity, batch_size=batch_size,
                        seed=0)
    added, i = 0, 0
    while added < seeds and i < seeds * 8:
        if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
            added += 1
        i += 1
    assert added > 0, "no seed programs tensorized"
    try:
        # Warmup: compile + both carried signatures, then keep draining
        # until two consecutive batches arrive fast — the timed window
        # must start in steady state (a cold tunnel compile bleeding
        # into it produced the r5 139-mutants/s artifact).
        # 5s separates steady state (~0.4s on-chip, ~2.2s CPU-pinned
        # at batch 2048) from a tunnel compile (~2min) on both
        # platforms this bench runs on.
        # The first wait doubles as the pool-lease catch window on the
        # tunneled backend (BENCH_WEDGE_DIAGNOSIS.md): the plugin's
        # client retries in a sleep loop until the far side grants a
        # session, so a generous first-batch timeout converts a
        # mid-window grant into a measurement instead of a failure.
        from syzkaller_tpu.health import env_float

        warmup_to = env_float("TZ_BENCH_WARMUP_TIMEOUT_S", 600.0)
        fast = 0
        for attempt in range(12):
            tw = time.time()
            pl.next_batch(timeout=warmup_to if attempt == 0 else 600)
            fast = fast + 1 if time.time() - tw < 5.0 else 0
            dump_telemetry()
            if fast >= 2:
                break
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            n += len(pl.next_batch(timeout=600))
        dt = time.time() - t0
        if sub_out is not None and pl.stats.d2h_batches:
            sub_out["d2h_bytes_per_batch"] = round(
                pl.stats.d2h_bytes / pl.stats.d2h_batches, 1)
        if sub_out is not None:
            # The realized drain->assemble overlap depth at the end of
            # the run (auto: wherever the DepthController settled;
            # pinned: the TZ_ASSEMBLE_DEPTH value).
            sub_out["assemble_depth_effective"] = pl._assemble_depth
            # Mutation-core shape (ISSUE 10): which backend ran, and
            # what fraction of emitted rows the mutant plane let
            # through (1.0 = every row novel; lower = dedup working).
            sub_out["mutate_backend"] = pl._backend
            if pl.stats.fused_batches:
                sub_out["fused_novel_frac"] = round(
                    pl.stats.fused_novel_rows
                    / (pl.stats.fused_batches * pl.batch_size), 4)
    finally:
        pl.stop()
        dump_telemetry()
    return n / dt


def bench_host_assembly(batch_size=PIPE_BATCH, capacity=PIPE_CAPACITY,
                        seeds=64, repeats=6) -> dict:
    """Host-assembly throughput on one drained batch, three numbers:

      - host_assemble_mutants_per_sec: the vectorized one-pass stream
        assemblers (emit.assemble_batch_table + splice_batch_table) —
        delta rows -> exec wire streams, like-for-like with
      - host_assemble_single_thread_mutants_per_sec: the per-mutant
        reference (assemble_delta + splice_insert row by row), same
        rows, same output streams,
      - host_assemble_pipeline_mutants_per_sec: the full production
        _assemble stage (sharding, pool, ExecMutant wrapping, stats) —
        what the worker actually sustains.

    Uses the flagship jit signature so a warm persistent compilation
    cache serves the launch; the worker thread never starts — the
    batch is launched and fetched inline, then assembled repeatedly
    on the host, so the numbers isolate the drain->exec-ready stage."""
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW, OP_INSERT
    from syzkaller_tpu.ops.emit import (
        DonorBankTable, assemble_batch_table, assemble_delta,
        splice_batch_table, splice_insert)
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity, batch_size=batch_size,
                        seed=0)
    added, i = 0, 0
    while added < seeds and i < seeds * 8:
        if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
            added += 1
        i += 1
    assert added > 0, "no seed programs tensorized"
    try:
        batch, tmpl, ets = pl._fetch(pl._launch())
        ok = (batch.flags & FLAG_OVERFLOW) == 0
        ok &= (batch.template_idx >= 0) & (batch.template_idx < len(tmpl))
        is_ins = batch.op == OP_INSERT
        import numpy as np

        js = np.flatnonzero(ok & ~is_ins)
        ins = np.flatnonzero(ok & is_ins)
        table = pl._template_table(ets)
        dtab = DonorBankTable(pl.bank.blocks)

        # Single-thread per-mutant reference.
        t0 = time.perf_counter()
        n_ref = 0
        for _ in range(repeats):
            for j in js:
                et = ets[int(batch.template_idx[j])]
                if et is None:
                    continue
                assemble_delta(et, batch, int(j))
                n_ref += 1
            for j in ins:
                i = int(batch.template_idx[j])
                et = ets[i]
                d = int(batch.donor[j])
                if et is None or not (0 <= d < len(pl.bank.blocks)):
                    continue
                splice_insert(et, batch.call_alive(j, max(et.ncalls, 1)),
                              pl.bank.blocks[d], int(batch.pos[j]))
                n_ref += 1
        ref_dt = time.perf_counter() - t0

        # The vectorized one-pass stream assemblers, same rows.
        t0 = time.perf_counter()
        n_fast = 0
        for _ in range(repeats):
            n_fast += sum(d is not None
                          for d in assemble_batch_table(table, batch, js))
            datas, fast_mask = splice_batch_table(table, dtab, batch, ins)
            n_fast += sum(d is not None for d in datas)
            # Rows outside the fast conditions go per-mutant, exactly
            # as the production path routes them.
            for j in ins[~fast_mask]:
                i = int(batch.template_idx[j])
                et = ets[i]
                d = int(batch.donor[j])
                if et is None or not (0 <= d < len(pl.bank.blocks)):
                    continue
                if splice_insert(
                        et, batch.call_alive(j, max(et.ncalls, 1)),
                        pl.bank.blocks[d], int(batch.pos[j])) is not None:
                    n_fast += 1
        fast_dt = time.perf_counter() - t0

        # The full production stage (pool + ExecMutant wrapping).
        t0 = time.perf_counter()
        n_pipe = 0
        for _ in range(repeats):
            n_pipe += len(pl._assemble(batch, tmpl, ets))
        pipe_dt = time.perf_counter() - t0
    finally:
        pl.stop()
    fast = n_fast / fast_dt if fast_dt else 0.0
    ref = n_ref / ref_dt if ref_dt else 0.0
    pipe = n_pipe / pipe_dt if pipe_dt else 0.0
    return {
        "host_assemble_mutants_per_sec": round(fast, 1),
        "host_assemble_single_thread_mutants_per_sec": round(ref, 1),
        "host_assemble_speedup_x": round(fast / ref, 2) if ref else None,
        "host_assemble_pipeline_mutants_per_sec": round(pipe, 1),
        "assemble_workers": pl._assemble_workers,
        "d2h_bytes_per_batch": round(
            pl.stats.d2h_bytes / max(1, pl.stats.d2h_batches), 1),
    }


def bench_triage(calls_per_check=512, edges_per_call=64, checks=80,
                 novel_every=20, seen_edges=1 << 16) -> dict:
    """Batched device-plane novelty triage vs the CPU reference.

    Replays the SAME synthetic signal stream through two Fuzzers: one
    with the TriageEngine (staged batches -> padded diff_batch against
    the device plane -> exact CPU confirm only for flagged calls) and
    one on the pure-CPU path (per-call Signal.diff_raw under the
    fuzzer lock — today's shape).  The stream models the production
    distribution: a pre-merged max_signal of `seen_edges` edges, most
    checks carrying nothing new, every `novel_every`-th check
    injecting fresh edges.  `triage_calls_per_sec` /
    `triage_cpu_calls_per_sec` are the two rates;
    `triage_plane_hit_rate` is the fraction of calls that needed a
    CPU confirm (the lock-free fast path is its complement).

    The engine runs at the production batch shape (B = half a check,
    so every check flushes two chunks through the transfer plane):
    `triage_h2d_overlap_frac` is the fraction of device batches whose
    upload flew while the previous batch's verdicts were still in
    flight (0 at TZ_TRIAGE_DISPATCH_DEPTH=1 — the serial fallback),
    and `triage_h2d_host_ms_per_batch` is the flush leader's measured
    staging+upload cost per batch (the `triage.h2d_wait` span — the
    pinned-arena number the ROADMAP's ~0.1 ms/batch re-pad item is
    judged by)."""
    import numpy as np

    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.triage import TriageEngine

    class _Info:
        __slots__ = ("call_index", "errno", "signal")

        def __init__(self, call_index, signal):
            self.call_index = call_index
            self.errno = 0
            self.signal = signal

    target = get_target("test", "64")
    rng = np.random.RandomState(11)
    pool = rng.randint(0, 1 << 32, size=seen_edges, dtype=np.uint32)
    base = Signal(dict.fromkeys(np.unique(pool).tolist(), 3))
    fresh_iter = iter(
        rng.randint(0, 1 << 32, size=checks * 8, dtype=np.uint32)
        .tolist())
    stream = []
    for i in range(checks):
        infos = []
        for c in range(calls_per_check):
            edges = pool[rng.randint(0, seen_edges, size=edges_per_call)]
            if i % novel_every == 0 and c == 0:
                edges = edges.copy()
                edges[:4] = [next(fresh_iter) for _ in range(4)]
            infos.append(_Info(c, edges))
        stream.append(infos)

    def prio_fn(_errno, _idx):
        return 3

    fz_dev = Fuzzer(target, wq=WorkQueue())
    eng = TriageEngine(batch=max(8, calls_per_check // 2),
                       max_edges=edges_per_call)
    fz_dev.set_triage(eng)
    fz_cpu = Fuzzer(target, wq=WorkQueue())
    fz_dev.add_max_signal(base.copy())
    fz_cpu.add_max_signal(base.copy())
    # Warmup outside the timed window: the plane upload + the jit
    # compiles of diff_batch/merge at the pinned (B, E) shape.
    fz_dev.check_new_signal_fn(prio_fn, stream[0])
    fz_cpu.check_new_signal_fn(prio_fn, stream[0])

    from syzkaller_tpu import telemetry

    h2d_hist = telemetry.REGISTRY.histogram(
        telemetry.span_metric_name("triage.h2d_wait"))
    h2d0 = (h2d_hist.count, h2d_hist.sum)
    batches0 = eng.stats.device_batches
    overlaps0 = eng.stats.h2d_overlaps
    t0 = time.perf_counter()
    for infos in stream[1:]:
        fz_dev.check_new_signal_fn(prio_fn, infos)
    dev_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for infos in stream[1:]:
        fz_cpu.check_new_signal_fn(prio_fn, infos)
    cpu_dt = time.perf_counter() - t0
    ncalls = (checks - 1) * calls_per_check
    dev_rate = ncalls / dev_dt if dev_dt else 0.0
    cpu_rate = ncalls / cpu_dt if cpu_dt else 0.0
    # The flush-leader staging micro-comparison (the ROADMAP
    # "pinned staging buffer ~0.1 ms/batch" item, measured on this
    # host): time padding one full B-row batch the legacy way (fresh
    # np.zeros + ragged scatter per flush) vs the transfer-plane way
    # (in-place writes into a persistent arena slot).  Runs at the
    # PRODUCTION batch shape (256, 512) — the shape the ROADMAP claim
    # was made for — not the bench's smaller edge budget.
    from syzkaller_tpu.ops.staging import StagingArena

    B, E = 256, 512
    chunk = [pool[rng.randint(0, seen_edges, size=edges_per_call)]
             for _ in range(B)]
    lens_l = np.array([c.size for c in chunk], dtype=np.int32)
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        edges = np.zeros((B, E), dtype=np.uint32)
        edges[np.arange(E)[None, :] < lens_l[:, None]] = \
            np.concatenate(chunk)
        nedges = np.zeros(B, dtype=np.int32)
        nedges[:] = lens_l
        prios = np.zeros(B, dtype=np.uint8)
        prios[:] = 3
    legacy_ms = 1e3 * (time.perf_counter() - t0) / reps
    arena = StagingArena(slots=2)
    cols = np.arange(E, dtype=np.int32)
    t0 = time.perf_counter()
    for _ in range(reps):
        bufs = arena.acquire(B, {
            "edges": ((B, E), np.uint32), "nedges": ((B,), np.int32),
            "prios": ((B,), np.uint8), "mask": ((B, E), np.bool_),
            "flat": ((B * E,), np.uint32)})
        bufs["nedges"][:] = lens_l
        bufs["prios"][:] = 3
        total = int(lens_l.sum())
        np.less(cols[None, :], lens_l[:, None], out=bufs["mask"])
        np.concatenate(chunk, out=bufs["flat"][:total])
        bufs["edges"][bufs["mask"]] = bufs["flat"][:total]
    staged_ms = 1e3 * (time.perf_counter() - t0) / reps

    s = eng.stats
    checked = s.plane_hits + s.plane_misses
    timed_batches = s.device_batches - batches0
    h2d_n = h2d_hist.count - h2d0[0]
    h2d_ms = (1e3 * (h2d_hist.sum - h2d0[1]) / h2d_n) if h2d_n else None
    return {
        "triage_calls_per_sec": round(dev_rate, 1),
        "triage_cpu_calls_per_sec": round(cpu_rate, 1),
        "triage_speedup_x": round(dev_rate / cpu_rate, 2)
        if cpu_rate else None,
        "triage_plane_hit_rate": round(s.plane_hits / checked, 4)
        if checked else None,
        "triage_h2d_overlap_frac": round(
            (s.h2d_overlaps - overlaps0) / timed_batches, 4)
        if timed_batches else None,
        "triage_h2d_host_ms_per_batch": round(h2d_ms, 4)
        if h2d_ms is not None else None,
        "triage_stage_ms_per_batch": round(staged_ms, 4),
        "triage_stage_legacy_repad_ms_per_batch": round(legacy_ms, 4),
        "triage_dispatch_depth": eng._dispatch_depth,
        "triage_fold_fn_rate_est": round(
            eng.snapshot()["fold_false_negative_rate"], 6),
        # Fold false negatives are possible on full 32-bit streams;
        # report the realized divergence instead of asserting it away.
        "triage_parity_max_signal": len(fz_dev.max_signal)
        == len(fz_cpu.max_signal),
    }


def bench_hints(batch=4096, maps=64, keys_per_map=24,
                vals_per_key=4, reps=5) -> dict:
    """The batched hints lane vs the per-program host path (ISSUE 19).

    Builds `maps` random comp maps (the fleet's staged TRACE_CMP
    tables) and `batch` candidate comparison windows spread across
    them, then expands the same workload two ways: the per-program
    reference (`shrink_expand` per window against its own CompMap —
    today's smash-phase hint pass, one map at a time) and the fused
    stacked kernel (ops/hints.stacked_shrink_expand_kernel — every
    map's tables stacked into one padded device batch, the shape the
    HintLane flush leader dispatches).  `hints_speedup_x` is the
    CPU-measured ratio at the production batch shape;
    `hint_mutants_per_sec` the fused path's replacer throughput;
    `hints_staged_comps_bytes_per_batch` the H2D bill for the stacked
    tables + value/map_of columns; `hints_sim_suppressed_frac` the
    fraction of replacers the lane's speculation fold would suppress
    on this stream (ops/hintlane.fold_suppress over a cold plane —
    the steady-state duplicate rate across call sites)."""
    import numpy as np

    from syzkaller_tpu.models.hints import CompMap, shrink_expand
    from syzkaller_tpu.ops.delta import pow2_rows
    from syzkaller_tpu.ops.hintlane import fold_suppress
    from syzkaller_tpu.ops.hints import (DeviceCompMap,
                                         shrink_expand_batch_stacked,
                                         stack_comp_maps)

    rng = np.random.RandomState(19)
    cms, dmaps = [], []
    for _ in range(maps):
        cm = CompMap()
        for _ in range(keys_per_map):
            k = int(rng.randint(0, 1 << 62))
            for _ in range(rng.randint(1, vals_per_key + 1)):
                cm.add_comp(k, int(rng.randint(0, 1 << 62)))
        cms.append(cm)
        dmaps.append(DeviceCompMap.from_comp_map(cm))
    vals, map_of = [], []
    for j in range(batch):
        mi = j % maps
        keys = list(cms[mi].m.keys())
        # Half the windows hit a staged key (the productive case);
        # half are random misses (the common case).
        v = int(keys[int(rng.randint(len(keys)))]) \
            if rng.rand() < 0.5 else int(rng.randint(0, 1 << 62))
        vals.append(v)
        map_of.append(mi)

    # Per-program reference: one CompMap walk per window.
    t0 = time.perf_counter()
    host_out = [sorted(shrink_expand(v, cms[mi]))
                for v, mi in zip(vals, map_of)]
    host_s = time.perf_counter() - t0

    m = pow2_rows(maps, lo=4, hi=64)
    k = pow2_rows(max(len(d) for d in dmaps), lo=16, hi=512)
    tables = stack_comp_maps(dmaps, m, k)
    varr = np.array(vals, dtype=np.uint64)
    moar = np.array(map_of, dtype=np.int32)
    dev_out = shrink_expand_batch_stacked(varr, moar, tables)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        dev_out = shrink_expand_batch_stacked(varr, moar, tables)
    dev_s = (time.perf_counter() - t0) / reps
    assert dev_out == host_out, "fused hints diverged from host oracle"

    mutants = sum(len(lst) for lst in dev_out)
    staged = (sum(tables[f].nbytes for f in
                  ("keys", "nkeys", "vmat", "nvals"))
              + varr.nbytes + moar.nbytes)
    plane = np.zeros(1 << 16, dtype=np.uint8)
    _, suppressed = fold_suppress(dev_out, plane, salt=0)
    return {
        "hint_mutants_per_sec": round(mutants / dev_s, 1),
        "hints_host_mutants_per_sec": round(mutants / host_s, 1),
        "hints_speedup_x": round(host_s / dev_s, 2),
        "hints_batch": batch,
        "hints_maps": maps,
        "hints_mutants": mutants,
        "hints_staged_comps_bytes_per_batch": staged,
        "hints_sim_suppressed_frac": round(
            suppressed / max(1, mutants), 4),
        "hints_device_ms_per_batch": round(dev_s * 1e3, 3),
    }


def bench_coverage(seen_edges=1 << 18, reps=20, novel_checks=40,
                   edges_per_call=64) -> dict:
    """Coverage-intelligence analytics at the full plane shape
    (ISSUE 7, telemetry/coverage.py + ops/signal coverage kernels).

    Seeds a TriageEngine's plane with `seen_edges` random 32-bit
    edges, then measures the flush-cadence reductions where they run
    in production: `coverage_analytics_ms_per_flush` is one exact
    occupancy popcount + 256-region heat histogram over the
    uint8[2^26] plane (the per-interval cost the flush leader pays),
    `coverage_drift_audit_ms` adds the 64 MB mirror upload +
    xor/popcount drift audit.  A short novelty stream through the
    verdict path then reports the tracker-side sub-metrics: the
    novelty-rate EWMA and the stall verdict."""
    import numpy as np

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.triage import TriageEngine

    class _Info:
        __slots__ = ("call_index", "errno", "signal")

        def __init__(self, call_index, signal):
            self.call_index = call_index
            self.errno = 0
            self.signal = signal

    rng = np.random.RandomState(13)
    eng = TriageEngine(batch=64, max_edges=edges_per_call)
    eng._merge_edges(
        rng.randint(0, 1 << 32, size=seen_edges, dtype=np.uint32), 3)
    eng.share_plane()  # materialize the device plane
    eng.run_analytics(audit=True)  # compile both kernels (once)

    t0 = time.perf_counter()
    for _ in range(reps):
        r = eng.run_analytics()
    stats_ms = 1e3 * (time.perf_counter() - t0) / reps
    audit_reps = max(1, reps // 4)
    t0 = time.perf_counter()
    for _ in range(audit_reps):
        r = eng.run_analytics(audit=True)
    audit_ms = 1e3 * (time.perf_counter() - t0) / audit_reps

    # Tracker-side sub-metrics: replay a short stream through the
    # verdict path so the EWMA/attribution have production inputs.
    target = get_target("test", "64")
    fz = Fuzzer(target, wq=WorkQueue())
    fz.set_triage(eng)
    for i in range(novel_checks):
        sig = rng.randint(0, 1 << 32, size=edges_per_call,
                          dtype=np.uint32)
        fz.check_new_signal_fn(lambda _e, _i: 3,
                               [_Info(0, sig)], source="exploration")
    telemetry.COVERAGE.tick(force=True)  # fold the stream into the EWMA
    snap = telemetry.COVERAGE.snapshot()
    occ = r["occupancy"]
    regions = r["regions"]
    return {
        "coverage_plane_occupancy": int(occ),
        "coverage_occupancy_frac": round(occ / dsig.PLANE_SIZE, 6),
        "coverage_heat_regions_occupied":
            int(np.count_nonzero(regions))
            if regions is not None else None,
        "coverage_analytics_ms_per_flush": round(stats_ms, 3),
        "coverage_drift_audit_ms": round(audit_ms, 3),
        "coverage_drift_buckets": r["drift"],
        "coverage_novelty_rate_ewma":
            round(snap["novelty_rate_ewma"], 4),
        "coverage_novel_edges_total": snap["novel_edges_total"],
        "coverage_stalled": int(snap["stalled"]),
    }


def bench_serve(tenants=6, batches=60, batch_rows=4096,
                row_bytes=64, demand_rows=5020,
                supply_rate=8947.0) -> dict:
    """Serving-plane composer bench (ISSUE 12, serve/): host-only —
    the composer, broker, and per-tenant planes are pure host code,
    and what this measures is the SCHEDULING overhead the serving
    plane adds per fused batch, not the drain itself.

    `tenants` session tenants post a fixed per-poll demand
    (`demand_rows`, the ~5,020 execs/s per-VM demand artifact), a
    scripted host drain supplies random rows, and the composer fills
    `batches` batches.  Reports `serve_compose_overhead_ms_per_batch`
    (compose+distribute wall time minus the drain itself — the tax on
    the 8,947/s supply), the demand-side tenants-per-chip break-even
    (supply_rate / demand rate), and the per-tenant novelty share the
    QoS credits converged to (docs/perf.md "The serving plane")."""
    import numpy as np

    from syzkaller_tpu.serve import BatchComposer, ServePlane, TenantPlanes

    rng = np.random.RandomState(29)
    names = [f"vm{i}" for i in range(tenants)]
    broker = ServePlane(lease_s=3600.0, queue_cap=batch_rows * 4,
                        max_tenants=tenants)
    planes = TenantPlanes(bits=18)
    drain_s = [0.0]

    def drain(n):
        t0 = time.perf_counter()
        rows = rng.randint(0, 256, size=(n, row_bytes)).astype(np.uint8)
        arena = rows.tobytes()
        payloads = [memoryview(arena)[j * row_bytes:(j + 1) * row_bytes]
                    for j in range(n)]
        drain_s[0] += time.perf_counter() - t0
        return rows, payloads

    comp = BatchComposer(broker, planes, drain, batch_rows=batch_rows,
                         rebalance_s=0.0, stall_window_s=3600.0)
    seqs = {}
    for name in names:
        broker.Connect({"name": name})
        seqs[name] = 0

    def poll_all():
        # Keep demand fresh and queues drained so headroom never
        # throttles composition (the steady-state serving shape).
        for name in names:
            seqs[name] += 1
            broker.Poll({"name": name, "epoch": broker.epoch,
                         "seq": seqs[name], "ack_seq": seqs[name] - 1,
                         "demand": {"backlog": demand_rows,
                                    "exec_rate": supply_rate / tenants}})

    poll_all()
    comp.compose_once()  # warm the planes/gauges out of the timing
    poll_all()
    total_rows = 0
    novel_by_tenant = {name: 0 for name in names}
    drain_s[0] = 0.0
    t0 = time.perf_counter()
    for _ in range(batches):
        report = comp.compose_once()
        total_rows += report.get("rows", 0)
        for name, tr in (report.get("tenants") or {}).items():
            novel_by_tenant[name] += tr["novel"]
        poll_all()
    wall_s = time.perf_counter() - t0
    compose_ms = 1e3 * wall_s / batches
    overhead_ms = 1e3 * (wall_s - drain_s[0]) / batches
    total_novel = sum(novel_by_tenant.values()) or 1
    return {
        "serve_tenants": tenants,
        "serve_batches": batches,
        "serve_rows_total": total_rows,
        "serve_compose_ms_per_batch": round(compose_ms, 3),
        "serve_compose_overhead_ms_per_batch": round(overhead_ms, 3),
        "serve_rows_per_sec": round(total_rows / max(wall_s, 1e-9)),
        # Demand-side break-even: how many full-demand VMs one chip's
        # measured supply covers — the number continuous batching is
        # meant to raise by spending rows only where demand is.
        "serve_tenants_per_chip_full_demand":
            round(supply_rate / demand_rows, 2),
        "serve_novelty_share": {
            name: round(n / total_novel, 4)
            for name, n in sorted(novel_by_tenant.items())},
        "serve_credits": {
            name: round(t.credit, 4) for name, t in
            sorted(broker.tenants.items())},
    }


def bench_hub(managers=4, progs_per_manager=300, prog_bytes=160,
              shared_frac=0.6, sig_per_prog=24) -> dict:
    """Hub federation bench (ISSUE 16, hub/): host-only — measures
    what the plane-indexed novelty diff keeps off the wire.

    `managers` managers each contribute `progs_per_manager` programs;
    a `shared_frac` fraction exercise only shared-pool signal (the
    common kernel behaviors every pod member finds on its own), the
    rest carry manager-unique signal.  Each manager syncs against the
    same populated hub twice — once blind, once presenting the digest
    of its own corpus signal — and the delta is the reply bytes the
    digest predicted the receiver didn't need (plus the per-sync wall
    time, to show the diff costs host-side microseconds)."""
    import shutil
    import tempfile

    import numpy as np

    from syzkaller_tpu.hub.state import HubState

    rng = np.random.RandomState(31)
    # A small hot pool: the common kernel behaviors every manager
    # rediscovers — small enough that each corpus covers essentially
    # all of it, which is exactly when the digest diff pays.
    shared_pool = rng.randint(0, 1 << 31, size=256).astype(np.int64)

    def make_corpus(mi):
        progs, sigs = [], []
        unique = rng.randint(0, 1 << 31,
                             size=4096).astype(np.int64) + (mi << 40)
        for pi in range(progs_per_manager):
            body = rng.bytes(prog_bytes - 16)
            progs.append(b"m%02d-p%04d:" % (mi, pi) + body)
            pool = shared_pool if pi < shared_frac * progs_per_manager \
                else unique
            sigs.append([int(x) for x in
                         rng.choice(pool, size=sig_per_prog)])
        return progs, sigs

    corpora = [make_corpus(mi) for mi in range(managers)]
    results = {}
    for use_digest in (False, True):
        tmp = tempfile.mkdtemp(prefix="tz-bench-hub-")
        try:
            st = HubState(tmp, lease_s=3600.0)
            for mi, (progs, sigs) in enumerate(corpora):
                st.connect(f"m{mi}", True, progs, sigs=sigs)
            total_bytes = 0
            total_progs = 0
            wall_s = 0.0
            for mi, (_progs, sigs) in enumerate(corpora):
                digest = None
                if use_digest:
                    from syzkaller_tpu.ops.signal import (
                        digest_from_folds, fold_hash_np)
                    elems = np.asarray(
                        [e for s in sigs for e in s],
                        dtype=np.int64).astype(np.uint32)
                    digest = digest_from_folds(
                        fold_hash_np(elems), st.digest_bits)
                t0 = time.perf_counter()
                while True:
                    progs, _repros, more = st.sync(
                        f"m{mi}", [], [], [], False, digest=digest)
                    total_bytes += sum(len(p) for p in progs)
                    total_progs += len(progs)
                    if not more:
                        break
                wall_s += time.perf_counter() - t0
            results[use_digest] = {
                "bytes": total_bytes, "progs": total_progs,
                "sync_us": 1e6 * wall_s / managers,
                "skipped": st.digest_skipped_total,
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    blind, diffed = results[False], results[True]
    saved = blind["bytes"] - diffed["bytes"]
    return {
        "hub_managers": managers,
        "hub_progs_per_manager": progs_per_manager,
        "hub_shared_frac": shared_frac,
        "hub_reply_bytes_blind": blind["bytes"],
        "hub_reply_bytes_digest": diffed["bytes"],
        "hub_sync_saved_bytes": saved,
        "hub_sync_reply_bytes_saved_pct":
            round(100.0 * saved / max(blind["bytes"], 1), 2),
        "hub_digest_skipped_progs": diffed["skipped"],
        "hub_sync_us_blind": round(blind["sync_us"], 1),
        "hub_sync_us_digest": round(diffed["sync_us"], 1),
    }


def bench_accounting(batches=5000, tenants=3, lanes=3, shards=4,
                     ticks=2000) -> dict:
    """Accounting & SLO plane bench (ISSUE 14): host-only — the ledger
    and the burn-rate engine sit on the fused-drain hot path (every
    batch pays one `note_batch`, every analytics flush one SLO tick),
    so what this measures is that tax, not the drain.

    A private DeviceTimeLedger takes `batches` fully-attributed
    batches (tenant+lane+shard row maps, the worst-case split), then
    `batches` unattributed ones (the default-key fast path); a private
    SloEngine with an injected clock evaluates the full SLO table for
    `ticks` ticks.  Reports the per-batch metering tax in µs, the
    worst conservation error the split accumulated (the ≤1e-6
    invariant under load), and the per-tick burn-evaluation cost."""
    from syzkaller_tpu.telemetry.accounting import DeviceTimeLedger
    from syzkaller_tpu.telemetry.slo import SloEngine

    ledger = DeviceTimeLedger()
    tenant_rows = {f"vm{i}": 100 + 7 * i for i in range(tenants)}
    lane_rows = {"exploration": 64, "candidate": 96, "smash": 128}
    lane_rows = dict(list(lane_rows.items())[:lanes])
    shard_rows = {str(i): 1 for i in range(shards)}
    for name in tenant_rows:          # novelty so the yield EWMAs move
        ledger.note_novel("tenant", name, 3)

    t0 = time.perf_counter()
    for _ in range(batches):
        ledger.note_batch(0.004, tenant_rows=tenant_rows,
                          lane_rows=lane_rows, shard_rows=shard_rows)
    attributed_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(batches):
        ledger.note_batch(0.004)
    default_s = time.perf_counter() - t0

    clk = [1000.0]
    eng = SloEngine(time_fn=lambda: clk[0], fast_s=300.0, slow_s=3600.0,
                    interval_s=0.0, ledger=ledger)
    # Warm one tick out of the timing (lazy gauge/prev-state setup).
    eng.tick()
    t0 = time.perf_counter()
    for _ in range(ticks):
        clk[0] += 5.0
        eng.tick()
    tick_s = time.perf_counter() - t0

    return {
        "acct_batches": batches,
        "acct_keys": tenants + len(lane_rows) + shards,
        "acct_note_batch_us": round(1e6 * attributed_s / batches, 3),
        "acct_note_batch_default_us":
            round(1e6 * default_s / batches, 3),
        "acct_conservation_error": ledger.conservation_error(),
        "slo_objectives": len(eng.snapshot()["objectives"]),
        "slo_tick_us": round(1e6 * tick_s / ticks, 3),
    }


def bench_device(updates=20000, reconciles=20) -> dict:
    """Device residency observatory bench (ISSUE 17): the two costs
    the ledger adds to a running rig, measured on PRIVATE instances so
    nothing leaks into the process registry.

      - ledger tax: one `BufferHandle.update()` per drained batch is
        what the pipeline hot path pays (the mutant-plane handle swap
        in `_launch`).  Timed over `updates` update calls against a
        device-resident 64 MB plane — the acceptance bar is
        <= 50 us/batch, noise next to the ~ms-scale drain.
      - reconcile: the audit-cadence pass that sweeps every handle's
        weakrefs and id-matches them against the backend's live-buffer
        report.  Timed at the flagship residency shape (the 64 MB
        signal plane + the mutant plane registered alongside a crowd
        of small host buffers) with the REAL `jax.live_arrays()` set,
        so the ms number includes the backend enumeration cost.
    """
    import jax.numpy as jnp
    import numpy as np

    from syzkaller_tpu.telemetry.hbm import DeviceBufferLedger
    from syzkaller_tpu.telemetry.registry import Registry

    class _Flight:
        def dump(self, *a, **k):
            return None

    ledger = DeviceBufferLedger(registry=Registry(), flight=_Flight())
    plane = jnp.zeros(1 << 26, jnp.uint8)      # the 64 MB signal plane
    mplane = jnp.zeros(1 << 22, jnp.uint8)     # the mutant plane
    h_plane = ledger.register("pipeline", "plane", plane)
    ledger.register("triage", "plane", mplane)
    for i in range(8):                          # small-buffer crowd
        ledger.register("serve", f"t{i}",
                        np.zeros(1 << 16, np.uint8), device="host")

    h_plane.update(plane)                       # warm the label path
    t0 = time.perf_counter()
    for _ in range(updates):
        h_plane.update(plane)
    tax_s = time.perf_counter() - t0

    rec = ledger.reconcile()                    # warm (gauge setup)
    t0 = time.perf_counter()
    for _ in range(reconciles):
        rec = ledger.reconcile()
    rec_s = time.perf_counter() - t0

    return {
        "device_ledger_updates": updates,
        "device_ledger_tax_us": round(1e6 * tax_s / updates, 3),
        "device_reconcile_ms":
            round(1e3 * rec_s / max(1, reconciles), 3),
        "device_reconcile_entries": rec["entries"],
        "device_reconcile_drift_bytes": rec["drift_bytes"],
        "device_tracked_bytes": rec["tracked_bytes"],
        "device_headroom_gb":
            round(ledger.headroom() / (1 << 30), 3),
    }


def bench_profile(batch_size=PIPE_BATCH, capacity=PIPE_CAPACITY,
                  seeds=64, steps=10, rounds=4,
                  triage_batch=256, triage_edges=512) -> dict:
    """Per-kernel device-time attribution at the flagship shape
    (ISSUE 6; the measurement the ROADMAP's Pallas-rewrite item is
    judged by).  Two views of the same kernels:

      - isolated: each kernel dispatched alone on a warm pipeline and
        timed around block_until_ready — `mutate` is the vmapped
        mutation core by itself, `emit_compact` is the fused
        step's pack+compact-pool share (fused minus mutate), and
        `novel_any` is the triage predicate at the production
        (TZ_TRIAGE_BATCH, TZ_TRIAGE_MAX_EDGES) shape,
      - always_on: what the in-loop profiler (telemetry/profiler.py)
        attributed while the warmup batches ran — the EWMA gauges
        exported as `tz_device_kernel_ms_per_batch{kernel=...}`.
        Host-observed dispatch→ready latencies, so on an async
        backend they include queue + transfer residency; the isolated
        numbers are the pure-kernel baseline to subtract against.

    Fused-path sub-metrics (ISSUE 10) ride the same dict:
    device_kernel_mutations_per_sec (batch over the fused-step time —
    the ROADMAP north-star rate), fused_d2h_bytes_per_batch (wire
    bytes per drain with the mutant plane dropping non-novel rows on
    device), and mutate_backend (pallas | vmap as resolved).
    """
    import jax
    import numpy as np
    from jax import random

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity,
                        batch_size=batch_size, rounds=rounds, seed=0)
    added, i = 0, 0
    while added < seeds and i < seeds * 8:
        if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
            added += 1
        i += 1
    assert added > 0, "no seed programs tensorized"
    try:
        # Warm the integrated path INLINE (no worker thread competing
        # with the timed loops) — this also feeds the always-on
        # profiler, whose EWMAs are reported alongside.
        for _ in range(3):
            pl._drain(pl._launch())
        corpus, n, _tmpl, _ets, cumw, total = pl._flush_pending()
        fv, fc = pl._flags_dev
        key = random.key(123)

        def timed(fn, warm=2):
            for i in range(warm):
                jax.block_until_ready(fn(i))
            t0 = time.perf_counter()
            for i in range(steps):
                out = fn(warm + i)
            jax.block_until_ready(out)
            return 1e3 * (time.perf_counter() - t0) / steps

        # The full fused step: mutate + delta pack + compact pool,
        # plus (when TZ_PIPELINE_FUSED, the default) the mutant-plane
        # novelty mask and novel-row compaction — one dispatch.
        if pl._fused:
            from syzkaller_tpu.ops.signal import new_mutant_plane

            mplane = pl._mutant_plane if pl._mutant_plane is not None \
                else new_mutant_plane(pl._plane_bits)
            step_ms = timed(lambda i: pl._step(
                corpus, cumw, total, random.fold_in(key, i), fv, fc,
                mplane))
        else:
            step_ms = timed(lambda i: pl._step(
                corpus, cumw, total, random.fold_in(key, i), fv, fc))

        # The mutation core alone, on the same sampled batch, through
        # the backend the pipeline resolved (TZ_MUTATE_BACKEND):
        # Pallas grid kernels on TPU, the vmap fallback elsewhere.
        import jax.numpy as jnp

        from syzkaller_tpu.ops.mutate import make_mutator

        idx = (random.bits(random.key(7), (batch_size,),
                           dtype=jnp.uint32)
               % jnp.maximum(n, 1).astype(jnp.uint32)).astype(jnp.int32)
        batch = {k: v[idx] for k, v in corpus.items()}
        mutate_only = make_mutator(rounds, backend=pl._backend)

        mutate_ms = timed(lambda i: mutate_only(
            batch, random.fold_in(key, 1000 + i), fv, fc))

        # novel_any at the production triage shape.
        plane = dsig.new_plane()
        rng = np.random.RandomState(3)
        edges = rng.randint(0, 1 << 32, size=(triage_batch,
                                              triage_edges),
                            dtype=np.uint32)
        nedges = np.full(triage_batch, triage_edges, dtype=np.int32)
        prios = np.full(triage_batch, 3, dtype=np.uint8)
        ed, nd, pr = dsig.stage_batch(edges, nedges, prios)
        novel_ms = timed(lambda i: dsig.novel_any(plane, ed, nd, pr))

        # Per-shard kernel ms (ISSUE 11): the mutation core isolated
        # on EACH device in turn, so a straggling chip shows up as
        # its own `tz_mesh_shard_ms_per_batch{shard=...}` gauge —
        # the differentiated view the collective launch can't give
        # (it completes at the slowest chip's pace).
        shard_ms = {}
        devices = jax.devices()
        if len(devices) > 1:
            per = max(1, batch_size // len(devices))
            for si, dev in enumerate(devices):
                telemetry.SHARD_PROFILER.ensure(si)
                shard_batch = {
                    k: jax.device_put(v[:per], dev)
                    for k, v in batch.items()}
                sfv = jax.device_put(fv, dev)
                sfc = jax.device_put(fc, dev)
                ms = timed(lambda i: mutate_only(
                    shard_batch, random.fold_in(key, 5000 + i),
                    sfv, sfc), warm=1)
                telemetry.SHARD_PROFILER.note(si, ms / 1e3)
                shard_ms[str(si)] = round(ms, 4)
    finally:
        pl.stop()
    fused_d2h = (pl.stats.d2h_bytes / pl.stats.d2h_batches
                 if pl._fused and pl.stats.d2h_batches else None)
    return {
        "device_kernel_ms_per_batch": {
            "mutate": round(mutate_ms, 4),
            "emit_compact": round(max(0.0, step_ms - mutate_ms), 4),
            "novel_any": round(novel_ms, 4),
        },
        "fused_step_ms_per_batch": round(step_ms, 4),
        # The ROADMAP north-star rate: mutants through the WHOLE
        # fused device step per second (mutate + pack + compact +
        # plane), at this profile's batch shape.
        "device_kernel_mutations_per_sec": round(
            batch_size / (step_ms / 1e3), 1) if step_ms else None,
        # Wire bytes per fused drain (rows prefix + pool prefix +
        # scalars): with the mutant plane on, non-novel rows never
        # cross D2H, so this tracks novel yield, not batch size.
        "fused_d2h_bytes_per_batch": (
            round(fused_d2h, 1) if fused_d2h is not None else None),
        "mutate_backend": pl._backend,
        # Per-device isolated mutate probes (empty on 1-device rigs);
        # also exported live as tz_mesh_shard_ms_per_batch gauges.
        "mesh_shard_ms_per_batch": shard_ms,
        "profile_batch": batch_size,
        "profile_triage_shape": [triage_batch, triage_edges],
        "always_on": telemetry.PROFILER.snapshot(),
        "note": ("isolated = kernel alone, block_until_ready-timed "
                 "(emit_compact attributed as fused step minus "
                 "mutate); always_on = host-observed dispatch->ready "
                 "EWMAs from the live loop "
                 "(tz_device_kernel_ms_per_batch gauges)"),
    }


def bench_device_kernel(batch_size=512, edges_per_prog=128,
                        steps=20) -> float:
    """The fused mutate+triage kernel alone (device steady state)."""
    import jax
    import jax.numpy as jnp
    from jax import random

    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.ops.mutate import _mutate_one
    from syzkaller_tpu.ops.tensor import (
        FlagTables, TensorConfig, encode_prog, stack_batch)

    target = get_target("test", "64")
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = []
    i = 0
    while len(tensors) < batch_size:
        p = _seed_programs(target, 1, seed0=42 + i)[0]
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    batch = {k: jnp.asarray(v) for k, v in stack_batch(tensors).items()}
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    plane = dsig.new_plane()

    def step(batch, plane, key):
        b = batch["kind"].shape[0]
        k1, k2 = random.split(key)
        keys = random.split(k1, b)
        mutated = jax.vmap(
            lambda st, k: _mutate_one(st, k, fv, fc, 4))(batch, keys)
        edges = random.bits(k2, (b, edges_per_prog), dtype=jnp.uint32)
        nedges = jnp.full((b,), edges_per_prog, dtype=jnp.int32)
        prios = jnp.full((b,), 2, dtype=jnp.uint8)
        new_mask, counts = dsig.diff_batch(plane, edges, nedges, prios)
        plane = dsig.merge(plane, edges, nedges, prios, counts > 0)
        # Strip the keys _mutate_one adds so the carried batch keeps a
        # stable jit signature (r2's 30x "regression" was exactly this:
        # 'touched' leaked into step 2's input and recompiled inside
        # the timed loop).
        mutated.pop("preserve_sizes", None)
        mutated.pop("touched", None)
        return mutated, plane, counts

    step = jax.jit(step)
    key = random.key(0)
    # Warm BOTH call signatures: the fresh batch and the carried one.
    for _ in range(2):
        key, sub = random.split(key)
        batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    t0 = time.time()
    for _ in range(steps):
        key, sub = random.split(key)
        batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    dt = time.time() - t0
    return batch_size * steps / dt


def bench_cpu(seconds=3.0) -> float:
    """Reference-equivalent CPU loop: clone + weighted-op mutate +
    exec-wire serialization per mutant (tools/syz-mutate analog;
    reference: syz-fuzzer/proc.go:92-95 + prog/encodingexec.go:57)."""
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.mutation import mutate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target

    target = get_target("test", "64")
    rng = RandGen(target, 7)
    corpus = _seed_programs(target, 16, seed0=0)
    n = 0
    t0 = time.time()
    while time.time() - t0 < seconds:
        p = corpus[n % len(corpus)].clone()
        mutate_prog(p, rng, 30, corpus=corpus)
        try:
            serialize_for_exec(p)
        except Exception:
            pass  # oversized mutants count as attempted work
        n += 1
    return n / (time.time() - t0)


def bench_sim(batch_size=PIPE_BATCH, capacity=PIPE_CAPACITY,
              seconds=6.0, loop_iters=20, seeds=64) -> dict:
    """The speculative sim-exec prescore (ISSUE 15), two measurements:

      - the PRESCORED DRAIN: the normal pipeline loop with the
        sim-exec stage fused in (TZ_SIM_PRESCORE path) — every mutant
        is simulated on device, so sim_execs_per_sec is the drained
        batch volume over the timed window,
        prescore_suppressed_frac is the fraction of each batch the
        speculation plane held back from D2H, and
        prescore_suppressed_of_candidates is the same count relative
        to the rows that survived signature dedup — the acceptance
        target (>= 0.5 once the plane warms) reads on the latter.
      - the PURE-DEVICE LOOP: mutate -> sim-exec -> triage-fold
        chained entirely on device (the step's plane outputs feed the
        next dispatch; ZERO host transfers inside the loop, one
        block_until_ready at the end) — the zero-host-transfer loop
        rate the acceptance criteria ask the report to carry."""
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity,
                        batch_size=batch_size, seed=0)
    pl.enable_sim_prescore()
    added, i = 0, 0
    while added < seeds and i < seeds * 8:
        if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
            added += 1
        i += 1
    assert added > 0, "no seed programs tensorized"
    out: dict = {"sim_backend": pl._sim.backend,
                 "pipeline_batch": batch_size}
    try:
        from syzkaller_tpu.health import env_float

        warmup_to = env_float("TZ_BENCH_WARMUP_TIMEOUT_S", 600.0)
        fast = 0
        for attempt in range(12):
            tw = time.time()
            pl.next_batch(timeout=warmup_to if attempt == 0 else 600)
            fast = fast + 1 if time.time() - tw < 5.0 else 0
            if fast >= 2:
                break
        base_b, base_sup = pl.stats.sim_batches, pl.stats.sim_suppressed
        base_adm = pl.stats.fused_novel_rows
        n = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            n += len(pl.next_batch(timeout=600))
        dt = time.time() - t0
        d_batches = pl.stats.sim_batches - base_b
        d_sup = pl.stats.sim_suppressed - base_sup
        d_adm = pl.stats.fused_novel_rows - base_adm
        out["exec_ready_mutants_per_sec"] = round(n / dt, 1)
        out["sim_execs_per_sec"] = round(
            d_batches * batch_size / dt, 1)
        out["prescore_suppressed_frac"] = round(
            d_sup / max(1, d_batches * batch_size), 4)
        # The acceptance-relevant rate: of the rows that survived
        # signature dedup (the only rows that would have crossed D2H
        # without the prescore), how many did the speculation plane
        # hold back?  Signature-dup rows never were D2H candidates, so
        # the whole-batch fraction above understates the filter.
        out["prescore_suppressed_of_candidates"] = round(
            d_sup / max(1, d_sup + d_adm), 4)
        # -- the pure-device loop -------------------------------------
        # Reuse the warm pipeline's device state but drive the
        # prescored step directly: no fetch, no assembly — the only
        # sync is the final block_until_ready.
        import jax

        pl.stop()
        corpus, cn, _tmpl, ets, cumw, total = pl._flush_pending()
        if corpus is None:
            corpus, cn = pl._corpus_dev, pl._n
            cumw, total = pl.arena._cumw_dev, pl.arena._total
        sim = pl._sim
        sim_tables = sim.device_tables(ets)
        sim_plane = sim.ensure_plane()
        plane = pl._mutant_plane
        if plane is None:
            from syzkaller_tpu.ops.signal import new_mutant_plane

            plane = new_mutant_plane(pl._plane_bits)
        fv, fc = pl._flags_dev
        heat = pl._heat_dev
        if heat is None:
            import jax.numpy as jnp

            heat = jnp.zeros((corpus["val"].shape[0],), jnp.uint32)
        key = pl._key
        rows = None
        # One untimed iteration absorbs any residual compile.
        for timed in (False, True):
            iters = loop_iters if timed else 1
            t0 = time.time()
            for _ in range(iters):
                key, sub = pl._random.split(key)
                (rows, _pool, _n_used, _n_novel, plane, sim_plane,
                 _n_sup, heat) = pl._step_sim(
                    corpus, cumw, total, sub, fv, fc, plane,
                    sim_plane, sim_tables, heat, pl._runs_dev,
                    pl._by_syscall_dev)
            jax.block_until_ready((rows, plane, sim_plane))
            loop_dt = time.time() - t0
        out["sim_loop_mutants_per_sec"] = round(
            loop_iters * batch_size / loop_dt, 1)
        out["sim_loop_batches_per_sec"] = round(
            loop_iters / loop_dt, 2)
    finally:
        pl.stop()
        dump_telemetry()
    return out


def bench_arena(batch_size=PIPE_BATCH, capacity=PIPE_CAPACITY,
                iters=50, seeds=64, distill_rounds=4) -> dict:
    """Device-resident corpus arena (ISSUE 18), three measurements at
    the flagship shape:

      - arena_sample_ms_per_batch: the device sampling path — jitted
        cumulative-weight search (`pick_rows`) + row gather against
        the resident slabs, zero host corpus bytes per batch.
      - host_sample_scatter_ms_per_batch: the pre-arena baseline the
        tentpole replaces — host-side pick against host authority,
        numpy gather, and a per-batch device_put of the sampled rows
        (the H2D scatter the old `_pending_rows` drain amortized but
        a host-authoritative sampler pays every batch).
      - distill_retired_rows_per_sec: the batched Minimize lane —
        fused suffix-truncation sim-exec rounds driven directly
        (`_distill_round`), with retired-row and candidate-row rates.

    h2d_corpus_bytes_per_batch_{host,arena} pins the steady-state
    transfer claim: the host baseline's is the sampled-batch byte
    volume, the arena's is the measured `upload_bytes` delta across
    the timed device loop (zero once resident).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.arena import pick_rows, pick_rows_host
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    pl = DevicePipeline(target, capacity=capacity,
                        batch_size=batch_size, seed=0)
    out: dict = {"pipeline_batch": batch_size,
                 "arena_slab_bits": pl.arena.slab_bits}
    try:
        added, i = 0, 0
        while added < seeds and i < seeds * 8:
            if pl.add(_seed_programs(target, 1, seed0=42 + i)[0]):
                added += 1
            i += 1
        assert added > 0, "no seed programs tensorized"
        pl.stop()
        corpus, cn, _tmpl, _ets, cumw, total = pl._flush_pending()
        if corpus is None:
            corpus, cn = pl._corpus_dev, pl._n
            cumw, total = pl.arena._cumw_dev, pl.arena._total
        out["arena_capacity_rows"] = pl.arena.capacity
        out["arena_rows"] = pl.arena.n

        # Shared sampling stream: the same uint32 bit batches drive
        # both arms, so the comparison is pure mechanism.
        rng = np.random.RandomState(7)
        bits_np = [rng.randint(0, 1 << 31, size=batch_size)
                   .astype(np.uint32) for _ in range(8)]
        bits_dev = [jnp.asarray(b) for b in bits_np]

        @jax.jit
        def _sample_dev(c, cw, tot, bits):
            idx = pick_rows(cw, tot, bits)
            return {k: v[idx] for k, v in c.items()}

        # -- device arm: on-device pick + gather ----------------------
        jax.block_until_ready(
            _sample_dev(corpus, cumw, total, bits_dev[0]))  # compile
        up0 = pl.arena.upload_bytes
        t0 = time.perf_counter()
        last = None
        for it in range(iters):
            last = _sample_dev(corpus, cumw, total,
                               bits_dev[it % len(bits_dev)])
        jax.block_until_ready(last)
        dev_dt = time.perf_counter() - t0
        out["arena_sample_ms_per_batch"] = round(
            1e3 * dev_dt / iters, 3)
        out["h2d_corpus_bytes_per_batch_arena"] = round(
            (pl.arena.upload_bytes - up0) / iters, 1)

        # -- host arm: host pick + gather + H2D scatter ---------------
        cumw_h = np.asarray(cumw)
        host = pl.arena.host
        gathered = None
        h2d_bytes = 0
        idx = pick_rows_host(cumw_h, total, bits_np[0])
        jax.block_until_ready(  # warm the transfer path
            {k: jax.device_put(v[idx]) for k, v in host.items()})
        t0 = time.perf_counter()
        for it in range(iters):
            idx = pick_rows_host(cumw_h, total,
                                 bits_np[it % len(bits_np)])
            gathered = {k: jax.device_put(np.ascontiguousarray(v[idx]))
                        for k, v in host.items()}
            if it == 0:
                h2d_bytes = sum(int(np.asarray(v[idx]).nbytes)
                                for v in host.values())
        jax.block_until_ready(gathered)
        host_dt = time.perf_counter() - t0
        out["host_sample_scatter_ms_per_batch"] = round(
            1e3 * host_dt / iters, 3)
        out["h2d_corpus_bytes_per_batch_host"] = h2d_bytes
        out["arena_sample_speedup_x"] = round(
            host_dt / max(dev_dt, 1e-9), 2)

        # -- distillation lane ----------------------------------------
        cand_rows = pl._distill.rows * (pl._distill.max_cands + 1)
        pl._distill_round()  # warm (check-kernel compile)
        r0 = pl._distill.retired
        c0 = pl._distill.rounds
        t0 = time.perf_counter()
        for _ in range(distill_rounds):
            pl._distill_round()
        distill_dt = time.perf_counter() - t0
        d_rounds = pl._distill.rounds - c0
        out["distill_rounds"] = d_rounds
        out["distill_retired_rows"] = pl._distill.retired - r0
        out["distill_retired_rows_per_sec"] = round(
            (pl._distill.retired - r0) / max(distill_dt, 1e-9), 2)
        out["distill_candidate_rows_per_sec"] = round(
            d_rounds * cand_rows / max(distill_dt, 1e-9), 1)
        out["distill_ms_per_round"] = round(
            1e3 * distill_dt / max(distill_rounds, 1), 3)
    finally:
        pl.stop()
        dump_telemetry()
    return out


def bench_ab_prescore(seconds=20.0) -> dict:
    """Prescore efficacy A/B (ISSUE 15 satellite): new-coverage edges
    on the sim-kernel executor at EQUAL WALL TIME, device engine on in
    both arms, speculative prescore on vs off.  The prescore spends
    device time simulating mutants to save D2H/assembly/exec time on
    stale ones — this measures whether that trade nets out on this
    platform."""
    prev = os.environ.get("TZ_SIM_PRESCORE")
    try:
        os.environ["TZ_SIM_PRESCORE"] = "1"
        on = _ab_run(True, seconds=seconds)
        os.environ["TZ_SIM_PRESCORE"] = "0"
        off = _ab_run(True, seconds=seconds)
    finally:
        if prev is None:
            os.environ.pop("TZ_SIM_PRESCORE", None)
        else:
            os.environ["TZ_SIM_PRESCORE"] = prev
    edges_pct = round(
        100.0 * (on["edges"] / off["edges"] - 1.0), 2) \
        if off["edges"] else 0.0
    return {
        "seconds": seconds, "mode": "prescore",
        "prescore_on": on, "prescore_off": off,
        "edges_pct_equal_wall": edges_pct,
        "note": ("both arms run the device engine; the A/B isolates "
                 "the speculative sim-exec stage (TZ_SIM_PRESCORE). "
                 "positive edges_pct = prescore-on found more new "
                 "edges at equal wall time"),
    }


def _ab_run(engine_on: bool, seconds: Optional[float] = None,
            max_execs: Optional[int] = None) -> dict:
    """One fuzzing run on the sim-kernel executor: either fixed wall
    time (seconds) or fixed exec budget (max_execs).  Returns edges,
    execs, wall seconds, and — for engine-on — on-path draw timing."""
    import threading

    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, Proc, WorkQueue
    from syzkaller_tpu.ipc.env import make_env
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    target = get_target("test", "64")
    cfg = FuzzerConfig(program_length=8, generate_period=100,
                       smash_mutants=5, fault_nth_max=3,
                       minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    for i, p in enumerate(_seed_programs(target, 16, length=6)):
        fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
    mutator = None
    pl = None
    draw_stats = {"n": 0, "secs": 0.0}
    if engine_on:
        from syzkaller_tpu.fuzzer.proc import PipelineMutator
        from syzkaller_tpu.ops.pipeline import DevicePipeline

        # Same capacity/batch as the flagship bench: the jit signature
        # matches the flagship's persistently-cached compile, so the
        # A/B works even when the tunnel's remote-compile service is
        # down but cached executables still load (the r5 failure mode:
        # UNAVAILABLE on fresh compiles only).
        pl = DevicePipeline(target, capacity=PIPE_CAPACITY,
                            batch_size=PIPE_BATCH)
        mutator = PipelineMutator(pl, drain_timeout=120.0)
        mutator.ops_journal = []  # count device vs CPU-op draws
        mutator._sync_corpus(fuzzer)
        # The full production device path includes the triage plane
        # (fuzzer/main.py wires the same); TZ_TRIAGE_DEVICE=0 drops it
        # for a mutation-engine-only A/B.
        from syzkaller_tpu.health import env_int

        if env_int("TZ_TRIAGE_DEVICE", 1):
            from syzkaller_tpu.triage import TriageEngine

            fuzzer.set_triage(TriageEngine.for_pipeline(pl))
        # Warm up compile + caches OUTSIDE the timed window.  The
        # first wait is the pool-lease catch window on the tunneled
        # backend (same contract as bench_pipeline's warmup).
        pl.next_batch(timeout=float(os.environ.get(
            "TZ_BENCH_WARMUP_TIMEOUT_S", "600")))
        pl.next_batch(timeout=600)
        # Time every mutator draw: total blocked-in-next() seconds is
        # the engine's on-path cost (the executor loop can do nothing
        # else meanwhile).
        inner_next = mutator.next

        def timed_next(fz, rng):
            t0 = time.time()
            try:
                return inner_next(fz, rng)
            finally:
                draw_stats["n"] += 1
                draw_stats["secs"] += time.time() - t0
        mutator.next = timed_next
    env = make_env(pid=0, sim=True, signal=True)
    proc = Proc(fuzzer, pid=0, env=env, mutator=mutator)
    stop = threading.Event()
    t = threading.Thread(target=proc.loop, args=(1 << 62,),
                         kwargs={"stop": stop}, daemon=True)
    t0 = time.time()
    t.start()
    if max_execs is not None:
        while fuzzer.exec_count() < max_execs and t.is_alive():
            time.sleep(0.05)
    else:
        time.sleep(seconds)
    wall = time.time() - t0
    stop.set()
    if pl is not None:
        pl.stop()  # wakes a proc blocked in pipeline.next()
    t.join(timeout=60)
    assert not t.is_alive(), "A/B proc thread leaked into next run"
    env.close()
    out = {"edges": len(fuzzer.max_signal), "execs": fuzzer.exec_count(),
           "wall_secs": round(wall, 3)}
    if engine_on and draw_stats["n"]:
        out["draws"] = draw_stats["n"]
        out["draw_cost_us"] = round(1e6 * draw_stats["secs"]
                                    / draw_stats["n"], 1)
        out["on_path_secs"] = round(draw_stats["secs"], 3)
        out["device_draws"] = sum(
            1 for o in (mutator.ops_journal or []) if o == "device")
    return out


def bench_ab_edges(seconds=20.0) -> dict:
    """A/B per BASELINE.md metric #2: new-coverage edges discovered on
    the sim-kernel executor in equal wall time, device engine on vs
    off (single proc, same seed corpus).  The result carries an
    explicit overhead figure and a break-even statement (VERDICT r4
    ask #2)."""
    on = _ab_run(True, seconds=seconds)
    off = _ab_run(False, seconds=seconds)
    # Per-mutant CPU mutation cost: the on-path work engine-off does
    # that engine-on moves off the critical path.
    cpu_rate = bench_cpu(seconds=2.0)
    cpu_us = 1e6 / cpu_rate if cpu_rate else float("inf")
    overhead_pct = round(100.0 * (1.0 - on["execs"] / off["execs"]), 2) \
        if off["execs"] else 0.0
    draw_us = on.get("draw_cost_us", 0.0)
    # Measured supply vs demand: demand = exec rate the executor
    # sustains when mutation is CPU-cheap; supply = mutants/s this
    # platform's pipeline delivers STANDALONE (in the fuzzing loop the
    # work queue rarely empties on a fresh sim corpus, so in-loop draw
    # counts are too sparse to be a rate).  The chip must beat
    # demand/supply for supply stalls to vanish — THE break-even.
    demand = off["execs"] / off["wall_secs"] if off["wall_secs"] else 0.0
    supply = bench_pipeline(seconds=4.0, seeds=16)
    break_even_x = round(demand / supply, 2) if supply else None
    statement = (
        "engine-on pays {:.1f}% of exec throughput at equal wall time "
        "on this platform (negative = engine-on did MORE execs). "
        "Supply stalls end when device mutant rate >= executor demand "
        "({:.0f} execs/s): that needs a {}x speedup over this "
        "platform's standalone pipeline rate ({:.0f} mutants/s). The "
        "residual supply-rich on-path cost is a prefetch-queue pop, "
        "bounded <5% of an exec by tests/test_ab_overhead.py."
        .format(overhead_pct, demand, break_even_x, supply))
    # The on-chip comparison is read from the journal at run time —
    # never a constant — and the verdict is computed, with the entry's
    # provenance flags carried along.
    last = journal_last_healthy()
    chip_block = None
    if last is not None and demand > 0:
        chip_rate = last.get("value", 0)
        chip_block = {
            "recorded_rate": chip_rate, "ts": last.get("ts"),
            "past_break_even": bool(chip_rate >= demand),
        }
        for flag in ("reconstructed", "provenance", "source"):
            if last.get(flag):
                chip_block[flag] = last[flag]
    res = {"seconds": seconds, "engine_on": on, "engine_off": off,
           "overhead": {
               "execs_pct_equal_wall": overhead_pct,
               "mutator_next_mean_us": draw_us,
               "cpu_mutation_cost_us": round(cpu_us, 1),
               "executor_demand_execs_per_sec": round(demand, 1),
               "platform_pipeline_mutants_per_sec": round(supply, 1),
           },
           "break_even": {
               "chip_speedup_x": break_even_x,
               "statement": statement,
               "recorded_on_chip": chip_block,
           }}
    return res


def bench_ab_overhead(target_execs=20000) -> dict:
    """Equal-EXEC-budget A/B: both sides run to the same exec count;
    the wall-time ratio is the pipeline's total overhead including
    supply stalls (VERDICT r4 ask #2 'overhead vs CPU path at equal
    execs')."""
    on = _ab_run(True, max_execs=target_execs)
    off = _ab_run(False, max_execs=target_execs)
    return {"metric": "ab_overhead_equal_execs",
            "target_execs": target_execs,
            "engine_on": on, "engine_off": off,
            "overhead_pct_wall": round(
                100.0 * (on["wall_secs"] / off["wall_secs"] - 1.0), 2)
            if off["wall_secs"] else 0.0,
            "note": ("on a CPU-pinned platform the wall overhead "
                     "includes host contention: the pipeline's batch "
                     "compute shares cores with the executor loop. "
                     "On-chip that compute leaves the host entirely; "
                     "the residual on-path cost is draw_cost_us (see "
                     "tests/test_ab_overhead.py's <5%-of-exec bound)")}


def bench_ab_scaled(speedup=16.3, base_execs=40000) -> dict:
    """Discovery-scales-with-mutant-rate simulation (VERDICT r4 ask #2):
    engine-on gets the full exec budget; engine-off gets base/speedup —
    modelling mutation-bound fuzzing where a CPU mutation source caps
    sustained exec rate at 1/speedup of the device path.  Shows the
    edges curve rises with mutant throughput; the speedup factor is the
    journal's recorded on-chip ratio, not a claim made here."""
    off_execs = max(1000, int(base_execs / speedup))
    on = _ab_run(True, max_execs=base_execs)
    off = _ab_run(False, max_execs=off_execs)
    return {"metric": "ab_scaled_mutant_rate",
            "speedup_simulated": speedup,
            "engine_on": {**on, "exec_budget": base_execs},
            "engine_off": {**off, "exec_budget": off_execs},
            "edges_ratio": round(on["edges"] / off["edges"], 3)
            if off["edges"] else None,
            "note": ("exec budgets scaled by the recorded on-chip mutant"
                     "-rate ratio (BENCH_HISTORY.jsonl); demonstrates "
                     "discovery scaling with supply rate, labeled a "
                     "simulation")}


def device_preflight(timeout_s: float = 180.0, attempts: int = 2,
                     backoff_s: float = 20.0) -> Optional[str]:
    """Probe the accelerator in a SUBPROCESS with a hard timeout.

    The tunneled TPU backend can wedge in a state where every jax op
    (even jnp.ones) blocks forever; probing in-process would hang the
    whole bench.  Each attempt re-initializes the backend in a fresh
    subprocess (the wedge is per-process in the common case), so the
    retry doubles as a recovery attempt.  Returns None if healthy,
    else the reason string of the last failed attempt."""
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((64, 64));"
            "print('OK', float((x @ x).sum()))")
    reason = "no probe attempts made"
    for i in range(max(1, attempts)):
        if i:
            time.sleep(backoff_s)
        try:
            res = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired:
            reason = (f"device probe timed out after {timeout_s:.0f}s "
                      f"on attempt {i + 1}/{attempts} "
                      f"(tunneled backend wedged?)")
            continue
        if res.returncode != 0 or "OK" not in res.stdout:
            reason = (f"device probe failed (attempt {i + 1}/{attempts}): "
                      f"{res.stderr.strip()[-300:]}")
            continue
        return None
    return reason


def main() -> None:
    argv = sys.argv[1:]
    # Every exit path leaves a final telemetry snapshot for the
    # watcher's wedge diagnostics (dump_telemetry is a no-op unless
    # TZ_TELEMETRY_SNAPSHOT is set).
    import atexit

    atexit.register(dump_telemetry)
    # Flight recorder (telemetry/flight.py): a bench attempt that
    # wedges leaves an incident file next to the journal; bench_watch
    # renders it in diagnose_wedge.  TZ_FLIGHT_DIR overrides.
    from syzkaller_tpu import telemetry as _telemetry

    if not _telemetry.FLIGHT.armed():
        _telemetry.FLIGHT.set_dir(
            os.path.dirname(os.path.abspath(__file__)))
    from syzkaller_tpu.telemetry import flight as _flight

    _flight.install_signal_handler()
    # TZ_BENCH_PLATFORM (or the shared TZ_JAX_PLATFORM) pins jax to a
    # working backend — used to record functional A/B artifacts while
    # the tunneled device is wedged.  Results are labeled with the
    # platform.
    from syzkaller_tpu.utils.jaxenv import (enable_compilation_cache,
                                            pin_jax_platform)

    enable_compilation_cache()
    platform = pin_jax_platform(os.environ.get("TZ_BENCH_PLATFORM", ""))
    if platform:
        # a pinned platform states the intent explicitly — probing the
        # (possibly wedged) default accelerator would be wrong and slow
        if "--no-preflight" not in argv:
            argv.insert(0, "--no-preflight")
    if "--no-preflight" not in argv:
        from syzkaller_tpu.health import env_float, env_int

        reason = device_preflight(
            timeout_s=env_float("TZ_BENCH_PREFLIGHT_TIMEOUT", 180.0),
            attempts=env_int("TZ_BENCH_PREFLIGHT_ATTEMPTS", 2))
        if reason is not None:
            result = {
                "metric": "exec_ready_mutants_per_sec_per_chip",
                "value": 0,
                "unit": "mutants/sec",
                "vs_baseline": 0,
                "error": reason,
            }
            last = journal_last_healthy()
            if last is not None:
                result["last_healthy"] = {
                    "ts": last.get("ts"), "git_rev": last.get("git_rev"),
                    "metric": last.get("metric"),
                    "value": last.get("value"),
                    "vs_baseline": last.get("vs_baseline"),
                    "sub": last.get("sub"),
                }
                for flag in ("reconstructed", "provenance", "source"):
                    if last.get(flag):
                        result["last_healthy"][flag] = last[flag]
                result["note"] = ("accelerator unreachable at bench time; "
                                  "last_healthy is read from "
                                  "BENCH_HISTORY.jsonl (recorded artifact); "
                                  "see BENCH_WEDGE_DIAGNOSIS.md for the "
                                  "pinpointed hang layer")
            else:
                result["note"] = ("accelerator unreachable at bench time; "
                                  "no recorded healthy measurement in "
                                  "BENCH_HISTORY.jsonl")
            print(json.dumps(result))
            return
    if "--ab-overhead" in argv:
        i = argv.index("--ab-overhead")
        execs = int(argv[i + 1]) if len(argv) > i + 1 else 20000
        res = bench_ab_overhead(execs)
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--ab-scaled" in argv:
        i = argv.index("--ab-scaled")
        speedup = float(argv[i + 1]) if len(argv) > i + 1 else 16.3
        res = bench_ab_scaled(speedup)
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--ab-prescore" in argv:
        i = argv.index("--ab-prescore")
        secs = float(argv[i + 1]) if len(argv) > i + 1 else 20.0
        res = bench_ab_prescore(secs)
        res["metric"] = "new_edges_sim_kernel_ab"
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--ab" in argv:
        secs = float(argv[argv.index("--ab") + 1]) \
            if len(argv) > argv.index("--ab") + 1 else 20.0
        res = bench_ab_edges(secs)
        res["metric"] = "new_edges_sim_kernel_ab"
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--sim" in argv:
        res = {"metric": "sim_execs_per_sec", "unit": "sim execs/sec",
               **bench_sim()}
        res["value"] = res["sim_execs_per_sec"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--host-assembly" in argv:
        res = {"metric": "host_assemble_mutants_per_sec", "unit":
               "mutants/sec", **bench_host_assembly()}
        res["value"] = res["host_assemble_mutants_per_sec"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--profile" in argv:
        res = {"metric": "device_kernel_ms_per_batch",
               "unit": "ms/batch", **bench_profile()}
        res["value"] = res["device_kernel_ms_per_batch"]["mutate"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--coverage" in argv:
        res = {"metric": "coverage_analytics_ms_per_flush",
               "unit": "ms/flush", **bench_coverage()}
        res["value"] = res["coverage_analytics_ms_per_flush"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--hub" in argv:
        res = {"metric": "hub_sync_reply_bytes_saved_pct",
               "unit": "% reply bytes", **bench_hub()}
        res["value"] = res["hub_sync_reply_bytes_saved_pct"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--serve" in argv:
        res = {"metric": "serve_compose_overhead_ms_per_batch",
               "unit": "ms/batch", **bench_serve()}
        res["value"] = res["serve_compose_overhead_ms_per_batch"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--accounting" in argv:
        res = {"metric": "acct_note_batch_us", "unit": "us/batch",
               **bench_accounting()}
        res["value"] = res["acct_note_batch_us"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--arena" in argv:
        res = {"metric": "arena_sample_ms_per_batch",
               "unit": "ms/batch", **bench_arena()}
        res["value"] = res["arena_sample_ms_per_batch"]
        res["vs_baseline"] = res.get("arena_sample_speedup_x")
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--device" in argv:
        res = {"metric": "device_ledger_tax_us", "unit": "us/batch",
               **bench_device()}
        res["value"] = res["device_ledger_tax_us"]
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--triage" in argv:
        res = {"metric": "triage_calls_per_sec", "unit": "calls/sec",
               **bench_triage()}
        res["value"] = res["triage_calls_per_sec"]
        res["vs_baseline"] = res.get("triage_speedup_x")
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    if "--hints" in argv:
        batch = int(argv[argv.index("--batch") + 1]) \
            if "--batch" in argv else 4096
        res = {"metric": "hint_mutants_per_sec", "unit": "mutants/sec",
               **bench_hints(batch=batch)}
        res["value"] = res["hint_mutants_per_sec"]
        res["vs_baseline"] = res.get("hints_speedup_x")
        if platform:
            res["platform"] = platform
        journal_append(res)
        print(json.dumps(res))
        return
    batch = int(argv[argv.index("--batch") + 1]) \
        if "--batch" in argv else PIPE_BATCH
    secs = float(argv[argv.index("--seconds") + 1]) \
        if "--seconds" in argv else 8.0
    pipe_sub: dict = {}
    pipe_rate = bench_pipeline(batch_size=batch, seconds=secs,
                               sub_out=pipe_sub)
    # The flagship rate is measured; don't let an auxiliary compile
    # failure discard it.  On the tunneled backend the far-side
    # compiler can break BETWEEN compiles (BENCH_WEDGE_DIAGNOSIS.md
    # §8 mode 3) — a transient window that yields the pipeline rate
    # must still produce a journal artifact.
    try:
        kernel_rate = bench_device_kernel()
    except Exception as e:
        kernel_rate = None
        kernel_err = f"{type(e).__name__}: {e}"[:200]
    # Host assembly sub-bench: same jit signature as the flagship, so
    # the persistent compilation cache serves its launch; a failure
    # here must not discard the measured flagship rate.
    try:
        assemble_sub = bench_host_assembly(batch_size=batch)
    except Exception as e:
        assemble_sub = {"host_assemble_error":
                        f"{type(e).__name__}: {e}"[:200]}
    # Triage sub-bench: the batched novelty pre-filter vs the CPU
    # Signal path (ISSUE 4); rides the flagship journal entry so the
    # last_healthy mechanism records it even when later attempts find
    # the accelerator wedged.
    try:
        triage_sub = bench_triage()
    except Exception as e:
        triage_sub = {"triage_error": f"{type(e).__name__}: {e}"[:200]}
    # Sim-prescore sub-bench (ISSUE 15): the speculative drain's
    # suppression fraction + pure-device loop rate ride the flagship
    # journal entry; a prescore failure never discards the flagship.
    try:
        sim_sub = {"sim": bench_sim(batch_size=batch, seconds=4.0,
                                    loop_iters=10, seeds=32)}
    except Exception as e:
        sim_sub = {"sim_error": f"{type(e).__name__}: {e}"[:200]}
    # Arena sub-bench (ISSUE 18): on-device sampling vs the host
    # sample+scatter baseline plus the distillation lane rates ride
    # the flagship journal entry; a failure never discards it.
    try:
        arena_sub = {"arena": bench_arena(batch_size=batch,
                                          iters=30, seeds=32,
                                          distill_rounds=2)}
    except Exception as e:
        arena_sub = {"arena_error": f"{type(e).__name__}: {e}"[:200]}
    cpu_rate = bench_cpu()
    result = {
        "metric": "exec_ready_mutants_per_sec_per_chip",
        "value": round(pipe_rate, 1),
        "unit": "mutants/sec",
        "vs_baseline": round(pipe_rate / cpu_rate, 2),
        "sub": {
            "device_kernel_mutations_per_sec":
                round(kernel_rate, 1) if kernel_rate is not None
                else None,
            "cpu_baseline_mutants_per_sec": round(cpu_rate, 1),
            "pipeline_batch": batch,
            **pipe_sub,
            **assemble_sub,
            **triage_sub,
            **sim_sub,
            **arena_sub,
        },
        "note": ("value = integrated corpus-tensor->exec-bytes rate off "
                 "ops/pipeline.DevicePipeline (the path fuzzer/proc.py "
                 "drains). baseline divisor = this repo's CPU reference "
                 "loop (clone+mutate+serialize_for_exec); no Go "
                 "toolchain in the image to run the reference's own "
                 "tools/syz-mutate."),
    }
    if kernel_rate is None:
        result["sub"]["device_kernel_error"] = kernel_err
    if platform:
        result["platform"] = platform
    journal_append(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
