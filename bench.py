"""Benchmark: mutations triaged/sec/chip, device pipeline vs CPU baseline.

Measures the fused device fuzz step (batched mutation + coverage triage
+ plane merge) on the available accelerator against the reference-
equivalent CPU path (single-program mutate + signal diff, the
tools/syz-mutate analog — BASELINE.md config #1).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def build(batch_size: int, edges_per_prog: int):
    import jax
    import jax.numpy as jnp
    from jax import random

    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.ops.mutate import _mutate_one
    from syzkaller_tpu.ops.tensor import (
        FlagTables, TensorConfig, encode_prog, stack_batch)

    target = get_target("test", "64")
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = []
    progs = []
    i = 0
    while len(tensors) < batch_size:
        p = generate_prog(target, RandGen(target, 42 + i), 8)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
            progs.append(p)
        except Exception:
            continue
    batch = {k: jnp.asarray(v) for k, v in stack_batch(tensors).items()}
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    plane = dsig.new_plane()

    def step(batch, plane, key):
        """One fused iteration: mutate all programs, synthesize their
        coverage (stand-in for executor DMA), triage + merge."""
        b = batch["kind"].shape[0]
        k1, k2 = random.split(key)
        keys = random.split(k1, b)
        mutated = jax.vmap(
            lambda st, k: _mutate_one(st, k, fv, fc, 4))(batch, keys)
        edges = random.bits(k2, (b, edges_per_prog), dtype=jnp.uint32)
        nedges = jnp.full((b,), edges_per_prog, dtype=jnp.int32)
        prios = jnp.full((b,), 2, dtype=jnp.uint8)
        new_mask, counts = dsig.diff_batch(plane, edges, nedges, prios)
        plane = dsig.merge(plane, edges, nedges, prios, counts > 0)
        mutated.pop("preserve_sizes", None)
        return mutated, plane, counts

    return jax.jit(step), batch, plane, progs, target


def bench_device(batch_size=1024, edges_per_prog=128, steps=20) -> float:
    import jax
    from jax import random

    step, batch, plane, _, _ = build(batch_size, edges_per_prog)
    key = random.key(0)
    # warmup/compile
    key, sub = random.split(key)
    batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    t0 = time.time()
    for _ in range(steps):
        key, sub = random.split(key)
        batch, plane, counts = step(batch, plane, sub)
    jax.block_until_ready(counts)
    dt = time.time() - t0
    return batch_size * steps / dt


def bench_cpu(seconds=3.0, edges_per_prog=128) -> float:
    """Reference-equivalent CPU loop: clone + mutate + signal triage
    per program (tools/syz-mutate analog)."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.mutation import mutate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.signal import Signal

    target = get_target("test", "64")
    rng = RandGen(target, 7)
    corpus = [generate_prog(target, RandGen(target, i), 8) for i in range(16)]
    sig = Signal()
    rs = np.random.RandomState(0)
    n = 0
    t0 = time.time()
    while time.time() - t0 < seconds:
        p = corpus[n % len(corpus)].clone()
        mutate_prog(p, rng, 30, corpus=corpus)
        raw = rs.randint(0, 1 << 26, size=edges_per_prog).tolist()
        new = sig.diff_raw(raw, 2)
        if new:
            sig.merge(new)
        n += 1
    return n / (time.time() - t0)


def main() -> None:
    batch = int(sys.argv[sys.argv.index("--batch") + 1]) \
        if "--batch" in sys.argv else 1024
    steps = int(sys.argv[sys.argv.index("--steps") + 1]) \
        if "--steps" in sys.argv else 20
    dev_rate = bench_device(batch_size=batch, steps=steps)
    cpu_rate = bench_cpu()
    print(json.dumps({
        "metric": "mutations_triaged_per_sec_per_chip",
        "value": round(dev_rate, 1),
        "unit": "programs/sec",
        "vs_baseline": round(dev_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
