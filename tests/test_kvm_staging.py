"""Full KVM guest staging (VERDICT r3 item #6): the executor's
syz_kvm_setup_cpu long-mode path stages the guest through the real
architectural bring-up — the vcpu starts in REAL mode at a trampoline
that loads GDT/IDT from guest-memory descriptor tables, enables
CR4.PAE, points CR3 at identity page tables, sets EFER.LME over
wrmsr, turns on CR0.PG|PE, and far-jumps through the 64-bit GDT
descriptor into the user text (reference model, not copied:
executor/common_kvm_amd64.h + kvm.S).

Verification layers (the live one needs /dev/kvm):
 1. the build must have KVM support compiled in (CI assert);
 2. the hand-assembled trampoline disassembles, via GNU binutils, to
    exactly the documented staging sequence;
 3. live: a guest executes x86-table-generated long-mode text under
    KVM_RUN — proven by a marker register read back via KVM_GET_REGS.
"""

from __future__ import annotations

import os
import random
import re
import subprocess
import tempfile

import pytest

from syzkaller_tpu.ipc.env import build_executor

PSEUDO_H = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "executor", "pseudo_linux.h")


def _selftest(hex_text: str) -> subprocess.CompletedProcess:
    binpath = build_executor()
    return subprocess.run([str(binpath), "--selftest-kvm", hex_text],
                          capture_output=True, text=True, timeout=60)


def test_build_has_kvm_support():
    """CI assert (VERDICT r3 weak #8): a header-less build would
    silently lose syz_kvm_setup_cpu; the selftest mode reports that
    state with exit code 2."""
    res = _selftest("f4")
    assert res.returncode != 2, "executor built without <linux/kvm.h>"
    assert "built without" not in res.stderr


def test_trampoline_is_the_staging_sequence():
    """Disassemble the trampoline bytes with binutils in 16-bit mode
    and assert the exact architectural bring-up order."""
    import shutil

    if shutil.which("objdump") is None:
        pytest.skip("no objdump on this host")
    src = open(PSEUDO_H).read()
    m = re.search(r"static const uint8_t kKvmTramp\[\] = \{(.*?)\};",
                  src, re.S)
    assert m, "trampoline array not found"
    body = re.sub(r"//[^\n]*", "", m.group(1))
    blob = bytes(int(t, 16)
                 for t in re.findall(r"0x([0-9a-fA-F]{2})\b", body))
    with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
        f.write(blob)
        path = f.name
    try:
        out = subprocess.run(
            ["objdump", "-D", "-b", "binary", "-m", "i386",
             "-Maddr16,data16", path],
            capture_output=True, text=True, timeout=30).stdout
    finally:
        os.unlink(path)
    mnemonics = [ln.split("\t")[-1].split()[0]
                 for ln in out.splitlines()
                 if re.match(r"\s+[0-9a-f]+:", ln)]
    want = ["cli", "lgdtl", "lidtl",
            "mov", "or", "mov",          # CR4 |= PAE
            "mov", "mov",                # CR3 = tables
            "mov", "rdmsr", "or", "wrmsr",  # EFER |= LME
            "mov", "or", "mov",          # CR0 |= PG|PE
            "ljmpl"]                     # -> 64-bit code descriptor
    assert mnemonics[:len(want)] == want, mnemonics
    # the far jump must target the 64-bit code selector, landing in
    # the long-mode prologue (ltr + segment loads) at 0x7800
    assert "ljmpl  $0x8,$0x7800" in out


def test_staged_long_mode_executes_generated_text():
    """Live: table-generated long-mode text runs under KVM_RUN after
    the real->long staging; a marker movabs at the head proves the
    guest reached the user text (read back via KVM_GET_REGS)."""
    if not os.path.exists("/dev/kvm"):
        pytest.skip("no /dev/kvm")
    from syzkaller_tpu.utils import x86

    marker = 0x7A6B766D6B564D31  # arbitrary distinctive value
    # movabs rbx, marker ; <generated long-mode insns> ; hlt-fill
    text = b"\x48\xbb" + marker.to_bytes(8, "little")
    cfg = x86.Config(mode=x86.LONG64, priv=False, avx=False, len_insns=4)
    text += x86.generate(cfg, random.Random(42))
    res = _selftest(text.hex())
    assert res.returncode == 0, res.stderr
    m = re.search(r"exit=(\d+) rip=0x([0-9a-f]+) rbx=0x([0-9a-f]+)",
                  res.stdout)
    assert m, res.stdout
    # the marker can only be in rbx if the staged guest entered the
    # user text in long mode (the movabs encoding is 64-bit-only)
    assert int(m.group(3), 16) == marker, res.stdout
    # exit 5 = KVM_EXIT_HLT (clean run into the hlt fill); generated
    # instructions may fault first, which triple-faults into
    # KVM_EXIT_SHUTDOWN (8) — both prove execution, the marker is the
    # real assertion
    assert int(m.group(1)) in (5, 8), res.stdout


def _stage_dump(hex_text="90f4"):
    exe = os.path.join(os.path.dirname(PSEUDO_H), "tz-executor")
    res = subprocess.run([exe, "--dump-kvm-stage", hex_text],
                         capture_output=True, text=True, timeout=60)
    if res.returncode != 0:
        pytest.skip("executor built without <linux/kvm.h>")
    mem = {}
    for line in res.stdout.splitlines():
        off_s, hexs = line.split()
        mem[int(off_s, 16)] = bytes.fromhex(hexs)
    blob = bytearray(0x9000)
    for off, chunk in mem.items():
        blob[off:off + len(chunk)] = chunk
    return bytes(blob)


def test_staged_tables_byte_exact():
    """VERDICT r4 ask #6: verify the staged descriptor tables
    byte-exactly — GDT entries (incl. the 16-byte 64-bit TSS
    descriptor and ring-3 code/data), all 256 IDT gates, the 4-level
    identity page tables, and the TSS image."""
    mem = _stage_dump("deadbeef")
    import struct

    def q(off):
        return struct.unpack_from("<Q", mem, off)[0]

    # GDT
    assert q(0x2000 + 0x00) == 0
    assert q(0x2000 + 0x08) == 0x00209A0000000000  # kernel code64, L=1
    assert q(0x2000 + 0x10) == 0x00CF92000000FFFF  # flat data
    assert q(0x2000 + 0x18) == 0x00CF9A000000FFFF  # 32-bit code
    assert q(0x2000 + 0x20) == 0x0000890060000067  # TSS64: base 0x6000
    assert q(0x2000 + 0x28) == 0                   # TSS high qword
    assert q(0x2000 + 0x30) == 0x00009A000000FFFF  # 16-bit code
    assert q(0x2000 + 0x38) == 0x000092000000FFFF  # 16-bit data
    assert q(0x2000 + 0x40) == 0x0020FA0000000000  # user code64 DPL3
    assert q(0x2000 + 0x48) == 0x00CFF2000000FFFF  # user data DPL3

    # IDT: 256 identical present interrupt gates -> ISR stub 0x7F00
    gate = bytes([0x00, 0x7F, 0x08, 0x00, 0x00, 0x8E]) + bytes(10)
    for v in range(256):
        assert mem[0x1000 + 16 * v:0x1000 + 16 * v + 16] == gate, v
    # ISR stub: hlt; jmp $-1
    assert mem[0x7F00:0x7F03] == bytes([0xF4, 0xEB, 0xFD])

    # page tables: PML4 -> PDPT -> 4 x 2MB identity PD entries
    assert q(0x3000) == 0x4000 | 3
    assert q(0x4000) == 0x5000 | 3
    for i in range(4):
        assert q(0x5000 + 8 * i) == (i << 21) | 0x83, i

    # TSS: rsp0, IST1, iomap base at the struct tail
    assert q(0x6000 + 4) == 0xE000
    assert q(0x6000 + 36) == 0xE800
    assert mem[0x6000 + 102] == 0x68

    # GDTR/IDTR operands the trampoline lgdt/lidt consume
    assert mem[0x7080:0x7086] == bytes([0x4F, 0x00, 0x00, 0x20, 0, 0])
    assert mem[0x7088:0x708E] == bytes([0xFF, 0x0F, 0x00, 0x10, 0, 0])

    # user text lands at 0x8000, hlt-filled beyond
    assert mem[0x8000:0x8004] == bytes.fromhex("deadbeef")
    assert mem[0x8004] == 0xF4


def test_staged_prologue_disassembles():
    """The long-mode prologue must be exactly: load TR (0x20), load
    data segments (0x10), set rsp, jump into the user text."""
    import shutil

    if shutil.which("objdump") is None:
        pytest.skip("no objdump on this host")
    mem = _stage_dump()
    pro = mem[0x7800:0x7800 + 40]
    with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
        f.write(pro)
        path = f.name
    try:
        out = subprocess.run(
            ["objdump", "-D", "-b", "binary", "-m", "i386",
             "-Mx86-64", path],
            capture_output=True, text=True, timeout=30).stdout
    finally:
        os.unlink(path)
    assert "ltr" in out
    assert out.count("mov    %eax,%ds") == 1
    assert out.count("mov    %eax,%ss") == 1
    assert "jmp" in out
