"""Fleet-resilient control plane (docs/health.md "Control-plane
sessions, leases, and admission control"): idempotent RPC via the
per-fuzzer reply cache, lease reaping with work conservation, and
breaker-driven admission control — capped by a kill/reconnect-storm
chaos test that asserts zero lost and zero double-counted work across
scripted connection faults and a manager restart.

Host-only: no jit compiles, no device; everything runs against
ManagerRPC directly or over the real TCP transport on loopback.
"""

from __future__ import annotations

import threading
import time

import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import (CircuitBreaker, FaultPlan,
                                  install_plan, reset_plan)
from syzkaller_tpu.manager.rpcserver import (THROTTLE_QUOTA,
                                             ManagerRPC)
from syzkaller_tpu.rpc import (ReconnectRequired, RPCClient, RPCError,
                               RPCServer)
from syzkaller_tpu.rpc.types import RPCCandidate


@pytest.fixture(autouse=True)
def _clean_plan():
    reset_plan()
    yield
    reset_plan()


def _input_dict(prog_text, elems, prio=3, call="c"):
    return {"call": call, "prog": prog_text,
            "signal": [elems, [prio] * len(elems)], "cover": []}


def _counters():
    return telemetry.snapshot()["counters"]


class _Clock:
    """Injectable monotonic clock for lease tests.  Starts non-zero:
    last_seen == 0.0 means "never polled" to the reaper."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- reply-cache idempotency ---------------------------------------------


def test_reply_cache_idempotent_poll():
    """The same (epoch, seq) Poll twice: one mutation, identical
    replies — the retry-after-completed-send case the session layer
    exists for."""
    serv = ManagerRPC()
    epoch = serv.Connect({"name": "f1"})["epoch"]
    assert epoch == serv.epoch
    serv.add_candidates([RPCCandidate(prog=f"p{i}()") for i in range(4)])
    params = {"name": "f1", "epoch": epoch, "seq": 1, "ack_seq": 0,
              "need_candidates": True, "stats": {"exec total": 5},
              "max_signal": [[], []]}
    r1 = serv.Poll(dict(params))
    r2 = serv.Poll(dict(params))  # the retry
    assert r1 == r2
    assert len(r1["candidates"]) == 4
    assert serv.stats_total["exec total"] == 5  # applied once
    assert serv.replays_total == 1
    # the replay did not double-issue: the batch sits in f1's custody
    # once, and the queue is empty
    assert len(serv.candidates) == 0
    assert serv.fuzzers["f1"].outstanding_candidates() == 4


def test_reply_cache_idempotent_new_input():
    serv = ManagerRPC()
    epoch = serv.Connect({"name": "f1"})["epoch"]
    serv.Connect({"name": "f2"})
    params = {"name": "f1", "epoch": epoch, "seq": 1, "ack_seq": 0,
              "input": _input_dict("text1()", [1, 2, 3])}
    r1 = serv.NewInput(dict(params))
    r2 = serv.NewInput(dict(params))
    assert r1 == r2 == {"accepted": True}
    assert len(serv.corpus) == 1
    # broadcast to f2 happened exactly once
    assert len(serv.fuzzers["f2"].inputs) == 1


def test_reply_cache_bounded():
    serv = ManagerRPC(reply_cache_size=3)
    epoch = serv.Connect({"name": "f"})["epoch"]
    for seq in range(1, 6):
        serv.Poll({"name": "f", "epoch": epoch, "seq": seq,
                   "ack_seq": seq - 1, "stats": {},
                   "max_signal": [[], []]})
    assert sorted(serv.fuzzers["f"].reply_cache) == [3, 4, 5]


def test_stale_epoch_answers_reconnect_required():
    serv = ManagerRPC()
    serv.Connect({"name": "f1"})
    with pytest.raises(ReconnectRequired):
        serv.Poll({"name": "f1", "epoch": "deadbeef", "seq": 1,
                   "ack_seq": 0, "stats": {}, "max_signal": [[], []]})


def test_legacy_unsessioned_calls_pass_through():
    """No epoch in params → the pre-session protocol: no reply cache,
    no custody ledger, duplicate polls double-apply (caller's
    problem, as before)."""
    serv = ManagerRPC()
    serv.Poll({"name": "f", "stats": {"exec total": 1},
               "max_signal": [[], []]})
    serv.Poll({"name": "f", "stats": {"exec total": 1},
               "max_signal": [[], []]})
    assert serv.stats_total["exec total"] == 2
    assert serv.fuzzers["f"].reply_cache == {}


# -- candidate custody ledger --------------------------------------------


def test_abandoned_reply_requeues_candidates():
    """A reply the client never processed (its ack_seq skipped the
    seq) returns the batch to the queue — candidates survive lost
    replies instead of evaporating."""
    serv = ManagerRPC()
    epoch = serv.Connect({"name": "f"})["epoch"]
    serv.add_candidates([RPCCandidate(prog=f"p{i}()") for i in range(3)])
    r1 = serv.Poll({"name": "f", "epoch": epoch, "seq": 1, "ack_seq": 0,
                    "need_candidates": True, "stats": {},
                    "max_signal": [[], []]})
    assert len(r1["candidates"]) == 3
    # seq 2 with ack_seq still 0: the client abandoned reply 1
    r2 = serv.Poll({"name": "f", "epoch": epoch, "seq": 2, "ack_seq": 0,
                    "need_candidates": True, "stats": {},
                    "max_signal": [[], []]})
    assert sorted(c["prog"] for c in r2["candidates"]) == \
        ["p0()", "p1()", "p2()"]
    # delivery confirmed + executions reported retires them
    serv.Poll({"name": "f", "epoch": epoch, "seq": 3, "ack_seq": 2,
               "stats": {"exec candidate": 3}, "max_signal": [[], []]})
    assert serv.candidate_backlog() == 0


# -- lease reaping + work conservation -----------------------------------


def test_lease_reap_redistributes_work():
    clock = _Clock()
    serv = ManagerRPC(lease_s=60.0, clock=clock)
    epoch = serv.Connect({"name": "dead"})["epoch"]
    serv.Connect({"name": "live"})
    serv.add_candidates([RPCCandidate(prog=f"p{i}()") for i in range(6)])
    # dead takes every candidate into its custody...
    r = serv.Poll({"name": "dead", "epoch": epoch, "seq": 1,
                   "ack_seq": 0, "need_candidates": True, "stats": {},
                   "max_signal": [[], []]})
    assert len(r["candidates"]) == 6
    assert serv.candidate_backlog() == 6
    # ...and an input is pending for it (broadcast from live)
    serv.NewInput({"name": "live", "epoch": epoch, "seq": 1,
                   "ack_seq": 0, "input": _input_dict("i0()", [9])})
    # live stays fresh; dead goes silent past the lease
    clock.advance(30)
    serv.Poll({"name": "live", "epoch": epoch, "seq": 2, "ack_seq": 1,
               "stats": {}, "max_signal": [[], []]})
    clock.advance(31)
    r = serv.Poll({"name": "live", "epoch": epoch, "seq": 3,
                   "ack_seq": 2, "need_candidates": True, "stats": {},
                   "max_signal": [[], []]})
    # the opportunistic reap ran inside that poll: dead's candidates
    # were requeued and handed straight to live, its pending input
    # redistributed — nothing dropped
    assert "dead" not in serv.fuzzers
    assert serv.reaped_total == 1
    assert sorted(c["prog"] for c in r["candidates"]) == \
        sorted(f"p{i}()" for i in range(6))
    assert [i["prog"] for i in r["new_inputs"]] == ["i0()"]
    # a late retry of dead's applied seq replays from the tombstone
    # instead of double-applying...
    r_dead = serv.Poll({"name": "dead", "epoch": epoch, "seq": 1,
                        "ack_seq": 0, "need_candidates": True,
                        "stats": {}, "max_signal": [[], []]})
    assert len(r_dead["candidates"]) == 6  # the cached reply, verbatim
    # ...but NEW work from the reaped name must re-Connect
    with pytest.raises(ReconnectRequired):
        serv.Poll({"name": "dead", "epoch": epoch, "seq": 2,
                   "ack_seq": 1, "stats": {}, "max_signal": [[], []]})
    # re-Connect clears the tombstone and starts a fresh lease
    serv.Connect({"name": "dead"})
    assert "dead" in serv.fuzzers


def test_reap_deferred_by_fault_seam():
    """A scripted manager.lease_expire fault defers that fuzzer's reap
    to the next pass — the lease plane tolerates its own maintenance
    failing mid-stride."""
    clock = _Clock()
    serv = ManagerRPC(lease_s=10.0, clock=clock)
    serv.Connect({"name": "dead"})
    clock.advance(11)
    install_plan(FaultPlan.parse("manager.lease_expire:fail@1"))
    serv.reap_expired()
    assert "dead" in serv.fuzzers  # deferred
    serv.reap_expired()
    assert "dead" not in serv.fuzzers  # next pass succeeds


# -- bounded queues -------------------------------------------------------


def test_input_queue_cap_drops_oldest():
    before = _counters().get("tz_manager_inputs_dropped_total", 0)
    serv = ManagerRPC(inputs_cap=5)
    serv.Connect({"name": "a"})
    serv.Connect({"name": "b"})
    for i in range(8):
        serv.NewInput({"name": "a",
                       "input": _input_dict(f"t{i}()", [i + 1])})
    q = serv.fuzzers["b"].inputs
    assert [i["prog"] for i in q] == [f"t{i}()" for i in range(3, 8)]
    assert _counters()["tz_manager_inputs_dropped_total"] - before == 3


def test_signal_cap_overflow_serves_full_resync():
    serv = ManagerRPC(signal_cap=4)
    serv.Connect({"name": "a"})
    serv.Connect({"name": "b"})
    serv.Poll({"name": "a", "stats": {},
               "max_signal": [list(range(1, 8)), [3] * 7]})
    f = serv.fuzzers["b"]
    assert f.signal_resync and f.new_max_signal.empty()
    # the overflow cleared b's delta, but the resync latch serves the
    # complete max signal — a superset of whatever was dropped
    r = serv.Poll({"name": "b", "stats": {}, "max_signal": [[], []]})
    assert sorted(r["max_signal"][0]) == list(range(1, 8))
    r2 = serv.Poll({"name": "b", "stats": {}, "max_signal": [[], []]})
    assert r2["max_signal"][0] == []  # latch cleared


# -- breaker-driven admission control ------------------------------------


def test_admission_control_shrinks_allotment():
    serv = ManagerRPC()
    epoch = serv.Connect({"name": "f"})["epoch"]
    serv.add_candidates([RPCCandidate(prog=f"p{i}()")
                         for i in range(50)])
    r = serv.Poll({"name": "f", "epoch": epoch, "seq": 1, "ack_seq": 0,
                   "need_candidates": True, "device_state": "open",
                   "stats": {}, "max_signal": [[], []]})
    assert r["throttle"]["state"] == "open"
    assert r["throttle"]["poll_interval_mult"] > 1.0
    # plenty queued, but the open breaker caps the allotment
    assert len(r["candidates"]) == THROTTLE_QUOTA["open"] == 10
    assert telemetry.snapshot()["gauges"][
        "tz_manager_throttle_state"] == 2
    # recovery: the device closes again → full allotment resumes
    r2 = serv.Poll({"name": "f", "epoch": epoch, "seq": 2, "ack_seq": 1,
                    "need_candidates": True, "device_state": "closed",
                    "stats": {}, "max_signal": [[], []]})
    assert r2["throttle"]["state"] == "closed"
    assert len(r2["candidates"]) == 40
    assert telemetry.snapshot()["gauges"][
        "tz_manager_throttle_state"] == 0


def test_admission_control_manager_local_breaker():
    br = CircuitBreaker(failure_threshold=2, backoff_initial=600.0)
    serv = ManagerRPC(breaker=br)
    serv.Connect({"name": "f"})
    serv.add_candidates([RPCCandidate(prog=f"p{i}()")
                         for i in range(30)])
    br.record_failure()
    br.record_failure()
    assert br.state == "open"
    r = serv.Poll({"name": "f", "need_candidates": True, "stats": {},
                   "max_signal": [[], []]})
    assert r["throttle"]["state"] == "open"
    assert len(r["candidates"]) == 10


def test_worst_fuzzer_state_wins():
    serv = ManagerRPC()
    serv.Connect({"name": "a"})
    serv.Connect({"name": "b"})
    serv.Poll({"name": "a", "stats": {}, "max_signal": [[], []],
               "device_state": "half_open"})
    r = serv.Poll({"name": "b", "stats": {}, "max_signal": [[], []],
                   "device_state": "closed"})
    assert r["throttle"]["state"] == "half_open"
    assert r["throttle"]["max_candidates"] == THROTTLE_QUOTA["half_open"]


# -- transport accounting -------------------------------------------------


class _Echo:
    def Ping(self, params):
        return {"pong": params.get("n")}


def _wait_counter(name, floor, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if _counters().get(name, 0) >= floor:
            return True
        time.sleep(0.01)
    return False


def test_conn_accounting():
    import socket

    before = _counters()
    srv = RPCServer()
    srv.register("Echo", _Echo())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    try:
        assert cli.call("Echo.Ping", {"n": 1}) == {"pong": 1}
        cli.close()  # clean hangup at a frame boundary → a drop
        assert _wait_counter(
            "tz_rpc_conn_dropped_total",
            before.get("tz_rpc_conn_dropped_total", 0) + 1)
        # a peer dying mid-frame (partial header then EOF) → an error
        s = socket.create_connection(srv.addr, timeout=5.0)
        s.sendall(b"\x01\x02\x03")
        s.close()
        assert _wait_counter(
            "tz_rpc_conn_errors_total",
            before.get("tz_rpc_conn_errors_total", 0) + 1)
        after = _counters()
        assert after["tz_rpc_conn_accepted_total"] - \
            before.get("tz_rpc_conn_accepted_total", 0) >= 2
    finally:
        cli.close()
        srv.close()


# -- retry + replay over the real transport ------------------------------


def test_retry_replays_after_reply_lost():
    """The rpc.reply_cache seam kills the connection AFTER the server
    applied the call but BEFORE the reply went out — the exact window
    idempotent retry exists for.  The client's resend of the same seq
    must be answered from the cache: stats applied exactly once."""
    serv = ManagerRPC()
    srv = RPCServer()
    srv.register("Manager", serv)
    srv.serve_in_background()
    cli = RPCClient(srv.addr, name="f1", timeout_s=5.0, retries=4,
                    backoff_s=0.01)
    try:
        res = cli.call("Manager.Connect", {"name": "f1"})
        cli.set_session(res["epoch"])
        install_plan(FaultPlan.parse("rpc.reply_cache:fail@1"))
        out = cli.call_session("Manager.Poll", {
            "stats": {"exec total": 7}, "max_signal": [[], []]})
        assert out is not None and "throttle" in out
        assert serv.stats_total["exec total"] == 7
        assert serv.replays_total == 1
    finally:
        cli.close()
        srv.close()


def test_manager_restart_drives_full_resync():
    """A new ManagerRPC (new epoch) behind the same port: the client's
    next sessioned call gets ReconnectRequired, runs the installed
    on_reconnect resync, and re-issues under the fresh epoch."""
    serv1 = ManagerRPC()
    srv = RPCServer()
    srv.register("Manager", serv1)
    srv.serve_in_background()
    cli = RPCClient(srv.addr, name="f1", timeout_s=5.0, retries=2,
                    backoff_s=0.01)
    resyncs = []

    def resync():
        res = cli.call("Manager.Connect", {"name": "f1"})
        cli.set_session(res["epoch"])
        resyncs.append(res["epoch"])

    try:
        res = cli.call("Manager.Connect", {"name": "f1"})
        cli.set_session(res["epoch"], on_reconnect=resync)
        cli.call_session("Manager.Poll", {"stats": {"exec total": 1},
                                          "max_signal": [[], []]})
        # "restart": swap in a fresh ManagerRPC with a new epoch
        serv2 = ManagerRPC()
        assert serv2.epoch != serv1.epoch
        srv.register("Manager", serv2)
        out = cli.call_session("Manager.Poll", {
            "stats": {"exec total": 2}, "max_signal": [[], []]})
        assert out is not None
        assert resyncs == [serv2.epoch]
        assert serv2.stats_total["exec total"] == 2  # on the new epoch
        assert serv1.stats_total["exec total"] == 1  # not double-applied
    finally:
        cli.close()
        srv.close()


# -- the kill/reconnect storm --------------------------------------------


class _StormClient:
    """A miniature fuzzer poll loop with ground-truth accounting:
    `executed` are candidate programs it received (and "ran"),
    `confirmed_polls` / `inputs_confirmed` only count calls whose
    reply actually came back — the conservation ledger the final
    asserts compare the managers against."""

    def __init__(self, idx, addr):
        self.idx = idx
        self.name = f"f{idx}"
        self.cli = RPCClient(addr, name=self.name, timeout_s=10.0,
                             retries=6, backoff_s=0.01)
        self.executed: list[str] = []
        self.pending_exec = 0  # executed, not yet reported upstream
        self.confirmed_polls = 0
        self.unconfirmed_polls = 0
        self.inputs_confirmed: list[str] = []
        self.reconnects = 0
        self.connect()

    def connect(self):
        res = self.cli.call("Manager.Connect", {"name": self.name})
        self.cli.set_session(res["epoch"], on_reconnect=self._resync)

    def _resync(self):
        self.reconnects += 1
        self.connect()

    def poll(self, need_candidates=True):
        stats = {"exec total": 1, "exec candidate": self.pending_exec}
        try:
            res = self.cli.call_session("Manager.Poll", {
                "need_candidates": need_candidates, "stats": stats,
                "max_signal": [[], []]}) or {}
        except (RPCError, ConnectionError, OSError):
            # Retries exhausted: the fuzzer would restore the drained
            # delta; here we just record the poll as unconfirmed.
            self.unconfirmed_polls += 1
            return
        self.confirmed_polls += 1
        self.pending_exec = 0
        for cand in res.get("candidates") or []:
            self.executed.append(cand["prog"])
            self.pending_exec += 1

    def new_input(self, k):
        prog = f"inp_{self.name}_{k}()"
        elem = 100000 + self.idx * 1000 + k
        try:
            res = self.cli.call_session("Manager.NewInput", {
                "input": _input_dict(prog, [elem])}) or {}
        except (RPCError, ConnectionError, OSError):
            return
        if res.get("accepted"):
            self.inputs_confirmed.append(prog)

    def storm_loop(self, polls):
        for k in range(polls):
            self.poll()
            if k % 3 == 0:
                self.new_input(k)
            time.sleep(0.005)

    def drain(self):
        """Fault-free settle: report outstanding executions so the
        manager's custody ledger retires them."""
        for _ in range(5):
            pending = self.pending_exec
            self.poll(need_candidates=False)
            if pending == 0 and self.pending_exec == 0:
                return


def _run_storm(clients, polls, fault_plan):
    install_plan(FaultPlan.parse(fault_plan))
    threads = [threading.Thread(target=c.storm_loop, args=(polls,),
                                daemon=True) for c in clients]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    reset_plan()  # quiesce: the drain/settle phase runs fault-free
    for c in clients:
        c.drain()


def test_kill_reconnect_storm_conserves_work():
    """The tentpole end-to-end: three session clients poll through a
    storm of scripted connection kills (every ~6th frame send dies,
    client- and server-side alike), then the manager restarts with a
    fresh epoch behind the same port.  Conservation must hold across
    all of it: every candidate is executed exactly once or still
    queued, every confirmed stat delta is applied exactly once in
    exactly one manager generation, every accepted input is in the
    carried corpus."""
    n_cands_p1, n_cands_p2 = 30, 15
    seeded = [f"cand{i}()" for i in range(n_cands_p1 + n_cands_p2)]

    serv1 = ManagerRPC()
    srv1 = RPCServer()
    srv1.register("Manager", serv1)
    srv1.serve_in_background()
    addr = srv1.addr
    clients = [_StormClient(i, addr) for i in range(3)]

    # Feed candidates gradually (as live triage would) so the batches
    # spread across clients and seqs instead of one taker draining
    # the queue, then run phase 1 of the storm.
    def feeder(serv, progs):
        for i in range(0, len(progs), 3):
            serv.add_candidates(
                [RPCCandidate(prog=p) for p in progs[i:i + 3]])
            time.sleep(0.01)

    f1 = threading.Thread(target=feeder,
                          args=(serv1, seeded[:n_cands_p1]), daemon=True)
    f1.start()
    _run_storm(clients, polls=12,
               fault_plan="rpc.send_frame:fail@"
               + ",".join(str(i) for i in range(9, 600, 6)))
    f1.join(timeout=10)

    # Phase-1 conservation against generation 1.
    executed_p1 = [p for c in clients for p in c.executed]
    assert len(executed_p1) == len(set(executed_p1))  # no double-exec
    snap1 = serv1.snapshot()
    left_p1 = [c["prog"] for c in serv1.candidates]
    assert serv1.candidate_backlog() == len(left_p1)  # custody settled
    assert sorted(executed_p1 + left_p1) == sorted(seeded[:n_cands_p1])
    confirmed_p1 = sum(c.confirmed_polls for c in clients)
    unconfirmed_p1 = sum(c.unconfirmed_polls for c in clients)
    assert confirmed_p1 <= snap1["stats"]["exec total"] \
        <= confirmed_p1 + unconfirmed_p1
    if unconfirmed_p1 == 0:  # the common, fully-confirmed run
        assert snap1["stats"].get("exec candidate", 0) == \
            len(executed_p1)

    # Scripted manager restart: clients drop their connections, the
    # server goes away, and a NEW ManagerRPC (fresh epoch) comes up
    # behind the same port carrying the persisted state — corpus,
    # corpus signal, and the unexecuted candidate queue.
    for c in clients:
        c.cli.close()
    srv1.close()
    serv2 = ManagerRPC()
    assert serv2.epoch != serv1.epoch
    serv2.candidates = list(serv1.candidates)
    serv2.corpus = dict(serv1.corpus)
    serv2.corpus_signal = serv1.corpus_signal
    serv2.max_signal = serv1.max_signal
    for _ in range(200):  # the kernel may need a beat to free the port
        try:
            srv2 = RPCServer(addr)
            break
        except OSError:
            time.sleep(0.01)
    else:
        pytest.fail("could not rebind the manager port after restart")
    srv2.register("Manager", serv2)
    srv2.serve_in_background()

    # Phase 2: same storm against the new generation.  Every client's
    # first sessioned call lands with the stale epoch and must resync
    # through ReconnectRequired → on_reconnect.
    f2 = threading.Thread(target=feeder,
                          args=(serv2, seeded[n_cands_p1:]), daemon=True)
    f2.start()
    _run_storm(clients, polls=12,
               fault_plan="rpc.send_frame:fail@"
               + ",".join(str(i) for i in range(9, 600, 6)))
    f2.join(timeout=10)
    srv2.close()

    assert all(c.reconnects >= 1 for c in clients)

    # Global conservation across both generations.
    executed = [p for c in clients for p in c.executed]
    assert len(executed) == len(set(executed))  # zero double-counted
    left = [c["prog"] for c in serv2.candidates]
    assert serv2.candidate_backlog() == len(left)
    assert sorted(executed + left) == sorted(seeded)  # zero lost
    confirmed = sum(c.confirmed_polls for c in clients)
    unconfirmed = sum(c.unconfirmed_polls for c in clients)
    applied = snap1["stats"]["exec total"] + \
        serv2.stats_total.get("exec total", 0)
    assert confirmed <= applied <= confirmed + unconfirmed
    if unconfirmed == 0:
        assert snap1["stats"].get("exec candidate", 0) + \
            serv2.stats_total.get("exec candidate", 0) == len(executed)
    # every input a client saw accepted exists in the carried corpus,
    # exactly once (the dict is keyed by program hash)
    corpus_progs = [i["prog"] for i in serv2.corpus.values()]
    assert len(corpus_progs) == len(set(corpus_progs))
    for c in clients:
        for prog in c.inputs_confirmed:
            assert prog in corpus_progs
