"""CI, dashboard, dashapi, bisect, and instance tests."""

import os
import subprocess
import time

import pytest

from syzkaller_tpu.ci.bisect import TestResult, bisect, bisect_fix
from syzkaller_tpu.ci.ci import CI, CIConfig
from syzkaller_tpu.dashboard.app import (Dashboard, STATUS_DUP,
                                         STATUS_FIXED, STATUS_REPORTED,
                                         serve_dashboard)
from syzkaller_tpu.dashboard.dashapi import DashClient, DashboardError


# -- dashboard state machine ---------------------------------------------


def test_dashboard_bug_dedup(tmp_path):
    d = Dashboard(str(tmp_path))
    r1 = d.report_crash({"manager": "m1", "title": "KASAN: uaf in foo",
                         "log": "log1"})
    r2 = d.report_crash({"manager": "m2", "title": "KASAN: uaf in foo",
                         "log": "log2"})
    assert r1["bug_id"] == r2["bug_id"]
    bug = d.bugs[r1["bug_id"]]
    assert bug.num_crashes == 2
    assert len(bug.crashes) == 2
    # crash from a second manager landed in the same bug
    assert {c.manager for c in bug.crashes} == {"m1", "m2"}


def test_dashboard_need_repro_flow(tmp_path):
    d = Dashboard(str(tmp_path))
    r = d.report_crash({"title": "BUG: x"})
    assert r["need_repro"]
    d.report_crash({"title": "BUG: x", "repro_prog": "prog()"})
    r3 = d.report_crash({"title": "BUG: x"})
    assert not r3["need_repro"]  # repro exists now
    assert not d.need_repro({"title": "BUG: x"})["need_repro"]


def test_dashboard_reporting_lifecycle(tmp_path):
    d = Dashboard(str(tmp_path), reporting_delay_s=0.0)
    r = d.report_crash({"title": "WARNING in bar"})
    reports = d.poll_reports()
    assert [x["title"] for x in reports] == ["WARNING in bar"]
    assert d.bugs[r["bug_id"]].status == STATUS_REPORTED
    assert d.poll_reports() == []  # reported once
    d.update_bug(r["bug_id"], fix_commit="deadbeef")
    assert d.bugs[r["bug_id"]].status == STATUS_FIXED
    # dup-marking
    r2 = d.report_crash({"title": "WARNING in baz"})
    d.update_bug(r2["bug_id"], dup_of=r["bug_id"])
    assert d.bugs[r2["bug_id"]].status == STATUS_DUP


def test_dashboard_persistence(tmp_path):
    d = Dashboard(str(tmp_path))
    d.report_crash({"title": "BUG: persists"})
    d2 = Dashboard(str(tmp_path))
    assert any(b.title == "BUG: persists" for b in d2.bugs.values())


def test_dashboard_jobs(tmp_path):
    d = Dashboard(str(tmp_path))
    jid = d.add_job("bug1", patch="--- a/f\n+++ b/f\n", manager="m1")
    job = d.job_poll({"client": "ci", "managers": ["m1"]})
    assert job["id"] == jid
    # claimed: not handed out twice
    assert d.job_poll({"client": "ci", "managers": ["m1"]}) == {}
    d.job_done({"id": jid, "ok": True})
    assert d.jobs[jid].status == "done"
    assert d.jobs[jid].result_ok


def test_dashboard_auth(tmp_path):
    d = Dashboard(str(tmp_path), clients={"ci": "key1"})
    with pytest.raises(PermissionError):
        d.report_crash({"client": "ci", "key": "bad", "title": "x"})
    d.report_crash({"client": "ci", "key": "key1", "title": "x"})


# -- HTTP API + client ---------------------------------------------------


def test_dashapi_over_http(tmp_path):
    srv, dash = serve_dashboard(str(tmp_path),
                                clients={"mgr": "secret"})
    try:
        host, port = srv.server_address
        c = DashClient(f"{host}:{port}", client="mgr", key="secret")
        build_id = c.upload_build("m1", "linux", "amd64",
                                  kernel_commit="abc123")
        assert build_id
        res = c.report_crash("m1", "KASAN: uaf in net",
                             log="console log", build_id=build_id)
        assert res["need_repro"]
        c.manager_stats("m1", corpus=100, execs=5000)
        assert not c.job_poll(["m1"])  # no jobs queued
        bad = DashClient(f"{host}:{port}", client="mgr", key="wrong")
        with pytest.raises(DashboardError, match="403"):
            bad.report_crash("m1", "x")
        # stats landed on disk
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "stats-m1.jsonl"))
    finally:
        srv.shutdown()


def test_manager_reports_crashes_to_dashboard(tmp_path):
    from syzkaller_tpu.manager.manager import Manager
    from syzkaller_tpu.manager.mgrconfig import load_config
    from syzkaller_tpu.report import Report

    srv, dash = serve_dashboard(str(tmp_path / "dash"))
    try:
        host, port = srv.server_address
        cfg = load_config({"workdir": str(tmp_path / "w"),
                           "target": "test/64", "http": "",
                           "dashboard_client": "m",
                           "dashboard_addr": f"{host}:{port}"})
        m = Manager(cfg)
        m.save_crash(Report(title="BUG: dashboard test",
                            output=b"out", report=b"rep"))
        m.shutdown()
        assert any(b.title == "BUG: dashboard test"
                   for b in dash.bugs.values())
    finally:
        srv.shutdown()


# -- bisect --------------------------------------------------------------


@pytest.fixture
def git_repo(tmp_path):
    repo = str(tmp_path / "repo")
    os.makedirs(repo)

    def git(*args, **kw):
        subprocess.run(["git", "-C", repo, *args], check=True,
                       capture_output=True, **kw)

    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    commits = []
    for i in range(10):
        with open(os.path.join(repo, "f.txt"), "w") as f:
            f.write(f"version {i}\n")
        git("add", "f.txt")
        git("commit", "-q", "-m", f"commit {i}")
        out = subprocess.run(["git", "-C", repo, "rev-parse", "HEAD"],
                             capture_output=True, text=True, check=True)
        commits.append(out.stdout.strip())
    return repo, commits


def test_bisect_finds_culprit(git_repo):
    repo, commits = git_repo
    culprit_idx = 6

    def pred(commit):
        idx = commits.index(commit)
        return TestResult.BAD if idx >= culprit_idx else TestResult.GOOD

    res = bisect(repo, good=commits[0], bad=commits[-1], pred=pred)
    assert res is not None
    assert res.commit == commits[culprit_idx]
    assert res.tested <= 5  # log2(10) + slack


def test_bisect_fix_finds_fixing_commit(git_repo):
    repo, commits = git_repo
    fix_idx = 4  # crashes before, fixed from here on

    def pred(commit):
        idx = commits.index(commit)
        return TestResult.GOOD if idx >= fix_idx else TestResult.BAD

    res = bisect_fix(repo, bad=commits[0], good=commits[-1], pred=pred)
    assert res is not None
    assert res.commit == commits[fix_idx]


# -- instance ------------------------------------------------------------


def test_instance_image_test(tmp_path):
    from syzkaller_tpu.ci.instance import test_image
    from syzkaller_tpu.manager.mgrconfig import load_config

    cfg = load_config({"workdir": str(tmp_path / "w"),
                       "target": "test/64", "http": "", "type": "local"})
    os.makedirs(cfg.workdir, exist_ok=True)
    test_image(cfg, duration_s=6.0)  # raises on failure


# -- CI loop -------------------------------------------------------------


def test_ci_build_and_restart_cycle(tmp_path, git_repo):
    repo, commits = git_repo
    marker = str(tmp_path / "built")
    cfg = CIConfig(workdir=str(tmp_path / "ci"), managers=[{
        "name": "mgr-a", "repo": repo, "branch": "main",
        "build_cmd": f"touch {marker}",
        "manager_cmd": "sleep 30",
    }])
    ci = CI(cfg)
    try:
        m = ci.managers[0]
        assert ci.check_manager(m)  # first deploy
        assert os.path.exists(marker)
        assert m.proc is not None and m.proc.poll() is None
        first_pid = m.proc.pid
        assert not ci.check_manager(m)  # no new commit: no restart
        assert m.proc.pid == first_pid
        # new commit appears → rebuild + restart
        with open(os.path.join(repo, "f.txt"), "w") as f:
            f.write("new\n")
        subprocess.run(["git", "-C", repo, "commit", "-aqm", "more"],
                       check=True, capture_output=True)
        assert ci.check_manager(m)
        assert m.proc.pid != first_pid
    finally:
        ci.shutdown()


def test_ci_build_failure_reported(tmp_path, git_repo):
    repo, _ = git_repo
    srv, dash = serve_dashboard(str(tmp_path / "dash"))
    try:
        host, port = srv.server_address
        cfg = CIConfig(workdir=str(tmp_path / "ci"),
                       dashboard_addr=f"{host}:{port}",
                       dashboard_client="ci",
                       managers=[{
                           "name": "mgr-a", "repo": repo,
                           "build_cmd": "false",
                       }])
        ci = CI(cfg)
        assert not ci.check_manager(ci.managers[0])
        assert any("build error" in b.title for b in dash.bugs.values())
    finally:
        srv.shutdown()


def test_ci_patch_test_job(tmp_path, git_repo):
    repo, _ = git_repo
    srv, dash = serve_dashboard(str(tmp_path / "dash"))
    try:
        host, port = srv.server_address
        patch = subprocess.run(
            ["git", "-C", repo, "format-patch", "--stdout", "HEAD~1"],
            capture_output=True, text=True, check=True).stdout
        # revert the file so the patch applies
        subprocess.run(["git", "-C", repo, "checkout", "-q", "HEAD~1"],
                       check=True, capture_output=True)
        jid = dash.add_job("bug1", patch=patch, manager="mgr-a")
        cfg = CIConfig(workdir=str(tmp_path / "ci"),
                       dashboard_addr=f"{host}:{port}",
                       dashboard_client="ci",
                       managers=[{"name": "mgr-a", "repo": repo}])
        ci = CI(cfg)
        res = ci.poll_jobs(test_fn=lambda job: True)
        assert res is not None and res["ok"]
        assert dash.jobs[jid].status == "done"
        assert dash.jobs[jid].result_ok
    finally:
        srv.shutdown()


def test_dashboard_fix_commit_closes_on_build(tmp_path):
    """A bug with an attached fix commit transitions fixed -> closed
    when a build containing that commit is uploaded (reference
    dashboard fix-detection flow)."""
    from syzkaller_tpu.dashboard.app import (
        STATUS_CLOSED, STATUS_FIXED, Dashboard)

    dash = Dashboard(str(tmp_path / "dash"))
    dash.report_crash({"title": "BUG: fixme", "manager": "m0"})
    bug_id = next(iter(dash.bugs))
    dash.update_bug(bug_id, fix_commit="net: fix refcount leak")
    assert dash.bugs[bug_id].status == STATUS_FIXED
    # build without the fix: stays fixed
    dash.upload_build({"manager": "m0", "kernel_commit": "abc",
                       "commits": ["unrelated: cleanup"]})
    assert dash.bugs[bug_id].status == STATUS_FIXED
    # build whose commit list contains the fix: closed
    res = dash.upload_build({"manager": "m0", "kernel_commit": "def",
                             "commits": ["net: fix refcount leak"]})
    assert bug_id in res["closed_bugs"]
    assert dash.bugs[bug_id].status == STATUS_CLOSED


def test_dashboard_web_ui(tmp_path):
    """Bug list/detail, builds and jobs pages serve real state."""
    import urllib.request

    from syzkaller_tpu.dashboard.app import serve_dashboard

    srv, dash = serve_dashboard(str(tmp_path / "dash"))
    try:
        dash.report_crash({"title": "WARNING: odd thing",
                           "manager": "m1",
                           "repro_prog": "open()\nread()\n"})
        dash.upload_build({"manager": "m1", "kernel_commit": "c0ffee"})
        bug_id = next(iter(dash.bugs))
        dash.add_job(bug_id, patch="--- a/f\n+++ b/f\n")
        host, port = srv.server_address[:2]

        def get(path):
            with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=10) as r:
                return r.read().decode()

        index = get("/")
        assert "WARNING: odd thing" in index and f"/bug?id={bug_id}" in index
        detail = get(f"/bug?id={bug_id}")
        assert "reproducer" in detail and "open()" in detail
        assert "m1" in get("/builds") and "c0ffee"[:12] in get("/builds")
        jobs = get("/jobs")
        assert bug_id[:12] in jobs and "pending" in jobs
        filtered = get("/?status=closed")
        assert "WARNING: odd thing" not in filtered
    finally:
        srv.shutdown()


def test_dashboard_namespaces_partition(tmp_path):
    """Clients bound to different namespaces see separate bug spaces:
    the same crash title dedups within a namespace, never across; fix
    detection and reporting respect the partition (reference:
    dashboard/app namespaces)."""
    from syzkaller_tpu.dashboard.app import Dashboard

    dash = Dashboard(str(tmp_path / "dash"), clients={
        "ci-up": {"key": "k1", "namespace": "upstream"},
        "ci-and": {"key": "k2", "namespace": "android"},
        "legacy": "k3",  # single-namespace legacy form -> default
    })
    up = {"client": "ci-up", "key": "k1"}
    an = {"client": "ci-and", "key": "k2"}
    dash.report_crash({**up, "title": "BUG: same title"})
    dash.report_crash({**an, "title": "BUG: same title"})
    dash.report_crash({**up, "title": "BUG: same title"})
    bugs = list(dash.bugs.values())
    assert len(bugs) == 2
    by_ns = {b.namespace: b for b in bugs}
    assert by_ns["upstream"].num_crashes == 2
    assert by_ns["android"].num_crashes == 1
    # wrong key rejected
    import pytest as _pytest
    with _pytest.raises(PermissionError):
        dash.report_crash({"client": "ci-up", "key": "bad", "title": "x"})
    # legacy client lands in default
    dash.report_crash({"client": "legacy", "key": "k3", "title": "t2"})
    assert any(b.namespace == "default" for b in dash.bugs.values())
    # per-namespace reporting
    reps = dash.poll_reports(namespace="android")
    assert len(reps) == 1 and reps[0]["namespace"] == "android"
    # fix detection confined to the uploader's namespace
    up_bug = by_ns["upstream"]
    an_bug = by_ns["android"]
    dash.update_bug(up_bug.id, fix_commit="net: fix it")
    dash.update_bug(an_bug.id, fix_commit="net: fix it")
    res = dash.upload_build({**an, "commits": ["net: fix it"]})
    assert res["closed_bugs"] == [an_bug.id]
    assert dash.bugs[up_bug.id].status == "fixed"


def test_dashboard_namespace_migration_and_jobs(tmp_path):
    """Pre-namespace state.json bugs survive the id-scheme change
    (dedup continues under the new id); jobs only flow to clients of
    the bug's namespace."""
    import json as json_mod

    from syzkaller_tpu.dashboard.app import Dashboard
    from syzkaller_tpu.utils.hashsig import hash_string

    work = tmp_path / "dash"
    work.mkdir()
    legacy_id = hash_string(b"BUG: old")[:16]
    (work / "state.json").write_text(json_mod.dumps({
        "bugs": [{"id": legacy_id, "title": "BUG: old",
                  "status": "reported", "num_crashes": 3}],
        "builds": [],
        "jobs": [{"id": "j1", "bug_id": legacy_id, "patch": "p"}],
    }))
    dash = Dashboard(str(work), clients={
        "up": {"key": "k1", "namespace": "upstream"},
        "an": {"key": "k2", "namespace": "android"},
    })
    new_id = hash_string(b"default\x00BUG: old")[:16]
    assert new_id in dash.bugs and legacy_id not in dash.bugs
    assert dash.jobs["j1"].bug_id == new_id
    # job routing respects namespaces
    dash.report_crash({"client": "up", "key": "k1", "title": "B2"})
    up_bug = next(b for b in dash.bugs.values()
                  if b.namespace == "upstream")
    dash.add_job(up_bug.id, patch="diff")
    got = dash.job_poll({"client": "an", "key": "k2"})
    assert got == {}, "android client claimed an upstream job"
    got = dash.job_poll({"client": "up", "key": "k1"})
    assert got.get("bug_id") == up_bug.id
    # fail-closed: dict client entry without a key never authenticates
    dash2 = Dashboard(str(tmp_path / "d2"),
                      clients={"c": {"namespace": "x"}})
    import pytest as _pytest
    with _pytest.raises(PermissionError):
        dash2.report_crash({"client": "c", "title": "t"})


def test_web_text_blobs_and_ns_summary(tmp_path):
    """Plain-text blob endpoints + namespace summary (reference:
    dashboard/app/main.go /x/log.txt, /x/repro.syz, handleMain)."""
    from urllib.request import urlopen

    srv, dash = serve_dashboard(str(tmp_path),
                                clients={"mgr": "secret"})
    try:
        host, port = srv.server_address
        c = DashClient(f"{host}:{port}", client="mgr", key="secret")
        res = c.report_crash("m1", "BUG: web blob", log="the log text",
                            repro_prog="r0 = open()\n", repro_c="int main")
        bid = res["bug_id"]
        base = f"http://{host}:{port}"
        assert urlopen(f"{base}/x/log.txt?id={bid}&crash=0").read() \
            == b"the log text"
        assert urlopen(f"{base}/x/repro.syz?id={bid}").read() \
            == b"r0 = open()\n"
        assert urlopen(f"{base}/x/repro.c?id={bid}").read() \
            == b"int main"
        assert urlopen(
            f"{base}/text?tag=repro_syz&id={bid}").read().startswith(b"r0")
        main = urlopen(base + "/").read().decode()
        assert "namespace" in main and "open" in main
        bugpage = urlopen(f"{base}/bug?id={bid}").read().decode()
        assert "/x/log.txt" in bugpage and "repro0.syz" in bugpage
        # unknown blob 404s
        import urllib.error
        try:
            urlopen(f"{base}/x/patch.diff?id=nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.shutdown()
