"""Device hints engine parity (SURVEY.md §7.7).

The batched shrinkExpand kernel must agree EXACTLY with the CPU
semantics engine (models/hints.py) — same replacer sets per value,
and byte-identical mutant programs in the same order when driving a
whole call (the reference golden strategy: prog/hints_test.go:216+).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.models.encoding import serialize_prog  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.hints import (  # noqa: E402
    CompMap,
    mutate_with_hints,
    shrink_expand,
)
from syzkaller_tpu.models.rand import SPECIAL_INTS  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.hints import (  # noqa: E402
    DeviceCompMap,
    mutate_with_hints_device,
    shrink_expand_batch,
)


def _random_comp_map(rs: np.random.RandomState, nkeys: int,
                     vals_per_key: int = 4) -> CompMap:
    cm = CompMap()
    pool = [int(rs.randint(0, 1 << 62)), 0, 1, 0xFF, 0xFFFF,
            0xFFFFFFFF, (1 << 64) - 1, 0x8000000000000000,
            int(SPECIAL_INTS[rs.randint(len(SPECIAL_INTS))])]
    for _ in range(nkeys):
        k = int(pool[rs.randint(len(pool))]) if rs.rand() < 0.3 \
            else int(rs.randint(0, 1 << 62))
        for _ in range(rs.randint(1, vals_per_key + 1)):
            v = int(pool[rs.randint(len(pool))]) if rs.rand() < 0.4 \
                else int(rs.randint(0, 1 << 62))
            cm.add_comp(k, v)
    return cm


def test_shrink_expand_parity_random():
    rs = np.random.RandomState(7)
    # 12 iterations still sweep small and large key counts plus the
    # hit/truncation value classes; the batch kernel recompiles per
    # distinct vals length, so each extra iteration costs a compile
    # the tier-1 ceiling can't carry.
    for it in range(12):
        cm = _random_comp_map(rs, nkeys=rs.randint(1, 12))
        dmap = DeviceCompMap.from_comp_map(cm)
        assert dmap.overflow is None
        # Values: random, plus exact keys (hit path), plus truncations.
        vals = [int(rs.randint(0, 1 << 62)) for _ in range(6)]
        vals += [int(k) for k in list(cm.m.keys())[:6]]
        vals += [v | (0xDEAD << 48) for v in vals[:4]]
        got = shrink_expand_batch(np.array(vals, dtype=np.uint64), dmap)
        for v, g in zip(vals, got):
            want = sorted(shrink_expand(v & ((1 << 64) - 1), cm))
            assert g == want, (
                f"iter {it}: value 0x{v:x}: device {g} != cpu {want}")


def test_shrink_expand_parity_sign_extension():
    """The sign-extension variants (negative widths) and the wide-hi
    filter (hints.go:199-204) must agree on crafted cases."""
    cm = CompMap()
    # Key = sign-extended 0xFF (8-bit -1): matches iwidth=-1 path.
    cm.add_comp((1 << 64) - 1, 0x1234)
    # Key = 16-bit truncation.
    cm.add_comp(0xBEEF, 0xC0DE)
    # Wide operand vs narrow cast: must be filtered unless signext.
    cm.add_comp(0x42, 0xFFFF_FFFF_FFFF_FF80)
    dmap = DeviceCompMap.from_comp_map(cm)
    vals = np.array([0xFF, 0xABCD_BEEF, 0x42, 0xFFFF_FFFF_FFFF_FFFF],
                    dtype=np.uint64)
    got = shrink_expand_batch(vals, dmap)
    for v, g in zip(vals.tolist(), got):
        assert g == sorted(shrink_expand(v, cm))


def test_mutate_with_hints_device_matches_cpu(test_target):
    """Whole-call parity: identical mutant sequence from both engines."""
    rs = np.random.RandomState(3)
    checked = 0
    # 15 seeds clear the checked>50 floor several times over; each
    # extra seed re-pays ~2.5s of device dispatch for the same parity
    # property, and the tier-1 ceiling can't carry 40.
    for seed in range(15):
        p = generate_prog(test_target, RandGen(test_target, 500 + seed), 3)
        cm = _random_comp_map(rs, nkeys=6)
        # Make hits likely: compare some actual arg values.
        from syzkaller_tpu.models.prog import ConstArg, foreach_arg

        def harvest(arg, ctx):
            if isinstance(arg, ConstArg) and arg.typ is not None:
                cm.add_comp(arg.val, int(rs.randint(1, 1 << 32)))

        for c in p.calls:
            foreach_arg(c, harvest)

        for ci in range(len(p.calls)):
            cpu_out: list[bytes] = []
            dev_out: list[bytes] = []
            mutate_with_hints(p, ci, cm,
                              lambda m: cpu_out.append(serialize_prog(m)))
            mutate_with_hints_device(
                p, ci, cm, lambda m: dev_out.append(serialize_prog(m)))
            assert dev_out == cpu_out, f"seed {seed} call {ci}"
            checked += len(cpu_out)
    assert checked > 50, "parity never exercised a real mutant"


def test_device_comp_map_overflow_falls_back(test_target):
    """A CompMap overflowing the per-key budget must still produce the
    exact CPU mutant sequence (fallback path)."""
    cm = CompMap()
    for i in range(40):  # one key, 40 operands > vmax=16
        cm.add_comp(0x1234, 0x1000 + i)
    dmap = DeviceCompMap.from_comp_map(cm)
    assert dmap.overflow is not None and dmap.overflow_operands == 40
    p = generate_prog(test_target, RandGen(test_target, 9), 2)
    cpu_out: list[bytes] = []
    dev_out: list[bytes] = []
    mutate_with_hints(p, 0, cm, lambda m: cpu_out.append(serialize_prog(m)))
    mutate_with_hints_device(p, 0, cm,
                             lambda m: dev_out.append(serialize_prog(m)))
    assert dev_out == cpu_out


def test_smash_hint_pass_drains_device_batch(test_target):
    """End-to-end: a Proc with device_hints collects comps from the
    sim executor and executes device-produced hint mutants."""
    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
    from syzkaller_tpu.fuzzer.fuzzer import Stat
    from syzkaller_tpu.fuzzer.proc import Proc
    from syzkaller_tpu.ipc.env import make_env

    env = make_env(pid=0, sim=True, signal=True)
    try:
        fuzzer = Fuzzer(test_target, wq=WorkQueue(),
                        cfg=FuzzerConfig(minimize_attempts=1))
        proc = Proc(fuzzer, pid=0, env=env, device_hints=True)
        ran = 0
        for seed in range(30):
            p = generate_prog(test_target, RandGen(test_target, seed), 4)
            for ci in range(len(p.calls)):
                proc.execute_hint_seed(p, ci)
            hints = fuzzer.stats[Stat.HINT]
            if hints > 0:
                ran = hints
                break
        assert ran > 0, "no hint mutants executed via the device engine"
    finally:
        env.close()


def test_per_key_overflow_supplement_exact(test_target):
    """A map mixing normal keys with one hot key (>vmax operands) must
    stay on device for the normal keys and produce the exact CPU
    mutant sequence via the per-key CPU supplement — no wholesale
    bailout (VERDICT r3 item #9)."""
    cm = CompMap()
    for i in range(40):  # hot key: 40 operands > vmax=16
        cm.add_comp(0x1234, 0x2000 + i)
    for k in range(12):  # plenty of in-budget keys
        cm.add_comp(0x9000 + k, 0x100 + k)
        cm.add_comp(0x9000 + k, 0x200 + k)
    dmap = DeviceCompMap.from_comp_map(cm)
    assert dmap.overflow is not None
    assert list(dmap.overflow.m.keys()) == [0x1234]
    assert len(dmap) == 12  # normal keys stayed on device
    p = generate_prog(test_target, RandGen(test_target, 21), 2)
    cpu_out: list[bytes] = []
    dev_out: list[bytes] = []
    mutate_with_hints(p, 0, cm, lambda m: cpu_out.append(serialize_prog(m)))
    mutate_with_hints_device(p, 0, cm,
                             lambda m: dev_out.append(serialize_prog(m)))
    assert dev_out == cpu_out


def test_fallback_rate_on_sim_trace_cmp(test_target):
    """Measure how often real TRACE_CMP data from the sim kernel
    overflows the per-key operand budget: the rate must be small
    enough that the device path handles the bulk of real comps (the
    observability VERDICT r3 item #9 asked for)."""
    from syzkaller_tpu.fuzzer.proc import Proc  # noqa: F401
    from syzkaller_tpu.ipc.env import ExecFlags, ExecOpts, make_env
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.ops import hints as dhints

    env = make_env(pid=0, sim=True, signal=True)
    opts = ExecOpts(flags=ExecFlags.COLLECT_COMPS)
    before = dict(dhints.FALLBACK_STATS)
    maps = 0
    try:
        for seed in range(40):
            p = generate_prog(test_target, RandGen(test_target, 100 + seed),
                              4)
            res = env.exec(opts, serialize_for_exec(p))
            if res is None:
                continue
            for ci in res.info:
                if not ci.comps:
                    continue
                cm = CompMap()
                for a, b in ci.comps:
                    cm.add_comp(a, b)
                DeviceCompMap.from_comp_map(cm)
                maps += 1
    finally:
        env.close()
    assert maps > 10, "sim kernel produced no TRACE_CMP data"
    keys = dhints.FALLBACK_STATS["keys"] - before["keys"]
    overflow = dhints.FALLBACK_STATS["overflow_keys"] - before["overflow_keys"]
    assert keys > 0
    rate = overflow / keys
    # the budget must cover the overwhelming majority of real keys
    assert rate < 0.05, f"per-key overflow rate {rate:.1%} on sim comps"
