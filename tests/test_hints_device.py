"""Device hints engine parity (SURVEY.md §7.7).

The batched shrinkExpand kernel must agree EXACTLY with the CPU
semantics engine (models/hints.py) — same replacer sets per value,
and byte-identical mutant programs in the same order when driving a
whole call (the reference golden strategy: prog/hints_test.go:216+).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.models.encoding import serialize_prog  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.hints import (  # noqa: E402
    CompMap,
    mutate_with_hints,
    shrink_expand,
)
from syzkaller_tpu.models.rand import SPECIAL_INTS  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.hints import (  # noqa: E402
    DeviceCompMap,
    mutate_with_hints_device,
    shrink_expand_batch,
)


def _random_comp_map(rs: np.random.RandomState, nkeys: int,
                     vals_per_key: int = 4) -> CompMap:
    cm = CompMap()
    pool = [int(rs.randint(0, 1 << 62)), 0, 1, 0xFF, 0xFFFF,
            0xFFFFFFFF, (1 << 64) - 1, 0x8000000000000000,
            int(SPECIAL_INTS[rs.randint(len(SPECIAL_INTS))])]
    for _ in range(nkeys):
        k = int(pool[rs.randint(len(pool))]) if rs.rand() < 0.3 \
            else int(rs.randint(0, 1 << 62))
        for _ in range(rs.randint(1, vals_per_key + 1)):
            v = int(pool[rs.randint(len(pool))]) if rs.rand() < 0.4 \
                else int(rs.randint(0, 1 << 62))
            cm.add_comp(k, v)
    return cm


def test_shrink_expand_parity_random():
    rs = np.random.RandomState(7)
    # 12 iterations still sweep small and large key counts plus the
    # hit/truncation value classes; the batch kernel recompiles per
    # distinct vals length, so each extra iteration costs a compile
    # the tier-1 ceiling can't carry.
    for it in range(12):
        cm = _random_comp_map(rs, nkeys=rs.randint(1, 12))
        dmap = DeviceCompMap.from_comp_map(cm)
        assert dmap.overflow is None
        # Values: random, plus exact keys (hit path), plus truncations.
        vals = [int(rs.randint(0, 1 << 62)) for _ in range(6)]
        vals += [int(k) for k in list(cm.m.keys())[:6]]
        vals += [v | (0xDEAD << 48) for v in vals[:4]]
        got = shrink_expand_batch(np.array(vals, dtype=np.uint64), dmap)
        for v, g in zip(vals, got):
            want = sorted(shrink_expand(v & ((1 << 64) - 1), cm))
            assert g == want, (
                f"iter {it}: value 0x{v:x}: device {g} != cpu {want}")


def test_shrink_expand_parity_sign_extension():
    """The sign-extension variants (negative widths) and the wide-hi
    filter (hints.go:199-204) must agree on crafted cases."""
    cm = CompMap()
    # Key = sign-extended 0xFF (8-bit -1): matches iwidth=-1 path.
    cm.add_comp((1 << 64) - 1, 0x1234)
    # Key = 16-bit truncation.
    cm.add_comp(0xBEEF, 0xC0DE)
    # Wide operand vs narrow cast: must be filtered unless signext.
    cm.add_comp(0x42, 0xFFFF_FFFF_FFFF_FF80)
    dmap = DeviceCompMap.from_comp_map(cm)
    vals = np.array([0xFF, 0xABCD_BEEF, 0x42, 0xFFFF_FFFF_FFFF_FFFF],
                    dtype=np.uint64)
    got = shrink_expand_batch(vals, dmap)
    for v, g in zip(vals.tolist(), got):
        assert g == sorted(shrink_expand(v, cm))


def test_mutate_with_hints_device_matches_cpu(test_target):
    """Whole-call parity: identical mutant sequence from both engines."""
    rs = np.random.RandomState(3)
    checked = 0
    # 15 seeds clear the checked>50 floor several times over; each
    # extra seed re-pays ~2.5s of device dispatch for the same parity
    # property, and the tier-1 ceiling can't carry 40.
    for seed in range(15):
        p = generate_prog(test_target, RandGen(test_target, 500 + seed), 3)
        cm = _random_comp_map(rs, nkeys=6)
        # Make hits likely: compare some actual arg values.
        from syzkaller_tpu.models.prog import ConstArg, foreach_arg

        def harvest(arg, ctx):
            if isinstance(arg, ConstArg) and arg.typ is not None:
                cm.add_comp(arg.val, int(rs.randint(1, 1 << 32)))

        for c in p.calls:
            foreach_arg(c, harvest)

        for ci in range(len(p.calls)):
            cpu_out: list[bytes] = []
            dev_out: list[bytes] = []
            mutate_with_hints(p, ci, cm,
                              lambda m: cpu_out.append(serialize_prog(m)))
            mutate_with_hints_device(
                p, ci, cm, lambda m: dev_out.append(serialize_prog(m)))
            assert dev_out == cpu_out, f"seed {seed} call {ci}"
            checked += len(cpu_out)
    assert checked > 50, "parity never exercised a real mutant"


def test_device_comp_map_overflow_falls_back(test_target):
    """A CompMap overflowing the per-key budget must still produce the
    exact CPU mutant sequence (fallback path)."""
    cm = CompMap()
    for i in range(40):  # one key, 40 operands > vmax=16
        cm.add_comp(0x1234, 0x1000 + i)
    dmap = DeviceCompMap.from_comp_map(cm)
    assert dmap.overflow is not None and dmap.overflow_operands == 40
    p = generate_prog(test_target, RandGen(test_target, 9), 2)
    cpu_out: list[bytes] = []
    dev_out: list[bytes] = []
    mutate_with_hints(p, 0, cm, lambda m: cpu_out.append(serialize_prog(m)))
    mutate_with_hints_device(p, 0, cm,
                             lambda m: dev_out.append(serialize_prog(m)))
    assert dev_out == cpu_out


def test_smash_hint_pass_drains_device_batch(test_target):
    """End-to-end: a Proc with device_hints collects comps from the
    sim executor and executes device-produced hint mutants."""
    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
    from syzkaller_tpu.fuzzer.fuzzer import Stat
    from syzkaller_tpu.fuzzer.proc import Proc
    from syzkaller_tpu.ipc.env import make_env

    env = make_env(pid=0, sim=True, signal=True)
    try:
        fuzzer = Fuzzer(test_target, wq=WorkQueue(),
                        cfg=FuzzerConfig(minimize_attempts=1))
        proc = Proc(fuzzer, pid=0, env=env, device_hints=True)
        ran = 0
        for seed in range(30):
            p = generate_prog(test_target, RandGen(test_target, seed), 4)
            for ci in range(len(p.calls)):
                proc.execute_hint_seed(p, ci)
            hints = fuzzer.stats[Stat.HINT]
            if hints > 0:
                ran = hints
                break
        assert ran > 0, "no hint mutants executed via the device engine"
    finally:
        env.close()


def test_per_key_overflow_supplement_exact(test_target):
    """A map mixing normal keys with one hot key (>vmax operands) must
    stay on device for the normal keys and produce the exact CPU
    mutant sequence via the per-key CPU supplement — no wholesale
    bailout (VERDICT r3 item #9)."""
    cm = CompMap()
    for i in range(40):  # hot key: 40 operands > vmax=16
        cm.add_comp(0x1234, 0x2000 + i)
    for k in range(12):  # plenty of in-budget keys
        cm.add_comp(0x9000 + k, 0x100 + k)
        cm.add_comp(0x9000 + k, 0x200 + k)
    dmap = DeviceCompMap.from_comp_map(cm)
    assert dmap.overflow is not None
    assert list(dmap.overflow.m.keys()) == [0x1234]
    assert len(dmap) == 12  # normal keys stayed on device
    p = generate_prog(test_target, RandGen(test_target, 21), 2)
    cpu_out: list[bytes] = []
    dev_out: list[bytes] = []
    mutate_with_hints(p, 0, cm, lambda m: cpu_out.append(serialize_prog(m)))
    mutate_with_hints_device(p, 0, cm,
                             lambda m: dev_out.append(serialize_prog(m)))
    assert dev_out == cpu_out


def test_fallback_rate_on_sim_trace_cmp(test_target):
    """Measure how often real TRACE_CMP data from the sim kernel
    overflows the per-key operand budget: the rate must be small
    enough that the device path handles the bulk of real comps (the
    observability VERDICT r3 item #9 asked for)."""
    from syzkaller_tpu.fuzzer.proc import Proc  # noqa: F401
    from syzkaller_tpu.ipc.env import ExecFlags, ExecOpts, make_env
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.ops import hints as dhints

    env = make_env(pid=0, sim=True, signal=True)
    opts = ExecOpts(flags=ExecFlags.COLLECT_COMPS)
    before = dict(dhints.FALLBACK_STATS)
    maps = 0
    try:
        for seed in range(40):
            p = generate_prog(test_target, RandGen(test_target, 100 + seed),
                              4)
            res = env.exec(opts, serialize_for_exec(p))
            if res is None:
                continue
            for ci in res.info:
                if not ci.comps:
                    continue
                cm = CompMap()
                for a, b in ci.comps:
                    cm.add_comp(a, b)
                DeviceCompMap.from_comp_map(cm)
                maps += 1
    finally:
        env.close()
    assert maps > 10, "sim kernel produced no TRACE_CMP data"
    keys = dhints.FALLBACK_STATS["keys"] - before["keys"]
    overflow = dhints.FALLBACK_STATS["overflow_keys"] - before["overflow_keys"]
    assert keys > 0
    rate = overflow / keys
    # the budget must cover the overwhelming majority of real keys
    assert rate < 0.05, f"per-key overflow rate {rate:.1%} on sim comps"


# -- the batched hints lane (ISSUE 19) -------------------------------------

from syzkaller_tpu import telemetry  # noqa: E402
from syzkaller_tpu.ops.hints import (  # noqa: E402
    resolve_hints_vmax,
    shrink_expand_batch_stacked,
    stack_comp_maps,
)


def _stacked_run(cms, vals, map_of, vmax=16):
    """Expand `vals` against stacked `cms` at the lane's smallest
    warm-shape bucket (b=64, m=4, k=16) so every stacked test in this
    module shares ONE kernel compile with the HintLane fixtures."""
    dmaps = [DeviceCompMap.from_comp_map(cm, vmax=vmax) for cm in cms]
    assert all(d.overflow is None for d in dmaps)
    assert all(len(d) <= 16 for d in dmaps) and len(dmaps) <= 4
    tables = stack_comp_maps(dmaps, 4, 16)
    n = len(vals)
    assert n <= 64
    varr = np.zeros(64, dtype=np.uint64)
    varr[:n] = np.array(vals, dtype=np.uint64)
    moar = np.zeros(64, dtype=np.int32)
    moar[:n] = np.array(map_of, dtype=np.int32)
    return shrink_expand_batch_stacked(varr, moar, tables)[:n]


def test_stacked_kernel_parity_random():
    """Fleet-shape parity: several comp maps stacked into one padded
    table set, windows routed by a map_of column — every window's
    replacer list must equal its own map's CPU shrink_expand."""
    rs = np.random.RandomState(23)
    for it in range(4):
        cms = [_random_comp_map(rs, nkeys=rs.randint(1, 5),
                                vals_per_key=3)
               for _ in range(1 + rs.randint(4))]
        cms = [cm for cm in cms
               if len(DeviceCompMap.from_comp_map(cm)) <= 16][:4]
        if not cms:
            continue
        vals, map_of = [], []
        for mi, cm in enumerate(cms):
            keys = list(cm.m.keys())
            for _ in range(6):
                v = int(keys[rs.randint(len(keys))]) \
                    if rs.rand() < 0.4 else int(rs.randint(0, 1 << 62))
                vals.append(v)
                map_of.append(mi)
        got = _stacked_run(cms, vals, map_of)
        for v, mi, g in zip(vals, map_of, got):
            want = sorted(shrink_expand(v, cms[mi]))
            assert g == want, f"iter {it} map {mi} value 0x{v:x}"


def test_stacked_kernel_swap_and_width_edges():
    """_swap_const width/endianness edges across DIFFERENT stacked
    maps: byte-swapped keys, sign-extended keys, and the wide-hi
    filter must each resolve against the right map's tables (a map_of
    routing bug would cross-contaminate the replacer sets)."""
    cm_a = CompMap()
    cm_a.add_comp((1 << 64) - 1, 0x1234)       # sext 8-bit -1 key
    cm_a.add_comp(0xBEEF, 0xC0DE)              # 16-bit truncation
    cm_a.add_comp(0x42, 0xFFFF_FFFF_FFFF_FF80)  # wide-hi filter
    cm_b = CompMap()
    cm_b.add_comp(0xEFBE, 0xAAAA)              # byteswap16 of 0xBEEF
    cm_b.add_comp(0x78563412, 0x5555)          # byteswap32 key
    cm_b.add_comp(0xFF, 0x9999)                # 8-bit key, no be var
    vals = [0xFF, 0xABCD_BEEF, 0x42, (1 << 64) - 1,
            0xBEEF, 0x1234_5678, 0xFF, 0xEFBE]
    map_of = [0, 0, 0, 0, 1, 1, 1, 1]
    got = _stacked_run([cm_a, cm_b], vals, map_of)
    for v, mi, g in zip(vals, map_of, got):
        want = sorted(shrink_expand(v, [cm_a, cm_b][mi]))
        assert g == want, f"map {mi} value 0x{v:x}"


def test_hints_vmax_knob_and_dropped_counter(monkeypatch):
    """Satellite: the vmax=16 truncation is no longer silent — capped
    comparands are counted (tz_hints_comps_dropped_total) and the cap
    is the TZ_HINTS_VMAX envsafe knob."""
    dropped = telemetry.counter(
        "tz_hints_comps_dropped_total", "").value
    cm = CompMap()
    for i in range(40):
        cm.add_comp(0x1234, 0x1000 + i)
    dmap = DeviceCompMap.from_comp_map(cm)
    assert dmap.overflow is not None and dmap.overflow_operands == 40
    assert telemetry.counter(
        "tz_hints_comps_dropped_total", "").value == dropped + 40
    # Raising the knob keeps the same map fully on device.
    monkeypatch.setenv("TZ_HINTS_VMAX", "64")
    assert resolve_hints_vmax() == 64
    wide = DeviceCompMap.from_comp_map(cm)
    assert wide.overflow is None and wide.vals.shape[1] == 64
    # kmax budget: keys past it also route to the supplement, counted.
    monkeypatch.delenv("TZ_HINTS_VMAX")
    many = CompMap()
    for i in range(8):
        many.add_comp(0x9000 + 16 * i, 0x1 + i)
    capped = DeviceCompMap.from_comp_map(many, kmax=4)
    assert capped.overflow is not None and len(capped) == 4
    # Malformed/extreme values clamp instead of exploding.
    monkeypatch.setenv("TZ_HINTS_VMAX", "0")
    assert resolve_hints_vmax() == 1
    monkeypatch.setenv("TZ_HINTS_VMAX", "99999")
    assert resolve_hints_vmax() == 1024


@pytest.fixture(scope="module")
def hint_rig():
    """One shared HintLane for the lane tests: the parity test warms
    its pow2 shape buckets, and the zero-new-jits test replays the
    SAME cases so every steady-state flush hits a warm bucket."""
    from syzkaller_tpu.ops.hintlane import HintLane

    return HintLane()


@pytest.fixture(scope="module")
def test_target_module():
    from syzkaller_tpu.models.target import get_target

    return get_target("test", "64")


def _lane_case(target, rs, seed):
    p = generate_prog(target, RandGen(target, seed), 3)
    cm = _random_comp_map(rs, nkeys=4, vals_per_key=2)
    from syzkaller_tpu.models.prog import ConstArg, foreach_arg

    def harvest(arg, ctx):
        if isinstance(arg, ConstArg) and arg.typ is not None:
            cm.add_comp(arg.val, int(rs.randint(1, 1 << 32)))

    for c in p.calls:
        foreach_arg(c, harvest)
    return p, cm


def test_hint_lane_parity_and_acct_lane(hint_rig, test_target_module):
    """Lane-level bit-exactness (the tentpole oracle): HintLane.run
    produces the identical mutant sequence to the per-program host
    path, and its kernel time books to
    tz_acct_device_ms_total{lane="hints"}."""
    rs = np.random.RandomState(31)
    acct0 = telemetry.counter("tz_acct_device_ms_total", "",
                              labels={"lane": "hints"}).value
    checked = 0
    for seed in range(3):
        p, cm = _lane_case(test_target_module, rs, 700 + seed)
        for ci in range(len(p.calls)):
            cpu_out: list[bytes] = []
            dev_out: list[bytes] = []
            mutate_with_hints(p, ci, cm,
                              lambda m: cpu_out.append(serialize_prog(m)))
            hint_rig.run(p, ci, cm,
                         lambda m: dev_out.append(serialize_prog(m)))
            assert dev_out == cpu_out, f"seed {seed} call {ci}"
            checked += len(cpu_out)
    assert checked > 20, "lane parity never exercised a real mutant"
    assert hint_rig.stats.device_batches > 0
    assert telemetry.counter(
        "tz_acct_device_ms_total", "",
        labels={"lane": "hints"}).value > acct0, \
        "fused hint kernel time never booked to the hints lane"


def test_hint_lane_warm_rig_zero_new_jits(hint_rig, test_target_module):
    """Acceptance: once the lane's pow2 buckets are warm (the parity
    test above), further flushes at steady-state shapes compile
    NOTHING — the stacked tables and value columns reuse the same
    module-level kernel."""
    from syzkaller_tpu.telemetry import assert_no_new_compiles

    # Replay the parity test's exact case stream: identical window
    # counts and table dims land in identical (already-compiled) pow2
    # buckets.
    rs = np.random.RandomState(31)
    assert hint_rig.stats.device_batches > 0, "rig not warm"
    batches0 = hint_rig.stats.device_batches
    with assert_no_new_compiles():
        for seed in range(3):
            p, cm = _lane_case(test_target_module, rs, 700 + seed)
            for ci in range(len(p.calls)):
                hint_rig.run(p, ci, cm, lambda m: None)
    assert hint_rig.stats.device_batches > batches0


def test_hint_lane_sim_fold_suppression(hint_rig, test_target_module):
    """With a sim prescore attached, repeat (call site, comparand)
    replacers are suppressed and re-admitted when the sim plane's
    epoch advances."""

    class _Sim:
        epochs = 0

        def demoted(self):
            return False

    sim = _Sim()
    hint_rig.attach_sim(sim)
    try:
        rs = np.random.RandomState(47)
        first, p, cm = 0, None, None
        for seed in range(900, 910):  # find a case with real mutants
            sim.epochs += 1  # fresh fold plane per candidate
            p, cm = _lane_case(test_target_module, rs, seed)
            first = hint_rig.run(p, 0, cm, lambda m: None)
            if first > 0:
                break
        assert first > 0, "no case produced hint mutants"
        sup0 = hint_rig.stats.suppressed
        again = hint_rig.run(p, 0, cm, lambda m: None)
        assert hint_rig.stats.suppressed > sup0, \
            "repeat comparands were not suppressed"
        assert again < first
        sim.epochs += 1  # the sim plane decayed: re-admit everything
        readmitted = hint_rig.run(p, 0, cm, lambda m: None)
        assert readmitted == first, \
            "epoch decay did not re-admit suppressed replacers"
    finally:
        hint_rig._sim = None


def test_hint_lane_e2e_proc_coverage_attribution(test_target):
    """End-to-end acceptance: a Proc wired to the lane executes fused
    hint mutants, and their novel edges attribute to
    tz_coverage_novel_edges_total{lane="hints"}."""
    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
    from syzkaller_tpu.fuzzer.fuzzer import Stat
    from syzkaller_tpu.fuzzer.proc import Proc
    from syzkaller_tpu.ipc.env import make_env
    from syzkaller_tpu.ops.hintlane import HintLane

    cov0 = telemetry.counter("tz_coverage_novel_edges_total", "",
                             labels={"lane": "hints"}).value
    lane = HintLane()
    env = make_env(pid=0, sim=True, signal=True)
    try:
        fuzzer = Fuzzer(test_target, wq=WorkQueue(),
                        cfg=FuzzerConfig(minimize_attempts=1))
        proc = Proc(fuzzer, pid=0, env=env, device_hints=True,
                    hint_lane=lane)
        ran = 0
        for seed in range(30):
            p = generate_prog(test_target, RandGen(test_target, seed), 4)
            for ci in range(len(p.calls)):
                proc.execute_hint_seed(p, ci)
            if fuzzer.stats[Stat.HINT] > 0:
                ran = fuzzer.stats[Stat.HINT]
                break
        assert ran > 0, "no hint mutants executed via the lane"
        assert lane.stats.mutants > 0 and lane.stats.device_batches > 0
        assert telemetry.counter(
            "tz_coverage_novel_edges_total", "",
            labels={"lane": "hints"}).value > cov0, \
            "hint-mutant novelty not attributed to the hints lane"
    finally:
        env.close()
