"""syz_fuse_mount / syz_fuseblk_mount: descriptions, executor
dispatch, and csource rendering (reference: sys/linux/fuse.txt
pseudo-calls + executor/common_linux.h fuse helpers)."""

import os
import tempfile

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def linux():
    return get_target("linux", "amd64")


def test_fuse_calls_compiled(linux):
    by_name = {c.name: c for c in linux.syscalls}
    fm = by_name["syz_fuse_mount"]
    fbm = by_name["syz_fuseblk_mount"]
    assert fm.nr == 2164260873 and fbm.nr == 2164260874
    assert fm.ret is not None and fm.ret.name == fbm.ret.name
    assert len(fm.args) == 6 and len(fbm.args) == 8


@pytest.mark.skipif(not os.path.exists("/dev/fuse"), reason="no /dev/fuse")
def test_executor_fuse_mount(linux):
    """The executor opens /dev/fuse and returns the fd; with mount
    permission the fs appears (best-effort — the fd is the contract,
    reference ignores mount errors the same way)."""
    from tests.test_linux_executor import _run_text

    text = (b"r0 = syz_fuse_mount(&(0x7f0000000000)='./file0\\x00', "
            b"0x8000, 0x0, 0x0, 0x0, 0x0)\n"
            b"read(r0, &(0x7f0000001000)=\"\"/64, 0x40)\n")
    res = _run_text(linux, text)
    assert res.completed
    assert res.info[0].errno == 0, \
        f"syz_fuse_mount returned errno {res.info[0].errno}"
    # the read on the fuse fd has no pending INIT consumer semantics
    # guarantee (EPERM until a mount binds the fd, EAGAIN when bound
    # with nothing pending); it must simply not crash the executor
    assert res.info[1].errno in (0, 11, 1)


def test_csource_renders_fuse(linux):
    from syzkaller_tpu.csource.csource import Options, write_csource

    text = (b"r0 = syz_fuse_mount(&(0x7f0000000000)='./file0\\x00', "
            b"0x8000, 0x0, 0x0, 0x0, 0x0)\n"
            b"r1 = syz_fuseblk_mount(&(0x7f0000000040)='./file1\\x00', "
            b"&(0x7f0000000080)='./file2\\x00', 0x4000, 0x0, 0x0, 0x0, "
            b"0x200, 0x0)\n")
    p = deserialize_prog(linux, text)
    src = write_csource(p, Options()).decode()
    assert "static long syz_fuse_mount" in src
    assert "static long syz_fuseblk_mount" in src
    assert src.count("static void tz_fuse_opts") == 1


def test_csource_fuse_compiles(linux):
    from syzkaller_tpu.csource.build import build_csource
    from syzkaller_tpu.csource.csource import Options, write_csource

    text = (b"r0 = syz_fuseblk_mount(&(0x7f0000000040)='./file1\\x00', "
            b"&(0x7f0000000080)='./file2\\x00', 0x4000, 0x0, 0x0, 0x0, "
            b"0x200, 0x0)\n")
    p = deserialize_prog(linux, text)
    src = write_csource(p, Options())
    binpath = build_csource(src)
    os.unlink(binpath)
