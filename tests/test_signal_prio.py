"""Signal semantics, choice-table sampling, minimization and hints
(reference strategy: pkg/signal tests, prog/minimization_test.go,
prog/hints_test.go golden tables)."""

import random

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.hints import CompMap, mutate_with_hints, shrink_expand
from syzkaller_tpu.models.minimization import minimize
from syzkaller_tpu.models.prio import build_choice_table, calculate_priorities
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.signal import Signal, from_raw, minimize_corpus


def test_signal_diff_merge():
    s = from_raw([1, 2, 3], 1)
    d = s.diff_raw([2, 3, 4], 2)
    assert d.m == {2: 2, 3: 2, 4: 2}
    d2 = s.diff_raw([1, 2], 1)
    assert d2.empty()
    s.merge(from_raw([3, 4], 3))
    assert s.m == {1: 1, 2: 1, 3: 3, 4: 3}
    inter = s.intersection(from_raw([1, 3], 2))
    assert inter.m == {1: 1}  # 3 has prio 3 > 2 in s, dropped


def test_signal_minimize_corpus():
    corpus = [
        (from_raw([1, 2, 3, 4], 1), "big"),
        (from_raw([1, 2], 1), "subset"),
        (from_raw([5], 1), "unique"),
    ]
    kept = set(minimize_corpus(corpus))
    assert kept == {"big", "unique"}


def test_choice_table_sampling(test_target):
    rng = RandGen(test_target, 0)
    corpus = [generate_prog(test_target, RandGen(test_target, i), 6)
              for i in range(5)]
    prios = calculate_priorities(test_target, corpus)
    n = len(test_target.syscalls)
    assert all(len(row) == n for row in prios)
    # static x dynamic, each normalized to [0.1, 1] -> product in [0.01, 1]
    assert all(0.01 <= p <= 1.0 for row in prios for p in row)
    ct = build_choice_table(test_target, prios)
    # Sampling respects enabled set and returns valid ids.
    for _ in range(200):
        idx = ct.choose(rng, rng.intn(n))
        assert 0 <= idx < n
    # Restricted enabled set.
    subset = {c: True for c in test_target.syscalls[:10]}
    ct2 = build_choice_table(test_target, prios, subset)
    for _ in range(100):
        assert ct2.choose(rng, 3) < 10


def test_minimize_simple(test_target):
    # Only the call with a nonzero first arg matters.
    p = deserialize_prog(test_target, b"\n".join([
        b"tz_nop()",
        b"tz_nop$ints(0x7, 0x0, 0x0, 0x0, 0x0)",
        b"tz_nop()",
    ]) + b"\n")

    def pred(p1, ci):
        for c in p1.calls:
            if c.meta.name == "tz_nop$ints" and c.args[0].val == 7:
                return True
        return False

    p1, ci = minimize(p, -1, False, pred)
    assert len(p1.calls) == 1
    assert p1.calls[0].meta.name == "tz_nop$ints"


def test_minimize_keeps_call_index(test_target):
    p = deserialize_prog(test_target, b"\n".join([
        b"tz_nop()",
        b"r0 = tz_res$make()",
        b"tz_res$use(r0)",
    ]) + b"\n")

    def pred(p1, ci):
        return ci >= 0 and p1.calls[ci].meta.name == "tz_res$use"

    p1, ci = minimize(p, 2, False, pred)
    assert p1.calls[ci].meta.name == "tz_res$use"
    assert len(p1.calls) <= 2


def test_minimize_data(test_target):
    # array[int8] lowers to a byte buffer; minimization bisects its length.
    text = b'tz_mut$blob(&(0x7f0000000000)="0101010101010101", 0x8)\n'
    p = deserialize_prog(test_target, text)

    def pred(p1, ci):
        if not p1.calls:
            return False
        return len(p1.calls[0].args[0].res.data) >= 2

    p1, _ = minimize(p, -1, False, pred)
    buf = p1.calls[0].args[0].res
    assert len(buf.data) == 2
    # size field reassigned
    assert p1.calls[0].args[1].val == 2


def test_minimize_array_elems(test_target):
    # tz_mut$vec: ptr[in, array[int32[0:1]]] stays a real array.
    text = b'tz_mut$vec(&(0x7f0000000000)=[0x1, 0x1, 0x1, 0x1], 0x4)\n'
    p = deserialize_prog(test_target, text)

    def pred(p1, ci):
        if not p1.calls:
            return False
        return len(p1.calls[0].args[0].res.inner) >= 2

    p1, _ = minimize(p, -1, False, pred)
    arr = p1.calls[0].args[0].res
    assert len(arr.inner) == 2
    assert p1.calls[0].args[1].val == 2


def test_minimize_random(test_target, iters):
    for i in range(max(4, iters // 4)):
        rng = RandGen(test_target, 7000 + i)
        p = generate_prog(test_target, rng, 6)
        # pred: always true -> everything removable except nothing pinned
        p1, _ = minimize(p.clone(), -1, False, lambda q, ci: True)
        assert len(p1.calls) <= 1
        # pred: always false -> program unchanged
        p2, _ = minimize(p.clone(), -1, False, lambda q, ci: False)
        assert serialize_prog(p2) == serialize_prog(p)


# -- shrink/expand golden cases (reference: prog/hints_test.go:216-365) --

def cm(d):
    m = CompMap()
    for k, vals in d.items():
        for v in vals:
            m.add_comp(k, v)
    return m


def test_shrink_16():
    got = shrink_expand(0x1234, cm({0x34: [0xAB], 0x1234: [0xCDCD]}))
    assert got == {0x12AB, 0xCDCD}


def test_shrink_32():
    got = shrink_expand(0x12345678, cm({
        0x78: [0xAB], 0x5678: [0xCDCD], 0x12345678: [0xEFEFEFEF]}))
    assert got == {0x123456AB, 0x1234CDCD, 0xEFEFEFEF}


def test_shrink_64():
    got = shrink_expand(0x1234567890ABCDEF, cm({
        0xEF: [0xAB], 0xCDEF: [0xCDCD],
        0x90ABCDEF: [0xEFEFEFEF],
        0x1234567890ABCDEF: [0x0101010101010101]}))
    assert got == {0x1234567890ABCDAB, 0x1234567890ABCDCD,
                   0x12345678EFEFEFEF, 0x0101010101010101}


def test_shrink_wider_replacer_rejected():
    assert shrink_expand(0x1234, cm({0x34: [0x1BAB]})) == set()


def test_shrink_sign_extended_replacer():
    got = shrink_expand(0x1234, cm({0x34: [0xFFFFFFFFFFFFFFFD]}))
    assert got == {0x12FD}


def test_expand_8_16_32():
    neg1 = 0xFFFFFFFFFFFFFFFF
    neg2 = 0xFFFFFFFFFFFFFFFE
    assert shrink_expand(0xFF, cm({neg1: [neg2]})) == {0xFE}
    assert shrink_expand(0xFFFF, cm({neg1: [neg2]})) == {0xFFFE}
    assert shrink_expand(0xFFFFFFFF, cm({neg1: [neg2]})) == {0xFFFFFFFE}


def test_expand_wider_replacer_rejected():
    assert shrink_expand(
        0xFF, cm({0xFFFFFFFFFFFFFFFF: [0xFFFFFFFFFFFFFEFF]})) == set()


def test_special_ints_filtered():
    # 0x100 (=256) is a special int; replacements to it are skipped.
    assert shrink_expand(0x1234, cm({0x1234: [0x100]})) == set()


def test_hints_end_to_end(test_target):
    p = deserialize_prog(
        test_target,
        b'tz_hint$data(&(0x7f0000000000)="11223344")\n')
    comps = CompMap()
    # data starts with 0x44332211 little-endian word
    comps.add_comp(0x44332211, 0xDEADBEEF)
    mutants = []
    mutate_with_hints(p, 0, comps, lambda q: mutants.append(serialize_prog(q)))
    assert any(b"efbead" in m for m in mutants), mutants
    # original program untouched
    assert b"11223344" in serialize_prog(p)


def test_hints_random(test_target, iters):
    for i in range(max(3, iters // 10)):
        rng = RandGen(test_target, 8000 + i)
        p = generate_prog(test_target, rng, 5)
        for ci in range(len(p.calls)):
            comps = CompMap()
            for _ in range(5):
                comps.add_comp(rng.rand_int(), rng.rand_int())
            mutate_with_hints(p, ci, comps, lambda q: None)
