"""Crash report parsing + VM layer tests."""

import threading
import time

import pytest

from syzkaller_tpu.report import get_reporter
from syzkaller_tpu.vm.vm import monitor_execution
from syzkaller_tpu.vm.vmimpl import Env, OutputStream, create_pool_impl


# -- report parsing ------------------------------------------------------

KASAN_LOG = b"""\
[  123.456789] ==================================================================
[  123.456790] BUG: KASAN: use-after-free in ip6_send_skb+0x2f5/0x330
[  123.456791] Read of size 8 at addr ffff8800398b4e00 by task syz-executor/1234
[  123.456792] Call Trace:
[  123.456793]  dump_stack+0x1b2/0x281
[  123.456794]  print_address_description+0x6f/0x22b
[  123.456795]  kasan_report+0x23f/0x350
[  123.456796]  ip6_send_skb+0x2f5/0x330
[  123.456797]  udpv6_sendmsg+0x2c1a/0x3420
"""

WARNING_LOG = b"""\
[   45.1] WARNING: CPU: 1 PID: 4321 at net/core/dev.c:2345 skb_warn_bad_offload+0x2bc/0x2d0
[   45.2] Call Trace:
[   45.3]  __warn+0x1b2/0x281
[   45.4]  skb_warn_bad_offload+0x2bc/0x2d0
"""

HUNG_LOG = b"""\
INFO: task syz-executor7:11249 blocked for more than 120 seconds.
      Not tainted 4.14.0+ #35
"""

DEADLOCK_LOG = b"""\
======================================================
WARNING: possible circular locking dependency detected
4.14.0-rc5+ #62 Not tainted
------------------------------------------------------
"""

GPF_LOG = b"""\
kasan: GPF could be caused by NULL-ptr deref or user memory access
general protection fault: 0000 [#1] SMP KASAN
Modules linked in:
CPU: 1 PID: 22753 Comm: syz-executor3 Not tainted 4.14.0+
task: ffff8801cc1a45c0 task.stack: ffff8801c08a8000
RIP: 0010:sctp_stream_free+0xb1/0x120
Call Trace:
 sctp_association_free+0x1f0/0x740
"""

SIM_LOG = b"""\
spawning child 1234
BUG: sim-kernel: use-after-free in sim_call_17
Call Trace:
 sim_call_17+0x3fc
 sim_dispatch+0x11
"""

PANIC_LOG = b"Kernel panic - not syncing: Fatal exception in interrupt\n"


@pytest.fixture(scope="module")
def linux_reporter():
    return get_reporter("linux")


@pytest.mark.parametrize("log,title", [
    (KASAN_LOG, "KASAN: use-after-free in ip6_send_skb"),
    (WARNING_LOG, "WARNING in skb_warn_bad_offload"),
    (HUNG_LOG, "INFO: task hung in syz-executor7"),
    (DEADLOCK_LOG, "possible deadlock (circular locking)"),
    (GPF_LOG, "general protection fault in sctp_stream_free"),
    (SIM_LOG, "BUG: sim-kernel: use-after-free in sim_call_17"),
    (PANIC_LOG, "kernel panic: Fatal exception in interrupt"),
])
def test_parse_titles(linux_reporter, log, title):
    assert linux_reporter.contains_crash(log)
    rep = linux_reporter.parse(log)
    assert rep is not None
    assert rep.title == title


def test_no_crash(linux_reporter):
    clean = b"booting...\nexecuting program 0:\nr0 = open(...)\nall good\n"
    assert not linux_reporter.contains_crash(clean)
    assert linux_reporter.parse(clean) is None


def test_title_dedup_across_addresses(linux_reporter):
    log2 = KASAN_LOG.replace(b"ffff8800398b4e00", b"ffff88003deadbee") \
                    .replace(b"0x2f5/0x330", b"0x111/0x330")
    assert linux_reporter.parse(KASAN_LOG).title == \
        linux_reporter.parse(log2).title


def test_guilty_function_skips_infrastructure(linux_reporter):
    rep = linux_reporter.parse(KASAN_LOG)
    # dump_stack/print_address_description/kasan_report are never guilty
    assert rep.guilty_file == "ip6_send_skb"


def test_corrupted_without_stack(linux_reporter):
    log = b"BUG: KASAN: use-after-free in foo_bar+0x11/0x20\n(cut)\n"
    rep = linux_reporter.parse(log)
    assert rep.corrupted


def test_suppressions():
    r = get_reporter("linux", suppressions=["KASAN: use-after-free in ip6"])
    rep = r.parse(KASAN_LOG)
    assert rep.suppressed
    rep2 = r.parse(WARNING_LOG)
    assert not rep2.suppressed


def test_ignores_line():
    r = get_reporter("linux", ignores=[rb"WARNING: CPU: \d+ PID"])
    assert not r.contains_crash(WARNING_LOG)
    assert r.contains_crash(KASAN_LOG)


def test_sim_reporter_registered():
    r = get_reporter("test")
    assert r.parse(SIM_LOG).title == \
        "BUG: sim-kernel: use-after-free in sim_call_17"


# -- vm monitor ----------------------------------------------------------


def _feed(stream, chunks, finish_error=None, delay=0.0):
    def run():
        for c in chunks:
            if delay:
                time.sleep(delay)
            stream.put(c)
        stream.finish(finish_error)

    threading.Thread(target=run, daemon=True).start()


def test_monitor_detects_crash(linux_reporter):
    stream = OutputStream()
    _feed(stream, [b"executing program 1\n", KASAN_LOG, b"tail\n"])
    res = monitor_execution(stream, linux_reporter)
    assert res.report is not None
    assert res.report.title == "KASAN: use-after-free in ip6_send_skb"


def test_monitor_clean_exit(linux_reporter):
    stream = OutputStream()
    _feed(stream, [b"executing program 1\ndone\n"])
    res = monitor_execution(stream, linux_reporter, exit_ok=True)
    assert res.report is None


def test_monitor_lost_connection(linux_reporter):
    stream = OutputStream()
    _feed(stream, [b"executing program 1\n"],
          finish_error=RuntimeError("ssh died"))
    res = monitor_execution(stream, linux_reporter)
    assert res.report.title == "lost connection to test machine"
    assert res.lost_connection


def test_monitor_no_output_timeout(linux_reporter):
    stream = OutputStream()
    # nothing ever arrives; use a tiny timeout
    res = monitor_execution(stream, linux_reporter,
                            no_output_timeout=0.1,
                            not_executing_timeout=0.1)
    assert res.timed_out
    assert "not executing programs" in res.report.title or \
        "no output" in res.report.title


def test_monitor_not_executing(linux_reporter):
    stream = OutputStream()

    def chatter():
        for _ in range(8):
            stream.put(b"chatter but no exec marker\n")
            time.sleep(0.05)
        stream.finish()

    threading.Thread(target=chatter, daemon=True).start()
    res = monitor_execution(stream, linux_reporter,
                            not_executing_timeout=0.2,
                            no_output_timeout=60)
    assert res.report.title in ("test machine is not executing programs",
                                "lost connection to test machine")


# -- local pool ----------------------------------------------------------


def test_local_pool_run_and_crash_detection(tmp_path, linux_reporter):
    env = Env(name="t", os="test", workdir=str(tmp_path),
              config={"count": 2})
    pool = create_pool_impl("local", env)
    assert pool.count() == 2
    inst = pool.create(str(tmp_path / "inst0"), 0)
    # copy
    src = tmp_path / "payload.txt"
    src.write_text("hello")
    dst = inst.copy(str(src))
    assert open(dst).read() == "hello"
    # run a command that prints an exec marker then a crash
    stop = threading.Event()
    stream = inst.run(
        30.0, stop,
        "echo 'executing program 0'; "
        "echo 'BUG: sim-kernel: use-after-free in sim_call_3'; "
        "printf 'Call Trace:\\n sim_call_3+0x1f\\n sim_dispatch+0x11\\n'")
    res = monitor_execution(stream, linux_reporter, exit_ok=True)
    assert res.report is not None
    assert res.report.title == "BUG: sim-kernel: use-after-free in sim_call_3"
    inst.close()


def test_local_pool_clean_run(tmp_path, linux_reporter):
    env = Env(name="t", os="test", workdir=str(tmp_path), config={})
    pool = create_pool_impl("local", env)
    inst = pool.create(str(tmp_path / "inst0"), 0)
    stop = threading.Event()
    stream = inst.run(30.0, stop, "echo 'executing program 0'; sleep 0.1")
    res = monitor_execution(stream, linux_reporter, exit_ok=True)
    assert res.report is None
    inst.close()
