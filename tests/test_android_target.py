"""android targets: the linux model + ION staging surface
(reference tree: sys/android/ion.txt layered on the linux set)."""

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target

ION_CALLS = {
    "openat$ion", "ioctl$ION_IOC_ALLOC", "ioctl$ION_IOC_FREE",
    "ioctl$ION_IOC_MAP", "ioctl$ION_IOC_SHARE", "ioctl$ION_IOC_IMPORT",
    "ioctl$ION_IOC_SYNC", "ioctl$ION_IOC_CUSTOM",
}


@pytest.fixture(scope="module")
def android():
    return get_target("android", "amd64")


def test_superset_of_linux(android):
    linux = get_target("linux", "amd64")
    android_names = {c.name for c in android.syscalls}
    linux_names = {c.name for c in linux.syscalls}
    assert linux_names <= android_names
    assert android_names - linux_names == ION_CALLS


def test_ion_calls_enabled(android):
    by_name = {c.name: c for c in android.syscalls}
    for name in ION_CALLS:
        assert name in by_name
    # the typed opener produces fd_ion, consumed by the ioctls
    opener = by_name["openat$ion"]
    assert opener.ret is not None
    alloc = by_name["ioctl$ION_IOC_ALLOC"]
    assert alloc.args[0].__class__.__name__ == "ResourceType"


def test_ion_ioctl_encodings(android):
    """ION_IOC_* are _IOWR('I', nr, size) — dir/type/nr/size facts of
    the 3.18 uapi, spot-checked against the computed encoding."""
    by_name = {c.name: c for c in android.syscalls}

    def cmd_of(call):
        return by_name[call].args[1].val

    def iowr(nr, size):
        return (3 << 30) | (size << 16) | (ord("I") << 8) | nr

    assert cmd_of("ioctl$ION_IOC_ALLOC") == iowr(0, 32)
    assert cmd_of("ioctl$ION_IOC_FREE") == iowr(1, 4)
    assert cmd_of("ioctl$ION_IOC_MAP") == iowr(2, 8)
    assert cmd_of("ioctl$ION_IOC_CUSTOM") == iowr(6, 16)


def test_generate_roundtrip_both_arches(android):
    for t in (android, get_target("android", "arm64")):
        p = generate_prog(t, RandGen(t, 3), 10)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(t, s)) == s


def test_arm64_uses_arm64_nr_table():
    t = get_target("android", "arm64")
    ioctl = next(c for c in t.syscalls if c.name == "ioctl")
    assert ioctl.nr == 29  # generic unistd, not amd64's 16
