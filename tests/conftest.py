"""Test harness configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
paths compile and execute without TPU hardware; the bench path runs on
the real chip separately (bench.py).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin ignores the JAX_PLATFORMS env var in this
# environment; the config flag is honored.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import syzkaller_tpu.models.validation as validation  # noqa: E402


@pytest.fixture(autouse=True)
def _debug_validation():
    # Validate program structure after every random op in tests
    # (reference: prog/export_test.go:15-17).
    validation.debug = True
    yield
    validation.debug = False


@pytest.fixture
def test_target():
    from syzkaller_tpu.models.target import get_target

    return get_target("test", "64")


@pytest.fixture
def linux_target():
    from syzkaller_tpu.models.target import get_target

    return get_target("linux", "amd64")


def pytest_addoption(parser):
    parser.addoption("--iters", type=int, default=None,
                     help="iterations for randomized tests")


def pytest_sessionfinish(session, exitstatus):
    """Exit-hygiene diagnostic (VERDICT r4 weak #8): the suite once sat
    minutes in interpreter teardown after [100%].  Name every survivor
    that can delay exit — non-daemon threads block threading._shutdown,
    and un-reaped children keep the process group's pipes open."""
    import subprocess
    import threading

    rogue = [t for t in threading.enumerate()
             if t is not threading.main_thread() and not t.daemon]
    if rogue:
        print(f"\n[conftest] NON-DAEMON THREADS ALIVE AT EXIT: "
              f"{[t.name for t in rogue]}", flush=True)
    try:
        out = subprocess.run(
            ["ps", "--ppid", str(os.getpid()), "-o", "pid=,comm="],
            capture_output=True, text=True, timeout=10).stdout.strip()
        kids = [ln.split() for ln in out.splitlines() if "ps" not in ln]
        if kids:
            print(f"[conftest] CHILD PROCESSES ALIVE AT EXIT: {kids} "
                  f"— killing (a fork-while-JAX-threaded child can "
                  f"deadlock pre-exec and wedge teardown)", flush=True)
        import signal
        for pid_comm in kids:
            try:
                os.kill(int(pid_comm[0]), signal.SIGKILL)
            except (OSError, ValueError, IndexError):
                pass
        while True:
            try:
                if os.waitpid(-1, os.WNOHANG) == (0, 0):
                    break
            except ChildProcessError:
                break
    except Exception:
        pass


@pytest.fixture
def iters(request):
    n = request.config.getoption("--iters")
    return n if n is not None else 30
