"""Test harness configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
paths compile and execute without TPU hardware; the bench path runs on
the real chip separately (bench.py).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin ignores the JAX_PLATFORMS env var in this
# environment; the config flag is honored.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import syzkaller_tpu.models.validation as validation  # noqa: E402


@pytest.fixture(autouse=True)
def _debug_validation():
    # Validate program structure after every random op in tests
    # (reference: prog/export_test.go:15-17).
    validation.debug = True
    yield
    validation.debug = False


@pytest.fixture
def test_target():
    from syzkaller_tpu.models.target import get_target

    return get_target("test", "64")


@pytest.fixture
def linux_target():
    from syzkaller_tpu.models.target import get_target

    return get_target("linux", "amd64")


def pytest_addoption(parser):
    parser.addoption("--iters", type=int, default=None,
                     help="iterations for randomized tests")


@pytest.fixture
def iters(request):
    n = request.config.getoption("--iters")
    return n if n is not None else 30
