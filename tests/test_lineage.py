"""Lineage tracing (telemetry/lineage.py, ISSUE 6): sampling,
per-stage wait histograms, wire roundtrip through the RPC frame
header, the correlated Perfetto track, and the zero-per-mutant-
overhead contract.  All host-only and stdlib-fast — the warm-pipeline
end-to-end propagation test lives in test_health_faults.py (shares
the module-scoped device rig, no new jit compiles)."""

from __future__ import annotations

import json
import threading

import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry import lineage


@pytest.fixture(autouse=True)
def _restore_rate():
    yield
    lineage.set_sample_rate(None)


# -- sampling -----------------------------------------------------------


def test_mint_respects_sample_rate():
    lineage.set_sample_rate(0.0)
    assert lineage.mint() is None  # the zero-overhead path
    lineage.set_sample_rate(1.0)
    ctx = lineage.mint()
    assert ctx is not None and ctx.sampled and ctx.trace_id
    assert ctx.last_stage == "lineage.mint"
    # two mints get distinct ids
    other = lineage.mint()
    assert other.trace_id != ctx.trace_id


def test_sample_rate_env_parse(monkeypatch):
    lineage.set_sample_rate(None)
    monkeypatch.setenv(lineage.ENV_SAMPLE, "0.25")
    assert lineage.sample_rate() == 0.25
    lineage.set_sample_rate(None)
    monkeypatch.setenv(lineage.ENV_SAMPLE, "not-a-rate")
    assert lineage.sample_rate() == 0.0  # envsafe: malformed -> off
    lineage.set_sample_rate(None)
    monkeypatch.setenv(lineage.ENV_SAMPLE, "7")
    assert lineage.sample_rate() == 1.0  # clamped


def test_sampled_counter_advances():
    c = telemetry.REGISTRY.counter("tz_lineage_sampled_total")
    before = c.value
    lineage.set_sample_rate(1.0)
    lineage.mint()
    assert c.value == before + 1


# -- hops ---------------------------------------------------------------


def test_hop_records_stage_wait_and_advances_stage():
    lineage.set_sample_rate(1.0)
    ctx = lineage.mint()
    h = telemetry.REGISTRY.histogram("tz_lineage_deliver_wait_seconds")
    before = h.count
    lineage.hop(ctx, "pipeline.deliver")
    assert h.count == before + 1
    assert ctx.last_stage == "pipeline.deliver"
    # None context: every hop is one `is None` test, nothing recorded
    lineage.hop(None, "pipeline.deliver")
    assert h.count == before + 1


# -- the wire form (RPC frame header) -----------------------------------


def test_wire_roundtrip_records_rpc_hop():
    lineage.set_sample_rate(1.0)
    ctx = lineage.mint()
    h = telemetry.REGISTRY.histogram("tz_lineage_rpc_wait_seconds")
    before = h.count
    data = lineage.to_wire(ctx)
    assert len(data) == lineage.WIRE.size
    got = lineage.from_wire(data)
    assert got.trace_id == ctx.trace_id and got.sampled
    assert got.last_stage == "rpc.frame"
    assert h.count == before + 1


def test_rpc_frame_carries_trace_to_server_thread():
    """The cross-process edge: a traced client call parks the decoded
    context in the server handler thread's thread-local, and an
    untraced call clears it (no stale context bleeds into the next
    dispatch on a pooled connection)."""
    from syzkaller_tpu.rpc import RPCClient, RPCServer

    seen: list = []

    class Svc:
        def Probe(self, params):
            ctx = lineage.current()
            seen.append(None if ctx is None else ctx.trace_id)
            return {"ok": True}

    srv = RPCServer()
    srv.register("Svc", Svc())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    try:
        lineage.set_sample_rate(1.0)
        ctx = lineage.mint()
        assert cli.call("Svc.Probe", {}, trace=ctx) == {"ok": True}
        assert cli.call("Svc.Probe", {}) == {"ok": True}
        assert seen == [ctx.trace_id, None]
    finally:
        cli.close()
        srv.close()


# -- the correlated track -----------------------------------------------


def test_trace_file_renders_one_correlated_track(tmp_path):
    """Every lifecycle hop of a sampled context lands in TZ_TRACE_FILE
    as an async-instant event keyed by ONE trace id — the Perfetto
    correlation contract — including the hop recorded on the RPC
    server's thread (a different tid, standing in for the second
    process whose pid the production deployment supplies)."""
    from syzkaller_tpu.rpc import RPCClient, RPCServer

    path = tmp_path / "trace.json"
    telemetry.set_trace_file(str(path))
    srv = RPCServer()

    class Svc:
        def Probe(self, params):
            return {}

    srv.register("Svc", Svc())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    try:
        lineage.set_sample_rate(1.0)
        ctx = lineage.mint()
        lineage.hop(ctx, "pipeline.deliver")
        lineage.hop(ctx, "proc.draw")
        cli.call("Svc.Probe", {}, trace=ctx)
        lineage.hop(ctx, "triage.verdict")
        lineage.hop(ctx, "corpus.add")
    finally:
        cli.close()
        srv.close()
        telemetry.set_trace_file(None)
    events = [json.loads(ln.rstrip(",")) for ln in
              path.read_text().splitlines()[1:]]
    track = [e for e in events if e.get("cat") == "tz.lineage"
             and e.get("id") == format(ctx.trace_id, "016x")]
    stages = {e["name"] for e in track}
    assert {"lineage.mint", "pipeline.deliver", "proc.draw",
            "rpc.frame", "triage.verdict", "corpus.add"} <= stages
    assert all(e["ph"] == "n" for e in track)
    # the rpc.frame hop was emitted from the server handler thread
    assert len({e["tid"] for e in track}) >= 2
    # hops after the first carry the measured wait
    waits = [e["args"]["wait_s"] for e in track
             if e["name"] != "lineage.mint"]
    assert all(w >= 0 for w in waits)


# -- zero per-mutant overhead -------------------------------------------


def test_exec_mutant_has_no_per_mutant_trace_storage():
    """The context lives on the BATCH; ExecMutant.trace is a property
    over the batch reference — unsampled mutants allocate nothing."""
    from syzkaller_tpu.ops.pipeline import AssembledBatch, ExecMutant

    assert "trace" not in ExecMutant.__slots__
    assert isinstance(ExecMutant.trace, property)
    ab = AssembledBatch(seq=3)
    assert ab.trace is None  # unsampled default


def test_cpu_check_path_hops_verdict():
    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.models.target import get_target

    fz = Fuzzer(get_target("test", "64"), wq=WorkQueue())
    lineage.set_sample_rate(1.0)
    ctx = lineage.mint()
    h = telemetry.REGISTRY.histogram("tz_lineage_verdict_wait_seconds")
    before = h.count
    assert fz.check_new_signal_fn(lambda e, i: 3, [], trace=ctx) == []
    assert h.count == before + 1
    assert ctx.last_stage == "triage.verdict"
    # and the no-trace call (every unsampled mutant) records nothing
    assert fz.check_new_signal_fn(lambda e, i: 3, []) == []
    assert h.count == before + 1
