"""Pallas mutation-core bit-exactness (ISSUE 10): the grid-over-batch
kernels in ops/pallas_mutate — run in interpret mode on CPU — must be
byte-identical to the vmap reference over the SAME threefry keys.
Pinned here: the full-state mutator (every output field), targeted
coverage of each value-slot kind (INT/FLAGS/PROC/LEN) and of the
dead-call removal + LEN fixup path, all seven `_mutate_data_span`
byte-arena ops via host-side key search, the fused mutate+pack
kernel against the pipeline's vmap `one`, and the grid-sequential
pool assigner (including the overflow path) against the prefix-sum
assigner.

Interpret-mode pallas traces are compile-dominated (~10 s each, warm
calls are free), so the module keeps exactly three expensive traces:
ONE mutator pair shared by every mutator-level test (module-scoped
fixture, one fixed batch shape), one fused-pack trace, one data-span
trace.  ROADMAP budget discipline: everything else reuses them."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.models.target import get_target  # noqa: E402
from syzkaller_tpu.ops import rng as d  # noqa: E402
from syzkaller_tpu.ops.delta import (  # noqa: E402
    DeltaSpec,
    _make_pool_assigner,
    make_packer,
)
from syzkaller_tpu.ops.mutate import (  # noqa: E402
    _mutate_data_span,
    _mutate_one,
    make_mutator,
)
from syzkaller_tpu.ops.pallas_mutate import (  # noqa: E402
    _OUT_EXTRA,
    _STATE_KEYS,
    _grid_apply,
    make_pallas_mutate_pack,
    make_pallas_mutator,
    make_pallas_pool_assigner,
    resolve_mutate_backend,
)
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    DATA,
    EMPTY,
    FLAGS,
    INT,
    LEN,
    PROC,
    FlagTables,
    TensorConfig,
    encode_prog,
)

CFG = TensorConfig(max_slots=128, arena=2048, max_blob=768)
FLAG_TABLES = FlagTables.empty()
ROUNDS = 2
BATCH = 6  # every mutator-level test uses this shape (one trace)


@pytest.fixture(scope="module")
def base_batch():
    """BATCH stacked program tensors (cycled if generation rejects
    some) — the ONE shape the shared mutator pair is traced at."""
    target = get_target("test", "64")
    arrs = []
    i = 0
    while len(arrs) < BATCH and i < BATCH * 8:
        p = generate_prog(target, RandGen(target, 500 + i), 6)
        i += 1
        try:
            arrs.append(encode_prog(p, CFG, FLAG_TABLES).arrays())
        except Exception:
            continue
    assert arrs
    return {k: jnp.stack([jnp.asarray(arrs[j % len(arrs)][k])
                          for j in range(BATCH)])
            for k in arrs[0]}


@pytest.fixture(scope="module")
def mutators():
    """The (vmap, pallas-interpret) mutator pair every parity test
    shares — each is one jitted callable, so all calls at the
    base_batch shape after the first reuse the same executable."""
    return (make_mutator(rounds=ROUNDS, backend="vmap"),
            make_pallas_mutator(rounds=ROUNDS, interpret=True))


def _flag_arrays():
    return jnp.asarray(FLAG_TABLES.vals), jnp.asarray(FLAG_TABLES.counts)


def _assert_state_equal(ref, got):
    for k in _STATE_KEYS + _OUT_EXTRA:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]),
            err_msg=f"backend divergence in field {k!r}")


def test_resolve_backend(monkeypatch):
    """auto = vmap off-TPU; explicit argument beats the env knob; a
    typo'd knob degrades to auto (health.envsafe discipline)."""
    monkeypatch.delenv("TZ_MUTATE_BACKEND", raising=False)
    assert resolve_mutate_backend() == "vmap"  # CPU test rig
    assert resolve_mutate_backend("pallas") == "pallas"
    monkeypatch.setenv("TZ_MUTATE_BACKEND", "pallas")
    assert resolve_mutate_backend() == "pallas"
    assert resolve_mutate_backend("vmap") == "vmap"
    monkeypatch.setenv("TZ_MUTATE_BACKEND", "palas")  # typo -> auto
    assert resolve_mutate_backend() == "vmap"


def test_mutator_parity_randomized(base_batch, mutators):
    """Full mutate_batch parity over randomized keys: every output
    field (state + the preserve_sizes/touched journals) bit-equal."""
    ref_fn, got_fn = mutators
    fv, fc = _flag_arrays()
    touched_any = False
    for trial in range(3):
        key = random.key(100 + trial)
        ref = ref_fn(base_batch, key, fv, fc)
        got = got_fn(base_batch, key, fv, fc)
        _assert_state_equal(ref, got)
        touched_any |= bool(np.asarray(ref["touched"]).any())
    assert touched_any, "no trial mutated any slot — keys too unlucky"


def test_slot_kind_parity_per_kind(base_batch, mutators):
    """Each value-slot mutator (and the DATA byte engine) covered in
    one batch: row j's slot 0 is forced to kind KINDS[j] and every
    other slot EMPTY, so masked_choice must pick it and the kind's
    branch is the one whose output survives — same shape as
    base_batch, so the shared mutator executable is reused."""
    KINDS = (INT, FLAGS, PROC, LEN, DATA)
    kind = np.full(np.asarray(base_batch["kind"]).shape, EMPTY,
                   dtype=np.asarray(base_batch["kind"]).dtype)
    for j, kc in enumerate(KINDS):
        kind[j, 0] = kc
    kind[len(KINDS):, 0] = INT  # spare rows: more INT coverage
    kb = dict(base_batch)
    kb["kind"] = jnp.asarray(kind)
    kb["call"] = base_batch["call"].at[:, 0].set(0)
    kb["call_alive"] = base_batch["call_alive"].at[:, 0].set(True)
    kb["width"] = base_batch["width"].at[:, 0].set(8)
    kb["flag_set"] = base_batch["flag_set"].at[:, 0].set(0)
    kb["aux1"] = base_batch["aux1"].at[:, 0].set(64)  # PROC range
    j_data = KINDS.index(DATA)
    kb["off"] = base_batch["off"].at[j_data, 0].set(0)
    kb["cap"] = base_batch["cap"].at[j_data, 0].set(64)
    kb["len_"] = base_batch["len_"].at[j_data, 0].set(16)

    ref_fn, got_fn = mutators
    fv, fc = _flag_arrays()
    # Several keys so the 1/11 remove class can't mask a whole kind
    # (a removed call leaves its row's forced slot untouched).
    touched = np.zeros(len(base_batch["kind"]), dtype=bool)
    for seed in range(4):
        ref = ref_fn(kb, random.key(7 + seed), fv, fc)
        got = got_fn(kb, random.key(7 + seed), fv, fc)
        _assert_state_equal(ref, got)
        touched |= np.asarray(ref["touched"])[:, 0]
    for j, kc in enumerate(KINDS):
        assert touched[j], \
            f"forced kind {kc} (row {j}) never mutated — not covered"


def test_dead_call_removal_parity(base_batch, mutators):
    """The remove-call class (1/11 per round) + the LEN fixup that
    follows: search keys on the vmap reference until a batch actually
    kills a call, then pin Pallas parity on that exact key."""
    ref_fn, got_fn = mutators
    fv, fc = _flag_arrays()
    alive0 = np.asarray(base_batch["call_alive"])
    key = None
    for seed in range(40):
        ref = ref_fn(base_batch, random.key(9000 + seed), fv, fc)
        if (np.asarray(ref["call_alive"]) != alive0).any():
            key = random.key(9000 + seed)
            break
    assert key is not None, "no key removed a call in 40 tries"
    got = got_fn(base_batch, key, fv, fc)
    _assert_state_equal(ref, got)


def test_data_span_ops_parity_all_seven():
    """All seven byte-arena ops (flip/insert/remove/append/replace/
    addsub/interesting): host-side key search picks one key per op
    branch (`d.intn(k_op, 7)` over the same split _mutate_data_span
    performs), then the unbatched reference and the grid kernel must
    agree byte-for-byte on (arena, length, ok)."""
    A = 128
    arena0 = jnp.asarray(
        np.random.RandomState(3).randint(0, 256, A, dtype=np.uint8))
    # dtypes as _mutate_slot passes them: off/len/cap int32 arena
    # spans, aux0/aux1 (min/max length) uint64.
    off = jnp.int32(16)
    length = jnp.int32(48)
    cap = jnp.int32(96)
    min_len = jnp.uint64(0)
    max_len = jnp.uint64(96)

    chosen = {}
    i = 0
    while len(chosen) < 7 and i < 4000:
        k = random.key(70_000 + i)
        i += 1
        op = int(d.intn(random.split(k, 8)[0], 7))
        chosen.setdefault(op, k)
    assert len(chosen) == 7, f"key search only hit ops {sorted(chosen)}"
    keys = [chosen[op] for op in range(7)]

    refs = [_mutate_data_span(k, arena0, off, length, cap,
                              min_len, max_len) for k in keys]
    ref_arena = np.stack([np.asarray(r[0]) for r in refs])
    ref_len = np.stack([np.asarray(r[1]) for r in refs])
    ref_ok = np.stack([np.asarray(r[2]) for r in refs])

    kd = jnp.stack([jax.random.key_data(k) for k in keys])
    arenas = jnp.tile(arena0[None], (7, 1))

    def per_row(arena, kd_i):
        return _mutate_data_span(
            jax.random.wrap_key_data(kd_i), arena, off, length,
            cap, min_len, max_len)

    got = _grid_apply(
        per_row, [arenas, kd], [],
        [(A,), (), ()],
        [ref_arena.dtype, ref_len.dtype, ref_ok.dtype],
        interpret=True)
    np.testing.assert_array_equal(ref_arena, np.asarray(got[0]))
    np.testing.assert_array_equal(ref_len, np.asarray(got[1]))
    np.testing.assert_array_equal(ref_ok, np.asarray(got[2]))


@pytest.mark.slow
def test_mutate_pack_parity(base_batch):
    """The fused mutate+pack kernel vs the pipeline's vmap `one`
    (including the insert-class journal masking): identical 228-byte
    delta rows, payload slots, and needs flags.

    Marked slow: this traces a third interpret-mode pallas executable
    (~38 s cold) and the pack path it pins is shared code already
    exercised end-to-end by the tier-1 pipeline tests; the slot-op,
    data-span, and dead-call parity tests above stay in tier-1."""
    spec = DeltaSpec()
    fv, fc = _flag_arrays()
    pack = make_packer(spec)
    mut_keys = random.split(random.key(42), BATCH)
    idx = jnp.arange(BATCH, dtype=jnp.int32)
    op = jnp.asarray([0, 1] * (BATCH // 2), dtype=jnp.uint8)
    donor = jnp.where(op != 0, jnp.int32(0), jnp.int32(-1))
    pos = jnp.zeros((BATCH,), dtype=jnp.uint8)

    def one(st, k, i, o, dn, po):
        mutated = _mutate_one(st, k, fv, fc, ROUNDS)
        mutated["call_alive"] = jnp.where(
            o != 0, st["call_alive"], mutated["call_alive"])
        return pack(mutated, i, op=o, donor=dn, pos=po)

    ref_rows, ref_payloads, ref_needs = jax.vmap(one)(
        base_batch, mut_keys, idx, op, donor, pos)
    got_rows, got_payloads, got_needs = make_pallas_mutate_pack(
        spec, rounds=ROUNDS, interpret=True)(
        base_batch, jax.random.key_data(mut_keys), idx, op, donor,
        pos, fv, fc)
    np.testing.assert_array_equal(np.asarray(ref_rows),
                                  np.asarray(got_rows))
    np.testing.assert_array_equal(np.asarray(ref_payloads),
                                  np.asarray(got_payloads))
    np.testing.assert_array_equal(np.asarray(ref_needs),
                                  np.asarray(got_needs))


@pytest.mark.parametrize("pool_slots", [8, 1], ids=["roomy", "overflow"])
def test_pool_assigner_parity(pool_slots):
    """Grid-sequential SMEM-counter pool claims vs the prefix-sum
    assigner: identical patched rows (flags byte, embedded pool_idx),
    packed pool prefix, and capped n_used — with pool_slots=1 forcing
    the FLAG_OVERFLOW loser path."""
    spec = DeltaSpec()
    rng = np.random.RandomState(11)
    b = 12
    rows = jnp.asarray(rng.randint(0, 256, (b, spec.row_bytes),
                                   dtype=np.uint8))
    payloads = jnp.asarray(rng.randint(0, 256, (b, spec.P),
                                       dtype=np.uint8))
    needs = jnp.asarray(rng.rand(b) < 0.5)
    assert int(np.asarray(needs).sum()) > pool_slots or pool_slots == 8
    ref_rows, ref_pool, ref_used = _make_pool_assigner(
        spec, pool_slots)(rows, payloads, needs)
    got_rows, got_pool, got_used = make_pallas_pool_assigner(
        spec, pool_slots, interpret=True)(rows, payloads, needs)
    np.testing.assert_array_equal(np.asarray(ref_rows),
                                  np.asarray(got_rows))
    np.testing.assert_array_equal(np.asarray(ref_pool),
                                  np.asarray(got_pool))
    assert int(ref_used) == int(got_used) <= pool_slots
