"""Device residency observatory (ISSUE 17), unit level: the HBM
buffer ledger's accounting/conservation invariants and the compile
observatory's storm detection, pinned against private registries and
a stub flight recorder — no warm rig, no device fixtures.  The
integration-side invariants (conservation on the warm pipeline, the
mesh drill re-pin) live in test_health_faults / test_mesh_faults."""

from __future__ import annotations

import gc
import json

import numpy as np
import pytest

from syzkaller_tpu.telemetry import Registry
from syzkaller_tpu.telemetry.compiles import (
    CompileObservatory,
    assert_no_new_compiles,
    key_diff,
)
from syzkaller_tpu.telemetry.hbm import (
    DEVICE_HOST,
    DeviceBufferLedger,
    OWNERS,
)


class _Flight:
    """Captures incident dumps the way hbm/compiles fire them."""

    def __init__(self):
        self.dumps = []

    def dump(self, kind, detail="", extra=None):
        self.dumps.append((kind, detail, extra or {}))
        return None


def _ledger():
    return DeviceBufferLedger(registry=Registry(), flight=_Flight())


# -- ledger accounting ----------------------------------------------------


def test_ledger_register_update_close_accounting():
    led = _ledger()
    a = np.zeros(100, np.uint8)
    h = led.register("pipeline", "corpus", a, device="0")
    assert h.nbytes == 100
    assert led.live_bytes("pipeline") == 100

    # a rebuild REPLACES the entry — no double counting
    b = np.zeros(300, np.uint8)
    h.update(b, device="0")
    assert led.live_bytes("pipeline") == 300
    assert led.live_bytes() == 300

    # invalidation zeroes the entry but keeps the handle reusable
    h.update(None)
    assert led.live_bytes("pipeline") == 0
    g = led._reg().gauge("tz_hbm_live_bytes",
                         labels={"owner": "pipeline", "device": "0",
                                 "kind": "corpus"})
    assert g.value == 0

    h.update(b, device="0")
    assert led.live_bytes("pipeline") == 300
    h.close()
    assert led.live_bytes() == 0
    h.update(b, device="0")  # updates after close are inert
    assert led.live_bytes() == 0


def test_ledger_peak_is_monotonic_and_snapshot_shape():
    led = _ledger()
    h = led.register("triage", "plane",
                     np.zeros(4096, np.uint8), device="0")
    h.update(np.zeros(1024, np.uint8), device="0")
    snap = led.snapshot()
    assert snap["owners"]["triage"]["live_bytes"] == 1024
    assert snap["owners"]["triage"]["peak_bytes"] == 4096
    assert snap["buffers"] == {"triage/plane@0": 1024}
    assert json.dumps(snap)  # JSON-ready for /api/device + incidents


def test_ledger_groups_payloads_and_opaque_bytes():
    led = _ledger()
    led.register("mesh", "planes",
                 [np.zeros(64, np.uint8), np.zeros(64, np.uint8)],
                 device="0-7")
    led.register("staging", "arena", 4096)  # opaque host byte count
    assert led.live_bytes("mesh") == 128
    assert led.live_bytes("staging") == 4096
    snap = led.snapshot()
    assert snap["buffers"]["mesh/planes@0-7"] == 128
    # an opaque registration defaults to the host device
    assert snap["buffers"][f"staging/arena@{DEVICE_HOST}"] == 4096


def test_ledger_headroom_excludes_host_and_counts_transient(
        monkeypatch):
    monkeypatch.setenv("TZ_HBM_CAPACITY_BYTES", "1000000")
    led = _ledger()
    led.register("pipeline", "tables",
                 np.zeros(2048, np.uint8), device="0")
    led.register("staging", "arena", 500)  # host: not in the forecast
    led.note_transient("pipeline", 100)
    assert led.capacity_bytes() == 1_000_000
    assert led.headroom() == 1_000_000 - 2048 - 100
    snap = led.snapshot()
    assert snap["device_resident_bytes"] == 2048
    assert snap["transient_bytes"] == 100
    assert snap["headroom_bytes"] == snap["capacity_bytes"] \
        - snap["device_resident_bytes"] - snap["transient_bytes"]


def test_ledger_bound_handle_closes_with_its_engine():
    """A transient engine (re-created triage engine, dropped sim
    prescorer) must not rot the ledger: a handle registered with
    bound_to closes itself when the owning object is collected."""
    led = _ledger()

    class _Engine:
        pass

    eng = _Engine()
    led.register("sim", "tables", np.zeros(256, np.uint8),
                 device="0", bound_to=eng)
    assert led.live_bytes("sim") == 256
    del eng
    gc.collect()
    assert led.live_bytes("sim") == 0
    assert led.reconcile(live_arrays=[])["entries"] == 0


def test_ledger_owner_vocabulary_is_closed():
    # the lint cross-check (tools/lint_metrics) greps call sites
    # against this tuple; the unit suite pins it is sorted + closed
    assert OWNERS == tuple(sorted(OWNERS))
    assert set(OWNERS) == {"arena", "mesh", "pipeline", "serve", "sim",
                           "staging", "triage"}


# -- reconcile: conservation vs the backend report ------------------------


def test_reconcile_conserves_and_two_strike_incident():
    jnp = pytest.importorskip("jax.numpy")
    led = _ledger()
    arr = jnp.asarray(np.arange(2048, dtype=np.uint8))
    h = led.register("pipeline", "corpus", arr)
    assert h.device != DEVICE_HOST

    rec = led.reconcile(live_arrays=[arr])
    assert rec["entries"] == 1
    assert rec["tracked_bytes"] == rec["backend_bytes"] == 2048
    assert rec["drift_bytes"] == 0 and rec["dead_entries"] == 0
    assert not rec["flagged"]
    assert led.last_reconcile == rec

    # the array dies without a handle update: an orphaned entry.
    # Strike one is tolerated (a legitimate swap race self-heals);
    # the second consecutive flagged pass fires exactly one incident.
    del arr
    gc.collect()
    rec = led.reconcile(live_arrays=[])
    assert rec["dead_entries"] == 1 and rec["flagged"]
    assert led._flight.dumps == []
    rec = led.reconcile(live_arrays=[])
    assert rec["flagged"]
    kinds = [k for k, _d, _e in led._flight.dumps]
    assert kinds == ["hbm_drift"]
    _k, detail, extra = led._flight.dumps[0]
    assert "1 orphaned entries" in detail
    assert "hbm" in extra  # the residency table rides the incident

    # ... and exactly one per episode: a persistent leak must not
    # flood the event ring / flight dir at every analytics pass
    rec = led.reconcile(live_arrays=[])
    assert rec["flagged"]
    assert [k for k, _d, _e in led._flight.dumps] == ["hbm_drift"]

    # a clean pass resets the strikes
    h.update(None)
    rec = led.reconcile(live_arrays=[])
    assert not rec["flagged"] and led._strikes == 0


def test_reconcile_drift_and_tolerance():
    jnp = pytest.importorskip("jax.numpy")
    led = _ledger()
    a = jnp.asarray(np.arange(1024, dtype=np.uint8))
    b = jnp.asarray(np.arange(512, dtype=np.uint8))
    led.register("triage", "plane", [a, b])
    # the backend stops reporting b's bytes: a leak upstream
    rec = led.reconcile(live_arrays=[a])
    assert rec["drift_bytes"] == 512 and rec["flagged"]
    # ... unless the operator tolerates it (TZ_HBM_DRIFT_TOLERANCE)
    rec = led.reconcile(live_arrays=[a], tolerance=512)
    assert rec["drift_bytes"] == 512 and not rec["flagged"]


def test_reconcile_skips_host_and_opaque_entries():
    led = _ledger()
    led.register("staging", "arena", 4096)
    led.register("serve", "tenant_planes",
                 np.zeros(64, np.uint8), device=DEVICE_HOST)
    rec = led.reconcile(live_arrays=[])
    assert rec["entries"] == 0 and not rec["flagged"]


def test_reconcile_armed_knob(monkeypatch):
    led = _ledger()
    assert led.reconcile_armed()
    monkeypatch.setenv("TZ_HBM_RECONCILE", "0")
    assert not led.reconcile_armed()
    monkeypatch.setenv("TZ_HBM_RECONCILE", "junk")
    assert led.reconcile_armed()  # malformed degrades to the default


# -- compile observatory --------------------------------------------------


def _observatory():
    return CompileObservatory(registry=Registry(), flight=_Flight())


def test_observatory_counts_and_snapshot():
    obs = _observatory()
    obs.note("mesh.fused_step", {"devices": 8}, seconds=1.5)
    obs.note("mesh.fused_step", {"devices": 7}, seconds=1.2)
    obs.note("pipeline.step", {"batch": 4096}, seconds=2.0)
    assert obs.total_builds() == 3
    assert obs.builds("mesh.fused_step") == 2
    assert len(obs.shapes("mesh.fused_step")) == 2
    obs.set_cache_size("mesh.fused_step", 2)
    snap = obs.snapshot()
    assert snap["total_builds"] == 3 and snap["storms"] == 0
    assert snap["graphs"]["mesh.fused_step"] == {"builds": 2,
                                                 "shapes": 2}
    assert len(snap["recent"]) == 3
    _ts, graph, _key, secs = snap["recent"][-1]
    assert graph == "pipeline.step" and secs == 2.0


def test_observe_notes_only_on_cache_growth():
    obs = _observatory()
    cache = []

    def sizer():
        return len(cache)

    with obs.observe("pipeline.step", {"batch": 64}, sizer=sizer):
        cache.append(object())  # cold: the cache grew — a build
    assert obs.builds("pipeline.step") == 1
    with obs.observe("pipeline.step", {"batch": 64}, sizer=sizer):
        pass  # warm: executable reused — nothing recorded
    assert obs.builds("pipeline.step") == 1
    # with no sizer, the body IS the build (a cache-miss branch)
    with obs.observe("mesh.fused_step", {"devices": 8}):
        pass
    assert obs.builds("mesh.fused_step") == 1


def test_storm_same_key_fires_once_with_cache_drop_diagnosis():
    obs = _observatory()
    obs.note("pipeline.step", {"batch": 4096})
    obs.note("pipeline.step", {"batch": 4096})  # 2nd build: storm
    obs.note("pipeline.step", {"batch": 4096})  # muted: same episode
    kinds = [k for k, _d, _e in obs._flight.dumps]
    assert kinds == ["compile_storm"], "one incident per episode"
    _k, detail, extra = obs._flight.dumps[0]
    storm = extra["compile_storm"]
    assert storm["graph"] == "pipeline.step" and storm["builds"] == 2
    # identical key -> empty diff -> the worst of the two causes
    assert storm["key_diff"] == {}
    assert "cache was dropped" in detail
    assert obs.snapshot()["storms"] == 1


def test_storm_key_churn_names_the_churning_field():
    obs = _observatory()
    obs.note("pipeline.step", {"batch": 4096, "rounds": 2})
    obs.note("pipeline.step", {"batch": 8192, "rounds": 2})
    obs.note("pipeline.step", {"batch": 8192, "rounds": 2})  # storm
    _k, detail, extra = obs._flight.dumps[0]
    diff = extra["compile_storm"]["key_diff"]
    assert diff == {"batch": ["4096", "8192"]}
    assert "key churn on ['batch']" in detail


def test_key_diff_canonicalizes_dict_order():
    from syzkaller_tpu.telemetry.compiles import _canon_key

    ka = _canon_key({"x": 1, "y": 2})
    kb = _canon_key({"y": 2, "x": 1})
    assert ka == kb and key_diff(ka, kb) == {}
    kc = _canon_key({"y": 3, "x": 1})
    assert key_diff(ka, kc) == {"y": ["2", "3"]}


# -- the shared warm-rig guard --------------------------------------------


def test_assert_no_new_compiles_passes_and_diagnoses():
    obs = _observatory()
    cache = [object()]

    with assert_no_new_compiles(lambda: len(cache), observatory=obs):
        pass  # warm body: clean

    with pytest.raises(AssertionError) as e:
        with assert_no_new_compiles(lambda: len(cache),
                                    observatory=obs):
            cache.append(object())
    assert "watched jit cache #0 grew 1 -> 2" in str(e.value)

    with pytest.raises(AssertionError) as e:
        with assert_no_new_compiles(observatory=obs):
            obs.note("mesh.fused_step", {"devices": 8}, seconds=1.0)
    msg = str(e.value)
    assert "new jit compiles on a warm rig" in msg
    assert "1 new build(s)" in msg and "mesh.fused_step" in msg


# -- trace metadata (satellite: "ph": "M") --------------------------------


def test_trace_process_metadata_events(tmp_path, monkeypatch):
    """The Chrome exporter's metadata header: concatenated
    multi-process traces render named, pid-sorted process tracks.
    TZ_TRACE_PROCESS overrides the argv-derived name for launchers
    that exec one binary in several roles."""
    import os
    import threading

    from syzkaller_tpu.telemetry.trace import TraceWriter

    monkeypatch.setenv("TZ_TRACE_PROCESS", "manager")
    path = tmp_path / "trace.json"
    tw = TraceWriter(str(path))
    tw.instant("breaker.open")
    tw.close()
    events = [json.loads(ln.rstrip(","))
              for ln in path.read_text().splitlines()[1:]]
    meta = {e["name"]: e for e in events if e.get("ph") == "M"}
    pid = os.getpid()
    assert meta["process_name"]["args"]["name"] == f"manager/{pid}"
    assert meta["process_sort_index"]["args"]["sort_index"] == pid
    assert meta["thread_name"]["args"]["name"] \
        == threading.current_thread().name
    assert meta["thread_name"]["tid"] == threading.get_ident()
    # metadata precedes the first real event in the stream
    names = [e["name"] for e in events]
    assert names.index("process_name") < names.index("breaker.open")
