"""Exec wire-format and text-encoding tests (reference strategy:
prog/encodingexec_test.go exact uint64 golden streams;
prog/encoding_test.go round-trips)."""

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.encodingexec import (
    EXEC_ARG_CONST,
    EXEC_ARG_DATA,
    EXEC_ARG_RESULT,
    EXEC_INSTR_COPYIN,
    EXEC_INSTR_COPYOUT,
    EXEC_INSTR_EOF,
    EXEC_NO_COPYOUT,
    serialize_for_exec,
    words_of,
)
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen

DATA_OFFSET = 0x20000000


def exec_words(target, text: bytes) -> list[int]:
    p = deserialize_prog(target, text)
    return words_of(serialize_for_exec(p))


def const(size, val, be=False, bf_off=0, bf_len=0, stride=0):
    meta = size | (bf_off << 16) | (bf_len << 24) | (stride << 32)
    if be:
        meta |= 1 << 8
    return [EXEC_ARG_CONST, meta, val]


def test_exec_simple_call(test_target):
    # tz_nop$ints(a0 intptr, a1 int8, a2 int16, a3 int32, a4 int64)
    got = exec_words(test_target, b"tz_nop$ints(0x1, 0x2, 0x3, 0x4, 0x5)\n")
    meta = test_target.syscall_map["tz_nop$ints"]
    want = [meta.id, EXEC_NO_COPYOUT, 5,
            *const(8, 1), *const(1, 2), *const(2, 3), *const(4, 4),
            *const(8, 5), EXEC_INSTR_EOF]
    assert got == want


def test_exec_copyin_struct(test_target):
    # pad_packed: i16 i32 i8 i16 i64 packed at +0,2,6,7,9
    got = exec_words(
        test_target,
        b"tz_align$packed(&(0x7f0000000000)={0x1, 0x2, 0x3, 0x4, 0x5})\n")
    meta = test_target.syscall_map["tz_align$packed"]
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(2, 1),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 2, *const(4, 2),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 6, *const(1, 3),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 7, *const(2, 4),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 9, *const(8, 5),
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_natural_padding(test_target):
    # pad_natural: i16@0 i32@4 i8@8 i16@10 i64@16 (pads skipped in stream)
    got = exec_words(
        test_target,
        b"tz_align$natural(&(0x7f0000000000)={0x1, 0x2, 0x3, 0x4, 0x5})\n")
    meta = test_target.syscall_map["tz_align$natural"]
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(2, 1),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 4, *const(4, 2),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 8, *const(1, 3),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 10, *const(2, 4),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 16, *const(8, 5),
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_result_copyout(test_target):
    got = exec_words(test_target,
                     b"r0 = tz_res$make()\ntz_res$use(r0)\n")
    make = test_target.syscall_map["tz_res$make"]
    use = test_target.syscall_map["tz_res$use"]
    want = [
        make.id, 0, 0,
        use.id, EXEC_NO_COPYOUT, 1,
        EXEC_ARG_RESULT, 4, 0, 0, 0, 0xFFFF,
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_data_arg(test_target):
    got = exec_words(test_target,
                     b'tz_buf$blob(&(0x7f0000000000)="68656c6c6f21")\n')
    meta = test_target.syscall_map["tz_buf$blob"]
    blob = int.from_bytes(b"hello!\x00\x00", "little")
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET, EXEC_ARG_DATA, 6, blob,
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_bitfields(test_target):
    # bf_grouped_inner: 3x int32:10 in one unit at offsets 0,10,20
    got = exec_words(
        test_target,
        b"tz_bf$grouped(&(0x7f0000000000)={{0x1, 0x2, 0x3}, 0x4})\n")
    meta = test_target.syscall_map["tz_bf$grouped"]
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(4, 1, bf_off=0, bf_len=10),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(4, 2, bf_off=10, bf_len=10),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(4, 3, bf_off=20, bf_len=10),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 4, *const(1, 4),
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_be_and_vma(test_target):
    got = exec_words(
        test_target,
        b"tz_be$ints(&(0x7f0000000000)={0x1, 0x2, 0x3, 0x4})\n")
    meta = test_target.syscall_map["tz_be$ints"]
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(1, 1),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 1, *const(2, 2, be=True),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 3, *const(4, 3, be=True),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 7, *const(8, 4, be=True),
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_csum(test_target):
    got = exec_words(
        test_target,
        b"tz_csum$inet(&(0x7f0000000000)={0x0, 0x11223344, 0x55667788})\n")
    meta = test_target.syscall_map["tz_csum$inet"]
    # csum_plain: sum@0 (csum int16), src@2 (i32be), dst@6 (i32be), packed
    EXEC_ARG_CSUM = 3
    want = [
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, *const(2, 0),  # csum placeholder
        EXEC_INSTR_COPYIN, DATA_OFFSET + 2, *const(4, 0x11223344, be=True),
        EXEC_INSTR_COPYIN, DATA_OFFSET + 6, *const(4, 0x55667788, be=True),
        # csum instruction: inet over parent struct (addr 0, size 10)
        EXEC_INSTR_COPYIN, DATA_OFFSET + 0, EXEC_ARG_CSUM, 2,
        0,  # ExecArgCsumInet
        1,  # one chunk
        0, DATA_OFFSET + 0, 10,  # chunk: data, addr, size
        meta.id, EXEC_NO_COPYOUT, 1, *const(8, DATA_OFFSET),
        EXEC_INSTR_EOF,
    ]
    assert got == want


def test_exec_proc_stride(test_target):
    got = exec_words(test_target, b"tz_proc(0x2)\n")
    meta = test_target.syscall_map["tz_proc"]
    # proc(100, 4): value = start + val = 102, stride = 4
    want = [meta.id, EXEC_NO_COPYOUT, 1, *const(2, 102, stride=4),
            EXEC_INSTR_EOF]
    assert got == want


def test_exec_random_progs(test_target, iters):
    for i in range(iters):
        rng = RandGen(test_target, 5000 + i)
        p = generate_prog(test_target, rng, 10)
        stream = serialize_for_exec(p)
        words = words_of(stream)
        assert words[-1] == EXEC_INSTR_EOF
        assert len(stream) < (2 << 20)


def test_text_roundtrip_random(test_target, iters):
    for i in range(iters):
        rng = RandGen(test_target, 6000 + i)
        p = generate_prog(test_target, rng, 10)
        s1 = serialize_prog(p)
        p2 = deserialize_prog(test_target, s1)
        s2 = serialize_prog(p2)
        assert s1 == s2, f"seed {6000 + i}"
        # Exec streams must match too (deeper equivalence).
        assert serialize_for_exec(p) == serialize_for_exec(p2), f"seed {6000+i}"
