"""VM backends against PATH-shimmed fake CLIs (VERDICT r2 #9).

Every cloud/device backend is exercised through its real subprocess
surface — fake qemu-system/ssh/scp/adb/gcloud/lkvm binaries driven by
a control directory — covering construct, boot-failure, recovery, run,
and crash detection via monitor_execution (the reference exercises
these only in production; here the CLI seam is the test boundary,
reference shape: vm/qemu/qemu.go:228 Boot, vm/vm.go MonitorExecution).
"""

from __future__ import annotations

import os
import threading

import pytest

from syzkaller_tpu.report import get_reporter
from syzkaller_tpu.vm.vm import monitor_execution
from syzkaller_tpu.vm.vmimpl import BootError, Env, create_pool_impl


@pytest.fixture
def fakecli(tmp_path, monkeypatch):
    ctl = tmp_path / "ctl"
    ctl.mkdir()
    bindir = tmp_path / "bin"
    bindir.mkdir()
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    class Fake:
        def __init__(self):
            self.ctl = ctl
            self.bindir = bindir

        def shim(self, name: str, body: str) -> None:
            p = bindir / name
            p.write_text(f"#!/bin/bash\nCTL={ctl}\n{body}\n")
            p.chmod(0o755)

        def set(self, flag: str) -> None:
            (ctl / flag).write_text("1")

        def clear(self, flag: str) -> None:
            try:
                (ctl / flag).unlink()
            except FileNotFoundError:
                pass

    f = Fake()
    # Shared ssh/scp fakes: `ssh ... user@host cmd...` succeeds once
    # $CTL/booted exists; the "true" probe is the boot gate; any other
    # command streams guest output until the oops flag kills sshd.
    f.shim("ssh", r"""
for last; do :; done
if [ ! -f "$CTL/booted" ]; then echo "Connection refused" >&2; exit 255; fi
if [ "$last" = "true" ]; then exit 0; fi
for i in $(seq 1 100); do
  echo "executing program 0:"
  sleep 0.1
  if [ -f "$CTL/oops" ]; then exit 255; fi
done
""")
    f.shim("scp", r"""
if [ ! -f "$CTL/booted" ]; then echo "Connection refused" >&2; exit 255; fi
exit 0
""")
    return f


def _drive_crash(inst, f) -> None:
    """Run the instance, inject an oops mid-run, expect a parsed
    report from monitor_execution."""
    stop = threading.Event()
    stream = inst.run(60.0, stop, "fuzz-forever")
    threading.Timer(1.0, lambda: f.set("oops")).start()
    res = monitor_execution(stream, get_reporter("linux"),
                            need_executing=False)
    stop.set()
    assert res.report is not None, \
        f"no crash detected; output tail: {res.output[-400:]!r}"
    assert b"NULL pointer" in res.report.title.encode() \
        or "BUG" in res.report.title


def test_qemu_boot_fail_recover_run_crash(fakecli, tmp_path):
    f = fakecli
    f.shim("qemu-system-x86_64", r"""
if [ -f "$CTL/qemu_fail" ]; then echo "qemu: could not load kernel"; exit 1; fi
echo "[    0.000000] Linux version 4.19.0-fake"
touch "$CTL/booted"
for i in $(seq 1 600); do
  sleep 0.1
  if [ -f "$CTL/oops" ]; then
    echo "BUG: unable to handle kernel NULL pointer dereference at 00000000000000a8"
    echo "IP: fake_poke+0x12/0x40"
    echo "Call Trace:"
    echo " fake_syscall+0x1/0x2"
    echo "---[ end trace ]---"
    rm -f "$CTL/oops"
  fi
done
""")
    env = Env(name="t", os="linux", arch="amd64",
              workdir=str(tmp_path), image="",
              config={"count": 1, "boot_timeout": 30})
    pool = create_pool_impl("qemu", env)
    assert pool.count() == 1

    # Boot failure surfaces as BootError with the console tail...
    f.set("qemu_fail")
    os.makedirs(tmp_path / "i0", exist_ok=True)
    with pytest.raises(BootError, match="could not load kernel"):
        pool.create(str(tmp_path / "i0"), 0)
    # ...and the next create (the manager's recovery loop) succeeds.
    f.clear("qemu_fail")
    os.makedirs(tmp_path / "i0", exist_ok=True)
    inst = pool.create(str(tmp_path / "i0"), 0)
    try:
        dst = inst.copy(__file__)
        assert dst.startswith("/")
        _drive_crash(inst, f)
        assert b"Linux version" in inst.diagnose()
    finally:
        inst.close()


def test_adb_device_flow(fakecli, tmp_path):
    f = fakecli
    f.shim("adb", r"""
shift 2  # -s <device>
case "$1" in
  wait-for-device) [ -f "$CTL/booted" ] || exit 1; exit 0;;
  push|reverse|reboot) exit 0;;
  shell)
    shift
    case "$*" in
      "echo ok") echo ok;;
      "dmesg -w")
        for i in $(seq 1 300); do
          sleep 0.1
          if [ -f "$CTL/oops" ]; then
            echo "BUG: unable to handle kernel NULL pointer dereference at 00000000deadbeef"
            echo "Call Trace:"
            rm -f "$CTL/oops"
          fi
        done;;
      dmesg) echo "fake dmesg";;
      *) for i in $(seq 1 100); do echo "executing program 0:"; sleep 0.1;
           [ -f "$CTL/oops.stop" ] && exit 1; done;;
    esac; exit 0;;
  *) exit 0;;
esac
""")
    env = Env(name="t", os="linux", arch="arm64", workdir=str(tmp_path),
              config={"devices": ["FAKESERIAL"]})
    pool = create_pool_impl("adb", env)
    # Device not up: construct fails (recovery = retry after boot).
    with pytest.raises(BootError):
        pool.create(str(tmp_path / "a0"), 0)
    f.set("booted")
    inst = pool.create(str(tmp_path / "a0"), 0)
    try:
        assert inst.copy(__file__).startswith("/data/local/tmp/")
        _drive_crash(inst, f)
    finally:
        inst.close()


def test_gce_instance_flow(fakecli, tmp_path):
    f = fakecli
    f.shim("gcloud", r"""
shift  # compute
case "$1" in
  instances)
    case "$2" in
      create) [ -f "$CTL/gce_fail" ] && { echo "quota" >&2; exit 1; }
              touch "$CTL/booted"; exit 0;;
      describe) echo "203.0.113.7"; exit 0;;
      delete) exit 0;;
    esac;;
  connect-to-serial-port)
    for i in $(seq 1 300); do
      sleep 0.1
      if [ -f "$CTL/oops" ]; then
        echo "BUG: unable to handle kernel NULL pointer dereference at 0000000000000000"
        echo "Call Trace:"
        rm -f "$CTL/oops"
      fi
    done; exit 0;;
esac
exit 0
""")
    env = Env(name="tz", os="linux", arch="amd64", workdir=str(tmp_path),
              config={"count": 1})
    pool = create_pool_impl("gce", env)
    f.set("gce_fail")
    with pytest.raises(BootError, match="quota"):
        pool.create(str(tmp_path / "g0"), 0)
    f.clear("gce_fail")
    inst = pool.create(str(tmp_path / "g0"), 0)
    try:
        assert inst.copy(__file__).startswith("/")
        _drive_crash(inst, f)
    finally:
        inst.close()


def test_isolated_machine_flow(fakecli, tmp_path):
    f = fakecli
    f.set("booted")
    env = Env(name="t", os="linux", arch="amd64", workdir=str(tmp_path),
              config={"targets": ["203.0.113.9"]})
    pool = create_pool_impl("isolated", env)
    inst = pool.create(str(tmp_path / "iso0"), 0)
    try:
        assert inst.copy(__file__)
        stop = threading.Event()
        stream = inst.run(5.0, stop, "runme")
        got = bytearray()
        while True:
            chunk = stream.get(timeout=1.0)
            if chunk is None:
                break
            got += chunk
            if b"executing program" in got:
                break
        stop.set()
        assert b"executing program" in got
    finally:
        inst.close()


def test_kvm_lkvm_flow(fakecli, tmp_path):
    f = fakecli
    f.shim("lkvm", r"""
case "$1" in
  run)
    echo "  # lkvm run -k bzImage"
    touch "$CTL/booted"
    for i in $(seq 1 200); do sleep 0.1; done;;
  *) exit 0;;
esac
""")
    f.set("booted")
    env = Env(name="t", os="linux", arch="amd64", workdir=str(tmp_path),
              config={"count": 1, "kernel": "bzImage"})
    pool = create_pool_impl("kvm", env)
    inst = pool.create(str(tmp_path / "k0"), 0)
    try:
        stop = threading.Event()
        stream = inst.run(5.0, stop, "true")
        while stream.get(timeout=0.5) is not None:
            pass
        stop.set()
    finally:
        inst.close()
