"""Unified telemetry layer (syzkaller_tpu/telemetry, ISSUE 2):
registry semantics, histogram bucketing, span timing + trace export,
Prometheus/JSON rendering, health-counter folding, the Stat drift
guard, and the grab_stats snapshot-and-reset race regression.

All CPU-only and stdlib-fast: no pipeline compiles, no device."""

from __future__ import annotations

import json
import threading
from enum import IntEnum

import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    EVENT_RING_SIZE,
    Counter,
    Gauge,
    Histogram,
    Registry,
)

# -- registry semantics -------------------------------------------------


def test_registration_is_idempotent_and_kind_checked():
    reg = Registry()
    c1 = reg.counter("tz_x_total", "help text")
    c2 = reg.counter("tz_x_total")
    assert c1 is c2  # same object: module-level registration shares
    with pytest.raises(TypeError):
        reg.gauge("tz_x_total")  # same name, different kind


def test_counter_and_gauge_values():
    reg = Registry()
    c = reg.counter("tz_c_total")
    c.inc()
    c.inc(2.5)  # float counters: backoff-seconds accumulate
    assert c.value == 3.5
    g = reg.gauge("tz_g_depth")
    g.set(7)
    assert g.value == 7
    # pull-style gauge samples its callback at read time
    box = {"v": 1}
    gf = reg.gauge("tz_gf_size", fn=lambda: box["v"])
    box["v"] = 42
    assert gf.value == 42
    # re-registering with a new callback rebinds (fresh manager case)
    reg.gauge("tz_gf_size", fn=lambda: 9)
    assert gf.value == 9
    # a raising callback reads as 0, never propagates into a scrape
    reg.gauge("tz_gf_size", fn=lambda: 1 / 0)
    assert gf.value == 0


# -- histogram bucketing ------------------------------------------------


def test_histogram_fixed_log_buckets():
    h = Histogram("tz_h_seconds")
    assert h.bounds == DEFAULT_LATENCY_BUCKETS
    assert h.bounds[0] == pytest.approx(1e-4)
    assert h.bounds[-1] == pytest.approx(1e3)
    for v in (0.0002, 0.0002, 0.05, 2.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(2.0504)
    assert snap["min"] == pytest.approx(0.0002)
    assert snap["max"] == pytest.approx(2.0)
    # buckets are cumulative and end at +Inf
    les, cums = zip(*snap["buckets"])
    assert les[-1] == "+Inf" and cums[-1] == 4
    assert all(a <= b for a, b in zip(cums, cums[1:]))
    # the two 200 µs observations are fully counted by the 1 ms bound
    cum_at_1ms = dict(snap["buckets"])[
        min(b for b in h.bounds if b >= 1e-3)]
    assert cum_at_1ms >= 2


def test_histogram_percentiles_stay_in_data_range():
    h = Histogram("tz_h2_seconds")
    assert h.percentile(0.5) == 0.0  # empty
    for _ in range(100):
        h.observe(0.01)
    for q in (0.5, 0.9, 0.99):
        p = h.percentile(q)
        assert 0.01 <= p <= max(b for b in h.bounds if b <= 0.011), p
    h2 = Histogram("tz_h3_seconds")
    h2.observe(5000.0)  # beyond the last bound: overflow bucket
    assert h2.percentile(0.99) == pytest.approx(5000.0)


def test_histogram_thread_safety_conserves_count():
    h = Histogram("tz_h4_seconds")

    def worker():
        for _ in range(1000):
            h.observe(0.001)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 4000


# -- spans + trace export -----------------------------------------------


def test_span_records_into_named_histogram():
    assert telemetry.span_metric_name("pipeline.drain") \
        == "tz_pipeline_drain_seconds"
    hist = telemetry.REGISTRY.histogram(
        telemetry.span_metric_name("pipeline.drain"))
    before = hist.count
    with telemetry.span("pipeline.drain"):
        pass
    assert hist.count == before + 1


def test_trace_file_shape(tmp_path):
    path = tmp_path / "trace.json"
    telemetry.set_trace_file(str(path))
    try:
        with telemetry.span("pipeline.drain"):
            pass
        telemetry.record_event("breaker.open", "test detail")
    finally:
        telemetry.set_trace_file(None)
    text = path.read_text()
    # Chrome JSON array format, closing "]" legally omitted
    assert text.startswith("[\n")
    events = [json.loads(ln.rstrip(",")) for ln in text.splitlines()[1:]]
    names = [e["name"] for e in events]
    assert "pipeline.drain" in names and "breaker.open" in names
    span_ev = events[names.index("pipeline.drain")]
    assert span_ev["ph"] == "X" and span_ev["cat"] == "tz"
    assert span_ev["dur"] >= 0 and "tid" in span_ev and "pid" in span_ev
    # the metadata header carries the wallclock origin for correlation
    assert events[0]["name"] == "process_start"
    assert "wallclock" in events[0]["args"]


# -- cross-process merging (ISSUE 4 satellite) --------------------------


def test_histogram_snapshot_merge_is_vector_add():
    """The fixed-shared-buckets payoff: merging N processes'
    histogram snapshots is a per-bucket sum with percentiles
    re-estimated from the merged counts."""
    from syzkaller_tpu.telemetry import merge_histogram_snapshots

    h1, h2 = Histogram("tz_m_seconds"), Histogram("tz_m_seconds")
    for _ in range(100):
        h1.observe(0.001)
    for _ in range(300):
        h2.observe(0.1)
    merged = merge_histogram_snapshots([h1.snapshot(), h2.snapshot()])
    assert merged["count"] == 400
    assert merged["sum"] == pytest.approx(30.1)
    assert merged["min"] == pytest.approx(0.001)
    assert merged["max"] == pytest.approx(0.1)
    # 75% of mass at 0.1: the median lands in 0.1's bucket
    assert 0.05 <= merged["p50"] <= 0.1
    les, cums = zip(*merged["buckets"])
    assert les[-1] == "+Inf" and cums[-1] == 400
    assert all(a <= b for a, b in zip(cums, cums[1:]))
    # a bucket-incompatible snapshot (version skew) is skipped, not
    # corrupting the merge
    skewed = {"count": 5, "sum": 1.0, "min": 0.1, "max": 0.3,
              "buckets": [[1.0, 5], ["+Inf", 5]]}
    merged2 = merge_histogram_snapshots([h1.snapshot(), skewed])
    assert merged2["count"] == 100


def test_merge_snapshots_fleet_rollup():
    from syzkaller_tpu.telemetry import (merge_snapshots,
                                         render_prometheus_snapshot)

    r1, r2 = Registry(), Registry()
    r1.counter("tz_pipeline_mutants_total").inc(5)
    r2.counter("tz_pipeline_mutants_total").inc(7)
    r1.gauge("tz_pipeline_queue_depth").set(2)
    r2.gauge("tz_pipeline_queue_depth").set(3)
    r1.histogram("tz_proc_exec_seconds").observe(0.01)
    r2.histogram("tz_proc_exec_seconds").observe(0.02)
    fleet = merge_snapshots([r1.snapshot(), r2.snapshot()])
    assert fleet["sources"] == 2
    assert fleet["counters"]["tz_pipeline_mutants_total"] == 12
    assert fleet["gauges"]["tz_pipeline_queue_depth"] == 5
    assert fleet["histograms"]["tz_proc_exec_seconds"]["count"] == 2
    text = render_prometheus_snapshot(fleet, {"source": "fleet"})
    assert 'tz_pipeline_mutants_total{source="fleet"} 12' in text
    assert ('tz_proc_exec_seconds_bucket{le="+Inf",source="fleet"} 2'
            in text)
    assert 'tz_proc_exec_seconds_count{source="fleet"} 2' in text


# -- rendering ----------------------------------------------------------


def test_render_prometheus():
    reg = Registry()
    reg.counter("tz_c_total", "a counter").inc(3)
    reg.gauge("tz_g_depth").set(1.5)
    reg.histogram("tz_h_seconds").observe(0.01)
    text = reg.render_prometheus()
    assert "# HELP tz_c_total a counter" in text
    assert "# TYPE tz_c_total counter" in text
    assert "\ntz_c_total 3\n" in text
    assert "tz_g_depth 1.5" in text
    assert 'tz_h_seconds_bucket{le="+Inf"} 1' in text
    assert "tz_h_seconds_count 1" in text
    assert "tz_h_seconds_sum 0.01" in text


def test_snapshot_roundtrips_through_json(tmp_path):
    reg = Registry()
    reg.counter("tz_c_total").inc()
    reg.histogram("tz_h_seconds").observe(0.5)
    reg.record_event("breaker.open", "detail")
    path = tmp_path / "snap.json"
    reg.dump_snapshot(str(path))
    snap = json.loads(path.read_text())
    assert snap["counters"]["tz_c_total"] == 1
    assert snap["histograms"]["tz_h_seconds"]["count"] == 1
    assert snap["events"][0][1] == "breaker.open"


def test_event_ring_is_bounded():
    reg = Registry()
    for i in range(EVENT_RING_SIZE + 50):
        reg.record_event("e", str(i))
    events = reg.events()
    assert len(events) == EVENT_RING_SIZE
    assert events[-1][2] == str(EVENT_RING_SIZE + 49)  # newest kept


# -- health counters folded into the registry ---------------------------


def test_breaker_transitions_hit_registry_and_events():
    from syzkaller_tpu.health import CircuitBreaker

    snap0 = telemetry.snapshot()["counters"]
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=2, backoff_initial=1.0,
                        clock=lambda: clock[0])
    br.record_failure()
    br.record_failure()  # trips open
    assert br.state == "open"
    clock[0] = 10.0
    assert br.allow()  # open -> half_open
    assert br.consume_rebuild()
    br.record_success()  # half_open -> closed
    snap1 = telemetry.snapshot()["counters"]
    for name in ("tz_breaker_opens_total", "tz_breaker_half_opens_total",
                 "tz_breaker_rebuilds_total", "tz_breaker_closes_total"):
        assert snap1[name] == snap0.get(name, 0) + 1, name
    assert snap1["tz_breaker_failures_total"] \
        == snap0.get("tz_breaker_failures_total", 0) + 2
    recent = [n for _ts, n, _d in telemetry.REGISTRY.events()][-4:]
    assert recent == ["breaker.open", "breaker.half_open",
                      "breaker.rebuild", "breaker.close"]
    # wallclock transition stamps for the wedge timeline
    bsnap = br.snapshot()
    assert bsnap["last_open_at"] > 0
    assert bsnap["last_close_at"] >= bsnap["last_open_at"]


def test_watchdog_wedge_sets_last_wedge_gauge():
    from syzkaller_tpu.health import DeviceWedged, Watchdog

    wd = Watchdog(deadline_s=0.05)
    hang = threading.Event()
    try:
        with pytest.raises(DeviceWedged):
            wd.call(hang.wait, "device.launch")
    finally:
        hang.set()  # release the abandoned thread
    assert wd.stats.last_wedge_at > 0
    assert wd.snapshot()["last_wedge_at"] == \
        pytest.approx(wd.stats.last_wedge_at, abs=1e-3)
    g = telemetry.REGISTRY.gauge("tz_watchdog_last_wedge_ts")
    assert g.value == pytest.approx(wd.stats.last_wedge_at, abs=1e-3)


# -- Stat drift guard ---------------------------------------------------


def test_stat_names_drift_guard():
    from syzkaller_tpu.fuzzer.fuzzer import (
        STAT_NAMES,
        Stat,
        _check_stat_names,
        _stat_metric_name,
    )

    _check_stat_names(Stat, STAT_NAMES)  # the real tables agree

    class Drifted(IntEnum):
        A = 0
        B = 1

    with pytest.raises(AssertionError, match="without a STAT_NAMES"):
        _check_stat_names(Drifted, {Drifted.A: "a"})
    with pytest.raises(AssertionError, match="without a Stat member"):
        _check_stat_names(Drifted, {Drifted.A: "a", Drifted.B: "b",
                                    "ghost": "g"})
    # every Stat has a registered monotonic mirror in the registry
    counters = telemetry.snapshot()["counters"]
    for s in Stat:
        assert _stat_metric_name(STAT_NAMES[s]) in counters


# -- grab_stats vs concurrent inc() -------------------------------------


def test_grab_stats_conserves_counts_under_concurrency():
    """Regression (ISSUE 2 satellite): the poll drain must snapshot
    AND reset under one lock acquisition — increments landing between
    a read and a separate reset would be lost.  Hammer stat_add from
    worker threads while draining and assert conservation."""
    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.fuzzer.fuzzer import STAT_NAMES, Stat
    from syzkaller_tpu.models.target import get_target

    fz = Fuzzer(get_target("test", "64"), wq=WorkQueue())
    per_thread, nthreads = 2000, 4

    def worker():
        for _ in range(per_thread):
            fz.stat_add(Stat.FUZZ)

    threads = [threading.Thread(target=worker) for _ in range(nthreads)]
    for t in threads:
        t.start()
    drained = 0
    while any(t.is_alive() for t in threads):
        drained += fz.grab_stats().get(STAT_NAMES[Stat.FUZZ], 0)
    for t in threads:
        t.join()
    drained += fz.grab_stats().get(STAT_NAMES[Stat.FUZZ], 0)
    assert drained == per_thread * nthreads
    # and the registry mirror holds the same monotonic total
    name = "tz_fuzzer_exec_fuzz_total"
    assert telemetry.REGISTRY.counter(name).value >= drained


# -- ShardProfiler (fault-domain mesh, ISSUE 11) ------------------------


def test_shard_profiler_fixed_slots_and_ewma():
    """ShardProfiler mirrors the KernelProfiler contract: slots are
    pre-allocated by ensure() at topology-build time, note() on an
    unknown shard is a no-op (zero-allocation hot path), the first
    sample seeds the EWMA exactly, and the labeled gauge family
    carries one series per shard."""
    from syzkaller_tpu.telemetry.profiler import EWMA_ALPHA, ShardProfiler

    prof = ShardProfiler()
    prof.ensure(0)
    prof.ensure(3)
    prof.ensure(3)  # idempotent

    prof.note(0, 0.010)
    assert prof.snapshot()["0"] == {"ms_per_batch": 10.0, "batches": 1}
    prof.note(0, 0.020)
    want = 10.0 + EWMA_ALPHA * (20.0 - 10.0)
    got = prof.snapshot()["0"]
    assert got["batches"] == 2
    assert abs(got["ms_per_batch"] - want) < 1e-6

    # unknown shard: ignored, no slot materializes
    prof.note(7, 0.5)
    assert set(prof.snapshot()) == {"0", "3"}
    assert prof.snapshot()["3"] == {"ms_per_batch": 0.0, "batches": 0}

    # the labeled series exists in the global registry family
    g = telemetry.REGISTRY.gauge("tz_mesh_shard_ms_per_batch",
                                 labels={"shard": "0"})
    assert g.full_name == 'tz_mesh_shard_ms_per_batch{shard="0"}'
