"""Email reporting workflow (VERDICT r2 #10; reference: pkg/email +
dashboard/app/reporting.go).

The lifecycle gate: a crash flows new -> reported (mail out) ->
fixed/invalid/dup (command replies in), plus '#syz test' patch jobs,
all via simulated mail round-trips.
"""

from email.message import EmailMessage

import pytest

from syzkaller_tpu.dashboard.app import (
    STATUS_DUP,
    STATUS_FIXED,
    STATUS_INVALID,
    STATUS_REPORTED,
    Dashboard,
)
from syzkaller_tpu.email import EmailReporting, Mailbox, parse_email


@pytest.fixture
def dash(tmp_path):
    return Dashboard(str(tmp_path), clients={"mgr": "key"},
                     reporting_delay_s=0.0)


def _crash(dash, title="BUG: unable to handle kernel NULL pointer "
                       "dereference in foo", repro=""):
    return dash.report_crash({
        "client": "mgr", "key": "key", "manager": "mgr",
        "title": title, "repro_prog": repro, "log": "log!",
        "report": "BUG: ...\nCall Trace:\n foo+0x1/0x2",
    })["bug_id"]


def _reply(reporting, commands: str, subject="Re: bug",
           patch: str = "", report_raw: bytes = None) -> None:
    if report_raw is None:
        report_raw = reporting.mailbox.outgoing[-1]
    rep = parse_email(report_raw)
    m = EmailMessage()
    m["Subject"] = subject
    m["From"] = "dev@kernel.org"
    m["To"] = rep.from_addr
    m["In-Reply-To"] = rep.msg_id
    m["Message-ID"] = "<reply-1@kernel.org>"
    body = f"Thanks.\n\n{commands}\n"
    if patch:
        body += "\n" + patch + "\n"
    body += "\n> quoted original\n"
    m.set_content(body)
    reporting.mailbox.deliver(bytes(m))


def test_lifecycle_new_reported_fixed(dash):
    bug_id = _crash(dash, repro="r0 = dz_open(...)")
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)

    assert dash.bugs[bug_id].status == "new"
    assert reporting.poll_and_send() == 1
    assert dash.bugs[bug_id].status == STATUS_REPORTED

    # The outbound mail is a well-formed report with the repro and
    # the command footer.
    rep = parse_email(mbox.outgoing[0])
    assert dash.bugs[bug_id].title in rep.subject
    assert "dz_open" in rep.raw_body
    assert "#syz fix:" in rep.raw_body

    _reply(reporting, "#syz fix: kernel: fix null deref in foo")
    assert reporting.process_incoming() == 1
    bug = dash.bugs[bug_id]
    assert bug.status == STATUS_FIXED
    assert bug.fix_commit == "kernel: fix null deref in foo"


def test_lifecycle_invalid_and_dup(dash):
    b1 = _crash(dash, title="WARNING in bar")
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    reporting.poll_and_send()
    _reply(reporting, "#syz invalid")
    reporting.process_incoming()
    assert dash.bugs[b1].status == STATUS_INVALID

    b2 = _crash(dash, title="KASAN: use-after-free in baz")
    reporting.poll_and_send()
    _reply(reporting, "#syz dup: WARNING in bar")
    reporting.process_incoming()
    assert dash.bugs[b2].status == STATUS_DUP
    # dup targets resolve to the canonical bug id (cross-namespace
    # dup management, r5): the title names it, the id is stored
    assert dash.bugs[b2].dup_of == b1

    # undup restores the reported state.
    _reply(reporting, "#syz undup")
    reporting.process_incoming()
    assert dash.bugs[b2].status == STATUS_REPORTED


def test_patch_test_command_creates_job(dash):
    bug_id = _crash(dash, title="BUG: soft lockup in qux")
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    reporting.poll_and_send()
    patch = (
        "diff --git a/fs/foo.c b/fs/foo.c\n"
        "--- a/fs/foo.c\n"
        "+++ b/fs/foo.c\n"
        "@@ -1,2 +1,3 @@\n"
        " int foo(void) {\n"
        "+  if (!p) return -EINVAL;\n"
        " }\n")
    _reply(reporting,
           "#syz test: git://git.kernel.org/torvalds/linux.git master",
           patch=patch)
    assert reporting.process_incoming() == 1
    jobs = [j for j in dash.jobs.values() if j.bug_id == bug_id]
    assert len(jobs) == 1
    assert "return -EINVAL" in jobs[0].patch
    assert jobs[0].kernel_repo.endswith("linux.git")
    assert jobs[0].kernel_branch == "master"


def test_bad_commands_get_error_replies(dash):
    _crash(dash, title="BUG: sleeping in atomic in quux")
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    reporting.poll_and_send()
    report_raw = mbox.outgoing[-1]
    n_out = len(mbox.outgoing)
    _reply(reporting, "#syz fix:")  # missing commit title
    assert reporting.process_incoming() == 0
    assert len(mbox.outgoing) == n_out + 1
    nack = parse_email(mbox.outgoing[-1])
    assert "could not be processed" in nack.raw_body

    _reply(reporting, "#syz frobnicate",  # unknown command
           report_raw=report_raw)
    reporting.process_incoming()
    assert "unknown command" in parse_email(mbox.outgoing[-1]).raw_body


def test_threading_survives_restart(dash, tmp_path):
    """Report threading is persisted on the bug: a reply arriving
    after the reporting process restarts still lands."""
    bug_id = _crash(dash, title="BUG: restart survivor")
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    reporting.poll_and_send()
    report_raw = mbox.outgoing[-1]

    # Fresh dashboard + reporting instances from persisted state.
    dash2 = Dashboard(str(tmp_path), clients={"mgr": "key"},
                      reporting_delay_s=0.0)
    mbox2 = Mailbox()
    reporting2 = EmailReporting(dash2, mbox2)
    _reply(reporting2, "#syz fix: the fix", report_raw=report_raw)
    assert reporting2.process_incoming() == 1
    assert dash2.bugs[bug_id].status == STATUS_FIXED


def test_reply_to_unknown_thread_ignored(dash):
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    m = EmailMessage()
    m["Subject"] = "stray"
    m["From"] = "rando@example.com"
    m["In-Reply-To"] = "<not-a-bug@localhost>"
    m.set_content("#syz invalid\n")
    mbox.deliver(bytes(m))
    assert reporting.process_incoming() == 0


def test_parse_quoting_and_patch_extraction():
    m = EmailMessage()
    m["Subject"] = "Re: something"
    m["From"] = "Dev Name <dev@example.com>"
    m["Message-ID"] = "<x@y>"
    m.set_content(
        "On Mon, Someone wrote:\n"
        "> #syz invalid\n"
        "Real text.\n"
        "#syz test: repo branch\n"
        "diff --git a/a.c b/a.c\n"
        "--- a/a.c\n"
        "+++ b/a.c\n"
        "@@ -1 +1 @@\n"
        "-old\n"
        "+new\n")
    em = parse_email(bytes(m))
    # Quoted '#syz invalid' must NOT be picked up.
    assert [c.name for c in em.commands] == ["test"]
    assert em.patch.startswith("diff --git")
    assert "+new" in em.patch
    assert em.from_addr == "dev@example.com"
