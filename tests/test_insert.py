"""Device-side call insertion (VERDICT r2 #4 / SURVEY §7.5).

Insert-class mutants come back as spliced exec streams; the oracle is
semantic: the stream must parse to the expected call sequence, the
donor's copyout indices must not collide with the template's, and the
typed decode must execute equivalently on the sim executor.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.models.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.models.validation import validate_prog  # noqa: E402
from syzkaller_tpu.ops.emit import parse_stream  # noqa: E402
from syzkaller_tpu.ops.insert import DonorBank, choice_table_rows  # noqa: E402
from syzkaller_tpu.ops.pipeline import (  # noqa: E402
    DevicePipeline,
    P_INSERT_GIVEN_DEVICE,
)


def _pipeline_with_corpus(target, n_seeds=10, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("batch_size", 64)
    pl = DevicePipeline(target, seed=21, **kw)
    added, i = 0, 0
    while added < n_seeds and i < n_seeds * 6:
        p = generate_prog(target, RandGen(target, 9000 + i), 5)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= n_seeds // 2
    return pl


def test_donor_bank_builds(test_target):
    from syzkaller_tpu.models.prio import build_choice_table

    ct = build_choice_table(test_target)
    bank = DonorBank(test_target, ct, seed=1)
    assert len(bank) >= len(test_target.syscalls) // 2
    for block in bank.blocks[:10]:
        # Standalone donor blocks are valid programs of their own.
        from syzkaller_tpu.models.prog import Prog

        validate_prog(Prog(target=test_target, calls=block.calls))
        assert block.words.size > 0
        assert parse_stream(block.words.tobytes()
                            + b"\xff" * 8) == block.call_ids
    runs = choice_table_rows(test_target, ct)
    assert runs.shape[0] == runs.shape[1]
    assert (runs[:, -1] > 0).all()


def test_insert_mutants_flow_and_parse(test_target):
    pl = _pipeline_with_corpus(test_target)
    try:
        inserts = []
        for _ in range(4):
            batch = pl.next_batch(timeout=240)
            inserts += [m for m in batch if m.donor is not None]
            if len(inserts) >= 20:
                break
        assert pl.stats.inserts >= 10, "no insert mutants produced"
        total = pl.stats.mutants
        frac = pl.stats.inserts / max(total, 1)
        assert abs(frac - P_INSERT_GIVEN_DEVICE) < 0.15, \
            f"insert fraction {frac} vs expected {P_INSERT_GIVEN_DEVICE}"
        for m in inserts[:12]:
            ids = parse_stream(m.exec_bytes)
            assert len(ids) == m.num_calls()
            # The donor's call ids appear contiguously at the boundary.
            pos = min(m.donor_pos, len(ids) - len(m.donor.call_ids))
            assert ids[pos:pos + len(m.donor.call_ids)] == m.donor.call_ids
    finally:
        pl.stop()


def test_insert_decode_valid_and_equivalent(test_target):
    """Typed decode of insert mutants validates, contains the donor
    calls, and executes equivalently to the spliced stream on the sim
    executor (same call sequence, same errnos)."""
    from syzkaller_tpu.ipc.env import ExecOpts, make_env

    pl = _pipeline_with_corpus(test_target)
    env = make_env(pid=0, sim=True, signal=True)
    try:
        inserts = []
        for _ in range(4):
            batch = pl.next_batch(timeout=240)
            inserts += [m for m in batch if m.donor is not None]
            if len(inserts) >= 6:
                break
        assert inserts
        for m in inserts[:6]:
            p = m.prog()
            validate_prog(p)
            assert len(p.calls) == m.num_calls()
            res_dev = env.exec(ExecOpts(), m.exec_bytes)
            res_typed = env.exec(ExecOpts(), serialize_for_exec(p))
            assert len(res_dev.info) == len(res_typed.info)
            for a, b in zip(res_dev.info, res_typed.info):
                assert a.call_id == b.call_id
                assert a.errno == b.errno, \
                    f"splice vs typed diverged on call {a.call_id}"
    finally:
        pl.stop()
        env.close()


def test_insert_copyout_rebasing(test_target):
    """A donor with internal result edges keeps them intact after
    splicing into a template that itself uses copyouts."""
    pl = _pipeline_with_corpus(test_target, n_seeds=16)
    try:
        found = False
        # The donor+template copyout coincidence is probabilistic (the
        # exact programs depend on every upstream RNG consumer, e.g.
        # the text-arg generator); give it a deep budget — each batch
        # is cheap once the step is compiled.
        for _ in range(30):
            batch = pl.next_batch(timeout=240)
            for m in batch:
                if m.donor is None or m.donor.ncopyouts == 0 \
                        or m.et.ncopyouts == 0:
                    continue
                parse_stream(m.exec_bytes)  # structurally sound
                # Donor copyout indices in the spliced stream must sit
                # at/above the template's range.
                words = np.frombuffer(m.exec_bytes, dtype="<u8")
                rebased = m.donor.rebased_words(m.et.ncopyouts)
                assert any(
                    np.array_equal(words[i:i + rebased.size], rebased)
                    for i in range(0, words.size - rebased.size + 1)), \
                    "rebased donor words not found in spliced stream"
                found = True
                break
            if found:
                break
        assert found, "never saw a donor+template copyout combination"
    finally:
        pl.stop()
