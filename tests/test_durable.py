"""Durable state & warm restart (ISSUE 13): WAL framing, checkpoint
images, fault-seam behavior, domain restore round-trips, and the
scripted-SIGKILL chaos drill that pins the crash-consistency contract
(zero lost corpus, zero double-counted custody, zero false-novel
edges, delivery order preserved)."""

import os
import signal as _signal
import subprocess
import sys
import time
from collections import Counter

import numpy as np
import pytest

from syzkaller_tpu.durable.checkpoint import (CheckpointError,
                                              pack_section,
                                              read_checkpoint,
                                              unpack_section,
                                              write_checkpoint)
from syzkaller_tpu.durable.store import (DurableStore, RECOVERY_FAILED,
                                         RECOVERY_NONE, RECOVERY_WARM)
from syzkaller_tpu.durable.wal import WriteAheadLog, read_wal
from syzkaller_tpu.health.faultinject import (FaultPlan, install_plan,
                                              reset_plan)
from syzkaller_tpu.manager.rpcserver import ManagerRPC
from syzkaller_tpu.rpc.types import RPCCandidate
from syzkaller_tpu.serve.broker import ServePlane

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_fault_plan():
    reset_plan()
    yield
    reset_plan()


# -- WAL -----------------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    path = str(tmp_path / "state.wal")
    wal = WriteAheadLog(path)
    wal.append("merge", {"prio": 2, "size": 64}, b"\x01\x02\x03")
    wal.append("cand_add", {"cands": [{"prog": "p()"}]})
    wal.append("empty")
    wal.close()
    recs = read_wal(path)
    assert [(r.kind, r.meta, r.blob) for r in recs] == [
        ("merge", {"prio": 2, "size": 64}, b"\x01\x02\x03"),
        ("cand_add", {"cands": [{"prog": "p()"}]}, b""),
        ("empty", {}, b""),
    ]


def test_wal_torn_tail_truncated(tmp_path):
    path = str(tmp_path / "state.wal")
    wal = WriteAheadLog(path)
    for i in range(3):
        wal.append("merge", {"i": i})
    wal.close()
    whole = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00torn-frame-without-its-bytes")
    recs = read_wal(path)
    assert [r.meta["i"] for r in recs] == [0, 1, 2]
    # physically truncated back to the last whole record, so a
    # post-recovery append lands after valid bytes
    assert os.path.getsize(path) == whole
    wal2 = WriteAheadLog(path)
    wal2.append("merge", {"i": 3})
    wal2.close()
    assert [r.meta["i"] for r in read_wal(path)] == [0, 1, 2, 3]


def test_wal_corrupt_record_drops_tail(tmp_path):
    path = str(tmp_path / "state.wal")
    wal = WriteAheadLog(path)
    wal.append("a", {"n": 1})
    keep = os.path.getsize(path)
    wal.append("b", {"n": 2})
    wal.close()
    # flip a payload byte of the second record: crc mismatch drops it
    # AND everything after it
    with open(path, "r+b") as f:
        f.seek(keep + 9)
        b = f.read(1)
        f.seek(keep + 9)
        f.write(bytes([b[0] ^ 0xFF]))
    recs = read_wal(path)
    assert [r.kind for r in recs] == ["a"]
    assert os.path.getsize(path) == keep


def test_wal_bad_magic_discards(tmp_path):
    path = str(tmp_path / "state.wal")
    wal = WriteAheadLog(path)
    wal.append("a", {})
    wal.close()
    with open(path, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    assert read_wal(path) == []


# -- checkpoint images ---------------------------------------------------


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "state.ckpt")
    plane = np.zeros(256, np.uint8)
    plane[[3, 77, 200]] = 2
    write_checkpoint(path, {
        "control": ({"queue": [{"prog": "p()"}]}, b""),
        "signal_plane": ({"size": 256}, pack_section(plane)),
    }, ts=123.456)
    img = read_checkpoint(path)
    assert img["__ts__"] == 123.456
    meta, blob = img["signal_plane"]
    assert np.array_equal(unpack_section(blob, meta["size"]), plane)
    assert img["control"][0] == {"queue": [{"prog": "p()"}]}


def test_checkpoint_corruption_detected(tmp_path):
    path = str(tmp_path / "state.ckpt")
    write_checkpoint(path, {"s": ({"k": 1}, b"payload")}, ts=1.0)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:len(data) // 2] + b"\x00" + data[len(data) // 2 + 1:])
    with pytest.raises(CheckpointError):
        read_checkpoint(path)
    with open(path, "wb") as f:
        f.write(data[:-3])  # truncated
    with pytest.raises(CheckpointError):
        read_checkpoint(path)


# -- DurableStore --------------------------------------------------------


def test_store_fresh_start_is_cold(tmp_path):
    store = DurableStore(str(tmp_path / "d"), interval_s=3600.0)
    assert store.recovered is None
    assert store.recovery_state == RECOVERY_NONE
    store.close(final_checkpoint=False)


def test_store_checkpoint_resets_wal_and_recovers(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    store.register("control", lambda: ({"queue": [{"prog": "a()"}],
                                        "corpus": {}}, b""))
    store.journal("cand_add", {"cands": [{"prog": "pre()"}]})
    assert store.wal.bytes_since_ckpt > 0
    assert store.checkpoint_now()
    assert store.wal.bytes_since_ckpt == 0
    # a post-checkpoint record rides the WAL on top of the image
    store.journal("cand_add", {"cands": [{"prog": "post()"}]})
    store.close(final_checkpoint=False)
    store2 = DurableStore(d, interval_s=3600.0)
    assert store2.recovery_state == RECOVERY_WARM
    queue = [c["prog"] for c in store2.recovered["control"]["queue"]]
    assert queue == ["a()", "post()"]  # image state + WAL replay
    store2.close(final_checkpoint=False)


def test_store_corpus_arena_section_roundtrip(tmp_path):
    """The arena's durable authority rides checkpoints as an opaque
    (meta, blob) section: pack_arena on the provider side, a jax-free
    passthrough in recovery.replay, unpack_arena on restore."""
    from syzkaller_tpu.ops.arena import pack_arena, unpack_arena

    progs = [b"r0(0x1)", b"r1(0x2, 0x3)", b"r2()"]
    weights = np.array([1, 5, 2], np.uint32)
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    store.register("corpus_arena",
                   lambda: pack_arena(progs, weights, epoch=4))
    assert store.checkpoint_now()
    store.close(final_checkpoint=False)

    store2 = DurableStore(d, interval_s=3600.0)
    assert store2.recovery_state == RECOVERY_WARM
    sec = store2.recovered["corpus_arena"]
    # recovery must not decode the section (jax-free passthrough):
    # it hands back exactly the meta dict + compressed blob
    assert isinstance(sec["blob"], bytes)
    assert sec["meta"]["n"] == 3 and sec["meta"]["epoch"] == 4
    got_progs, got_w, got_epoch = unpack_arena(sec["meta"], sec["blob"])
    assert got_progs == progs
    assert got_w.dtype == np.uint32
    assert got_w.tolist() == [1, 5, 2]
    assert got_epoch == 4
    store2.close(final_checkpoint=False)

    # a checkpoint written without the section recovers without it:
    # older images stay readable (forward/backward compatibility)
    d2 = str(tmp_path / "d2")
    store3 = DurableStore(d2, interval_s=3600.0)
    store3.register("control", lambda: ({"queue": [], "corpus": {}}, b""))
    assert store3.checkpoint_now()
    store3.close(final_checkpoint=False)
    store4 = DurableStore(d2, interval_s=3600.0)
    assert "corpus_arena" not in store4.recovered
    store4.close(final_checkpoint=False)


def test_store_ckpt_seam_leaves_previous_image_authoritative(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    state = {"queue": [{"prog": "v1()"}], "corpus": {}}
    store.register("control", lambda: (dict(state), b""))
    assert store.checkpoint_now()
    state["queue"] = [{"prog": "v2()"}]
    store.journal("max_sig", {"sig": [[9], [3]]})
    wal_bytes = store.wal.bytes_since_ckpt
    install_plan(FaultPlan.parse("durable.ckpt_write:fail@1"))
    assert not store.checkpoint_now()
    assert store.last_ckpt_error
    # the WAL was NOT reset: the previous image + journal stay
    # authoritative, and the fully-written-but-unpublished tmp exists
    assert store.wal.bytes_since_ckpt == wal_bytes
    assert os.path.exists(os.path.join(d, "state.ckpt.tmp"))
    store.close(final_checkpoint=False)
    reset_plan()
    store2 = DurableStore(d, interval_s=3600.0)
    # stale tmp cleaned; recovery sees v1 image + the journaled record
    assert not os.path.exists(os.path.join(d, "state.ckpt.tmp"))
    control = store2.recovered["control"]
    assert [c["prog"] for c in control["queue"]] == ["v1()"]
    assert 9 in control["max_signal"].serialize()[0]
    store2.close(final_checkpoint=False)


def test_store_wal_append_seam_swallowed_and_counted(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    install_plan(FaultPlan.parse("durable.wal_append:fail@2"))
    store.journal("cand_add", {"cands": [{"prog": "a()"}]})
    store.journal("cand_add", {"cands": [{"prog": "lost()"}]})
    store.journal("cand_add", {"cands": [{"prog": "c()"}]})
    assert store.wal_errors == 1
    store.close(final_checkpoint=False)
    reset_plan()
    store2 = DurableStore(d, interval_s=3600.0)
    # durability regressed to the previous record, never correctness:
    # the surviving records replay cleanly
    queue = [c["prog"] for c in store2.recovered["control"]["queue"]]
    assert queue == ["a()", "c()"]
    store2.close(final_checkpoint=False)


def test_store_journal_after_close_is_noop(tmp_path):
    """Holders may outlive the store (e.g. the process-global
    coverage tracker racing shutdown): a post-close journal() must
    no-op — never raise, never count as a WAL error."""
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    store.journal("cand_add", {"cands": [{"prog": "kept()"}]})
    store.close(final_checkpoint=False)
    store.journal("cand_add", {"cands": [{"prog": "late()"}]})
    assert store.wal_errors == 0
    store2 = DurableStore(d, interval_s=3600.0)
    queue = [c["prog"] for c in store2.recovered["control"]["queue"]]
    assert queue == ["kept()"]
    store2.close(final_checkpoint=False)


def test_store_corrupt_image_quarantined_wal_only_recovery(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    store.register("control", lambda: ({"queue": [], "corpus": {
        "k": {"prog": "from_image()"}}}, b""))
    assert store.checkpoint_now()
    store.journal("cand_add", {"cands": [{"prog": "from_wal()"}]})
    store.close(final_checkpoint=False)
    ckpt = os.path.join(d, "state.ckpt")
    data = open(ckpt, "rb").read()
    with open(ckpt, "wb") as f:
        f.write(data[:-2] + b"\xff\xff")  # break the trailing crc
    store2 = DurableStore(d, interval_s=3600.0)
    assert store2.recovery_state == RECOVERY_FAILED
    assert os.path.exists(ckpt + ".corrupt")
    assert not os.path.exists(ckpt)
    # WAL-only recovery still lands what the journal held
    queue = [c["prog"] for c in store2.recovered["control"]["queue"]]
    assert queue == ["from_wal()"]
    store2.close(final_checkpoint=False)


def test_store_broken_provider_skips_section_only(tmp_path):
    store = DurableStore(str(tmp_path / "d"), interval_s=3600.0)
    store.register("control", lambda: ({"queue": [], "corpus": {
        "k": {"prog": "ok()"}}}, b""))
    store.register("broken", lambda: (_ for _ in ()).throw(
        RuntimeError("provider died")))
    assert store.checkpoint_now()
    img = read_checkpoint(os.path.join(str(tmp_path / "d"),
                                       "state.ckpt"))
    assert "control" in img and "broken" not in img
    store.close(final_checkpoint=False)


def test_store_wal_cap_requests_early_checkpoint(tmp_path):
    # the cap floors at 1 MiB (store.__init__), so cross it for real
    store = DurableStore(str(tmp_path / "d"), interval_s=3600.0,
                         wal_cap_mb=1.0)
    assert not store._ckpt_due.is_set()
    store.journal("merge", {"size": 64}, b"\x00" * ((1 << 20) + 64))
    assert store._ckpt_due.is_set()
    store.close(final_checkpoint=False)


def test_store_unknown_wal_kind_skipped(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    store.journal("from_the_future", {"v": 2}, b"opaque")
    store.journal("cand_add", {"cands": [{"prog": "p()"}]})
    store.close(final_checkpoint=False)
    store2 = DurableStore(d, interval_s=3600.0)
    queue = [c["prog"] for c in store2.recovered["control"]["queue"]]
    assert queue == ["p()"]
    store2.close(final_checkpoint=False)


# -- domain round-trips --------------------------------------------------


def _mk_control(store):
    serv = ManagerRPC(lease_s=3600.0)
    serv.durable = store
    store.register("control", serv.durable_export)
    return serv


def test_control_plane_roundtrip_conserves_custody(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    serv = _mk_control(store)
    conn = serv.Connect({"name": "f"})
    serv.add_candidates([RPCCandidate(prog=f"c{i}()")
                         for i in range(6)])
    # issue all six into f's custody (sessioned, so the ledger tracks)
    serv.Poll({"name": "f", "epoch": conn["epoch"], "seq": 1,
               "ack_seq": 0, "need_candidates": True, "stats": {},
               "max_signal": [[], []]})
    assert serv.candidate_backlog() == 6  # in flight, not lost
    serv.NewInput({"name": "f", "input": {
        "call": "x", "prog": "corp()", "signal": [[5, 6], [3, 3]],
        "cover": [41]}})
    assert store.checkpoint_now()
    # post-checkpoint mutations ride the WAL
    serv.add_candidates([RPCCandidate(prog="late()")])
    serv.NewInput({"name": "f", "input": {
        "call": "y", "prog": "corp2()", "signal": [[7], [3]],
        "cover": []}})
    store.close(final_checkpoint=False)

    store2 = DurableStore(d, interval_s=3600.0)
    serv2 = _mk_control(store2)
    serv2.durable_restore(store2.recovered["control"])
    # custody collapsed: every unexecuted candidate is back in the
    # queue exactly once (zero loss, zero double-count)
    queue = Counter(c["prog"] for c in serv2.candidates)
    assert queue == Counter([f"c{i}()" for i in range(6)] + ["late()"])
    assert {v["prog"] for v in serv2.corpus.values()} == \
        {"corp()", "corp2()"}
    # signal aggregates and cover survive
    assert sorted(serv2.corpus_signal.serialize()[0]) == [5, 6, 7]
    assert 41 in serv2.cover
    # fuzzer sessions are NOT restored: the fresh epoch forces
    # re-Connect, and the restored corpus is served there
    assert not serv2.fuzzers
    conn2 = serv2.Connect({"name": "f"})
    assert {i["prog"] for i in conn2["corpus"]} == {"corp()", "corp2()"}
    store2.close(final_checkpoint=False)


def test_serve_plane_roundtrip_preserves_delivery_order(tmp_path):
    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    broker = ServePlane(lease_s=3600.0)
    broker.durable = store
    store.register("serve", broker.durable_provider)
    broker.Connect({"name": "vm"})
    broker.offer("vm", [b"m1", b"m2"], rows_spent=2, novel=1)
    # issue m1+m2 in flight under seq 1 (never acked -> must requeue
    # at the FRONT on recovery, ahead of later offers)
    broker.Poll({"name": "vm", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 10}})
    assert store.checkpoint_now()
    broker.offer("vm", [b"m3"], rows_spent=1, novel=0)
    store.close(final_checkpoint=False)

    store2 = DurableStore(d, interval_s=3600.0)
    broker2 = ServePlane(lease_s=3600.0)
    broker2.durable = store2
    broker2.durable_restore(store2.recovered["serve"])
    t = broker2.tenants["vm"]
    assert [bytes(p) for _rid, p in t.pending] == [b"m1", b"m2", b"m3"]
    # rids unique across the checkpoint boundary (the rid counter was
    # restored, so new offers never collide with recovered ones)
    broker2.offer("vm", [b"m4"], rows_spent=1, novel=0)
    rids = [rid for rid, _p in broker2.tenants["vm"].pending]
    assert len(set(rids)) == len(rids) == 4
    # recovered tenants idle un-reaped until their VM re-Connects
    broker2.reap_expired()
    assert "vm" in broker2.tenants
    # and Connect keeps the recovered queue
    broker2.Connect({"name": "vm"})
    assert [bytes(p) for _rid, p in
            broker2.tenants["vm"].pending][:3] == [b"m1", b"m2", b"m3"]
    store2.close(final_checkpoint=False)


def test_coverage_roundtrip(tmp_path):
    from syzkaller_tpu.telemetry.coverage import CoverageTracker

    d = str(tmp_path / "d")
    store = DurableStore(d, interval_s=3600.0)
    cov = CoverageTracker(stall_window_s=300.0, stall_edges=1,
                          interval_s=0.0)
    cov.journal = store.journal
    cov.note_novel("triage", 17)
    cov.sample(occupancy=17)
    store.register("coverage", lambda: (cov.export_state(), b""))
    assert store.checkpoint_now()
    store.close(final_checkpoint=False)

    store2 = DurableStore(d, interval_s=3600.0)
    cov2 = CoverageTracker(stall_window_s=300.0, stall_edges=1,
                           interval_s=0.0)
    cov2.restore_state(store2.recovered["coverage"])
    snap = cov2.snapshot()
    assert snap["novel_edges_total"] == 17
    assert snap["occupancy"] == 17
    assert len(snap["growth_curve"]) >= 1
    store2.close(final_checkpoint=False)


# -- the scripted-SIGKILL chaos drill ------------------------------------

_DRILL_CHILD = r"""
import os, sys, time
import numpy as np
from syzkaller_tpu.durable.checkpoint import pack_section
from syzkaller_tpu.durable.store import DurableStore
from syzkaller_tpu.manager.rpcserver import ManagerRPC
from syzkaller_tpu.serve.broker import ServePlane
from syzkaller_tpu.rpc.types import RPCCandidate

workdir, ack_path = sys.argv[1], sys.argv[2]
MIRROR = 4096
store = DurableStore(workdir, interval_s=3600.0)
serv = ManagerRPC(lease_s=3600.0)
serv.durable = store
broker = ServePlane(lease_s=3600.0)
broker.durable = store
mirror = np.zeros(MIRROR, np.uint8)
store.register("control", serv.durable_export)
store.register("serve", broker.durable_provider)
store.register("signal_plane",
               lambda: ({"size": MIRROR}, pack_section(mirror)))
epoch = serv.Connect({"name": "f"})["epoch"]
broker.Connect({"name": "vm"})
ack = open(ack_path, "ab")
for r in range(1, 100000):
    serv.NewInput({"name": "f", "input": {
        "call": "x", "prog": "p%d()" % r,
        "signal": [[r], [3]], "cover": []}})
    serv.add_candidates([RPCCandidate(prog="c%d()" % r)])
    serv.Poll({"name": "f", "epoch": epoch, "seq": r,
               "ack_seq": r - 1, "need_candidates": True,
               "stats": {}, "max_signal": [[], []]})
    idx = np.array([(r * 7) % MIRROR], dtype=np.uint32)
    np.maximum.at(mirror, idx.astype(np.int64), np.uint8(3))
    store.journal("merge", {"prio": 2, "size": MIRROR}, idx.tobytes())
    broker.offer("vm", [b"r%d" % r], rows_spent=1, novel=1)
    if r == 5:
        assert store.checkpoint_now()
    # the round is durable (every journal append fsync'd) -> ack it
    ack.write(b"%d\n" % r)
    ack.flush()
    os.fsync(ack.fileno())
    time.sleep(0.002)
"""


@pytest.mark.slow
def test_sigkill_chaos_drill(tmp_path):
    """Kill -9 a live manager-shaped process mid-round; recovery must
    show zero lost corpus, zero double-counted custody, zero
    false-novel plane edges, and delivery order intact."""
    workdir = str(tmp_path / "durable")
    ack_path = str(tmp_path / "ack.log")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(
        [sys.executable, "-c", _DRILL_CHILD, workdir, ack_path],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 120.0
        acked = []
        while time.time() < deadline:
            if os.path.exists(ack_path):
                acked = open(ack_path, "rb").read().split()
            if len(acked) >= 12:
                break
            if child.poll() is not None:
                raise AssertionError(
                    "drill child exited early:\n"
                    + child.stderr.read().decode()[-2000:])
            time.sleep(0.02)
        assert len(acked) >= 12, "drill child made no progress"
        os.kill(child.pid, _signal.SIGKILL)
    finally:
        try:
            child.kill()
        except OSError:
            pass
        child.wait(timeout=30)
        child.stdout.close()
        child.stderr.close()
    acked = [int(x) for x in open(ack_path, "rb").read().split()]
    assert acked == list(range(1, len(acked) + 1))
    K = max(acked)

    store = DurableStore(workdir, interval_s=3600.0)
    assert store.recovery_state == RECOVERY_WARM
    rec = store.recovered
    control = rec["control"]
    # zero lost corpus: every acked round's input survives, and its
    # signal is already merged (nothing will be re-triaged or
    # re-claimed as novel)
    corpus_progs = {v["prog"] for v in control["corpus"].values()}
    sig_elems = set(control["corpus_signal"].serialize()[0])
    max_elems = set(control["max_signal"].serialize()[0])
    for r in range(1, K + 1):
        assert f"p{r}()" in corpus_progs
        assert r in sig_elems and r in max_elems
    # zero double-counted custody: every candidate appears at most
    # once across the collapsed ledger, and every acked round's
    # candidate is conserved
    queue = Counter(c["prog"] for c in control["queue"])
    assert not [p for p, n in queue.items() if n > 1]
    for r in range(1, K + 1):
        assert queue[f"c{r}()"] == 1
    # zero false-novel edges: every acked round's plane bucket is
    # still marked at its merged priority, and no bucket is set that
    # no round ever journaled (at most one un-acked tail round)
    mirror = rec["signal_mirror"]
    for r in range(1, K + 1):
        assert mirror[(r * 7) % 4096] == 3
    allowed = {(r * 7) % 4096 for r in range(1, K + 2)}
    assert set(np.nonzero(mirror)[0]) <= allowed
    # delivery order preserved: the serve queue replays the offers in
    # exact order, with at most one un-acked tail payload
    pending = rec["serve"]["tenants"]["vm"]["pending"]
    payloads = [bytes(p) for _rid, p in pending]
    assert payloads[:K] == [b"r%d" % r for r in range(1, K + 1)]
    assert len(payloads) <= K + 1
    rids = [rid for rid, _p in pending]
    assert len(set(rids)) == len(rids)
    store.close(final_checkpoint=False)
