"""Mesh-sharded fuzz step: multi-device correctness on the virtual
8-device CPU mesh (the driver separately dry-runs __graft_entry__)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.ops import signal as dsig  # noqa: E402
from syzkaller_tpu.parallel.mesh import (  # noqa: E402
    make_mesh,
    make_sharded_fuzz_step,
    shard_batch,
    shard_plane,
)


@pytest.fixture(scope="module")
def built():
    import __graft_entry__ as g

    return g._build_batch(batch_size=8, edges_per_prog=32)


@pytest.mark.parametrize("cov", [1, 2, 4])
def test_sharded_step_matches_single_device(built, cov):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    batch, plane, edges, nedges, prios, key, fv, fc = built
    mesh = make_mesh(jax.devices()[:8], cov=cov)
    step = make_sharded_fuzz_step(mesh, rounds=2)
    sb = shard_batch(mesh, batch)
    sp = shard_plane(mesh, plane)
    mutated, new_plane, counts = step(sb, sp, edges, nedges, prios, key,
                                      fv, fc)
    jax.block_until_ready(counts)

    # Reference single-device triage on the same inputs.
    ref_mask, ref_counts = dsig.diff_batch(plane, edges, nedges, prios)
    assert np.array_equal(np.asarray(counts), np.asarray(ref_counts)), cov
    ref_plane = dsig.merge(plane, edges, nedges, prios, ref_counts > 0)
    assert np.array_equal(np.asarray(new_plane), np.asarray(ref_plane)), cov

    # Mutated batch remains structurally sane (decoded elsewhere);
    # minimal sanity: dtypes/shapes preserved, some value changed.
    assert set(mutated.keys()) >= set(batch.keys())
    changed = any(
        not np.array_equal(np.asarray(mutated[k]), np.asarray(batch[k]))
        for k in ("val", "arena", "call_alive", "len_"))
    assert changed


def test_pipeline_mutants_decode_valid(test_target):
    """Device pipeline mutants decode into structurally valid typed
    programs (the triage-path decode)."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.validation import validate_prog
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    pl = DevicePipeline(test_target, capacity=32, batch_size=16, seed=3)
    added, i = 0, 0
    while added < 10 and i < 60:
        p = generate_prog(test_target, RandGen(test_target, i), 8)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= 8
    try:
        batch = pl.next_batch(timeout=120)
        assert len(batch) >= 8
        for m in batch:
            validate_prog(m.prog())
    finally:
        pl.stop()
