"""Mesh-sharded fuzz step: multi-device correctness on the virtual
8-device CPU mesh (the driver separately dry-runs __graft_entry__)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.ops import signal as dsig  # noqa: E402
from syzkaller_tpu.parallel.mesh import (  # noqa: E402
    make_mesh,
    make_sharded_fuzz_step,
    shard_batch,
    shard_plane,
)


@pytest.fixture(scope="module")
def built():
    import __graft_entry__ as g

    return g._build_batch(batch_size=8, edges_per_prog=32)


# Slow tier + one cov width only: each variant pays a fresh ~20s
# multi-device compile of the full fuzz-step graph, which the tier-1
# ceiling (ROADMAP: ~870s against an 870s timeout) cannot carry.
# Tier-1 coverage of the compat shim's collectives on the 8-way CPU
# mesh lives in test_mesh_faults; `pytest -m slow` runs the full
# sharded-step parity suite (cov=2 exercises both mesh axes; 1 and 4
# lower identically modulo ring size).
@pytest.mark.slow
@pytest.mark.parametrize("cov", [2])
def test_sharded_step_matches_single_device(built, cov):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    batch, plane, edges, nedges, prios, key, fv, fc = built
    mesh = make_mesh(jax.devices()[:8], cov=cov)
    step = make_sharded_fuzz_step(mesh, rounds=2)
    sb = shard_batch(mesh, batch)
    sp = shard_plane(mesh, plane)
    mutated, new_plane, counts = step(sb, sp, edges, nedges, prios, key,
                                      fv, fc)
    jax.block_until_ready(counts)

    # Reference single-device triage on the same inputs.
    ref_mask, ref_counts = dsig.diff_batch(plane, edges, nedges, prios)
    assert np.array_equal(np.asarray(counts), np.asarray(ref_counts)), cov
    ref_plane = dsig.merge(plane, edges, nedges, prios, ref_counts > 0)
    assert np.array_equal(np.asarray(new_plane), np.asarray(ref_plane)), cov

    # Mutated batch remains structurally sane (decoded elsewhere);
    # minimal sanity: dtypes/shapes preserved, some value changed.
    assert set(mutated.keys()) >= set(batch.keys())
    changed = any(
        not np.array_equal(np.asarray(mutated[k]), np.asarray(batch[k]))
        for k in ("val", "arena", "call_alive", "len_"))
    assert changed


def test_pipeline_mutants_decode_valid(test_target):
    """Device pipeline mutants decode into structurally valid typed
    programs (the triage-path decode)."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.validation import validate_prog
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    pl = DevicePipeline(test_target, capacity=32, batch_size=16, seed=3)
    added, i = 0, 0
    while added < 10 and i < 60:
        p = generate_prog(test_target, RandGen(test_target, i), 8)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= 8
    try:
        batch = pl.next_batch(timeout=120)
        assert len(batch) >= 8
        for m in batch:
            validate_prog(m.prog())
    finally:
        pl.stop()


# Slow tier: each of these pays its own ~20s multi-device XLA
# compile; tier-1 carries the compat-shim collectives via
# test_mesh_faults instead.  `pytest -m slow` runs them all.
@pytest.mark.slow
def test_sharded_pack_step_parses_per_shard(built):
    """The sharded production step (mutate -> pack -> pool) emits a
    self-contained wire block per shard whose mutants assemble to
    parseable exec streams."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW
    from syzkaller_tpu.ops.emit import (assemble_delta,
                                        build_exec_template, parse_stream)
    from syzkaller_tpu.ops.pipeline import PIPELINE_TENSOR_CONFIG
    from syzkaller_tpu.ops.tensor import FlagTables, encode_prog, stack_batch
    from syzkaller_tpu.parallel.mesh import (make_sharded_pack_step,
                                             shard_batch, unshard_delta)

    target = get_target("test", "64")
    flags = FlagTables.empty()
    tensors = []
    i = 0
    while len(tensors) < 16 and i < 128:
        p = generate_prog(target, RandGen(target, 600 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, PIPELINE_TENSOR_CONFIG, flags))
        except Exception:
            continue
    assert len(tensors) == 16
    ets = [build_exec_template(t) for t in tensors]
    mesh = make_mesh(jax.devices()[:8], cov=1)
    batch = shard_batch(
        mesh, {k: jnp.asarray(v)
               for k, v in stack_batch(tensors).items()})
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    tidx = jnp.arange(16, dtype=jnp.int32)
    step = make_sharded_pack_step(mesh, rounds=2)
    flat = step(batch, random.key(3), fv, fc, tidx)
    shards = unshard_delta(flat, mesh)
    assert len(shards) == 8
    parsed = 0
    for si, db in enumerate(shards):
        assert len(db) == 2
        for j in range(len(db)):
            if db.flags[j] & FLAG_OVERFLOW:
                continue
            ti = int(db.template_idx[j])
            assert si * 2 <= ti < (si + 1) * 2
            data = assemble_delta(ets[ti], db, j)
            if data is not None:
                parse_stream(data)
                parsed += 1
    assert parsed >= 8, f"only {parsed} mutants assembled"


# One host topology only (same compile-cost rationale as above).
@pytest.mark.slow
@pytest.mark.parametrize("hosts,cov", [(2, 2)])
def test_host_mesh_step_matches_single_device(built, hosts, cov):
    """The 3-axis ('host','batch','cov') step with inline DCN pmax
    produces exactly the single-device triage/merge result, and the
    periodic plane_host_sync collective is idempotent on the agreed
    plane."""
    from syzkaller_tpu.parallel.mesh import (
        make_host_mesh,
        make_plane_host_sync,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    batch, plane, edges, nedges, prios, key, fv, fc = built
    mesh = make_host_mesh(jax.devices()[:8], hosts=hosts, cov=cov)
    step = make_sharded_fuzz_step(mesh, rounds=2)
    sb = shard_batch(mesh, batch)
    sp = shard_plane(mesh, plane)
    mutated, new_plane, counts = step(sb, sp, edges, nedges, prios, key,
                                      fv, fc)
    jax.block_until_ready(counts)

    ref_mask, ref_counts = dsig.diff_batch(plane, edges, nedges, prios)
    assert np.array_equal(np.asarray(counts), np.asarray(ref_counts))
    ref_plane = dsig.merge(plane, edges, nedges, prios, ref_counts > 0)
    assert np.array_equal(np.asarray(new_plane), np.asarray(ref_plane))

    sync = make_plane_host_sync(mesh)
    synced = sync(new_plane)
    assert np.array_equal(np.asarray(synced), np.asarray(ref_plane))
