"""Core program-model tests: target build, layout, defaults, clone,
generation and mutation invariants (reference test strategy:
prog/prog_test.go, prog/mutation_test.go with logged seeds)."""

import random

import pytest

from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.prog import default_arg
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.types import StructType
from syzkaller_tpu.models.validation import validate_prog


def test_target_builds(test_target):
    assert len(test_target.syscalls) > 60
    assert test_target.syscall_map["tz_mmap"].id == 0
    # Resource subtyping (imprecise): kind chains prefix-compatible both
    # ways; unrelated kinds are not (reference: prog/resources.go:52-73).
    assert test_target.is_compatible_resource("token", "token_big")
    assert test_target.is_compatible_resource("token_big", "token")
    assert not test_target.is_compatible_resource("fd", "token")


def find_struct(target, name):
    out = []

    def rec(t, seen):
        if id(t) in seen:
            return
        seen.add(id(t))
        if getattr(t, "elem", None) is not None:
            rec(t.elem, seen)
        for f in getattr(t, "fields", []) or []:
            rec(f, seen)
        if t.name == name:
            out.append(t)

    seen = set()
    for c in target.syscalls:
        for a in c.args:
            rec(a, seen)
    return out[0] if out else None


@pytest.mark.parametrize("name,size", [
    # natural alignment: i16 i32 i8 i16 i64 -> 2+p2+4+1+p1+2+p6+8 = 24
    ("pad_natural", 24),
    ("pad_packed", 2 + 4 + 1 + 2 + 8),
    ("align_four", 4),
    ("align_one", 1),
    # packed+align4: 1+2=3 -> pad to 4
    ("packed_aligned", 4),
    # bf_aligned: two groups (3x int8:1 -> 1 byte, 3x int16:1 -> 2 bytes)
    # packed align 8 -> pad to 8
    ("bf_aligned", 8),
    # bf_grouped_inner: 3x int32:10 pack into one int32
    ("bf_grouped_inner", 4),
    ("be_ints", 1 + 2 + 4 + 8),
    ("arr_fixed", 2 + 16 + 2),
])
def test_struct_layout(test_target, name, size):
    st = find_struct(test_target, name)
    assert st is not None, f"struct {name} not found"
    assert not st.varlen, name
    assert st.size() == size, f"{name}: got {st.size()}, want {size}"


def test_varlen_structs(test_target):
    for name in ("tail_varlen", "arr_mid", "arr_tail", "u_varlen_host"):
        st = find_struct(test_target, name)
        assert st is not None and st.varlen, name
    u = find_struct(test_target, "u_fixed")
    assert not u.varlen and u.size() == 80  # array[int64, 10]


def test_default_args_validate(test_target):
    from syzkaller_tpu.models.prog import Call, Prog, make_return_arg

    for meta in test_target.syscalls:
        c = Call(meta=meta,
                 args=[default_arg(test_target, t) for t in meta.args],
                 ret=make_return_arg(meta.ret))
        p = Prog(target=test_target, calls=[c])
        validate_prog(p)


def test_generate_random(test_target, iters):
    for i in range(iters):
        rng = RandGen(test_target, i)
        p = generate_prog(test_target, rng, 10)
        assert len(p.calls) >= 10
        validate_prog(p)


def test_mutate_random(test_target, iters):
    corpus = []
    for i in range(iters):
        rng = RandGen(test_target, 1000 + i)
        p = generate_prog(test_target, rng, 10)
        corpus.append(p.clone())
        mutate_prog(p, rng, 30, ct=None, corpus=corpus)
        validate_prog(p)


def test_clone_preserves_graph(test_target, iters):
    from syzkaller_tpu.models.prog import ResultArg, foreach_arg

    for i in range(iters):
        rng = RandGen(test_target, 2000 + i)
        p = generate_prog(test_target, rng, 12)
        p1 = p.clone()
        validate_prog(p1)
        # Same shape
        assert [c.meta.name for c in p.calls] == [c.meta.name for c in p1.calls]
        # No shared args between p and p1
        ids0 = set()
        for c in p.calls:
            foreach_arg(c, lambda a, ctx: ids0.add(id(a)))
        for c in p1.calls:
            foreach_arg(c, lambda a, ctx: (
                pytest.fail("shared arg") if id(a) in ids0 else None))


def test_mutate_changes_something(test_target):
    changed = 0
    total = 40
    from syzkaller_tpu.models.encoding import serialize_prog

    for i in range(total):
        rng = RandGen(test_target, 3000 + i)
        p = generate_prog(test_target, rng, 10)
        before = serialize_prog(p)
        mutate_prog(p, rng, 30, ct=None, corpus=[])
        after = serialize_prog(p)
        if before != after:
            changed += 1
    # The reference demands ~every mutation changes the program
    # (reference: prog/mutation_test.go:27-47); allow a tiny slack.
    assert changed >= total - 2


def test_linux_target_builds(linux_target):
    assert linux_target.syscall_map["mmap"].nr == 9
    rng = RandGen(linux_target, 7)
    p = generate_prog(linux_target, rng, 15)
    validate_prog(p)


def test_transitively_enabled(test_target):
    enabled = {c: True for c in test_target.syscalls}
    supported, disabled = test_target.transitively_enabled_calls(enabled)
    assert len(supported) == len(test_target.syscalls)
    # Disable the only token ctor: users of token must be disabled too.
    enabled = {c: True for c in test_target.syscalls
               if c.name not in ("tz_res$make", "tz_res$make_big",
                                 "tz_res$out_arg")}
    supported, disabled = test_target.transitively_enabled_calls(enabled)
    names = {c.name for c in supported}
    assert "tz_res$use" not in names
    assert "tz_res$use_big" not in names
    assert any(c.name == "tz_res$use" for c in disabled)


def test_rand_range_int_negative_bounds(test_target):
    """int32[-20:19]-style ranges arrive as wrapped uint64 bounds
    (begin > end); the span must wrap Go-style — a negative Python
    modulus made these ranges produce uniform 64-bit garbage."""
    from syzkaller_tpu.models.rand import MASK64, RandGen

    rng = RandGen(test_target, 5)
    begin = (-20) & MASK64
    end = 19
    hits = 0
    n = 500
    for _ in range(n):
        v = rng.rand_range_int(begin, end)
        sv = v - (1 << 64) if v >= (1 << 63) else v
        hits += -20 <= sv <= 19
    # ~1% intentionally escapes the range via rand_int
    assert hits >= n * 0.9, f"only {hits}/{n} in range"
