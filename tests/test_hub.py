"""Hub federation tests: state machine, RPC service, manager syncer."""

import pytest

from syzkaller_tpu.hub.hub import Hub, serve_hub
from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.rpc import RPCClient, RPCError


def test_hub_state_exchange(tmp_path):
    st = HubState(str(tmp_path / "hub"))
    st.connect("mgrA", fresh=True, corpus=[b"a1()", b"a2()"])
    st.connect("mgrB", fresh=True, corpus=[b"b1()"])
    # B syncs: gets A's programs, not its own
    progs, repros, more = st.sync("mgrB", [], [], [], False)
    assert sorted(progs) == [b"a1()", b"a2()"]
    assert more == 0
    # second sync: nothing new
    progs, _, _ = st.sync("mgrB", [], [], [], False)
    assert progs == []
    # A adds a new program and receives B's in the same sync
    progs, _, _ = st.sync("mgrA", [b"a3()"], [], [], False)
    assert progs == [b"b1()"]
    # B receives only the delta
    progs, _, _ = st.sync("mgrB", [], [], [], False)
    assert progs == [b"a3()"]


def test_hub_state_repro_fanout(tmp_path):
    st = HubState(str(tmp_path / "hub"))
    for name in ("m1", "m2", "m3"):
        st.connect(name, fresh=True, corpus=[])
    st.sync("m1", [], [], [b"crasher()"], False)
    for name in ("m2", "m3"):
        _, repros, _ = st.sync(name, [], [], [], True)
        assert repros == [b"crasher()"]
        # delivered once only
        _, repros2, _ = st.sync(name, [], [], [], True)
        assert repros2 == []
    # the sender never gets its own repro back
    _, repros, _ = st.sync("m1", [], [], [], True)
    assert repros == []


def test_hub_state_persistence(tmp_path):
    wd = str(tmp_path / "hub")
    st = HubState(wd)
    st.connect("mgrA", fresh=True, corpus=[b"a1()"])
    st.connect("mgrB", fresh=True, corpus=[])
    st.sync("mgrB", [], [], [], False)  # consume
    # restart the hub: cursors and corpus survive
    st2 = HubState(wd)
    assert st2.stats()["corpus"] == 1
    progs, _, _ = st2.sync("mgrB", [], [], [], False)
    assert progs == []  # already delivered before restart


def test_hub_state_delete_and_purge(tmp_path):
    st = HubState(str(tmp_path / "hub"))
    st.connect("mgrA", fresh=True, corpus=[b"a1()", b"a2()"])
    from syzkaller_tpu.utils.hashsig import hash_string

    st.sync("mgrA", [], [hash_string(b"a1()")], [], False)
    st.purge_corpus()
    assert st.stats()["corpus"] == 1


def test_hub_rpc_auth(tmp_path):
    srv, hub = serve_hub(str(tmp_path / "hub"),
                         clients={"clientA": "secret"})
    try:
        c = RPCClient(srv.addr)
        with pytest.raises(RPCError, match="unauthorized"):
            c.call("Hub.Connect", {"client": "clientA", "key": "wrong",
                                   "manager": "m"})
        c.call("Hub.Connect", {"client": "clientA", "key": "secret",
                               "manager": "m", "fresh": True,
                               "corpus": ["x()"]})
        res = c.call("Hub.Sync", {"client": "clientA", "key": "secret",
                                  "manager": "m"})
        assert res["progs"] == []  # own program not echoed back
    finally:
        srv.close()


def test_manager_hub_integration(tmp_path, test_target):
    """Two managers federate corpus through a live hub."""
    from syzkaller_tpu.manager.manager import Manager, PHASE_TRIAGED_CORPUS
    from syzkaller_tpu.manager.mgrconfig import load_config
    from syzkaller_tpu.models.encoding import serialize_prog
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    srv, hub = serve_hub(str(tmp_path / "hub"), target=test_target)
    addr = f"{srv.addr[0]}:{srv.addr[1]}"

    def make_mgr(name):
        cfg = load_config({
            "workdir": str(tmp_path / name), "target": "test/64",
            "http": "", "name": name, "hub_client": name,
            "hub_addr": addr})
        return Manager(cfg)

    mA, mB = make_mgr("mgrA"), make_mgr("mgrB")
    try:
        p = generate_prog(test_target, RandGen(test_target, 5), 3)
        text = serialize_prog(p).decode()
        mA.serv.NewInput({"name": "f", "input": {
            "call": "c", "prog": text, "signal": [[1, 2], [3, 3]],
            "cover": []}})
        mA.phase = mB.phase = PHASE_TRIAGED_CORPUS
        mA.hub.sync_once()
        res = mB.hub.sync_once()
        assert res["received"] == 1
        assert mB.serv.candidate_backlog() >= 1
        assert mB.serv.candidates[0]["prog"] == text
    finally:
        mA.shutdown()
        mB.shutdown()
        srv.close()
