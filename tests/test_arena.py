"""Device-resident corpus arena tests (ISSUE 18).

The load-bearing oracles:
  - SAMPLING: `pick_rows` (the on-device weighted cumulative-weight
    search) must equal `pick_rows_host` bit for bit on the same
    uint32 draws, and with unit weights must degenerate EXACTLY to
    the legacy `bits % n` row stream — turning the arena on may not
    move a single sample.
  - SPLICE: `splice_insert_group_flat` (flat DonorBankTable indexing,
    no per-base donor re-stack) must be byte-identical to the staged
    `splice_insert_group` path on the same inputs.
  - DISTILL: the fused device bisection (`make_distill_check`) must
    agree verdict-for-verdict with the host oracle
    (`distill_verdicts_host` = sim_exec_host + digest_covers at
    FOLD_BITS, where the digest bucket IS the fold).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from syzkaller_tpu.ops.arena import (  # noqa: E402
    CorpusArena,
    DistillLane,
    alive_mask_bits,
    build_distill_batch,
    cumw_from_weights,
    distill_verdicts_host,
    make_distill_check,
    pack_arena,
    pick_rows,
    pick_rows_host,
    slab_capacity,
    truncated_alive,
    truncation_keep_counts,
    unpack_arena,
)


# -- sizing ---------------------------------------------------------------


def test_slab_capacity_rounds_up_and_trims_to_headroom():
    # Plenty of headroom: round the ring up to whole slabs.
    assert slab_capacity(64, 100, headroom_bytes=1 << 30,
                         slab_bits=10) == 1024
    assert slab_capacity(1025, 100, headroom_bytes=1 << 40,
                         slab_bits=10) == 2048
    # Tight headroom: trim whole slabs back toward the request, but
    # never below it — the ring needs its slots.
    tight = slab_capacity(64, 1 << 20, headroom_bytes=1 << 20,
                          slab_bits=4)
    assert 64 <= tight < 1024
    assert tight % (1 << 4) == 0
    assert slab_capacity(64, 1 << 20, headroom_bytes=0,
                         slab_bits=4) == 64
    # Degenerate request still yields one slab.
    assert slab_capacity(1, 8, headroom_bytes=1 << 30,
                         slab_bits=4) == 16


# -- sampling parity ------------------------------------------------------


def test_pick_rows_unit_weights_is_legacy_modulo_stream():
    """Unit weights: cumw = [1..n, n, ..], total = n, so the pick is
    bit-exactly the legacy `bits % n` — for every n and a threefry-
    sized random draw."""
    rng = np.random.RandomState(11)
    for n in (1, 2, 7, 64, 100):
        cumw, total = cumw_from_weights(np.ones(n, np.uint32), n, 128)
        assert total == n
        bits = rng.randint(0, 1 << 32, size=256, dtype=np.uint64) \
            .astype(np.uint32)
        legacy = (bits % np.uint32(n)).astype(np.int32)
        host = pick_rows_host(cumw, total, bits)
        dev = np.asarray(pick_rows(jnp.asarray(cumw), total,
                                   jnp.asarray(bits)))
        np.testing.assert_array_equal(host, legacy)
        np.testing.assert_array_equal(dev, legacy)


def test_pick_rows_weighted_parity_randomized():
    """Randomized weighted parity: device and host pickers agree bit
    for bit on arbitrary small-int weight vectors (including zero-
    weight holes), and every pick lands on a positive-weight row."""
    rng = np.random.RandomState(23)
    for trial in range(10):
        cap = int(rng.choice([16, 64, 256]))
        n = int(rng.randint(1, cap + 1))
        weights = rng.randint(0, 9, size=cap).astype(np.uint32)
        weights[rng.randint(0, n)] = 1  # at least one occupied row
        weights[n:] = 0
        cumw, total = cumw_from_weights(weights, n, cap)
        assert total == int(weights[:n].sum())
        bits = rng.randint(0, 1 << 32, size=512, dtype=np.uint64) \
            .astype(np.uint32)
        host = pick_rows_host(cumw, total, bits)
        dev = np.asarray(pick_rows(jnp.asarray(cumw), total,
                                   jnp.asarray(bits)))
        np.testing.assert_array_equal(dev, host)
        assert host.min() >= 0 and host.max() < n
        assert np.all(weights[host] > 0), \
            "weighted pick landed on a zero-weight row"


def test_pick_rows_weight_bias_observable():
    """A heavily weighted row dominates the sample — the heat
    feedback must actually steer the stream."""
    weights = np.ones(8, np.uint32)
    weights[3] = 100
    cumw, total = cumw_from_weights(weights, 8, 16)
    rng = np.random.RandomState(5)
    bits = rng.randint(0, 1 << 32, size=2048, dtype=np.uint64) \
        .astype(np.uint32)
    picks = pick_rows_host(cumw, total, bits)
    frac = float(np.mean(picks == 3))
    assert frac > 0.8, f"weight-100 row drew only {frac:.2%}"


# -- arena lifecycle ------------------------------------------------------


def _row(i, seed=0):
    rng = np.random.RandomState(seed + i)
    return {"val": rng.randint(0, 1 << 31, size=6).astype(np.uint64),
            "len": np.int32(i + 1)}


def test_arena_stage_flush_matches_host_authority():
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for i in range(5):
        a.stage(i, _row(i))
    dev, n, cumw, total = a.flush(jnp)
    assert n == 5 and a.capacity == 16
    assert a.uploads == 1 and a.upload_bytes > 0
    assert len(a._pending) == 0
    for i in range(5):
        np.testing.assert_array_equal(
            np.asarray(dev["val"][i]), a.host["val"][i])
    assert total == 5  # unit weights
    # Clean re-flush: no new upload, same device image.
    dev2, _n2, _cw2, _t2 = a.flush(jnp)
    assert a.uploads == 1 and dev2 is dev


def test_arena_invalidate_bumps_epoch_and_restages_everything():
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for i in range(4):
        a.stage(i, _row(i))
    a.flush(jnp)
    assert a.epoch == 0
    a.invalidate()
    assert a.epoch == 1
    assert len(a._pending) == 4  # every occupied row re-stages
    dev, n, _cw, total = a.flush(jnp)
    assert a.uploads == 2 and n == 4 and total == 4
    np.testing.assert_array_equal(
        np.asarray(dev["val"][:4]), a.host["val"][:4])


def test_arena_flush_failure_keeps_pending_for_retry():
    """A scripted staging.h2d fault mid-commit leaves the pending set
    intact — the worker's retry re-uploads exactly what the failed
    scatter did not deliver."""
    from syzkaller_tpu.health.faultinject import (
        FaultInjected,
        FaultPlan,
        install_plan,
        reset_plan,
    )

    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for i in range(3):
        a.stage(i, _row(i))
    try:
        install_plan(FaultPlan.parse("staging.h2d:fail@1"))
        with pytest.raises(FaultInjected):
            a.flush(jnp)
        assert len(a._pending) == 3 and a.uploads == 0
        dev, n, _cw, _t = a.flush(jnp)  # seam fires only once
        assert a.uploads == 1 and n == 3
        assert len(a._pending) == 0
        np.testing.assert_array_equal(
            np.asarray(dev["val"][:3]), a.host["val"][:3])
    finally:
        reset_plan()


def test_arena_restage_during_flush_stays_pending():
    """The staleness-tick contract: a row re-staged between phase A
    and phase B (its data changed after the memcpy) survives the
    commit still pending, so the NEW data uploads next flush."""
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    a.stage(0, _row(0))
    token = a.begin_flush(jnp)
    assert token[0] == "staged"
    a.stage(0, _row(0, seed=99))  # newer tick, new bytes
    a.commit_flush(jnp, token)
    assert 0 in a._pending, "re-staged row was dropped by the commit"
    dev, _n, _cw, _t = a.flush(jnp)
    np.testing.assert_array_equal(
        np.asarray(dev["val"][0]), _row(0, seed=99)["val"])


def test_arena_kill_switch_forces_unit_weights(monkeypatch):
    monkeypatch.setenv("TZ_ARENA_DEVICE", "0")
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    assert not a.device_enabled
    for i in range(4):
        a.stage(i, _row(i), weight=7)
    _dev, n, cumw, total = a.flush(jnp)
    assert total == n == 4  # unit weights despite weight=7 stages
    np.testing.assert_array_equal(
        np.asarray(cumw[:4]), np.arange(1, 5, dtype=np.uint32))
    # fold_heat is a no-op under the kill switch
    a.fold_heat(np.full(16, 5, np.uint32))
    assert a.heat_folds == 0


def test_arena_fold_heat_updates_weights():
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for i in range(3):
        a.stage(i, _row(i))
    heat = np.zeros(16, np.uint32)
    heat[:3] = [0, 3, 40]
    a.fold_heat(heat)
    assert a.heat_folds == 1
    # weight = 1 + min(heat, 7) for occupied rows
    np.testing.assert_array_equal(a.weights[:3], [1, 4, 8])
    _dev, _n, _cw, total = a.flush(jnp)
    assert total == 13


def test_arena_shard_rows_partition_is_exact():
    a = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for i in range(7):
        a.stage(i, _row(i))
    seen = np.concatenate([a.shard_rows(s, 4) for s in range(4)])
    np.testing.assert_array_equal(np.sort(seen), np.arange(7))
    rows = a.authority_rows(a.shard_rows(1, 4))
    np.testing.assert_array_equal(
        rows["val"], a.host["val"][a.shard_rows(1, 4)])


# -- durable codec --------------------------------------------------------


def test_pack_unpack_arena_roundtrip():
    progs = [b"prog-one", b"", b"a longer serialized program" * 9]
    weights = np.array([1, 3, 250], np.uint32)
    meta, blob = pack_arena(progs, weights, epoch=7)
    got_progs, got_w, got_epoch = unpack_arena(meta, blob)
    assert [bytes(p) for p in got_progs] == progs
    np.testing.assert_array_equal(got_w, weights)
    assert got_epoch == 7
    # meta must stay JSON-ish (ints and lists, jax-free recovery path)
    assert isinstance(meta["n"], int)
    assert all(isinstance(w, int) for w in meta["weights"])


def test_corpus_arena_warm_restart_single_reupload(test_target):
    """ISSUE 18 restart contract, on the real pipeline seam: a
    quiesced pipeline (worker never started — exactly the recovery
    window attach_durable restores in) re-enters a checkpoint section
    through restore_corpus_arena, and the first flush afterwards is
    ONE scatter covering every restored row.  No invalidate, no new
    epoch, no step compile, weights preserved, and the rebuilt device
    rows are byte-identical to the pre-crash authority (the encode
    path is deterministic)."""
    from syzkaller_tpu import telemetry
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = test_target
    pl1 = DevicePipeline(target, capacity=16, batch_size=8, seed=0,
                         dispatch_depth=1, rounds=1)
    pl2 = None
    try:
        added, i = 0, 0
        while added < 4 and i < 60:
            if pl1.add(generate_prog(target, RandGen(target, 5200 + i),
                                     4)):
                added += 1
            i += 1
        assert added == 4
        pl1.arena.set_weight(1, 6)
        pl1.arena.set_weight(3, 2)
        pl1._flush_pending()
        assert pl1.arena.uploads >= 1
        meta, blob = pl1.durable_corpus_arena()
        assert meta["n"] == 4 and meta["weights"][1] == 6

        # "restart": a fresh pipeline, worker not yet started
        pl2 = DevicePipeline(target, capacity=16, batch_size=8, seed=0,
                             dispatch_depth=1, rounds=1)
        with telemetry.assert_no_new_compiles(pl2._step._cache_size):
            pl2.restore_corpus_arena({"meta": meta, "blob": blob})
            assert pl2._n == 4              # every row deserialized
            assert int(pl2.arena.weights[1]) == 6
            assert int(pl2.arena.weights[3]) == 2
            assert len(pl2.arena._pending) == 4  # staged, not shipped
            assert pl2.arena.uploads == 0
            _corpus, n, _t, _e, _cumw, total = pl2._flush_pending()
        assert pl2.arena.uploads == 1       # ONE re-upload scatter
        assert n == 4 and not pl2.arena._pending
        assert int(total) == 1 + 6 + 1 + 2  # weighted cumw rebuilt
        assert pl2.arena.epoch == meta["epoch"]  # continued, not bumped
        for k, v in pl1.arena.host.items():
            np.testing.assert_array_equal(pl2.arena.host[k][:4], v[:4])
    finally:
        pl1.stop()
        if pl2 is not None:
            pl2.stop()


# -- truncation helpers ---------------------------------------------------


def test_truncation_keep_counts_ladder():
    assert truncation_keep_counts(8, 4) == [7, 4, 2, 1]
    assert truncation_keep_counts(2, 4) == [1]
    assert truncation_keep_counts(1, 4) == []
    assert truncation_keep_counts(9, 2) == [8, 4]
    for ks in (truncation_keep_counts(n, 4) for n in range(2, 20)):
        assert ks == sorted(ks, reverse=True)
        assert len(ks) == len(set(ks))


def test_truncated_alive_keeps_prefix_of_alive_calls():
    ca = np.array([True, False, True, True, False, True])
    np.testing.assert_array_equal(
        truncated_alive(ca, 2),
        [True, False, True, False, False, False])
    assert alive_mask_bits(truncated_alive(ca, 2)) == 0b101
    assert alive_mask_bits(ca) == 0b101101
    np.testing.assert_array_equal(truncated_alive(ca, 10), ca)


# -- splice: flat donor-bank parity ---------------------------------------


def test_splice_insert_group_flat_matches_staged_group(test_target):
    """The arena's base-independent splicer: donor words straight out
    of the shared DonorBankTable flat arrays with an in-flight rebase
    must be byte-identical to the per-base `build_donor_table` path
    across random alive masks, positions, and donors."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.prio import build_choice_table
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.ops.emit import (
        DonorBankTable,
        build_exec_template,
        splice_insert_group,
        splice_insert_group_flat,
    )
    from syzkaller_tpu.ops.insert import DonorBank
    from syzkaller_tpu.ops.tensor import (
        FlagTables,
        TensorConfig,
        encode_prog,
    )

    ct = build_choice_table(test_target)
    bank = DonorBank(test_target, ct, seed=5)
    assert len(bank.blocks) > 4
    dtab = DonorBankTable(bank.blocks)
    cfg = TensorConfig()
    flags = FlagTables.empty()
    rng = np.random.RandomState(91)
    tensors, i = [], 0
    while len(tensors) < 5 and i < 40:
        p = generate_prog(test_target, RandGen(test_target, 700 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    assert tensors
    checked = 0
    for t in tensors:
        et = build_exec_template(t)
        m = 24
        donors = rng.randint(0, len(bank.blocks), size=m)
        poses = rng.randint(0, et.ncalls + 3, size=m).astype(np.uint8)
        full = (1 << max(et.ncalls, 1)) - 1
        alive_bits = np.where(
            rng.rand(m) < 0.5, full,
            rng.randint(0, full + 1, size=m)).astype(np.uint64)
        want = splice_insert_group(et, alive_bits, donors, poses,
                                   bank.blocks)
        got = splice_insert_group_flat(et, alive_bits, donors, poses,
                                       dtab)
        assert len(want) == len(got) == m
        for k in range(m):
            if want[k] is None:
                assert got[k] is None
            else:
                assert got[k] is not None \
                    and bytes(got[k]) == bytes(want[k]), \
                    f"flat splice row {k} diverged"
            checked += 1
    assert checked >= 24


# -- distillation ---------------------------------------------------------


def _distill_fixture(target, n_rows=3, max_calls=16):
    """Templates with duplicated calls (so suffix truncation can
    genuinely cover), their exec templates, and an arena holding
    their rows."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.prog import clone_call
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.ops.emit import build_exec_template
    from syzkaller_tpu.ops.tensor import (
        FlagTables,
        TensorConfig,
        encode_prog,
    )

    cfg = TensorConfig(max_calls=max_calls)
    flags = FlagTables.empty()
    tmpl, ets = [], []
    i = 0
    while len(tmpl) < n_rows and i < n_rows * 20:
        p = generate_prog(target, RandGen(target, 5100 + i), 3)
        i += 1
        # Duplicate the calls: [a, b, c, a, b, c] — the second half
        # predicts no new sim edges, so the keep=n/2 suffix
        # truncation covers the original and a verdict fires.
        p.calls = p.calls + [clone_call(c) for c in p.calls]
        try:
            t = encode_prog(p, cfg, flags)
        except Exception:
            continue
        tmpl.append(t)
        ets.append(build_exec_template(t))
    assert tmpl, "no distill fixture programs tensorized"
    arena = CorpusArena(8, slab_bits=4, headroom_bytes=1 << 30)
    for k, t in enumerate(tmpl):
        arena.stage(k, t.arrays())
    return arena, tmpl, ets


def test_distill_device_matches_host_oracle(test_target):
    """The fused device bisection's cover verdicts equal the host
    sim_exec_host + digest_covers oracle bit for bit, and duplicated
    suffixes actually retire (a non-trivial win exists)."""
    arena, tmpl, ets = _distill_fixture(test_target)
    lane = DistillLane(max_calls=16, every=1, rows=4, max_cands=3)
    slots = lane.select_slots(tmpl, len(tmpl))
    assert slots, "no distillable rows in the fixture"
    table_rows, ncalls, alive, vals, keeps = build_distill_batch(
        arena, tmpl, ets, slots, 16, lane.max_cands)
    covers_dev, n_orig = lane.check(table_rows, ncalls, alive, vals)
    covers_host = distill_verdicts_host(table_rows, ncalls, alive,
                                        vals)
    np.testing.assert_array_equal(covers_dev, covers_host)
    assert covers_dev[:, 0].all(), "originals must cover themselves"
    wins = lane.choose(covers_dev, keeps)
    assert any(w is not None for w in wins), \
        "duplicated-call rows produced no truncation win"
    for r, m in enumerate(wins):
        if m is not None:
            assert keeps[r, m] < keeps[r, 0]
            assert covers_dev[r, m]


def test_distill_check_jit_compiles_once(test_target):
    """The lane's cover check is ONE jit at the pinned (R, M) shape:
    a second round at the same shape reuses the executable."""
    arena, tmpl, ets = _distill_fixture(test_target)
    lane = DistillLane(max_calls=16, every=1, rows=4, max_cands=3)
    slots = lane.select_slots(tmpl, len(tmpl))
    batch = build_distill_batch(arena, tmpl, ets, slots, 16,
                                lane.max_cands)
    lane.check(*batch[:4])
    sizes = lane._check._cache_size()
    lane.check(*batch[:4])
    assert lane._check._cache_size() == sizes
    assert lane.rounds == 2


def test_distill_lane_cadence_and_cursor():
    lane = DistillLane(max_calls=8, every=3, rows=2, max_cands=2)
    fires = [lane.tick() for _ in range(9)]
    assert fires == [False, False, True] * 3
    assert not DistillLane(max_calls=8, every=0).tick()

    class _T:
        def __init__(self, n_alive):
            self.call_alive = np.zeros(8, bool)
            self.call_alive[:n_alive] = True

    tmpl = [_T(4), _T(1), _T(3), _T(5), _T(2)]
    first = lane.select_slots(tmpl, len(tmpl))
    assert first == [0, 2]  # slot 1 has < 2 alive calls
    second = lane.select_slots(tmpl, len(tmpl))
    assert second == [3, 4]  # cursor advanced past the first window
    assert lane.select_slots([], 0) == []
