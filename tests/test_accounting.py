"""Accounting & SLO plane (ISSUE 14, telemetry/accounting.py +
telemetry/slo.py): the conservation invariant on the device-time
ledger's row-weighted splits, novelty-yield pricing through the
serving composer's credit rebalance, multi-window burn-rate alerting
with injected clocks (fast-fire / slow-hold / clear-hysteresis), the
self-diagnosing `slo_burn` flight incident, and the durable-state
round trips that make a warm restart neither zero the meter nor
false-clear a burning alert.

Host-only: ledger and engine are pure host code — private instances,
injected time, zero jit compiles.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry.accounting import (DEFAULT_KEY, MAX_KEYS,
                                                OVERFLOW_KEY,
                                                DeviceTimeLedger)
from syzkaller_tpu.telemetry.slo import SloEngine

# The acceptance invariant: per-key splits of every dimension sum to
# the metered total within this relative error.
CONSERVE_EPS = 1e-6


def _dim_sum(ledger, dim):
    return sum(v["device_ms"]
               for v in ledger.dimension_snapshot(dim).values())


# -- the ledger: conservation --------------------------------------------


def test_conservation_mixed_three_tenant_batches():
    """Mixed 3-tenant batches with awkward row ratios — including a
    tenant that disappears mid-stream (reaped lease: its rows stop
    arriving but its accumulated ms must stay on the books) — hold
    the conservation invariant on every dimension."""
    ledger = DeviceTimeLedger()
    # Ratios chosen to be unrepresentable in binary (1/3, 1/7, ...):
    # the naive proportional split would leak ulps every batch.
    for i in range(500):
        tenants = {"vmA": 1, "vmB": 3, "vmC": 7}
        if i >= 300:
            tenants.pop("vmC")  # reaped after batch 300
        ledger.note_batch(0.0037 + 1e-5 * i,
                          tenant_rows=tenants,
                          lane_rows={"exploration": 11,
                                     "candidate": 5, "smash": 1},
                          shard_rows={str(i % 3): 1,
                                      str((i + 1) % 3): 1})
    assert ledger.batches == 500
    assert ledger.conservation_error() <= CONSERVE_EPS
    for dim in ("tenant", "lane", "shard"):
        assert _dim_sum(ledger, dim) == \
            pytest.approx(ledger.total_ms, rel=CONSERVE_EPS)
    # The reaped tenant's cumulative ms survives its disappearance.
    snap = ledger.dimension_snapshot("tenant")
    assert snap["vmC"]["device_ms"] > 0
    # Largest-remainder exactness: the two-key split is bit-exact.
    two = DeviceTimeLedger()
    two.note_batch(0.001, tenant_rows={"a": 1, "b": 2})
    assert _dim_sum(two, "tenant") == two.total_ms  # ==, not approx


def test_unattributed_batches_book_to_defaults_and_overflow_caps():
    ledger = DeviceTimeLedger()
    ledger.note_batch(0.002)
    snap = ledger.snapshot()
    for dim in ("tenant", "lane", "shard"):
        assert snap[dim][DEFAULT_KEY[dim]]["device_ms"] == \
            pytest.approx(2.0)
    # Garbage in, metering out: non-positive batches are ignored.
    ledger.note_batch(0.0)
    ledger.note_batch(-1.0)
    assert ledger.batches == 1
    # A label leak folds into "overflow" past MAX_KEYS but still
    # conserves (the cap bounds /metrics cardinality, not the books).
    for i in range(MAX_KEYS + 20):
        ledger.note_batch(0.001, tenant_rows={f"leak{i}": 1})
    tsnap = ledger.dimension_snapshot("tenant")
    assert len(tsnap) <= MAX_KEYS + 1
    assert tsnap[OVERFLOW_KEY]["device_ms"] > 0
    assert ledger.conservation_error() <= CONSERVE_EPS


def test_yield_ewma_joins_novelty_to_device_time():
    """`note_novel` prices in at the key's NEXT device-time accrual:
    the first observation sets the EWMA (profiler idiom), later
    zero-novelty accruals decay it toward zero."""
    ledger = DeviceTimeLedger()
    ledger.note_novel("tenant", "a", 7)
    ledger.note_batch(0.020, tenant_rows={"a": 1})  # 7 / 0.02s
    assert ledger.yield_ewmas("tenant")["a"] == pytest.approx(350.0)
    before = ledger.yield_ewmas("tenant")["a"]
    for _ in range(10):
        ledger.note_batch(0.020, tenant_rows={"a": 1})
    after = ledger.yield_ewmas("tenant")["a"]
    assert 0.0 < after < before * 0.2
    # Shards carry no novelty join (a chip discovers nothing).
    ledger.note_novel("shard", "0", 5)
    assert "shard" not in ledger.snapshot()["tenant"]
    assert ledger.dimension_snapshot("shard")["0"]["novel"] == 0


def test_top_consumers_ranked_table():
    ledger = DeviceTimeLedger()
    ledger.note_novel("tenant", "big", 10)
    ledger.note_batch(0.010, tenant_rows={"big": 9, "small": 1})
    top = ledger.top_consumers(n=2)
    assert top["total_device_ms"] == pytest.approx(10.0)
    assert top["tenant"][0]["key"] == "big"
    assert top["tenant"][0]["share"] == pytest.approx(0.9)
    assert top["tenant"][0]["yield"] > 0


# -- yield pricing through the composer ----------------------------------


def _mk_composer(clock):
    from syzkaller_tpu.serve import (BatchComposer, ServePlane,
                                     TenantPlanes)
    broker = ServePlane(lease_s=3600.0, queue_cap=1000, max_tenants=8,
                        clock=clock)
    comp = BatchComposer(broker, TenantPlanes(bits=12), None,
                         batch_rows=100, credit_floor=0.05,
                         credit_decay=0.5, rebalance_s=0.0,
                         stall_window_s=3600.0, clock=clock)
    return broker, comp


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_serve_price_yield_zero_yield_tenant_lands_on_floor(
        monkeypatch):
    """ISSUE 14 acceptance: under TZ_SERVE_PRICE=yield a scripted
    zero-yield tenant's credit decays to EXACTLY the credit floor
    while the productive tenant takes the rest — even though the
    zero-yield tenant is healthy by the novelty-delivery latch."""
    monkeypatch.setenv("TZ_SERVE_PRICE", "yield")
    ledger = DeviceTimeLedger()
    monkeypatch.setattr(telemetry, "ACCOUNTING", ledger)
    broker, comp = _mk_composer(_Clock())
    assert comp.price == "yield"
    for name in ("hot", "idle"):
        broker.Connect({"name": name})
        # Keep both tenants delivery-healthy: yield pricing, not the
        # plateau latch, must be what floors the idle one.
        broker.tenants[name].last_novel_ts = 1000.0
        broker.tenants[name].novelty_ewma = 5.0
    # The ledger's story: both burned device time, only hot yielded.
    ledger.note_novel("tenant", "hot", 50)
    ledger.note_batch(0.010, tenant_rows={"hot": 1, "idle": 1})
    credits = comp.rebalance_credits(force=True)
    assert credits["idle"] == 0.05  # exactly the floor, not approx
    assert credits["hot"] == pytest.approx(0.95)


def test_serve_price_default_novelty_ignores_ledger(monkeypatch):
    """Default pricing is bit-exact pre-accounting behaviour: the
    credit weights come from the delivery-novelty EWMAs and a skewed
    ledger moves nothing."""
    ledger = DeviceTimeLedger()
    monkeypatch.setattr(telemetry, "ACCOUNTING", ledger)
    broker, comp = _mk_composer(_Clock())
    assert comp.price == "novelty"
    for name, ewma in (("a", 3.0), ("b", 1.0)):
        broker.Connect({"name": name})
        broker.tenants[name].last_novel_ts = 1000.0
        broker.tenants[name].novelty_ewma = ewma
    # Ledger says "b" is the only yielder; novelty pricing ignores it.
    ledger.note_novel("tenant", "b", 100)
    ledger.note_batch(0.010, tenant_rows={"b": 1})
    credits = comp.rebalance_credits(force=True)
    assert credits["a"] == pytest.approx(0.05 + 0.9 * 0.75)
    assert credits["b"] == pytest.approx(0.05 + 0.9 * 0.25)


# -- the SLO engine: multi-window burn -----------------------------------


UTIL_OBJ = {"name": "util", "kind": "floor", "env": "TZ_SLO_UTIL_FLOOR",
            "default": 1.0, "lo": 0.0, "hi": 10.0, "budget": 0.1,
            "metric": "tz_acct_device_ms_total", "help": "test floor"}


def _mk_engine(value, ledger=None, fast_s=60.0, slow_s=300.0,
               burn=1.0):
    clk = [10_000.0]
    eng = SloEngine(time_fn=lambda: clk[0], fast_s=fast_s,
                    slow_s=slow_s, burn=burn, interval_s=0.0,
                    table=[UTIL_OBJ],
                    value_overrides={"util": lambda: value[0]},
                    ledger=ledger or DeviceTimeLedger())
    return clk, eng


def _events_since(mark):
    return [(n, d) for _ts, n, d in telemetry.REGISTRY.events()[mark:]]


def test_burn_fires_only_after_slow_window_confirms(tmp_path):
    """A breach must burn BOTH windows: the fast window alone (a
    blip, or a freshly started engine with 60s of history) never
    pages; once the slow window spans and agrees, the alert fires
    ONCE with a `slo.burn` event and a `slo_burn` flight incident
    carrying the top-consumers table."""
    ledger = DeviceTimeLedger()
    ledger.note_novel("tenant", "culprit", 3)
    ledger.note_batch(0.050, tenant_rows={"culprit": 9, "minor": 1})
    value = [0.2]  # floor target 1.0 -> every sample breaches
    clk, eng = _mk_engine(value, ledger=ledger)
    telemetry.FLIGHT.set_dir(str(tmp_path))
    saved = telemetry.FLIGHT.min_interval_s
    telemetry.FLIGHT.min_interval_s = 0.0
    mark = len(telemetry.REGISTRY.events())
    try:
        # 20 ticks x 5s = 95s of all-bad history: the fast window
        # (60s) is saturated, the slow window (300s) can't vote yet.
        for _ in range(20):
            eng.tick()
            clk[0] += 5.0
        st = eng.snapshot()["objectives"][0]
        assert st["fast_burn"] >= 1.0 and st["slow_burn"] == 0.0
        assert not st["burning"]
        assert not any(n == "slo.burn" for n, _ in _events_since(mark))
        # Keep breaching past the slow window: exactly one fire.
        for _ in range(45):
            eng.tick()
            clk[0] += 5.0
        st = eng.snapshot()["objectives"][0]
        assert st["burning"] and st["slow_burn"] >= 1.0
        burns = [d for n, d in _events_since(mark) if n == "slo.burn"]
        assert len(burns) == 1 and "util" in burns[0]
        assert telemetry.REGISTRY.snapshot()["gauges"][
            'tz_slo_burn{slo="util"}'] == 1
        # The incident is self-diagnosing: the attached ledger table
        # names who was eating the device when the objective burned.
        dumps = glob.glob(os.path.join(str(tmp_path),
                                       "tz_flight_slo_burn_*.json"))
        assert len(dumps) == 1
        with open(dumps[0]) as f:
            incident = json.load(f)
        assert incident["slo"]["name"] == "util"
        consumers = incident["top_consumers"]
        assert consumers["tenant"][0]["key"] == "culprit"
        assert consumers["tenant"][0]["share"] == pytest.approx(0.9)
    finally:
        telemetry.FLIGHT.set_dir(None)
        telemetry.FLIGHT.min_interval_s = saved


def test_burn_clears_with_hysteresis():
    """Recovery flaps are absorbed: a latched burn survives the first
    good samples and clears only when the fast-window burn falls
    under half the firing threshold — then emits `slo.clear`."""
    value = [0.2]
    clk, eng = _mk_engine(value)
    for _ in range(65):  # latch it
        eng.tick()
        clk[0] += 5.0
    assert eng.snapshot()["objectives"][0]["burning"]
    mark = len(telemetry.REGISTRY.events())
    value[0] = 5.0  # healthy again
    for _ in range(3):
        eng.tick()
        clk[0] += 5.0
    st = eng.snapshot()["objectives"][0]
    assert st["burning"]  # hysteresis holds through early recovery
    assert not any(n == "slo.clear" for n, _ in _events_since(mark))
    for _ in range(15):  # flush the fast window with good samples
        eng.tick()
        clk[0] += 5.0
    st = eng.snapshot()["objectives"][0]
    assert not st["burning"] and st["fast_burn"] <= 0.5
    assert any(n == "slo.clear" and "util" in d
               for n, d in _events_since(mark))
    assert telemetry.REGISTRY.snapshot()["gauges"][
        'tz_slo_burn{slo="util"}'] == 0


def test_interval_rate_limit_and_tick_never_raises():
    clk = [10_000.0]
    eng = SloEngine(time_fn=lambda: clk[0], fast_s=60.0, slow_s=300.0,
                    burn=1.0, interval_s=5.0, table=[UTIL_OBJ],
                    value_overrides={"util": lambda: 2.0},
                    ledger=DeviceTimeLedger())
    assert eng.tick() is True
    clk[0] += 1.0
    assert eng.tick() is False  # inside the interval: no sample
    clk[0] += 5.0
    assert eng.tick() is True
    # A broken override must not break the flush path hosting us.
    def boom():
        raise RuntimeError("scripted")
    bad = SloEngine(time_fn=lambda: clk[0], interval_s=0.0,
                    table=[UTIL_OBJ],
                    value_overrides={"util": boom},
                    ledger=DeviceTimeLedger())
    assert bad.tick() is False


# -- durable round trips -------------------------------------------------


def test_ledger_state_round_trip_preserves_meter():
    ledger = DeviceTimeLedger()
    ledger.note_novel("tenant", "a", 12)
    for _ in range(20):
        ledger.note_batch(0.003, tenant_rows={"a": 2, "b": 1},
                          lane_rows={"candidate": 1})
    state = json.loads(json.dumps(ledger.export_state()))  # WAL trip
    warm = DeviceTimeLedger()
    warm.restore_state(state)
    assert warm.total_ms == pytest.approx(ledger.total_ms)
    assert warm.batches == ledger.batches
    assert warm.conservation_error() <= CONSERVE_EPS
    assert warm.dimension_snapshot("tenant") == \
        ledger.dimension_snapshot("tenant")
    assert warm.yield_ewmas("tenant")["a"] == \
        pytest.approx(ledger.yield_ewmas("tenant")["a"])
    # The meter keeps climbing from where it left off, not from zero.
    warm.note_batch(0.001, tenant_rows={"a": 1})
    assert warm.total_ms == pytest.approx(ledger.total_ms + 1.0)


def test_slo_restore_relatches_silently():
    """Warm restart must not flap the alert: a burning objective
    comes back latched (gauge up, ring intact) with NO `slo.burn` or
    `slo.clear` event fired by recovery itself."""
    value = [0.2]
    clk, eng = _mk_engine(value)
    for _ in range(65):
        eng.tick()
        clk[0] += 5.0
    assert eng.snapshot()["objectives"][0]["burning"]
    state = json.loads(json.dumps(eng.export_state()))
    mark = len(telemetry.REGISTRY.events())
    clk2, warm = _mk_engine(value)
    clk2[0] = clk[0]
    warm.restore_state(state)
    st = warm.snapshot()["objectives"][0]
    assert st["burning"] and st["samples"] > 0
    assert _events_since(mark) == []  # silent re-latch
    assert telemetry.REGISTRY.snapshot()["gauges"][
        'tz_slo_burn{slo="util"}'] == 1
    # The restored ring is live history: continued breaches keep the
    # latch without re-firing, recovery clears it normally.
    warm.tick()
    assert not any(n == "slo.burn" for n, _ in _events_since(mark))
    value[0] = 5.0
    for _ in range(15):
        clk2[0] += 5.0
        warm.tick()
    assert not warm.snapshot()["objectives"][0]["burning"]
