"""On-device simulated executor (ISSUE 15, syzkaller_tpu/sim): the
host model's deterministic edge map, the exec-stream -> SimTable
lowering, randomized bit-exactness of the batched device kernel
(vmap and Pallas-interpret) against the ipc/sim host oracle, the
speculation plane's suppress/re-admit semantics, and the VM-free
load generator's determinism.

The device tests run at their own tiny shapes (C<=6, B<=16) so the
per-file compile cost stays in the low seconds; the warm-rig
integration (prescore fused into the real drain, fault seam, compile
guard) lives in test_health_faults.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from syzkaller_tpu.ipc.sim import (
    MASK64,
    SIM_EDGE_SLOTS,
    SIM_MAX_ARGS,
    SIM_SLOT_COMBO_MIXED,
    SIM_SLOT_CRASH_ARM,
    SIM_SLOT_ENTRY,
    SIM_SLOT_HANDLE0,
    SIM_SLOT_MAGIC0,
    SimKernelModel,
    arg_magic,
    call_hash,
    crash_magics,
    is_crashy,
    is_lockless,
    value_bucket,
)
from syzkaller_tpu.models.encodingexec import (
    EXEC_ARG_CONST,
    EXEC_ARG_DATA,
    EXEC_ARG_RESULT,
    EXEC_INSTR_EOF,
    EXEC_NO_COPYOUT,
)
from syzkaller_tpu.sim.table import (
    MODE_CONST,
    MODE_PROC,
    MODE_RESULT,
    MODE_SLOT,
    MODE_ZERO,
    SIM_MAX_COPYOUT,
    STATUS_CRASHED,
    STATUS_RAN,
    SimTable,
    build_sim_table_from_words,
    sim_exec_host,
)


def _find(pred, lo=0, hi=4096):
    for c in range(lo, hi):
        if pred(c):
            return c
    raise AssertionError("no call id matched the predicate")


# -- host model (pure python, no jax) -------------------------------------


def test_value_bucket_matches_reference_loop():
    """The branch-free log2 used on device must agree with a literal
    C-style loop over the interesting boundary values."""
    def ref(v):
        v &= MASK64
        log2 = 0
        while (v >> (log2 + 1)) and log2 < 63:
            log2 += 1
        return (log2 << 4) | (v & 0xF)

    samples = [0, 1, 2, 3, 15, 16, 17, 255, 256, 0x1000, 0xFFFF,
               1 << 31, (1 << 32) - 1, 1 << 32, 1 << 63, MASK64]
    for v in samples:
        assert value_bucket(v) == ref(v), hex(v)


def test_host_model_magic_and_combo_edges():
    cid = _find(lambda c: not is_lockless(c) and not is_crashy(c)
                and (call_hash(c) & 3) == 1)  # a ctor
    model = SimKernelModel(pid=0)
    r = model.exec(cid, [7])
    assert r.valid[SIM_SLOT_ENTRY] and not r.crashed
    handle = r.ret
    assert handle == 0x1000  # first ctor handle, pid 0
    # A later call passing the live handle + a magic comparand lights
    # the handle edge, BOTH magic-pair slots, and the mixed combo.
    cid2 = _find(lambda c: not is_lockless(c) and not is_crashy(c)
                 and (call_hash(c) & 3) not in (1, 2))
    r2 = model.exec(cid2, [handle, arg_magic(cid2, 1)])
    assert r2.valid[SIM_SLOT_HANDLE0 + 0]
    assert r2.valid[SIM_SLOT_MAGIC0 + 2] and r2.valid[SIM_SLOT_MAGIC0 + 3]
    assert r2.valid[SIM_SLOT_COMBO_MIXED]
    assert r2.errno == 0


def test_host_model_ebadf_and_crash_sequencing():
    # A handle-wanting call with no valid handle fails EBADF.
    cid = _find(lambda c: not is_lockless(c)
                and (call_hash(c) & 3) == 2)
    model = SimKernelModel(pid=0)
    r = model.exec(cid, [0xDEAD])
    assert r.errno == 9 and not r.crashed
    # Two-stage crash: arm emits ONLY the arm edge extra; the full
    # combination reports no surviving edges at all (the executor
    # _exits before copyout).
    crashy = _find(lambda c: is_crashy(c) and not is_lockless(c))
    c0, c1 = crash_magics(crashy)
    armed = model.exec(crashy, [c0, 0])
    assert armed.valid[SIM_SLOT_CRASH_ARM] and not armed.crashed
    crashed = model.exec(crashy, [c0, c1])
    assert crashed.crashed and not any(crashed.valid)


def test_host_model_lockless_races_entry_only():
    cid = _find(lambda c: is_lockless(c))
    model = SimKernelModel(pid=0)
    r = model.exec(cid, [arg_magic(cid, 0)])
    assert r.valid[SIM_SLOT_ENTRY]
    assert sum(r.valid) == 1, "lockless calls emit the entry edge only"
    assert not r.crashed and r.errno == 0


# -- exec-stream lowering -------------------------------------------------


def _call_words(call_id, args, copyout=EXEC_NO_COPYOUT):
    """One serialized call with 8-byte little-endian const args."""
    w = [call_id & 0xFFFFFFFF, copyout, len(args)]
    for a in args:
        w += [EXEC_ARG_CONST, 8, a & MASK64]
    return w


def test_lowering_modes_and_limits():
    words = []
    words += _call_words(3, [5, 7], copyout=1)
    # call 1: a DATA arg (reads as 0) + a RESULT arg chained to the
    # ret-backed copyout index 1 with div=2, add=3, default=99.
    words += [4, EXEC_NO_COPYOUT, 2,
              EXEC_ARG_DATA, 8, 0,
              EXEC_ARG_RESULT, 8, 1, 2, 3, 99]
    words.append(EXEC_INSTR_EOF)
    t = build_sim_table_from_words(np.asarray(words, np.uint64),
                                   max_calls=4)
    assert t.ncalls == 2
    assert t.call_id[:2].tolist() == [3, 4]
    assert t.ret_idx[0] == 1 and t.ret_idx[1] == -1
    assert t.amode[0, 0] == MODE_CONST and t.aconst[0, 0] == 5
    assert t.amode[1, 0] == MODE_ZERO
    assert t.amode[1, 1] == MODE_RESULT
    assert t.aslot[1, 1] == 1  # chained to call 0's copyout
    assert (t.ameta[1, 1], t.aaux[1, 1], t.aconst[1, 1]) == (2, 3, 99)
    # An out-of-window copyout index degrades to never-done on both
    # sides of the parity contract: ret_idx stays -1.
    w2 = _call_words(3, [1], copyout=SIM_MAX_COPYOUT + 5) \
        + [EXEC_INSTR_EOF]
    t2 = build_sim_table_from_words(np.asarray(w2, np.uint64))
    assert t2.ret_idx[0] == -1
    # The executor failf's >8-arg calls; the lowering refuses too.
    w3 = _call_words(3, list(range(9))) + [EXEC_INSTR_EOF]
    with pytest.raises(ValueError):
        build_sim_table_from_words(np.asarray(w3, np.uint64))


def test_sim_exec_host_sequencing_and_copyout_chain():
    ctor = _find(lambda c: not is_lockless(c) and not is_crashy(c)
                 and (call_hash(c) & 3) == 1)
    wants = _find(lambda c: not is_lockless(c)
                  and (call_hash(c) & 3) == 2)
    crashy = _find(lambda c: is_crashy(c) and not is_lockless(c))
    c0, c1 = crash_magics(crashy)
    words = []
    words += _call_words(ctor, [0], copyout=0)  # ret 0x1000 -> idx 0
    # RESULT arg: covals[0] // 0x10 + 0 == 0x100... then the wants-
    # handle call gets the RAW handle via div=1.
    words += [wants & 0xFFFFFFFF, EXEC_NO_COPYOUT, 1,
              EXEC_ARG_RESULT, 8, 0, 1, 0, 99]
    words += _call_words(crashy, [c0, c1])
    words += _call_words(3, [1])  # never runs: the crash _exits
    words.append(EXEC_INSTR_EOF)
    t = build_sim_table_from_words(np.asarray(words, np.uint64),
                                   max_calls=6)
    edges, valid, ret, errno, status = sim_exec_host(t)
    assert status[:4].tolist() == [STATUS_RAN, STATUS_RAN,
                                   STATUS_CRASHED, 0]
    assert ret[0] == 0x1000
    # The chained handle satisfied the wants-handle call: no EBADF,
    # and the handle edge lit for arg 0.
    assert errno[1] == 0
    assert valid[1, SIM_SLOT_HANDLE0 + 0]
    assert not valid[2].any(), "crashed call leaked edges"
    assert not valid[3].any(), "a call after the crash ran"
    # Dead calls are skipped and their copyouts never happen: killing
    # the ctor makes the chained call read the default -> EBADF.
    _e, v2, _r, errno2, status2 = sim_exec_host(t, alive_bits=~1)
    assert status2[0] == 0 and errno2[1] == 9
    assert not v2[1, SIM_SLOT_HANDLE0 + 0]


# -- device kernel parity (vmap + pallas interpret) -----------------------


def _random_word_program(rng, max_ncalls=4):
    """A random serialized exec stream biased toward the interesting
    regimes: magic comparands, two-stage crash arms, ret-backed
    copyout chains, data args."""
    words = []
    ncalls = 1 + rng.randint(max_ncalls)
    for c in range(ncalls):
        call_id = int(rng.randint(0, 64))
        na = int(rng.randint(0, 5))
        args = []
        for i in range(na):
            k = rng.randint(4)
            if k == 0:
                args.append(int(arg_magic(call_id, i)))
            elif k == 1 and is_crashy(call_id) and i < 2:
                args.append(int(crash_magics(call_id)[i]))
            elif k == 2:
                args.append(0x1000)  # the first ctor handle value
            else:
                args.append(int(rng.randint(0, 1 << 30)))
        co = int(rng.randint(4)) if rng.randint(3) == 0 \
            else EXEC_NO_COPYOUT
        if rng.randint(4) == 0 and na > 0:
            # Replace the last const with a RESULT ref (random chain).
            w = [call_id & 0xFFFFFFFF, co, na]
            for a in args[:-1]:
                w += [EXEC_ARG_CONST, 8, a & MASK64]
            w += [EXEC_ARG_RESULT, 8, int(rng.randint(4)),
                  int(rng.randint(3)), int(rng.randint(16)),
                  int(rng.randint(1 << 16))]
            words += w
        else:
            words += _call_words(call_id, args, copyout=co)
        if rng.randint(5) == 0:
            words += [int(rng.randint(0, 64)), EXEC_NO_COPYOUT, 1,
                      EXEC_ARG_DATA, 16, 0, 0]  # 16-byte data arg
    words.append(EXEC_INSTR_EOF)
    return np.asarray(words, np.uint64)


def _stack_tables(tables):
    import jax.numpy as jnp

    from syzkaller_tpu.sim.kernel import TABLE_FIELDS

    rows = {k: jnp.asarray(np.stack([getattr(t, k) for t in tables]))
            for k in TABLE_FIELDS}
    ncalls = jnp.asarray([t.ncalls for t in tables], jnp.int32)
    return rows, ncalls


def _assert_parity(tables, alive, vals, backend, pid=0):
    import jax.numpy as jnp

    from syzkaller_tpu.sim.kernel import sim_exec_batch

    rows, ncalls = _stack_tables(tables)
    out = sim_exec_batch(rows, ncalls, jnp.asarray(alive, jnp.uint64),
                         jnp.asarray(vals), backend, interpret=True,
                         pid=pid)
    edges_d, valid_d, ret_d, errno_d, status_d = \
        [np.asarray(o) for o in out]
    for b, t in enumerate(tables):
        eh, vh, rh, nh, sh = sim_exec_host(
            t, vals=vals[b], alive_bits=int(alive[b]), pid=pid)
        assert np.array_equal(valid_d[b], vh), (backend, b)
        assert np.array_equal(edges_d[b] * valid_d[b], eh * vh), \
            (backend, b)
        assert np.array_equal(ret_d[b], rh), (backend, b)
        assert np.array_equal(errno_d[b], nh), (backend, b)
        assert np.array_equal(status_d[b], sh), (backend, b)


def test_vmap_parity_randomized_word_streams():
    pytest.importorskip("jax")
    rng = np.random.RandomState(1215)
    B, C, S = 16, 6, 4
    tables = [build_sim_table_from_words(_random_word_program(rng),
                                         max_calls=C)
              for _ in range(B)]
    alive = np.where(rng.randint(4, size=B) == 0,
                     rng.randint(1, 16, size=B).astype(np.uint64),
                     np.uint64(MASK64)).astype(np.uint64)
    vals = np.zeros((B, S), np.uint64)
    _assert_parity(tables, alive, vals, "vmap")


def test_vmap_parity_slot_proc_result_modes():
    """Direct SimTable construction drives the mutable-slot paths the
    raw-stream lowering cannot reach (MODE_SLOT/MODE_PROC gather from
    the mutant's value vector) under a nonzero pid, so the pid-stride
    + big-endian const transform is pinned against the host oracle."""
    pytest.importorskip("jax")
    rng = np.random.RandomState(77)
    B, C, S, A = 12, 4, 6, SIM_MAX_ARGS
    pid = 3
    tables = []
    vals = np.zeros((B, S), np.uint64)
    for b in range(B):
        nc = 1 + rng.randint(C)
        call_id = rng.randint(0, 64, size=C).astype(np.int32)
        nargs = rng.randint(0, 5, size=C).astype(np.int32)
        nargs[nc:] = 0
        ret_idx = np.where(rng.randint(3, size=C) == 0,
                           rng.randint(0, 4, size=C), -1) \
            .astype(np.int32)
        amode = np.zeros((C, A), np.int32)
        aslot = np.full((C, A), -1, np.int32)
        aconst = np.zeros((C, A), np.uint64)
        ameta = np.zeros((C, A), np.uint64)
        aaux = np.zeros((C, A), np.uint64)
        for c in range(nc):
            for i in range(int(nargs[c])):
                mode = int(rng.choice(
                    [MODE_CONST, MODE_SLOT, MODE_PROC, MODE_RESULT]))
                amode[c, i] = mode
                size = 1 + rng.randint(8)
                be = rng.randint(2)
                stride = rng.randint(4)
                meta = size | (be << 8) | (stride << 32)
                if mode == MODE_CONST:
                    aconst[c, i] = rng.randint(1 << 30)
                    ameta[c, i] = meta
                elif mode == MODE_SLOT:
                    aslot[c, i] = rng.randint(S)
                    ameta[c, i] = meta
                elif mode == MODE_PROC:
                    aslot[c, i] = rng.randint(S)
                    aconst[c, i] = rng.randint(1 << 20)
                    ameta[c, i] = meta
                    aaux[c, i] = 8  # default proc meta: size 8
                else:
                    aslot[c, i] = rng.randint(-1, 4)
                    aconst[c, i] = rng.randint(1 << 16)
                    ameta[c, i] = rng.randint(3)  # op_div
                    aaux[c, i] = rng.randint(16)  # op_add
        tables.append(SimTable(
            ncalls=nc, call_id=call_id, nargs=nargs, ret_idx=ret_idx,
            amode=amode, aslot=aslot, aconst=aconst, ameta=ameta,
            aaux=aaux))
        for s in range(S):
            # Mix concrete slot values with the PROC 0xFF..F default.
            vals[b, s] = MASK64 if rng.randint(3) == 0 \
                else rng.randint(1 << 30)
    alive = np.full(B, MASK64, np.uint64)
    _assert_parity(tables, alive, vals, "vmap", pid=pid)


def test_pallas_interpret_parity():
    """The grid-over-batch path (the TPU kernel, interpreted on CPU)
    is bit-exact with the host oracle too — the same guarantee the
    mutation core pins for its Pallas twin."""
    pytest.importorskip("jax")
    rng = np.random.RandomState(9)
    B, C, S = 4, 4, 4
    tables = [build_sim_table_from_words(_random_word_program(rng),
                                         max_calls=C)
              for _ in range(B)]
    alive = np.full(B, MASK64, np.uint64)
    vals = np.zeros((B, S), np.uint64)
    _assert_parity(tables, alive, vals, "pallas")


# -- the speculation plane ------------------------------------------------


def test_predict_and_mark_suppresses_repeats():
    jnp = pytest.importorskip("jax.numpy")

    from syzkaller_tpu.sim.kernel import predict_and_mark

    bits = 10
    plane = jnp.zeros(1 << bits, jnp.uint8)
    rng = np.random.RandomState(5)
    edges = rng.randint(1, 1 << 32, size=(3, 2, SIM_EDGE_SLOTS),
                        dtype=np.uint64).astype(np.uint32)
    valid = np.zeros((3, 2, SIM_EDGE_SLOTS), bool)
    valid[:, :, :4] = True
    pred, plane = predict_and_mark(jnp.asarray(edges),
                                   jnp.asarray(valid), plane, bits)
    assert np.asarray(pred).all(), "fresh edges must predict novel"
    # The same batch again: every fold is marked now.
    pred2, plane = predict_and_mark(jnp.asarray(edges),
                                    jnp.asarray(valid), plane, bits)
    assert not np.asarray(pred2).any(), "repeats must suppress"
    # A row with zero valid edges can never claim novelty.
    pred3, _ = predict_and_mark(jnp.asarray(edges),
                                jnp.asarray(np.zeros_like(valid)),
                                jnp.zeros(1 << bits, jnp.uint8), bits)
    assert not np.asarray(pred3).any()


def test_prescore_epoch_decay_readmits(monkeypatch):
    """The no-starvation bound: the speculation plane decays by full
    reset every TZ_SIM_EPOCH_BATCHES commits, so a suppressed fold is
    admissible again at most one epoch later; demotion/repromotion
    bookkeeping rides the same commit path."""
    pytest.importorskip("jax")
    monkeypatch.setenv("TZ_SIM_EPOCH_BATCHES", "2")
    monkeypatch.setenv("TZ_SIM_PLANE_BITS", "10")

    from syzkaller_tpu.sim.prescore import SimPrescore

    sp = SimPrescore(capacity=4, max_calls=4, backend="vmap")
    assert sp.epoch_batches == 2 and sp.plane_bits == 10
    plane = sp.ensure_plane()
    marked = plane.at[7].set(1)
    sp.commit(marked)
    assert sp._plane is marked and sp.epochs == 0
    sp.commit(sp._plane)  # commit #2: the epoch boundary
    assert sp.epochs == 1
    assert sp._plane is None, "decay must drop the marked plane"
    assert int(sp.ensure_plane()[7]) == 0, "re-admitted fold"
    # Failure demotes once; the next successful commit re-promotes.
    sp.note_failure(RuntimeError("scripted"))
    sp.note_failure(RuntimeError("scripted"))
    assert sp.demoted() and sp.demotions == 1
    sp.commit(sp.ensure_plane())
    assert not sp.demoted() and sp.repromotions == 1
    snap = sp.snapshot()
    assert snap["epochs"] == 1 and snap["batches"] == 3
    assert snap["breaker"]["state"] == "closed"


def test_plane_bits_clamped(monkeypatch):
    from syzkaller_tpu.sim.prescore import resolve_sim_plane_bits

    monkeypatch.setenv("TZ_SIM_PLANE_BITS", "40")
    assert resolve_sim_plane_bits() == 28
    monkeypatch.setenv("TZ_SIM_PLANE_BITS", "2")
    assert resolve_sim_plane_bits() == 10
    monkeypatch.delenv("TZ_SIM_PLANE_BITS")
    assert resolve_sim_plane_bits() == 20


# -- the VM-free load generator -------------------------------------------


def test_loadgen_deterministic_and_realistic_mix():
    from syzkaller_tpu.sim.loadgen import SimLoadGenerator

    g1 = SimLoadGenerator(seed=7, repeat_every=4)
    g2 = SimLoadGenerator(seed=7, repeat_every=4)
    r1, p1 = g1.drain(96)
    r2, p2 = g2.drain(96)
    assert np.array_equal(r1, r2), "same seed must replay bit-exactly"
    assert p1 == p2
    assert r1.shape == (96, g1.spec.row_bytes) and r1.dtype == np.uint8
    assert len(p1) == 96
    # A different seed diverges.
    r3, _ = SimLoadGenerator(seed=8, repeat_every=4).drain(96)
    assert not np.array_equal(r1, r3)
    # Every repeat_every-th row replays a recent row byte-identically
    # (the composer's staleness source); the rest are unique.
    uniq = len({row.tobytes() for row in r1})
    assert uniq <= 96 - 96 // 4 + 1
    mix = g1.verdict_mix()
    assert mix["repeat_frac"] == pytest.approx(0.25)
    # The verdict mix is realistic, not degenerate: crashes, EBADF
    # and lockless races all occur, none dominate.
    assert 0 < mix["crash_frac"] < 0.5
    assert 0 < mix["ebadf_frac"] < 0.8
    assert g1.stats["programs"] == 72  # 96 minus the replays
    assert g1.stats["magic_hits"] > 0
    assert g1.stats["handle_hits"] > 0
