"""Watcher decision logic (tools/bench_watch): the journal filter and
the A/B artifact eligibility gate.

The r4 advisor finding was precisely a filter bug here (CPU-pinned
runs satisfying --want); these pin both filters so the watcher's
done-conditions can only be met by accelerator measurements."""

from __future__ import annotations

import json

from syzkaller_tpu.tools import bench_watch as bw


def _write_journal(tmp_path, entries):
    with open(tmp_path / "BENCH_HISTORY.jsonl", "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")


def test_flagship_entries_filters(tmp_path, monkeypatch):
    monkeypatch.setattr(bw, "REPO", str(tmp_path))
    flag = {"metric": "exec_ready_mutants_per_sec_per_chip", "value": 9000}
    _write_journal(tmp_path, [
        flag,                                          # counts
        {**flag, "platform": "cpu"},                   # pinned: no
        {**flag, "harness_artifact": True},            # artifact: no
        {**flag, "reconstructed": True},               # reconstructed: no
        {**flag, "value": 0},                          # zero: no
        {"metric": "new_edges_sim_kernel_ab", "value": 5},  # wrong metric
        flag,                                          # counts
    ])
    assert bw.flagship_entries() == 2


def test_flagship_entries_missing_journal(tmp_path, monkeypatch):
    monkeypatch.setattr(bw, "REPO", str(tmp_path))
    assert bw.flagship_entries() == 0


def test_ab_eligibility_gate():
    good = {"metric": "new_edges_sim_kernel_ab",
            "engine_on": {"edges": 10}, "engine_off": {"edges": 9}}
    assert bw.ab_result_eligible(good)
    assert not bw.ab_result_eligible({**good, "platform": "cpu"})
    assert not bw.ab_result_eligible({**good, "error": "UNAVAILABLE"})
    assert not bw.ab_result_eligible({**good, "metric": "other"})
    assert not bw.ab_result_eligible(
        {"metric": "new_edges_sim_kernel_ab"})  # no engine_on payload


def test_log_file_survives_inode_swap(tmp_path, monkeypatch):
    path = tmp_path / "watch.log"
    monkeypatch.setattr(bw, "LOG_PATH", str(path))
    bw.log("first")
    # swap the file on disk (what detached the r5 evidence log)
    path.unlink()
    bw.log("second")
    assert "second" in path.read_text()


def test_bench_journal_last_healthy_filter(tmp_path, monkeypatch):
    """bench.py's wedge-path note reads the journal, never a constant
    (r4 ask #10); the filter must skip platform-pinned and
    harness-artifact entries but accept reconstructed ones (they carry
    provenance flags through to the caller)."""
    import bench

    path = tmp_path / "BENCH_HISTORY.jsonl"
    monkeypatch.setattr(bench, "JOURNAL", str(path))
    flag = {"metric": "exec_ready_mutants_per_sec_per_chip",
            "value": 9000, "ts": "t1"}
    entries = [
        flag,
        {**flag, "value": 21000, "ts": "t2", "platform": "cpu"},
        {**flag, "value": 139, "ts": "t3", "harness_artifact": True},
    ]
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    got = bench.journal_last_healthy()
    assert got["value"] == 9000 and got["ts"] == "t1"
    # reconstructed entries ARE eligible, flags carried through
    with open(path, "a") as f:
        f.write(json.dumps({**flag, "value": 20947, "ts": "t4",
                            "reconstructed": True,
                            "provenance": "weak"}) + "\n")
    got = bench.journal_last_healthy()
    assert got["value"] == 20947 and got.get("reconstructed")
