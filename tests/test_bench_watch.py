"""Watcher decision logic (tools/bench_watch): the journal filter and
the A/B artifact eligibility gate.

The r4 advisor finding was precisely a filter bug here (CPU-pinned
runs satisfying --want); these pin both filters so the watcher's
done-conditions can only be met by accelerator measurements."""

from __future__ import annotations

import json

from syzkaller_tpu.tools import bench_watch as bw


def _write_journal(tmp_path, entries):
    with open(tmp_path / "BENCH_HISTORY.jsonl", "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")


def test_flagship_entries_filters(tmp_path, monkeypatch):
    monkeypatch.setattr(bw, "REPO", str(tmp_path))
    flag = {"metric": "exec_ready_mutants_per_sec_per_chip", "value": 9000}
    _write_journal(tmp_path, [
        flag,                                          # counts
        {**flag, "platform": "cpu"},                   # pinned: no
        {**flag, "harness_artifact": True},            # artifact: no
        {**flag, "reconstructed": True},               # reconstructed: no
        {**flag, "value": 0},                          # zero: no
        {"metric": "new_edges_sim_kernel_ab", "value": 5},  # wrong metric
        flag,                                          # counts
    ])
    assert bw.flagship_entries() == 2


def test_flagship_entries_missing_journal(tmp_path, monkeypatch):
    monkeypatch.setattr(bw, "REPO", str(tmp_path))
    assert bw.flagship_entries() == 0


def test_ab_eligibility_gate():
    good = {"metric": "new_edges_sim_kernel_ab",
            "engine_on": {"edges": 10}, "engine_off": {"edges": 9}}
    assert bw.ab_result_eligible(good)
    assert not bw.ab_result_eligible({**good, "platform": "cpu"})
    assert not bw.ab_result_eligible({**good, "error": "UNAVAILABLE"})
    assert not bw.ab_result_eligible({**good, "metric": "other"})
    assert not bw.ab_result_eligible(
        {"metric": "new_edges_sim_kernel_ab"})  # no engine_on payload


def test_log_file_survives_inode_swap(tmp_path, monkeypatch):
    path = tmp_path / "watch.log"
    monkeypatch.setattr(bw, "LOG_PATH", str(path))
    bw.log("first")
    # swap the file on disk (what detached the r5 evidence log)
    path.unlink()
    bw.log("second")
    assert "second" in path.read_text()


def test_bench_journal_last_healthy_filter(tmp_path, monkeypatch):
    """bench.py's wedge-path note reads the journal, never a constant
    (r4 ask #10); the filter must skip platform-pinned and
    harness-artifact entries but accept reconstructed ones (they carry
    provenance flags through to the caller)."""
    import bench

    path = tmp_path / "BENCH_HISTORY.jsonl"
    monkeypatch.setattr(bench, "JOURNAL", str(path))
    flag = {"metric": "exec_ready_mutants_per_sec_per_chip",
            "value": 9000, "ts": "t1"}
    entries = [
        flag,
        {**flag, "value": 21000, "ts": "t2", "platform": "cpu"},
        {**flag, "value": 139, "ts": "t3", "harness_artifact": True},
    ]
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    got = bench.journal_last_healthy()
    assert got["value"] == 9000 and got["ts"] == "t1"
    # reconstructed entries ARE eligible, flags carried through
    with open(path, "a") as f:
        f.write(json.dumps({**flag, "value": 20947, "ts": "t4",
                            "reconstructed": True,
                            "provenance": "weak"}) + "\n")
    got = bench.journal_last_healthy()
    assert got["value"] == 20947 and got.get("reconstructed")


# -- telemetry-driven wedge diagnostics (ISSUE 2) -----------------------


def _wedge_snapshot():
    """A real registry snapshot shaped like a wedged bench attempt:
    healthy launches, a drain percentile walking toward the deadline,
    breaker transitions with a timeline, and a recorded wedge."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    launch = reg.histogram("tz_pipeline_launch_seconds")
    drain = reg.histogram("tz_pipeline_drain_seconds")
    for _ in range(50):
        launch.observe(0.002)
        drain.observe(0.07)
    for _ in range(5):
        drain.observe(90.0)  # the stalls
    reg.counter("tz_breaker_opens_total").inc(3)
    reg.counter("tz_breaker_half_opens_total").inc(2)
    reg.gauge("tz_watchdog_last_wedge_ts").set(1_700_000_000.0)
    reg.record_event("breaker.open", "after 4 consecutive failures")
    reg.record_event("watchdog.wedge", "device.drain exceeded 120.0s")
    snap = reg.snapshot()
    snap["ts"] = 1_700_000_123.0
    return snap


def test_wedge_report_phase_percentiles_and_timeline():
    lines = bw.wedge_report(_wedge_snapshot())
    text = "\n".join(lines)
    # per-phase latency percentiles from telemetry.snapshot()
    assert "phase tz_pipeline_drain_seconds: n=55" in text
    assert "phase tz_pipeline_launch_seconds: n=50" in text
    drain_line = next(ln for ln in lines
                      if "tz_pipeline_drain_seconds" in ln)
    assert "p50=" in drain_line and "p99=" in drain_line
    # the p99 shows the stall (~90 s), not the healthy 70 ms
    assert "s" in drain_line.split("p99=")[1].split()[0]
    # breaker transition counters (the open ROADMAP item)
    assert "breaker transitions:" in text
    assert "opens=3" in text and "half_opens=2" in text
    # last-wedge timestamp with age relative to the snapshot
    assert "last wedge:" in text and "123s before snapshot" in text
    # the transition event timeline
    assert "breaker.open (after 4 consecutive failures)" in text
    assert "watchdog.wedge" in text


def test_wedge_report_transfer_plane_line():
    """The transfer-plane diagnostics (ISSUE 5): arena footprint,
    both live depths, the realized triage H2D overlap, and stale
    slots render next to the d2h/assembly lines."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_staging_arena_bytes").set(163840)
    reg.gauge("tz_staging_assemble_depth").set(3)
    reg.gauge("tz_staging_h2d_dispatch_depth").set(2)
    reg.counter("tz_triage_batches_total").inc(40)
    reg.counter("tz_triage_h2d_overlap_total").inc(20)
    reg.counter("tz_triage_stale_slots_total").inc(1)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("transfer plane"))
    assert "arenas 160.0 KiB" in line
    assert "assemble depth 3" in line
    assert "h2d dispatch depth 2" in line
    assert "h2d overlap 50.0%" in line
    assert "1 stale slots" in line
    # a snapshot without transfer-plane gauges renders no line
    assert not any(ln.startswith("transfer plane")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_sim_prescore_line():
    """The speculative prescore diagnostics (ISSUE 15): backend,
    batch count, the suppressed fraction against the pipeline batch
    size, re-admission epochs and demotions render as one line."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_pipeline_batch_size").set(4096)
    reg.gauge("tz_sim_backend").set(0)
    reg.counter("tz_sim_prescore_batches_total").inc(10)
    reg.counter("tz_sim_suppressed_rows_total").inc(24576)
    reg.counter("tz_sim_readmit_epochs_total").inc(2)
    reg.counter("tz_sim_demotions_total").inc(1)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("sim prescore"))
    assert "backend vmap" in line
    assert "10 batches" in line
    assert "suppressed 60.0%" in line  # 24576 of 10 x 4096 rows
    assert "2 readmit epochs" in line
    assert "1 demotions" in line
    # the pallas backend renders by name
    reg.gauge("tz_sim_backend").set(1)
    lines = bw.wedge_report(reg.snapshot())
    assert any("sim prescore: backend pallas" in ln for ln in lines)
    # a snapshot without prescore counters renders no line
    assert not any(ln.startswith("sim prescore")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_corpus_arena_line():
    """The corpus-arena diagnostics (ISSUE 18): residency, epoch,
    slab footprint, upload cadence and the distillation lane's
    retired-row yield render as one line."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_arena_rows").set(64)
    reg.gauge("tz_arena_capacity_rows").set(1024)
    reg.gauge("tz_arena_epoch").set(2)
    reg.gauge("tz_arena_slab_bytes").set(512 * 1024)
    reg.counter("tz_arena_uploads_total").inc(3)
    reg.counter("tz_arena_upload_bytes_total").inc(96 * 1024)
    reg.counter("tz_arena_distill_rounds_total").inc(5)
    reg.counter("tz_arena_retired_rows_total").inc(7)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("corpus arena"))
    assert "64/1024 rows" in line
    assert "epoch 2" in line
    assert "slabs 512.0 KiB" in line
    assert "3 uploads (96.0 KiB)" in line
    assert "distill 5 rounds (7 rows retired)" in line
    # zero uploads / no distill rounds: the optional clauses drop
    reg2 = Registry()
    reg2.gauge("tz_arena_rows").set(12)
    reg2.gauge("tz_arena_capacity_rows").set(1024)
    lines = bw.wedge_report(reg2.snapshot())
    line = next(ln for ln in lines if ln.startswith("corpus arena"))
    assert "uploads" not in line and "distill" not in line
    # a snapshot without arena gauges renders no line
    assert not any(ln.startswith("corpus arena")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_control_plane_line():
    """The control-plane health line (ISSUE 9): fleet liveness,
    retry/replay volume, and the admission state render in the wedge
    diagnostics so a fleet problem is distinguishable from a
    kernel-under-test problem."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_manager_connected_fuzzers").set(3)
    reg.gauge("tz_manager_throttle_state").set(2)
    reg.counter("tz_manager_leases_reaped_total").inc(1)
    reg.counter("tz_rpc_retries_total").inc(7)
    reg.counter("tz_manager_reply_replays_total").inc(4)
    reg.counter("tz_manager_candidates_reissued_total").inc(12)
    reg.counter("tz_manager_inputs_dropped_total").inc(2)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("control plane"))
    assert "3 live fuzzers" in line
    assert "1 reaped" in line
    assert "7 rpc retries" in line
    assert "4 replayed from cache" in line
    assert "admission open" in line
    assert "12 candidates reissued" in line
    assert "2 inputs dropped" in line
    # a snapshot without control-plane signals renders no line
    assert not any(ln.startswith("control plane")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_mesh_health_line():
    """The fault-domain mesh line (ISSUE 11): topology width,
    per-shard breaker states, re-shard age, and the demotion /
    re-admission totals render so a demoted chip is visible at a
    glance while the engine keeps serving from N-1."""
    import time as _time

    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_mesh_devices_live").set(7)
    reg.gauge("tz_mesh_devices_demoted").set(1)
    for shard, state in ((0, 0), (3, 2), (5, 1)):
        reg.gauge("tz_mesh_shard_breaker_state",
                  labels={"shard": str(shard)}).set(state)
    reg.gauge("tz_mesh_last_reshard_ts").set(_time.time() - 42)
    reg.counter("tz_mesh_demote_total").inc(2)
    reg.counter("tz_mesh_repromote_total").inc(1)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("mesh:"))
    assert "7 live / 1 demoted" in line
    assert "shards 0:closed 3:open 5:half_open" in line
    assert "last re-shard 42s ago" in line
    assert "(2 demotions, 1 re-admissions)" in line
    # a snapshot without mesh gauges renders no line
    assert not any(ln.startswith("mesh:")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_stalled_coverage_line():
    """ISSUE 7: the coverage trajectory renders next to the health
    layers — occupancy + novelty rate, the STALLED verdict, plane
    drift, and the per-lane attribution breakdown."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_coverage_occupancy").set(123456)
    reg.gauge("tz_coverage_novelty_rate").set(4.25)
    reg.gauge("tz_coverage_stalled").set(1)
    reg.gauge("tz_coverage_plane_drift").set(7)
    reg.counter("tz_coverage_novel_edges_total",
                labels={"lane": "smash"}).inc(40)
    reg.counter("tz_coverage_novel_edges_total",
                labels={"lane": "exploration"}).inc(9)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("coverage:"))
    assert "123456 plane buckets occupied" in line
    assert "novelty 4.250 edges/s" in line
    assert "STALLED" in line
    assert "plane drift 7 buckets" in line
    lane = next(ln for ln in lines
                if ln.startswith("novel edges by lane:"))
    assert "smash=40" in lane and "exploration=9" in lane
    # a snapshot without coverage gauges renders no line
    assert not any(ln.startswith("coverage:")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_coverage_report_renders_api_payload():
    """ISSUE 7: the /api/coverage payload renders into diagnostic
    lines — verdict, growth-curve tail, attribution, drift, heat map.
    Pure function, no live manager."""
    payload = {
        "local": {
            "occupancy": 5000, "novelty_rate_ewma": 1.5,
            "novel_edges_total": 321, "stalled": True, "stalls": 2,
            "stall_window_s": 300.0, "stall_edges": 1,
            "last_novel_age_s": 400.0,
            "growth_curve": [[1e9, 4000, 100], [1e9 + 5, 5000, 221]],
            "attribution": {"by_source": {"smash": 300,
                                          "candidate": 21},
                            "by_proc": {"0": 321},
                            "total_novel_edges": 321},
            "drift": {"ts": 1e9, "buckets": 3, "audits": 5},
            "heat_regions": [0, 10, 2, 0],
        },
        "fleet": {},
        "stalled": True,
    }
    text = "\n".join(bw.coverage_report(payload))
    assert "coverage: STALLED" in text
    assert "occupancy 5000" in text
    assert "novelty 1.500 edges/s" in text
    assert "stalls: 2" in text
    assert "occupancy=5000 +221" in text
    assert "by lane: smash=300 candidate=21" in text
    assert "3 buckets DRIFTED (5 audits)" in text
    assert "heat map: 2/4 regions occupied" in text
    assert "hottest region 1 (10 buckets)" in text
    # a bare tracker snapshot (no local/fleet wrapper) renders too
    lines = bw.coverage_report(payload["local"])
    assert any("coverage: STALLED" in ln for ln in lines)


def test_wedge_report_empty_snapshot():
    lines = bw.wedge_report({"ts": 0, "counters": {}, "gauges": {},
                             "histograms": {}, "events": []})
    assert lines == ["telemetry snapshot carried no phase latencies "
                     "or health transitions"]


def test_flight_report_renders_incident():
    """ISSUE 6: the flight-recorder incident payload renders into
    diagnostic lines — breaker timeline, span summary, queue-depth
    history, recorded attempts.  Pure function, no live TPU."""
    incident = {
        "reason": "device_wedged", "detail": "device.launch hung",
        "ts": 1e9, "pid": 42,
        "spans": [[1e9, "pipeline.drain", 0.02],
                  [1e9, "pipeline.drain", 0.03],
                  [1e9, "pipeline.launch", 0.001]],
        "queue_depths": [{"ts": 1e9, "tz_pipeline_queue_depth": 2}],
        "breaker_timeline": [[1e9, "watchdog.wedge", "0.3s"],
                             [1e9, "breaker.open", "4 failures"]],
        "attempts": [{"ts": 1e9, "kind": "timeout",
                      "reason": "lease never granted"}],
    }
    text = "\n".join(bw.flight_report(incident))
    assert "incident: device_wedged" in text
    assert "device.launch hung" in text
    assert "watchdog.wedge" in text and "breaker.open" in text
    assert "pipeline.drain=2" in text
    assert "queue_depth=2" in text
    assert "attempt" in text and "lease never granted" in text
    # an empty incident degrades to a note, never a crash
    assert any("no timeline" in ln for ln in bw.flight_report({}))


def test_report_flight_reads_files(tmp_path, capsys):
    path = tmp_path / "tz_flight_breaker_open_1.json"
    with open(path, "w") as f:
        json.dump({"reason": "breaker_open", "ts": 1e9, "pid": 1,
                   "spans": [], "queue_depths": [],
                   "breaker_timeline": []}, f)
    bw.report_flight([str(path)])
    out = capsys.readouterr().out
    assert "flight recorder" in out and "breaker_open" in out
    bw.report_flight([])
    assert "no flight-recorder incident files" \
        in capsys.readouterr().out


def test_run_bench_lease_catching_bounded(tmp_path, monkeypatch):
    """ISSUE 6 satellite (ROADMAP carry-over from BENCH_r05): a
    Client_Create-style subprocess timeout retries with backoff a
    BOUNDED number of times, recording every attempt in the incident
    journal instead of failing the round on the first wedge."""
    import subprocess as sp

    calls = {"n": 0}

    def fake_run(*a, **kw):
        calls["n"] += 1
        raise sp.TimeoutExpired(cmd="bench.py", timeout=kw["timeout"])

    monkeypatch.setattr(bw.subprocess, "run", fake_run)
    monkeypatch.setattr(bw, "INCIDENT_PATH",
                        str(tmp_path / "tz_flight_bench_watch.json"))
    assert bw.run_bench([], timeout_s=5, lease_retries=2,
                        lease_backoff_s=0.0) is None
    assert calls["n"] == 3  # initial + 2 bounded retries
    payload = json.loads(open(bw.INCIDENT_PATH).read())
    kinds = [a["kind"] for a in payload["attempts"]]
    assert kinds == ["timeout"] * 3
    assert payload["attempts"][0]["attempt"] == 1
    assert payload["attempts"][-1]["attempt"] == 3

    # a non-timeout failure does NOT retry (the wedge signature is
    # the subprocess timeout, not an ordinary bench error)
    def fake_fail(*a, **kw):
        calls["n"] += 1
        return sp.CompletedProcess(a[0], returncode=1, stdout="",
                                   stderr="boom")

    calls["n"] = 0
    monkeypatch.setattr(bw.subprocess, "run", fake_fail)
    assert bw.run_bench([], timeout_s=5, lease_retries=2,
                        lease_backoff_s=0.0) is None
    assert calls["n"] == 1


def test_report_telemetry_reads_dump(tmp_path, monkeypatch, capsys):
    """End-to-end: a telemetry dump on disk (what bench.dump_telemetry
    leaves behind) renders into diagnose_wedge's log output."""
    path = tmp_path / "TELEMETRY_SNAPSHOT.json"
    with open(path, "w") as f:
        json.dump(_wedge_snapshot(), f)
    bw.report_telemetry(str(path))
    out = capsys.readouterr().out
    assert "breaker transitions:" in out and "opens=3" in out
    # a missing snapshot degrades to a note, never a crash
    bw.report_telemetry(str(tmp_path / "absent.json"))
    assert "no telemetry snapshot" in capsys.readouterr().out


def test_wedge_report_hub_federation_line():
    """The hub federation line (ISSUE 16): live vs reaped manager
    sessions, digest-diff byte savings, per-manager sync breakers,
    and the last leader-failover age render so a flapping manager or
    a warm-restarted hub is visible from the bench watch."""
    import time as _time

    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_hub_managers_size").set(3)
    reg.counter("tz_hub_leases_reaped_total").inc(1)
    reg.counter("tz_hub_sync_saved_bytes_total").inc(2048)
    reg.gauge("tz_hub_breaker_state", labels={"manager": "mA"}).set(0)
    reg.gauge("tz_hub_breaker_state", labels={"manager": "mB"}).set(2)
    reg.gauge("tz_hub_last_failover_ts").set(_time.time() - 42)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("hub:"))
    assert "3 managers live / 1 reaped" in line
    assert "sync saved 2.0 KiB" in line
    assert "breakers mA:closed mB:open" in line
    assert "last failover 42s ago" in line
    # a snapshot without hub signals renders no line
    assert not any(ln.startswith("hub:")
                   for ln in bw.wedge_report(_wedge_snapshot()))


def test_wedge_report_device_residency_lines():
    """The device-residency observatory (ISSUE 17, layer 8): the
    per-buffer residency rollup with the headroom forecast and
    reconcile drifts, plus the per-family compile ledger with its
    storm count, render next to the other wedge layers."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.gauge("tz_hbm_live_bytes",
              labels={"owner": "pipeline", "device": "0",
                      "kind": "corpus"}).set(64e6)
    reg.gauge("tz_hbm_live_bytes",
              labels={"owner": "mesh", "device": "0-7",
                      "kind": "planes"}).set(128e6)
    reg.gauge("tz_hbm_headroom_bytes").set(15.5e9)
    reg.counter("tz_hbm_drift_total").inc(2)
    reg.counter("tz_compile_builds_total",
                labels={"graph": "mesh.fused_step"}).inc(2)
    reg.counter("tz_compile_builds_total",
                labels={"graph": "pipeline.step"}).inc(1)
    reg.counter("tz_compile_storms_total").inc(1)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines
                if ln.startswith("device residency"))
    assert "pipeline/corpus@0:64.0MB" in line
    assert "mesh/planes@0-7:128.0MB" in line
    assert "headroom 15.50GB" in line
    assert "2 reconcile DRIFTS" in line
    cline = next(ln for ln in lines if ln.startswith("compiles:"))
    assert "mesh.fused_step=2" in cline
    assert "pipeline.step=1" in cline
    assert "1 STORMS" in cline
    # a snapshot without residency gauges renders neither line
    other = bw.wedge_report(_wedge_snapshot())
    assert not any(ln.startswith("device residency") for ln in other)
    assert not any(ln.startswith("compiles:") for ln in other)


def test_device_report_renders_api_payload():
    """device_report renders a manager /api/device payload — the
    residency summary and per-buffer table, the reconcile verdict
    (flagged drift shouts), and the compile ledger with recent
    builds.  Pure function — pinned with no live manager."""
    payload = {
        "hbm": {
            "owners": {"pipeline": {"live_bytes": 64_000_000,
                                    "peak_bytes": 80_000_000}},
            "buffers": {"pipeline/corpus@0": 64_000_000,
                        "staging/arena@host": 2_000_000},
            "device_resident_bytes": 64_000_000,
            "transient_bytes": 4_000_000,
            "capacity_bytes": 16_000_000_000,
            "headroom_bytes": 15_932_000_000,
            "last_reconcile": {"tracked_bytes": 64_000_000,
                               "backend_bytes": 63_000_000,
                               "drift_bytes": 1_000_000,
                               "dead_entries": 1,
                               "entries": 3, "flagged": True,
                               "seconds": 0.001},
        },
        "compiles": {"total_builds": 3, "storms": 1,
                     "graphs": {"mesh.fused_step":
                                {"builds": 2, "shapes": 2}},
                     "recent": [[1_700_000_000.0, "mesh.fused_step",
                                 [["devices", "8"]], 1.25]]},
    }
    lines = bw.device_report(payload)
    text = "\n".join(lines)
    assert "64.0 MB device-resident of 16.0 GB" in text
    assert "headroom 15.93 GB" in text
    assert "pipeline/corpus@0: 64.0 MB" in text
    assert "staging/arena@host: 2.0 MB" in text
    assert "DRIFT 1000000 B" in text and "over 3 entries" in text
    assert "mesh.fused_step=2(2 shapes)" in text
    assert "1 STORMS" in text
    assert "built mesh.fused_step in 1.25s" in text
    # an empty payload still renders the summary, not a crash
    assert any("reconcile: never ran" in ln
               for ln in bw.device_report({}))


def test_wedge_report_hints_lane_line():
    """ISSUE 19: the hints lane renders its fused-batch throughput,
    staging bill, suppression fraction, off-device comparand count,
    and fallback posture as one wedge line."""
    from syzkaller_tpu.telemetry import Registry

    reg = Registry()
    reg.counter("tz_hints_batches_total").inc(12)
    reg.counter("tz_hints_values_total").inc(700)
    reg.counter("tz_hints_mutants_total").inc(150)
    reg.counter("tz_hints_staged_bytes_total").inc(262144)
    reg.counter("tz_hints_sim_suppressed_total").inc(50)
    reg.counter("tz_hints_comps_dropped_total").inc(9)
    reg.counter("tz_hints_cpu_fallback_values_total").inc(30)
    reg.counter("tz_hints_demotions_total").inc(1)
    lines = bw.wedge_report(reg.snapshot())
    line = next(ln for ln in lines if ln.startswith("hints lane:"))
    assert "12 batches" in line
    assert "700 windows -> 150 mutants" in line
    assert "staged 256.0 KiB" in line
    assert "suppressed 25.0%" in line  # 50 / (50 + 150)
    assert "9 comps off-device" in line
    assert "30 windows on CPU" in line
    assert "1 demotions" in line
    # CPU-only posture (demoted lane, zero device batches) still
    # renders, so a wedged device is visible from the hints line.
    cpu = Registry()
    cpu.counter("tz_hints_cpu_fallback_values_total").inc(5)
    lines = bw.wedge_report(cpu.snapshot())
    assert any(ln.startswith("hints lane:") for ln in lines)
    # a snapshot without hints counters renders no line
    assert not any(ln.startswith("hints lane:")
                   for ln in bw.wedge_report(_wedge_snapshot()))
