"""Device-plane batched triage (syzkaller_tpu/triage, ISSUE 4).

The contract under test: with the TriageEngine installed, corpus
bookkeeping — max_signal, new_signal, and the (call_index, diff) work
items that feed WorkTriage — is byte-identical to the pure-CPU path.
The randomized streams draw edges below 2^FOLD_BITS, where the xor-
fold is the identity and therefore injective: the plane's only
approximation (fold collisions) is switched off by construction, so
any divergence is an engine bug, not fold noise.  A separate test
forces a collision to pin the documented false-negative semantics and
its exported estimate.

All CPU-only and compile-light: the engine is built at batch=8 /
max_edges=64, so the plane kernels run at the same (8, 64) shapes
test_ops already warms, and the two new kernels (novel_any,
merge_into) are small single-fusion compiles.
"""

from __future__ import annotations

import numpy as np
import pytest

from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
from syzkaller_tpu.ops import signal as dsig
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.triage import TriageEngine


class _Info:
    """Duck-typed CallInfo: what check_new_signal_fn reads."""

    __slots__ = ("call_index", "errno", "signal")

    def __init__(self, call_index, signal, errno=0):
        self.call_index = call_index
        self.errno = errno
        self.signal = signal


def _prio_fn(errno, _idx):
    return 3 if errno == 0 else 1


@pytest.fixture()
def engine_fuzzer(test_target):
    fz = Fuzzer(test_target, wq=WorkQueue())
    eng = TriageEngine(batch=8, max_edges=64)
    fz.set_triage(eng)
    return fz, eng


def _news_key(news):
    return [(ci, dict(diff.m)) for ci, diff in news]


def test_triage_parity_randomized(test_target, engine_fuzzer):
    """The acceptance property: identical max_signal / new_signal /
    triage work items vs the CPU path on random signal streams with
    interleaved manager max-signal merges."""
    fz_dev, eng = engine_fuzzer
    fz_cpu = Fuzzer(test_target, wq=WorkQueue())
    rng = np.random.RandomState(7)
    work_dev, work_cpu = [], []
    for step in range(40):
        infos = []
        for c in range(rng.randint(1, 9)):
            n = rng.randint(0, 65)
            # < 2^FOLD_BITS: fold-injective, so parity is exact.
            edges = rng.randint(0, 1 << dsig.FOLD_BITS, size=n,
                                dtype=np.uint32)
            # Re-observed edges mixed in so the filtered fast path
            # actually runs (fresh-only streams always flag).
            infos.append(_Info(c, edges, errno=int(rng.randint(0, 2))))
        news_dev = fz_dev.check_new_signal_fn(_prio_fn, infos)
        news_cpu = fz_cpu.check_new_signal_fn(_prio_fn, infos)
        assert _news_key(news_dev) == _news_key(news_cpu), step
        work_dev.extend(_news_key(news_dev))
        work_cpu.extend(_news_key(news_cpu))
        if step % 3 == 0:
            # Replay a prior program: plane-filtered on the device
            # path, dict-diffed to empty on the CPU path.
            assert fz_dev.check_new_signal_fn(_prio_fn, infos) == []
            assert fz_cpu.check_new_signal_fn(_prio_fn, infos) == []
        if step % 7 == 0:
            # Manager-distributed max signal scatters into the plane.
            sig = Signal({int(e): 2 for e in rng.randint(
                0, 1 << dsig.FOLD_BITS, size=16)})
            fz_dev.add_max_signal(sig.copy())
            fz_cpu.add_max_signal(sig.copy())
    assert work_dev == work_cpu
    assert fz_dev.max_signal.m == fz_cpu.max_signal.m
    assert fz_dev.new_signal.m == fz_cpu.new_signal.m
    s = eng.stats
    assert s.device_batches > 0 and s.plane_misses > 0, \
        "the lock-free fast path never ran"
    assert s.plane_hits > 0 and s.cpu_fallback_calls == 0
    # The mirror under-approximates max_signal exactly: every exact
    # element is present at >= its prio, and the flush-cadence device
    # popcount (ISSUE 7: the only occupancy source now) agrees with
    # the mirror bit-exactly.
    mirror = eng._mirror
    for e, p in fz_dev.max_signal.m.items():
        assert mirror[int(dsig.fold_hash_np(np.uint32(e)))] >= p + 1
    eng.run_analytics()
    assert int(np.count_nonzero(mirror)) == eng._occupancy


def test_triage_overflow_and_empty_calls(test_target, engine_fuzzer):
    """Signals over the E budget confirm on the exact CPU path
    (counted as overflows); empty signals short-circuit — both
    bit-identical to the CPU fuzzer."""
    fz_dev, eng = engine_fuzzer
    fz_cpu = Fuzzer(test_target, wq=WorkQueue())
    rng = np.random.RandomState(3)
    big = rng.randint(0, 1 << dsig.FOLD_BITS, size=500, dtype=np.uint32)
    infos = [_Info(0, np.empty(0, np.uint32)), _Info(1, big)]
    a = fz_dev.check_new_signal_fn(_prio_fn, infos)
    b = fz_cpu.check_new_signal_fn(_prio_fn, infos)
    assert _news_key(a) == _news_key(b) and len(a) == 1
    assert eng.stats.overflow_calls == 1
    assert fz_dev.max_signal.m == fz_cpu.max_signal.m


def test_triage_fold_false_negative_measured(test_target):
    """The documented approximation: a novel edge whose fold collides
    with an occupied bucket is filtered without a CPU confirm, and the
    exported estimate prices exactly that event."""
    fz = Fuzzer(test_target, wq=WorkQueue())
    eng = TriageEngine(batch=8, max_edges=64)
    fz.set_triage(eng)
    x = 12345
    seen = np.asarray([x ^ 1], dtype=np.uint32)  # folds to x^1
    # (x | 2^26) >> 26 == 1, so its fold is (x ^ 1) masked — the same
    # bucket as `seen` from a distinct 32-bit edge.
    collider = np.asarray([x | (1 << dsig.FOLD_BITS)],
                          dtype=np.uint32)
    assert int(dsig.fold_hash_np(seen)[0]) \
        == int(dsig.fold_hash_np(collider)[0])
    assert len(fz.check_new_signal_fn(_prio_fn, [_Info(0, seen)])) == 1
    # CPU truth: the collider is new signal.  Plane verdict: filtered.
    ref = Fuzzer(test_target, wq=WorkQueue())
    ref.add_max_signal(Signal({int(seen[0]): 3}))
    assert len(ref.cpu_check_new_signal(
        _prio_fn, [_Info(0, collider)])) == 1
    assert fz.check_new_signal_fn(_prio_fn, [_Info(0, collider)]) == []
    eng.run_analytics()  # occupancy/FN-rate update at flush cadence
    snap = eng.snapshot()
    assert snap["plane_misses"] >= 1
    assert 0 < snap["fold_false_negative_rate"] < 1e-3
    assert snap["plane_occupancy"] == 1


def test_triage_flush_staging_zero_allocations(test_target,
                                               engine_fuzzer):
    """ISSUE 5 regression: the flush leader's per-batch np.zeros +
    copy re-pad is gone — after the first flush warms a bucket's
    arena, every later flush writes rows IN PLACE into the rotating
    slots.  Zero new bucket-sized allocations, pinned by the arena's
    growth counters."""
    fz, eng = engine_fuzzer
    rng = np.random.RandomState(13)

    def check():
        infos = [_Info(c, rng.randint(0, 1 << dsig.FOLD_BITS, size=24,
                                      dtype=np.uint32))
                 for c in range(8)]
        fz.check_new_signal_fn(_prio_fn, infos)

    check()  # warms the single (B=8) bucket's slot pair
    allocs0, bytes0 = eng._arena.allocations, eng._arena.nbytes
    assert allocs0 >= 1
    for _ in range(20):
        check()
    assert eng._arena.allocations == allocs0, \
        "flush leader allocated staging buffers after warmup"
    assert eng._arena.nbytes == bytes0
    # And the batches really went through the device plane, not some
    # degraded path that would trivially satisfy the counters.
    assert eng.stats.device_batches >= 21


def test_triage_dispatch_overlap_parity(test_target):
    """TZ_TRIAGE_DISPATCH_DEPTH=2 (the default): a check spanning
    several chunks dispatches batch k's H2D while batch k-1's
    verdicts are still in flight.  Results stay bit-identical to the
    CPU path, verdicts resolve in strict dispatch order, and nothing
    is dropped."""
    fz = Fuzzer(test_target, wq=WorkQueue())
    eng = TriageEngine(batch=8, max_edges=64, dispatch_depth=2)
    assert eng._dispatch_depth == 2
    fz.set_triage(eng)
    ref = Fuzzer(test_target, wq=WorkQueue())
    rng = np.random.RandomState(21)
    for step in range(12):
        infos = [
            _Info(c, rng.randint(0, 1 << dsig.FOLD_BITS,
                                 size=int(rng.randint(1, 33)),
                                 dtype=np.uint32))
            for c in range(20)]  # 20 calls -> 3 chunks at B=8
        a = fz.check_new_signal_fn(_prio_fn, infos)
        b = ref.cpu_check_new_signal(_prio_fn, infos)
        assert _news_key(a) == _news_key(b), step
    assert fz.max_signal.m == ref.max_signal.m
    assert fz.new_signal.m == ref.new_signal.m
    assert eng.stats.h2d_overlaps > 0, "the H2D overlap never engaged"
    # Strict seq delivery: every dispatched batch resolved, in order.
    assert eng._resolve_seq == eng._dispatch_seq
    assert eng.snapshot()["h2d_overlaps"] == eng.stats.h2d_overlaps


def test_triage_kill_switch_and_envsafe_knobs(monkeypatch, test_target):
    """TZ_TRIAGE_* knobs parse through health.envsafe: malformed
    values degrade to the constructor defaults instead of killing
    startup, well-formed values override."""
    monkeypatch.setenv("TZ_TRIAGE_BATCH", "not-a-number")
    monkeypatch.setenv("TZ_TRIAGE_MAX_EDGES", "")
    monkeypatch.setenv("TZ_TRIAGE_FLUSH_S", "1.2.3")
    eng = TriageEngine(batch=16, max_edges=128, flush_s=0.5)
    assert eng.B == 16 and eng.E == 128 and eng.flush_s == 0.5
    monkeypatch.setenv("TZ_TRIAGE_BATCH", "32")
    monkeypatch.setenv("TZ_TRIAGE_MAX_EDGES", "0x100")
    monkeypatch.setenv("TZ_TRIAGE_FLUSH_S", "0.25")
    eng = TriageEngine(batch=16, max_edges=128)
    assert eng.B == 32 and eng.E == 256 and eng.flush_s == 0.25
    # The transfer-plane depth knob parses the same hardened way.
    monkeypatch.setenv("TZ_TRIAGE_DISPATCH_DEPTH", "not-a-depth")
    eng = TriageEngine(batch=16, max_edges=128, dispatch_depth=3)
    assert eng._dispatch_depth == 3  # ctor fallback, not a crash
    monkeypatch.setenv("TZ_TRIAGE_DISPATCH_DEPTH", "1")
    eng = TriageEngine(batch=16, max_edges=128, dispatch_depth=3)
    assert eng._dispatch_depth == 1  # the serial kill path
    # The kill switch is read the same hardened way at the wiring
    # site (fuzzer/main.py): malformed -> default-on.
    from syzkaller_tpu.health import env_int

    monkeypatch.setenv("TZ_TRIAGE_DEVICE", "maybe")
    assert env_int("TZ_TRIAGE_DEVICE", 1) == 1
    monkeypatch.setenv("TZ_TRIAGE_DEVICE", "0")
    assert env_int("TZ_TRIAGE_DEVICE", 1) == 0


def test_triage_plane_shared_with_mesh(test_target):
    """One plane per process: the mesh step consumes the engine's
    plane (cov-sharded) instead of allocating its own, and step
    output merges back through absorb_plane."""
    import jax

    from syzkaller_tpu.parallel.mesh import make_mesh, shard_engine_plane

    fz = Fuzzer(test_target, wq=WorkQueue())
    eng = TriageEngine(batch=8, max_edges=64)
    fz.set_triage(eng)
    rng = np.random.RandomState(5)
    edges = rng.randint(0, 1 << dsig.FOLD_BITS, size=32, dtype=np.uint32)
    fz.check_new_signal_fn(_prio_fn, [_Info(0, edges)])
    mesh = make_mesh(jax.devices(), cov=2)
    shared = shard_engine_plane(mesh, eng)
    assert np.array_equal(np.asarray(shared), eng._mirror)
    # An externally updated plane (the mesh step's pmax output) folds
    # back: the mirror covers both sides afterwards.
    extra = np.zeros_like(eng._mirror)
    extra_idx = dsig.fold_hash_np(
        rng.randint(0, 1 << dsig.FOLD_BITS, size=8, dtype=np.uint32))
    extra[extra_idx] = 4
    updated = np.maximum(np.asarray(shared), extra)
    eng.absorb_plane(updated)
    assert np.array_equal(eng._mirror, updated)
    assert eng._occupancy == int(np.count_nonzero(updated))
    # the absorbed signal is authority now: those buckets filter
    assert eng.snapshot()["plane_occupancy"] == eng._occupancy


def test_triage_cross_proc_batching(test_target, engine_fuzzer):
    """Concurrent procs submitting together resolve through shared
    flush leaders with exact per-proc results (the staging buffer is
    cross-proc state; results must not cross wires)."""
    import threading

    fz, eng = engine_fuzzer
    rng = np.random.RandomState(9)
    streams = []
    for t in range(4):
        checks = []
        for _ in range(10):
            checks.append([
                _Info(c, rng.randint(0, 1 << dsig.FOLD_BITS, size=24,
                                     dtype=np.uint32))
                for c in range(4)])
        streams.append(checks)
    results: dict[int, list] = {}
    errors: list[BaseException] = []

    def worker(t):
        try:
            out = []
            for infos in streams[t]:
                out.append(_news_key(
                    fz.check_new_signal_fn(_prio_fn, infos)))
            results[t] = out
        except BaseException as e:  # surfaced to the assertion below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == 4
    # Replay the union on a fresh CPU fuzzer: same final max_signal
    # regardless of interleaving (max-merge is order-independent).
    ref = Fuzzer(test_target, wq=WorkQueue())
    for checks in streams:
        for infos in checks:
            ref.cpu_check_new_signal(_prio_fn, infos)
    assert fz.max_signal.m == ref.max_signal.m
    # every submitted call was answered
    assert eng.stats.calls == 4 * 10 * 4
