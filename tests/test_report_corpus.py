"""Oops-parser regression corpus: ≥30 console logs hand-written in
real kernel output formats (timestamps, ramoops <N>[...] prefixes,
interleaved CPU tags, executor-log noise, truncated trailers) with
expected titles, corruption flags, guilty source files, and
maintainer routing (VERDICT r3 item #5; reference analogue:
pkg/report/testdata/linux/report — content here is original, not
copied from the reference's testdata)."""

from __future__ import annotations

import glob
import os

import pytest

from syzkaller_tpu.report import get_reporter
from syzkaller_tpu.report.linux import guilty_source, maintainers_for

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "testdata", "report")


def _load(path):
    directives = {}
    with open(path, "rb") as f:
        raw = f.read()
    head, _, log = raw.partition(b"#---\n")
    for line in head.splitlines():
        k, _, v = line[1:].decode().partition(" ")
        directives[k] = v.strip()
    return directives, log


CASES = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.log")))


def test_corpus_is_big_enough():
    assert len(CASES) >= 30


@pytest.mark.parametrize("path", CASES, ids=[os.path.basename(p)
                                             for p in CASES])
def test_corpus_entry(path):
    directives, log = _load(path)
    reporter = get_reporter("linux")
    assert reporter.contains_crash(log), "oops not detected at all"
    rep = reporter.parse(log)
    assert rep is not None
    assert rep.title == directives["TITLE"]
    if "CORRUPTED" in directives:
        assert rep.corrupted, "expected corrupted report"
    else:
        assert not rep.corrupted, f"unexpectedly corrupted: " \
                                  f"{rep.corrupted_reason}"
    if "SRC" in directives:
        assert rep.guilty_src == directives["SRC"]
    if "MAINT" in directives:
        assert directives["MAINT"] in rep.maintainers


def test_maintainers_builtin_routing():
    assert "netdev@vger.kernel.org" in maintainers_for("net/core/dev.c")
    assert "linux-ext4@vger.kernel.org" in maintainers_for(
        "fs/ext4/inode.c")
    # longest prefix wins
    assert "linux-sctp@vger.kernel.org" in maintainers_for(
        "net/sctp/socket.c")
    # everything routes to lkml too
    assert "linux-kernel@vger.kernel.org" in maintainers_for(
        "kernel/fork.c")
    assert maintainers_for("") == []


def test_guilty_source_skips_report_machinery():
    region = (b"Call Trace:\n"
              b" __kasan_report mm/kasan/report.c:511 [inline]\n"
              b" kasan_report+0x33/0x50 mm/kasan/common.c:625,\n"
              b" tcp_v4_rcv+0x2d2/0x3a20 net/ipv4/tcp_ipv4.c:1973,\n")
    assert guilty_source(region) == "net/ipv4/tcp_ipv4.c"
