"""Real-OS executor backend: benign handcrafted programs issue actual
syscalls on the build host (no VM needed — the same pattern as the
reference's host-side ipc tests, pkg/ipc/ipc_test.go).

Programs here are hand-built from known-safe calls only; random
generated programs are never executed against the host kernel.
"""

import os

import pytest

from syzkaller_tpu.ipc.env import ExecOpts, make_env
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.prog import (Call, ConstArg, DataArg, PointerArg,
                                       Prog, make_return_arg)
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def linux_target():
    return get_target("linux", "amd64")


def _call(target, name, args):
    meta = next(c for c in target.syscalls if c.name == name)
    return Call(meta=meta, args=args, ret=make_return_arg(meta.ret))


def _getpid_prog(target):
    return Prog(target=target, calls=[_call(target, "getpid", [])])


def test_real_getpid(linux_target):
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(
            _getpid_prog(linux_target)))
        assert res.completed
        info = res.info[0]
        assert info.errno == 0
        # the executor forked per-program? no — same process pool, so
        # the pid must be the executor's own (a real, positive pid)
        assert len(info.signal) > 0  # synthetic or kcov edges flow
    finally:
        env.close()


def test_real_open_read_devnull(linux_target):
    """A description-compiled program (text -> typed -> exec bytes)
    issues real syscalls and threads the fd result through — the
    end-to-end gate on the compiled linux model."""
    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        b"r0 = openat(0xffffffffffffff9c, "
        b"&(0x7f0000000000)='/dev/null\\x00', 0x0, 0x0)\n"
        b"read(r0, &(0x7f0000001000)=\"\"/16, 0x10)\n"
    )
    p = deserialize_prog(linux_target, text)
    assert p.calls[1].args[0].res is p.calls[0].ret, \
        "fd result edge not threaded by the parser"
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        assert res.info[0].errno == 0, "openat(/dev/null) failed"
        assert res.info[1].errno == 0, "read(fd) failed — result arg " \
            "did not thread the real fd"
    finally:
        env.close()


def test_real_bad_call_errno(linux_target):
    """A call with an invalid argument must report the real errno."""
    target = linux_target
    from syzkaller_tpu.models.prog import ResultArg

    meta = next(c for c in target.syscalls if c.name == "close")
    p = Prog(target=target, calls=[
        Call(meta=meta, args=[ResultArg(meta.args[0], val=0xFFFFFFFF)],
             ret=make_return_arg(meta.ret))])
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.info[0].errno == 9  # EBADF
    finally:
        env.close()


# ---- pseudo-syscalls (executor/pseudo_linux.h) ----------------------

def _run_text(target, text, **env_kw):
    from syzkaller_tpu.models.encoding import deserialize_prog

    p = deserialize_prog(target, text)
    env = make_env(0, sim=False, **env_kw)
    try:
        return env.exec(ExecOpts(), serialize_for_exec(p))
    finally:
        env.close()


def test_syz_open_procfs(linux_target):
    res = _run_text(
        linux_target,
        b"r0 = syz_open_procfs(0x0, &(0x7f0000000000)='status\\x00')\n"
        b"read(r0, &(0x7f0000001000)=\"\"/64, 0x40)\n")
    assert res.completed
    assert res.info[0].errno == 0, "syz_open_procfs(self/status) failed"
    assert res.info[1].errno == 0


def test_syz_open_dev_hash_substitution(linux_target, tmp_path):
    # '#' in the template is replaced by the id argument
    base = tmp_path / "tzdev"
    (tmp_path / "tzdev7").write_bytes(b"hello")
    path = str(base).encode() + b"#"
    text = (b"r0 = syz_open_dev(&(0x7f0000000000)='"
            + path.replace(b"/", b"/") + b"\\x00', 0x7, 0x0)\n"
            b"read(r0, &(0x7f0000001000)=\"\"/8, 0x5)\n")
    res = _run_text(linux_target, text)
    assert res.completed
    assert res.info[0].errno == 0, "syz_open_dev did not substitute #"
    assert res.info[1].errno == 0


def test_syz_open_pts(linux_target):
    if not os.path.exists("/dev/ptmx"):
        pytest.skip("no /dev/ptmx")
    res = _run_text(
        linux_target,
        b"r0 = syz_open_dev$ptmx(&(0x7f0000000000)='/dev/ptmx\\x00', "
        b"0x0, 0x2)\n"
        b"r1 = syz_open_pts(r0, 0x2)\n")
    assert res.completed
    assert res.info[0].errno == 0
    # pts open can fail in exotic containers (no devpts); accept open
    # errors but require the pseudo-call to have executed
    assert res.info[1].flags & 1  # executed


def test_syz_emit_ethernet_no_tun(linux_target):
    # without ENABLE_TUN the call must fail cleanly with ENODEV (19)
    res = _run_text(
        linux_target,
        b"syz_emit_ethernet(0xe, &(0x7f0000000000)=\""
        + b"aa" * 14 + b"\")\n")
    assert res.completed
    assert res.info[0].errno == 19  # ENODEV


def test_namespace_sandbox_and_tun_flags(linux_target):
    # namespace sandbox + tun + cgroups are best-effort: the env must
    # come up and run programs whether or not the kernel grants them
    res = _run_text(linux_target,
                    b"getpid()\n",
                    sandbox="namespace", tun=True, cgroups=True)
    assert res.completed
    assert res.info[0].errno == 0


def test_syz_genetlink_family(linux_target):
    res = _run_text(
        linux_target,
        b"syz_genetlink_get_family_id(&(0x7f0000000000)='nlctrl\\x00')\n")
    assert res.completed
    info = res.info[0]
    # on hosts with genetlink the call succeeds; otherwise clean errno
    assert info.flags & 1


def test_kvm_descriptions_compile(linux_target):
    names = {c.name for c in linux_target.syscalls}
    for n in ("openat$kvm", "ioctl$KVM_CREATE_VM", "ioctl$KVM_CREATE_VCPU",
              "ioctl$KVM_RUN", "syz_kvm_setup_cpu"):
        assert n in names
    kvm = next(c for c in linux_target.syscalls
               if c.name == "syz_kvm_setup_cpu")
    assert kvm.nr == 0x81000008


def test_syz_kvm_setup_cpu_live(linux_target):
    if not os.path.exists("/dev/kvm"):
        pytest.skip("no /dev/kvm")
    res = _run_text(
        linux_target,
        b"r0 = openat$kvm(0xffffffffffffff9c, "
        b"&(0x7f0000000000)='/dev/kvm\\x00', 0x2, 0x0)\n"
        b"r1 = ioctl$KVM_CREATE_VM(r0, 0xae01, 0x0)\n"
        b"r2 = ioctl$KVM_CREATE_VCPU(r1, 0xae41, 0x0)\n"
        b"syz_kvm_setup_cpu(r1, r2, &(0x7f0000100000)=\"\"/98304, "
        b"&(0x7f0000000100)=[{0x0, &(0x7f0000000200)=\"f4\", 0x1}], "
        b"0x1, 0x0)\n")
    assert res.completed
    for i, info in enumerate(res.info):
        assert info.errno == 0, f"call {i} errno={info.errno}"


def test_real_sctp_socket_and_sockopt(linux_target):
    """Round-4 family smoke: SCTP socket + struct sockopt execute on
    the host kernel (or fail with a clean errno where the protocol is
    not built in — either way the executor path works end to end)."""
    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        b"r0 = socket$inet_sctp(0x2, 0x1, 0x84)\n"
        b"setsockopt$inet_sctp_SCTP_INITMSG(r0, 0x84, 0x2, "
        b"&(0x7f0000000000)={0x4, 0x4, 0x2, 0x3e8}, 0x8)\n"
        b"getsockopt$inet_sctp_SCTP_STATUS(r0, 0x84, 0xe, "
        b"&(0x7f0000001000)={0x0}, &(0x7f0000002000)=0xe8)\n"
    )
    p = deserialize_prog(linux_target, text)
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        # socket() either works (sctp module present) or EPROTONOSUPPORT
        # / EAFNOSUPPORT; any of those proves dispatch+decode worked.
        import errno as e
        assert res.info[0].errno in (0, e.EPROTONOSUPPORT, e.EAFNOSUPPORT,
                                     e.ESOCKTNOSUPPORT, e.EPERM)
    finally:
        env.close()


def test_real_tcp_sockopt_variants(linux_target):
    """Round-4 family smoke: TCP_CONGESTION string opt, TCP_REPAIR,
    MD5SIG struct layout, and TCP_INFO readback on a real TCP socket."""
    import errno as e

    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        b"r0 = socket$inet_tcp(0x2, 0x1, 0x0)\n"
        b"setsockopt$inet_tcp_TCP_CONGESTION(r0, 0x6, 0xd, "
        b"&(0x7f0000000000)='cubic\\x00', 0x6)\n"
        b"setsockopt$inet_tcp_TCP_REPAIR(r0, 0x6, 0x13, "
        b"&(0x7f0000003000)=0x1, 0x4)\n"
        b"setsockopt$inet_tcp_TCP_MD5SIG(r0, 0x6, 0xe, "
        b"&(0x7f0000004000)={@in={{0x2, 0x0, @loopback}}, 0x0, 0x0, "
        b"0x4, 0x0, \"deadbeef\"}, 0xd8)\n"
        b"getsockopt$inet_tcp_TCP_INFO(r0, 0x6, 0xb, "
        b"&(0x7f0000001000)=\"\"/232, &(0x7f0000002000)=0xe8)\n"
    )
    p = deserialize_prog(linux_target, text)
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        assert res.info[0].errno == 0  # plain TCP socket must work
        assert res.info[1].errno == 0  # cubic is always available
        # repair needs CAP_NET_ADMIN: 0 as root, EPERM otherwise —
        # EINVAL would mean the layout/dispatch is broken
        assert res.info[2].errno in (0, e.EPERM)
        # md5sig on a closed socket: 0 or EINVAL-free alternatives;
        # the kernel accepts keys on unconnected sockets
        assert res.info[3].errno in (0, e.EPERM, e.ENOMEM)
        assert res.info[4].errno == 0
    finally:
        env.close()


def test_real_inet6_mcast_group_req(linux_target):
    """Round-4 family smoke: protocol-independent multicast join via
    128-byte group_req storage layout."""
    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        b"r0 = socket$inet_udp(0x2, 0x2, 0x0)\n"
        b"setsockopt$inet_MCAST_JOIN_GROUP(r0, 0x0, 0x2a, "
        b"&(0x7f0000000000)={0x0, 0x0, @in={{0x2, 0x0, "
        b"@multicast=0xe0000001}}}, 0x88)\n"
    )
    p = deserialize_prog(linux_target, text)
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        assert res.info[0].errno == 0
        # join may fail without a default route; errno just must be
        # sane (0 / ENODEV / EADDRNOTAVAIL), not EINVAL-on-layout
        import errno as e
        assert res.info[1].errno in (0, e.ENODEV, e.EADDRNOTAVAIL,
                                     e.ENOBUFS)
    finally:
        env.close()


def test_real_typed_netlink_families(linux_target):
    """Round-4 family smoke: xfrm SA flush, audit status query, and a
    traffic-shaping qdisc get run against the host kernel's netlink
    stacks (families compiled out degrade to clean socket errnos)."""
    import errno as e

    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        # xfrm: FLUSHSA (no payload body beyond proto byte)
        b"r0 = socket$nl_xfrm(0x10, 0x3, 0x6)\n"
        b"sendmsg$nl_xfrm(r0, &(0x7f0000000000)={0x0, 0x0, "
        b"&(0x7f0000000100)={&(0x7f0000000200)=@flushsa={{0x18, 0x1c, "
        b"0x1, 0x0, 0x0, 0x32}}, 0x18}}, 0x0)\n"
        # audit: AUDIT_GET
        b"r1 = socket$nl_audit(0x10, 0x3, 0x9)\n"
        b"sendmsg$auditctl(r1, &(0x7f0000001000)={0x0, 0x0, "
        b"&(0x7f0000001100)={&(0x7f0000001200)=@get={{0x10, 0x3e8, "
        b"0x1, 0x0, 0x0}}, 0x10}}, 0x0)\n"
        # tc: GETQDISC dump
        b"r2 = socket$nl_route(0x10, 0x3, 0x0)\n"
        b"sendmsg$nl_route_sched(r2, &(0x7f0000002000)={0x0, 0x0, "
        b"&(0x7f0000002100)={&(0x7f0000002200)=@getqdisc={{0x24, 0x26, "
        b"0x301, 0x0, 0x0, {0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0}}}, "
        b"0x24}}, 0x0)\n"
    )
    p = deserialize_prog(linux_target, text)
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        errs = [ci.errno for ci in res.info]
        # sockets: 0 or family-not-built; sendmsgs on good sockets
        # must be accepted by the framing layer (0 / EPERM / ENOENT,
        # never a framing EINVAL when the socket opened)
        ok_send = (0, e.EPERM, e.ENOENT, e.EOPNOTSUPP)
        for sock_i, send_i in ((0, 1), (2, 3), (4, 5)):
            assert errs[sock_i] in (0, e.EPROTONOSUPPORT,
                                    e.EAFNOSUPPORT), errs
            if errs[sock_i] == 0:
                assert errs[send_i] in ok_send, errs
    finally:
        env.close()
