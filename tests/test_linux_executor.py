"""Real-OS executor backend: benign handcrafted programs issue actual
syscalls on the build host (no VM needed — the same pattern as the
reference's host-side ipc tests, pkg/ipc/ipc_test.go).

Programs here are hand-built from known-safe calls only; random
generated programs are never executed against the host kernel.
"""

import os

import pytest

from syzkaller_tpu.ipc.env import ExecOpts, make_env
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.prog import (Call, ConstArg, DataArg, PointerArg,
                                       Prog, make_return_arg)
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def linux_target():
    return get_target("linux", "amd64")


def _call(target, name, args):
    meta = next(c for c in target.syscalls if c.name == name)
    return Call(meta=meta, args=args, ret=make_return_arg(meta.ret))


def _getpid_prog(target):
    return Prog(target=target, calls=[_call(target, "getpid", [])])


def test_real_getpid(linux_target):
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(
            _getpid_prog(linux_target)))
        assert res.completed
        info = res.info[0]
        assert info.errno == 0
        # the executor forked per-program? no — same process pool, so
        # the pid must be the executor's own (a real, positive pid)
        assert len(info.signal) > 0  # synthetic or kcov edges flow
    finally:
        env.close()


def test_real_open_read_devnull(linux_target):
    """A description-compiled program (text -> typed -> exec bytes)
    issues real syscalls and threads the fd result through — the
    end-to-end gate on the compiled linux model."""
    from syzkaller_tpu.models.encoding import deserialize_prog

    text = (
        b"r0 = openat(0xffffffffffffff9c, "
        b"&(0x7f0000000000)='/dev/null\\x00', 0x0, 0x0)\n"
        b"read(r0, &(0x7f0000001000)=\"\"/16, 0x10)\n"
    )
    p = deserialize_prog(linux_target, text)
    assert p.calls[1].args[0].res is p.calls[0].ret, \
        "fd result edge not threaded by the parser"
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
        assert res.info[0].errno == 0, "openat(/dev/null) failed"
        assert res.info[1].errno == 0, "read(fd) failed — result arg " \
            "did not thread the real fd"
    finally:
        env.close()


def test_real_bad_call_errno(linux_target):
    """A call with an invalid argument must report the real errno."""
    target = linux_target
    from syzkaller_tpu.models.prog import ResultArg

    meta = next(c for c in target.syscalls if c.name == "close")
    p = Prog(target=target, calls=[
        Call(meta=meta, args=[ResultArg(meta.args[0], val=0xFFFFFFFF)],
             ret=make_return_arg(meta.ret))])
    env = make_env(0, sim=False)
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.info[0].errno == 9  # EBADF
    finally:
        env.close()
