"""Bluetooth / DRM / ashmem model families + syz_init_net_socket
(reference: sys/linux/socket_bluetooth.txt, dri.txt, ashmem.txt)."""

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def linux():
    return get_target("linux", "amd64")


def test_family_counts(linux):
    names = {c.name for c in linux.syscalls}
    bt = [n for n in names if "bt_" in n or n.startswith(
        ("ioctl$HIDP", "ioctl$CMTP", "ioctl$BNEP"))]
    drm = [n for n in names if "DRM_IOCTL" in n or "$dri" in n]
    ash = [n for n in names if "ashmem" in n.lower() or "ASHMEM" in n]
    assert len(bt) >= 55, bt
    assert len(drm) >= 55, drm
    assert len(ash) >= 9, ash
    assert len(names) >= 2050  # past reference's 1,986 declared variants


def test_init_net_socket_nr(linux):
    by = {c.name: c for c in linux.syscalls}
    assert by["syz_init_net_socket$bt_hci"].nr == 2164260875
    assert by["syz_init_net_socket$bt_sco"].nr == 2164260875
    # HCI ioctl table resolved (spot value: HCIDEVUP = _IOW('H',201,int))
    hci = by["ioctl$sock_bt_hci"]
    assert 1074022601 in hci.args[1].vals


def test_drm_ioctl_encodings(linux):
    by = {c.name: c for c in linux.syscalls}
    assert by["ioctl$DRM_IOCTL_VERSION"].args[1].val == 3225445376
    assert by["ioctl$DRM_IOCTL_GEM_OPEN"].args[1].val == 3222299659
    assert by["ioctl$DRM_IOCTL_MODE_GETCRTC"].args[1].val == 3228066977
    # resource flow: GEM_OPEN consumes a name, produces a handle
    gem = by["ioctl$DRM_IOCTL_GEM_OPEN"]
    assert gem.args[2].elem.fields[0].name == "drm_gem_name"


def test_generate_serialize_roundtrip(linux):
    for seed in (11, 12, 13):
        p = generate_prog(linux, RandGen(linux, seed), 12)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(linux, s)) == s


def test_executor_init_net_socket(linux):
    """syz_init_net_socket returns a usable socket fd (falls back to
    the current netns without privileges)."""
    import os

    from tests.test_linux_executor import _run_text

    if not os.path.exists("/proc/1/ns/net"):
        pytest.skip("no /proc/1/ns/net")
    text = (b"r0 = syz_init_net_socket$bt_hci(0x1f, 0x3, 0x1)\n")
    res = _run_text(linux, text)
    assert res.completed
    # AF_BLUETOOTH may be compiled out of the host kernel; accept
    # EAFNOSUPPORT/EPROTONOSUPPORT but not a crash
    assert res.info[0].errno in (0, 97, 93, 22)
