"""Long-tail components: KD decoder, const extraction, cover report,
VM backend registry."""

import struct

import pytest

from syzkaller_tpu.manager.cover import CoverReporter
from syzkaller_tpu.sys.extract import extract_consts, write_const_file
from syzkaller_tpu.utils import kd


# -- kd ------------------------------------------------------------------


def _kd_print_packet(text: bytes) -> bytes:
    body = struct.pack("<I", kd.DBGKD_PRINT_STRING) + b"\x00" * 8 \
        + struct.pack("<I", len(text)) + text
    hdr = kd.PACKET_LEADER + struct.pack(
        "<HHII", kd.PACKET_TYPE_KD_DEBUG_IO, len(body), 1, 0)
    return hdr + body + b"\xaa"


def test_kd_decode_print():
    pkt = _kd_print_packet(b"Assertion failed: foo.c:42\n")
    text, rest = kd.decode(b"boot text\n" + pkt + b"tail")
    assert b"boot text" in text
    assert b"Assertion failed: foo.c:42" in text
    assert rest == b""


def test_kd_incomplete_packet_buffered():
    pkt = _kd_print_packet(b"hello from the kernel")
    text1, rest = kd.decode(pkt[:20])
    assert rest  # incomplete: buffered for the next chunk
    text2, rest2 = kd.decode(rest + pkt[20:])
    assert b"hello from the kernel" in text2
    assert rest2 == b""


def test_kd_raw_passthrough():
    text, rest = kd.decode(b"plain console line\x00\x01\xff ok\n")
    assert b"plain console line ok\n" == text


# -- extract -------------------------------------------------------------


def test_extract_consts(tmp_path):
    vals = extract_consts(["O_RDONLY", "O_CREAT", "PROT_READ",
                           "MAP_PRIVATE", "NOT_A_REAL_CONST_XYZ"])
    assert vals["O_RDONLY"] == 0
    assert vals["PROT_READ"] == 1
    assert vals["MAP_PRIVATE"] == 2
    assert vals["NOT_A_REAL_CONST_XYZ"] is None
    out = tmp_path / "test.const"
    write_const_file(str(out), vals)
    content = out.read_text()
    assert "PROT_READ = 1" in content
    assert "# NOT_A_REAL_CONST_XYZ is not defined" in content


def test_extract_syscall_numbers():
    vals = extract_consts(["__NR_openat", "__NR_read"])
    assert vals["__NR_openat"] == 257  # amd64 ABI
    assert vals["__NR_read"] == 0


# -- cover reporter ------------------------------------------------------


def test_cover_report_without_vmlinux():
    r = CoverReporter("")
    html = r.render_html([0xFFFF800012345678, 0xFFFF800012345679])
    assert "2 PCs covered" in html
    assert "0xffff800012345678" in html


def test_cover_report_with_real_binary():
    """Use the executor binary itself as the 'kernel' — nm+addr2line
    work on any ELF."""
    from syzkaller_tpu.ipc.env import build_executor

    binpath = str(build_executor())
    r = CoverReporter(binpath)
    r._load_symbols()
    if not r._addr_index:
        pytest.skip("no symbols in executor binary")
    addr, end, name = r._addr_index[len(r._addr_index) // 2]
    assert r.func_of(addr) == name
    per_fn = r.per_function([addr, addr + 1 if addr + 1 < end else addr])
    assert name in per_fn


# -- VM registry ---------------------------------------------------------


def test_all_vm_types_registered():
    from syzkaller_tpu.vm.vmimpl import _CTORS, create_pool_impl, Env

    with pytest.raises(ValueError):
        create_pool_impl("definitely-not-a-backend", Env())
    for typ in ("local", "qemu", "isolated", "adb", "gce", "kvm",
                "odroid"):
        assert typ in _CTORS, f"backend {typ} not registered"


def test_kcovtrace_compiles(tmp_path):
    import subprocess

    out = str(tmp_path / "kcovtrace")
    res = subprocess.run(["gcc", "-O2", "-o", out,
                          "executor/kcovtrace.c"], capture_output=True)
    assert res.returncode == 0, res.stderr.decode()
