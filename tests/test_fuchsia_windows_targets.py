"""fuchsia/amd64 + windows/amd64 model targets (VERDICT r4 missing
#4): the OS-tree breadth beyond linux + BSDs — a handle-centric
Zircon model and a typed Win32 model, each compiled from its own
description tree + ABI const table + arch hooks (reference:
sys/fuchsia/*.txt, sys/windows/windows.txt, sys/targets/targets.go)."""

from __future__ import annotations

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def fuchsia():
    return get_target("fuchsia", "amd64")


@pytest.fixture(scope="module")
def windows():
    return get_target("windows", "amd64")


def test_both_compile_with_nothing_disabled():
    from syzkaller_tpu.sys.sysgen import compile_os

    for osn, floor in (("fuchsia", 65), ("windows", 75)):
        res = compile_os(osn, "amd64", register=False)
        assert res.disabled_calls == [], osn
        assert len(res.target.syscalls) >= floor, osn


def test_fuchsia_handle_model(fuchsia):
    by_name = {c.name: c for c in fuchsia.syscalls}
    # the channel pair produces typed channel handles consumed by
    # write/read/call — the resource graph, not flat ints
    create = by_name["zx_channel_create"]
    assert create.args[1].elem.name == create.args[2].elem.name
    assert "zx_channel" in create.args[1].elem.name
    # rights constants resolved from the hand const table
    from syzkaller_tpu.sys.sysgen import load_os_consts

    k = load_os_consts("fuchsia")
    assert k["ZX_RIGHT_SAME_RIGHTS"] == 1 << 31
    assert k["ZX_VM_PERM_READ"] == 1


def test_windows_handle_model(windows):
    names = {c.name for c in windows.syscalls}
    for fam in ("CreateFileA", "ReadFile", "WriteFile", "CloseHandle",
                "VirtualAlloc", "RegCreateKeyExA", "CreateEventA",
                "WaitForSingleObject", "CreateNamedPipeA"):
        assert fam in names, fam


@pytest.mark.parametrize("osn", ["fuchsia", "windows"])
def test_generate_mutate_roundtrip(osn, iters):
    t = get_target(osn, "amd64")
    for i in range(max(iters, 20)):
        p = generate_prog(t, RandGen(t, 9100 + i), 8)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(t, s)) == s
        mutate_prog(p, RandGen(t, i), 16, corpus=[p.clone()])
        serialize_for_exec(p)


def test_make_mmap_hooks(fuchsia, windows):
    for t in (fuchsia, windows):
        c = t.make_mmap(t.data_offset, t.page_size * 4)
        assert c.meta.name in ("zx_vmar_map", "VirtualAlloc")


def test_akaros_target_generates():
    t = get_target("akaros", "amd64")
    assert len(t.syscalls) >= 40
    p = generate_prog(t, RandGen(t, 3), 8)
    s = serialize_prog(p)
    assert serialize_prog(deserialize_prog(t, s)) == s


def test_seven_os_trees_registered():
    """OS-tree parity with the reference's sys/ (VERDICT missing #4):
    linux, freebsd, netbsd, fuchsia, windows, akaros + the hermetic
    test target."""
    for osn, arch in (("linux", "amd64"), ("freebsd", "amd64"),
                      ("netbsd", "amd64"), ("fuchsia", "amd64"),
                      ("windows", "amd64"), ("akaros", "amd64"),
                      ("test", "64")):
        t = get_target(osn, arch)
        assert len(t.syscalls) > 0, osn


def test_fuchsia_arm64_shares_the_model():
    """Zircon calls dispatch by vDSO name (no per-arch NR table), so
    the arm64 target is the same model against its own const file —
    the reference ships sys/fuchsia/*_arm64.const identically."""
    a64 = get_target("fuchsia", "arm64")
    amd = get_target("fuchsia", "amd64")
    assert {c.name for c in a64.syscalls} == \
        {c.name for c in amd.syscalls}
    p = generate_prog(a64, RandGen(a64, 5), 8)
    s = serialize_prog(p)
    assert serialize_prog(deserialize_prog(a64, s)) == s
