"""CLI tools tests (reference behaviors: tools/syz-*)."""

import json
import os

import pytest

from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen


def _write_prog(tmp_path, target, seed=1, name="p.prog"):
    p = generate_prog(target, RandGen(target, seed), 4)
    path = tmp_path / name
    path.write_bytes(serialize_prog(p))
    return path, p


def test_mutate_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.mutate import main

    path, p = _write_prog(tmp_path, test_target)
    assert main([str(path), "-seed", "7"]) == 0
    out = capsys.readouterr().out
    assert "(" in out  # program text
    # deterministic under the same seed
    assert main([str(path), "-seed", "7"]) == 0
    assert capsys.readouterr().out == out


def test_execprog_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.execprog import main

    path, _ = _write_prog(tmp_path, test_target)
    assert main([str(path), "-repeat", "2", "-cover"]) == 0
    out = capsys.readouterr().out
    assert "executed 2 programs" in out
    assert "call #0" in out


def test_prog2c_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.prog2c import main

    path, _ = _write_prog(tmp_path, test_target)
    assert main([str(path), "-repeat", "-procs", "2"]) == 0
    out = capsys.readouterr().out
    assert "int main" in out


def test_db_tool_roundtrip(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.db_tool import main

    src = tmp_path / "progs"
    src.mkdir()
    for i in range(3):
        p = generate_prog(test_target, RandGen(test_target, i), 3)
        (src / f"p{i}").write_bytes(serialize_prog(p))
    db = str(tmp_path / "corpus.db")
    assert main(["pack", str(src), db]) == 0
    out_dir = tmp_path / "out"
    assert main(["unpack", db, str(out_dir)]) == 0
    assert len(list(out_dir.iterdir())) == 3
    # merge into an empty db
    db2 = str(tmp_path / "corpus2.db")
    assert main(["merge", db2, db]) == 0
    assert "merged 3" in capsys.readouterr().out


def test_benchcmp_tool(tmp_path, capsys):
    from syzkaller_tpu.tools.benchcmp import main

    for name, base in (("old.json", 100), ("new.json", 200)):
        with open(tmp_path / name, "w") as f:
            for i in range(5):
                f.write(json.dumps({"corpus": base + i * 10,
                                    "signal": base * 2 + i,
                                    "ts": i}) + "\n")
    out = str(tmp_path / "cmp.html")
    assert main([str(tmp_path / "old.json"), str(tmp_path / "new.json"),
                 "-o", out]) == 0
    html = open(out).read()
    assert "corpus" in html and "polyline" in html


def test_crush_tool_no_crash(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.crush import main

    p = generate_prog(test_target, RandGen(test_target, 3), 3)
    logf = tmp_path / "log"
    logf.write_bytes(b"executing program 0:\n" + serialize_prog(p))
    rc = main([str(logf), "-duration", "1"])
    assert rc == 3  # replay finished without reproducing any crash


def test_symbolize_tool(tmp_path, capsys):
    from syzkaller_tpu.tools.symbolize import main

    logf = tmp_path / "log"
    logf.write_bytes(
        b"BUG: KASAN: use-after-free in foo_fn+0x11/0x20\n"
        b"Call Trace:\n foo_fn+0x11/0x20\n bar_fn+0x22/0x40\n")
    assert main([str(logf)]) == 0
    out = capsys.readouterr().out
    assert "TITLE: KASAN: use-after-free in foo_fn" in out
    assert "GUILTY: foo_fn" in out


def test_dispatcher_lists_tools(capsys, monkeypatch):
    import syzkaller_tpu.__main__ as m

    monkeypatch.setattr("sys.argv", ["tz", "help"])
    assert m.main() == 0
    out = capsys.readouterr().out
    for tool in ("manager", "fuzzer", "execprog", "repro", "hub"):
        assert tool in out
