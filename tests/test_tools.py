"""CLI tools tests (reference behaviors: tools/syz-*)."""

import json
import os

import pytest

from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen


def _write_prog(tmp_path, target, seed=1, name="p.prog"):
    p = generate_prog(target, RandGen(target, seed), 4)
    path = tmp_path / name
    path.write_bytes(serialize_prog(p))
    return path, p


def test_mutate_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.mutate import main

    path, p = _write_prog(tmp_path, test_target)
    assert main([str(path), "-seed", "7"]) == 0
    out = capsys.readouterr().out
    assert "(" in out  # program text
    # deterministic under the same seed
    assert main([str(path), "-seed", "7"]) == 0
    assert capsys.readouterr().out == out


def test_execprog_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.execprog import main

    path, _ = _write_prog(tmp_path, test_target)
    assert main([str(path), "-repeat", "2", "-cover"]) == 0
    out = capsys.readouterr().out
    assert "executed 2 programs" in out
    assert "call #0" in out


def test_prog2c_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.prog2c import main

    path, _ = _write_prog(tmp_path, test_target)
    assert main([str(path), "-repeat", "-procs", "2"]) == 0
    out = capsys.readouterr().out
    assert "int main" in out


def test_db_tool_roundtrip(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.db_tool import main

    src = tmp_path / "progs"
    src.mkdir()
    for i in range(3):
        p = generate_prog(test_target, RandGen(test_target, i), 3)
        (src / f"p{i}").write_bytes(serialize_prog(p))
    db = str(tmp_path / "corpus.db")
    assert main(["pack", str(src), db]) == 0
    out_dir = tmp_path / "out"
    assert main(["unpack", db, str(out_dir)]) == 0
    assert len(list(out_dir.iterdir())) == 3
    # merge into an empty db
    db2 = str(tmp_path / "corpus2.db")
    assert main(["merge", db2, db]) == 0
    assert "merged 3" in capsys.readouterr().out


def test_benchcmp_tool(tmp_path, capsys):
    from syzkaller_tpu.tools.benchcmp import main

    for name, base in (("old.json", 100), ("new.json", 200)):
        with open(tmp_path / name, "w") as f:
            for i in range(5):
                f.write(json.dumps({"corpus": base + i * 10,
                                    "signal": base * 2 + i,
                                    "ts": i}) + "\n")
    out = str(tmp_path / "cmp.html")
    assert main([str(tmp_path / "old.json"), str(tmp_path / "new.json"),
                 "-o", out]) == 0
    html = open(out).read()
    assert "corpus" in html and "polyline" in html


def test_crush_tool_no_crash(tmp_path, test_target, capsys):
    from syzkaller_tpu.tools.crush import main

    p = generate_prog(test_target, RandGen(test_target, 3), 3)
    logf = tmp_path / "log"
    logf.write_bytes(b"executing program 0:\n" + serialize_prog(p))
    rc = main([str(logf), "-duration", "1"])
    assert rc == 3  # replay finished without reproducing any crash


def test_symbolize_tool(tmp_path, capsys):
    from syzkaller_tpu.tools.symbolize import main

    logf = tmp_path / "log"
    logf.write_bytes(
        b"BUG: KASAN: use-after-free in foo_fn+0x11/0x20\n"
        b"Call Trace:\n foo_fn+0x11/0x20\n bar_fn+0x22/0x40\n")
    assert main([str(logf)]) == 0
    out = capsys.readouterr().out
    assert "TITLE: KASAN: use-after-free in foo_fn" in out
    assert "GUILTY: foo_fn" in out


def test_dispatcher_lists_tools(capsys, monkeypatch):
    import syzkaller_tpu.__main__ as m

    monkeypatch.setattr("sys.argv", ["tz", "help"])
    assert m.main() == 0
    out = capsys.readouterr().out
    for tool in ("manager", "fuzzer", "execprog", "repro", "hub"):
        assert tool in out


# ---- tz-fmt ----------------------------------------------------------

def test_fmt_tool(tmp_path, capsys):
    from syzkaller_tpu.compiler.parser import parse
    from syzkaller_tpu.tools.fmt import format_text, main

    src = ("resource  fd2 [ int32 ] : -1\n"
           "\n"
           "mycall( a  fd2 , b int32 )  fd2\n")
    f = tmp_path / "x.txt"
    f.write_text(src)
    # canonical form parses to the same description and is idempotent
    out = format_text(src)
    assert format_text(out) == out
    assert len(parse(out).decls) == len(parse(src).decls)
    # -d flags the unformatted file
    assert main(["-d", str(f)]) == 1
    # -w rewrites; then -d is clean
    assert main(["-w", str(f)]) == 0
    capsys.readouterr()
    assert main(["-d", str(f)]) == 0
    # parse errors exit 2
    bad = tmp_path / "bad.txt"
    bad.write_text("mycall(((\n")
    assert main([str(bad)]) == 2


def test_fmt_real_descriptions_roundtrip(tmp_path):
    """Formatting the shipped linux descriptions preserves them
    semantically (same decl count after a reparse)."""
    from pathlib import Path

    from syzkaller_tpu.compiler.parser import parse
    from syzkaller_tpu.tools.fmt import format_text

    root = Path(__file__).resolve().parents[1] / \
        "syzkaller_tpu/sys/descriptions/linux"
    for path in sorted(root.glob("*.txt"))[:4]:
        src = path.read_text()
        out = format_text(src, str(path))
        assert len(parse(out, str(path)).decls) == \
            len(parse(src, str(path)).decls), path
        assert format_text(out) == out, f"{path} not idempotent"


# ---- tz-upgrade ------------------------------------------------------

def test_upgrade_tool(tmp_path, test_target, capsys):
    from syzkaller_tpu.db import open_db
    from syzkaller_tpu.db.db import CUR_VERSION
    from syzkaller_tpu.tools.upgrade import main

    dbpath = str(tmp_path / "corpus.db")
    db = open_db(dbpath, version=0)
    for seed in (1, 2):
        _, p = _write_prog(tmp_path, test_target, seed=seed,
                           name=f"p{seed}.prog")
        db.save(f"k{seed}", serialize_prog(p), 0)
    db.save("junk", b"not_a_syscall(0x1)\n", 0)
    db.flush()
    assert main([dbpath]) == 0
    assert "kept 2" in capsys.readouterr().out
    db2 = open_db(dbpath)
    assert db2.version == CUR_VERSION
    assert len(db2.records) == 2


# ---- tz-tty ----------------------------------------------------------

def test_tty_tool_plain(tmp_path, capsys):
    from syzkaller_tpu.tools.tty import main

    log = tmp_path / "console.log"
    log.write_bytes(b"booting...\n"
                    b"BUG: unable to handle kernel NULL pointer "
                    b"dereference at 0000000000000000\n"
                    b"bye\n")
    assert main([str(log)]) == 3  # crash seen
    out = capsys.readouterr().out
    assert "*** CRASH:" in out and "booting..." in out


def test_tty_tool_kd(tmp_path, capsys):
    import struct

    from syzkaller_tpu.tools.tty import main
    from syzkaller_tpu.utils import kd

    text = b"hello from kd\n"
    body = struct.pack("<I", kd.DBGKD_PRINT_STRING) + b"\0" * 8 + \
        struct.pack("<I", len(text)) + text
    pkt = kd.PACKET_LEADER + struct.pack(
        "<HHII", kd.PACKET_TYPE_KD_DEBUG_IO, len(body), 0, 0) + \
        body + b"\xaa"
    log = tmp_path / "kd.bin"
    log.write_bytes(pkt)
    assert main([str(log), "-kd", "-os", "linux"]) == 0
    assert "hello from kd" in capsys.readouterr().out


# ---- tz-imagegen -----------------------------------------------------

def test_imagegen_tool(tmp_path, capsys):
    import subprocess

    from syzkaller_tpu.tools.imagegen import generate, main

    script = generate("bzImage", "disk.raw", "tz-executor")
    assert "mkfs.ext4" in script and "panic_on_warn=1" in script
    assert "busybox" in script
    out = tmp_path / "create-image.sh"
    assert main(["-kernel", "bzImage", "-o", str(out)]) == 0
    assert os.access(out, os.X_OK)
    # the generated script is valid shell
    subprocess.run(["sh", "-n", str(out)], check=True)
    deb = generate("bzImage", "d.raw", "x", userspace="debootstrap")
    assert "debootstrap" in deb


# ---- tz-extract kernel-src mode --------------------------------------

def test_extract_kernel_src_includes(tmp_path):
    """Extraction against a kernel source tree picks up constants the
    host libc doesn't define, via the arch include-path ladder."""
    from syzkaller_tpu.sys.extract import (
        extract_consts, kernel_include_flags)

    # fake kernel tree: include/uapi defines an exotic constant
    uapi = tmp_path / "include" / "uapi" / "linux"
    uapi.mkdir(parents=True)
    (uapi / "tzfake.h").write_text("#define TZ_FAKE_CONST 0xabc\n")
    (tmp_path / "arch" / "x86" / "include" / "uapi").mkdir(parents=True)
    flags = kernel_include_flags(str(tmp_path), "amd64")
    assert "-I" in flags
    # the flags must be usable AS SHIPPED alongside libc headers
    vals = extract_consts(["TZ_FAKE_CONST", "TZ_MISSING"],
                          includes=["<stdio.h>", "<unistd.h>",
                                    "<linux/tzfake.h>"],
                          cflags=flags)
    assert vals["TZ_FAKE_CONST"] == 0xABC
    assert vals["TZ_MISSING"] is None

from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen


def test_parse_tool(tmp_path, capsys):
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.tools.parse_tool import main

    test_target = get_target("test", "64")

    progs = [generate_prog(test_target, RandGen(test_target, s), 3)
             for s in (1, 2)]
    log = b"boot noise\n"
    for i, p in enumerate(progs):
        log += f"{i:02}:00:00 executing program {i}:\n".encode()
        log += serialize_prog(p)
    log += b"tail noise\n"
    f = tmp_path / "console.log"
    f.write_bytes(log)
    assert main([str(f)]) == 0
    out = capsys.readouterr().out
    assert "# proc 0" in out and "# proc 1" in out
    outdir = tmp_path / "progs"
    assert main([str(f), "-o", str(outdir)]) == 0
    assert sorted(os.listdir(outdir)) == ["prog0", "prog1"]
    empty = tmp_path / "empty.log"
    empty.write_bytes(b"nothing here\n")
    assert main([str(empty)]) == 1


def test_headerparser_tool(tmp_path, capsys):
    from syzkaller_tpu.tools.headerparser import main, parse_header

    hdr = tmp_path / "foo.h"
    hdr.write_text("""
/* a comment */
struct foo_req {
        __u32 id;       // inline comment
        __u16 flags;
        __u8  data[16];
        char *name;
        __u64 big : 12;
        struct bar nested;
};
""")
    structs = parse_header(hdr.read_text())
    assert len(structs) == 1
    name, fields = structs[0]
    assert name == "foo_req"
    fmap = {f: t for f, t, _ in fields}
    assert fmap["id"] == "int32"
    assert fmap["flags"] == "int16"
    assert fmap["data"] == "array[int8, 16]"
    assert fmap["name"].startswith("ptr64")
    assert fmap["big"] == "int64:12"
    assert fmap["nested"] == "bar"
    notes = {f: n for f, _, n in fields}
    assert "TODO" in notes["name"] and "TODO" in notes["nested"]
    assert main([str(hdr)]) == 0
    out = capsys.readouterr().out
    assert "foo_req {" in out


def test_headerparser_edge_cases():
    from syzkaller_tpu.tools.headerparser import parse_header

    structs = parse_header("""
struct multi {
        int a, b;
        char *argv[4];
        unsigned long flags;
};
""")
    assert len(structs) == 1
    _, fields = structs[0]
    notes = [n for _, _, n in fields]
    # multi-declarator leaves a visible TODO, never silence
    assert any("could not parse" in n for n in notes)
    fmap = {f: t for f, t, _ in fields}
    # pointer arrays keep their dimension
    assert fmap["argv"] == "array[ptr64[inout, array[int8]], 4]"
    assert fmap["flags"] == "intptr"


# -- metric-name linter (tools/lint_metrics, ISSUE 2) -------------------


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_metrics_repo_is_clean(capsys):
    """The tier-1 wrapper for the linter: the live tree's metric names
    and the docs/observability.md catalogue must agree exactly."""
    from syzkaller_tpu.tools.lint_metrics import lint, main

    assert lint(REPO_ROOT) == []
    assert main([REPO_ROOT]) == 0
    assert "lint_metrics: ok" in capsys.readouterr().out


def _lint_tree(tmp_path, source: str, docs: str):
    from syzkaller_tpu.tools.lint_metrics import lint

    pkg = tmp_path / "syzkaller_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(source)
    docs_dir = tmp_path / "docs"
    docs_dir.mkdir()
    (docs_dir / "observability.md").write_text(docs)
    return lint(str(tmp_path))


def test_lint_metrics_flags_unregistered_literal(tmp_path):
    problems = _lint_tree(
        tmp_path,
        'c = telemetry.counter("tz_good_total", "ok")\n'
        'snap["tz_typo_total"] += 1\n',
        "catalogue: `tz_good_total`\n")
    assert any("tz_typo_total" in p and "never registered" in p
               for p in problems)


def test_lint_metrics_flags_docs_drift_both_ways(tmp_path):
    problems = _lint_tree(
        tmp_path,
        'c = telemetry.counter(\n    "tz_undocumented_total")\n'
        'with telemetry.span("phase.work"):\n    pass\n',
        "catalogue: `tz_phase_work_seconds` and `tz_stale_total`\n")
    # multi-line registration and span names are both recognized
    assert any("tz_undocumented_total" in p and "missing from" in p
               for p in problems)
    assert any("tz_stale_total" in p and "not registered" in p
               for p in problems)
    assert not any("tz_phase_work_seconds" in p for p in problems)


def test_lint_metrics_flags_span_event_name_drift(tmp_path):
    """ISSUE 6 satellite: span names, timeline-event names, and
    lineage hop stages are cross-checked against the doc catalogue —
    both directions, namespace-filtered so prose like
    `time.perf_counter` never false-positives."""
    problems = _lint_tree(
        tmp_path,
        'with telemetry.span("phase.work"):\n    pass\n'
        'telemetry.record_event("phase.trip", "detail")\n'
        'lineage.hop(ctx, "phase.hop")\n',
        "catalogue: `tz_phase_work_seconds` `tz_phase_trip_x` ok\n"
        "spans: `phase.work` `phase.trip` `phase.stale`\n"
        "prose: `time.perf_counter` and `mod.py` stay unflagged\n")
    assert any(p.startswith("phase.hop:") and "missing from" in p
               for p in problems)
    assert any(p.startswith("phase.stale:") and "not used" in p
               for p in problems)
    for name in ("phase.work", "phase.trip", "time.perf_counter",
                 "mod.py"):
        assert not any(p.startswith(f"{name}:") for p in problems), \
            (name, problems)


# -- SLO-table linter (tools/lint_slo, ISSUE 14) ------------------------


def test_lint_slo_repo_is_clean(capsys):
    """Tier-1 wrapper: the live SLO_TABLE must be internally
    consistent and every objective's source metric must exist."""
    from syzkaller_tpu.tools.lint_slo import lint, main

    assert lint(REPO_ROOT) == []
    assert main([REPO_ROOT]) == 0
    assert "lint_slo: ok" in capsys.readouterr().out


def test_lint_slo_flags_broken_table():
    from syzkaller_tpu.tools.lint_slo import lint

    bad = [
        # default outside the clamp range: the knob could never set it
        {"name": "a", "kind": "floor", "env": "TZ_SLO_A",
         "default": 5.0, "lo": 0.0, "hi": 1.0, "budget": 0.1,
         "metric": "tz_pipeline_mutants_total", "help": "x"},
        # zero budget (burn would divide by it) + unknown metric
        {"name": "b", "kind": "sideways", "env": "TZ_SLO_B",
         "default": 0.5, "lo": 0.0, "hi": 1.0, "budget": 0.0,
         "metric": "tz_never_registered_total", "help": "x"},
        {"name": "b", "kind": "ceiling", "env": "TZ_B",
         "default": 0.5, "lo": 0.0, "hi": 1.0, "budget": 0.1,
         "metric": None, "help": "x"},
    ]
    problems = lint(REPO_ROOT, table=bad, fast_s=600.0, slow_s=300.0)
    assert any("windows inverted" in p for p in problems)
    assert any("[a]" in p and "outside its own clamp range" in p
               for p in problems)
    assert any("[b]" in p and "sideways" in p for p in problems)
    assert any("[b]" in p and "budget" in p for p in problems)
    assert any("[b]" in p and "tz_never_registered_total" in p
               for p in problems)
    assert any("[b]" in p and "duplicate" in p for p in problems)
    assert any("'TZ_B'" in p and "must be TZ_SLO_" in p
               for p in problems)
