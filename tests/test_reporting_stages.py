"""Two-stage (moderation -> public) reporting with access levels
(VERDICT r3 item #7; reference: dashboard/app/reporting.go Reporting
lists + access.go levels).

A bug in a namespace configured with [moderation(admin), public]
flows: new -> reported@moderation (visible to admins only) ->
'#syz upstream' email -> new@public -> reported@public (visible to
everyone) -> '#syz fix:' -> fixed.  A second namespace with the
legacy single public stage reports directly at public access.
"""

from __future__ import annotations

from email.message import EmailMessage

import pytest

from syzkaller_tpu.dashboard.app import (
    ACCESS_ADMIN,
    ACCESS_PUBLIC,
    ACCESS_USER,
    STATUS_FIXED,
    STATUS_NEW,
    STATUS_REPORTED,
    Dashboard,
    ReportingStage,
)
from syzkaller_tpu.email import EmailReporting, Mailbox, parse_email


@pytest.fixture
def dash(tmp_path):
    return Dashboard(
        str(tmp_path),
        clients={
            "mod-mgr": {"key": "k1", "namespace": "moderated"},
            "pub-mgr": {"key": "k2", "namespace": "open"},
        },
        reporting={
            "moderated": [
                ReportingStage("moderation", ACCESS_ADMIN, 0.0),
                ReportingStage("public", ACCESS_PUBLIC, 0.0),
            ],
            # "open" gets the default single public stage
        })


def _crash(dash, client, key, title):
    return dash.report_crash({
        "client": client, "key": key, "manager": client,
        "title": title, "log": "log", "report": "rep",
    })["bug_id"]


def _reply(reporting, commands, report_raw=None):
    if report_raw is None:
        report_raw = reporting.mailbox.outgoing[-1]
    rep = parse_email(report_raw)
    m = EmailMessage()
    m["Subject"] = "Re: " + rep.subject
    m["From"] = "moderator@kernel.org"
    m["To"] = rep.from_addr
    m["In-Reply-To"] = rep.msg_id
    m["Message-ID"] = f"<r{len(reporting.mailbox.outgoing)}@k.org>"
    m.set_content(commands + "\n")
    reporting.mailbox.deliver(bytes(m))


def test_moderation_to_public_flow(dash):
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    bug_id = _crash(dash, "mod-mgr", "k1", "KASAN: use-after-free in a")

    bug = dash.bugs[bug_id]
    assert bug.status == STATUS_NEW
    assert dash.bug_stage(bug).name == "moderation"

    # Stage 1: reported at moderation, admin-access only.
    assert reporting.poll_and_send() == 1
    bug = dash.bugs[bug_id]
    assert bug.status == STATUS_REPORTED
    assert bug.reporting_stage == "moderation"
    assert dash.bug_access(bug) == ACCESS_ADMIN
    admin_ids = {b.id for b in dash.visible_bugs(ACCESS_ADMIN)}
    public_ids = {b.id for b in dash.visible_bugs(ACCESS_PUBLIC)}
    user_ids = {b.id for b in dash.visible_bugs(ACCESS_USER)}
    assert bug_id in admin_ids
    assert bug_id not in public_ids and bug_id not in user_ids

    # Moderator upstreams -> back to NEW at the public stage.
    _reply(reporting, "#syz upstream")
    assert reporting.process_incoming() == 1
    bug = dash.bugs[bug_id]
    assert bug.status == STATUS_NEW
    assert dash.bug_stage(bug).name == "public"

    # Stage 2: re-reported publicly with a fresh mail thread.
    n_before = len(mbox.outgoing)
    assert reporting.poll_and_send() == 1
    bug = dash.bugs[bug_id]
    assert bug.status == STATUS_REPORTED
    assert bug.reporting_stage == "public"
    assert dash.bug_access(bug) == ACCESS_PUBLIC
    assert bug_id in {b.id for b in dash.visible_bugs(ACCESS_PUBLIC)}
    assert len(mbox.outgoing) == n_before + 1  # new report mail

    # Fix closes it from the public thread.
    _reply(reporting, "#syz fix: net: fix uaf in a")
    assert reporting.process_incoming() == 1
    assert dash.bugs[bug_id].status == STATUS_FIXED


def test_single_stage_namespace_reports_publicly(dash):
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    bug_id = _crash(dash, "pub-mgr", "k2", "WARNING in b")
    assert reporting.poll_and_send() == 1
    bug = dash.bugs[bug_id]
    assert bug.reporting_stage == "public"
    assert dash.bug_access(bug) == ACCESS_PUBLIC
    assert bug_id in {b.id for b in dash.visible_bugs(ACCESS_PUBLIC)}
    # upstream on a last-stage bug is a user error -> nack mail
    _reply(reporting, "#syz upstream")
    n_out = len(mbox.outgoing)
    assert reporting.process_incoming() == 0
    assert len(mbox.outgoing) == n_out + 1  # the nack
    assert b"already at the last" in mbox.outgoing[-1]


def test_two_namespaces_do_not_cross(dash):
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    a = _crash(dash, "mod-mgr", "k1", "BUG: t")
    b = _crash(dash, "pub-mgr", "k2", "BUG: t")
    assert a != b  # same title, different namespaces -> distinct bugs
    assert reporting.poll_and_send() == 2
    assert dash.bugs[a].reporting_stage == "moderation"
    assert dash.bugs[b].reporting_stage == "public"
