"""Execution-stack tests: native executor + IPC layer.

Spawns the real C++ tz-executor binary (built on demand) with the sim
kernel backend and drives serialized programs through the full
copyin/exec/copyout/signal pipeline — the hermetic analogue of the
reference's executor tests (reference: pkg/ipc/ipc_test.go,
executor/test_executor_linux.cc via executor/test.go).
"""

import threading

import numpy as np
import pytest

from syzkaller_tpu.ipc import (
    CallFlags,
    ExecFlags,
    ExecOpts,
    ExecutorCrash,
    Gate,
    build_executor,
    make_env,
)
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def env():
    build_executor()
    e = make_env(pid=0, sim=True, signal=True)
    yield e
    e.close()


@pytest.fixture(scope="module")
def target():
    return get_target("test", "64")


def _gen(target, seed, ncalls=6):
    return generate_prog(target, RandGen(target, seed), ncalls)


def test_exec_basic(env, target):
    p = _gen(target, 1)
    res = env.exec(ExecOpts(), serialize_for_exec(p))
    assert res.completed
    assert len(res.info) == len(p.calls)
    for ci, call in zip(res.info, p.calls):
        assert ci.call_id == call.meta.id
        assert ci.flags & CallFlags.EXECUTED
        assert ci.flags & CallFlags.FINISHED
        assert len(ci.signal) > 0  # sim kernel always yields edges


def test_exec_deterministic(env, target):
    """Same program twice → identical signal (fresh handles aside, the
    sim kernel is deterministic for a fresh process)."""
    p = _gen(target, 2)
    data = serialize_for_exec(p)
    r1 = env.exec(ExecOpts(), data)
    r2 = env.exec(ExecOpts(), data)
    for a, b in zip(r1.info, r2.info):
        assert a.errno == b.errno


def test_exec_many_programs(env, target):
    """Fork-server loop: many programs through one executor process."""
    restarts_before = env.stat_restarts
    for seed in range(30):
        p = _gen(target, 100 + seed, ncalls=4)
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.completed
    assert env.stat_restarts == restarts_before  # no respawns needed


def test_cover_collection(env, target):
    p = _gen(target, 3)
    res = env.exec(ExecOpts(flags=ExecFlags.COLLECT_COVER),
                   serialize_for_exec(p))
    assert any(len(ci.cover) > 0 for ci in res.info)
    # cover is raw PCs; signal is edge-hashed so generally differs
    ci = res.info[0]
    assert ci.cover.dtype == np.uint32


def test_comps_collection(env, target):
    p = _gen(target, 4)
    res = env.exec(ExecOpts(flags=ExecFlags.COLLECT_COMPS),
                   serialize_for_exec(p))
    allcomps = [c for ci in res.info for c in ci.comps]
    assert allcomps, "sim kernel must emit comparisons"
    ops1 = {a for a, _ in allcomps}
    assert len(ops1) >= 1


def test_threaded_and_collide(env, target):
    p = _gen(target, 5)
    data = serialize_for_exec(p)
    res = env.exec(ExecOpts(flags=ExecFlags.THREADED), data)
    assert len(res.info) == len(p.calls)
    res = env.exec(ExecOpts(flags=ExecFlags.THREADED | ExecFlags.COLLIDE),
                   data)
    assert len(res.info) == len(p.calls)


def test_fault_injection(env, target):
    p = _gen(target, 6, ncalls=3)
    data = serialize_for_exec(p)
    hit = False
    for nth in range(3):
        res = env.exec(
            ExecOpts(flags=ExecFlags.FAULT, fault_call=0, fault_nth=nth),
            data)
        if res.info and res.info[0].flags & CallFlags.FAULT_INJECTED:
            assert res.info[0].errno == 12  # ENOMEM
            hit = True
            break
    assert hit, "fault injection never fired"


def test_signal_gradient(env, target):
    """Different programs yield different signal: the sim kernel gives
    the fuzzer a real gradient."""
    sigs = set()
    for seed in range(8):
        p = _gen(target, 300 + seed, ncalls=3)
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        for ci in res.info:
            sigs.update(int(s) for s in ci.signal)
    assert len(sigs) > 20


def test_crash_detection(env, target):
    """Force the sim kernel's two-stage crash trigger via a handcrafted
    program and verify the oops surfaces as ExecutorCrash."""
    import struct as st

    from syzkaller_tpu.ipc.env import IN_SHMEM_SIZE

    from syzkaller_tpu.ipc import sim as simmod

    crash_id = None
    for cid in range(len(target.syscalls)):
        if simmod.is_crashy(cid) and len(target.syscalls[cid].args) >= 2:
            crash_id = cid
            c0, c1 = simmod.crash_magics(cid)
            break
    if crash_id is None:
        pytest.skip("no crashy call with 2+ args in test target")

    # handcraft the exec stream: one call, two magic const args
    MASK = (1 << 64) - 1
    nargs = len(target.syscalls[crash_id].args)
    words = [crash_id, MASK, nargs, 0, 8, c0, 0, 8, c1]
    for _ in range(nargs - 2):
        words += [0, 8, 0]
    words.append(MASK)  # EOF
    data = st.pack(f"<{len(words)}Q", *[w & MASK for w in words])
    assert len(data) < IN_SHMEM_SIZE

    with pytest.raises(ExecutorCrash) as ei:
        env.exec(ExecOpts(), data)
    assert "BUG: sim-kernel" in ei.value.log
    # env recovers: next exec works
    p = _gen(target, 7)
    res = env.exec(ExecOpts(), serialize_for_exec(p))
    assert res.completed


def test_resource_dataflow_rewarded(env, target):
    """Programs that thread results into later calls reach handle-hit
    edges no handle-free program can."""
    base = set()
    for seed in range(10):
        p = _gen(target, 500 + seed, ncalls=8)
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        for ci in res.info:
            base.update(int(s) for s in ci.signal)
    assert len(base) > 0


def test_gate_window():
    entered = []
    stops = []
    g = Gate(2, stop_cb=lambda: stops.append(len(entered)))

    def worker(i):
        with g:
            entered.append(i)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(entered) == 8
    assert stops, "stop callback never ran"


def test_pid_striding(target):
    """proc-typed args materialize different values per executor pid."""
    build_executor()
    e0 = make_env(pid=0)
    e1 = make_env(pid=3)
    try:
        # any program exercises pid striding only if it has proc args;
        # correctness here = both execute fine and envs are independent
        p = _gen(target, 8)
        d = serialize_for_exec(p)
        r0 = e0.exec(ExecOpts(), d)
        r1 = e1.exec(ExecOpts(), d)
        assert r0.completed and r1.completed
    finally:
        e0.close()
        e1.close()
