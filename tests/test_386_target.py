"""linux/386 target: third architecture, 32-bit ABI (VERDICT r4
ask #3 "multi-arch consts" beyond the arm64 second arch).

The 386 const file comes from sys/extract.extract_386 (host kernel-ABI
values + an <asm/unistd_32.h> override pass); i386 keeps the legacy
syscalls arm64 drops but renumbers everything, pointers are 4 bytes,
and amd64-only entries compile disabled (reference analog: per-arch
sys/linux/*_386.const + gen/386.go)."""

from __future__ import annotations

import pytest

from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def i386():
    return get_target("linux", "386")


def test_compiles_with_own_nr_table(i386):
    amd64 = get_target("linux", "amd64")
    names64 = {s.name: s for s in amd64.syscalls}
    names32 = {s.name: s for s in i386.syscalls}
    shared = set(names64) & set(names32)
    assert len(shared) > 1700
    differing = [n for n in shared
                 if not n.startswith("syz_")
                 and names64[n].nr != names32[n].nr]
    # the i386 table numbers almost nothing like amd64
    assert len(differing) > 1000, f"only {len(differing)} renumbered"
    assert names32["open"].nr == 5      # classic i386 anchors
    assert names32["openat"].nr == 295


def test_legacy_calls_survive_on_386(i386):
    # i386 KEEPS the legacy calls arm64 drops
    names = {s.name for s in i386.syscalls}
    for legacy in ("open", "epoll_create", "inotify_init", "mkdir",
                   "readlink", "unlink", "rename", "pipe", "dup2"):
        assert legacy in names, f"{legacy} must exist on 386"


def test_amd64_only_calls_disabled(i386):
    names = {s.name for s in i386.syscalls}
    # these have no __NR in the 32-bit table
    for a64only in ("arch_prctl",):
        assert a64only not in names, f"{a64only} must be absent on 386"


def test_pointer_size_is_4(i386):
    assert i386.ptr_size == 4
    amd64 = get_target("linux", "amd64")
    m32 = {s.name: s for s in i386.syscalls}
    m64 = {s.name: s for s in amd64.syscalls}
    # a pointer argument really is 4 bytes wide in the 32-bit model
    c32, c64 = m32["openat"], m64["openat"]
    a32 = next(a for a in c32.args if a.__class__.__name__ == "PtrType")
    a64 = next(a for a in c64.args if a.__class__.__name__ == "PtrType")
    assert a32.size() == 4
    assert a64.size() == 8


def test_generation_and_serialization_on_386(i386):
    from syzkaller_tpu.models.encoding import (
        deserialize_prog,
        serialize_prog,
    )
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    p = generate_prog(i386, RandGen(i386, 7), 8)
    assert 1 <= len(p.calls) <= 8
    s = serialize_prog(p)
    assert serialize_prog(deserialize_prog(i386, s)) == s


def test_csource_compile_checks_for_386(i386, tmp_path):
    """A linux/386 reproducer compile-checks with -m32 on this 64-bit
    host (no 32-bit libc.a to link; the syscall numbers and pointer
    widths in the rendered C are the 32-bit ones)."""
    import os

    from syzkaller_tpu.csource import Options, write_csource
    from syzkaller_tpu.csource.build import build_csource, m32_flags
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    p = generate_prog(i386, RandGen(i386, 11), 6)
    src = write_csource(p, Options())
    assert b"syscall(" in src
    obj = build_csource(src, extra_flags=m32_flags(str(tmp_path)),
                        compile_only=True)
    try:
        assert os.path.getsize(obj) > 0
    finally:
        os.unlink(obj)
