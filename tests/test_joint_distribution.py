"""Joint (per-draw sequence) op-distribution bound for the integrated
PipelineMutator vs the CPU reference ladder (VERDICT r3 item #8).

The round-3 parity test checked only the FIRST landed op's marginal.
This one compares whole per-draw class patterns over >=10k draws.

Known, architectural deviation (documented here and bounded below):
a draw that lands a device-class op first returns an exec-ready
device mutant immediately — the reference's continue-coin would
sometimes additionally land a structural (squash/splice) op inside
the same draw.  Decoding every device mutant back to a typed tree to
apply that tail would forfeit the lazy-decode throughput the engine
exists for, so device-first draws are device-pure by design.  Draws
that land a structural op first DO compose into device classes via
the CPU ladder, exactly as the reference does.

(NB: landed-op rates are success-conditioned — squash/splice fail and
redraw far more often than the raw ladder weights suggest, e.g.
structural-first lands at ~8.5% not 20.8% on this corpus — so the
bound below is computed from the reference sample itself, not from
the ladder constants.)

The test therefore asserts:
  1. first-landed-op marginals match;
  2. P(mixed | structural-first) matches the reference within
     tolerance — the composition that IS implemented is faithful;
  3. the pipeline's overall mixing equals the reference's
     structural-first mixing (its only mixing source), i.e. the
     whole deficit is the documented device-first tail and there is
     no ADDITIONAL unexplained drift
     (reference ladder: prog/mutation.go:17-131).
"""

from __future__ import annotations

import pytest

from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.signal.cover import Cover

STRUCTURAL = {"squash", "splice"}
DEVICE = {"insert", "mutate_arg", "remove", "device"}


def _pattern(seq: list[str]) -> str:
    has_s = any(o in STRUCTURAL for o in seq)
    has_d = any(o in DEVICE for o in seq)
    if has_s and has_d:
        return "mixed"
    return "structural" if has_s else "device"


def _first_class(seq: list[str]) -> str:
    return "structural" if seq[0] in STRUCTURAL else "device"


@pytest.mark.slow
def test_joint_op_sequence_distribution():
    pytest.importorskip("jax")
    from syzkaller_tpu.fuzzer.proc import PipelineMutator
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=FuzzerConfig())
    for i in range(8):
        p = generate_prog(target, RandGen(target, 5000 + i), 4)
        fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
    corpus = [it.p for it in fuzzer.corpus_snapshot()]

    n = 10_000

    # Reference sample: the CPU ladder, sequences per draw.
    ref_rng = RandGen(target, 777)
    ref_seqs = []
    for i in range(n):
        ops: list[str] = []
        q = corpus[i % len(corpus)].clone()
        mutate_prog(q, ref_rng, 12, ct=fuzzer.ct, corpus=corpus,
                    ops_out=ops)
        if ops:
            ref_seqs.append(ops)

    # Integrated sample: PipelineMutator draws.
    pl = DevicePipeline(target, capacity=64, batch_size=64, seed=11)
    pm = PipelineMutator(pl, drain_timeout=300.0)
    pm_rng = RandGen(target, 888)
    pm_seqs = []
    try:
        for _ in range(n):
            pm.ops_journal = journal = []
            m = pm.next(fuzzer, pm_rng)
            if m is not None and journal:
                pm_seqs.append(list(journal))
    finally:
        pl.stop()

    assert len(ref_seqs) > 9000 and len(pm_seqs) > 9000

    def stats(seqs):
        pats = {"structural": 0, "device": 0, "mixed": 0}
        firsts = {"structural": 0, "device": 0}
        mixed_given_struct_first = [0, 0]  # mixed, total
        for s in seqs:
            pats[_pattern(s)] += 1
            fc = _first_class(s)
            firsts[fc] += 1
            if fc == "structural":
                mixed_given_struct_first[1] += 1
                if _pattern(s) == "mixed":
                    mixed_given_struct_first[0] += 1
        total = len(seqs)
        return ({k: v / total for k, v in pats.items()},
                {k: v / total for k, v in firsts.items()},
                mixed_given_struct_first[0]
                / max(1, mixed_given_struct_first[1]))

    ref_pats, ref_firsts, ref_mix_sf = stats(ref_seqs)
    pm_pats, pm_firsts, pm_mix_sf = stats(pm_seqs)

    # 1. First-op marginals match (binomial tolerance at n=10k ~ 1.3%
    #    at 3 sigma; use 3% to keep the test unflaky).
    assert abs(ref_firsts["structural"] - pm_firsts["structural"]) < 0.03, \
        (ref_firsts, pm_firsts)

    # 2. The composition that IS implemented (structural-first draws
    #    continuing into device classes) is faithful.
    assert abs(ref_mix_sf - pm_mix_sf) < 0.06, (ref_mix_sf, pm_mix_sf)

    # 3. The pipeline's only mixing source is structural-first draws:
    #    its overall mixed share must equal the reference's
    #    structural-first mixing contribution.  A larger gap in either
    #    direction means an unexplained distribution bug (measured on
    #    this corpus: ref mixed ~17%, of which ~5% structural-first —
    #    the ~12% device-first tail is the documented deviation).
    predicted_pm_mixed = ref_firsts["structural"] * ref_mix_sf
    assert abs(pm_pats["mixed"] - predicted_pm_mixed) < 0.03, \
        (ref_pats, pm_pats, ref_firsts, ref_mix_sf, predicted_pm_mixed)
    # and the documented deficit itself stays bounded
    deficit = ref_pats["mixed"] - pm_pats["mixed"]
    assert deficit < 0.2, (ref_pats, pm_pats)
