"""freebsd/amd64 target: the multi-OS machinery proof (VERDICT r3
missing #4) — a second real OS compiled from its own description tree
+ ABI const table + arch hooks, registered alongside linux/amd64."""

from __future__ import annotations

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def fbsd():
    return get_target("freebsd", "amd64")


def test_compiles_with_nothing_disabled():
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("freebsd", "amd64", register=False)
    assert res.disabled_calls == []
    assert len(res.target.syscalls) >= 130


def test_bsd_abi_facts(fbsd):
    # classic BSD numbering and BSD-specific flag values (distinct
    # from linux: O_CREAT is 0x200, MAP_ANON 0x1000, mmap is NR 477)
    by_name = {c.name: c for c in fbsd.syscalls}
    assert by_name["read"].nr == 3
    assert by_name["wait4"].nr == 7
    assert by_name["mmap"].nr == 477
    assert by_name["fstat"].nr == 551  # freebsd12 renumbered ino64 stat
    from syzkaller_tpu.sys.freebsd import _load_consts

    k = _load_consts()
    assert k["O_CREAT"] == 0x200
    assert k["MAP_ANON"] == 0x1000
    assert k["AF_INET6"] == 28  # BSD family numbering


def test_generate_mutate_roundtrip(fbsd, iters):
    for i in range(max(iters, 20)):
        p = generate_prog(fbsd, RandGen(fbsd, 7100 + i), 8)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(fbsd, s)) == s
        serialize_for_exec(p)
        mutate_prog(p, RandGen(fbsd, i), 10)
        serialize_for_exec(p)


def test_mmap_hook_and_sanitize(fbsd):
    c = fbsd.make_mmap(0x20000000, 0x4000)
    assert c.meta.name == "mmap"
    # anonymous BSD mapping: MAP_ANON set, fd slot -1
    assert c.args[3].val & 0x1000
    assert c.args[4].val == 0xFFFFFFFFFFFFFFFF
    # kill(SIGKILL) neutralized
    p = deserialize_prog(fbsd, b"kill(0x0, 0x9)\n")
    fbsd.sanitize_call(p.calls[0])
    assert p.calls[0].args[1].val == 0


def test_registered_next_to_linux():
    lt = get_target("linux", "amd64")
    ft = get_target("freebsd", "amd64")
    assert lt is not ft
    assert len({c.name for c in lt.syscalls}) != \
        len({c.name for c in ft.syscalls})


def test_netbsd_target_compiles_and_roundtrips(iters):
    """Third OS (model-only): NetBSD compiles with nothing disabled
    and round-trips; NRs follow the NetBSD table (mmap=197)."""
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("netbsd", "amd64", register=False)
    assert res.disabled_calls == []
    t = get_target("netbsd", "amd64")
    by_name = {c.name: c for c in t.syscalls}
    assert by_name["read"].nr == 3
    assert by_name["mmap"].nr == 197  # NetBSD numbering, not BSD 477
    for i in range(max(iters, 15)):
        p = generate_prog(t, RandGen(t, 8800 + i), 6)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(t, s)) == s
        serialize_for_exec(p)
