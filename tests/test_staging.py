"""The shared transfer plane (ops/staging, ISSUE 5): persistent
pow2-bucketed staging arenas with slot rotation, the self-tuning
assemble-depth controller, and the hardened knob parsing + unknown-
knob typo guard that ride along.

All host-only numpy/stdlib — no device work, no jit compiles, so the
whole module costs milliseconds inside the tier-1 suite."""

from __future__ import annotations

import numpy as np
import pytest

from syzkaller_tpu.health import envsafe
from syzkaller_tpu.ops.delta import pow2_rows
from syzkaller_tpu.ops.staging import (
    DepthController,
    StagingArena,
    resolve_assemble_depth,
)
from syzkaller_tpu.telemetry.registry import Histogram

# -- pow2 bucketing (the one rule every transfer follows) -----------------


def test_pow2_rows():
    assert pow2_rows(1) == 1
    assert pow2_rows(3) == 4
    assert pow2_rows(8) == 8
    assert pow2_rows(9) == 16
    assert pow2_rows(5, lo=8) == 8
    assert pow2_rows(0, lo=4) == 4
    assert pow2_rows(9, lo=8, hi=256) == 16
    assert pow2_rows(999, hi=256) == 256


# -- staging arena --------------------------------------------------------

_FIELDS = {"edges": ((8, 64), np.uint32), "n": ((8,), np.int32)}


def test_arena_rotates_slots_and_reuses_buffers():
    """The double-buffer contract: consecutive acquires of one bucket
    return DIFFERENT slots (batch k stages while batch k-1's upload
    is in flight), and rotation reuses the same arrays forever — one
    allocation event per bucket, then flat."""
    a = StagingArena(slots=2)
    s0 = a.acquire("k", _FIELDS)
    s1 = a.acquire("k", _FIELDS)
    s2 = a.acquire("k", _FIELDS)
    assert s0["edges"].shape == (8, 64)
    assert s0["edges"] is not s1["edges"]  # slot pair
    assert s0["edges"] is s2["edges"]  # rotation wraps
    assert a.allocations == 1
    nbytes0 = a.nbytes
    assert nbytes0 == 2 * sum(
        np.zeros(s, d).nbytes for s, d in _FIELDS.values())
    for _ in range(16):
        a.acquire("k", _FIELDS)
    assert a.allocations == 1 and a.nbytes == nbytes0


def test_arena_growth_and_key_isolation():
    """A new bucket (or a new consumer key) is one growth event; the
    buffers never alias across buckets or keys."""
    a = StagingArena(slots=1)
    small = a.acquire("k", _FIELDS)
    big_fields = {"edges": ((16, 64), np.uint32), "n": ((16,), np.int32)}
    big = a.acquire("k", big_fields)
    other = a.acquire("other", _FIELDS)
    assert a.allocations == 3 and a.bucket_count() == 3
    assert big["edges"].shape == (16, 64)
    assert other["edges"] is not small["edges"]
    # in-place writes persist across acquires (slots=1: same buffer)
    small["n"][:] = 7
    assert (a.acquire("k", _FIELDS)["n"] == 7).all()


def test_arena_single_slot_floor():
    a = StagingArena(slots=0)  # clamped to 1
    assert a.acquire("k", _FIELDS)["n"] is a.acquire("k", _FIELDS)["n"]


# -- depth controller -----------------------------------------------------


def _hist(values):
    h = Histogram("h")
    for v in values:
        h.observe(v)
    return h


def _ctrl(drain, work, **kw):
    kw.setdefault("initial", 2)
    kw.setdefault("interval", 1)
    kw.setdefault("cooldown", 0)
    kw.setdefault("min_samples", 32)
    return DepthController(drain_hist=_hist(drain), work_hist=_hist(work),
                           **kw)


def test_depth_controller_raises_when_d2h_dominates():
    """The pool idling behind D2H (drain p50 >> assembly p50) raises
    the depth one step per evaluation, clamped at hi."""
    c = _ctrl([0.1] * 64, [0.01] * 64, lo=1, hi=4)
    assert c.update() == 3
    assert c.update() == 4
    assert c.update() == 4  # clamped


def test_depth_controller_lowers_when_assembly_dominates():
    c = _ctrl([0.01] * 64, [0.1] * 64, initial=3, lo=1, hi=4)
    assert c.update() == 2
    assert c.update() == 1
    assert c.update() == 1  # clamped at lo


def test_depth_controller_hysteresis_dead_zone():
    """A ratio inside (lower_ratio, raise_ratio) never moves the
    depth — noisy percentiles must not flap it."""
    c = _ctrl([0.05] * 64, [0.05] * 64)
    for _ in range(8):
        assert c.update() == 2


def test_depth_controller_inert_without_samples():
    """A fresh pipeline (and the tier-1 suite) has empty histograms:
    the controller stays at the seed depth."""
    c = _ctrl([0.1] * 8, [0.01] * 8, min_samples=32)  # under the bar
    for _ in range(8):
        assert c.update() == 2


def test_depth_controller_cooldown_and_interval():
    """Moves are rate-limited: only every `interval`-th update
    evaluates, and a move starts a cooldown of evaluations."""
    c = _ctrl([0.1] * 64, [0.01] * 64, interval=2, cooldown=2)
    assert c.update() == 2  # off-interval tick
    assert c.update() == 3  # evaluates, raises
    assert c.update() == 3  # off-interval
    assert c.update() == 3  # cooling (1)
    assert c.update() == 3  # off-interval
    assert c.update() == 3  # cooling (2)
    assert c.update() == 3  # off-interval
    assert c.update() == 4  # cooled: raises again


# -- knob parsing + typo guard --------------------------------------------


def test_env_auto_int(monkeypatch):
    monkeypatch.delenv("TZ_ASSEMBLE_DEPTH", raising=False)
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", None) is None
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "auto")
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", 3) is None
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "Auto")
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", 3) is None
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "3")
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", None) == 3
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "0x10")
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", None) == 16
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "banana")
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", None) is None
    assert envsafe.env_auto_int("TZ_ASSEMBLE_DEPTH", 5) == 5


def test_resolve_assemble_depth_env(monkeypatch):
    """TZ_ASSEMBLE_DEPTH=auto|N (health.envsafe discipline): unset
    and malformed both resolve to the self-tuning controller at the
    compiled-in default; a pinned N disables it."""
    monkeypatch.delenv("TZ_ASSEMBLE_DEPTH", raising=False)
    depth, ctrl = resolve_assemble_depth(2)
    assert depth == 2 and ctrl is not None and ctrl.depth == 2
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "3")
    depth, ctrl = resolve_assemble_depth(2)
    assert depth == 3 and ctrl is None
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "auto")
    depth, ctrl = resolve_assemble_depth(4)
    assert ctrl is not None and depth == ctrl.depth == 4
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "two")
    depth, ctrl = resolve_assemble_depth(2)
    assert depth == 2 and ctrl is not None  # malformed -> auto
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "0")
    depth, ctrl = resolve_assemble_depth(2)
    assert depth == 1 and ctrl is None  # floor at 1


def test_unknown_tz_var_warned_once(monkeypatch):
    """The typo guard: a TZ_* name no knob parses is flagged exactly
    once per process; known knobs never are."""
    name = "TZ_DEFINITELY_MISSPELLED_KNOB"
    monkeypatch.setenv(name, "1")
    monkeypatch.setenv("TZ_TRIAGE_DISPATCH_DEPTH", "2")  # known
    with envsafe._warn_lock:
        envsafe._warned.discard(name)
    flagged = envsafe.warn_unknown_tz_vars()
    assert name in flagged
    assert "TZ_TRIAGE_DISPATCH_DEPTH" not in flagged
    assert envsafe.warn_unknown_tz_vars() == []  # once per process


def test_known_tz_registry_covers_engine_knobs():
    """Every knob the engines parse is in the static seed — the guard
    must be correct at engine START, before later parse sites run."""
    for knob in ("TZ_TRIAGE_DISPATCH_DEPTH", "TZ_ASSEMBLE_DEPTH",
                 "TZ_PIPELINE_DISPATCH_DEPTH", "TZ_ASSEMBLE_WORKERS",
                 "TZ_FAULT_PLAN", "TZ_TRACE_FILE",
                 "TZ_BENCH_WARMUP_TIMEOUT_S"):
        assert knob in envsafe.KNOWN_TZ_VARS, knob


# -- pipeline knob integration (no device: constructor-level) -------------


def test_pipeline_assemble_depth_knob(monkeypatch):
    """The pipeline resolves TZ_ASSEMBLE_DEPTH at construction:
    pinned N disables the controller, auto enables it."""
    pytest.importorskip("jax")
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    target = get_target("test", "64")
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "3")
    pl = DevicePipeline(target, capacity=8, batch_size=4)
    assert pl._assemble_depth == 3 and pl._depth_ctrl is None
    assert pl.health_snapshot()["assemble_depth"] == 3
    assert pl.health_snapshot()["assemble_depth_auto"] is False
    pl.stop()
    monkeypatch.setenv("TZ_ASSEMBLE_DEPTH", "auto")
    pl = DevicePipeline(target, capacity=8, batch_size=4,
                        assemble_depth=2)
    assert pl._assemble_depth == 2 and pl._depth_ctrl is not None
    assert pl.health_snapshot()["assemble_depth_auto"] is True
    pl.stop()


def test_arena_flags_repad_allocs_regression():
    """ISSUE 10 `tz_staging_arena_allocs_total` regression: the
    pipeline's flag-table re-pads (ops/pipeline._flush_pending) route
    growth re-uploads through pow2_rows + ONE rotating arena bucket
    per pow2 row count — repeated growth inside a bucket is zero
    allocation events (rotation only), the counter advances exactly
    once per new bucket, and an exact-pow2 length skips staging
    entirely (the tables upload unpadded)."""
    from syzkaller_tpu.ops.staging import _M_ARENA_ALLOCS

    a = StagingArena(slots=2)
    c0 = _M_ARENA_ALLOCS.value

    def repad(n_flags):
        # The _flush_pending staging contract, verbatim: pad to the
        # pow2 bucket, zero the tail (stale rotated bytes must not
        # reach the device tables).
        rows = pow2_rows(n_flags)
        vals = np.arange(n_flags * 4, dtype=np.uint64).reshape(-1, 4)
        counts = np.full(n_flags, 2, dtype=np.int32)
        if rows > n_flags:
            bufs = a.acquire(("flags", rows), {
                "vals": ((rows, 4), vals.dtype),
                "counts": ((rows,), counts.dtype)})
            bufs["vals"][:n_flags] = vals
            bufs["vals"][n_flags:] = 0
            bufs["counts"][:n_flags] = counts
            bufs["counts"][n_flags:] = 0
            vals, counts = bufs["vals"], bufs["counts"]
        assert vals.shape[0] == rows and counts.shape[0] == rows
        return vals, counts

    v, c = repad(5)  # bucket 8: the one allocation event
    assert a.allocations == 1
    assert (v[5:] == 0).all() and (c[5:] == 0).all()
    repad(6)
    v7, _ = repad(7)  # same bucket: slot rotation, zero growth
    assert a.allocations == 1
    assert _M_ARENA_ALLOCS.value == c0 + 1
    assert (v7[7:] == 0).all()  # rotated slot's stale tail re-zeroed
    repad(9)  # crosses into bucket 16: exactly one more event
    assert a.allocations == 2
    assert _M_ARENA_ALLOCS.value == c0 + 2
    repad(16)  # exact pow2: no padding, no staging acquire at all
    assert a.allocations == 2 and _M_ARENA_ALLOCS.value == c0 + 2


def test_corpus_arena_flush_reuses_staging_rotation():
    """ISSUE 18 alongside the ISSUE 5 pin above: the corpus arena's
    flush stages through the SAME pow2 ("corpus", bucket) keys and
    slot rotation — arena growth inside a bucket is zero allocation
    events, `tz_staging_arena_allocs_total` advances only when the
    pending-row count crosses into a new pow2 bucket, and a full
    invalidate re-stage (the breaker/re-shard path) rotates the
    existing bucket rather than allocating.  Phase A only (numpy
    stands in for jnp): the staging contract lives entirely in
    `begin_flush`; the device scatter never touches the arena."""
    from syzkaller_tpu.ops.arena import CorpusArena
    from syzkaller_tpu.ops.staging import _M_ARENA_ALLOCS

    a = StagingArena(slots=2)
    arena = CorpusArena(64, staging=a, slab_bits=6,
                        headroom_bytes=1 << 30)
    c0 = _M_ARENA_ALLOCS.value

    def row(i):
        return {"val": np.full(6, i, np.uint64)}

    def flush_phase_a():
        token = arena.begin_flush(np)
        assert token[0] == "staged"
        pending, idx_list, bufs, _nbytes = token[2]
        # Phase B's pending bookkeeping, minus the device scatter.
        with arena._lock:
            for i in idx_list:
                if arena._pending.get(i) == pending[i]:
                    del arena._pending[i]
        return bufs

    for i in range(5):
        arena.stage(i, row(i))
    bufs = flush_phase_a()  # 5 pending rows -> ("corpus", 8) bucket
    assert a.allocations == 1
    assert bufs["row:val"].shape[0] == 8
    # Growth inside the bucket: 6 more rows, same pow2 count ->
    # rotation only, the counter must stay flat.
    for i in range(5, 11):
        arena.stage(i, row(i))
    flush_phase_a()
    assert a.allocations == 1 and _M_ARENA_ALLOCS.value == c0 + 1
    # Invalidate: all 11 occupied rows re-stage -> bucket 16, exactly
    # one more allocation event.
    arena.invalidate()
    flush_phase_a()
    assert a.allocations == 2 and _M_ARENA_ALLOCS.value == c0 + 2
    # A second full re-stage rotates the bucket-16 slot: still flat.
    arena.invalidate()
    flush_phase_a()
    assert a.allocations == 2 and _M_ARENA_ALLOCS.value == c0 + 2
