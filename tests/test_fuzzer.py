"""Fuzzer-layer tests: workqueue priorities, host detection, and the
full proc loop against the native executor + simulated kernel."""

from __future__ import annotations

import pytest

from syzkaller_tpu.fuzzer import (
    Fuzzer,
    FuzzerConfig,
    Proc,
    WorkCandidate,
    WorkQueue,
    WorkSmash,
    WorkTriage,
    signal_prio,
)
from syzkaller_tpu.fuzzer import host
from syzkaller_tpu.fuzzer.fuzzer import Stat
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.signal import Signal


@pytest.fixture(scope="module")
def target():
    return get_target("test", "64")


def test_workqueue_priorities(target):
    wq = WorkQueue()
    p = generate_prog(target, RandGen(target, 1), 3)
    smash = WorkSmash(p, 0)
    cand = WorkCandidate(p)
    triage = WorkTriage(p, 0, Signal())
    tcand = WorkTriage(p, 0, Signal(), from_candidate=True)
    for item in (smash, triage, cand, tcand):
        wq.enqueue(item)
    assert wq.dequeue() is tcand
    assert wq.dequeue() is cand
    assert wq.dequeue() is triage
    assert wq.dequeue() is smash
    assert wq.dequeue() is None


def test_host_detection(target):
    supported, unsupported = host.detect_supported_syscalls(target)
    assert len(supported) > 0
    enabled, disabled = host.enabled_calls(target, supported)
    # every enabled call's resources must be constructible
    assert len(enabled) > 0
    for c, reason in disabled.items():
        assert "resource" in reason


def test_signal_prio(target):
    p = generate_prog(target, RandGen(target, 2), 3)
    assert signal_prio(p, 0, 0) == 3  # success + no ANY
    assert signal_prio(p, 9, 0) == 1  # failure + no ANY


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    from syzkaller_tpu.ipc.env import make_env

    e = make_env(pid=0, sim=True, signal=True,
                 workdir=str(tmp_path_factory.mktemp("fuzzer-ipc")))
    yield e
    e.close()


def test_proc_loop_end_to_end(target, env):
    """A few hundred iterations against the sim kernel must grow the
    corpus and accumulate signal (the syz-stress slice)."""
    cfg = FuzzerConfig(program_length=6, generate_period=10,
                       smash_mutants=3, fault_nth_max=3,
                       triage_runs=3, minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    proc = Proc(fuzzer, pid=0, env=env)
    proc.loop(iterations=300)
    assert len(fuzzer.corpus) > 0, "no inputs triaged into corpus"
    assert len(fuzzer.max_signal) > 0
    assert len(fuzzer.corpus_signal) > 0
    # corpus signal must be a subset of max signal
    assert len(fuzzer.max_signal.diff(fuzzer.corpus_signal)) == 0
    stats = fuzzer.grab_stats()
    assert stats.get("exec total", 0) >= 300


def test_proc_loop_with_pipeline_mutator(target, env):
    """The integrated device path: procs drain exec-ready mutants off
    the DevicePipeline and feed them straight to the executor; new
    signal still lands in the corpus via lazy typed decode
    (VERDICT r2 item #1)."""
    pytest.importorskip("jax")
    from syzkaller_tpu.fuzzer.proc import PipelineMutator
    from syzkaller_tpu.ops.pipeline import DevicePipeline
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    cfg = FuzzerConfig(program_length=6, generate_period=5,
                       smash_mutants=2, fault_nth_max=2,
                       minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    pl = DevicePipeline(target, capacity=64, batch_size=16, seed=3)
    pm = PipelineMutator(pl, drain_timeout=120.0)
    pm.ops_journal = []
    # Seed the corpus so the pipeline ring has templates.
    seeded = 0
    i = 0
    while seeded < 8 and i < 200:
        p = generate_prog(target, RandGen(target, 1000 + i), 4)
        i += 1
        fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
        seeded += 1
    proc = Proc(fuzzer, pid=1, env=env, mutator=pm)
    try:
        proc.loop(iterations=150)
        # The loop's fuzz draws are rationed by triage/smash work, so
        # deterministically drive the mutation source until both op
        # routes (device exec-ready, host structural) have executed.
        deadline = 400
        while deadline > 0 and ("device" not in pm.ops_journal
                                or len(set(pm.ops_journal)) < 2):
            m = pm.next(fuzzer, proc.rng)
            if m is None:
                continue
            proc.execute(proc.exec_opts, m, Stat.FUZZ)
            deadline -= 1
    finally:
        pl.stop()
    assert pl.stats.mutants > 0, "device pipeline produced no mutants"
    assert "device" in pm.ops_journal, "no device mutant was executed"
    # Host structural ops flowed too (~72% of ladder draws).
    assert any(op in ("squash", "splice", "insert")
               for op in pm.ops_journal)


def test_pipeline_mutator_op_distribution(target, env):
    """Integrated op-class distribution parity vs models/mutation.py:
    the first landed op of each PipelineMutator draw must be
    distributed like the first landed op of the CPU reference loop
    over the same corpus (insert/arg-mutate/remove are device classes
    there — ~79% of iteration weight, VERDICT r2 #4).
    Two-sample chi-square, df=2, crit p=.001 -> 13.82."""
    pytest.importorskip("jax")
    from syzkaller_tpu.fuzzer.proc import PipelineMutator
    from syzkaller_tpu.models.mutation import mutate_prog
    from syzkaller_tpu.ops.pipeline import DevicePipeline
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=FuzzerConfig())
    for i in range(8):
        p = generate_prog(target, RandGen(target, 3000 + i), 4)
        fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
    corpus = [it.p for it in fuzzer.corpus_snapshot()]
    classes = ("squash", "splice", "device")

    # Reference sample: CPU mutate_prog over the same corpus.
    ref_rng = RandGen(target, 4242)
    n = 600
    ref_counts = dict.fromkeys(classes, 0)
    for i in range(n):
        p = corpus[ref_rng.intn(len(corpus))].clone()
        journal: list = []
        mutate_prog(p, ref_rng, fuzzer.cfg.program_length,
                    ct=fuzzer.ct, corpus=corpus, ops_out=journal)
        first = journal[0]
        if first in ("insert", "mutate_arg", "remove"):
            first = "device"
        ref_counts[first] += 1

    # Integrated sample: the pipeline mutator's routing.
    pl = DevicePipeline(target, capacity=64, batch_size=64, seed=9)
    pm = PipelineMutator(pl, drain_timeout=120.0)
    rng = RandGen(target, 77)
    got_counts = dict.fromkeys(classes, 0)
    try:
        for _ in range(n):
            pm.ops_journal = []
            m = pm.next(fuzzer, rng)
            assert m is not None
            got_counts[pm.ops_journal[0]] += 1
    finally:
        pl.stop()

    chi2 = 0.0
    for k in classes:
        tot = ref_counts[k] + got_counts[k]
        if tot == 0:
            continue
        e = tot / 2  # equal sample sizes
        chi2 += (ref_counts[k] - e) ** 2 / e + (got_counts[k] - e) ** 2 / e
    assert chi2 < 13.82, (
        f"op distribution skewed: ref={ref_counts} got={got_counts}")


def test_sim_model_matches_executor(target, env):
    """The Python sim model (ipc/sim.py) predicts executor behavior:
    hitting an arg magic yields extra edges vs. not hitting it."""
    from syzkaller_tpu.ipc import sim as simmod
    from syzkaller_tpu.ipc.env import ExecOpts
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.prog import Call, ConstArg, Prog, make_return_arg
    from syzkaller_tpu.models.types import ConstType, IntType

    # find a syscall whose first arg is a plain scalar we control
    meta = None
    for c in target.syscalls:
        if c.args and isinstance(c.args[0], IntType) \
                and not isinstance(c.args[0], ConstType):
            meta = c
            break
    if meta is None:
        pytest.skip("no scalar-arg syscall in test target")
    magic = simmod.arg_magic(meta.id, 0)

    def run(val):
        args = [ConstArg(meta.args[0], val)]
        for t in meta.args[1:]:
            args.append(target.default_arg(t))
        p = Prog(target, [Call(meta, args, make_return_arg(meta.ret))])
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.info
        return len(res.info[0].signal)

    assert run(magic) > run((magic + 7) & 0xFFFFFFFF)


# ---- host syscall-support detection (pkg/host analogue) --------------

def test_host_detection_linux_probes():
    """The linux probe excludes calls whose backing facility is absent
    and calls the kernel doesn't implement, keeps the rest."""
    import os

    import pytest

    if not os.path.exists("/proc/version"):
        pytest.skip("not a linux host")
    from syzkaller_tpu.fuzzer.host import (
        check_comparisons, check_coverage, check_fault_injection,
        detect_supported_syscalls, enabled_calls)
    from syzkaller_tpu.models.target import get_target

    t = get_target("linux", "amd64")
    sup, unsup = detect_supported_syscalls(t, backend="linux")
    assert len(sup) > 300
    names = {c.name for c in sup}
    assert "getpid" in names and "openat" in names
    # a no-probe call is never spuriously dropped
    assert "exit_group" in names
    if not os.path.exists("/dev/kvm"):
        assert "openat$kvm" not in names
        assert "syz_kvm_setup_cpu" not in names
        # the kvm ioctl chain dies transitively with its ctor
        enabled, disabled = enabled_calls(t, sup)
        dis_names = {c.name for c in disabled}
        assert "ioctl$KVM_CREATE_VM" in dis_names
    assert isinstance(check_fault_injection("linux"), bool)
    assert isinstance(check_coverage("linux"), bool)
    assert isinstance(check_comparisons("linux"), bool)
    # sim backend: everything is supported by construction
    assert check_fault_injection() and check_coverage()
    sup_sim, unsup_sim = detect_supported_syscalls(t)
    assert not unsup_sim


def test_host_detection_sim_supports_all(test_target):
    from syzkaller_tpu.fuzzer.host import detect_supported_syscalls

    sup, unsup = detect_supported_syscalls(test_target)
    assert not unsup
    assert len(sup) == len(test_target.syscalls)


def test_host_detection_dangerous_devices():
    """Opening /dev/watchdog arms a reboot timer: the linux probe
    keeps it (and its ioctl chain, transitively) out of the default
    enabled set even when the device exists."""
    import os

    import pytest

    if not os.path.exists("/proc/version"):
        pytest.skip("not a linux host")
    from syzkaller_tpu.fuzzer.host import (detect_supported_syscalls,
                                           enabled_calls)
    from syzkaller_tpu.models.target import get_target

    t = get_target("linux", "amd64")
    sup, unsup = detect_supported_syscalls(t, backend="linux")
    names = {c.name: r for c, r in unsup.items()}
    assert "openat$watchdog" in names
    assert "watchdog" in names["openat$watchdog"]
    enabled, disabled = enabled_calls(t, sup)
    dis = {c.name for c in disabled}
    assert "ioctl$WDIOC_KEEPALIVE" in dis  # dies with its ctor
