"""Fuzzer-layer tests: workqueue priorities, host detection, and the
full proc loop against the native executor + simulated kernel."""

from __future__ import annotations

import pytest

from syzkaller_tpu.fuzzer import (
    Fuzzer,
    FuzzerConfig,
    Proc,
    WorkCandidate,
    WorkQueue,
    WorkSmash,
    WorkTriage,
    signal_prio,
)
from syzkaller_tpu.fuzzer import host
from syzkaller_tpu.fuzzer.fuzzer import Stat
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.signal import Signal


@pytest.fixture(scope="module")
def target():
    return get_target("test", "64")


def test_workqueue_priorities(target):
    wq = WorkQueue()
    p = generate_prog(target, RandGen(target, 1), 3)
    smash = WorkSmash(p, 0)
    cand = WorkCandidate(p)
    triage = WorkTriage(p, 0, Signal())
    tcand = WorkTriage(p, 0, Signal(), from_candidate=True)
    for item in (smash, triage, cand, tcand):
        wq.enqueue(item)
    assert wq.dequeue() is tcand
    assert wq.dequeue() is cand
    assert wq.dequeue() is triage
    assert wq.dequeue() is smash
    assert wq.dequeue() is None


def test_host_detection(target):
    supported, unsupported = host.detect_supported_syscalls(target)
    assert len(supported) > 0
    enabled, disabled = host.enabled_calls(target, supported)
    # every enabled call's resources must be constructible
    assert len(enabled) > 0
    for c, reason in disabled.items():
        assert "resource" in reason


def test_signal_prio(target):
    p = generate_prog(target, RandGen(target, 2), 3)
    assert signal_prio(p, 0, 0) == 3  # success + no ANY
    assert signal_prio(p, 9, 0) == 1  # failure + no ANY


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    from syzkaller_tpu.ipc.env import make_env

    e = make_env(pid=0, sim=True, signal=True,
                 workdir=str(tmp_path_factory.mktemp("fuzzer-ipc")))
    yield e
    e.close()


def test_proc_loop_end_to_end(target, env):
    """A few hundred iterations against the sim kernel must grow the
    corpus and accumulate signal (the syz-stress slice)."""
    cfg = FuzzerConfig(program_length=6, generate_period=10,
                       smash_mutants=3, fault_nth_max=3,
                       triage_runs=3, minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    proc = Proc(fuzzer, pid=0, env=env)
    proc.loop(iterations=300)
    assert len(fuzzer.corpus) > 0, "no inputs triaged into corpus"
    assert len(fuzzer.max_signal) > 0
    assert len(fuzzer.corpus_signal) > 0
    # corpus signal must be a subset of max signal
    assert len(fuzzer.max_signal.diff(fuzzer.corpus_signal)) == 0
    stats = fuzzer.grab_stats()
    assert stats.get("exec total", 0) >= 300


def test_proc_loop_with_batch_mutator(target, env):
    """The TPU-engine feed/drain path produces valid mutants that the
    executor accepts."""
    from syzkaller_tpu.engine import TpuEngine
    from syzkaller_tpu.fuzzer.proc import BatchMutator

    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    cfg = FuzzerConfig(program_length=6, generate_period=5,
                       smash_mutants=2, fault_nth_max=2,
                       minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    engine = TpuEngine(target, rounds=2, seed=3)
    # Seed the corpus with tensor-encodable programs so the device path
    # is exercised (non-encodable programs fall back to the CPU mutator).
    seeded = 0
    i = 0
    while seeded < 8 and i < 200:
        p = generate_prog(target, RandGen(target, 1000 + i), 4)
        i += 1
        if engine.encode(p) is not None:
            fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())
            seeded += 1
    assert seeded > 0, "no encodable programs generated"
    bm = BatchMutator(engine, batch_size=8)
    proc = Proc(fuzzer, pid=1, env=env, batch_mutator=bm)
    proc.loop(iterations=150)
    assert engine.stats.device_mutations + engine.stats.host_mutations > 0


def test_sim_model_matches_executor(target, env):
    """The Python sim model (ipc/sim.py) predicts executor behavior:
    hitting an arg magic yields extra edges vs. not hitting it."""
    from syzkaller_tpu.ipc import sim as simmod
    from syzkaller_tpu.ipc.env import ExecOpts
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.prog import Call, ConstArg, Prog, make_return_arg
    from syzkaller_tpu.models.types import ConstType, IntType

    # find a syscall whose first arg is a plain scalar we control
    meta = None
    for c in target.syscalls:
        if c.args and isinstance(c.args[0], IntType) \
                and not isinstance(c.args[0], ConstType):
            meta = c
            break
    if meta is None:
        pytest.skip("no scalar-arg syscall in test target")
    magic = simmod.arg_magic(meta.id, 0)

    def run(val):
        args = [ConstArg(meta.args[0], val)]
        for t in meta.args[1:]:
            args.append(target.default_arg(t))
        p = Prog(target, [Call(meta, args, make_return_arg(meta.ret))])
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        assert res.info
        return len(res.info[0].signal)

    assert run(magic) > run((magic + 7) & 0xFFFFFFFF)
