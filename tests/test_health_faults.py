"""Self-healing device runtime, driven by the deterministic fault
plan (syzkaller_tpu/health): scripted seam failures take the real
DevicePipeline through demote → half-open probe (with host-snapshot
rebuild on EVERY re-entry) → re-promote, with zero lost corpus items;
a scripted hang proves the watchdog converts a stall into DeviceWedged
within its deadline instead of blocking the worker thread forever
(the round-5 wedge, BENCH_WEDGE_DIAGNOSIS.md)."""

from __future__ import annotations

import queue
import threading
import time

import pytest

from syzkaller_tpu.health import (
    CircuitBreaker,
    DeviceWedged,
    FaultInjected,
    FaultPlan,
    Watchdog,
    env_float,
    env_int,
    fault_point,
    install_plan,
    plan_from_env,
    reset_plan,
)
from syzkaller_tpu.health.breaker import CLOSED, HALF_OPEN, OPEN


@pytest.fixture(autouse=True)
def _clean_plan():
    reset_plan()
    yield
    reset_plan()


# -- fault plan grammar ---------------------------------------------------


def test_plan_grammar():
    plan = FaultPlan.parse(
        "device.launch:fail@3,5;rpc.send_frame:hang@2")
    assert plan._rules["device.launch"].mode == "fail"
    assert plan._rules["device.launch"].occurrences == {3, 5}
    assert plan._rules["rpc.send_frame"].mode == "hang"

    ranged = FaultPlan.parse("device.launch:fail@1-8")
    assert ranged._rules["device.launch"].occurrences == set(range(1, 9))

    always = FaultPlan.parse("queue.put:fail@*")
    assert always._rules["queue.put"].always


@pytest.mark.parametrize("bad", [
    "", "device.launch", "device.launch:fail", "device.launch:@3",
    "device.launch:explode@3", "device.launch:fail@0",
    "device.launch:fail@5-3", "device.launch:fail@x",
    "device.launch:fail@1;device.launch:fail@2",
])
def test_plan_grammar_rejects(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_env_plan_malformed_is_ignored(monkeypatch):
    monkeypatch.setenv("TZ_FAULT_PLAN", "this is not a plan")
    assert plan_from_env() is None
    monkeypatch.setenv("TZ_FAULT_PLAN", "device.launch:fail@2")
    plan = plan_from_env()
    assert plan is not None and "device.launch" in plan._rules


def test_fault_point_fires_on_scripted_invocations_only():
    install_plan(FaultPlan.parse("device.launch:fail@2"))
    fault_point("device.launch")  # invocation 1: clean
    with pytest.raises(FaultInjected) as ei:
        fault_point("device.launch")  # invocation 2: scripted
    assert ei.value.seam == "device.launch" and ei.value.n == 2
    assert isinstance(ei.value, ConnectionError)  # realistic type
    fault_point("device.launch")  # invocation 3: clean again
    fault_point("rpc.recv_frame")  # other seams unaffected
    install_plan(None)  # deactivated: seams are free
    fault_point("device.launch")


def test_fault_point_hang_releases_on_heal():
    plan = install_plan(FaultPlan.parse("device.launch:hang@1"))
    done = threading.Event()

    def hit():
        fault_point("device.launch")
        done.set()

    t = threading.Thread(target=hit, daemon=True)
    t.start()
    assert not done.wait(timeout=0.3), "hang seam did not block"
    plan.heal("device.launch")
    assert done.wait(timeout=5), "heal did not release the hung seam"


# -- env hardening --------------------------------------------------------


def test_envsafe_falls_back_on_malformed(monkeypatch):
    monkeypatch.setenv("TZ_X_INT", "not-a-number")
    monkeypatch.setenv("TZ_X_FLOAT", "1.5.9")
    assert env_int("TZ_X_INT", 7) == 7
    assert env_float("TZ_X_FLOAT", 2.5) == 2.5
    monkeypatch.setenv("TZ_X_INT", "0x10")
    assert env_int("TZ_X_INT", 7) == 16
    monkeypatch.setenv("TZ_X_FLOAT", "3.5")
    assert env_float("TZ_X_FLOAT", 2.5) == 3.5
    assert env_int("TZ_X_UNSET", 9) == 9


def test_pipeline_survives_malformed_dispatch_depth(monkeypatch):
    jax = pytest.importorskip("jax")
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    monkeypatch.setenv("TZ_PIPELINE_DISPATCH_DEPTH", "two")
    pl = DevicePipeline(get_target("test", "64"), capacity=8,
                        batch_size=4, dispatch_depth=3)
    assert pl._dispatch_depth == 3  # constructor fallback, not a crash


# -- circuit breaker ------------------------------------------------------


def test_breaker_state_machine_deterministic():
    clk = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=3, backoff_initial=1.0,
                        backoff_cap=4.0, jitter=0.0, seed=7,
                        clock=lambda: clk["t"])
    assert br.state == CLOSED and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED  # below threshold
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED  # success reset the streak
    br.record_failure()
    assert br.state == OPEN and br.counters.opens == 1
    assert not br.allow()  # backoff not elapsed
    assert br.seconds_until_probe() == pytest.approx(1.0)

    clk["t"] = 1.0
    assert br.allow()  # probe admitted
    assert br.state == HALF_OPEN and br.counters.half_opens == 1
    assert br.consume_rebuild()  # one rebuild per half-open entry
    assert not br.consume_rebuild()
    br.record_failure()  # failed probe: reopen, backoff doubles
    assert br.state == OPEN and br.counters.opens == 2
    assert br.seconds_until_probe() == pytest.approx(2.0)

    clk["t"] = 3.0
    assert br.allow() and br.consume_rebuild()  # rebuild re-triggers
    br.record_failure()
    assert br.seconds_until_probe() == pytest.approx(4.0)  # capped next
    clk["t"] = 7.0
    assert br.allow() and br.consume_rebuild()
    br.record_success()  # probe succeeded: re-promotion
    assert br.state == CLOSED and br.counters.closes == 1
    assert br.counters.rebuilds == 3
    assert not br.consume_rebuild()  # cleared by the close
    snap = br.snapshot()
    assert snap["state"] == CLOSED and snap["opens"] == 3


def test_breaker_jitter_is_deterministic():
    def mk():
        clk = {"t": 0.0}
        br = CircuitBreaker(failure_threshold=1, backoff_initial=1.0,
                            backoff_cap=60.0, jitter=0.2, seed=42,
                            clock=lambda: clk["t"])
        br.record_failure()
        return br.seconds_until_probe()

    assert mk() == mk()  # same seed, same trajectory


# -- watchdog -------------------------------------------------------------


def test_watchdog_passes_results_and_errors_through():
    wd = Watchdog(deadline_s=5.0)
    assert wd.call(lambda: 42, "device.launch") == 42
    with pytest.raises(KeyError):
        wd.call(lambda: {}["x"], "device.launch")
    assert wd.stats.calls == 2 and wd.stats.wedges == 0
    wd0 = Watchdog(deadline_s=0)  # disabled: direct call
    assert wd0.call(lambda: "ok", "device.launch") == "ok"


def test_watchdog_converts_hang_to_device_wedged():
    wd = Watchdog(deadline_s=0.2)
    release = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(DeviceWedged) as ei:
        wd.call(release.wait, "device.launch")
    detect = time.monotonic() - t0
    assert ei.value.op == "device.launch"
    assert detect < 5.0  # detected promptly, not an eternal stall
    assert wd.stats.wedges == 1
    assert wd.stats.abandoned_live == 1  # the stuck call lives on
    release.set()  # let the abandoned thread finish


# -- pipeline integration -------------------------------------------------


def _build_pipeline(target, n_seeds=8, **kw):
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    kw.setdefault("capacity", 64)
    kw.setdefault("batch_size", 8)
    # The pool is explicitly on (the cpu-aware default would disable
    # it on single-core CI hosts, and the concurrency tests exercise
    # real pool threads).
    kw.setdefault("assemble_workers", 2)
    pl = DevicePipeline(target, seed=3, **kw)
    added, i = 0, 0
    while added < n_seeds and i < n_seeds * 6:
        p = generate_prog(target, RandGen(target, 4000 + i), 5)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= n_seeds // 2
    return pl


@pytest.fixture(scope="module")
def device_rig():
    """One warm (compiled) pipeline shared by the integration tests —
    the jit compile dominates test wall-clock, and every test below
    scripts its faults from a freshly installed plan, so seam counts
    are deterministic from the install point regardless of history.
    depth 1 keeps at most one launch in flight, so a scripted failure
    cannot silently drop an unrelated healthy batch from the deque.
    Each test must leave the pipeline healthy (breaker closed, no
    active plan — the autouse _clean_plan fixture enforces the
    latter)."""
    pytest.importorskip("jax")
    from syzkaller_tpu.models.target import get_target

    target = get_target("test", "64")
    pl = _build_pipeline(target, dispatch_depth=1, rounds=1)
    pl.breaker.configure_backoff(initial=0.15, cap=0.4)
    first = pl.next_batch(timeout=300)  # compile + warmup
    assert first
    yield target, pl
    pl.stop()


def _drain_until(pl, cond, timeout=60.0):
    """Keep draining batches (unblocking the worker's delivery) until
    cond() holds; returns the last drained batch, if any."""
    last = None
    deadline = time.time() + timeout
    while not cond() and time.time() < deadline:
        try:
            last = pl.next_batch(timeout=0.1)
        except queue.Empty:
            pass
    return last


@pytest.fixture()
def fuzzer_state():
    pytest.importorskip("jax")
    from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.signal.cover import Cover

    target = get_target("test", "64")
    fz = Fuzzer(target, wq=WorkQueue(),
                cfg=FuzzerConfig(program_length=6))
    for i in range(6):
        p = generate_prog(target, RandGen(target, 8800 + i), 4)
        fz.add_input_to_corpus(p, Signal({i: 1}), Cover())
    return target, fz


def test_fault_plan_demote_rebuild_repromote_no_corpus_loss(
        device_rig, fuzzer_state):
    """The acceptance trajectory: ≥8 consecutive scripted
    device-launch failures trip the breaker (CPU demotion), every
    half-open probe re-triggers the host-snapshot rebuild (not just
    once at error #4), and the pipeline re-promotes after the seam
    heals — with zero lost corpus items."""
    from syzkaller_tpu.fuzzer.proc import PipelineMutator
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    target, pl = device_rig
    _, fz = fuzzer_state
    pm = PipelineMutator(pl, drain_timeout=0.5, demote_after=3,
                         probe_interval=0.05, probe_timeout=0.5)
    # Don't also feed the fuzzer's corpus into the shared ring: the
    # feed path is covered by test_fuzzer, and keeping the add set
    # small avoids paying XLA scatter compiles for extra row-count
    # shapes in this timing-sensitive test.
    pm._fed = fz.corpus_len()
    rng = RandGen(target, 17)
    errors0 = pl.stats.worker_errors
    snap0 = pl.breaker.snapshot()
    # Seam counting starts at install: the worker's next 8 launches
    # fail back-to-back; invocation 9 is unscripted (the heal).
    install_plan(FaultPlan.parse("device.launch:fail@1-8"))

    # Drain pre-fault batches so the worker keeps launching into the
    # seam; the failure streak trips the breaker open.
    _drain_until(pl, pl.breaker.is_open)
    assert pl.breaker.is_open(), "breaker never opened"

    # The mutator's fast-demote path must latch to CPU fallback
    # without burning demote_after drain timeouts.
    deadline = time.time() + 60
    while pm.healthy() and pl.breaker.is_open() \
            and time.time() < deadline:
        pm.next(fz, rng)
    assert not pm.healthy(), "mutator never demoted to CPU"

    # Corpus items added while the device is down must not be lost:
    # they stage host-side and ride the next rebuild.
    added_while_down = 0
    for i in range(3):
        p = generate_prog(target, RandGen(target, 9900 + i), 5)
        if pl.add(p):
            added_while_down += 1
    assert added_while_down > 0

    # Recovery: a half-open probe eventually lands and re-closes.
    deadline = time.time() + 120
    while pl.breaker.state != CLOSED and time.time() < deadline:
        time.sleep(0.02)
    assert pl.breaker.state == CLOSED, "breaker never re-closed"
    assert pl.stats.worker_errors - errors0 >= 8
    snap = pl.breaker.snapshot()
    assert snap["opens"] - snap0["opens"] >= 2, \
        "failed probes must re-open"
    # The one-shot-latch bug: the rebuild must have re-triggered on
    # EVERY half-open re-entry, not fired once at error #4.
    rebuilds = snap["rebuilds"] - snap0["rebuilds"]
    assert rebuilds >= 2, \
        f"rebuild latch fired {rebuilds}x across the streak"
    assert rebuilds == snap["half_opens"] - snap0["half_opens"]
    assert snap["closes"] - snap0["closes"] >= 1

    # The probe thread re-promotes the mutator.
    deadline = time.time() + 60
    while not pm.healthy() and time.time() < deadline:
        time.sleep(0.02)
    assert pm.healthy(), "mutator never re-promoted"
    assert pm.demotions >= 1 and pm.repromotions >= 1

    # Zero lost corpus: every add is still live host-side and the
    # rebuilt ring serves templates for every produced mutant.
    batch = pl.next_batch(timeout=300)
    assert batch
    assert pl.stats.evictions == 0
    assert len(pl) == pl.stats.adds
    live = sum(t is not None for t in pl.templates)
    assert live == pl.stats.adds
    for m in batch[:8]:
        assert pl.templates[int(m.batch.template_idx[m.j])] is not None
    health = pm.health_snapshot()
    assert health["pipeline"]["breaker"]["state"] == CLOSED


def test_watchdog_detects_hung_launch_in_pipeline(device_rig):
    """A hung device.launch (the r5 PJRT wedge) is detected by the
    watchdog within its deadline and converted into a structured
    failure the worker survives — not an eternal worker stall."""
    _target, pl = device_rig
    saved_deadline = pl.watchdog.deadline_s
    pl.watchdog.deadline_s = 0.3
    wedges0 = pl.watchdog.stats.wedges
    errors0 = pl.stats.worker_errors
    plan = install_plan(FaultPlan.parse("device.launch:hang@1"))
    try:
        # Keep draining so the worker keeps launching into the seam.
        _drain_until(
            pl, lambda: pl.watchdog.stats.wedges > wedges0, timeout=30)
        assert pl.watchdog.stats.wedges > wedges0, \
            "watchdog never converted the hang into DeviceWedged"
        assert pl.stats.worker_errors > errors0
        assert pl._worker.is_alive(), "worker thread died on the wedge"

        # Only invocation 1 is scripted: the very next launch succeeds
        # and batches flow again — the wedge cost one deadline, not
        # the fuzzer.
        batch = pl.next_batch(timeout=300)
        assert batch, "pipeline never recovered after the wedge"
    finally:
        pl.watchdog.deadline_s = saved_deadline
        plan.heal("device.launch")  # release the abandoned thread


def test_assembly_pool_ordering_backpressure_under_queue_faults(
        device_rig):
    """ISSUE 3 concurrency: with the parallel assembly pool active,
    scripted queue.put faults drop exactly their batches while
    delivery stays in strict drain order (AssembledBatch.seq
    monotonic, gaps only at the dropped batches), nothing deadlocks,
    production halts at the bounded in-flight budget when nobody
    drains, and the breaker — what PipelineMutator's demote path
    watches — records no device failure."""
    from syzkaller_tpu.fuzzer.proc import PipelineMutator

    _target, pl = device_rig
    assert pl._assemble_workers >= 2, "assembly pool not active"
    pm = PipelineMutator(pl, drain_timeout=30.0)
    drops0 = pl.stats.delivery_errors
    failures0 = pl.breaker.counters.failures
    install_plan(FaultPlan.parse("queue.put:fail@2,4"))
    seqs: list[int] = []
    parsed = 0
    deadline = time.time() + 120
    while (pl.stats.delivery_errors < drops0 + 2 or len(seqs) < 6) \
            and time.time() < deadline:
        try:
            b = pl.next_batch(timeout=0.2)
        except queue.Empty:
            continue
        assert len(b) > 0
        seqs.append(b.seq)
        for m in b[:2]:  # recombined shards produce sound streams
            from syzkaller_tpu.ops.emit import parse_stream

            parse_stream(m.exec_bytes)
            parsed += 1
    assert pl.stats.delivery_errors == drops0 + 2, \
        "scripted delivery faults did not fire exactly twice"
    assert len(seqs) >= 6, "pipeline deadlocked under delivery faults"
    assert parsed > 0
    # Strict drain order across the pool; only the two dropped batches
    # may be missing from the delivered stream.
    assert all(a < b for a, b in zip(seqs, seqs[1:])), seqs
    missing = set(range(seqs[0], seqs[-1] + 1)) - set(seqs)
    assert len(missing) <= 2, (seqs, missing)
    # Backpressure: with no consumer, the worker saturates the
    # prefetch queue + assembling deque and stops producing.
    time.sleep(0.5)
    b0 = pl.stats.batches
    time.sleep(1.0)
    assert pl.stats.batches - b0 <= \
        pl._queue.maxsize + pl._assemble_depth + 1, \
        "production did not halt at the in-flight budget"
    # The delivery seam is not a device failure: breaker closed, no
    # failures recorded, mutator stays promoted.
    assert pl.breaker.counters.failures == failures0
    assert pl.breaker.state == CLOSED
    assert pm.healthy()
    snap = pm.health_snapshot()["pipeline"]
    assert snap["assemble_workers"] >= 2


def test_queue_put_seam_drops_batch_without_tripping_breaker(device_rig):
    _target, pl = device_rig
    drops0 = pl.stats.delivery_errors
    failures0 = pl.breaker.counters.failures
    install_plan(FaultPlan.parse("queue.put:fail@1"))
    batch = _drain_until(
        pl, lambda: pl.stats.delivery_errors > drops0, timeout=30)
    # One batch died at the delivery seam; the next ones still flow.
    assert pl.stats.delivery_errors == drops0 + 1
    if batch is None:
        batch = pl.next_batch(timeout=300)
    assert batch
    assert pl.breaker.state == CLOSED
    assert pl.breaker.counters.failures == failures0


# -- triage engine seam ---------------------------------------------------


def test_triage_fault_plan_demote_cpu_zero_loss_then_repromote():
    """ISSUE 4: scripted failures on the `device.triage` seam trip the
    engine's breaker open (triage demotes to the CPU path), every
    step's results stay byte-identical to a pure-CPU reference (zero
    lost signal — a failed chunk confirms exactly on CPU), and once
    the seam heals a half-open probe re-promotes the device plane and
    rebuilds it from the host mirror."""
    import numpy as np

    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.triage import TriageEngine

    target = get_target("test", "64")
    br = CircuitBreaker(failure_threshold=2, backoff_initial=0.05,
                        backoff_cap=0.1, jitter=0.0, seed=1)
    eng = TriageEngine(batch=8, max_edges=64, breaker=br,
                       watchdog=Watchdog(deadline_s=0),
                       owns_breaker=True)
    fz = Fuzzer(target, wq=WorkQueue())
    fz.set_triage(eng)
    ref = Fuzzer(target, wq=WorkQueue())
    rng = np.random.RandomState(2)
    prio_fn = (lambda errno, idx: 3)

    class _Info:
        __slots__ = ("call_index", "errno", "signal")

        def __init__(self, ci, sig):
            self.call_index = ci
            self.errno = 0
            self.signal = sig

    # Invocations 1-2 trip the threshold-2 breaker; 3 is the failed
    # probe (reopen, doubled backoff); 4+ are clean (the heal).
    install_plan(FaultPlan.parse("device.triage:fail@1-3"))
    saw_open = False
    deadline = time.time() + 60
    while time.time() < deadline:
        edges = rng.randint(0, 1 << dsig.FOLD_BITS, size=16,
                            dtype=np.uint32)
        infos = [_Info(0, edges)]
        a = fz.check_new_signal_fn(prio_fn, infos)
        b = ref.cpu_check_new_signal(prio_fn, infos)
        assert [(ci, d.m) for ci, d in a] == [(ci, d.m) for ci, d in b]
        saw_open = saw_open or br.is_open()
        if br.state == CLOSED and eng.stats.repromotions >= 1:
            break
        time.sleep(0.02)
    assert fz.max_signal.m == ref.max_signal.m  # zero lost signal
    assert fz.new_signal.m == ref.new_signal.m
    assert saw_open, "breaker never opened on the scripted streak"
    snap = eng.snapshot()
    assert snap["device_errors"] >= 3
    assert snap["demotions"] >= 1, "engine never demoted to CPU"
    assert snap["cpu_fallback_calls"] > 0, \
        "demoted checks did not run the CPU path"
    assert snap["repromotions"] >= 1, "engine never re-promoted"
    assert snap["plane_rebuilds"] >= 1, \
        "device plane not rebuilt from the mirror after the failures"
    assert br.state == CLOSED and not snap["demoted"]
    # post-heal: the plane serves filtered verdicts again
    edges = rng.randint(0, 1 << dsig.FOLD_BITS, size=16,
                        dtype=np.uint32)
    infos = [_Info(0, edges)]
    assert len(fz.check_new_signal_fn(prio_fn, infos)) == 1
    misses0 = eng.stats.plane_misses
    assert fz.check_new_signal_fn(prio_fn, infos) == []
    assert eng.stats.plane_misses == misses0 + 1


def test_triage_engine_coresident_with_pipeline_rebuild(device_rig):
    """Plane co-residency (ISSUE 4): the pipeline's half-open ring
    rebuild invalidates the attached engine's device plane, and the
    shared-breaker engine demotes while the pipeline breaker is open
    — symmetric with PipelineMutator's fast-demote."""
    from syzkaller_tpu.triage import TriageEngine

    _target, pl = device_rig
    eng = TriageEngine.for_pipeline(pl, batch=8, max_edges=64)
    try:
        assert pl.triage_engine is eng
        assert eng.breaker is pl.breaker and eng.watchdog is pl.watchdog
        assert not eng.owns_breaker
        eng.share_plane()  # materialize the device plane
        assert eng._plane_dev is not None
        pl._reset_device_state()
        assert eng._plane_dev is None, \
            "ring rebuild did not invalidate the co-resident plane"
        assert "triage" in pl.health_snapshot()
    finally:
        pl.triage_engine = None  # the module-scoped rig lives on


# -- the transfer plane (ISSUE 5) -----------------------------------------


def _mk_infos(rng, n, size=16):
    import numpy as np

    from syzkaller_tpu.ops import signal as dsig

    class _Info:
        __slots__ = ("call_index", "errno", "signal")

        def __init__(self, ci, sig):
            self.call_index = ci
            self.errno = 0
            self.signal = sig

    return [_Info(c, rng.randint(0, 1 << dsig.FOLD_BITS, size=size,
                                 dtype=np.uint32))
            for c in range(n)]


def test_staging_h2d_fault_mid_overlap_strict_delivery():
    """ISSUE 5: scripted `staging.h2d` faults while uploads overlap
    the previous batch's in-flight verdict fetch must not reorder or
    drop verdicts — every staged call resolves exactly once, results
    stay byte-identical to the CPU path (a failed chunk confirms on
    CPU — zero lost signal), and the tripped breaker demotes the
    dispatch depth to serial until a probe re-closes it."""
    import numpy as np

    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.triage import TriageEngine

    target = get_target("test", "64")
    br = CircuitBreaker(failure_threshold=2, backoff_initial=0.05,
                        backoff_cap=0.1, jitter=0.0, seed=1)
    eng = TriageEngine(batch=8, max_edges=64, dispatch_depth=2,
                       breaker=br, watchdog=Watchdog(deadline_s=0),
                       owns_breaker=True)
    fz = Fuzzer(target, wq=WorkQueue())
    fz.set_triage(eng)
    ref = Fuzzer(target, wq=WorkQueue())
    rng = np.random.RandomState(4)
    prio_fn = (lambda errno, idx: 3)
    # Upload 1 is clean; uploads 2-3 fail MID-OVERLAP (each check
    # stages 24 calls = 3 chunks at B=8, so chunk 2's upload flies
    # while chunk 1's verdicts are still in flight).  The failure
    # streak trips the threshold-2 breaker; later uploads are clean.
    install_plan(FaultPlan.parse("staging.h2d:fail@2-3"))
    saw_open = False
    deadline = time.time() + 60
    while time.time() < deadline:
        infos = _mk_infos(rng, 24)
        a = fz.check_new_signal_fn(prio_fn, infos)
        b = ref.cpu_check_new_signal(prio_fn, infos)
        assert [(ci, d.m) for ci, d in a] == [(ci, d.m) for ci, d in b]
        saw_open = saw_open or br.is_open()
        if br.state == CLOSED and eng.stats.repromotions >= 1:
            break
        time.sleep(0.02)
    assert saw_open, "breaker never opened on the scripted streak"
    assert fz.max_signal.m == ref.max_signal.m  # zero lost signal
    assert fz.new_signal.m == ref.new_signal.m
    snap = eng.snapshot()
    assert snap["device_errors"] >= 2
    assert snap["h2d_overlaps"] >= 1, "faults never hit mid-overlap"
    # Strict seq delivery: every dispatched batch resolved, in order,
    # none dropped (failed chunks never got a seq — they resolved on
    # the CPU-confirm path inside the dispatch).
    assert eng._resolve_seq == eng._dispatch_seq
    assert br.state == CLOSED and not snap["demoted"]
    # Demote-to-serial: a non-closed breaker caps the depth at 1,
    # symmetric with PipelineMutator/TriageEngine CPU demotion.
    br.record_failure()
    br.record_failure()
    assert br.is_open()
    assert eng._effective_depth() == 1
    br.record_success()  # half-open bookkeeping done; restore
    assert eng._effective_depth() == eng._dispatch_depth == 2


def test_plane_rebuild_stales_inflight_staged_slot(device_rig):
    """ISSUE 5: a pipeline half-open ring rebuild with a batch
    sitting in the second buffer slot (dispatched, verdicts not yet
    fetched) must lose zero signal: the epoch bump stales the
    in-flight handle and it resolves as a full CPU confirm — without
    counting a device failure against the shared breaker."""
    import numpy as np

    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.triage import TriageEngine
    from syzkaller_tpu.triage.engine import _Entry, _Request

    _target, pl = device_rig
    eng = TriageEngine.for_pipeline(pl, batch=8, max_edges=64,
                                    dispatch_depth=2)
    try:
        rng = np.random.RandomState(6)
        req = _Request(4)
        entries = [
            _Entry(rng.randint(0, 1 << dsig.FOLD_BITS, size=12,
                               dtype=np.uint32), 3, req)
            for _ in range(4)]
        failures0 = pl.breaker.counters.failures
        with eng._device_lock:
            handle = eng._dispatch_chunk(entries)
            assert handle is not None  # in flight in its arena slot
            pl._reset_device_state()  # the half-open rebuild path
            assert eng._plane_dev is None
            eng._resolve_chunk(handle)
        assert req.done.is_set(), "staled batch never resolved"
        assert all(en.flagged for en in entries), \
            "staled batch must confirm every call on CPU (zero loss)"
        assert eng.stats.stale_slots == 1
        assert eng.stats.device_batches == 0  # not a verdict batch
        # Invalidation is recovery bookkeeping, not a device failure.
        assert pl.breaker.counters.failures == failures0
    finally:
        pl.triage_engine = None  # the module-scoped rig lives on


def test_transfer_plane_zero_new_jits_on_warm_pipeline(device_rig):
    """ISSUE 5 compile-count guard: staging-arena growth,
    dispatch-depth changes, and depth-controller adjustments are all
    host-only — zero new jit compiles on a warm pipeline.  Pinned via
    the shared `assert_no_new_compiles` guard (ISSUE 17), which
    watches the jitted callables' cache sizes AND the process build
    ledger, so a violation names the graph that built."""
    import numpy as np

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.ops.staging import DepthController
    from syzkaller_tpu.telemetry.registry import Histogram
    from syzkaller_tpu.triage import TriageEngine
    from syzkaller_tpu.triage.engine import _Entry, _Request

    _target, pl = device_rig
    eng = TriageEngine.for_pipeline(pl, batch=8, max_edges=64,
                                    dispatch_depth=2)
    rng = np.random.RandomState(8)

    def run_chunk():
        req = _Request(3)
        entries = [
            _Entry(rng.randint(0, 1 << dsig.FOLD_BITS, size=10,
                               dtype=np.uint32), 3, req)
            for _ in range(3)]
        with eng._device_lock:
            h = eng._dispatch_chunk(entries)
            assert h is not None
            eng._resolve_chunk(h)
        assert req.done.is_set()

    saved_depth = pl._dispatch_depth
    try:
        run_chunk()  # warm novel_any + the plane upload once
        with telemetry.assert_no_new_compiles(
                pl._step._cache_size, dsig.novel_any._cache_size,
                dsig.merge_into._cache_size,
                dsig.diff_batch._cache_size):
            # 1) staging-arena growth: new host buckets, both arenas.
            pl._staging.acquire(("corpus", 4),
                                {"idx": ((4,), np.int32)})
            eng._arena.acquire(16, {"edges": ((16, 64), np.uint32)})

            # 2) dispatch-depth changes on the live engines.
            eng._dispatch_depth = 1
            run_chunk()
            eng._dispatch_depth = 2
            run_chunk()
            pl._dispatch_depth = 2
            batch = pl.next_batch(timeout=300)
            assert batch

            # 3) depth-controller adjustments (forced moves) +
            # applying a changed assemble depth to the live worker.
            drain, work = Histogram("d"), Histogram("w")
            for _ in range(64):
                drain.observe(0.1)
                work.observe(0.01)
            ctrl = DepthController(initial=1, interval=1, cooldown=0,
                                   drain_hist=drain, work_hist=work)
            assert ctrl.update() == 2 and ctrl.update() == 3
            old_depth = pl._assemble_depth
            pl._assemble_depth = 3
            batch = pl.next_batch(timeout=300)
            assert batch
            pl._assemble_depth = old_depth
    finally:
        pl._dispatch_depth = saved_depth
        pl.triage_engine = None


def test_fused_mutation_core_zero_new_jits_on_warm_pipeline(device_rig):
    """ISSUE 10 compile-count guard: the fused mutate->emit-compact->
    novelty drain is ONE jitted step — steady-state batches (whatever
    novel count each draws, whatever pow2 row prefix the host then
    fetches), and a device-state rebuild that drops the mutant plane
    (the breaker's half-open path) all add ZERO per-batch jit
    compiles after warmup.  Steady-state drains also may not grow the
    staging arena (the flags/corpus re-pads rotate existing
    buckets)."""
    from syzkaller_tpu import telemetry

    _target, pl = device_rig
    assert pl._fused, "device rig must exercise the fused drain"
    assert pl.next_batch(timeout=300)  # warm the fused step
    allocs0 = pl._staging.allocations
    fused0 = pl.stats.fused_batches
    with telemetry.assert_no_new_compiles(pl._step._cache_size):
        for _ in range(2):
            assert pl.next_batch(timeout=300) is not None
        assert pl.stats.fused_batches > fused0
        assert pl.stats.fused_novel_rows > 0
        assert pl._staging.allocations == allocs0, \
            "steady-state drains grew the staging arena"
        # The half-open rebuild drops the mutant plane (dedup history
        # is advisory); the next launch rebuilds it lazily — same
        # shapes, so the step executable is reused, not retraced.
        pl._reset_device_state()
        # No plane-is-None assert here: the worker thread may already
        # be launching the next batch and rebuild it before we look.
        assert pl.next_batch(timeout=300)
        assert pl._mutant_plane is not None


def test_corpus_arena_zero_new_jits_and_zero_steady_h2d(device_rig):
    """ISSUE 18 compile + transfer guards on the warm rig: the
    steady-state hot path moves ZERO host corpus bytes per batch
    (the arena upload counters stay flat across drains with nothing
    staged), and every arena lifecycle event — growth via new corpus
    adds, an epoch bump (invalidate → full authority re-stage), and
    the breaker rebuild's device-state drop — reuses the warm step
    executable: zero new jit compiles, one scatter each."""
    from syzkaller_tpu import telemetry
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    target, pl = device_rig
    # Settle: drain until nothing is pending in the arena (earlier
    # tests in the module may have staged rows).
    _drain_until(pl, lambda: len(pl.arena._pending) == 0)
    assert len(pl.arena._pending) == 0

    with telemetry.assert_no_new_compiles(pl._step._cache_size):
        # -- the zero-steady-state-H2D pin ---------------------------
        up0, bytes0 = pl.arena.uploads, pl.arena.upload_bytes
        for _ in range(3):
            assert pl.next_batch(timeout=300)
        assert pl.arena.uploads == up0 \
            and pl.arena.upload_bytes == bytes0, \
            "steady-state batches moved corpus bytes H2D"

        # -- growth: new adds ride one flush scatter -----------------
        added = 0
        for i in range(2):
            p = generate_prog(target, RandGen(target, 8600 + i), 5)
            if pl.add(p):
                added += 1
        assert added > 0
        _drain_until(pl, lambda: pl.arena.uploads > up0)
        assert pl.arena.uploads > up0
        assert pl.arena.upload_bytes > bytes0

        # -- epoch bump: full re-stage from host authority -----------
        _drain_until(pl, lambda: len(pl.arena._pending) == 0)
        epoch0, up1 = pl.arena.epoch, pl.arena.uploads
        pl.arena.invalidate()
        assert pl.arena.epoch == epoch0 + 1
        _drain_until(pl, lambda: pl.arena.uploads > up1)
        assert pl.arena.uploads > up1

        # -- the breaker rebuild's device-state drop -----------------
        # _reset_device_state is exactly what every half-open
        # re-entry consumes; it must invalidate the arena (another
        # epoch) and recover with a re-upload, never a re-trace.
        _drain_until(pl, lambda: len(pl.arena._pending) == 0)
        epoch1, up2 = pl.arena.epoch, pl.arena.uploads
        pl._reset_device_state()
        assert pl.arena.epoch == epoch1 + 1
        _drain_until(pl, lambda: pl.arena.uploads > up2)
        assert pl.arena.uploads > up2
        assert pl.next_batch(timeout=300)

        # Back to steady state: flat again.
        _drain_until(pl, lambda: len(pl.arena._pending) == 0)
        up3, bytes3 = pl.arena.uploads, pl.arena.upload_bytes
        assert pl.next_batch(timeout=300)
        assert pl.arena.uploads == up3 \
            and pl.arena.upload_bytes == bytes3
    assert pl.health_snapshot()["arena"]["epoch"] == pl.arena.epoch


def test_sim_prescore_fault_demotes_to_passthrough_zero_loss(device_rig):
    """ISSUE 15: scripted `device.sim` failures demote the prescore
    stage to PASS-THROUGH — the faulted launches still deliver their
    batches through the plain fused step (zero lost mutants) and the
    pipeline breaker never hears about it — and once the seam heals
    the next prescored commit re-promotes.  Steady-state prescored
    batches plus the whole demote/heal cycle add zero jit compiles
    after the one-time _step_sim warm-up."""
    _target, pl = device_rig
    assert pl._fused, "prescore requires the fused drain"
    pl.enable_sim_prescore(backend="vmap")
    sim = pl._sim
    sim.breaker.configure_backoff(initial=0.05, cap=0.1)
    try:
        # Warm the prescored step: drain until a prescored batch lands.
        _drain_until(pl, lambda: pl.stats.sim_batches >= 1, timeout=300)
        assert pl.stats.sim_batches >= 1, "no prescored batch arrived"
        from syzkaller_tpu import telemetry

        with telemetry.assert_no_new_compiles(
                pl._step._cache_size, pl._step_sim._cache_size):
            batches0 = sim.batches
            install_plan(FaultPlan.parse("device.sim:fail@1-2"))
            batch = _drain_until(pl, sim.demoted, timeout=60)
            assert sim.demoted(), "prescore never demoted"
            if batch is None:
                batch = pl.next_batch(timeout=300)
            assert batch, "demoted prescore lost a batch"
            # The prescore seam is the sim's OWN breaker's problem:
            # the pipeline breaker stays closed, nothing demotes.
            assert pl.breaker.state == CLOSED

            # Heal (only occurrences 1-2 were scripted): the next
            # prescored commit re-promotes.
            reset_plan()
            _drain_until(pl, lambda: sim.repromotions >= 1,
                         timeout=120)
            assert sim.repromotions >= 1, "prescore never re-promoted"
            assert not sim.demoted()
            assert sim.batches > batches0
            snap = pl.health_snapshot()["sim"]
            assert snap["demotions"] >= 1
            assert snap["repromotions"] >= 1
            assert snap["breaker"]["state"] == CLOSED
    finally:
        reset_plan()
        pl.disable_sim_prescore()
    assert pl._sim is None and pl._step_sim is None
    # Pass-through forever after: the plain fused step still drains.
    assert pl.next_batch(timeout=300)


def test_mesh_reshard_topology_cache_compile_guard(monkeypatch):
    """ISSUE 11 compile-count guard: the fault-domain engine caches
    jitted step graphs per live-topology, so the demote -> serve-from-
    N-1 -> re-promote cycle builds exactly the two expected meshes and
    any topology REVISIT is a pure cache hit (zero new jits).  The
    graph builder is stubbed with a counter so this pins the caching
    policy without burning device compiles; the chaos drill in
    test_mesh_faults asserts the same counts on real jitted graphs."""
    import jax

    from syzkaller_tpu.parallel import fault_domain as fd
    from syzkaller_tpu.parallel import mesh as pmesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    builds = []

    def counting_builder(mesh, **kw):
        builds.append(int(mesh.devices.size))

        def _stub_step(*a, **k):
            raise AssertionError("stub step must never launch")
        return _stub_step

    monkeypatch.setattr(pmesh, "make_fused_mesh_step", counting_builder)
    from syzkaller_tpu import telemetry

    b0 = telemetry.COMPILES.builds("mesh.fused_step")
    shapes0 = set(telemetry.COMPILES.shapes("mesh.fused_step"))
    eng = fd.MeshEngine(devices=jax.devices()[:8], cov=1, rounds=1,
                        plane_size=1 << 16, mutant_bits=10,
                        breaker_threshold=1, seed=3)
    assert builds == [8]
    # Zero backoff BEFORE tripping: the probe time is fixed at trip.
    for d in eng.domains:
        d.breaker.configure_backoff(initial=0.0, cap=0.0)

    # Chip 5 "dies": its breaker opens, the shard demotes, and the
    # engine re-shards over the surviving seven.
    dom = eng.domains[5]
    dom.breaker.record_failure()
    assert dom.breaker.is_open()
    assert eng._demote_opened()
    eng._build()
    assert builds == [8, 7]
    snap = eng.health_snapshot()
    assert snap["devices_live"] == 7
    assert snap["shards"][5]["demoted"]

    # Half-open probe re-admits the chip: the full-width topology was
    # already built, so re-promotion must be a cache hit.
    assert eng._try_repromote()
    assert eng.health_snapshot()["devices_live"] == 8
    assert builds == [8, 7], "re-promote retraced the full mesh"

    # The SAME chip dying again revisits the N-1 graph: cache hit too.
    dom.breaker.record_failure()
    assert eng._demote_opened()
    eng._build()
    assert builds == [8, 7], "revisited topology retraced"
    assert len(eng._graphs) == 2
    # ISSUE 17 re-pin through the CompileObservatory: the drill is
    # exactly two recorded mesh.fused_step builds — one per distinct
    # topology key — and the keys disagree only on the live width.
    assert telemetry.COMPILES.builds("mesh.fused_step") - b0 == 2
    new_shapes = set(
        telemetry.COMPILES.shapes("mesh.fused_step")) - shapes0
    assert len(new_shapes) == 2, new_shapes
    assert {dict(k).get("devices") for k in new_shapes} == {"8", "7"}


def test_coverage_analytics_zero_new_jits_on_warm_rig(device_rig):
    """ISSUE 7 compile-count guard: the coverage analytics kernels
    compile exactly ONCE (pinned plane shape) and the per-batch hot
    path — dispatch/resolve chunks, merges, rebuilds — triggers zero
    new jits with analytics armed.  Flush-cadence means flush
    cadence: repeated analytics passes reuse the same executables."""
    import numpy as np

    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.signal import Signal
    from syzkaller_tpu.triage import TriageEngine
    from syzkaller_tpu.triage.engine import _Entry, _Request

    _target, pl = device_rig
    eng = TriageEngine.for_pipeline(pl, batch=8, max_edges=64)
    rng = np.random.RandomState(21)

    def run_chunk():
        req = _Request(3)
        entries = [
            _Entry(rng.randint(0, 1 << dsig.FOLD_BITS, size=10,
                               dtype=np.uint32), 3, req)
            for _ in range(3)]
        with eng._device_lock:
            h = eng._dispatch_chunk(entries)
            assert h is not None
            eng._resolve_chunk(h)
        assert req.done.is_set()

    def merge_some():
        eng.merge_signal(Signal(
            {int(e): 3 for e in rng.randint(
                0, 1 << dsig.FOLD_BITS, size=16)}))

    try:
        run_chunk()  # warm novel_any + the plane upload
        merge_some()
        run_chunk()  # warm the backlog scatter (merge_into)
        eng.run_analytics(audit=True)  # both analytics kernels compile
        assert dsig.coverage_stats._cache_size() == 1
        assert dsig.plane_drift._cache_size() == 1
        occ0 = eng._occupancy
        from syzkaller_tpu import telemetry

        with telemetry.assert_no_new_compiles(
                pl._step._cache_size, dsig.novel_any._cache_size,
                dsig.merge_into._cache_size,
                dsig.coverage_stats._cache_size,
                dsig.plane_drift._cache_size):
            for _ in range(3):
                merge_some()
                run_chunk()
                eng.run_analytics(audit=True)
            assert eng._occupancy > occ0  # popcount tracked the merges
            # a rebuild (invalidation) + re-analytics re-jits nothing
            eng.invalidate_device_plane()
            run_chunk()
            eng.run_analytics(audit=True)
        assert dsig.coverage_stats._cache_size() == 1, \
            "analytics kernels must compile exactly once"
    finally:
        pl.triage_engine = None  # the module-scoped rig lives on


def test_warm_restart_zero_new_jits(device_rig):
    """ISSUE 13 compile-count guard: restoring a recovered signal
    mirror (restore_mirror) and mutant plane (restore_mutant_plane)
    re-uploads through the EXISTING host-mirror/jnp.asarray paths —
    one H2D each, zero new jit compiles on a warm rig.  Recovery must
    never pay a compile storm on top of a crash."""
    import numpy as np

    from syzkaller_tpu.ops import signal as dsig
    from syzkaller_tpu.triage import TriageEngine
    from syzkaller_tpu.triage.engine import _Entry, _Request

    _target, pl = device_rig
    eng = TriageEngine.for_pipeline(pl, batch=8, max_edges=64)
    rng = np.random.RandomState(31)

    def run_chunk():
        req = _Request(2)
        entries = [
            _Entry(rng.randint(0, 1 << dsig.FOLD_BITS, size=10,
                               dtype=np.uint32), 3, req)
            for _ in range(2)]
        with eng._device_lock:
            h = eng._dispatch_chunk(entries)
            assert h is not None
            eng._resolve_chunk(h)
        assert req.done.is_set()

    try:
        run_chunk()  # warm novel_any + the plane upload
        from syzkaller_tpu import telemetry

        with telemetry.assert_no_new_compiles(
                pl._step._cache_size, dsig.novel_any._cache_size,
                dsig.merge_into._cache_size):
            # the checkpoint/restore round trip, as recovery performs
            # it: provider packs the mirror, restore installs it and
            # drops the device plane
            meta, blob = eng.durable_provider()
            mirror = dsig.unpack_plane(blob, meta["size"])
            rebuilds0 = eng.stats.plane_rebuilds
            eng.restore_mirror(mirror)
            run_chunk()  # forces the rebuild H2D, normal path
            assert eng.stats.plane_rebuilds == rebuilds0 + 1
            # mutant-plane restore rides the same discipline
            mmeta, mblob = pl.durable_mutant_plane()
            pl.restore_mutant_plane(
                dsig.unpack_plane(mblob, mmeta["size"]),
                bits=mmeta["bits"])
    finally:
        pl.triage_engine = None  # the module-scoped rig lives on


# -- device-residency conservation (ISSUE 17) -----------------------------


def test_hbm_ledger_conservation_on_warm_rig(device_rig):
    """ISSUE 17 conservation: the bytes the residency ledger tracks
    for the warm pipeline's device buffers equal the backend's
    live-buffer report for exactly those buffers (drift 0, no
    orphaned entries), the invariant survives the breaker-path
    device-state rebuild, and reconcile itself is host-only — zero
    new jit compiles on the warm rig."""
    from syzkaller_tpu import telemetry

    import gc

    _target, pl = device_rig
    assert pl.next_batch(timeout=300)  # tables + planes resident
    # Earlier tests dropped transient triage/sim engines; their
    # handles close at collection (register's bound_to), so flush the
    # finalizers before asserting conservation over the live set.
    gc.collect()

    def settled_reconcile():
        # The worker legitimately swaps the mutant plane between the
        # ledger snapshot and the backend report; one retry absorbs
        # that race exactly like the production two-strike rule does.
        for _ in range(3):
            rec = telemetry.HBM.reconcile()
            if not rec["flagged"]:
                return rec
            time.sleep(0.1)
        return rec

    with telemetry.assert_no_new_compiles(pl._step._cache_size):
        rec = settled_reconcile()
    assert rec["entries"] >= 1, "warm pipeline registered no buffers"
    assert rec["dead_entries"] == 0 and rec["drift_bytes"] == 0, rec
    assert not rec["flagged"], rec
    assert telemetry.HBM.live_bytes("pipeline") > 0

    # The breaker's half-open rebuild drops device state; every
    # dropped buffer's handle must be updated, not orphaned —
    # conservation holds again once the rig re-warms.
    pl._reset_device_state()
    assert pl.next_batch(timeout=300)
    rec = settled_reconcile()
    assert rec["dead_entries"] == 0 and rec["drift_bytes"] == 0, rec

    snap = telemetry.HBM.snapshot()
    assert snap["headroom_bytes"] == (
        snap["capacity_bytes"] - snap["device_resident_bytes"]
        - snap["transient_bytes"])
    assert snap["owners"]["pipeline"]["peak_bytes"] \
        >= snap["owners"]["pipeline"]["live_bytes"]


# -- lineage + flight recorder + profiler on the warm rig (ISSUE 6) -------


def test_lineage_trace_threads_warm_pipeline(device_rig, fuzzer_state,
                                             tmp_path):
    """A sampled mutant's trace id survives DeltaBatch → assembly →
    the RPC frame → triage verdict intact, and the TZ_TRACE_FILE
    JSONL renders the lifecycle as ONE correlated track (same trace
    id from pipeline flush through the verdict, hops on ≥2 threads —
    the production deployment's second process supplies the second
    pid the same way).  Shares the warm rig: no new jit compiles."""
    import json

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.fuzzer.proc import PipelineMutator
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.rpc import RPCClient, RPCServer
    from syzkaller_tpu.telemetry import lineage

    target, pl = device_rig
    _, fz = fuzzer_state
    trace_path = tmp_path / "trace.json"
    telemetry.set_trace_file(str(trace_path))
    lineage.set_sample_rate(1.0)
    srv = RPCServer()

    class Svc:
        def NewInput(self, params):
            return {}

    srv.register("Manager", Svc())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    pm = PipelineMutator(pl, drain_timeout=60.0)
    pm._fed = fz.corpus_len()
    rng = RandGen(target, 23)
    try:
        # Draw until a device mutant off a SAMPLED batch arrives
        # (batches launched before arming carry trace=None).
        m = None
        deadline = time.time() + 120
        while time.time() < deadline:
            cand = pm.next(fz, rng)
            if cand is not None and hasattr(cand, "exec_bytes") \
                    and cand.trace is not None:
                m = cand
                break
        assert m is not None, "no sampled device mutant produced"
        ctx = m.trace
        # The context is the BATCH's: every mutant shares it, and the
        # delta batch it views carries the same object.
        assert m.batch.trace is ctx
        assert ctx.last_stage == "proc.draw"
        # RPC frame: the id crosses the transport intact.
        cli.call("Manager.NewInput", {"x": 1}, trace=ctx)
        # Triage verdict on the exec result (CPU path — the fixture
        # fuzzer has no engine; engine delivery is hopped in
        # TriageEngine.check the same way).
        class _Info:
            call_index, errno, signal = 0, 0, [1, 2, 3]

        fz.check_new_signal_fn(lambda e, i: 3, [_Info()], trace=ctx)
        assert ctx.last_stage == "triage.verdict"
    finally:
        cli.close()
        srv.close()
        lineage.set_sample_rate(None)
        telemetry.set_trace_file(None)
    events = [json.loads(ln.rstrip(",")) for ln in
              trace_path.read_text().splitlines()[1:]]
    track = [e for e in events if e.get("cat") == "tz.lineage"
             and e.get("id") == format(ctx.trace_id, "016x")]
    stages = [e["name"] for e in track]
    for stage in ("lineage.mint", "pipeline.deliver", "proc.draw",
                  "rpc.frame", "triage.verdict"):
        assert stage in stages, (stage, stages)
    # flush (worker thread), draw (this thread), rpc (server thread)
    assert len({e["tid"] for e in track}) >= 2
    # queue-time histograms fell out of the hops
    for name in ("tz_lineage_deliver_wait_seconds",
                 "tz_lineage_draw_wait_seconds",
                 "tz_lineage_rpc_wait_seconds",
                 "tz_lineage_verdict_wait_seconds"):
        assert telemetry.REGISTRY.histogram(name).count > 0, name


def test_device_wedged_writes_flight_incident(device_rig, tmp_path):
    """Acceptance (ISSUE 6): an injected DeviceWedged (TZ_FAULT_PLAN
    seam) produces a flight-recorder incident file with the breaker
    timeline, last-N spans, and queue-depth history — and
    bench_watch's diagnostics render it."""
    import json
    import os

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.tools import bench_watch as bw

    _target, pl = device_rig
    telemetry.FLIGHT.set_dir(str(tmp_path))
    saved_interval = telemetry.FLIGHT.min_interval_s
    telemetry.FLIGHT.min_interval_s = 0.0
    saved_deadline = pl.watchdog.deadline_s
    pl.watchdog.deadline_s = 0.3
    wedges0 = pl.watchdog.stats.wedges
    plan = install_plan(FaultPlan.parse("device.launch:hang@1"))
    try:
        path = os.path.join(
            tmp_path, f"tz_flight_device_wedged_{os.getpid()}.json")
        # The wedge counter increments just before the dump lands on
        # disk, so the wait condition is the file itself.
        _drain_until(pl, lambda: os.path.exists(path), timeout=30)
        assert pl.watchdog.stats.wedges > wedges0
        assert os.path.exists(path), "wedge did not dump an incident"
        incident = json.loads(open(path).read())
        assert incident["reason"] == "device_wedged"
        assert any(n == "watchdog.wedge"
                   for _ts, n, _d in incident["breaker_timeline"])
        assert incident["spans"], "no span ring in the incident"
        assert incident["queue_depths"], "no queue-depth history"
        lines = bw.flight_report(incident)
        text = "\n".join(lines)
        assert "incident: device_wedged" in text
        assert "watchdog.wedge" in text
        assert "last spans:" in text
        # pipeline recovers (only invocation 1 was scripted)
        batch = pl.next_batch(timeout=300)
        assert batch
    finally:
        pl.watchdog.deadline_s = saved_deadline
        telemetry.FLIGHT.set_dir(None)
        telemetry.FLIGHT.min_interval_s = saved_interval
        plan.heal("device.launch")


def test_profiler_always_on_zero_new_jits(device_rig):
    """ISSUE 6: the always-on per-kernel attribution is pure host
    float math — gauges advance with every drained batch while the
    jitted callables' caches stay untouched, and the profiler's
    fixed-slot storage never grows (no steady-state allocations)."""
    from syzkaller_tpu import telemetry
    from syzkaller_tpu.telemetry.profiler import KERNELS

    _target, pl = device_rig
    prof = telemetry.PROFILER
    batches0 = prof.snapshot()["mutate"]["batches"]
    slots0 = (len(prof._ewma), len(prof._counts), len(prof._gauges))
    with telemetry.assert_no_new_compiles(pl._step._cache_size):
        batch = pl.next_batch(timeout=300)
        assert batch
        deadline = time.time() + 30
        while prof.snapshot()["mutate"]["batches"] == batches0 \
                and time.time() < deadline:
            time.sleep(0.05)
        snap = prof.snapshot()
        assert snap["mutate"]["batches"] > batches0
        assert snap["emit_compact"]["batches"] > 0
    assert (len(prof._ewma), len(prof._counts),
            len(prof._gauges)) == slots0
    assert set(prof._ewma) == set(KERNELS)
    g = telemetry.REGISTRY.gauge("tz_device_kernel_ms_per_batch",
                                 labels={"kernel": "mutate"})
    assert g.value >= 0.0 and g.full_name.endswith('{kernel="mutate"}')


# -- rpc seams ------------------------------------------------------------


class _Echo:
    def Ping(self, params):
        return {"pong": params.get("n")}


def test_rpc_send_seam_exercises_client_retry():
    """fail@N on rpc.send_frame kills the pooled connection exactly
    once; the client's reconnect-and-resend path recovers
    transparently (the real stale-connection code path)."""
    from syzkaller_tpu.rpc import RPCClient, RPCServer

    srv = RPCServer()
    srv.register("Echo", _Echo())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    try:
        assert cli.call("Echo.Ping", {"n": 1}) == {"pong": 1}
        # Seam counting starts at plan install.  Call 2 burns send
        # invocations 1 (client request) and 2 (server response);
        # call 3's request is invocation 3 — scripted to fail on the
        # pooled connection, recovered by reconnect-and-resend.
        plan = install_plan(FaultPlan.parse("rpc.send_frame:fail@3"))
        assert cli.call("Echo.Ping", {"n": 2}) == {"pong": 2}
        assert plan.fired("rpc.send_frame") == 0
        assert cli.call("Echo.Ping", {"n": 3}) == {"pong": 3}
        assert plan.fired("rpc.send_frame") == 1
    finally:
        cli.close()
        srv.close()


def test_rpc_recv_seam_surfaces_connection_error():
    from syzkaller_tpu.rpc import RPCClient, RPCServer

    srv = RPCServer()
    srv.register("Echo", _Echo())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, timeout_s=5.0)
    try:
        assert cli.call("Echo.Ping", {"n": 1}) == {"pong": 1}
        # The client's NEXT recv (invocation 3: server already did
        # recv #1... counting is process-wide, so script by mode
        # instead: every recv fails until healed.
        plan = install_plan(FaultPlan.parse("rpc.recv_frame:fail@*"))
        with pytest.raises((ConnectionError, OSError)):
            cli.call("Echo.Ping", {"n": 2})
        plan.heal("rpc.recv_frame")
        assert cli.call("Echo.Ping", {"n": 3}) == {"pong": 3}
    finally:
        cli.close()
        srv.close()


def test_hints_fault_plan_demote_cpu_zero_loss_then_repromote():
    """ISSUE 19: scripted failures on the `device.hints` seam trip the
    lane's breaker open (hints demote to the exact per-program CPU
    path), every run's mutant sequence stays byte-identical to the
    mutate_with_hints host reference (zero lost comparison traces —
    a failed chunk expands exactly on CPU), and once the seam heals a
    half-open probe re-promotes the fused device batch."""
    import numpy as np

    from syzkaller_tpu.health import SEAMS
    from syzkaller_tpu.models.encoding import serialize_prog
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.hints import CompMap, mutate_with_hints
    from syzkaller_tpu.models.prog import ConstArg, foreach_arg
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target
    from syzkaller_tpu.ops.hintlane import HintLane

    assert "device.hints" in SEAMS
    target = get_target("test", "64")
    br = CircuitBreaker(failure_threshold=2, backoff_initial=0.05,
                        backoff_cap=0.1, jitter=0.0, seed=1)
    lane = HintLane(breaker=br, watchdog=Watchdog(deadline_s=0),
                    owns_breaker=True)
    rs = np.random.RandomState(5)

    def case(seed):
        p = generate_prog(target, RandGen(target, seed), 3)
        cm = CompMap()

        def harvest(arg, ctx):
            if isinstance(arg, ConstArg) and arg.typ is not None:
                cm.add_comp(arg.val, int(rs.randint(1, 1 << 32)))

        for c in p.calls:
            foreach_arg(c, harvest)
        return p, cm

    def run_both(seed):
        p, cm = case(seed)
        cpu_out: list[bytes] = []
        dev_out: list[bytes] = []
        mutate_with_hints(p, 0, cm,
                          lambda m: cpu_out.append(serialize_prog(m)))
        lane.run(p, 0, cm, lambda m: dev_out.append(serialize_prog(m)))
        assert dev_out == cpu_out, f"seed {seed}: lane diverged"

    run_both(100)  # warm the kernel with the seam clean
    assert lane.stats.device_batches > 0

    # Dispatches 1-2 trip the threshold-2 breaker open; while open,
    # runs take the CPU path without touching the seam; the half-open
    # probe after the 0.05s backoff hits a healed seam and re-closes.
    install_plan(FaultPlan.parse("device.hints:fail@1-2"))
    saw_open = False
    seed = 200
    deadline = time.time() + 60
    while time.time() < deadline:
        run_both(seed)
        seed += 1
        saw_open = saw_open or br.is_open()
        if br.state == CLOSED and lane.stats.repromotions >= 1:
            break
        time.sleep(0.02)
    assert saw_open, "breaker never opened on the scripted streak"
    assert lane.stats.device_errors >= 2
    assert lane.stats.demotions >= 1, "lane never demoted to CPU"
    assert lane.stats.cpu_fallback_values > 0, \
        "demoted runs did not expand on the CPU path"
    assert lane.stats.repromotions >= 1, "lane never re-promoted"
    assert br.state == CLOSED and not lane.demoted()
    # Post-heal: flushes resolve on device again.
    batches0 = lane.stats.device_batches
    run_both(seed + 1)
    assert lane.stats.device_batches > batches0
