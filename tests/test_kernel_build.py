"""Kernel build + image pipeline on a stub makefile tree (VERDICT r4
ask #9; reference: pkg/kernel/kernel.go, syz-ci/manager.go:235).

The stub tree implements the same make targets a kernel tree exposes
(defconfig / olddefconfig / bzImage), so the pipeline driver is
exercised end to end — configure writes and normalizes .config with
the fuzzing fragment, build produces the bzImage, make_image packages
a bootable {kernel, initrd} pair whose initramfs is a valid newc cpio
containing /init and the executor."""

from __future__ import annotations

import os
import subprocess

import pytest

from syzkaller_tpu.ci.ci import CI, CIConfig, ManagedInstance
from syzkaller_tpu.ci.kernel import (
    BuildError,
    FUZZING_CONFIG,
    KernelBuilder,
    cpio_newc,
)

STUB_MAKEFILE = r"""
O ?= .
defconfig:
	mkdir -p $(O)/arch/x86/boot
	printf 'CONFIG_64BIT=y\n' > $(O)/.config
olddefconfig:
	printf '# normalized\n' >> $(O)/.config
bzImage:
	mkdir -p $(O)/arch/x86/boot
	printf 'FAKEKERNEL' > $(O)/arch/x86/boot/bzImage
broken:
	exit 1
"""


@pytest.fixture
def stub_tree(tmp_path):
    src = tmp_path / "linux"
    src.mkdir()
    (src / "Makefile").write_text(STUB_MAKEFILE)
    return str(src)


def test_configure_build_image(stub_tree, tmp_path):
    out = str(tmp_path / "kbuild")
    kb = KernelBuilder(kernel_src=stub_tree, out_dir=out)
    cfg = kb.configure()
    text = open(cfg).read()
    assert "CONFIG_64BIT=y" in text          # defconfig ran
    assert "CONFIG_KCOV=y" in text           # fuzzing fragment applied
    assert "CONFIG_KASAN=y" in text
    assert text.endswith("# normalized\n")   # olddefconfig ran last

    image = kb.make_image(str(tmp_path / "image"))
    assert open(image["kernel"], "rb").read() == b"FAKEKERNEL"
    data = open(image["initrd"], "rb").read()
    assert data.startswith(b"070701")        # newc magic
    assert b"init\0" in data
    assert b"TRAILER!!!" in data


def test_image_packs_executor(stub_tree, tmp_path):
    exe = tmp_path / "tz-executor"
    exe.write_bytes(b"\x7fELF-fake")
    kb = KernelBuilder(kernel_src=stub_tree, out_dir=str(tmp_path / "o"))
    kb.configure()
    image = kb.make_image(str(tmp_path / "img"), executor=str(exe))
    data = open(image["initrd"], "rb").read()
    assert b"bin/tz-executor\0" in data
    assert b"\x7fELF-fake" in data


def test_build_failure_surfaces(stub_tree, tmp_path):
    kb = KernelBuilder(kernel_src=stub_tree, out_dir=str(tmp_path / "o"),
                       defconfig="broken")
    with pytest.raises(BuildError):
        kb.configure()


def test_cpio_is_readable_by_system_cpio(tmp_path):
    """The archive must round-trip through the system cpio/file tools
    when present — it is what the kernel's initramfs loader parses."""
    import shutil

    data = cpio_newc([("init", 0o755, b"#!/bin/sh\n"),
                      ("bin", 0o40755, b""),
                      ("bin/x", 0o644, b"payload-bytes")])
    p = tmp_path / "t.cpio"
    p.write_bytes(data)
    if shutil.which("cpio"):
        res = subprocess.run(["cpio", "-it"], input=data,
                             capture_output=True, timeout=30)
        names = res.stdout.decode().split()
        assert names == ["init", "bin", "bin/x"], (names, res.stderr)
    else:
        assert data.startswith(b"070701")


def test_ci_drives_kernel_pipeline(stub_tree, tmp_path):
    ci = CI(CIConfig(workdir=str(tmp_path / "ci"), managers=[]))
    m = ManagedInstance(name="kmgr", kernel_src=stub_tree)
    assert ci._build(m)
    assert m.last_build_ok
    assert os.path.exists(m.image["kernel"])
    assert os.path.exists(m.image["initrd"])

    bad = ManagedInstance(name="bad", kernel_src=stub_tree,
                          kernel_defconfig="broken")
    assert not ci._build(bad)
    assert "make broken failed" in bad.last_error
