"""FreeBSD execution capability (VERDICT r4 ask #4): the executor's
BSD backend type-checks end to end and csource renders BSD-buildable
C for freebsd-target programs.

No FreeBSD host or sysroot exists in this image, so the contract
verified here is the one the ask names: the executor BUILDS against a
FreeBSD-selecting compile (the TZ_OS_FREEBSD force-flag compiles the
exact code path __FreeBSD__ selects; its surface is plain POSIX), and
a freebsd-targeted csource compiles cleanly.  Execution on a real BSD
host stays untested, loudly (reference analog: per-OS executor builds
via sys/targets cflags, reference Makefile:139-144 +
executor/common_bsd.h)."""

from __future__ import annotations

import os
import subprocess

import pytest

from syzkaller_tpu.csource.csource import Options, write_csource
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_executor_freebsd_backend_typechecks():
    res = subprocess.run(["make", "freebsd-check"],
                         cwd=os.path.join(REPO, "executor"),
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "OK" in res.stdout


def test_freebsd_csource_renders_and_compiles(tmp_path):
    target = get_target("freebsd", "amd64")
    p = generate_prog(target, RandGen(target, 11), 6)
    src = write_csource(p, Options(repeat=False)).decode()
    # raw-syscall rendering (via the 64-bit-clean tz_syscall shim),
    # no linux pseudo bodies
    assert "tz_syscall(" in src
    assert "sim_call(" not in src
    assert "__NR_" not in src  # numeric NRs: no libc syscall-name dep
    path = str(tmp_path / "tz_bsd_repro.c")
    with open(path, "w") as f:
        f.write(src)
    # Host gcc syntax pass: the output's only OS-conditional include
    # is the endian header; everything else is portable POSIX, so a
    # clean host compile is a faithful proxy for the BSD cc pass.
    res = subprocess.run(
        ["gcc", "-fsyntax-only", "-Wall", path],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr


def test_netbsd_csource_renders():
    target = get_target("netbsd", "amd64")
    p = generate_prog(target, RandGen(target, 13), 6)
    src = write_csource(p, Options()).decode()
    assert "tz_syscall(" in src and "sim_call(" not in src
