"""Tests for the corpus DB, RPC transport, and strict config loader."""

import threading

import pytest

from syzkaller_tpu.db import open_db
from syzkaller_tpu.rpc import RPCClient, RPCError, RPCServer
from syzkaller_tpu.utils.config import ConfigError
from syzkaller_tpu.manager.mgrconfig import load_config


# -- db ------------------------------------------------------------------


def test_db_roundtrip(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    db.save("a", b"hello", 1)
    db.save("b", b"\x00\xffbinary", 7)
    db.flush()
    db2 = open_db(path)
    assert db2.records["a"].val == b"hello"
    assert db2.records["a"].seq == 1
    assert db2.records["b"].val == b"\x00\xffbinary"
    assert db2.records["b"].seq == 7


def test_db_supersede_and_delete(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    db.save("k", b"v1", 1)
    db.flush()
    db.save("k", b"v2", 2)
    db.delete("gone")
    db.save("gone", b"x", 1)
    db.delete("gone")
    db.flush()
    db2 = open_db(path)
    assert db2.records["k"].val == b"v2"
    assert "gone" not in db2.records


def test_db_corrupted_tail(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    for i in range(5):
        db.save(f"k{i}", bytes([i]) * 10, i)
    db.flush()
    with open(path, "ab") as f:
        f.write(b"\x50\x00\x00\x00garbage-that-is-not-a-record")
    db2 = open_db(path)
    assert len(db2.records) == 5
    # and the file was repaired: reopening again still works
    db2.save("k9", b"y", 9)
    db2.flush()
    assert len(open_db(path).records) == 6


def test_db_corrupted_header_keeps_records(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    for i in range(5):
        db.save(f"k{i}", bytes([i]) * 10, i)
    db.flush()
    with open(path, "r+b") as f:
        f.write(b"\xde\xad")  # flip the magic
    db2 = open_db(path)
    assert len(db2.records) == 5  # corpus survives a corrupt header
    # the header was repaired in place with the caller's version
    db3 = open_db(path)
    assert len(db3.records) == 5
    assert db3.version == db.version


def test_db_compaction(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    for i in range(300):
        db.save("same-key", bytes(50), i)
        db.flush()
    import os

    # 300 versions of one record must have been compacted down
    assert os.path.getsize(path) < 300 * 30
    db2 = open_db(path)
    assert db2.records["same-key"].seq == 299


def test_db_version_bump(tmp_path):
    path = str(tmp_path / "corpus.db")
    db = open_db(path, version=1)
    db.save("k", b"v", 0)
    db.bump_version(4)
    assert open_db(path).version == 4


def test_db_append_fault_keeps_pending(tmp_path):
    """ISSUE 13 satellite: a flush dying mid-append (db.append seam)
    must leave `pending` intact so the next flush re-appends — the
    partially-written records are superseded by key, never lost."""
    from syzkaller_tpu.health.faultinject import (FaultPlan,
                                                  install_plan,
                                                  reset_plan)

    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    db.save("a", b"va", 1)
    db.save("b", b"vb", 2)
    db.save("c", b"vc", 3)
    install_plan(FaultPlan.parse("db.append:fail@2"))
    try:
        with pytest.raises(ConnectionError):
            db.flush()
        assert set(db.pending) == {"a", "b", "c"}
        # the interrupted file still opens (zero or more whole
        # records; never a torn one surviving)
        assert set(open_db(path).records) <= {"a", "b", "c"}
    finally:
        reset_plan()
    db.flush()
    db2 = open_db(path)
    assert {k: r.val for k, r in db2.records.items()} == {
        "a": b"va", "b": b"vb", "c": b"vc"}


def test_db_compact_fault_old_file_authoritative(tmp_path):
    """A crash between the compaction tmp's fsync and its rename
    (db.compact seam) leaves the old file authoritative; the next
    open unlinks the orphaned tmp instead of mistaking it for data."""
    import os

    from syzkaller_tpu.health.faultinject import (FaultPlan,
                                                  install_plan,
                                                  reset_plan)

    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    for i in range(4):
        db.save(f"k{i}", bytes([i]) * 8, i)
    db.flush()
    install_plan(FaultPlan.parse("db.compact:fail@1"))
    try:
        with pytest.raises(ConnectionError):
            db.bump_version(9)
        assert os.path.exists(path + ".tmp")
    finally:
        reset_plan()
    db2 = open_db(path)
    assert not os.path.exists(path + ".tmp")  # stale tmp cleaned
    assert len(db2.records) == 4
    assert db2.version != 9  # the rename never published


def test_db_fsync_escape_hatch(tmp_path, monkeypatch):
    """TZ_DB_FSYNC=0 trades the append-path fsync for throughput; the
    flush still lands records (just without the durability barrier)."""
    monkeypatch.setenv("TZ_DB_FSYNC", "0")
    path = str(tmp_path / "corpus.db")
    db = open_db(path)
    db.save("k", b"v", 1)
    db.flush()
    assert open_db(path).records["k"].val == b"v"


# -- rpc -----------------------------------------------------------------


class EchoService:
    def __init__(self):
        self.calls = []

    def Echo(self, params):
        self.calls.append(params)
        return {"echo": params}

    def Fail(self, params):
        raise ValueError("nope")


@pytest.fixture
def rpc_pair():
    srv = RPCServer(("127.0.0.1", 0))
    svc = EchoService()
    srv.register("Manager", svc)
    srv.serve_in_background()
    client = RPCClient(srv.addr, name="test")
    yield srv, svc, client
    client.close()
    srv.close()


def test_rpc_roundtrip(rpc_pair):
    _, svc, client = rpc_pair
    res = client.call("Manager.Echo", {"x": 1, "y": "z"})
    assert res == {"echo": {"x": 1, "y": "z"}}
    assert svc.calls == [{"x": 1, "y": "z"}]


def test_rpc_large_payload_compressed(rpc_pair):
    _, _, client = rpc_pair
    big = "A" * (1 << 20)
    res = client.call_transient("Manager.Echo", {"blob": big})
    assert res["echo"]["blob"] == big


def test_rpc_error_propagates(rpc_pair):
    _, _, client = rpc_pair
    with pytest.raises(RPCError, match="nope"):
        client.call("Manager.Fail", {})
    # connection still usable after a server-side error
    assert client.call("Manager.Echo", {}) == {"echo": {}}


def test_rpc_unknown_method(rpc_pair):
    _, _, client = rpc_pair
    with pytest.raises(RPCError, match="unknown method"):
        client.call("Manager.Missing", {})
    with pytest.raises(RPCError, match="unknown method"):
        client.call("Nope.Echo", {})


def test_rpc_concurrent_clients(rpc_pair):
    srv, _, _ = rpc_pair
    results = []

    def worker(i):
        c = RPCClient(srv.addr)
        for j in range(20):
            results.append(c.call("Manager.Echo", {"i": i, "j": j}))
        c.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 80


# -- config --------------------------------------------------------------


def test_config_defaults(tmp_path):
    cfg = load_config({"workdir": str(tmp_path), "target": "test/64"})
    assert cfg.procs == 1
    assert cfg.sandbox == "none"
    assert cfg.name  # derived from workdir


def test_config_unknown_field_rejected(tmp_path):
    with pytest.raises(ConfigError, match="unknown config field"):
        load_config({"workdir": str(tmp_path), "porcs": 4})


def test_config_validation(tmp_path):
    with pytest.raises(ConfigError, match="workdir"):
        load_config({})
    with pytest.raises(ConfigError, match="procs"):
        load_config({"workdir": str(tmp_path), "procs": 0})
    with pytest.raises(ConfigError, match="sandbox"):
        load_config({"workdir": str(tmp_path), "sandbox": "chroot"})
    with pytest.raises(ConfigError, match="hub"):
        load_config({"workdir": str(tmp_path), "hub_client": "c"})


def test_config_file_with_comments(tmp_path):
    p = tmp_path / "mgr.cfg"
    p.write_text('{\n// the workdir\n"workdir": "%s",\n'
                 '"vm": {"qemu_args": "-enable-kvm", "cpu": 2}\n}'
                 % str(tmp_path))
    cfg = load_config(str(p))
    assert cfg.vm["cpu"] == 2
