"""Delta-transfer oracle: the sparse path (touched journal ->
make_packer -> assemble_delta) must be BIT-IDENTICAL to the dense path
(full mutated rows -> assemble) for the same device mutation, and
rebuild_row must reconstruct the full row exactly."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.delta import (  # noqa: E402
    DeltaBatch,
    DeltaSpec,
    make_packer,
    make_pooler,
)
from syzkaller_tpu.ops.emit import (  # noqa: E402
    assemble,
    assemble_delta,
    build_exec_template,
)
from syzkaller_tpu.ops.mutate import _mutate_one  # noqa: E402
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    FlagTables,
    TensorConfig,
    encode_prog,
)


def _encode_some(target, n, cfg, flags, seed0=500):
    tensors = []
    i = 0
    while len(tensors) < n and i < n * 8:
        p = generate_prog(target, RandGen(target, seed0 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    assert tensors
    return tensors


def test_delta_matches_dense_assembly(test_target, iters):
    cfg = TensorConfig(max_slots=128, arena=2048, max_blob=768)
    flags = FlagTables.empty()
    spec = DeltaSpec()
    tensors = _encode_some(test_target, 8, cfg, flags)
    pack = make_packer(spec)
    pool1 = make_pooler(spec, 1)

    def both(state, key, tidx):
        mutated = _mutate_one(state, key, fv, fc, 4)
        row, payload, needs = pack(mutated, tidx)
        return mutated, pool1(row[None], payload[None], needs[None])

    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    fn = jax.jit(lambda st, k, i: both(st, k, i))
    key = random.key(11)
    checked = 0
    for it in range(iters * 3):
        t = tensors[it % len(tensors)]
        et = build_exec_template(t)
        state = {k: jnp.asarray(v) for k, v in t.arrays().items()}
        key, sub = random.split(key)
        mutated, flat = fn(state, sub, jnp.int32(it % len(tensors)))
        batch = DeltaBatch(np.asarray(flat), spec, 1)
        if batch.overflowed(0):
            continue
        mut = {k: np.asarray(v) for k, v in mutated.items()}
        dense = assemble(et, mut["val"], mut["len_"], mut["arena"],
                         mut["call_alive"])
        sparse = assemble_delta(et, batch, 0)
        assert sparse == dense, f"delta/dense mismatch at iteration {it}"

        # rebuild_row reconstructs the full mutated row exactly for
        # every field the decode path reads.
        rebuilt = batch.rebuild_row(0, t)
        assert bool(rebuilt["preserve_sizes"]) == bool(mut["preserve_sizes"])
        np.testing.assert_array_equal(rebuilt["val"], mut["val"])
        np.testing.assert_array_equal(rebuilt["len_"], mut["len_"])
        np.testing.assert_array_equal(
            rebuilt["call_alive"][:t.ncalls], mut["call_alive"][:t.ncalls])
        # Arena: only changed spans are shipped; compare the spans the
        # decode path reads (each DATA slot's [off, off+len)).
        for s in range(len(t.slot_args)):
            if t.len_target is not None and et.len_word[s] >= 0:
                off = int(t.off[s])
                ln = int(rebuilt["len_"][s])
                np.testing.assert_array_equal(
                    rebuilt["arena"][off:off + ln],
                    mut["arena"][off:off + ln])
        checked += 1
    assert checked >= iters


def test_compact_pooler_matches_flat_layout(test_target):
    """Compacted D2H (ISSUE 3): make_compact_pooler's separate
    rows/pool/used-count must describe the same batch as make_pooler's
    flat rows++pool buffer, and the bucketed pool prefix alone must
    reconstruct an identical DeltaBatch."""
    from syzkaller_tpu.ops.delta import make_compact_pooler, pool_bucket

    cfg = TensorConfig(max_slots=128, arena=2048, max_blob=768)
    flags = FlagTables.empty()
    spec = DeltaSpec()
    tensors = _encode_some(test_target, 4, cfg, flags, seed0=900)
    pack = make_packer(spec)
    B = 4
    flat_pool = make_pooler(spec, B)
    compact_pool = make_compact_pooler(spec, B)
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)

    def both(states, keys, tidx):
        rows, payloads, needs = jax.vmap(
            lambda st, k, i: pack(_mutate_one(st, k, fv, fc, 4), i)
        )(states, keys, tidx)
        return flat_pool(rows, payloads, needs), \
            compact_pool(rows, payloads, needs)

    fn = jax.jit(both)
    states = {k: jnp.stack([jnp.asarray(t.arrays()[k]) for t in tensors])
              for k in tensors[0].arrays()}
    for seed in (0, 1, 2):
        keys = random.split(random.key(seed), B)
        tidx = jnp.arange(B, dtype=jnp.int32)
        flat, (rows, pool, n_used) = fn(states, keys, tidx)
        flat = np.asarray(flat)
        rows, pool = np.asarray(rows), np.asarray(pool)
        n_used = int(n_used)
        ref = DeltaBatch(flat, spec, B)
        # Full-pool equivalence.
        np.testing.assert_array_equal(ref.buf, rows)
        np.testing.assert_array_equal(ref._pool, pool)
        # The bucketed prefix covers every claimed slot, so the
        # compacted batch reads identically everywhere.
        assert n_used == int(np.count_nonzero(ref.pool_idx >= 0))
        bucket = pool_bucket(n_used, spec.pool_slots(B))
        assert (ref.pool_idx < bucket).all()
        got = DeltaBatch(rows, spec, pool=pool[:bucket])
        np.testing.assert_array_equal(got.payload, ref.payload)
        np.testing.assert_array_equal(got.template_idx, ref.template_idx)
        np.testing.assert_array_equal(got.vals, ref.vals)


def test_pool_bucket_is_pow2_and_bounded():
    from syzkaller_tpu.ops.delta import pool_bucket

    assert pool_bucket(0, 256) == 0
    assert pool_bucket(1, 256) == 1
    assert pool_bucket(3, 256) == 4
    assert pool_bucket(129, 256) == 256
    assert pool_bucket(999, 256) == 256  # clamped to the pool
    for n in range(1, 300):
        b = pool_bucket(n, 256)
        assert b & (b - 1) == 0 and b >= min(n, 256)


def test_delta_template_index_roundtrip(test_target):
    cfg = TensorConfig(max_slots=128, arena=2048, max_blob=768)
    flags = FlagTables.empty()
    spec = DeltaSpec()
    t = _encode_some(test_target, 1, cfg, flags)[0]
    pack = make_packer(spec)
    pool1 = make_pooler(spec, 1)
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    state = {k: jnp.asarray(v) for k, v in t.arrays().items()}

    def one(st, k, i):
        row, payload, needs = pack(_mutate_one(st, k, fv, fc, 2), i)
        return pool1(row[None], payload[None], needs[None])

    fn = jax.jit(one)
    for tidx in (0, 7, 2047):
        flat = fn(state, random.key(tidx), jnp.int32(tidx))
        batch = DeltaBatch(np.asarray(flat), spec, 1)
        assert int(batch.template_idx[0]) == tidx
