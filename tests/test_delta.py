"""Delta-transfer oracle: the sparse path (touched journal ->
make_packer -> assemble_delta) must be BIT-IDENTICAL to the dense path
(full mutated rows -> assemble) for the same device mutation, and
rebuild_row must reconstruct the full row exactly."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.delta import (  # noqa: E402
    DeltaBatch,
    DeltaSpec,
    make_packer,
    make_pooler,
)
from syzkaller_tpu.ops.emit import (  # noqa: E402
    assemble,
    assemble_delta,
    build_exec_template,
)
from syzkaller_tpu.ops.mutate import _mutate_one  # noqa: E402
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    FlagTables,
    TensorConfig,
    encode_prog,
)


def _encode_some(target, n, cfg, flags, seed0=500):
    tensors = []
    i = 0
    while len(tensors) < n and i < n * 8:
        p = generate_prog(target, RandGen(target, seed0 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    assert tensors
    return tensors


def test_delta_matches_dense_assembly(test_target, iters):
    cfg = TensorConfig(max_slots=128, arena=2048, max_blob=768)
    flags = FlagTables.empty()
    spec = DeltaSpec()
    tensors = _encode_some(test_target, 8, cfg, flags)
    pack = make_packer(spec)
    pool1 = make_pooler(spec, 1)

    def both(state, key, tidx):
        mutated = _mutate_one(state, key, fv, fc, 4)
        row, payload, needs = pack(mutated, tidx)
        return mutated, pool1(row[None], payload[None], needs[None])

    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    fn = jax.jit(lambda st, k, i: both(st, k, i))
    key = random.key(11)
    checked = 0
    for it in range(iters * 3):
        t = tensors[it % len(tensors)]
        et = build_exec_template(t)
        state = {k: jnp.asarray(v) for k, v in t.arrays().items()}
        key, sub = random.split(key)
        mutated, flat = fn(state, sub, jnp.int32(it % len(tensors)))
        batch = DeltaBatch(np.asarray(flat), spec, 1)
        if batch.overflowed(0):
            continue
        mut = {k: np.asarray(v) for k, v in mutated.items()}
        dense = assemble(et, mut["val"], mut["len_"], mut["arena"],
                         mut["call_alive"])
        sparse = assemble_delta(et, batch, 0)
        assert sparse == dense, f"delta/dense mismatch at iteration {it}"

        # rebuild_row reconstructs the full mutated row exactly for
        # every field the decode path reads.
        rebuilt = batch.rebuild_row(0, t)
        assert bool(rebuilt["preserve_sizes"]) == bool(mut["preserve_sizes"])
        np.testing.assert_array_equal(rebuilt["val"], mut["val"])
        np.testing.assert_array_equal(rebuilt["len_"], mut["len_"])
        np.testing.assert_array_equal(
            rebuilt["call_alive"][:t.ncalls], mut["call_alive"][:t.ncalls])
        # Arena: only changed spans are shipped; compare the spans the
        # decode path reads (each DATA slot's [off, off+len)).
        for s in range(len(t.slot_args)):
            if t.len_target is not None and et.len_word[s] >= 0:
                off = int(t.off[s])
                ln = int(rebuilt["len_"][s])
                np.testing.assert_array_equal(
                    rebuilt["arena"][off:off + ln],
                    mut["arena"][off:off + ln])
        checked += 1
    assert checked >= iters


def test_delta_template_index_roundtrip(test_target):
    cfg = TensorConfig(max_slots=128, arena=2048, max_blob=768)
    flags = FlagTables.empty()
    spec = DeltaSpec()
    t = _encode_some(test_target, 1, cfg, flags)[0]
    pack = make_packer(spec)
    pool1 = make_pooler(spec, 1)
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    state = {k: jnp.asarray(v) for k, v in t.arrays().items()}

    def one(st, k, i):
        row, payload, needs = pack(_mutate_one(st, k, fv, fc, 2), i)
        return pool1(row[None], payload[None], needs[None])

    fn = jax.jit(one)
    for tidx in (0, 7, 2047):
        flat = fn(state, random.key(tidx), jnp.int32(tidx))
        batch = DeltaBatch(np.asarray(flat), spec, 1)
        assert int(batch.template_idx[0]) == tidx
