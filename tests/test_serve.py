"""Multi-tenant serving plane (ISSUE 12, syzkaller_tpu/serve/):
per-tenant novelty planes (bit-exact vs solo, isolated between
tenants), QoS-credit batch composition with the fairness floor, the
zero-copy annex transport, and the tentpole conservation test — three
session tenants over the real loopback transport with kill/reconnect
churn on one, asserting zero lost, zero duplicated, and zero
cross-tenant-leaked mutants plus bit-exact per-tenant plane verdicts
vs running each tenant alone on a fresh plane.

Host-only: the broker, composer, and planes are pure host code; the
scripted drains below supply numpy rows — no jit compiles anywhere.
"""

from __future__ import annotations

import struct
import threading
import time
from collections import Counter as TallyCounter

import numpy as np
import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import FaultPlan, install_plan, reset_plan
from syzkaller_tpu.rpc import RPCClient, RPCError, RPCServer
from syzkaller_tpu.serve import (SERVE_QUOTA, BatchComposer, ServePlane,
                                 ServeTenant, TenantPlanes)
from syzkaller_tpu.serve.plane import fold_idx_np, hash_rows_np


@pytest.fixture(autouse=True)
def _clean_plan():
    reset_plan()
    yield
    reset_plan()


class _Clock:
    """Injectable monotonic clock (same shape as the control-plane
    tests').  Starts non-zero: last_seen == 0.0 means "never"."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _events_since(mark: int) -> list[tuple[str, str]]:
    return [(n, d) for _ts, n, d in telemetry.REGISTRY.events()[mark:]]


def _rows(vals, width: int = 16) -> np.ndarray:
    """Deterministic distinct test rows: value v -> row of v's little-
    endian u64 repeated to `width` bytes."""
    out = np.zeros((len(vals), width), np.uint8)
    for i, v in enumerate(vals):
        out[i, :8] = np.frombuffer(struct.pack("<Q", v), np.uint8)
    return out


# -- per-tenant planes ---------------------------------------------------


def test_tenant_planes_fold_rules_and_isolation():
    """The host fold pins the device rules (FNV-1a offset/prime, the
    xor-shift fold), one tenant's occupancy never leaks into
    another's verdicts, within-batch duplicates all pass, and
    invalidation is scoped to its tenant."""
    rows = _rows([7, 7, 9])
    # Pure-python FNV-1a over row bytes == the vectorized fold input.
    for j, row in enumerate(rows):
        h = 0x811C9DC5
        for b in row.tobytes():
            h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
        assert int(hash_rows_np(rows)[j]) == h
        bits = 12
        assert int(fold_idx_np(hash_rows_np(rows), bits)[j]) \
            == ((h ^ (h >> bits)) & ((1 << bits) - 1))

    planes = TenantPlanes(bits=12)
    # Within-batch duplicates (rows 0 and 1) both read the pre-update
    # plane: all three verdicts pass.
    assert planes.verdict("a", rows).tolist() == [True, True, True]
    # Cross-batch they are marked...
    assert planes.verdict("a", rows).tolist() == [False, False, False]
    # ...but only for tenant "a": "b" has its own plane.
    assert planes.verdict("b", rows).tolist() == [True, True, True]
    # Occupancy counts unique buckets (two for the duplicate pair).
    assert planes.analytics()["a"]["occupancy"] == 2
    # Invalidation is scoped: "a" resets, "b" keeps its occupancy.
    assert planes.invalidate("a") == 1
    assert planes.verdict("a", rows).tolist() == [True, True, True]
    assert planes.verdict("b", rows).tolist() == [False, False, False]
    assert planes.analytics()["a"]["epoch"] == 1
    assert planes.analytics()["b"]["epoch"] == 0


# -- batch composition ---------------------------------------------------


def _mk_serving(clock, batch_rows=100, floor=0.05, decay=0.5,
                stall_window=30.0, drain=None, bits=14):
    broker = ServePlane(lease_s=3600.0, queue_cap=10_000,
                        max_tenants=8, clock=clock)
    planes = TenantPlanes(bits=bits)
    comp = BatchComposer(broker, planes, drain, batch_rows=batch_rows,
                         credit_floor=floor, credit_decay=decay,
                         rebalance_s=0.0, stall_window_s=stall_window,
                         clock=clock)
    return broker, planes, comp


def test_allocate_largest_remainder_fill():
    clock = _Clock()
    _broker, _planes, comp = _mk_serving(clock, batch_rows=100)
    # Credit shares capped by demand, leftovers redistributed.
    alloc = dict(comp.allocate({"a": 0.5, "b": 0.3, "c": 0.2},
                               {"a": 1000, "b": 1000, "c": 1000}))
    assert alloc == {"a": 50, "b": 30, "c": 20}
    # A demand-capped tenant's unused share flows to the others.
    alloc = dict(comp.allocate({"a": 0.5, "b": 0.3, "c": 0.2},
                               {"a": 10, "b": 1000, "c": 1000}))
    assert alloc["a"] == 10 and sum(alloc.values()) == 100
    # Aggregate demand below batch_rows: the batch is just smaller.
    alloc = dict(comp.allocate({"a": 0.5, "b": 0.5},
                               {"a": 7, "b": 3}))
    assert alloc == {"a": 7, "b": 3}
    # Zero-credit tenants still ride the redistribution loop (the
    # no-hard-starvation property holds even if a credit hits 0).
    alloc = dict(comp.allocate({"a": 1.0, "b": 0.0},
                               {"a": 10, "b": 1000}))
    assert alloc["b"] == 90


def test_compose_tenant_column_and_demand_bound():
    clock = _Clock()
    counter = [0]

    def drain(n):
        vals = list(range(counter[0], counter[0] + n))
        counter[0] += n
        rows = _rows(vals)
        return rows, [row.tobytes() for row in rows]

    broker, _planes, comp = _mk_serving(clock, batch_rows=100,
                                        drain=drain)
    for name in ("a", "b"):
        broker.Connect({"name": name})
    broker.Poll({"name": "a", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 60}})
    broker.Poll({"name": "b", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 25}})
    report = comp.compose_once()
    # Demand-bound composition: 85 rows, not a padded 100.
    assert report["rows"] == 85
    assert report["order"] == ["a", "b"]
    assert report["tenants"]["a"]["rows"] == 60
    assert report["tenants"]["b"]["rows"] == 25
    # The per-row tenant-id column maps each row to its requester.
    col = report["tenant_col"]
    assert col.dtype == np.int32 and col.shape == (85,)
    assert col[:60].tolist() == [0] * 60
    assert col[60:].tolist() == [1] * 25
    # Supply landed in the right queues; nothing was produced beyond
    # demand, so outstanding demand is now zero.
    assert broker.tenants["a"].queued() == 60
    assert broker.tenants["b"].queued() == 25
    assert comp.compose_once()["rows"] == 0


def test_fairness_plateau_decays_to_floor_and_recovers():
    """The ISSUE 12 fairness satellite: a plateaued tenant's share
    decays to EXACTLY the credit floor (5 rows of a 100-row batch at
    floor 0.05) while the hot tenant takes the rest; the first novel
    verdict after the plateau emits `coverage.resume` and the next
    rebalance restores a demand-weighted share."""
    clock = _Clock()
    counter = [0]
    pool = 1 << 14  # fresh rows for the hot tenant every batch

    def drain(n):
        vals = [counter[0] + j for j in range(n)]
        counter[0] += n
        rows = _rows(vals)
        return rows, [row.tobytes() for row in rows]

    broker, planes, comp = _mk_serving(clock, batch_rows=100,
                                       floor=0.05, decay=0.5,
                                       stall_window=30.0, drain=drain,
                                       bits=20)
    for name in ("cold", "hot"):
        broker.Connect({"name": name})
    # Pre-seed the cold tenant's plane with every row the drain will
    # produce for a while: its verdicts come back all-stale, so its
    # novelty EWMA never rises and last_novel_ts never advances —
    # the per-tenant plateau.  The hot tenant's OWN plane is empty,
    # so the very same rows are novel for it (isolation).
    planes.verdict("cold", _rows(list(range(pool))))
    seqs = {"cold": 0, "hot": 0}

    def poll(name, backlog=1000):
        seqs[name] += 1
        return broker.Poll({"name": name, "epoch": broker.epoch,
                            "seq": seqs[name],
                            "ack_seq": seqs[name] - 1,
                            "demand": {"backlog": backlog}})

    mark = len(telemetry.REGISTRY.events())
    poll("cold"), poll("hot")
    r = comp.compose_once()
    # Cold start: even 0.5/0.5 shares, and the seeded plane already
    # splits novelty (hot all-novel, cold none).
    assert r["tenants"]["cold"]["rows"] == 50
    assert r["tenants"]["cold"]["novel"] == 0
    assert r["tenants"]["hot"]["novel"] == 50
    # Past the stall window with no cold novelty: the latch flips and
    # the credit decays geometrically to exactly the floor.  The hot
    # tenant keeps producing novelty through the window (two hops so
    # ITS last-novel timestamp stays fresh while cold's goes stale).
    clock.advance(20.0)
    poll("cold"), poll("hot")
    comp.compose_once()  # hot refreshes last_novel_ts at t+20
    clock.advance(15.0)  # cold gap 35s >= 30s; hot gap 15s
    poll("cold"), poll("hot")
    comp.compose_once()
    assert broker.tenants["cold"].stalled
    assert not broker.tenants["hot"].stalled
    assert any(n == "coverage.stall" and "cold" in d
               for n, d in _events_since(mark))
    for _ in range(8):
        clock.advance(1.0)
        comp.rebalance_credits(force=True)
    assert broker.tenants["cold"].credit == pytest.approx(0.05)
    assert broker.tenants["hot"].credit == pytest.approx(0.95)
    # The floor share is exact rows, never zero: 5 of 100.
    poll("cold"), poll("hot")
    r = comp.compose_once()
    assert r["tenants"]["cold"]["rows"] == 5
    assert r["tenants"]["hot"]["rows"] == 95
    # Recovery: invalidate cold's plane (operator reset) — the next
    # batch's rows are novel again, the latch clears with a
    # `coverage.resume` event, and the share climbs off the floor.
    planes.invalidate("cold")
    mark = len(telemetry.REGISTRY.events())
    poll("cold"), poll("hot")
    r = comp.compose_once()
    assert r["tenants"]["cold"]["novel"] == r["tenants"]["cold"]["rows"]
    assert not broker.tenants["cold"].stalled
    assert any(n == "coverage.resume" and "cold" in d
               for n, d in _events_since(mark))
    clock.advance(1.0)
    credits = comp.rebalance_credits(force=True)
    assert credits["cold"] > 0.05


def test_compose_fault_seam_defers_batch():
    clock = _Clock()
    calls = [0]

    def drain(n):
        calls[0] += 1
        rows = _rows(list(range(n)))
        return rows, [row.tobytes() for row in rows]

    broker, _planes, comp = _mk_serving(clock, batch_rows=32,
                                        drain=drain)
    broker.Connect({"name": "a"})
    broker.Poll({"name": "a", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 32}})
    install_plan(FaultPlan.parse("serve.compose:fail@1"))
    r = comp.compose_once()
    assert r.get("deferred") and r["rows"] == 0
    assert calls[0] == 0  # nothing drained, demand intact
    r = comp.compose_once()  # occurrence 2: passes
    assert r["rows"] == 32 and calls[0] == 1


def test_sim_loadgen_drives_composer_multi_tenant():
    """ISSUE 15: the VM-free load generator (syzkaller_tpu/sim) stands
    in for the fused drain — byte-realistic rows with a deterministic
    verdict mix (crashes, EBADF, lockless races, repeated/stale rows)
    — so the multi-tenant composer is stress-tested at full batch
    shape with no executor subprocess anywhere."""
    from syzkaller_tpu.sim import SimLoadGenerator

    clock = _Clock()
    gen = SimLoadGenerator(seed=11, repeat_every=4)
    broker, _planes, comp = _mk_serving(clock, batch_rows=128,
                                        drain=gen.drain, bits=16)
    for name in ("a", "b"):
        broker.Connect({"name": name})
    seqs = {"a": 0, "b": 0}
    delivered = 0

    def poll(name, backlog):
        seqs[name] += 1
        resp, _annex = broker.Poll(
            {"name": name, "epoch": broker.epoch, "seq": seqs[name],
             "ack_seq": seqs[name] - 1,
             "demand": {"backlog": backlog}})
        return len(resp["results"])

    poll("a", 96)
    poll("b", 32)
    r = comp.compose_once()
    # Demand-exact composition off the generator's rows.
    assert r["rows"] == 128
    assert r["tenants"]["a"]["rows"] == 96
    assert r["tenants"]["b"]["rows"] == 32
    total_rows = r["rows"]
    total_novel = sum(t["novel"] for t in r["tenants"].values())
    for _ in range(6):
        delivered += poll("a", 96)
        delivered += poll("b", 32)
        r = comp.compose_once()
        for t in r["tenants"].values():
            total_rows += t["rows"]
            total_novel += t["novel"]
    # The generator's replayed rows are byte-identical, so per-tenant
    # planes mark some of the stream stale across batches — the
    # verdict mix a real corpus produces, without a single VM.
    assert total_rows > 256, "the generator never sustained supply"
    assert 0 < total_novel < total_rows
    # Conservation: every novel row is delivered or still queued
    # (queued() includes unacked inflight, which `delivered` already
    # counted — at-least-once delivery, so back them out).
    queued = broker.tenants["a"].queued() + broker.tenants["b"].queued()
    inflight = sum(len(items) for t in ("a", "b")
                   for _seq, items in broker.tenants[t].inflight)
    assert delivered + queued - inflight == total_novel
    mix = gen.verdict_mix()
    assert 0.2 < mix["repeat_frac"] < 0.3
    assert mix["crash_frac"] > 0 and mix["ebadf_frac"] > 0
    assert gen.stats["programs"] > 0 and gen.stats["repeats"] > 0


# -- admission quotas ----------------------------------------------------


def test_admission_quota_scales_with_throttle_and_credit():
    state = {"s": "closed"}
    broker = ServePlane(lease_s=3600.0, queue_cap=10_000,
                        max_tenants=4, throttle_fn=lambda: state["s"])
    broker.Connect({"name": "a"})
    broker.offer("a", [b"x%d" % i for i in range(600)],
                 rows_spent=600, novel=600)
    broker.tenants["a"].credit = 0.05  # floor-pinned tenant

    def poll(seq):
        reply, _annex = broker.Poll(
            {"name": "a", "epoch": broker.epoch, "seq": seq,
             "ack_seq": seq - 1, "demand": {"backlog": 0}})
        return reply

    # closed: 4096 * 0.05 = 204 results in one poll.
    r = poll(1)
    assert r["quota"]["state"] == "closed"
    assert len(r["results"]) == int(SERVE_QUOTA["closed"] * 0.05)
    # open: the tier shrinks the allotment 16x — but the floor never
    # starves: max(1, 256 * 0.05) = 12.
    state["s"] = "open"
    r = poll(2)
    assert r["quota"]["state"] == "open"
    assert len(r["results"]) == max(1, int(SERVE_QUOTA["open"] * 0.05))
    # Even a near-zero credit still trickles one result per poll.
    broker.tenants["a"].credit = 0.0001
    assert len(poll(3)["results"]) == 1


def test_admission_cap_rejects_excess_tenants():
    broker = ServePlane(lease_s=3600.0, max_tenants=2)
    broker.Connect({"name": "a"})
    broker.Connect({"name": "b"})
    with pytest.raises(RuntimeError, match="admission"):
        broker.Connect({"name": "c"})
    broker.Connect({"name": "a"})  # re-Connect is not a new tenant


# -- leases, custody, replay --------------------------------------------


def test_lease_reap_tombstone_and_reconnect_custody():
    clock = _Clock()
    broker = ServePlane(lease_s=60.0, queue_cap=100, max_tenants=4,
                        clock=clock)
    broker.Connect({"name": "t1"})
    broker.offer("t1", [b"m1", b"m2", b"m3"], rows_spent=3, novel=3)
    r1, annex1 = broker.Poll({"name": "t1", "epoch": broker.epoch,
                              "seq": 1, "ack_seq": 0,
                              "demand": {"backlog": 0},
                              "max_results": 2})
    assert [x["rid"] for x in r1["results"]] == ["t1:1", "t1:2"]
    # Unacked delivery sits in inflight custody, not gone.
    assert broker.tenants["t1"].queued() == 3
    # Re-Connect (VM restart): pending kept, inflight returned to the
    # queue FRONT — redelivery preserves the original order.
    broker.Connect({"name": "t1"})
    r2, _ = broker.Poll({"name": "t1", "epoch": broker.epoch,
                         "seq": 2, "ack_seq": 0,
                         "demand": {"backlog": 0}})
    assert [x["rid"] for x in r2["results"]] == ["t1:1", "t1:2", "t1:3"]
    # Ack retires custody.
    broker.Poll({"name": "t1", "epoch": broker.epoch, "seq": 3,
                 "ack_seq": 2, "demand": {"backlog": 0}})
    assert broker.tenants["t1"].queued() == 0
    assert broker.tenants["t1"].delivered == 3
    # Reap: idle past the lease, reply cache tombstoned — a late
    # retry of an applied seq still replays byte-identically...
    cached = broker.tenants["t1"].reply_cache[3]
    clock.advance(61.0)
    broker.reap_expired()
    assert "t1" not in broker.tenants
    assert broker.reaped_total == 1
    replay = broker.Poll({"name": "t1", "epoch": broker.epoch,
                          "seq": 3, "ack_seq": 2,
                          "demand": {"backlog": 0}})
    assert replay == cached
    # ...while an unseen seq from the reaped tenant demands resync.
    from syzkaller_tpu.rpc import ReconnectRequired

    with pytest.raises(ReconnectRequired):
        broker.Poll({"name": "t1", "epoch": broker.epoch, "seq": 4,
                     "ack_seq": 3, "demand": {"backlog": 0}})


def test_reaped_tenant_results_dropped_never_reassigned():
    """Reaped custody is dropped and accounted — handing another
    tenant's mutants to a survivor would be the cross-tenant leak."""
    clock = _Clock()
    broker = ServePlane(lease_s=60.0, queue_cap=100, max_tenants=4,
                        clock=clock)
    broker.Connect({"name": "dead"})
    broker.Connect({"name": "live"})
    broker.offer("dead", [b"d1", b"d2"], rows_spent=2, novel=2)
    before = telemetry.snapshot()["counters"].get(
        "tz_serve_results_dropped_total", 0)
    clock.advance(61.0)
    # Only "live" keeps polling; the reap runs opportunistically.
    broker.Connect({"name": "live"})
    broker.reap_expired()
    assert "dead" not in broker.tenants
    after = telemetry.snapshot()["counters"].get(
        "tz_serve_results_dropped_total", 0)
    assert after - before == 2
    # The survivor's queue never saw them.
    assert broker.tenants["live"].queued() == 0


# -- the zero-copy annex transport --------------------------------------


class _AnnexService:
    def Echo(self, params):
        parts = [b"alpha", b"beta-beta", b"x" * int(params.get("pad", 0))]
        refs, off = [], 0
        for p in parts:
            refs.append({"off": off, "len": len(p)})
            off += len(p)
        return {"refs": refs}, [memoryview(p) for p in parts]

    def Plain(self, params):
        return {"ok": True}


def test_annex_roundtrip_over_loopback():
    """(dict, parts) from a handler arrives as (result, annex bytes);
    refs slice the annex back into the original parts; a big JSON
    payload (zlib path) coexists with the annex; plain replies return
    annex=None and legacy callers never see a tuple."""
    srv = RPCServer()
    srv.register("Svc", _AnnexService())
    srv.serve_in_background()
    cli = RPCClient(srv.addr, name="t")
    try:
        result, annex = cli.call("Svc.Echo", {"pad": 0},
                                 want_annex=True)
        parts = [bytes(annex[r["off"]:r["off"] + r["len"]])
                 for r in result["refs"]]
        assert parts == [b"alpha", b"beta-beta", b""]
        # Force the JSON payload over the zlib threshold too.
        result, annex = cli.call(
            "Svc.Echo", {"pad": 9000, "blob": "z" * 8192},
            want_annex=True)
        assert len(annex) == sum(r["len"] for r in result["refs"])
        assert annex[-1:] == b"x"
        # No annex on a plain reply; legacy call() shape unchanged.
        result, annex = cli.call("Svc.Plain", {}, want_annex=True)
        assert result == {"ok": True} and annex is None
        assert cli.call("Svc.Plain", {}) == {"ok": True}
        with pytest.raises(RPCError):
            cli.call("Svc.Nope", {})
    finally:
        cli.close()
        srv.close()


def test_annex_replayed_identically_from_reply_cache():
    """A lost reply's retry (same seq) replays the cached (reply,
    annex) pair byte-identically — at-most-once delivery holds across
    the zero-copy path too."""
    broker = ServePlane(lease_s=3600.0, queue_cap=100, max_tenants=4)
    srv = RPCServer()
    srv.register("Serve", broker)
    srv.serve_in_background()
    tenant = ServeTenant(srv.addr, name="t1")
    try:
        tenant.connect()
        broker.offer("t1", [b"payload-a", b"payload-b"],
                     rows_spent=2, novel=2)
        got = tenant.poll(backlog=0)
        assert [(rid, bytes(p)) for rid, p in got] == \
            [("t1:1", b"payload-a"), ("t1:2", b"payload-b")]
        # Retry of the applied seq straight at the broker: identical
        # reply AND identical annex out of the cache.
        seq = tenant.client._seq
        r1 = broker.Poll({"name": "t1", "epoch": broker.epoch,
                          "seq": seq, "ack_seq": seq - 1,
                          "demand": {"backlog": 0}})
        r2 = broker.Poll({"name": "t1", "epoch": broker.epoch,
                          "seq": seq, "ack_seq": seq - 1,
                          "demand": {"backlog": 0}})
        assert r1 == r2
        assert broker.replays_total >= 2
        # The client's rid window dedups an application-level replay.
        assert tenant.poll(backlog=0) == []
    finally:
        tenant.close()
        srv.close()


# -- the tentpole: multi-tenant conservation under churn ----------------


class _TenantVM:
    """One scripted fuzzer VM: session polls with demand, collecting
    every delivered (rid, payload)."""

    def __init__(self, name: str, addr, demand: int):
        self.name = name
        self.demand = demand
        self.tenant = ServeTenant(addr, name=name, timeout_s=10.0)
        self.tenant.client.backoff_s = 0.01
        self.got: list[tuple[str, bytes]] = []
        self.errors = 0

    def connect(self):
        self.tenant.connect()

    def poll_once(self, backlog=None):
        try:
            res = self.tenant.poll(
                backlog=self.demand if backlog is None else backlog,
                exec_rate=100.0)
        except (RPCError, ConnectionError, OSError):
            self.errors += 1
            return 0
        self.got.extend((rid, bytes(p)) for rid, p in res)
        return len(res)

    def storm_loop(self, polls, churn=False):
        for k in range(polls):
            if churn and k % 5 == 4:
                # Kill the connection mid-session (VM churn); the
                # next sessioned call reconnects and, every other
                # time, re-Connects the whole session.
                self.tenant.client.close()
                if k % 10 == 9:
                    try:
                        self.connect()
                    except (RPCError, ConnectionError, OSError):
                        self.errors += 1
            self.poll_once()
            time.sleep(0.004)


def test_multi_tenant_conservation_under_churn():
    """The ISSUE 12 acceptance test: three session tenants with mixed
    demand share one composed drain over the real loopback transport
    while scripted frame faults and kill/reconnect churn hammer one
    tenant.  Afterwards: zero lost mutants, zero duplicates, zero
    cross-tenant leaks (every delivered payload was produced for its
    receiving tenant), and each tenant's plane verdicts replay
    bit-exactly on a fresh solo plane."""
    broker = ServePlane(lease_s=3600.0, queue_cap=5000, max_tenants=8)
    planes = TenantPlanes(bits=12)  # small plane: real collisions
    counter = [0]
    drain_log: list[np.ndarray] = []

    def drain(n):
        # Rows cycle a 600-value pool so non-novel verdicts (and the
        # within-batch duplicate rule) actually occur; payload ==
        # row bytes, so a delivered payload identifies its row.
        vals = [(counter[0] + j) % 600 for j in range(n)]
        counter[0] += n
        rows = _rows(vals)
        drain_log.append(rows)
        return rows, [row.tobytes() for row in rows]

    comp = BatchComposer(broker, planes, drain, batch_rows=96,
                         rebalance_s=0.0, stall_window_s=3600.0)
    srv = RPCServer()
    srv.register("Serve", broker)
    srv.serve_in_background()

    vms = [_TenantVM("vm0", srv.addr, demand=300),
           _TenantVM("vm1", srv.addr, demand=120),
           _TenantVM("vm2", srv.addr, demand=40)]
    for vm in vms:
        vm.connect()

    # Ground truth, from the composer reports: which payloads were
    # produced FOR which tenant, and each tenant's exact row/verdict
    # stream for the bit-exactness replay.
    produced: dict[str, TallyCounter] = {
        vm.name: TallyCounter() for vm in vms}
    replay: dict[str, list[tuple[np.ndarray, list[int]]]] = {
        vm.name: [] for vm in vms}

    stop = threading.Event()

    def compose_loop():
        i = 0
        while not stop.is_set():
            rows_before = len(drain_log)
            report = comp.compose_once()
            if report.get("rows"):
                rows = drain_log[rows_before]
                off = 0
                for name in report["order"]:
                    tr = report["tenants"][name]
                    chunk = rows[off:off + tr["rows"]]
                    off += tr["rows"]
                    replay[name].append((chunk, tr["novel_idx"]))
                    for j in tr["novel_idx"]:
                        produced[name][chunk[j].tobytes()] += 1
            i += 1
            time.sleep(0.002)

    composer_thread = threading.Thread(target=compose_loop, daemon=True)
    composer_thread.start()

    # Storm: every ~6th frame send dies (both directions), vm1 also
    # churns its connection/session.
    install_plan(FaultPlan.parse(
        "rpc.send_frame:fail@"
        + ",".join(str(i) for i in range(9, 900, 6))))
    threads = [
        threading.Thread(target=vms[0].storm_loop, args=(25,),
                         daemon=True),
        threading.Thread(target=vms[1].storm_loop, args=(25, True),
                         daemon=True),
        threading.Thread(target=vms[2].storm_loop, args=(25,),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    reset_plan()

    # Quiesce: stop producing, then drain every queue fault-free.
    stop.set()
    composer_thread.join(timeout=10)
    for vm in vms:
        for _ in range(50):
            st = broker.tenants[vm.name]
            if vm.poll_once(backlog=0) == 0 and st.queued() == 0:
                break
        assert broker.tenants[vm.name].queued() == 0

    srv.close()

    total = sum(len(vm.got) for vm in vms)
    assert total > 0, "storm delivered nothing; test is vacuous"

    for vm in vms:
        # Zero cross-tenant leaks: every rid is tagged with its
        # requester (the client itself raises on a mismatched tenant
        # tag — reaching here means none occurred).
        assert all(rid.startswith(f"{vm.name}:") for rid, _ in vm.got)
        # Zero duplicates: rids are delivered at most once.
        rids = [rid for rid, _ in vm.got]
        assert len(rids) == len(set(rids))
        # Zero lost, zero foreign: the delivered payload multiset is
        # exactly what the composer produced for this tenant.
        delivered = TallyCounter(p for _rid, p in vm.got)
        assert delivered == produced[vm.name]
        # Bit-exactness: replaying this tenant's exact row chunks on
        # a FRESH solo plane reproduces every novelty verdict.
        solo = TenantPlanes(bits=12)
        for chunk, novel_idx in replay[vm.name]:
            got_idx = np.flatnonzero(
                solo.verdict(vm.name, chunk)).tolist()
            assert got_idx == novel_idx


def test_compose_lane_tenant_drains_through_its_own_fn():
    """The ISSUE 19 composer satellite: a tenant registered via
    attach_lane draws its rows from its OWN drain (the hints lane's
    compose_drain) while default tenants share drain_fn, the segments
    stitch back in alloc order so tenant_col stays aligned, and the
    lane's row share books to tz_acct_device_ms_total{lane="hints"}
    (with the default rows conserved under lane="exploration")."""
    clock = _Clock()
    counter = [0]

    def default_drain(n):
        vals = list(range(counter[0], counter[0] + n))
        counter[0] += n
        rows = _rows(vals)
        return rows, [row.tobytes() for row in rows]

    broker, _planes, comp = _mk_serving(clock, batch_rows=100,
                                        drain=default_drain)
    lane_calls: list[int] = []

    def hints_drain(n):
        lane_calls.append(n)
        rows = _rows(list(range(1 << 20, (1 << 20) + n)))
        return rows, [row.tobytes() for row in rows]

    comp.attach_lane("hints", hints_drain, lane="hints")
    for name in ("fleet", "hints"):
        broker.Connect({"name": name})
    broker.Poll({"name": "fleet", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 60}})
    broker.Poll({"name": "hints", "epoch": broker.epoch, "seq": 1,
                 "ack_seq": 0, "demand": {"backlog": 25}})
    acct0 = telemetry.counter("tz_acct_device_ms_total", "",
                              labels={"lane": "hints"}).value
    expl0 = telemetry.counter("tz_acct_device_ms_total", "",
                              labels={"lane": "exploration"}).value
    report = comp.compose_once()
    # QoS credits honoured: both tenants got their demand-bound share
    # and the hints tenant's rows came from hints_drain, exactly once.
    assert report["rows"] == 85
    assert report["order"] == ["fleet", "hints"]
    assert report["tenants"]["fleet"]["rows"] == 60
    assert report["tenants"]["hints"]["rows"] == 25
    assert lane_calls == [25]
    assert counter[0] == 60  # default drain produced only its segment
    # tenant_col alignment survives the segmented stitch.
    col = report["tenant_col"]
    assert col[:60].tolist() == [0] * 60
    assert col[60:].tolist() == [1] * 25
    # Supply landed in the right queues; the hints queue holds the
    # lane drain's rows, not the default drain's.
    assert broker.tenants["fleet"].queued() == 60
    assert broker.tenants["hints"].queued() == 25
    hint_rows = _rows(list(range(1 << 20, (1 << 20) + 25)))
    pending = list(broker.tenants["hints"].pending)[:3]
    assert [p for _rid, p in pending] == \
        [row.tobytes() for row in hint_rows[:3]]
    # The ledger booked the lane split: hints ms grew, and the default
    # segment's share landed under "exploration" (conservation).
    assert telemetry.counter("tz_acct_device_ms_total", "",
                             labels={"lane": "hints"}).value > acct0
    assert telemetry.counter("tz_acct_device_ms_total", "",
                             labels={"lane": "exploration"}).value > expl0
