"""Device-kernel tests: tensor codec round-trip, batched mutation
validity, RNG distribution parity, signal bitmap equivalence."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.encoding import serialize_prog  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.models.validation import validate_prog  # noqa: E402
from syzkaller_tpu.ops import rng as drng  # noqa: E402
from syzkaller_tpu.ops import signal as dsig  # noqa: E402
from syzkaller_tpu.ops.mutate import make_mutator  # noqa: E402
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    FlagTables,
    TensorConfig,
    decode_prog,
    encode_prog,
    stack_batch,
)
from syzkaller_tpu.signal import Signal, from_raw  # noqa: E402


def make_corpus(target, n, seed=0, ncalls=8):
    return [generate_prog(target, RandGen(target, seed + i), ncalls)
            for i in range(n)]


def test_codec_identity_roundtrip(test_target):
    cfg = TensorConfig()
    flags = FlagTables.empty()
    for i, p in enumerate(make_corpus(test_target, 10, seed=100)):
        t = encode_prog(p, cfg, flags)
        p2 = decode_prog(t, {k: np.asarray(v) for k, v in t.arrays().items()})
        validate_prog(p2)
        assert serialize_prog(p2) == serialize_prog(p), f"prog {i}"


def test_batched_mutation_produces_valid_programs(test_target):
    cfg = TensorConfig()
    flags = FlagTables.empty()
    corpus = make_corpus(test_target, 16, seed=200)
    tensors = [encode_prog(p, cfg, flags) for p in corpus]
    batch = stack_batch(tensors)
    mutate = make_mutator(rounds=4)
    key = random.key(0)
    out = mutate(
        {k: jnp.asarray(v) for k, v in batch.items()}, key,
        jnp.asarray(flags.vals), jnp.asarray(flags.counts))
    out_np = {k: np.asarray(v) for k, v in out.items()}
    changed = 0
    for i, t in enumerate(tensors):
        mut = {k: v[i] for k, v in out_np.items()}
        p2 = decode_prog(t, mut, preserve_sizes=bool(mut["preserve_sizes"]))
        validate_prog(p2)
        if serialize_prog(p2) != serialize_prog(corpus[i]):
            changed += 1
    # The op mix guarantees nearly every program changes.
    assert changed >= 12, f"only {changed}/16 changed"


def test_mutation_repeated_rounds(test_target):
    cfg = TensorConfig()
    flags = FlagTables.empty()
    corpus = make_corpus(test_target, 4, seed=300)
    tensors = [encode_prog(p, cfg, flags) for p in corpus]
    batch = {k: jnp.asarray(v) for k, v in stack_batch(tensors).items()}
    mutate = make_mutator(rounds=4)
    fv, fc = jnp.asarray(flags.vals), jnp.asarray(flags.counts)
    key = random.key(7)
    for step in range(5):
        key, sub = random.split(key)
        batch = mutate(batch, sub, fv, fc)
    out_np = {k: np.asarray(v) for k, v in batch.items()}
    for i, t in enumerate(tensors):
        mut = {k: v[i] for k, v in out_np.items()}
        p2 = decode_prog(t, mut, preserve_sizes=bool(mut["preserve_sizes"]))
        validate_prog(p2)


def test_rand_int_distribution_parity(test_target):
    """Device rand_int must match the CPU distribution on key stats
    (SURVEY.md §7 hard part b)."""
    cpu = RandGen(test_target, 12345)
    cpu_vals = np.array([cpu.rand_int() for _ in range(20000)],
                        dtype=np.uint64)
    keys = random.split(random.key(5), 20000)
    dev_vals = np.asarray(jax.vmap(drng.rand_int)(keys)).astype(np.uint64)

    def stats(v):
        return (
            np.mean(v < 10),             # small-value mass
            np.mean(v == 0),             # zero mass
            np.mean(v < 256),
            np.mean(v > np.uint64(1) << np.uint64(63)),  # negated mass
        )

    s_cpu, s_dev = stats(cpu_vals), stats(dev_vals)
    for a, b in zip(s_cpu, s_dev):
        assert abs(a - b) < 0.03, (s_cpu, s_dev)


def test_biased_rand_parity(test_target):
    cpu = RandGen(test_target, 1)
    cpu_vals = np.array([cpu.biased_rand(10, 5) for _ in range(20000)])
    keys = random.split(random.key(2), 20000)
    dev_vals = np.asarray(jax.vmap(lambda k: drng.biased_rand(k, 10, 5))(keys))
    # Compare histograms
    hc = np.bincount(cpu_vals, minlength=10) / len(cpu_vals)
    hd = np.bincount(dev_vals, minlength=10) / len(dev_vals)
    assert np.abs(hc - hd).max() < 0.02, (hc, hd)


def test_signal_plane_matches_cpu_signal():
    rng = np.random.RandomState(0)
    B, E = 8, 64
    edges = rng.randint(0, 1 << 32, size=(B, E), dtype=np.uint32)
    nedges = rng.randint(1, E, size=B).astype(np.int32)
    prios = rng.randint(0, 3, size=B).astype(np.uint8)

    plane = dsig.new_plane()
    cpu_sig = Signal()
    for step in range(3):
        new_mask, new_count = dsig.diff_batch(
            plane, jnp.asarray(edges), jnp.asarray(nedges),
            jnp.asarray(prios))
        new_count = np.asarray(new_count)
        # CPU decisions on the SAME folded hashes, against the same
        # pre-batch snapshot the device saw.
        folded = np.asarray(dsig.fold_hash(jnp.asarray(edges)))
        snapshot = cpu_sig.copy()
        for b in range(B):
            raw = folded[b, :nedges[b]]
            cpu_new = snapshot.diff_raw(raw.tolist(), int(prios[b]))
            assert len(cpu_new) == int(new_count[b]), (step, b)
            cpu_sig.merge(cpu_new)
        plane = dsig.merge(plane, jnp.asarray(edges), jnp.asarray(nedges),
                           jnp.asarray(prios),
                           jnp.ones(B, dtype=bool))
        assert int(dsig.plane_count(plane)) == len(cpu_sig)
        # fresh batch for next round
        edges = rng.randint(0, 1 << 32, size=(B, E), dtype=np.uint32)
        nedges = rng.randint(1, E, size=B).astype(np.int32)
        prios = rng.randint(0, 3, size=B).astype(np.uint8)
