"""Device-resident pipeline tests: corpus-on-device mutation to
exec-ready bytes, with lazy typed decode for triage."""

import queue

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.models.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.emit import parse_stream  # noqa: E402
from syzkaller_tpu.ops.pipeline import DevicePipeline  # noqa: E402


def _make_pipeline(target, n_seeds=12, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("batch_size", 16)
    pl = DevicePipeline(target, seed=5, **kw)
    added, i = 0, 0
    while added < n_seeds and i < n_seeds * 4:
        p = generate_prog(target, RandGen(target, 1000 + i), 6)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= n_seeds // 2
    return pl


def test_pipeline_produces_wellformed_mutants(test_target):
    """Well-formedness + the ISSUE 3 hot-path wiring, on one warm
    pipeline (the jit compile dominates test wall-clock): compacted
    D2H never exceeds the uncompacted layout, fast-path mutants carry
    zero-copy arena views, batches carry monotonic drain sequence
    numbers."""
    pl = _make_pipeline(test_target)
    try:
        batch = pl.next_batch(timeout=120)
        assert len(batch) >= 1
        for m in batch[:8]:
            ids = parse_stream(m.exec_bytes)  # well-formed stream
            assert len(ids) == m.num_calls()
            # Lazy decode agrees with the mutant's structure and
            # re-serializes through the typed path.
            p = m.prog()
            assert len(p.calls) == m.num_calls()
            assert serialize_for_exec(p)  # typed path accepts it
        b2 = pl.next_batch(timeout=120)
        assert 0 <= batch.seq < b2.seq
        # rows + bucketed pool prefix + used-slot count <= flat layout
        full = pl.spec.batch_bytes(pl.batch_size)
        assert pl.stats.d2h_batches >= 2
        assert pl.stats.d2h_bytes / pl.stats.d2h_batches <= full + 4
        views = sum(isinstance(m.exec_bytes, memoryview)
                    for m in batch if m.donor is None)
        assert views >= sum(m.donor is None for m in batch) // 2, \
            "fast path never produced zero-copy arena views"
        # Views pin their arena and compare/convert like bytes.
        for m in batch[:4]:
            assert bytes(m.exec_bytes) == m.exec_bytes
    finally:
        pl.stop()


def test_pipeline_mutants_differ_from_templates(test_target):
    """Mutation actually happens: across a batch, most mutants differ
    from their template's exec bytes."""
    pl = _make_pipeline(test_target)
    try:
        batch = pl.next_batch(timeout=120)
        diff = 0
        for m in batch:
            tmpl_bytes = m.et.words.tobytes()
            if m.exec_bytes != tmpl_bytes:
                diff += 1
        assert diff > len(batch) // 2
    finally:
        pl.stop()


def test_pipeline_prefetch_and_ring(test_target):
    """Multiple batches flow; ring eviction keeps producing valid
    mutants referencing the snapshot templates."""
    pl = _make_pipeline(test_target, capacity=8, batch_size=8)
    try:
        for _ in range(3):
            batch = pl.next_batch(timeout=120)
            for m in batch[:4]:
                parse_stream(m.exec_bytes)
        # Grow past capacity mid-flight.
        added = 0
        i = 0
        while added < 12 and i < 60:
            p = generate_prog(test_target, RandGen(test_target, 7000 + i), 5)
            i += 1
            if pl.add(p):
                added += 1
        assert pl.stats.evictions > 0 or added < 12
        for _ in range(3):
            batch = pl.next_batch(timeout=120)
            for m in batch[:4]:
                parse_stream(m.exec_bytes)
                m.prog()
    finally:
        pl.stop()


def test_pipeline_empty_corpus_no_mutants(test_target):
    pl = DevicePipeline(test_target, capacity=8, batch_size=4)
    try:
        pl.start()
        with pytest.raises(queue.Empty):
            pl._queue.get(timeout=0.8)
    finally:
        pl.stop()


def test_exec_mutant_contains_any(test_target):
    pl = _make_pipeline(test_target)
    try:
        batch = pl.next_batch(timeout=120)
        m = batch[0]
        for i in range(m.num_calls()):
            assert m.contains_any_call(i) in (False, True)
        assert m.contains_any_call(999) is False
    finally:
        pl.stop()


def test_worker_survives_device_failures(test_target):
    """A device failure (e.g. the tunneled backend refusing compiles
    while the session stays up) must not kill the worker thread: it
    drops in-flight work, backs off, and recovers when the device
    answers again — so the fuzzer's health-latch probe can re-enable
    device mutation."""
    import time

    pl = _make_pipeline(test_target)
    pl.retry_backoff_initial = 0.05
    pl.retry_backoff_cap = 0.2
    real_step = pl._step
    fail = {"n": 0}

    def flaky_step(*a, **kw):
        if fail["n"] < 3:
            fail["n"] += 1
            raise RuntimeError("UNAVAILABLE: injected compile error")
        return real_step(*a, **kw)

    pl._step = flaky_step
    try:
        batch = pl.next_batch(timeout=120)
        assert batch, "worker never recovered from injected failures"
        assert fail["n"] == 3
        assert pl.stats.worker_errors == 3
        assert pl._worker.is_alive()
    finally:
        pl.stop()


def test_worker_rebuilds_device_state_after_persistent_failures(test_target):
    """Four consecutive failures trigger the device-state rebuild (a
    backend restart invalidates old buffers); the ring re-stages from
    the host template snapshot and mutants stay template-consistent."""
    pl = _make_pipeline(test_target)
    pl.retry_backoff_initial = 0.05
    pl.retry_backoff_cap = 0.1
    real_step = pl._step
    fail = {"n": 0}

    def flaky_step(*a, **kw):
        if fail["n"] < 5:
            fail["n"] += 1
            raise RuntimeError("UNAVAILABLE: injected backend restart")
        return real_step(*a, **kw)

    pl._step = flaky_step
    try:
        batch = pl.next_batch(timeout=120)
        assert batch, "worker never recovered after state rebuild"
        assert pl.stats.worker_errors >= 5
        # post-rebuild mutants parse and reference live templates
        for m in batch[:8]:
            parse_stream(m.exec_bytes)
    finally:
        pl.stop()
