"""PipelineMutator health latch: a wedged device pipeline demotes the
mutator to CPU fallback within one draw instead of serializing procs on
drain timeouts, and a background probe re-enables it when the device
answers again (VERDICT r3 item #4; the wedge is the axon-tunnel failure
mode memorialized in BENCH notes)."""

from __future__ import annotations

import threading
import time

import pytest

from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, WorkQueue
from syzkaller_tpu.fuzzer.proc import PipelineMutator
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.signal.cover import Cover


class FakeMutant:
    exec_bytes = b"\x00" * 8
    signal_prio = 0


class FakePipeline:
    """Duck-typed DevicePipeline: 'ok' answers instantly, 'dead'
    simulates a drain timeout (returns None without sleeping)."""

    def __init__(self):
        self.mode = "ok"
        self._stop = threading.Event()
        self.calls_by_thread: dict[int, int] = {}
        self._lock = threading.Lock()

    def add(self, p):
        return True

    def __len__(self):
        return 4

    def next(self, timeout=10.0):
        ident = threading.get_ident()
        with self._lock:
            self.calls_by_thread[ident] = \
                self.calls_by_thread.get(ident, 0) + 1
        return FakeMutant() if self.mode == "ok" else None


@pytest.fixture()
def fuzzer():
    target = get_target("test", "64")
    fz = Fuzzer(target, wq=WorkQueue(), cfg=FuzzerConfig(program_length=6))
    for i in range(6):
        p = generate_prog(target, RandGen(target, 7000 + i), 4)
        fz.add_input_to_corpus(p, Signal({i: 1}), Cover())
    return fz


def _draw_device(pm, fuzzer, rng, want_mutant, tries=400):
    """Drive next() until a draw takes the device route (device draws
    are ~79% of the ladder); returns what that draw produced."""
    for _ in range(tries):
        m = pm.next(fuzzer, rng)
        if isinstance(m, FakeMutant):
            return m
        if m is None:
            # None = a device draw that hit the latch/timeout (CPU
            # fallback); squash/splice draws return typed Progs.
            return None if not want_mutant else _fail("latched early")
    raise AssertionError("no device draw in %d tries" % tries)


def _fail(msg):
    raise AssertionError(msg)


def test_latch_demotes_and_recovers(fuzzer):
    rng = RandGen(fuzzer.target, 99)
    fake = FakePipeline()
    pm = PipelineMutator(fake, drain_timeout=0.01, demote_after=2,
                         probe_interval=0.02, probe_timeout=0.01)

    # Healthy: device draws return mutants.
    assert isinstance(_draw_device(pm, fuzzer, rng, want_mutant=True),
                      FakeMutant)
    assert pm.healthy()

    # Kill the device: after demote_after timed-out device draws the
    # mutator latches.
    fake.mode = "dead"
    deadline = time.time() + 10
    while pm.healthy() and time.time() < deadline:
        pm.next(fuzzer, rng)
    assert not pm.healthy(), "mutator never demoted on a dead pipeline"

    # While demoted, device draws return None immediately and do NOT
    # touch the pipeline from the proc thread (only the probe thread
    # may poll it).
    main = threading.get_ident()
    calls_before = fake.calls_by_thread.get(main, 0)
    nones = 0
    t0 = time.time()
    for _ in range(50):
        if pm.next(fuzzer, rng) is None:
            nones += 1
    assert nones > 0
    assert fake.calls_by_thread.get(main, 0) == calls_before, \
        "demoted mutator still polled the pipeline from the draw path"
    assert time.time() - t0 < 5.0, "demoted draws are not fast"

    # Revive the device: the background probe clears the latch.
    fake.mode = "ok"
    deadline = time.time() + 10
    while not pm.healthy() and time.time() < deadline:
        time.sleep(0.02)
    assert pm.healthy(), "probe never re-enabled the recovered pipeline"
    assert isinstance(_draw_device(pm, fuzzer, rng, want_mutant=True),
                      FakeMutant)


def test_latch_fast_demotes_on_open_breaker(fuzzer):
    """When the pipeline's circuit breaker reports open (the worker
    detected the failure streak first), the mutator demotes on the
    next device draw instead of burning demote_after drain-timeout
    waits rediscovering the wedge — and the probe re-promotes once
    the breaker closes and batches flow again."""
    from syzkaller_tpu.health import CircuitBreaker

    rng = RandGen(fuzzer.target, 23)
    fake = FakePipeline()
    fake.breaker = CircuitBreaker(failure_threshold=1,
                                  backoff_initial=60.0)
    pm = PipelineMutator(fake, drain_timeout=30.0, demote_after=50,
                         probe_interval=0.02, probe_timeout=0.01)

    # Healthy breaker: device draws flow normally.
    assert isinstance(_draw_device(pm, fuzzer, rng, want_mutant=True),
                      FakeMutant)

    # Trip the breaker; the pipeline itself still answers (the worker
    # may have failed on a later batch) but the latch must not wait
    # for 50 drain timeouts — it demotes on the next device draw.
    fake.mode = "dead"
    fake.breaker.record_failure()
    assert fake.breaker.is_open()
    deadline = time.time() + 10
    while pm.healthy() and time.time() < deadline:
        pm.next(fuzzer, rng)
    assert not pm.healthy(), "open breaker did not fast-demote"
    assert pm.demotions == 1

    # Breaker closes + pipeline answers: probe re-promotes.
    fake.breaker.record_success()
    fake.mode = "ok"
    deadline = time.time() + 10
    while not pm.healthy() and time.time() < deadline:
        time.sleep(0.02)
    assert pm.healthy(), "probe never re-promoted after breaker close"
    assert pm.repromotions == 1
    snap = pm.health_snapshot()
    assert snap["demotions"] == 1 and not snap["demoted"]


def test_latch_reports_health_transitions_as_stats(fuzzer):
    """Demotions/re-promotions reach the fuzzer's poll-synced Stat
    counters (the manager status page's data source)."""
    rng = RandGen(fuzzer.target, 31)
    fake = FakePipeline()
    pm = PipelineMutator(fake, drain_timeout=0.01, demote_after=2,
                         probe_interval=0.02, probe_timeout=0.01)
    fake.mode = "dead"
    deadline = time.time() + 10
    while pm.healthy() and time.time() < deadline:
        pm.next(fuzzer, rng)
    assert not pm.healthy()
    fake.mode = "ok"
    deadline = time.time() + 10
    while not pm.healthy() and time.time() < deadline:
        time.sleep(0.02)
    # One more draw syncs the counters into stats.
    deadline = time.time() + 10
    while time.time() < deadline:
        pm.next(fuzzer, rng)
        stats = fuzzer.grab_stats()
        if stats.get("device demotions"):
            assert stats["device demotions"] == 1
            break
    else:
        raise AssertionError("demotion never reached Stat counters")


def test_latch_not_tripped_by_single_timeout(fuzzer):
    """One isolated timeout (demote_after=3) must not demote."""
    rng = RandGen(fuzzer.target, 5)
    fake = FakePipeline()
    pm = PipelineMutator(fake, drain_timeout=0.01, demote_after=3,
                         probe_interval=0.02, probe_timeout=0.01)
    fake.mode = "dead"
    # Exactly one device-draw timeout...
    while True:
        before = pm._consec_timeouts
        pm.next(fuzzer, rng)
        if pm._consec_timeouts > before:
            break
    assert pm.healthy()
    # ...then a success resets the streak.
    fake.mode = "ok"
    _draw_device(pm, fuzzer, rng, want_mutant=True)
    assert pm._consec_timeouts == 0
    assert pm.healthy()
