"""csource rendering/compilation, log parsing, and repro pipeline."""

import os
import struct as st
import tempfile

import pytest

from syzkaller_tpu.csource import Options, build_csource, write_csource
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.parse import parse_log
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.repro.repro import Reproducer, bisect_progs


def _gen(target, seed, ncalls=5):
    return generate_prog(target, RandGen(target, seed), ncalls)


# -- csource -------------------------------------------------------------


def test_csource_renders(test_target):
    p = _gen(test_target, 1)
    src = write_csource(p, Options())
    text = src.decode()
    assert "execute_one" in text
    assert "int main" in text
    assert p.calls[0].meta.name.split("$")[0].split("(")[0]  # sanity


def test_csource_compiles_test_target(test_target):
    p = _gen(test_target, 2, ncalls=8)
    src = write_csource(p, Options(repeat=True, procs=2))
    binpath = build_csource(src)
    try:
        assert os.path.exists(binpath)
    finally:
        os.unlink(binpath)


def test_csource_compiles_linux_target():
    target = get_target("linux", "amd64")
    for seed in range(3):
        p = _gen(target, 100 + seed, ncalls=6)
        src = write_csource(p, Options())
        assert b"syscall(" in src
        binpath = build_csource(src)
        os.unlink(binpath)


def test_csource_options_roundtrip():
    opts = Options(threaded=True, repeat=True, procs=4, sandbox="setuid",
                   fault=True, fault_call=3, fault_nth=7)
    s = opts.serialize()
    opts2 = Options.deserialize(s)
    assert opts2 == opts


def test_csource_result_dataflow(test_target):
    # find a generated prog with cross-call resource flow and check the
    # C carries r[...] references
    for seed in range(40):
        p = _gen(test_target, seed, ncalls=8)
        src = write_csource(p)
        if b"r[0]" in src:
            return
    pytest.skip("no resource dataflow in generated programs")


# -- parse_log -----------------------------------------------------------


def test_parse_log_roundtrip(test_target):
    p1, p2 = _gen(test_target, 11, 3), _gen(test_target, 12, 4)
    logdata = (b"booting the machine...\n"
               b"executing program 0:\n" + serialize_prog(p1) +
               b"\nsome console noise\n"
               b"executing program 1:\n" + serialize_prog(p2) +
               b"\nBUG: something died\n")
    entries = parse_log(test_target, logdata)
    assert len(entries) == 2
    assert serialize_prog(entries[0].p) == serialize_prog(p1)
    assert serialize_prog(entries[1].p) == serialize_prog(p2)
    assert entries[0].proc == 0 and entries[1].proc == 1


def test_parse_log_fault_markers(test_target):
    p = _gen(test_target, 13, 2)
    logdata = (b"executing program 2 (fault-call:1 fault-nth:5):\n" +
               serialize_prog(p))
    entries = parse_log(test_target, logdata)
    assert len(entries) == 1
    assert entries[0].fault_call == 1
    assert entries[0].fault_nth == 5


def test_parse_log_tolerates_garbage(test_target):
    logdata = (b"executing program 0:\n"
               b"totally not a program {{{\n"
               b"executing program 1:\n")
    assert parse_log(test_target, logdata) == []


# -- bisect --------------------------------------------------------------


def test_bisect_progs_finds_minimal_set(test_target):
    progs = [_gen(test_target, s, 2) for s in range(10)]
    culprits = {id(progs[3]), id(progs[7])}

    def pred(subset):
        return culprits <= {id(p) for p in subset}

    result = bisect_progs(list(progs), pred)
    assert result is not None
    assert {id(p) for p in result} == culprits


def test_bisect_progs_not_reproducible(test_target):
    progs = [_gen(test_target, s, 2) for s in range(4)]
    assert bisect_progs(progs, lambda ps: False) is None


# -- repro end-to-end against the sim kernel -----------------------------


def _crash_prog(target):
    """Build a Prog that deterministically crashes the sim kernel
    (two magic args on a crashy call)."""
    import syzkaller_tpu.ipc.sim as simmod
    from syzkaller_tpu.models.prog import Call, ConstArg, Prog, make_return_arg

    for cid, meta in enumerate(target.syscalls):
        if simmod.is_crashy(cid) and len(meta.args) >= 2:
            c0, c1 = simmod.crash_magics(cid)
            args = []
            for i, t in enumerate(meta.args):
                val = c0 if i == 0 else c1 if i == 1 else 0
                args.append(ConstArg(t, val))
            call = Call(meta=meta, args=args,
                        ret=make_return_arg(meta.ret))
            return Prog(target=target, calls=[call])
    return None


def test_repro_end_to_end(test_target):
    from syzkaller_tpu.repro.repro import make_env_tester

    crash_p = _crash_prog(test_target)
    if crash_p is None:
        pytest.skip("no crashy call in test target")
    # a crash log with noise + innocent programs + the crasher
    innocent = [_gen(test_target, s, 3) for s in range(3)]
    logdata = b"boot noise\n"
    for i, p in enumerate(innocent):
        logdata += (f"executing program {i}:\n".encode() +
                    serialize_prog(p) + b"\n")
    logdata += (b"executing program 0:\n" + serialize_prog(crash_p) +
                b"\nBUG: sim-kernel: use-after-free in sim_call_x\n")

    tester = make_env_tester(test_target)
    r = Reproducer(test_target, tester, base_duration_s=5.0)
    result = r.run(logdata)
    assert result is not None
    # the reproducer is the crashing call alone (innocents bisected out)
    assert len(result.prog.calls) == 1
    assert result.prog.calls[0].meta.id == crash_p.calls[0].meta.id
    assert result.c_src is not None
    assert b"execute_one" in result.c_src
    assert "repeat" in result.opts_desc


def test_manager_repro_integration(tmp_path, test_target):
    """save_crash → need_repro → run_from_manager → save_repro."""
    from syzkaller_tpu.manager.manager import Manager
    from syzkaller_tpu.manager.mgrconfig import load_config
    from syzkaller_tpu.repro.repro import run_from_manager
    from syzkaller_tpu.utils.hashsig import hash_string

    crash_p = _crash_prog(test_target)
    if crash_p is None:
        pytest.skip("no crashy call in test target")
    cfg = load_config({"workdir": str(tmp_path / "w"), "target": "test/64",
                       "http": ""})
    m = Manager(cfg)
    try:
        logdata = (b"executing program 0:\n" + serialize_prog(crash_p) +
                   b"\nBUG: sim-kernel: use-after-free in sim_call_9\n"
                   b"Call Trace:\n sim_call_9+0x1\n sim_dispatch+0x11\n")
        rep = m.reporter.parse(logdata)
        assert rep is not None
        crash = m.save_crash(rep)
        assert m.need_repro(crash)
        result = run_from_manager(m, crash.title, logdata)
        # title_filter matching is strict; the sim crash title varies by
        # call id, so fall back to no-filter reproduction check
        if result is None:
            from syzkaller_tpu.repro.repro import (Reproducer,
                                                   make_env_tester)

            result = Reproducer(test_target,
                                make_env_tester(test_target),
                                base_duration_s=5.0).run(logdata)
        assert result is not None
        m.save_repro(crash.title, result.prog_text, result.c_src,
                     result.opts_desc)
        sig = hash_string(crash.title.encode())
        repro_file = os.path.join(m.crashdir, sig, "repro.prog")
        assert os.path.exists(repro_file)
    finally:
        m.shutdown()


def test_csource_pseudo_syscalls_compile_and_run():
    """A program using syz_* pseudo-calls renders their C bodies and
    the binary actually opens /proc/self/status through the helper."""
    import subprocess

    from syzkaller_tpu.models.encoding import deserialize_prog

    target = get_target("linux", "amd64")
    text = (b"r0 = syz_open_procfs(0x0, &(0x7f0000000000)='status\\x00')\n"
            b"read(r0, &(0x7f0000001000)=\"\"/16, 0x10)\n")
    p = deserialize_prog(target, text)
    src = write_csource(p, Options())
    s = src.decode()
    assert "static long syz_open_procfs" in s
    assert "syz_open_procfs((long)" in s
    binpath = build_csource(src)
    try:
        # run in a scratch cwd: the generated C mkdtemp's ./syzkaller.XXXXXX
        with tempfile.TemporaryDirectory() as scratch:
            res = subprocess.run([binpath], timeout=30, cwd=scratch)
        assert res.returncode == 0
    finally:
        os.unlink(binpath)


def test_csource_tun_and_sandbox_options():
    """tun/cgroups/namespace options emit their env setup; the binary
    still builds (facilities degrade at runtime, not compile time)."""
    target = get_target("linux", "amd64")
    p = _gen(target, 7, ncalls=4)
    src = write_csource(p, Options(sandbox="namespace", tun=True,
                                   cgroups=True))
    s = src.decode()
    assert "sandbox_namespace();" in s
    assert "setup_tun();" in s and "setup_cgroups();" in s
    binpath = build_csource(src)
    os.unlink(binpath)


def test_csource_emit_ethernet_renders_tun():
    from syzkaller_tpu.models.encoding import deserialize_prog

    target = get_target("linux", "amd64")
    text = (b"syz_emit_ethernet(0xe, &(0x7f0000000000)=\""
            + b"aa" * 14 + b"\")\n")
    p = deserialize_prog(target, text)
    src = write_csource(p, Options())
    s = src.decode()
    assert "setup_tun" in s and "static long syz_emit_ethernet" in s
    binpath = build_csource(src)
    os.unlink(binpath)


def test_csource_new_options_roundtrip():
    opts = Options(sandbox="namespace", tun=True, cgroups=True)
    assert Options.deserialize(opts.serialize()) == opts


def test_csource_big_endian_const_renders():
    """A program with big-endian const fields (network byte order)
    renders htobe conversions and still builds — this path was only
    reachable once descriptions carried int16be/int32be fields."""
    from syzkaller_tpu.models.encoding import deserialize_prog

    target = get_target("linux", "amd64")
    text = (b"r0 = socket$packet(0x11, 0x3, 0x300)\n"
            b"bind$packet(r0, &(0x7f0000000000)={0x11, 0x800, 0x0, 0x0, "
            b"0x0, 0x6, @mac=\"aabbccddeeff0000\"}, 0x14)\n")
    try:
        p = deserialize_prog(target, text)
    except Exception:
        # the exact literal shape is parser-sensitive; generate instead
        from syzkaller_tpu.models.generation import generate_prog
        from syzkaller_tpu.models.prio import build_choice_table
        from syzkaller_tpu.models.rand import RandGen

        enabled = {c: c.name.startswith(("socket$packet", "bind$packet",
                                         "sendto$packet"))
                   for c in target.syscalls}
        ct = build_choice_table(target, enabled=enabled)
        p = None
        for s in range(30):
            cand = generate_prog(target, RandGen(target, 600 + s), 4,
                                 ct=ct)
            if any(c.meta.name == "bind$packet" for c in cand.calls):
                p = cand
                break
        assert p is not None
    src = write_csource(p, Options())
    assert b"htobe16(" in src
    binpath = build_csource(src)
    os.unlink(binpath)
