"""Description-compiler tests: parse round-trips, const patching,
template expansion, and end-to-end compile → generate → serialize.

Mirrors the reference compiler test strategy (reference:
pkg/ast parse tests, pkg/compiler/compiler_test.go) against our own
fresh description source.
"""

import pytest

from syzkaller_tpu.compiler import ast as A
from syzkaller_tpu.compiler.compile import CompileError, compile_description
from syzkaller_tpu.compiler.consts import (
    ConstError,
    eval_expr,
    parse_const_file,
    patch_consts,
    serialize_const_file,
)
from syzkaller_tpu.compiler.parser import ParseError, parse

SRC = """\
# A fresh description exercising the type system.
include <uapi/fake.h>
incdir <include>

define DSL_MAGIC 0x1000 | 0x24
define DSL_NEXT DSL_MAGIC + 1

resource dsl_fd[int32]: -1, DSL_MAGIC
resource dsl_sock[dsl_fd]

open_flags = 1, 2, 4, OPEN_EXTRA
name_strs = "alpha", "beta"

type pair_t[T] {
\tfirst\tT
\tsecond\tT
}
type small int8[0:15]

dsl_hdr {
\tmagic\tconst[DSL_MAGIC, int32]
\tsz\tlen[parent, int16]
\tkind\tint8:4
\tpad\tint8:4
\tbody\tarray[int8, 0:8]
} [packed]

dsl_opts [
\tnum\tint64
\tstr\tstring["fixed", 16]
\tnested\tptr[in, dsl_hdr]
] [varlen]

dsl_mmap(addr vma, len len[addr])
dsl_open(name ptr[in, string[name_strs]], flags flags[open_flags], x bool8) dsl_fd
dsl_use(fd dsl_fd, buf buffer[in], n len[buf], p pair_t[int16be], o ptr[in, optional[int32]])
dsl_sock$make(fd dsl_fd) dsl_sock
dsl_range(a int32[0:100], b proc[1000, 8], c small, v vma[1:4])
dsl_union(u ptr[inout, dsl_opts], extra ptr[out, array[int64, 4]])
"""


def _compile(src=SRC, consts=None, **kw):
    base = {"OPEN_EXTRA": 8, "__NR_dsl_open": 42}
    if consts:
        base.update(consts)
    return compile_description(src, base, **kw)


def test_parse_roundtrip():
    d1 = parse(SRC)
    text = d1.format()
    d2 = parse(text)
    assert d2.format() == text
    kinds = [type(d).__name__ for d in d1.decls]
    assert "Resource" in kinds and "TypeDef" in kinds
    assert "Struct" in kinds and "Call" in kinds


def test_parse_errors_collected():
    with pytest.raises(ParseError) as ei:
        parse("foo(\nbar baz qux(")
    assert "\n" in str(ei.value) or "expected" in str(ei.value)


def test_const_file_roundtrip():
    consts = {"A": 1, "B": 0xFFFF_FFFF_FFFF_FFFF}
    text = serialize_const_file(consts)
    assert parse_const_file(text) == consts


def test_eval_expr():
    env = {"X": 8}
    assert eval_expr("1 << 4 | X", env) == 24
    assert eval_expr("-1", env) == (1 << 64) - 1
    with pytest.raises(ConstError):
        eval_expr("UNKNOWN", env)
    with pytest.raises(ConstError):
        eval_expr("__import__('os')", env)


def test_missing_const_disables_call():
    res = compile_description("foo(a const[MISSING])\nbar(a int32)", {})
    assert res.disabled_calls == ["foo"]
    assert [s.name for s in res.target.syscalls] == ["bar"]


def test_patch_consts_resolves_symbolic():
    d = parse("foo(a const[KNOWN])")
    patch_consts(d, {"KNOWN": 7})
    call = next(x for x in d.decls if isinstance(x, A.Call))
    arg = call.args[0].type.args[0]
    assert isinstance(arg, A.IntValue) and arg.value == 7


def test_compile_basic():
    res = _compile()
    t = res.target
    names = [s.name for s in t.syscalls]
    assert "dsl_open" in names and "dsl_sock$make" in names
    assert not res.disabled_calls
    opn = next(s for s in t.syscalls if s.name == "dsl_open")
    assert opn.nr == 42  # from __NR_dsl_open
    assert opn.ret is not None and opn.ret.name == "dsl_fd"
    # flags patched: OPEN_EXTRA resolved to 8
    fl = opn.args[1]
    assert 8 in fl.vals and fl.vals[:3] == (1, 2, 4)


def test_compile_struct_layout():
    t = _compile().target
    use = next(s for s in t.syscalls if s.name == "dsl_use")
    pair = use.args[3]
    assert pair.name == "pair_t[int16be]"
    assert pair.type_size == 4  # two int16
    assert all(f.big_endian for f in pair.fields)
    opt_ptr = use.args[4]
    un = opt_ptr.elem
    assert un.name == "optional[int32]"
    assert un.varlen  # varlen union


def test_compile_bitfields_and_packed():
    t = _compile().target
    hdr_call = next(s for s in t.syscalls if s.name == "dsl_union")
    union = hdr_call.args[0].elem
    assert union.name == "dsl_opts"
    nested_ptr = union.fields[2]
    hdr = nested_ptr.elem
    # packed struct: const32 + int16 + two 4-bit int8 + blob 0..8
    assert hdr.fields[2].bitfield_length() == 4
    assert hdr.fields[2].bitfield_middle()
    assert not hdr.fields[3].bitfield_middle()


def test_compile_resource_subtyping():
    t = _compile().target
    socks = {r.name: r for r in t.resources}
    assert socks["dsl_sock"].kind == ("dsl_fd", "dsl_sock")
    assert socks["dsl_fd"].values[0] == (1 << 64) - 1  # -1 masked


def test_compile_generates_and_serializes():
    from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    t = _compile().target
    for seed in range(20):
        p = generate_prog(t, RandGen(t, seed), 6)
        text = serialize_prog(p)
        p2 = deserialize_prog(t, text)
        assert serialize_prog(p2) == text


def test_compile_error_unknown_type():
    with pytest.raises(CompileError) as ei:
        _compile("foo(a nosuchtype)")
    assert "unknown type" in str(ei.value)


def test_compile_error_bad_ret():
    with pytest.raises(CompileError) as ei:
        _compile("foo() int32")
    assert "must be a resource" in str(ei.value)


def test_builtin_aliases():
    t = _compile("f(a bool8, b boolptr, c buffer[out])").target
    f = t.syscalls[0]
    assert f.args[0].range_end == 1 and f.args[0].type_size == 1
    assert f.args[1].range_end == 1 and f.args[1].type_size == 8
    # buffer[out] = ptr[out, array[int8]] → pointer to blob
    from syzkaller_tpu.models.types import BufferType, PtrType

    assert isinstance(f.args[2], PtrType)
    assert isinstance(f.args[2].elem, BufferType)


def test_auto_nr_assignment():
    t = _compile("b()\na()\n").target
    nrs = {s.name: s.nr for s in t.syscalls}
    assert nrs["b"] != nrs["a"]


def test_mutation_on_compiled_target():
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.mutation import mutate_prog
    from syzkaller_tpu.models.rand import RandGen

    t = _compile().target
    rg = RandGen(t, 7)
    p = generate_prog(t, rg, 5)
    for _ in range(30):
        mutate_prog(p, rg, 8, corpus=[p])
    assert 1 <= len(p.calls) <= 8


def test_shipped_dsl_target():
    """The dsl OS compiles from shipped descriptions and fuzzes."""
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen
    from syzkaller_tpu.models.target import get_target

    t = get_target("dsl", "64")
    assert t.revision
    assert len(t.syscalls) >= 14
    nrs = {s.name: s.nr for s in t.syscalls}
    assert nrs["dz_open"] == 2  # from dsl_64.const
    p = generate_prog(t, RandGen(t, 3), 8)
    assert p.calls


def test_intptr_respects_ptr_size():
    t = compile_description("g(a intptr)\ns {\n\tf\tintptr\n}\nh(p ptr[in, s])",
                            {}, ptr_size=4).target
    g = next(s for s in t.syscalls if s.name == "g")
    assert g.args[0].type_size == 4
    h = next(s for s in t.syscalls if s.name == "h")
    assert h.args[0].elem.fields[0].type_size == 4


def test_symbolic_range():
    t = compile_description("f(a int32[C1:C2], b proc[0, 1, int16:4])",
                            {"C1": 1, "C2": 9}).target
    a = t.syscalls[0].args[0]
    assert (a.range_begin, a.range_end) == (1, 9)


def test_size_attr_const():
    t = compile_description(
        "s {\n\tf\tint32\n} [size[SZ]]\nh(p ptr[in, s])", {"SZ": 16}).target
    assert t.syscalls[0].args[0].elem.type_size == 16


def test_alias_with_args_rejected():
    with pytest.raises(CompileError) as ei:
        compile_description("k(a bool8[5])", {})
    assert "expects 0 args" in str(ei.value)


def test_lazy_target_survives_failed_factory():
    from syzkaller_tpu.models import target as T

    calls = {"n": 0}

    def factory():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient")
        return T.Target(os="lazyt", arch="x", syscalls=[], resources=[])

    T.register_lazy_target("lazyt", "x", factory)
    with pytest.raises(RuntimeError):
        T.get_target("lazyt", "x")
    t = T.get_target("lazyt", "x")
    assert t.os == "lazyt"
