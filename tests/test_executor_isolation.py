"""Executor process model + race provocation (VERDICT r2 #6/#8).

- fork-per-program: a program that _exits (or wedges) its process
  must not take the fork-server Env down;
- collide mode: the sim kernel's race-window pair is only findable
  with concurrent re-issue — sequential execution never trips it;
- KCOV_TRACE_CMP: comparison operands flow from the real-kernel
  backend when the host has kcov.
"""

import os
import struct

import pytest

from syzkaller_tpu.ipc import sim as simmod
from syzkaller_tpu.ipc.env import (
    ExecFlags,
    ExecOpts,
    ExecutorCrash,
    make_env,
)
from syzkaller_tpu.models.encodingexec import (
    EXEC_ARG_CONST,
    EXEC_INSTR_EOF,
    EXEC_NO_COPYOUT,
)

MASK64 = (1 << 64) - 1


def _raw_call(call_id: int, args: list[int], nr: int = 0) -> list[int]:
    words = [call_id | (nr << 32), EXEC_NO_COPYOUT, len(args)]
    for a in args:
        words += [EXEC_ARG_CONST, 8, a]
    return words


def _stream(calls: list[list[int]]) -> bytes:
    words = [w for c in calls for w in c] + [EXEC_INSTR_EOF]
    return struct.pack(f"<{len(words)}Q", *(w & MASK64 for w in words))


def _find_race_ids() -> tuple[int, int]:
    prep = trig = None
    for cid in range(1, 4096):
        if prep is None and simmod.is_race_prepare(cid):
            prep = cid
        if trig is None and simmod.is_race_trigger(cid):
            trig = cid
        if prep is not None and trig is not None:
            return prep, trig
    raise AssertionError("no race ids in range")


def test_collide_finds_race_window_sequential_does_not():
    prep, trig = _find_race_ids()
    key = 0x1234
    prog = _stream([_raw_call(prep, [key]), _raw_call(trig, [key])])

    # Sequential (and threaded-sequential-wait) execution: the window
    # closes before the trigger runs — never crashes.
    env = make_env(pid=0, sim=True)
    try:
        for _ in range(30):
            res = env.exec(ExecOpts(), prog)
            assert res is not None
    finally:
        env.close()

    # Collide mode re-issues the pair concurrently: the trigger can
    # land inside the prepare's open window.
    env = make_env(pid=1, sim=True)
    crashed = False
    log = ""
    try:
        for _ in range(60):
            try:
                env.exec(ExecOpts(flags=ExecFlags.COLLIDE), prog)
            except ExecutorCrash as e:
                crashed = True
                log = e.log
                break
    finally:
        env.close()
    assert crashed, "collide mode never provoked the race window"
    assert "data race" in log


def test_fork_prog_sim_backend_runs():
    """Fork-per-program on the sim backend: programs execute and
    results flow through the shared out region."""
    env = make_env(pid=0, sim=True, fork_prog=True)
    try:
        prog = _stream([_raw_call(123, [1, 2]), _raw_call(124, [3])])
        for _ in range(3):
            res = env.exec(ExecOpts(), prog)
            assert res.completed
            assert len(res.info) == 2
            assert res.info[0].call_id == 123
    finally:
        env.close()


def test_fork_prog_contains_exit():
    """A real-OS program that exit_group()s mid-run kills only its
    child; the Env keeps serving (VERDICT r2 #6 'done when')."""
    from syzkaller_tpu.models.encoding import deserialize_prog
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.target import get_target

    target = get_target("linux", "amd64")
    text = b"getpid()\nexit_group(0x7)\ngetpid()\n"
    p = deserialize_prog(target, text)
    env = make_env(pid=0, sim=False)  # fork_prog defaults on for real OS
    try:
        res = env.exec(ExecOpts(), serialize_for_exec(p))
        # exit_group killed the child: the run is partial, not trusted.
        assert not res.completed
        # ...but the Env survived and keeps executing programs.
        p2 = deserialize_prog(target, b"getpid()\n")
        res2 = env.exec(ExecOpts(), serialize_for_exec(p2))
        assert res2.completed
        assert res2.info[0].errno == 0
    finally:
        env.close()


def test_fork_prog_preserves_sim_crash_contract():
    """A sim-kernel oops inside the forked child still surfaces as an
    ExecutorCrash (dead executor + oops log)."""
    for cid in range(1, 4096):
        if simmod.is_crashy(cid) and not simmod.is_race_prepare(cid) \
                and not simmod.is_race_trigger(cid):
            c0, c1 = simmod.crash_magics(cid)
            break
    prog = _stream([_raw_call(cid, [c0, c1])])
    env = make_env(pid=0, sim=True, fork_prog=True)
    try:
        with pytest.raises(ExecutorCrash) as ei:
            env.exec(ExecOpts(), prog)
        assert "BUG: sim-kernel" in ei.value.log
    finally:
        env.close()


def test_trace_cmp_linux_backend():
    """KCOV_TRACE_CMP comparison capture on the real-kernel backend
    (skipped when the host has no kcov debugfs)."""
    if not os.path.exists("/sys/kernel/debug/kcov"):
        pytest.skip("host has no kcov")
    from syzkaller_tpu.models.encoding import deserialize_prog
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.target import get_target

    target = get_target("linux", "amd64")
    p = deserialize_prog(
        target, b"openat(0xffffffffffffff9c, "
                b"&(0x7f0000000000)='/dev/null\\x00', 0x0, 0x0)\n")
    env = make_env(pid=0, sim=False)
    try:
        res = env.exec(ExecOpts(flags=ExecFlags.COLLECT_COMPS),
                       serialize_for_exec(p))
        assert res.completed
        assert res.info[0].comps, "no comparison operands flowed"
    finally:
        env.close()
