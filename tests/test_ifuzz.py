"""Tests for the table-driven x86 instruction model (utils/x86.py).

Mirrors the reference's ifuzz tests (reference: pkg/ifuzz/ifuzz_test.go)
— generate/decode round-trips per mode, mode filtering, pseudo
sequences — against our spec-driven table.
"""

import random

import pytest

from syzkaller_tpu.models.types import TextKind
from syzkaller_tpu.utils import ifuzz, x86

MODES = [x86.REAL16, x86.PROT16, x86.PROT32, x86.LONG64]


def test_table_size_and_shape():
    assert len(x86.INSNS) >= 500
    names = {i.name for i in x86.INSNS}
    # spot-check families from every map region
    for nm in ["add", "mov", "push_r", "jz", "lgdt", "wrmsr", "cpuid",
               "vmcall", "vmrun", "movups", "pshufb", "palignr",
               "vaddps", "bswap", "cmpxchg8b", "syscall", "fadd",
                   "movapd", "movss", "cvtsd2si", "pshufd", "roundps",
                   "vfmadd132ps", "pclmulqdq", "popcnt", "fsqrt",
                   "rorx"]:
        assert nm in names, nm
    privs = [i for i in x86.INSNS if i.priv]
    assert len(privs) >= 40
    vex = [i for i in x86.INSNS if i.flags & x86.VEX]
    assert len(vex) >= 20


@pytest.mark.parametrize("mode", MODES)
def test_generate_decode_roundtrip(mode):
    r = random.Random(1234 + mode)
    cfg = x86.Config(mode=mode)
    for _ in range(500):
        insn = x86.generate_insn(cfg, r)
        assert x86.decode(mode, insn) == len(insn), insn.hex()


@pytest.mark.parametrize("mode", MODES)
def test_stream_split(mode):
    r = random.Random(99 + mode)
    cfg = x86.Config(mode=mode, len_insns=16)
    blob = x86.generate(cfg, r)
    chunks = x86.split_insns(mode, blob)
    assert b"".join(chunks) == blob
    for c in chunks:
        assert x86.decode(mode, c) == len(c), c.hex()


@pytest.mark.parametrize("mode", MODES)
def test_pseudo_sequences_decode(mode):
    r = random.Random(7 + mode)
    for _ in range(200):
        seq = x86.pseudo(mode, r)
        chunks = x86.split_insns(mode, seq)
        assert b"".join(chunks) == seq
        for c in chunks:
            assert x86.decode(mode, c) == len(c), (seq.hex(), c.hex())


def test_mode_filtering():
    # NO64 instructions never generate in long mode and vice versa.
    cfg64 = x86.Config(mode=x86.LONG64)
    for i in x86.mode_insns(cfg64):
        assert i.modes & x86.LONG64
    cfg16 = x86.Config(mode=x86.REAL16)
    names16 = {i.name for i in x86.mode_insns(cfg16)}
    assert "aaa" in names16 and "syscall" not in names16
    names64 = {i.name for i in x86.mode_insns(cfg64)}
    assert "syscall" in names64 and "aaa" not in names64


def test_priv_filtering():
    cfg = x86.Config(mode=x86.LONG64, priv=False)
    for i in x86.mode_insns(cfg):
        assert not i.priv
    r = random.Random(5)
    # wrmsr (0F 30) must never appear as a generated instruction
    for _ in range(300):
        insn = x86.generate_insn(cfg, r)
        stripped = insn.lstrip(bytes(x86.LEGACY_PREFIXES))
        assert not stripped.startswith(b"\x0f\x30")


def test_decode_garbage_no_crash():
    r = random.Random(3)
    for _ in range(2000):
        data = bytes(r.randrange(256) for _ in range(r.randrange(1, 18)))
        for mode in MODES:
            n = x86.decode(mode, data)
            assert isinstance(n, int) and (n == -1 or 0 < n <= len(data))


def test_decode_known_encodings():
    # Hand-checked SDM encodings.
    assert x86.decode(x86.LONG64, bytes.fromhex("0fa2")) == 2      # cpuid
    assert x86.decode(x86.LONG64, bytes.fromhex("f4")) == 1        # hlt
    assert x86.decode(x86.LONG64, bytes.fromhex("4889d8")) == 3    # mov rax,rbx
    assert x86.decode(x86.LONG64, bytes.fromhex("b878563412")) == 5  # mov eax,imm32
    assert x86.decode(x86.LONG64,
                      bytes.fromhex("48b80102030405060708")) == 10  # movabs
    assert x86.decode(x86.LONG64, bytes.fromhex("0f0101")) == 3    # sgdt [rcx]
    assert x86.decode(x86.LONG64, bytes.fromhex("0f01c1")) == 3    # vmcall
    assert x86.decode(x86.LONG64, bytes.fromhex("e8deadbeef")) == 5  # call rel32
    assert x86.decode(x86.REAL16, bytes.fromhex("e8dead")) == 3    # call rel16
    assert x86.decode(x86.LONG64, bytes.fromhex("c3")) == 1        # ret
    assert x86.decode(x86.LONG64,
                      bytes.fromhex("810424efbeadde")) == 7  # add [rsp],imm32
    # LES is invalid in long mode; C4 is VEX there (truncated => -1)
    assert x86.decode(x86.LONG64, bytes.fromhex("c410")) == -1
    assert x86.decode(x86.PROT32, bytes.fromhex("c410")) == 2      # les
    # VEX3: vpaddd xmm,xmm,xmm = C4 E1 79... our table uses pp=0 form
    assert x86.decode(x86.LONG64, bytes.fromhex("c4e178fec1")) == 5
    # VEX2 vaddps
    assert x86.decode(x86.LONG64, bytes.fromhex("c5f858c1")) == 4


@pytest.mark.parametrize("mode", MODES)
def test_mutate_structural(mode):
    r = random.Random(42 + mode)
    cfg = x86.Config(mode=mode)
    blob = x86.generate(cfg, r)
    for _ in range(50):
        blob = x86.mutate(cfg, r, blob)
        assert isinstance(blob, bytes)
    # mutation keeps the stream mostly decodable (structural ops keep
    # boundaries; only byte-perturbs can corrupt)
    chunks = x86.split_insns(mode, blob)
    ok = sum(1 for c in chunks if x86.decode(mode, c) == len(c))
    assert ok >= len(chunks) // 2


def test_ifuzz_facade():
    r = random.Random(0)
    for kind in (TextKind.X86_REAL, TextKind.X86_16, TextKind.X86_32,
                 TextKind.X86_64, TextKind.ARM64):
        blob = ifuzz.generate(kind, r)
        assert isinstance(blob, bytes) and blob
        mut = ifuzz.mutate(kind, r, blob)
        assert isinstance(mut, bytes)
    arm = ifuzz.generate(TextKind.ARM64, r)
    assert len(arm) % 4 == 0


def test_mode_coverage_per_family():
    """Every ISA family reaches the modes it architecturally supports
    (real16..long64) — VERDICT r4 ask #5's per-family mode assertion."""
    by_mode = {m: set() for m in MODES}
    for i in x86.INSNS:
        for m in MODES:
            if i.modes & m:
                by_mode[m].add(i.name)
    # legacy families exist everywhere
    for fam in ("add", "mov", "fadd", "movups", "movapd", "movss",
                "pshufb", "sha1msg1", "bswap", "popcnt"):
        for m in MODES:
            assert fam in by_mode[m], (fam, x86.MODE_NAMES[m])
    # VEX/EVEX exist only where the encodings are defined
    for fam in ("vaddps", "vmovapd", "ev_movapd", "evpternlogd",
                "rorx", "pdep"):
        assert fam in by_mode[x86.LONG64], fam
        assert fam in by_mode[x86.PROT32], fam
        assert fam not in by_mode[x86.REAL16], fam
    # 16-bit-only legacy ops never leak into long mode
    for fam in ("aaa", "daa", "pusha", "bound"):
        assert fam not in by_mode[x86.LONG64], fam
    # sizeable per-mode coverage overall
    # 16-bit modes lack the VEX/EVEX planes; 32/64 carry everything
    floors = {x86.REAL16: 700, x86.PROT16: 700,
              x86.PROT32: 1100, x86.LONG64: 1100}
    for m in MODES:
        n = len(by_mode[m])
        assert n > floors[m], (x86.MODE_NAMES[m], n)
