"""Const-extraction machinery (sys/extract): hosted stubs, the
freestanding -m32 pass, and cross-arch curated inheritance.

The 386/arm64 target tests cover the shipped OUTPUT files; these
cover the functions, in particular the two properties that make a
32-bit const set trustworthy on this 64-bit host: struct-size-encoded
ioctls come from a real -m32 compile, and size-coupled values never
inherit across pointer widths (reference analog: per-arch
sys/linux/*.const produced by syz-extract with real cross sysroots).
"""

from __future__ import annotations

import shutil

import pytest

from syzkaller_tpu.sys import extract

pytestmark = pytest.mark.skipif(
    not shutil.which("gcc"), reason="gcc not available")


def test_hosted_extraction_macros_and_enums():
    v = extract.extract_consts(
        ["O_APPEND", "KCMP_FILE", "TZ_NO_SUCH_CONST"],
        includes=["<fcntl.h>", "<linux/kcmp.h>"])
    assert v["O_APPEND"] == 0o2000
    assert v["KCMP_FILE"] == 0      # enumerator: via the fallback pass
    assert v["TZ_NO_SUCH_CONST"] is None


def test_hosted_extraction_skips_enum_fallback_when_disabled():
    v = extract.extract_consts(
        ["KCMP_FILE"], includes=["<linux/kcmp.h>"], enum_fallback=False)
    assert v["KCMP_FILE"] is None   # #ifdef can't see enumerators


def test_m32_pass_gets_32bit_ioctl_sizes():
    """The point of the freestanding pass: _IOR/_IOW numbers embed
    sizeof(struct ...), and 32-bit structs holding longs/pointers are
    smaller — amd64 values are actively wrong for them."""
    v = extract.extract_consts_m32(
        ["VIDIOC_QUERYBUF", "KCOV_INIT_TRACE", "O_LARGEFILE"],
        includes=["<linux/videodev2.h>", "<linux/kcov.h>",
                  "<asm/fcntl.h>"])
    assert v["VIDIOC_QUERYBUF"] == 0xC0445609   # 68-byte 32-bit struct
    assert v["KCOV_INIT_TRACE"] == 0x80046301   # 4-byte unsigned
    assert v["O_LARGEFILE"] == 0o100000         # kernel-ABI view


def test_curated_inheritance_word_size_guard(tmp_path, monkeypatch):
    from syzkaller_tpu.sys import sysgen

    (tmp_path / "linux").mkdir()
    (tmp_path / "linux" / "linux_amd64.const").write_text(
        "HCI_CHANNEL_RAW = 0\n"            # plain: portable
        "ASHMEM_GET_SIZE = 30468\n"        # _IO (size 0): portable
        "ASHMEM_SET_SIZE = 1074296579\n"   # _IOW(size 8): width-coupled
    )
    monkeypatch.setattr(sysgen, "DESC_ROOT", tmp_path)
    merged = {"HCI_CHANNEL_RAW": None, "ASHMEM_GET_SIZE": None,
              "ASHMEM_SET_SIZE": None, "__NR_open": None}
    extract._inherit_curated(merged, "amd64", same_word_size=False)
    assert merged["HCI_CHANNEL_RAW"] == 0
    assert merged["ASHMEM_GET_SIZE"] == 30468
    assert merged["ASHMEM_SET_SIZE"] is None   # stays disabled
    assert merged["__NR_open"] is None         # NR tables never inherit
    # same word size (arm64): the size-encoded value IS portable
    merged2 = {"ASHMEM_SET_SIZE": None}
    extract._inherit_curated(merged2, "amd64", same_word_size=True)
    assert merged2["ASHMEM_SET_SIZE"] == 1074296579
