"""Fault-domain mesh engine (parallel/fault_domain + parallel/compat):
shard-loss chaos in a fresh subprocess, compat-shim emulation
semantics, and graceful-degradation bookkeeping."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

REPO = Path(__file__).resolve().parents[1]


# -- compat shim ----------------------------------------------------------

def _with_emulated_impl(monkeypatch):
    from syzkaller_tpu.parallel import compat

    monkeypatch.setenv("TZ_MESH_COMPAT", "emulated")
    compat.reset_impl()
    return compat


@pytest.fixture
def emulated_compat(monkeypatch):
    compat = _with_emulated_impl(monkeypatch)
    yield compat
    # Drop the forced probe so later tests re-select for this build.
    monkeypatch.delenv("TZ_MESH_COMPAT", raising=False)
    compat.reset_impl()


def test_compat_emulated_collectives_match_reference(emulated_compat):
    """The nested-vmap emulation gives psum/pmax/axis_index the exact
    per-shard view shard_map would: a two-axis mesh function using
    all three reduces to the analytic reference."""
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from syzkaller_tpu.parallel import mesh as pmesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    assert emulated_compat.impl_name() == "emulated"
    mesh = pmesh.make_mesh(jax.devices()[:8], cov=2)  # batch=4, cov=2

    def f(x, y):
        # x sharded over batch (dim 0), y replicated.  x is replicated
        # over cov, so the all-axis psum counts each element cov times.
        total = lax.psum(x.sum(), ("batch", "cov"))
        peak = lax.pmax(x.max(), ("batch", "cov"))
        lane = lax.axis_index("batch").astype(jnp.int32)
        return x + y + lane, total, peak

    step = emulated_compat.shard_map(
        f, mesh=mesh, in_specs=(P("batch"), P()),
        out_specs=(P("batch"), P(), P()))
    x = np.arange(16, dtype=np.int32).reshape(8, 2)
    y = np.int32(100)
    out, total, peak = jax.jit(step)(x, y)
    lanes = np.repeat(np.arange(4, dtype=np.int32), 2)[:, None]
    assert np.array_equal(np.asarray(out), x + 100 + lanes)
    assert int(total) == 2 * int(x.sum())   # cov=2 replicas
    assert int(peak) == int(x.max())


def test_compat_probe_never_imports_shard_map_at_module_load():
    """parallel.mesh must import cleanly on every jax build: the
    compat probe runs at first shard_map use, not at import (the
    pre-shim module died with AttributeError at import on builds
    lacking jax.shard_map — the old 7-failure tier-1 floor)."""
    import ast

    src = (REPO / "syzkaller_tpu" / "parallel" / "mesh.py").read_text()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.ImportFrom):
            assert "shard_map" not in (node.module or ""), \
                f"mesh.py imports shard_map directly: {node.module}"
            assert not any("shard_map" in a.name for a in node.names)
        elif isinstance(node, ast.Import):
            assert not any("shard_map" in a.name for a in node.names)
    assert "compat.shard_map" in src


def test_compat_forced_level_is_honored(emulated_compat):
    assert emulated_compat.impl_name() == "emulated"


# -- graceful-degradation bookkeeping (no device compiles) ----------------

def test_mesh_engine_pads_batch_to_live_width():
    """Shrinking N re-pads the staged batch with zero-edge rows —
    pad rows can never merge signal, real rows are never dropped."""
    from syzkaller_tpu.parallel.fault_domain import MeshEngine

    B = 10
    batch = {"kind": np.arange(B, dtype=np.int32)}
    edges = np.ones((B, 4), np.int32)
    nedges = np.full(B, 4, np.int32)
    prios = np.full(B, 2, np.int32)
    got = MeshEngine._pad(None, 4, batch, edges, nedges, prios, None)
    B0, batch_p, edges_p, nedges_p, prios_p, tidx = got
    assert B0 == B
    assert batch_p["kind"].shape[0] == 12
    assert np.array_equal(nedges_p[B:], np.zeros(2, np.int32))
    assert np.array_equal(batch_p["kind"][:B],
                          np.arange(B, dtype=np.int32))


def test_mesh_arena_reshard_conserves_rows():
    """ISSUE 18 fault-domain seam: attach_arena re-stages the corpus
    slabs from HOST authority (row-exact copy) and invalidates the
    owning pipeline's slab so its next flush is the one-scatter epoch
    rebuild; a topology rebuild repeats the re-stage with zero lost
    rows.  Uses a real 1-device mesh — the conservation contract is
    identical at any width, and the 8->7 odd-width replicate fallback
    runs in the slow chaos drill below."""
    import threading

    jax = pytest.importorskip("jax")
    import jax.numpy as jnp  # noqa: F401

    from syzkaller_tpu import telemetry
    from syzkaller_tpu.ops.arena import CorpusArena
    from syzkaller_tpu.parallel import mesh as pmesh
    from syzkaller_tpu.parallel.fault_domain import MeshEngine

    eng = object.__new__(MeshEngine)
    eng._lock = threading.RLock()
    eng._mesh = pmesh.make_mesh(jax.devices()[:1], 1)
    eng._arena = None
    eng._arena_dev = None
    eng._hbm_arena = telemetry.HBM.register("mesh", "arena",
                                            bound_to=eng)

    arena = CorpusArena(8, slab_bits=3, headroom_bytes=1 << 30)
    for i in range(5):
        arena.stage(i, {"val": np.full(4, 10 * i, np.uint64),
                        "len": np.int32(i)})
    arena.flush(jnp)
    assert arena.uploads == 1 and arena.n == 5
    e0 = arena.epoch

    eng.attach_arena(arena)
    assert eng._arena_dev is not None
    # the mesh-resident copy holds every occupied row byte-exact
    for k, v in arena.host.items():
        np.testing.assert_array_equal(
            np.asarray(eng._arena_dev[k])[:5], v[:5])
    # the owner's slab was invalidated: one epoch bump, full restage
    # pending — the pipeline's next flush is the one-scatter rebuild
    assert arena.epoch == e0 + 1
    assert len(arena._pending) == 5
    arena.flush(jnp)

    # chip-loss rebuild path: _reshard_arena runs again on every
    # _build; rows conserved, another single epoch bump
    eng._reshard_arena()
    for k, v in arena.host.items():
        np.testing.assert_array_equal(
            np.asarray(eng._arena_dev[k])[:5], v[:5])
    assert arena.epoch == e0 + 2 and arena.n == 5


def test_mesh_engine_cov_fit_shrinks_with_live_set():
    from syzkaller_tpu.parallel.fault_domain import MeshEngine

    eng = object.__new__(MeshEngine)
    eng._cov_req = 4
    eng.plane_size = 1 << 26
    eng.mutant_bits = 10
    assert eng._fit_cov(8) == 4
    assert eng._fit_cov(7) == 1   # 7 has no even divisor
    assert eng._fit_cov(6) == 2   # largest c <= 4 dividing 6 and 2^k
    assert eng._fit_cov(1) == 1


# -- shard-loss chaos (fresh subprocess, no warm fixtures) ----------------

_CHAOS_SCRIPT = r"""
import os, json, sys, time
import numpy as np
import jax

from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.ops.pipeline import PIPELINE_TENSOR_CONFIG
from syzkaller_tpu.ops.tensor import FlagTables, encode_prog, stack_batch
from syzkaller_tpu.ops import signal as dsig
from syzkaller_tpu.parallel.fault_domain import MeshEngine
from syzkaller_tpu.health import faultinject

assert len(jax.devices()) == 8, jax.devices()

target = get_target("test", "64")
flags = FlagTables.empty()
tensors, i = [], 0
while len(tensors) < 8 and i < 64:
    p = generate_prog(target, RandGen(target, 600 + i), 4)
    i += 1
    try:
        tensors.append(encode_prog(p, PIPELINE_TENSOR_CONFIG, flags))
    except Exception:
        continue
assert len(tensors) == 8
batch = {k: np.asarray(v) for k, v in stack_batch(tensors).items()}

B, E = 8, 8
rng = np.random.default_rng(0)
mk = lambda: rng.integers(0, 1 << 20, size=(B, E),
                          dtype=np.uint32).astype(np.int32)
nedges = np.full(B, E, np.int32)
prios = np.full(B, 2, np.int32)

eng = MeshEngine(devices=jax.devices()[:8], cov=1, rounds=1,
                 breaker_threshold=1, mutant_bits=10, seed=7,
                 flags=flags)
for d in eng.domains:
    d.breaker.configure_backoff(initial=0.05, cap=0.05)

# -- corpus arena rides the fault domain (ISSUE 18): attach a small
# arena; every topology rebuild must re-stage it from host authority
import jax.numpy as jnp
from syzkaller_tpu.ops.arena import CorpusArena
arena = CorpusArena(8, slab_bits=3, headroom_bytes=1 << 30)
for i in range(6):
    arena.stage(i, {"val": np.full(4, 100 + i, np.uint64),
                    "len": np.int32(i)})
arena.flush(jnp)
eng.attach_arena(arena)
arena.flush(jnp)  # the owner's one-scatter epoch rebuild
arena_epoch0 = arena.epoch

def assert_arena_conserved(tag):
    assert arena.n == 6, (tag, arena.n)
    for k, v in arena.host.items():
        got = np.asarray(eng._arena_dev[k])[:6]
        assert np.array_equal(got, v[:6]), (tag, k)
assert_arena_conserved("attach")

# -- warm step: mirror must replay the device merge exactly
e1 = mk()
out1 = eng.step(batch, e1, nedges, prios)
ref = dsig.merge(np.zeros(dsig.PLANE_SIZE, np.uint8), e1, nedges,
                 prios, out1["new_counts"] > 0)
assert np.array_equal(eng.mirror_plane(), np.asarray(ref)), "mirror drift"
assert int(out1["n_novel"].sum()) > 0
for s, rows in enumerate(out1["novel_rows"]):
    assert rows.shape[0] == int(out1["n_novel"][s])

# -- chaos: the collective launch dies; the probe sweep (shard order,
# one mesh.shard_probe occurrence each) blames exactly shard 3
faultinject.install_plan(faultinject.FaultPlan.parse(
    "device.launch:fail@1;mesh.shard_probe:fail@4"))
e2 = mk()
out2 = eng.step(batch, e2, nedges, prios)
snap = eng.health_snapshot()
assert snap["devices_live"] == 7, snap
assert snap["devices_demoted"] == 1
assert snap["shards"][3]["demoted"], snap["shards"][3]

# chip loss costs device residency, never corpus rows: 7 does not
# divide the pow2 slab, so the rebuild replicated the slabs — every
# row still resident and byte-exact, and the owning pipeline's slab
# was invalidated for its own one-scatter re-upload
assert_arena_conserved("demote")
assert snap["arena_rows"] == 6 and snap["arena_sharded"], snap
assert arena.epoch == arena_epoch0 + 1, arena.epoch
arena.flush(jnp)

# zero lost corpus: the staged batch re-dispatched to survivors —
# every program got a verdict and every shard's novel prefix is whole
assert out2["new_counts"].shape[0] == B
assert sum(r.shape[0] for r in out2["novel_rows"]) \
    == int(out2["n_novel"].sum())

# zero lost signal: N-1 verdicts and mirror match the exact reference
_, rc2 = dsig.diff_batch(np.asarray(ref), e2, nedges, prios)
assert np.array_equal(out2["new_counts"], np.asarray(rc2)), "lost verdicts"
ref = dsig.merge(np.asarray(ref), e2, nedges, prios, rc2 > 0)
assert np.array_equal(eng.mirror_plane(), np.asarray(ref)), "lost signal"

# -- heal: half-open probe re-admits, planes re-shard back up
faultinject.reset_plan()
time.sleep(0.1)
e3 = mk()
out3 = eng.step(batch, e3, nedges, prios)
snap = eng.health_snapshot()
assert snap["devices_live"] == 8, snap
_, rc3 = dsig.diff_batch(np.asarray(ref), e3, nedges, prios)
assert np.array_equal(out3["new_counts"], np.asarray(rc3))

# re-promote re-shards the slabs back over the full pow2 width —
# the whole demote -> serve-from-7 -> re-promote trajectory lost
# zero corpus rows
assert_arena_conserved("repromote")
assert arena.epoch == arena_epoch0 + 2, arena.epoch
arena.flush(jnp)

# -- compile-count guard: N -> N-1 -> N built exactly the two
# expected meshes.  One more step absorbs the loop-back signature
# (jit-OUTPUT planes feeding back as inputs adds a C++ fastpath
# cache entry without recompiling); after that, steady state must
# add zero cache entries of any kind.
assert len(eng._graphs) == 2, len(eng._graphs)
eng.step(batch, mk(), nedges, prios)
sizes = [s._cache_size() for _m, s in eng._graphs.values()]
assert all(c <= 2 for c in sizes), sizes
eng.step(batch, mk(), nedges, prios)
assert [s._cache_size() for _m, s in eng._graphs.values()] == sizes, \
    "steady-state mesh step retraced"

# -- ISSUE 17: the CompileObservatory recorded exactly those two
# builds (fresh interpreter, so absolute counts are exact), and the
# residency ledger conserves across the demote/re-shard/re-promote
# cycle — tracked mesh bytes match the backend report with no
# orphaned entries.
from syzkaller_tpu import telemetry
assert telemetry.COMPILES.builds("mesh.fused_step") == 2
assert len(telemetry.COMPILES.shapes("mesh.fused_step")) == 2
assert telemetry.HBM.live_bytes("mesh", device_only=True) > 0
rec = telemetry.HBM.reconcile()
assert rec["drift_bytes"] == 0 and rec["dead_entries"] == 0, rec

print(json.dumps({"ok": True, "graphs": len(eng._graphs),
                  "novel_total": int(out1["n_novel"].sum())}))
"""


@pytest.mark.slow
def test_mesh_shard_loss_chaos_subprocess():
    """ISSUE 11 chaos drill, in a FRESH interpreter sharing no warm
    fixtures: scripted chip loss on an 8-way CPU mesh (the
    device.launch fault kills the collective, the mesh.shard_probe
    occurrence blames shard 3) must lose zero corpus programs and
    zero signal across demote -> serve-from-7 -> re-promote, and the
    whole trajectory compiles exactly the two expected meshes.  The
    same asserts run in-subprocess; this test checks the verdict."""
    env = dict(os.environ)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_PLATFORMS": "cpu",
        "TZ_MUTANT_PLANE_BITS": "10",
        "PYTHONPATH": str(REPO),
    })
    env.pop("TZ_FAULT_PLAN", None)
    env.pop("TZ_MESH_COMPAT", None)
    res = subprocess.run(
        [sys.executable, "-c", _CHAOS_SCRIPT], env=env, cwd=str(REPO),
        capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, \
        f"chaos subprocess failed:\n{res.stdout}\n{res.stderr}"
    verdict = json.loads(res.stdout.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["graphs"] == 2
    assert verdict["novel_total"] > 0
