"""Device exec-bytes emitter tests.

The load-bearing oracle: for any device mutation, patch-assembled exec
bytes must be BIT-IDENTICAL to serializing the decoded typed mutant
with the same data capacities (reference golden-stream strategy:
prog/encodingexec_test.go:14).  Call-removal mutants are checked
structurally (segment slicing keeps the stream well-formed and drops
exactly the dead calls).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.prog import foreach_arg  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.emit import (  # noqa: E402
    build_exec_template,
    assemble,
    mutant_call_ids,
    parse_stream,
)
from syzkaller_tpu.ops.mutate import make_mutator  # noqa: E402
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    DATA,
    FlagTables,
    TensorConfig,
    decode_prog,
    encode_prog,
)


def _encode_some(target, n, cfg, flags, seed0=100):
    tensors = []
    i = 0
    while len(tensors) < n and i < n * 8:
        p = generate_prog(target, RandGen(target, seed0 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    assert len(tensors) >= max(1, n // 2), "generated programs stopped tensorizing"
    return tensors


def _cloned_data_caps(t, decoded):
    """Map the template's slot caps onto the decoded clone's args
    (valid only when no call was removed)."""
    tmpl_args, clone_args = [], []
    for c in t.template.calls:
        foreach_arg(c, lambda a, ctx: tmpl_args.append(a))
    for c in decoded.calls:
        foreach_arg(c, lambda a, ctx: clone_args.append(a))
    amap = {id(a): b for a, b in zip(tmpl_args, clone_args)}
    return {id(amap[id(t.slot_args[s])]): int(t.cap[s])
            for s in range(len(t.slot_args)) if t.kind[s] == DATA}


def test_template_assembly_identity(test_target):
    """With unmutated rows, assembly reproduces the template stream."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    for t in _encode_some(test_target, 10, cfg, flags):
        et = build_exec_template(t)
        got = assemble(et, t.val, t.len_, t.arena, t.call_alive)
        caps = {id(t.slot_args[s]): int(t.cap[s])
                for s in range(len(t.slot_args)) if t.kind[s] == DATA}
        want = serialize_for_exec(t.template, data_caps=caps)
        assert got == want


def test_assembly_matches_typed_serialization(test_target, iters):
    """The oracle: assembled bytes == typed serialization of the
    decoded mutant, for every device mutation that keeps all calls."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = _encode_some(test_target, 8, cfg, flags)
    mutate = make_mutator(rounds=4)
    key = random.key(7)
    checked = 0
    for it in range(iters * 4):
        t = tensors[it % len(tensors)]
        et = build_exec_template(t)
        batch = {k: jnp.asarray(v)[None] for k, v in t.arrays().items()}
        key, sub = random.split(key)
        mut = mutate(batch, sub, jnp.asarray(flags.vals),
                     jnp.asarray(flags.counts))
        row = {k: np.asarray(v[0]) for k, v in mut.items()}
        alive = row["call_alive"]
        if not alive[:t.ncalls].all():
            continue  # removal covered by test_assembly_call_removal
        got = assemble(et, row["val"], row["len_"], row["arena"], alive)
        decoded = decode_prog(t, row, preserve_sizes=True)
        caps = _cloned_data_caps(t, decoded)
        want = serialize_for_exec(decoded, data_caps=caps)
        assert got == want, f"stream mismatch on iteration {it}"
        checked += 1
    assert checked >= iters  # the oracle actually ran


def test_assembly_call_removal(test_target):
    """Killing calls slices exactly their segments out."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    rng = np.random.RandomState(3)
    for t in _encode_some(test_target, 10, cfg, flags, seed0=300):
        if t.ncalls < 2:
            continue
        et = build_exec_template(t)
        alive = t.call_alive.copy()
        kill = rng.randint(0, t.ncalls)
        alive[kill] = False
        stream = assemble(et, t.val, t.len_, t.arena, alive)
        got_ids = parse_stream(stream)
        want_ids = [t.template.calls[i].meta.id
                    for i in mutant_call_ids(et, alive)]
        assert got_ids == want_ids


def test_parse_stream_rejects_garbage():
    with pytest.raises(ValueError):
        parse_stream(b"\x07\x00\x00\x00\x00\x00\x00\x00" * 3)


def _synth_delta_batch(ets, spec, B, rng):
    """Randomized DeltaBatch straight in numpy — no device, no jit:
    rows reference real templates in ring-wrap interleaved order,
    carry random value patches (incl. PROC default/concrete forms),
    random data spans with pooled payloads (some rows pool-less),
    dead-call alive masks, and a sprinkle of overflow-flagged rows."""
    from syzkaller_tpu.ops.delta import (
        FLAG_OVERFLOW, FLAG_PRESERVE, DeltaBatch)

    K, D, P = spec.K, spec.D, spec.P
    buf = np.zeros((B, spec.row_bytes), np.uint8)
    npool = max(1, B // spec.pool_div)
    pool = rng.randint(0, 256, size=(npool, P)).astype(np.uint8)
    hdr_i32 = lambda col, v: v.astype("<i4").view(np.uint8)  # noqa: E731

    tidx = rng.randint(0, len(ets), size=B).astype(np.int32)
    for j in range(B):
        et = ets[tidx[j]]
        # value patches: sample real patchable slots (value + PROC)
        # without replacement, plus -1 padding.
        cand = np.concatenate([et.value_slots, et.proc_slots])
        nv = rng.randint(0, min(K, max(len(cand), 1)) + 1)
        val_idx = np.full(K, -1, np.int16)
        vals = np.zeros(K, np.uint64)
        if nv and len(cand):
            picks = rng.choice(cand, size=min(nv, len(cand)),
                               replace=False)
            nv = len(picks)
            val_idx[:nv] = picks
            raw = rng.randint(0, 1 << 62, size=nv).astype(np.uint64)
            for i, s in enumerate(picks):
                if et.is_proc[s] and rng.rand() < 0.5:
                    raw[i] = np.uint64(0xFFFFFFFFFFFFFFFF)  # default
            vals[:nv] = raw
        else:
            nv = 0
        # data spans: real DATA slots, lens occasionally over cap
        # (clamp path), 8-aligned pool offsets that stay in the slot.
        data_slot = np.full(D, -1, np.int16)
        data_len = np.zeros(D, np.int32)
        data_off = np.zeros(D, np.int32)
        nd = rng.randint(0, min(D, max(len(et.data_slots), 1)) + 1) \
            if len(et.data_slots) else 0
        off = 0
        kept = 0
        for s in (rng.choice(et.data_slots, size=nd, replace=False)
                  if nd else ()):
            cap = int(et.data_cap[s])
            ln = rng.randint(0, cap + 3)  # may exceed cap: clamps
            if off + min(ln, cap) > P:
                break
            data_slot[kept] = s
            data_len[kept] = ln
            data_off[kept] = off
            off += (min(ln, cap) + 7) & ~7
            kept += 1
        nd = kept
        pool_idx = -1
        if nd and rng.rand() < 0.8:
            pool_idx = int(rng.randint(0, npool))
        # alive mask: mostly full, sometimes dead calls (even all-dead)
        alive = np.uint64((1 << max(et.ncalls, 1)) - 1)
        if rng.rand() < 0.4 and et.ncalls > 0:
            alive &= np.uint64(rng.randint(0, 1 << et.ncalls))
        flags = 0
        if rng.rand() < 0.1:
            flags |= FLAG_OVERFLOW
        if rng.rand() < 0.3:
            flags |= FLAG_PRESERVE
        buf[j, 0] = nv
        buf[j, 1] = nd
        buf[j, 2] = flags
        buf[j, 3] = 0
        buf[j, 4:8] = hdr_i32(4, np.array([tidx[j]]))
        buf[j, 8:16] = np.array([alive], "<u8").view(np.uint8)
        buf[j, 16:20] = hdr_i32(16, np.array([-1]))
        buf[j, 20] = 0
        buf[j, 24:28] = hdr_i32(24, np.array([pool_idx]))
        o = spec.o_val_idx
        buf[j, o:o + 2 * K] = val_idx.astype("<i2").view(np.uint8)
        o = spec.o_vals
        buf[j, o:o + 8 * K] = vals.astype("<u8").view(np.uint8)
        o = spec.o_data_slot
        buf[j, o:o + 2 * D] = data_slot.astype("<i2").view(np.uint8)
        o = spec.o_data_len
        buf[j, o:o + 4 * D] = data_len.astype("<i4").view(np.uint8)
        o = spec.o_data_off
        buf[j, o:o + 4 * D] = data_off.astype("<i4").view(np.uint8)
    return DeltaBatch(buf, spec, pool=pool)


def test_vectorized_arena_matches_delta_reference(test_target, iters):
    """ISSUE 3 regression: the vectorized arena fast path is
    byte-identical to the per-mutant assemble_delta reference on
    randomized DeltaBatches — ring-wrap template interleaving,
    overflow rows, dead-call (and all-dead) slicing, over-cap lengths,
    pool-less payload rows.  Pure numpy, no device step, no compiles
    (the suite runs at its wall-clock budget)."""
    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW, DeltaSpec
    from syzkaller_tpu.ops.emit import (
        TemplateTable, assemble_batch, assemble_batch_table,
        assemble_delta)

    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = _encode_some(test_target, 6, cfg, flags, seed0=700)
    ets = [build_exec_template(t) for t in tensors] + [None]  # dead slot
    table = TemplateTable(ets)
    spec = DeltaSpec()
    rng = np.random.RandomState(1234)
    seen_view = seen_dead = seen_poolless = 0
    for _ in range(max(3, iters // 10)):
        batch = _synth_delta_batch(ets[:-1], spec, 48, rng)
        ok = (batch.flags & FLAG_OVERFLOW) == 0
        js = np.flatnonzero(ok)
        datas = assemble_batch(ets, batch, js)
        # The one-pass stacked-table assembler agrees with the
        # per-group path entry by entry (both bytes-like or both None).
        tdatas = assemble_batch_table(table, batch, js)
        assert len(tdatas) == len(datas)
        for a, b in zip(datas, tdatas):
            if a is None:
                assert b is None
            else:
                assert b is not None and bytes(a) == bytes(b)
        for j, got in zip(js, datas):
            et = ets[int(batch.template_idx[j])]
            try:
                want = assemble_delta(et, batch, int(j))
            except Exception:
                want = None
            if want is None:
                assert got is None
                continue
            assert got is not None and bytes(got) == want, \
                f"row {j} diverged from the delta oracle"
            if isinstance(got, memoryview):
                seen_view += 1
            full = (1 << max(et.ncalls, 1)) - 1
            if int(batch.alive_bits[j]) & full != full:
                seen_dead += 1
            if batch.ndata[j] and int(batch.pool_idx[j]) < 0:
                seen_poolless += 1
    # The interesting paths actually ran.
    assert seen_view > 0, "fast path never produced arena views"
    assert seen_dead > 0, "no dead-call slicing exercised"
    assert seen_poolless > 0, "no pool-less payload row exercised"


def test_splice_insert_group_matches_per_mutant(test_target):
    """The vectorized insert splicer (unique-donor rebase + ragged
    arena copies) is byte-identical to per-mutant splice_insert across
    random alive masks, positions (incl. past-the-end clamping), and
    donors — pure numpy, no device step."""
    from syzkaller_tpu.models.prio import build_choice_table
    from syzkaller_tpu.ops.emit import splice_insert, splice_insert_group
    from syzkaller_tpu.ops.insert import DonorBank

    ct = build_choice_table(test_target)
    bank = DonorBank(test_target, ct, seed=5)
    assert len(bank.blocks) > 4
    cfg = TensorConfig()
    flags = FlagTables.empty()
    rng = np.random.RandomState(77)
    checked = 0
    for t in _encode_some(test_target, 6, cfg, flags, seed0=820):
        et = build_exec_template(t)
        m = 24
        donors = rng.randint(0, len(bank.blocks), size=m)
        poses = rng.randint(0, et.ncalls + 3, size=m).astype(np.uint8)
        full = (1 << max(et.ncalls, 1)) - 1
        alive_bits = np.where(
            rng.rand(m) < 0.5, full,
            rng.randint(0, full + 1, size=m)).astype(np.uint64)
        datas = splice_insert_group(et, alive_bits, donors, poses,
                                    bank.blocks)
        for i in range(m):
            alive = ((alive_bits[i] >> np.arange(
                max(et.ncalls, 1), dtype=np.uint64)) & 1).astype(bool)
            want = splice_insert(et, alive, bank.blocks[int(donors[i])],
                                 int(poses[i]))
            got = datas[i]
            if want is None:
                assert got is None
            else:
                assert got is not None and bytes(got) == want, \
                    f"insert row {i} diverged from splice_insert"
            checked += 1
    assert checked >= 48


def test_splice_batch_table_matches_per_mutant(test_target):
    """The one-pass cross-template splicer handles exactly the
    tiled/full-alive/budget-ok rows (fast mask), byte-identical to
    splice_insert; dead-call, invalid-donor, and dead-slot rows are
    declined for the per-group path."""
    from syzkaller_tpu.models.prio import build_choice_table
    from syzkaller_tpu.ops.emit import (
        DonorBankTable, TemplateTable, splice_insert, splice_batch_table)
    from syzkaller_tpu.ops.insert import DonorBank

    ct = build_choice_table(test_target)
    bank = DonorBank(test_target, ct, seed=9)
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = _encode_some(test_target, 5, cfg, flags, seed0=860)
    ets = [build_exec_template(t) for t in tensors] + [None]
    table = TemplateTable(ets)
    dtab = DonorBankTable(bank.blocks)
    rng = np.random.RandomState(31)
    m = 64

    class _B:
        template_idx = rng.randint(0, len(ets), size=m)
        donor = rng.randint(-1, len(bank.blocks), size=m)
        pos = rng.randint(0, 8, size=m).astype(np.uint8)
        alive_bits = np.zeros(m, np.uint64)

    b = _B()
    for i in range(m):
        et = ets[b.template_idx[i]]
        nc = et.ncalls if et is not None else 0
        full = (1 << max(nc, 1)) - 1
        b.alive_bits[i] = full if rng.rand() < 0.7 \
            else rng.randint(0, full + 1)
    datas, fast = splice_batch_table(table, dtab, b, np.arange(m))
    n_fast = n_declined = 0
    for i in range(m):
        et = ets[b.template_idx[i]]
        if fast[i]:
            alive = np.ones(max(et.ncalls, 1), bool)
            want = splice_insert(et, alive, bank.blocks[int(b.donor[i])],
                                 int(b.pos[i]))
            assert want is not None
            assert bytes(datas[i]) == want, f"row {i} diverged"
            n_fast += 1
        else:
            assert datas[i] is None
            full = (1 << max(et.ncalls if et else 0, 1)) - 1
            declined_ok = (et is None or b.donor[i] < 0
                           or (int(b.alive_bits[i]) & full) != full
                           or et.ncopyouts + bank.blocks[
                               int(b.donor[i])].ncopyouts > 256
                           or not et.seg_tiled)
            assert declined_ok, f"row {i} wrongly declined"
            n_declined += 1
    assert n_fast >= 8 and n_declined >= 4


def test_assemble_batch_matches_assemble_delta(test_target):
    """The vectorized group assembler is bit-identical to the
    per-mutant delta assembler over a full device batch."""
    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW, DeltaBatch
    from syzkaller_tpu.ops.emit import assemble_batch, assemble_delta
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    pl = DevicePipeline(test_target, capacity=32, batch_size=64, seed=11)
    added, i = 0, 0
    while added < 10 and i < 60:
        p = generate_prog(test_target, RandGen(test_target, 4000 + i), 6)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= 5
    batch, tmpl, ets = pl._fetch(pl._launch())
    ok = (batch.flags & FLAG_OVERFLOW) == 0
    ok &= (batch.template_idx >= 0) & (batch.template_idx < len(tmpl))
    js = np.flatnonzero(ok)
    assert js.size >= 32
    datas = assemble_batch(ets, batch, js)
    for j, got in zip(js, datas):
        et = ets[int(batch.template_idx[j])]
        want = assemble_delta(et, batch, int(j))
        assert got == want, f"mutant {j} diverged from the delta oracle"
