"""Device exec-bytes emitter tests.

The load-bearing oracle: for any device mutation, patch-assembled exec
bytes must be BIT-IDENTICAL to serializing the decoded typed mutant
with the same data capacities (reference golden-stream strategy:
prog/encodingexec_test.go:14).  Call-removal mutants are checked
structurally (segment slicing keeps the stream well-formed and drops
exactly the dead calls).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import random  # noqa: E402

from syzkaller_tpu.models.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.models.generation import generate_prog  # noqa: E402
from syzkaller_tpu.models.prog import foreach_arg  # noqa: E402
from syzkaller_tpu.models.rand import RandGen  # noqa: E402
from syzkaller_tpu.ops.emit import (  # noqa: E402
    build_exec_template,
    assemble,
    mutant_call_ids,
    parse_stream,
)
from syzkaller_tpu.ops.mutate import make_mutator  # noqa: E402
from syzkaller_tpu.ops.tensor import (  # noqa: E402
    DATA,
    FlagTables,
    TensorConfig,
    decode_prog,
    encode_prog,
)


def _encode_some(target, n, cfg, flags, seed0=100):
    tensors = []
    i = 0
    while len(tensors) < n and i < n * 8:
        p = generate_prog(target, RandGen(target, seed0 + i), 6)
        i += 1
        try:
            tensors.append(encode_prog(p, cfg, flags))
        except Exception:
            continue
    assert len(tensors) >= max(1, n // 2), "generated programs stopped tensorizing"
    return tensors


def _cloned_data_caps(t, decoded):
    """Map the template's slot caps onto the decoded clone's args
    (valid only when no call was removed)."""
    tmpl_args, clone_args = [], []
    for c in t.template.calls:
        foreach_arg(c, lambda a, ctx: tmpl_args.append(a))
    for c in decoded.calls:
        foreach_arg(c, lambda a, ctx: clone_args.append(a))
    amap = {id(a): b for a, b in zip(tmpl_args, clone_args)}
    return {id(amap[id(t.slot_args[s])]): int(t.cap[s])
            for s in range(len(t.slot_args)) if t.kind[s] == DATA}


def test_template_assembly_identity(test_target):
    """With unmutated rows, assembly reproduces the template stream."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    for t in _encode_some(test_target, 10, cfg, flags):
        et = build_exec_template(t)
        got = assemble(et, t.val, t.len_, t.arena, t.call_alive)
        caps = {id(t.slot_args[s]): int(t.cap[s])
                for s in range(len(t.slot_args)) if t.kind[s] == DATA}
        want = serialize_for_exec(t.template, data_caps=caps)
        assert got == want


def test_assembly_matches_typed_serialization(test_target, iters):
    """The oracle: assembled bytes == typed serialization of the
    decoded mutant, for every device mutation that keeps all calls."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    tensors = _encode_some(test_target, 8, cfg, flags)
    mutate = make_mutator(rounds=4)
    key = random.key(7)
    checked = 0
    for it in range(iters * 4):
        t = tensors[it % len(tensors)]
        et = build_exec_template(t)
        batch = {k: jnp.asarray(v)[None] for k, v in t.arrays().items()}
        key, sub = random.split(key)
        mut = mutate(batch, sub, jnp.asarray(flags.vals),
                     jnp.asarray(flags.counts))
        row = {k: np.asarray(v[0]) for k, v in mut.items()}
        alive = row["call_alive"]
        if not alive[:t.ncalls].all():
            continue  # removal covered by test_assembly_call_removal
        got = assemble(et, row["val"], row["len_"], row["arena"], alive)
        decoded = decode_prog(t, row, preserve_sizes=True)
        caps = _cloned_data_caps(t, decoded)
        want = serialize_for_exec(decoded, data_caps=caps)
        assert got == want, f"stream mismatch on iteration {it}"
        checked += 1
    assert checked >= iters  # the oracle actually ran


def test_assembly_call_removal(test_target):
    """Killing calls slices exactly their segments out."""
    cfg = TensorConfig()
    flags = FlagTables.empty()
    rng = np.random.RandomState(3)
    for t in _encode_some(test_target, 10, cfg, flags, seed0=300):
        if t.ncalls < 2:
            continue
        et = build_exec_template(t)
        alive = t.call_alive.copy()
        kill = rng.randint(0, t.ncalls)
        alive[kill] = False
        stream = assemble(et, t.val, t.len_, t.arena, alive)
        got_ids = parse_stream(stream)
        want_ids = [t.template.calls[i].meta.id
                    for i in mutant_call_ids(et, alive)]
        assert got_ids == want_ids


def test_parse_stream_rejects_garbage():
    with pytest.raises(ValueError):
        parse_stream(b"\x07\x00\x00\x00\x00\x00\x00\x00" * 3)


def test_assemble_batch_matches_assemble_delta(test_target):
    """The vectorized group assembler is bit-identical to the
    per-mutant delta assembler over a full device batch."""
    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW, DeltaBatch
    from syzkaller_tpu.ops.emit import assemble_batch, assemble_delta
    from syzkaller_tpu.ops.pipeline import DevicePipeline

    pl = DevicePipeline(test_target, capacity=32, batch_size=64, seed=11)
    added, i = 0, 0
    while added < 10 and i < 60:
        p = generate_prog(test_target, RandGen(test_target, 4000 + i), 6)
        i += 1
        if pl.add(p):
            added += 1
    assert added >= 5
    rows_dev, tmpl, ets = pl._launch()
    buf = np.asarray(rows_dev)
    batch = DeltaBatch(buf, pl.spec)
    ok = (batch.flags & FLAG_OVERFLOW) == 0
    ok &= (batch.template_idx >= 0) & (batch.template_idx < len(tmpl))
    js = np.flatnonzero(ok)
    assert js.size >= 32
    datas = assemble_batch(ets, batch, js)
    for j, got in zip(js, datas):
        et = ets[int(batch.template_idx[j])]
        want = assemble_delta(et, batch, int(j))
        assert got == want, f"mutant {j} diverged from the delta oracle"
