"""Partition-tolerant hub federation tests (ISSUE 16).

Covers the session plane (replay, stale-epoch/lease verdicts, custody
rollback), the plane-indexed novelty diff (counter-asserted byte
reduction), cold-open edge cases (torn db tail, stale manager dirs,
ParseError quarantine), warm leader failover over the durable store,
annex-safe transport regressions, the byte-bounded reply cache, and
the scripted SIGKILL-mid-Sync + same-port-restart chaos drill.

All tests are host-only: direct receiver calls where the wire adds
nothing, raw sockets where the wire IS the subject, and one
subprocess drill (slow-marked, like the manager's) where process
death is the subject.
"""

import collections
import os
import signal as _signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from syzkaller_tpu.durable.store import DurableStore
from syzkaller_tpu.hub.hub import Hub, serve_hub
from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.ops.signal import digest_from_folds, fold_hash_np
from syzkaller_tpu.rpc import RPCClient
from syzkaller_tpu.rpc.replycache import ReplyCache, approx_size
from syzkaller_tpu.rpc.rpc import (ReconnectRequired, _recv_frame,
                                   _send_frame)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- reply cache (S2) ----------------------------------------------------


def test_reply_cache_byte_bound():
    """The cache evicts oldest-seq once the byte bound is crossed,
    counts the freed bytes, and never evicts the newest entry."""
    # each ({"progs": []}, 50B blob) entry approx_sizes to 67 bytes:
    # two fit under 150, the third forces an oldest-first eviction
    cache = ReplyCache(entries=100, max_mb=150.0 / (1 << 20))
    blob = b"x" * 50
    cache.put(1, ({"progs": []}, blob))
    cache.put(2, ({"progs": []}, blob))
    assert len(cache) == 2
    cache.put(3, ({"progs": []}, blob))
    assert cache.get(1) is None
    assert cache.get(2) is not None and cache.get(3) is not None
    assert cache.evicted_bytes >= approx_size(({"progs": []}, blob))
    # the just-cached reply survives even when it alone busts the cap:
    # dropping it would break at-most-once for its in-flight retry
    cache.put(4, ({"progs": []}, b"y" * 4096))
    assert cache.get(4) is not None
    assert cache.get(2) is None and cache.get(3) is None
    snap = cache.snapshot()
    assert snap["entries"] == 1 and snap["evicted_bytes"] > 0


def test_reply_cache_entry_bound_still_holds():
    cache = ReplyCache(entries=3, max_mb=64.0)
    for seq in range(1, 6):
        cache.put(seq, {"seq": seq})
    assert sorted(cache) == [3, 4, 5]
    assert cache == {3: {"seq": 3}, 4: {"seq": 4}, 5: {"seq": 5}}


# -- sessioned sync: replay + verdicts -----------------------------------


def _ident(name):
    # empty client -> canonical name is just the manager name
    return {"client": "", "key": "", "manager": name}


def _connect(hub, name, corpus=(), sigs=None, fresh=True):
    return hub.Connect({**_ident(name), "session": True, "fresh": fresh,
                        "corpus": list(corpus), "corpus_sigs": sigs})


def test_sessioned_sync_replays_from_cache(tmp_path):
    """A duplicate (epoch, seq) Sync replays the cached (reply, annex)
    byte-for-byte and re-applies nothing."""
    hub = Hub(HubState(str(tmp_path / "hub"), lease_s=3600.0))
    _connect(hub, "mA", ["a1()", "a2()"])
    res = _connect(hub, "mB", [])
    epoch = res["epoch"]
    params = {**_ident("mB"), "epoch": epoch, "seq": 1, "ack_seq": 0,
              "add": ["b1()"], "add_sigs": [], "delete": [],
              "repros": [], "need_repros": True}
    reply1, annex1 = hub.Sync(dict(params))
    assert [bytes(memoryview(annex1)[o:o + ln]).decode()
            for o, ln in reply1["progs"]] == ["a1()", "a2()"]
    seq_after = hub.state.next_seq
    reply2, annex2 = hub.Sync(dict(params))  # the retry
    assert reply2 == reply1 and annex2 == annex1
    assert hub.state.next_seq == seq_after  # b1() not re-added
    assert hub.state.replays_total == 1


def test_stale_epoch_and_reaped_lease_verdicts(tmp_path):
    clock = [1000.0]
    st = HubState(str(tmp_path / "hub"), lease_s=5.0,
                  clock=lambda: clock[0])
    hub = Hub(st)
    epoch = _connect(hub, "mA", ["a()"])["epoch"]
    with pytest.raises(ReconnectRequired, match="stale"):
        hub.Sync({**_ident("mA"), "epoch": "deadbeef", "seq": 1,
                  "ack_seq": 0})
    clock[0] += 60.0  # idle past the lease
    with pytest.raises(ReconnectRequired, match="expired"):
        hub.Sync({**_ident("mA"), "epoch": epoch, "seq": 1,
                  "ack_seq": 0})
    assert st.reaped_total == 1
    # the ManagerState survived the reap — only the session died
    assert "mA" in st.managers and not st.managers["mA"].connected
    # re-Connect re-uploads the same corpus: zero duplicate adds
    seq_before = st.next_seq
    _connect(hub, "mA", ["a()"], fresh=False)
    assert st.next_seq == seq_before


def test_custody_rollback_redelivers_exactly(tmp_path):
    """An un-acked sync reply rolls the cursor back to the batch start
    and requeues its repros; an acked one retires.  Redelivery is by
    re-scan, so nothing is lost and nothing double-delivered."""
    st = HubState(str(tmp_path / "hub"), lease_s=3600.0)
    st.connect("mA", True, [b"a1()", b"a2()"])
    st.connect("mB", True, [])
    st.sync("mA", [], [], [b"crash()"], False)
    progs, repros, _ = st.sync("mB", [], [], [], True, rseq=1,
                               ack_seq=0)
    assert sorted(progs) == [b"a1()", b"a2()"]
    assert repros == [b"crash()"]
    # seq 2 abandoned seq 1 (ack still 0): same batch redelivered
    progs2, repros2, _ = st.sync("mB", [], [], [], True, rseq=3,
                                 ack_seq=0)
    assert sorted(progs2) == [b"a1()", b"a2()"]
    assert repros2 == [b"crash()"]
    # acking seq 3 retires it: nothing left to deliver
    progs3, repros3, _ = st.sync("mB", [], [], [], True, rseq=4,
                                 ack_seq=3)
    assert progs3 == [] and repros3 == []
    assert st.managers["mB"].last_seq == st.next_seq - 1


def test_breaker_throttles_single_manager(tmp_path):
    """An open breaker degrades one manager to backoff-hint replies;
    the rest of the pod keeps syncing."""
    hub = Hub(HubState(str(tmp_path / "hub"), lease_s=3600.0))
    _connect(hub, "mA", ["a()"])
    epoch = _connect(hub, "mB", [])["epoch"]
    for _ in range(4):
        hub.state.record_sync_result("mB", ok=False)
    assert hub.state.managers["mB"].breaker.state == "open"
    reply, annex = hub.Sync({**_ident("mB"), "epoch": epoch, "seq": 1,
                             "ack_seq": 0})
    assert reply["throttled"] and reply["backoff_s"] > 0
    assert reply["progs"] == [] and annex is None
    # the throttle reply is cached too: its retry replays
    reply2, _ = hub.Sync({**_ident("mB"), "epoch": epoch, "seq": 1,
                          "ack_seq": 0})
    assert reply2 == reply
    # mA is unaffected
    epoch_a = hub.state.epoch
    replyA, _ = hub.Sync({**_ident("mA"), "epoch": epoch_a, "seq": 1,
                          "ack_seq": 0})
    assert "throttled" not in replyA


# -- plane-indexed novelty diffs -----------------------------------------


def test_digest_diff_reduces_reply_bytes(tmp_path):
    """Counter-asserted: a sync presenting a digest that covers mA's
    signal receives fewer bytes, tz_hub_sync_saved_bytes_total grows
    by exactly the withheld payload, and a program with no stored
    folds always ships."""
    from syzkaller_tpu.hub import state as hub_state

    st = HubState(str(tmp_path / "hub"), lease_s=3600.0)
    known_sig = [11, 22, 33]
    st.connect("mA", True, [b"known_prog()", b"unsigned_prog()"],
               sigs=[known_sig, None])
    st.connect("mB", True, [])
    folds = fold_hash_np(np.asarray(known_sig, dtype=np.int64)
                         .astype(np.uint32))
    digest = digest_from_folds(folds, st.digest_bits)
    before = hub_state._M_SAVED_BYTES.value
    progs, _, _ = st.sync("mB", [], [], [], False, digest=digest)
    # known_prog withheld (digest covers its folds); unsigned_prog has
    # no stored folds -> never withheld
    assert progs == [b"unsigned_prog()"]
    assert st.digest_skipped_total == 1
    assert st.sync_saved_bytes == len(b"known_prog()")
    assert hub_state._M_SAVED_BYTES.value - before \
        == len(b"known_prog()")
    # the withheld program's seq was consumed: no redelivery later
    progs2, _, _ = st.sync("mB", [], [], [], False)
    assert progs2 == []


def test_digest_without_coverage_ships_everything(tmp_path):
    st = HubState(str(tmp_path / "hub"), lease_s=3600.0)
    st.connect("mA", True, [b"p()"], sigs=[[77]])
    st.connect("mB", True, [])
    empty = digest_from_folds(np.empty(0, np.int64), st.digest_bits)
    progs, _, _ = st.sync("mB", [], [], [], False, digest=empty)
    assert progs == [b"p()"]


# -- cold-open edge cases (S4) -------------------------------------------


def test_cold_open_torn_corpus_tail(tmp_path):
    wd = str(tmp_path / "hub")
    st = HubState(wd)
    st.connect("mA", True, [b"a1()", b"a2()"])
    next_seq = st.next_seq
    with open(os.path.join(wd, "corpus.db"), "ab") as f:
        f.write(b"\x13torn-half-record\xff")
    st2 = HubState(wd)
    assert len(st2.corpus_db.records) == 2
    assert st2.next_seq == next_seq
    # new adds still get fresh, unique seqs
    st2.connect("mB", True, [b"b1()"])
    seqs = [rec.seq for rec in st2.corpus_db.records.values()]
    assert len(set(seqs)) == 3


def test_cold_open_stale_manager_dirs(tmp_path):
    wd = str(tmp_path / "hub")
    os.makedirs(wd, exist_ok=True)
    # a manager dir with cursor files but no own corpus.db: the cursor
    # survives, ownership rebuilds on re-upload
    ghost = os.path.join(wd, "manager-" + "0" * 16)
    os.makedirs(ghost)
    open(os.path.join(ghost, "name"), "w").write("ghost")
    open(os.path.join(ghost, "seq"), "w").write("7")
    # a torn dir (no name) and a garbled seq: both skipped, not fatal
    torn = os.path.join(wd, "manager-" + "1" * 16)
    os.makedirs(torn)
    bad = os.path.join(wd, "manager-" + "2" * 16)
    os.makedirs(bad)
    open(os.path.join(bad, "name"), "w").write("bad")
    open(os.path.join(bad, "seq"), "w").write("not-a-number")
    st = HubState(wd)
    assert st.managers["ghost"].last_seq == 7
    assert st.managers["ghost"].own_hashes == set()
    assert "bad" not in st.managers and len(st.managers) == 1


def test_parse_errors_counted_and_skipped(tmp_path, test_target):
    """A corrupt upload is counted and refused; the seq index never
    advances for it, so other managers' cursors are not poisoned."""
    from syzkaller_tpu.models.encoding import serialize_prog
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    text = serialize_prog(
        generate_prog(test_target, RandGen(test_target, 5), 3))
    st = HubState(str(tmp_path / "hub"), target=test_target)
    st.connect("mA", True, [text, b"garbage(((", b"nope)"])
    assert st.rejected_total == 2
    assert len(st.corpus_db.records) == 1
    assert st.next_seq == 2
    st.connect("mB", True, [])
    progs, _, _ = st.sync("mB", [], [], [], False)
    assert progs == [text]


# -- warm leader failover (in-process) -----------------------------------


def test_warm_failover_redelivers_unacked_only(tmp_path):
    """Kill the hub (by abandoning it un-closed) with one acked and
    one un-acked sync batch outstanding: the successor redelivers
    exactly the un-acked batch, with zero duplicate corpus adds."""
    wd, dd = str(tmp_path / "hub"), str(tmp_path / "dur")
    store = DurableStore(dd, interval_s=3600.0)
    st = HubState(wd, durable=store)
    st.connect("mA", True, [b"a1()", b"a2()"])
    st.connect("mB", True, [])
    st.sync("mA", [], [], [b"crash()"], False)
    # batch 1: delivered AND acked (by batch 2's ack_seq)
    progs, _, _ = st.sync("mB", [], [], [], False, rseq=1, ack_seq=0)
    assert sorted(progs) == [b"a1()", b"a2()"]
    st.sync("mA", [b"a3()"], [], [], False)
    # batch 2: delivered, never acked — dies with the leader
    progs2, repros2, _ = st.sync("mB", [], [], [], True, rseq=2,
                                 ack_seq=1)
    assert progs2 == [b"a3()"] and repros2 == [b"crash()"]
    next_seq = st.next_seq
    acked_cursor = 2  # a1,a2 confirmed by ack_seq=1

    # SIGKILL-equivalent: no close(), no final checkpoint — the WAL is
    # the only survivor.  The successor opens the same dirs.
    store2 = DurableStore(dd, interval_s=3600.0)
    assert store2.recovered is not None and "hub" in store2.recovered
    st2 = HubState(wd, durable=store2)
    assert st2.last_failover_ts > 0
    assert st2.next_seq == next_seq  # zero lost, zero re-added
    # cursor monotonic vs acked progress, rolled back past un-acked
    assert acked_cursor <= st2.managers["mB"].last_seq < next_seq - 1
    # the successor redelivers exactly batch 2 (session re-mint means
    # the manager re-Connects first, as it would through RPC)
    st2.connect("mB", False, [])
    progs3, repros3, _ = st2.sync("mB", [], [], [], True, rseq=1,
                                  ack_seq=0)
    assert progs3 == [b"a3()"] and repros3 == [b"crash()"]
    store2.close(final_checkpoint=False)


# -- annex-safe transport (S1) -------------------------------------------


class _Boom:
    def Ok(self, params):
        return {"ok": params.get("n")}

    def Boom(self, params):
        raise ValueError("handler exploded")


def test_server_drains_request_annex_on_handler_error(tmp_path):
    """A request carrying an annex to a raising handler must not
    desync the connection: the error reply arrives and the NEXT frame
    on the same socket parses cleanly."""
    from syzkaller_tpu.rpc import RPCServer

    srv = RPCServer(("127.0.0.1", 0))
    srv.register("T", _Boom())
    srv.serve_in_background()
    try:
        sock = socket.create_connection(srv.addr, timeout=10)
        try:
            _send_frame(sock, {"id": 1, "method": "T.Boom",
                               "params": {}}, annex=b"A" * 4096)
            resp = _recv_frame(sock)
            assert "handler exploded" in resp["error"]
            _send_frame(sock, {"id": 2, "method": "T.Ok",
                               "params": {"n": 7}}, annex=b"B" * 512)
            resp2 = _recv_frame(sock)
            assert resp2["result"] == {"ok": 7}
        finally:
            sock.close()
    finally:
        srv.close()


def test_client_socket_survives_garbled_compressed_reply():
    """A reply whose zlib payload is garbled (but whose annex length
    is honest) must leave the pooled client socket at an exact frame
    boundary: the decode error propagates, the next call succeeds."""
    import zlib

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    addr = lst.getsockname()
    _FRAME = struct.Struct("<IB")
    _ANNEX = struct.Struct("<Q")

    def serve():
        conn, _ = lst.accept()
        with conn:
            # request 1 -> garbled-zlib reply with a real annex tail
            _recv_frame(conn)
            bad = b"this is not zlib data"
            conn.sendall(_FRAME.pack(len(bad), 1 | 4)
                         + _ANNEX.pack(8) + bad + b"ANNEXTAIL"[:8])
            # request 2 -> honest reply
            req = _recv_frame(conn)
            _send_frame(conn, {"id": req["id"], "result": {"ok": 1}},
                        annex=b"payload")

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    client = RPCClient(addr, timeout_s=10.0)
    try:
        with pytest.raises(zlib.error):
            client.call("X.Y", {})
        res, annex = client.call("X.Z", {}, want_annex=True)
        assert res == {"ok": 1} and bytes(annex) == b"payload"
    finally:
        client.close()
        lst.close()
    t.join(timeout=10)


# -- the SIGKILL-mid-Sync + same-port-restart chaos drill ----------------

_HUB_CHILD = r"""
import sys, time
from syzkaller_tpu.hub.hub import serve_hub
workdir, port = sys.argv[1], int(sys.argv[2])
srv, hub = serve_hub(workdir, ("127.0.0.1", port))
print("READY", flush=True)
while True:
    time.sleep(0.5)
"""


def _spawn_hub(workdir, port, fault_plan=""):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    if fault_plan:
        env["TZ_FAULT_PLAN"] = fault_plan
    else:
        env.pop("TZ_FAULT_PLAN", None)
    child = subprocess.Popen(
        [sys.executable, "-c", _HUB_CHILD, workdir, str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    line = child.stdout.readline()
    if b"READY" not in line:
        err = child.stderr.read().decode()[-2000:]
        child.kill()
        raise AssertionError(f"hub child failed to start: {err}")
    return child


class _DrillMgr:
    """A session manager as the drill drives it: corpus upload at
    (re-)Connect, incremental adds, annex-decoded receives."""

    def __init__(self, name, addr):
        self.name = name
        self.progs: list[str] = [f"{name}_p{i}()" for i in range(2)]
        self.received = collections.Counter()
        self.client = RPCClient(addr, name=name, timeout_s=30.0,
                                retries=12, backoff_s=0.3)
        self.reconnects = 0

    def _ident(self):
        return {"client": "", "key": "", "manager": self.name}

    def connect(self):
        res = self.client.call_transient("Hub.Connect", {
            **self._ident(), "session": True, "fresh": False,
            "corpus": list(self.progs),
            "corpus_sigs": [[] for _ in self.progs]}) or {}
        self.client.set_session(res["epoch"],
                                on_reconnect=self._reconnect)

    def _reconnect(self):
        self.reconnects += 1
        self.connect()

    def sync(self, add=()):
        self.progs.extend(add)
        res, annex = self.client.call_session("Hub.Sync", {
            **self._ident(), "add": list(add),
            "add_sigs": [[] for _ in add], "delete": [],
            "repros": [], "need_repros": True}, want_annex=True)
        view = memoryview(annex or b"")
        for off, ln in res.get("progs") or []:
            self.received[bytes(view[off:off + ln]).decode()] += 1
        return res

    def stats(self):
        return self.client.call_transient("Hub.Stats", self._ident())


@pytest.mark.slow
def test_hub_sigkill_chaos_drill(tmp_path):
    """SIGKILL the hub while a Sync is executing (a scripted hang
    holds it mid-call), restart a successor behind the SAME port, and
    let 3 live session managers ride their retry/reconnect paths
    through the failover.  Pins: zero lost programs, zero
    double-counted corpus adds, per-manager cursors monotonic vs
    acked progress across generations."""
    wd = str(tmp_path / "hub")
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    port = lst.getsockname()[1]
    lst.close()
    # syncs run A,B,C per round; occurrence 7 = A's round-3 sync
    gen1 = _spawn_hub(wd, port, fault_plan="hub.sync:hang@7")
    gen2_box = {}
    mgrs = [_DrillMgr(n, ("127.0.0.1", port))
            for n in ("mA", "mB", "mC")]
    try:
        for m in mgrs:
            m.connect()
        for rnd in (1, 2):
            for m in mgrs:
                m.sync(add=[f"{m.name}_r{rnd}()"])
        seqs_g1 = {n: s["seq"] for n, s in
                   mgrs[0].stats()["managers"].items()}

        def kill_and_restart():
            time.sleep(1.0)  # let A's sync reach the scripted hang
            os.kill(gen1.pid, _signal.SIGKILL)
            gen1.wait(timeout=30)
            gen2_box["child"] = _spawn_hub(wd, port)

        killer = threading.Thread(target=kill_and_restart)
        killer.start()
        # This sync hangs in gen-1, dies with it, retries against the
        # refused port, then hits gen-2's fresh epoch: the
        # ReconnectRequired verdict drives the re-Connect resync.
        for m in mgrs:
            m.sync()
        killer.join(timeout=120)
        assert "child" in gen2_box, "hub successor never started"
        assert any(m.reconnects for m in mgrs)
        # converge: everyone drains everything
        for _ in range(3):
            for m in mgrs:
                m.sync()

        expected = {p for m in mgrs for p in m.progs}
        assert len(expected) == 12  # 3 managers x (2 connect + 2 adds)
        stats = mgrs[0].stats()
        # zero lost, zero double-counted: every program exactly one
        # corpus entry / one seq, despite re-uploads and redelivery
        assert stats["corpus"] == len(expected)
        assert stats["next_seq"] == len(expected) + 1
        for m in mgrs:
            others = {p for o in mgrs if o is not m for p in o.progs}
            assert set(m.received) == others, m.name
        # cursors: monotonic vs gen-1 acked progress, fully converged
        for name, s in stats["managers"].items():
            assert s["seq"] == stats["next_seq"] - 1
            assert s["seq"] >= seqs_g1[name] - 3  # rollback bounded
    finally:
        for proc in (gen1, gen2_box.get("child")):
            if proc is None:
                continue
            try:
                proc.kill()
            except OSError:
                pass
            proc.wait(timeout=30)
            proc.stdout.close()
            proc.stderr.close()


# -- serve_hub wiring ----------------------------------------------------


def test_serve_hub_registers_gauges_and_durable(tmp_path):
    from syzkaller_tpu import telemetry

    srv, hub = serve_hub(str(tmp_path / "hub"))
    try:
        assert hub.state.durable is not None
        _connect(hub, "mA", ["a()"])
        snap = telemetry.REGISTRY.snapshot()
        assert snap["gauges"]["tz_hub_managers_size"] == 1
        assert snap["gauges"]["tz_hub_corpus_size"] == 1
        assert snap["gauges"]["tz_hub_pending_repros_depth"] == 0
    finally:
        srv.close()
        hub.state.durable.close()
