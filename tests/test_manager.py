"""Manager orchestration tests: RPC service, persistence, crashes,
and a full manager⇄fuzzer⇄executor end-to-end loop."""

import os
import time

import pytest

from syzkaller_tpu.manager.manager import (Manager, PHASE_TRIAGED_CORPUS)
from syzkaller_tpu.manager.mgrconfig import load_config
from syzkaller_tpu.manager.rpcserver import ManagerRPC
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.report import Report
from syzkaller_tpu.rpc.types import RPCCandidate


def _input_dict(prog_text, elems, prio=3, call="c"):
    return {"call": call, "prog": prog_text,
            "signal": [elems, [prio] * len(elems)], "cover": []}


# -- ManagerRPC unit tests ----------------------------------------------


def test_rpc_new_input_dedup_and_broadcast():
    serv = ManagerRPC()
    serv.Connect({"name": "f1"})
    serv.Connect({"name": "f2"})
    r1 = serv.NewInput({"name": "f1",
                        "input": _input_dict("text1()", [1, 2, 3])})
    assert r1["accepted"]
    # same signal again: rejected
    r2 = serv.NewInput({"name": "f2",
                        "input": _input_dict("text2()", [1, 2, 3])})
    assert not r2["accepted"]
    # f2 should receive text1 via poll
    res = serv.Poll({"name": "f2", "stats": {}, "max_signal": [[], []]})
    assert [i["prog"] for i in res["new_inputs"]] == ["text1()"]
    # f1 must NOT get its own input back
    res1 = serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []]})
    assert res1["new_inputs"] == []


def test_rpc_higher_prio_signal_accepted():
    serv = ManagerRPC()
    serv.Connect({"name": "f1"})
    serv.NewInput({"name": "f1",
                   "input": _input_dict("p()", [7], prio=1)})
    r = serv.NewInput({"name": "f1",
                       "input": _input_dict("p()", [7], prio=3)})
    assert r["accepted"]  # higher prio on the same edge is novel


def test_rpc_candidates_queued_once_shuffled():
    # Queued 1x: loss recovery is lease-tracked reissue now, not the
    # reference's blind 2x duplication.
    serv = ManagerRPC()
    serv.add_candidates([RPCCandidate(prog=f"p{i}()") for i in range(10)])
    assert serv.candidate_backlog() == 10
    res = serv.Poll({"name": "f", "need_candidates": True,
                     "stats": {}, "max_signal": [[], []]})
    assert len(res["candidates"]) == 10
    assert sorted(c["prog"] for c in res["candidates"]) == \
        sorted(f"p{i}()" for i in range(10))
    assert serv.candidate_backlog() == 0


def test_rpc_max_signal_distribution():
    serv = ManagerRPC()
    serv.Connect({"name": "f1"})
    serv.Connect({"name": "f2"})
    serv.Poll({"name": "f1", "stats": {}, "max_signal": [[11, 12], [3, 3]]})
    res = serv.Poll({"name": "f2", "stats": {}, "max_signal": [[], []]})
    assert sorted(res["max_signal"][0]) == [11, 12]
    # and not echoed back to f1
    res1 = serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []]})
    assert res1["max_signal"][0] == []


def test_rpc_poll_telemetry_fleet_merge():
    """ISSUE 4 satellite (ROADMAP PR 2 leftover): fuzzer poll
    telemetry snapshots merge into one fleet rollup — counters sum,
    histograms vector-add over the fixed shared buckets, latest
    snapshot per fuzzer wins."""
    from syzkaller_tpu.telemetry import Registry

    serv = ManagerRPC()
    snaps = []
    for execs, lat in ((5, 0.01), (7, 0.04)):
        reg = Registry()
        reg.counter("tz_pipeline_mutants_total").inc(execs)
        h = reg.histogram("tz_proc_exec_seconds")
        for _ in range(execs):
            h.observe(lat)
        s = reg.snapshot()
        snaps.append({"counters": s["counters"], "gauges": s["gauges"],
                      "histograms": s["histograms"]})
    serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []],
               "telemetry": snaps[0]})
    serv.Poll({"name": "f2", "stats": {}, "max_signal": [[], []],
               "telemetry": snaps[1]})
    fleet = serv.fleet_telemetry()
    assert fleet["sources"] == 2
    assert fleet["counters"]["tz_pipeline_mutants_total"] == 12
    merged = fleet["histograms"]["tz_proc_exec_seconds"]
    assert merged["count"] == 12
    assert merged["min"] == pytest.approx(0.01)
    assert merged["max"] == pytest.approx(0.04)
    # latest-wins: f1 polls again with a fresher cumulative snapshot
    snaps[0]["counters"]["tz_pipeline_mutants_total"] = 6
    serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []],
               "telemetry": snaps[0]})
    assert serv.fleet_telemetry()["counters"][
        "tz_pipeline_mutants_total"] == 13
    # a poll without telemetry keeps the last snapshot (no regression)
    serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []]})
    assert serv.fleet_telemetry()["sources"] == 2
    # ISSUE 14 monotonicity: f1 restarts (counters back near zero)
    # with NO fleet read between the last pre-crash poll and the
    # first post-crash one — the reset must be absorbed at poll time,
    # so the fleet sees retired life + new life (6 + 1 + 7), never a
    # negative delta.
    snaps[0]["counters"]["tz_pipeline_mutants_total"] = 1
    serv.Poll({"name": "f1", "stats": {}, "max_signal": [[], []],
               "telemetry": snaps[0]})
    assert serv.fleet_telemetry()["counters"][
        "tz_pipeline_mutants_total"] == 14


# -- Manager daemon -----------------------------------------------------


@pytest.fixture
def mgr(tmp_path):
    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": "",
                       "reproduce": False})
    m = Manager(cfg)
    yield m
    m.shutdown()


def test_manager_corpus_persistence(tmp_path, test_target):
    # Warm restart (ISSUE 13): the durable checkpoint restores the
    # corpus WITH its triaged signal, so nothing is re-queued for
    # re-triage — the record is immediately servable to a fuzzer.
    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": ""})
    m = Manager(cfg)
    p = generate_prog(test_target, RandGen(test_target, 1), 4)
    text = serialize_prog(p).decode()
    m.serv.NewInput({"name": "f",
                     "input": _input_dict(text, [5, 6], call="x")})
    m.shutdown()
    # Shutdown must detach the journal hook it installed on the
    # process-global coverage tracker: the tracker outlives the
    # manager, and a later analytics tick journaling into the closed
    # WAL would poison unrelated rigs in the same process.
    from syzkaller_tpu import telemetry

    assert telemetry.COVERAGE.journal is None
    m2 = Manager(cfg)
    assert m2.serv.candidate_backlog() == 0
    assert [i["prog"] for i in m2.serv.corpus.values()] == [text]
    # a fresh fuzzer is served the restored corpus on Connect
    conn = m2.serv.Connect({"name": "g"})
    assert [i["prog"] for i in conn["corpus"]] == [text]
    m2.shutdown()


def test_manager_corpus_persistence_cold(tmp_path, test_target,
                                         monkeypatch):
    # TZ_CKPT_INTERVAL_S=0 is the durability escape hatch: no durable
    # store, and a restart falls back to the cold path — the corpus DB
    # is re-queued as candidates for full re-triage (the seed's
    # original semantics, reference: syz-manager loadCorpus).
    monkeypatch.setenv("TZ_CKPT_INTERVAL_S", "0")
    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": ""})
    m = Manager(cfg)
    assert m.durable is None
    p = generate_prog(test_target, RandGen(test_target, 1), 4)
    text = serialize_prog(p).decode()
    m.serv.NewInput({"name": "f",
                     "input": _input_dict(text, [5, 6], call="x")})
    m.shutdown()
    m2 = Manager(cfg)
    assert m2.serv.candidate_backlog() == 1
    assert m2.serv.candidates[0]["prog"] == text
    m2.shutdown()


def test_manager_drops_broken_corpus(tmp_path):
    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": ""})
    m = Manager(cfg)
    m.corpus_db.save("bad", b"not_a_call(1, 2)", 0)
    m.corpus_db.flush()
    m.shutdown()
    m2 = Manager(cfg)
    assert "bad" not in m2.corpus_db.records
    m2.shutdown()


def test_manager_crash_dedup(mgr):
    rep = Report(title="KASAN: use-after-free in foo",
                 output=b"log1", report=b"rep1")
    c1 = mgr.save_crash(rep)
    assert c1.first
    c2 = mgr.save_crash(Report(title="KASAN: use-after-free in foo",
                               output=b"log2", report=b"rep2"))
    assert not c2.first
    sig_dirs = os.listdir(mgr.crashdir)
    assert len(sig_dirs) == 1
    files = os.listdir(os.path.join(mgr.crashdir, sig_dirs[0]))
    assert "description" in files
    assert "log0" in files and "log1" in files


def test_manager_need_repro_policy(tmp_path):
    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": "",
                       "reproduce": True})
    m = Manager(cfg)
    c = m.save_crash(Report(title="BUG: nice crash", output=b"x",
                            report=b"y"))
    assert m.need_repro(c)
    assert not m.need_repro(c)  # only one attempt per title
    c2 = m.save_crash(Report(title="no output from test machine",
                             output=b"", report=b""))
    assert not m.need_repro(c2)  # synthetic titles are not reproduced
    c3 = m.save_crash(Report(title="BUG: cut", output=b"", report=b"",
                             corrupted=True))
    assert not m.need_repro(c3)
    m.shutdown()


def test_manager_minimize_corpus(mgr):
    # a's signal is a subset of b's → a gets dropped
    mgr.serv.NewInput({"name": "f", "input": _input_dict("a()", [1, 2])})
    mgr.serv.NewInput({"name": "f",
                       "input": _input_dict("b()", [1, 2, 3, 4])})
    mgr.minimize_corpus()
    progs = [i["prog"] for i in mgr.serv.corpus.values()]
    assert progs == ["b()"]
    # dropped record is gone from the DB too
    from syzkaller_tpu.utils.hashsig import hash_string

    assert hash_string(b"a()") not in mgr.corpus_db.records


def test_manager_phase_machine(mgr):
    mgr.update_phase()  # no candidates pending → triaged
    assert mgr.phase >= PHASE_TRIAGED_CORPUS


def test_manager_stats_and_bench(mgr, tmp_path):
    mgr.serv.Poll({"name": "f", "stats": {"exec total": 42},
                   "max_signal": [[], []]})
    snap = mgr.stats_snapshot()
    assert snap["stats"]["exec total"] == 42
    bench_path = str(tmp_path / "bench.json")
    mgr.start_bench(bench_path, period_s=0.1)
    time.sleep(0.35)
    mgr.stop_ev.set()
    time.sleep(0.15)
    lines = [l for l in open(bench_path).read().splitlines() if l]
    assert len(lines) >= 2
    import json

    rec = json.loads(lines[0])
    assert "corpus" in rec and "ts" in rec


# -- end-to-end: manager + fuzzer over real RPC + real executor ---------


def test_end_to_end_manager_fuzzer(tmp_path):
    from syzkaller_tpu.fuzzer.main import FuzzerProcess

    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": ""})
    m = Manager(cfg)
    fp = FuzzerProcess("fuzzer-0", ("test", "64"),
                       manager_addr=m.rpc_addr, procs=1)
    try:
        # run the proc loop inline for a bounded number of iterations
        fp.procs[0].loop(300, stop=fp.stop)
        fp.poll_once()
        snap = m.serv.snapshot()
        assert snap["stats"].get("exec total", 0) > 0
        # the fuzzer must have triaged at least one input into the
        # manager corpus via NewInput
        assert snap["corpus"] > 0
        assert snap["signal"] > 0
        # a second fuzzer connecting receives the corpus
        res = m.serv.Connect({"name": "fuzzer-1"})
        assert len(res["corpus"]) == snap["corpus"]
    finally:
        fp.shutdown()
        m.shutdown()


# -- HTTP UI ------------------------------------------------------------


def test_http_ui_endpoints(tmp_path, test_target):
    """Every reference UI endpoint (html.go:30-41 analogue) serves a
    sane page against a live manager with corpus + crash state."""
    import json as json_mod
    import urllib.request

    from syzkaller_tpu.manager.html import serve_http
    from syzkaller_tpu.report.report import Report
    from syzkaller_tpu.utils.hashsig import hash_string

    cfg = load_config({"workdir": str(tmp_path / "work"),
                       "target": "test/64", "http": "",
                       "reproduce": False})
    m = Manager(cfg)
    try:
        p = generate_prog(test_target, RandGen(test_target, 3), 4)
        text = serialize_prog(p).decode()
        first_call = p.calls[0].meta.name
        m.serv.NewInput({"name": "f",
                         "input": _input_dict(text, [7, 8], call="c")})
        rep = Report(title="KASAN: use-after-free in tz_write",
                     report=b"KASAN: use-after-free in tz_write\n...",
                     output=b"console log tail\n")
        m.save_crash(rep)
        srv = serve_http(m, ("127.0.0.1", 0))
        try:
            host, port = srv.server_address[:2]

            def get(path):
                with urllib.request.urlopen(
                        f"http://{host}:{port}{path}", timeout=10) as r:
                    return r.read().decode()

            summary = get("/")
            assert "Crashes" in summary and "use-after-free" in summary
            stats = json_mod.loads(get("/stats"))
            assert stats["corpus"] == 1
            # /metrics: Prometheus exposition of the whole telemetry
            # registry, health breaker transitions included (ISSUE 2)
            metrics = get("/metrics")
            assert "# TYPE tz_breaker_opens_total counter" in metrics
            assert "tz_watchdog_wedges_total" in metrics
            assert "tz_manager_corpus_size 1" in metrics
            # every metric registered in this process is exposed:
            # importing the fuzzer module registers its Stat mirrors
            import syzkaller_tpu.fuzzer.fuzzer  # noqa: F401

            metrics = get("/metrics")
            assert "tz_fuzzer_exec_total_total" in metrics
            # /api/stats: manager rollup + full telemetry snapshot
            api = json_mod.loads(get("/api/stats"))
            assert api["manager"]["corpus"] == 1
            assert "tz_breaker_opens_total" in api["telemetry"]["counters"]
            assert api["telemetry"]["gauges"]["tz_manager_corpus_size"] == 1
            # cross-process rollup: a fuzzer's poll telemetry lands on
            # /metrics (source="fleet" label) and /api/stats (ISSUE 4)
            assert api["fleet"]["sources"] == 0  # nothing polled yet
            m.serv.Poll({"name": "f", "stats": {},
                         "max_signal": [[], []],
                         "telemetry": {
                             "counters": {"tz_pipeline_mutants_total": 9},
                             "gauges": {},
                             "histograms": {}}})
            api = json_mod.loads(get("/api/stats"))
            assert api["fleet"]["sources"] == 1
            assert api["fleet"]["counters"][
                "tz_pipeline_mutants_total"] == 9
            metrics = get("/metrics")
            assert ('tz_pipeline_mutants_total{source="fleet"} 9'
                    in metrics)
            # CI satellite (ISSUE 6): the whole exposition — process
            # registry + labeled gauge families + the fleet section —
            # must parse as well-formed Prometheus text, so a
            # fleet-merge or new-gauge regression fails here instead
            # of at scrape time.
            from syzkaller_tpu.telemetry.promcheck import (
                validate_exposition,
            )

            assert validate_exposition(metrics) == []
            # the per-kernel profiler family renders with one TYPE
            # line and a label per kernel
            assert ('tz_device_kernel_ms_per_batch{kernel="mutate"}'
                    in metrics)
            assert metrics.count(
                "# TYPE tz_device_kernel_ms_per_batch gauge") == 1
            # /api/debug/flight: the live flight-recorder payload
            flight = json_mod.loads(get("/api/debug/flight"))
            assert flight["reason"] == "on_demand"
            for key in ("spans", "queue_depths", "breaker_timeline",
                        "registry"):
                assert key in flight
            # /api/coverage (ISSUE 7): growth curve + heat regions +
            # attribution + drift status, local and fleet, and the
            # labeled novelty family validates through promcheck on
            # the live /metrics exposition.
            from syzkaller_tpu import telemetry as _telemetry

            _telemetry.COVERAGE.note_novel("candidate", 3, proc=0)
            cov = json_mod.loads(get("/api/coverage"))
            assert "stalled" in cov and cov["stalled"] is False
            local = cov["local"]
            for key in ("occupancy", "novelty_rate_ewma",
                        "growth_curve", "attribution", "drift",
                        "heat_regions", "stalls"):
                assert key in local
            assert local["attribution"]["by_source"].get(
                "candidate", 0) >= 3
            metrics = get("/metrics")
            assert ('tz_coverage_novel_edges_total{lane="candidate"}'
                    in metrics)
            assert metrics.count(
                "# TYPE tz_coverage_novel_edges_total counter") == 1
            assert "tz_coverage_stalled 0" in metrics
            assert validate_exposition(metrics) == []
            # Accounting & SLO plane (ISSUE 14): the ledger's labeled
            # device-ms family, the SLO scorecard gauge, and the
            # /api/accounting surface all land on the exposition and
            # validate through promcheck.
            _telemetry.ACCOUNTING.note_batch(
                0.004, tenant_rows={"vmA": 3, "vmB": 1})
            _telemetry.SLO.tick()
            metrics = get("/metrics")
            assert 'tz_acct_device_ms_total{tenant="vmA"}' in metrics
            assert metrics.count(
                "# TYPE tz_acct_device_ms_total counter") == 1
            assert 'tz_slo_burn{slo="device_util"}' in metrics
            assert metrics.count("# TYPE tz_slo_burn gauge") == 1
            assert validate_exposition(metrics) == []
            acct = json_mod.loads(get("/api/accounting"))
            assert acct["ledger"]["batches"] >= 1
            assert acct["ledger"]["conservation_error"] <= 1e-6
            assert acct["ledger"]["tenant"]["vmA"]["device_ms"] > 0
            assert {o["name"] for o in acct["slo"]["objectives"]} >= {
                "device_util", "mutant_rate", "triage_p99"}
            assert "total_device_ms" in acct["top_consumers"]
            assert "Accounting" in get("/")
            # Fleet-merge monotonicity (ISSUE 14 satellite): a fuzzer
            # restart resets its process-local counters; the fleet
            # rollup must absorb the regression (retired life + new
            # high-water = 9 + 2), never step backwards.
            m.serv.Poll({"name": "f", "stats": {},
                         "max_signal": [[], []],
                         "telemetry": {
                             "counters": {"tz_pipeline_mutants_total": 2},
                             "gauges": {},
                             "histograms": {}}})
            api = json_mod.loads(get("/api/stats"))
            assert api["fleet"]["counters"][
                "tz_pipeline_mutants_total"] == 11
            assert api["telemetry"]["counters"][
                "tz_telemetry_merge_resets_total"] >= 1
            metrics = get("/metrics")
            assert ('tz_pipeline_mutants_total{source="fleet"} 11'
                    in metrics)
            assert validate_exposition(metrics) == []
            # the summary page rolls the same plane up, and the
            # status snapshot carries the manager-level flag
            assert "Coverage intelligence" in get("/")
            assert json_mod.loads(
                get("/stats"))["coverage_stalled"] is False
            corpus = get("/corpus")
            assert "/input?sig=" in corpus
            sig = corpus.split("/input?sig=")[1].split("'")[0]
            inp = get(f"/input?sig={sig}")
            assert "signal: 2" in inp
            filtered = get(f"/corpus?call={first_call}")
            assert "<pre>" in filtered
            empty = get("/corpus?call=definitely_not_a_call")
            assert "<pre>" not in empty
            syscalls = get("/syscalls")
            assert first_call in syscalls and "inputs" in syscalls
            prio = get("/prio")
            assert "top partners" in prio
            prio_one = get(f"/prio?call={first_call}")
            assert "target call" in prio_one
            crash_id = hash_string(rep.title.encode())
            crash = get(f"/crash?id={crash_id}")
            assert "console log tail" in crash
            report = get(f"/report?id={crash_id}")
            assert "use-after-free" in report
            assert "not found" in get("/report?id=../../etc")
            raw = get("/rawcover")
            assert isinstance(raw, str)
        finally:
            srv.shutdown()
    finally:
        m.shutdown()
