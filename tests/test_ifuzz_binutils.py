"""Binutils oracle for the x86 table: generated instructions must
disassemble at the same lengths GNU objdump computes.

This is an INDEPENDENT implementation check — binutils' decoder
shares no code or tables with utils/x86.py, so agreement on
instruction boundaries across thousands of generated encodings is
strong evidence the table's modrm/imm/prefix rules match the ISA
(reference analogue: pkg/ifuzz's decode test against its own table;
we additionally cross-check a foreign decoder)."""

from __future__ import annotations

import random
import re
import shutil
import subprocess
import tempfile

import pytest

from syzkaller_tpu.utils import x86

pytestmark = pytest.mark.skipif(
    not (shutil.which("objdump") and shutil.which("as")),
    reason="binutils not available")

_MODES = {
    x86.REAL16: ("i8086", 16),
    x86.PROT32: ("i386", 32),
    x86.LONG64: ("x86-64", 64),
}


def _objdump_lengths(blob: bytes, march: str) -> list[int]:
    """Instruction lengths objdump assigns to a flat code blob."""
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        f.write(blob)
        f.flush()
        out = subprocess.run(
            ["objdump", "-D", "-b", "binary", "-m", "i386",
             *(["-M", "x86-64"] if march == "x86-64" else
               ["-M", "i8086"] if march == "i8086" else []),
             f.name],
            capture_output=True, text=True, timeout=60).stdout
    lengths = []
    cur = 0
    for line in out.splitlines():
        # "   0:\t48 89 d8             \tmov ..." — hex byte groups
        m = re.match(r"\s*[0-9a-f]+:\t([0-9a-f ]+)\t", line)
        cont = re.match(r"\s*[0-9a-f]+:\t([0-9a-f ]+)\s*$", line)
        if m:
            if cur:
                lengths.append(cur)
            cur = len(m.group(1).split())
        elif cont:  # continuation line of a long instruction
            cur += len(cont.group(1).split())
    if cur:
        lengths.append(cur)
    return lengths


@pytest.mark.parametrize("mode", sorted(_MODES))
def test_decoder_agrees_with_objdump(mode):
    march, _bits = _MODES[mode]
    r = random.Random(77 + mode)
    cfg = x86.Config(mode=mode, avx=False)  # objdump -M has no AVX16
    mismatches = []
    total = 0
    for trial in range(300):
        insn = x86.generate_insn(cfg, r)
        # objdump needs (bad) padding to not run past the end
        got = _objdump_lengths(insn + b"\x90" * 4, march)
        if not got:
            continue
        total += 1
        ours = x86.decode(mode, insn)
        if got[0] != ours:
            # objdump folds some prefixes into the next line and
            # flags undefined combos "(bad)" at length 1; tolerate
            # only genuinely undefined encodings
            disasm = _disasm_first(insn, march)
            if "(bad)" in disasm:
                continue
            mismatches.append((insn.hex(), ours, got[0], disasm))
    assert total >= 250
    assert not mismatches, mismatches[:10]


def _disasm_first(blob: bytes, march: str) -> str:
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        f.write(blob + b"\x90" * 4)
        f.flush()
        out = subprocess.run(
            ["objdump", "-D", "-b", "binary", "-m", "i386",
             *(["-M", "x86-64"] if march == "x86-64" else
               ["-M", "i8086"] if march == "i8086" else []),
             f.name],
            capture_output=True, text=True, timeout=60).stdout
    for line in out.splitlines():
        if re.match(r"\s*0:\t", line):
            return line
    return ""


def test_decoder_agrees_with_objdump_avx():
    """The VEX/EVEX planes against the oracle (long mode, where the
    encodings are unambiguous)."""
    march = "x86-64"
    r = random.Random(991)
    cfg = x86.Config(mode=x86.LONG64, avx=True)
    mismatches = []
    total = 0
    for _ in range(400):
        insn = x86.generate_insn(cfg, r)
        if insn[0] not in (0xC4, 0xC5, 0x62):
            continue
        got = _objdump_lengths(insn + b"\x90" * 4, march)
        if not got:
            continue
        total += 1
        ours = x86.decode(x86.LONG64, insn)
        if got[0] != ours:
            disasm = _disasm_first(insn, march)
            if "(bad)" in disasm:
                continue
            mismatches.append((insn.hex(), ours, got[0], disasm))
    assert total >= 60
    assert not mismatches, mismatches[:10]
