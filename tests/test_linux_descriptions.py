"""Gates on the syzlang-compiled linux/amd64 model (VERDICT r2 #2).

The description corpus (sys/descriptions/linux/*.txt + extracted
.const) must compile to hundreds of enabled syscalls and interoperate
with every downstream layer: generation under debug validation, text
and exec serialization, the choice table, and the device tensor codec
(the reference's equivalent sanity layer: sys/linux decl tests,
prog/decl_test.go:51).
"""

import pytest

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def linux():
    return get_target("linux", "amd64")


def test_scale_and_shape(linux):
    assert len(linux.syscalls) >= 300, "description corpus shrank"
    assert len(linux.resources) >= 20
    # Real amd64 syscall numbers flow from the extracted consts.
    nrs = {c.call_name: c.nr for c in linux.syscalls}
    assert nrs["read"] == 0 and nrs["write"] == 1
    assert nrs["openat"] == 257 and nrs["mmap"] == 9
    # Variants share the wire NR of their call_name.
    fcntls = [c for c in linux.syscalls if c.call_name == "fcntl"]
    assert len(fcntls) >= 10
    assert len({c.nr for c in fcntls}) == 1 == len({72} & {fcntls[0].nr})


def test_compile_disables_nothing(linux):
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("linux", "amd64")
    assert res.disabled_calls == []
    assert res.warnings == []


def test_transitively_enabled_all(linux):
    enabled, disabled = linux.transitively_enabled_calls(
        {c: True for c in linux.syscalls})
    assert not disabled, f"resource ctor gaps: {disabled}"
    assert len(enabled) == len(linux.syscalls)


def test_generate_roundtrip_exec(linux, iters):
    import syzkaller_tpu.models.validation as validation

    assert validation.debug
    corpus = []
    for seed in range(max(iters, 30)):
        p = generate_prog(linux, RandGen(linux, seed), 8)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(linux, s)) == s, seed
        assert serialize_for_exec(p)
        corpus.append(p)
    for seed in range(max(iters // 2, 15)):
        p = corpus[seed % len(corpus)].clone()
        mutate_prog(p, RandGen(linux, 10_000 + seed), 20, corpus=corpus)
        s = serialize_prog(p)
        assert serialize_prog(deserialize_prog(linux, s)) == s, seed
        serialize_for_exec(p)


def test_choice_table_builds(linux):
    from syzkaller_tpu.models.prio import build_choice_table

    ct = build_choice_table(linux)
    rng = RandGen(linux, 3)
    seen = {ct.choose(rng, -1) for _ in range(100)}
    base = linux.syscalls[0].id
    seen |= {ct.choose(rng, base) for _ in range(100)}
    assert len(seen) > 30, "choice table collapsed"


def test_device_tensor_codec_covers_linux(linux):
    """The pipeline's tensor codec must encode a healthy share of
    generated linux programs (non-encodable ones fall back to host
    mutation, but the device path needs real coverage)."""
    pytest.importorskip("jax")
    from syzkaller_tpu.ops.pipeline import PIPELINE_TENSOR_CONFIG
    from syzkaller_tpu.ops.tensor import FlagTables, encode_prog

    flags = FlagTables.empty()
    ok = 0
    n = 40
    for seed in range(n):
        p = generate_prog(linux, RandGen(linux, 500 + seed), 6)
        try:
            encode_prog(p, PIPELINE_TENSOR_CONFIG, flags)
            ok += 1
        except Exception:
            pass
    assert ok >= n // 2, f"only {ok}/{n} linux programs tensorize"


def test_sanitize_neutralizes_kill(linux):
    text = b"kill(0x0, 0x9)\n"
    p = deserialize_prog(linux, text)
    # deserialize runs sanitize_call: SIGKILL must be neutralized.
    assert p.calls[0].args[1].val != 9


def test_revision_tracks_descriptions(linux):
    from syzkaller_tpu.sys.sysgen import revision_hash

    assert linux.revision == revision_hash("linux")
    assert len(linux.revision) == 40


def test_new_subsystem_surfaces(linux):
    """bpf/perf/tty/block/random/alg/namespace surfaces compile with
    real NRs and ioctl codes from the extracted consts."""
    names = {c.name for c in linux.syscalls}
    for n in ("bpf$BPF_MAP_CREATE", "bpf$BPF_PROG_LOAD",
              "perf_event_open", "ioctl$PERF_EVENT_IOC_ENABLE",
              "ioctl$TCGETS", "ioctl$TIOCGPTN",
              "syz_open_dev$loop", "ioctl$LOOP_SET_FD",
              "ioctl$BLKRRPART", "ioctl$RNDADDENTROPY",
              "socket$alg", "bind$alg_hash", "bind$alg_aead",
              "accept4$alg",
              "unshare", "setns", "syz_open_procfs$ns",
              "openat$fuse", "write$fuse_init",
              "ioctl$UI_DEV_CREATE", "write$uinput_event",
              "ioctl$VT_ACTIVATE", "ioctl$KDSETMODE",
              "ioctl$KCOV_ENABLE", "prctl$PR_MCE_KILL"):
        assert n in names, n
    nrs = {c.name: c.nr for c in linux.syscalls}
    assert nrs["bpf$BPF_MAP_CREATE"] == 321       # __NR_bpf on amd64
    assert nrs["perf_event_open"] == 298
    # ioctl const args carry real codes (TCGETS = 0x5401)
    tcgets = next(c for c in linux.syscalls if c.name == "ioctl$TCGETS")
    assert tcgets.args[1].val == 0x5401


def test_new_surfaces_generate_and_serialize(linux, iters):
    """Focused generation over the new call families round-trips
    through text and exec serialization."""
    from syzkaller_tpu.models.encoding import (
        deserialize_prog, serialize_prog)
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.prio import build_choice_table
    from syzkaller_tpu.models.rand import RandGen

    fams = ("bpf", "perf_event_open", "ioctl$TC", "ioctl$LOOP",
            "socket$alg", "setns")
    enabled = {c: c.name.startswith(fams) for c in linux.syscalls}
    ct = build_choice_table(linux, enabled=enabled)
    hit = set()
    for seed in range(max(iters, 10) * 4):
        p = generate_prog(linux, RandGen(linux, 7000 + seed), 8, ct=ct)
        text = serialize_prog(p)
        p2 = deserialize_prog(linux, text)
        assert serialize_prog(p2) == text
        serialize_for_exec(p)
        for c in p.calls:
            if c.meta.name.startswith(fams):
                hit.add(c.meta.name.split("$")[0])
    assert hit, "new families never generated"


def test_pseudo_nr_base_contract(linux):
    """The executor<->descriptions pseudo-NR range is pinned in three
    places (wire.h, pseudo_amd64.const, ipc/env.py) — they must
    agree."""
    import re
    from pathlib import Path

    from syzkaller_tpu.ipc.env import PSEUDO_NR_BASE

    wire = (Path(__file__).resolve().parents[1]
            / "executor" / "wire.h").read_text()
    m = re.search(r"kPseudoNrBase = (0x[0-9a-fA-F]+)", wire)
    assert m and int(m.group(1), 16) == PSEUDO_NR_BASE
    pseudo_nrs = [c.nr for c in linux.syscalls
                  if c.call_name.startswith("syz_")]
    assert pseudo_nrs and all(nr >= PSEUDO_NR_BASE for nr in pseudo_nrs)
    real_nrs = [c.nr for c in linux.syscalls
                if not c.call_name.startswith("syz_")]
    assert all(nr < PSEUDO_NR_BASE for nr in real_nrs)
