"""Coverage intelligence plane (ISSUE 7, telemetry/coverage.py +
ops/signal analytics kernels + triage-engine flush-cadence wiring).

Host-only except the kernel bit-exactness/drift tests, which compile
the two analytics kernels once on the CPU backend (they are
flush-cadence reductions, never per-batch — the warm-rig guard in
test_health_faults pins that).  Stall-detector tests script time via
the tracker's injectable clock instead of sleeping through windows.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry.coverage import SOURCES, CoverageTracker


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _Info:
    __slots__ = ("call_index", "errno", "signal")

    def __init__(self, call_index, signal, errno=0):
        self.call_index = call_index
        self.errno = errno
        self.signal = signal


def _prio_fn(_errno, _idx):
    return 3


def _counter_value(source: str) -> float:
    return telemetry.counter("tz_coverage_novel_edges_total",
                             labels={"lane": source}).value


# -- the tracker (growth ring, EWMA, attribution) --------------------------


def test_growth_ring_ewma_and_attribution():
    clock = _Clock()
    tr = CoverageTracker(time_fn=clock, stall_window_s=1e9,
                         interval_s=1.0, ring=32)
    base = {s: _counter_value(s) for s in SOURCES}
    tr.note_novel("smash", 10, proc=1)
    tr.note_novel("candidate", 4, proc=2)
    tr.note_novel("smash", 6, proc=1)
    tr.note_novel("definitely_not_a_lane", 3, proc=9)  # bounded labels
    clock.advance(2.0)
    tr.sample(500, regions=[1, 2, 0, 3])
    snap = tr.snapshot()
    assert snap["occupancy"] == 500
    assert snap["novel_edges_total"] == 23
    assert snap["novelty_rate_ewma"] > 0
    assert snap["heat_regions"] == [1, 2, 0, 3]
    attr = snap["attribution"]
    assert attr["by_source"] == {"smash": 16, "candidate": 4,
                                 "exploration": 3}
    assert attr["by_proc"] == {"1": 16, "2": 4, "9": 3}
    assert _counter_value("smash") - base["smash"] == 16
    assert _counter_value("exploration") - base["exploration"] == 3
    # curve: one point carrying the accumulated delta
    assert snap["growth_curve"][-1][1:] == [500, 23]
    # ring is bounded
    for _ in range(100):
        clock.advance(2.0)
        tr.tick(force=True)
    assert len(tr.curve()) == 32


def test_tick_rate_limited_and_forced():
    clock = _Clock()
    tr = CoverageTracker(time_fn=clock, stall_window_s=1e9,
                         interval_s=10.0)
    tr.tick()
    assert tr.curve() == []  # inside the interval: no point appended
    clock.advance(11.0)
    tr.tick()
    assert len(tr.curve()) == 1
    tr.tick(force=True)
    assert len(tr.curve()) == 2


# -- the plateau detector --------------------------------------------------


def test_stall_detector_fires_incident_and_resumes(tmp_path):
    clock = _Clock()
    tr = CoverageTracker(time_fn=clock, stall_window_s=30.0,
                         stall_edges=1, interval_s=1.0)
    telemetry.FLIGHT.set_dir(str(tmp_path))
    saved = telemetry.FLIGHT.min_interval_s
    telemetry.FLIGHT.min_interval_s = 0.0
    try:
        tr.note_novel("exploration", 5, proc=0)
        clock.advance(10.0)
        tr.tick(force=True)
        assert not tr.stalled()  # window not yet dry
        # A scripted zero-novelty run: the window passes with nothing.
        for _ in range(6):
            clock.advance(10.0)
            tr.tick(force=True)
        assert tr.stalled()
        snap = tr.snapshot()
        assert snap["stalls"] == 1
        # The structured incident landed in TZ_FLIGHT_DIR with the
        # growth-curve tail and attribution table riding the payload.
        path = os.path.join(
            tmp_path, f"tz_flight_coverage_stalled_{os.getpid()}.json")
        assert os.path.exists(path), "plateau incident never dumped"
        incident = json.loads(open(path).read())
        assert incident["reason"] == "coverage_stalled"
        assert incident["growth_curve"], "no growth-curve tail"
        assert incident["attribution"]["by_source"] == \
            {"exploration": 5}
        assert any(n == "coverage.stall"
                   for _ts, n, _d in incident["events"])
        # staying dry does not re-fire (one transition, one incident)
        clock.advance(50.0)
        tr.tick(force=True)
        assert tr.snapshot()["stalls"] == 1
        # the first novel edge resumes
        tr.note_novel("smash", 2)
        assert not tr.stalled()
        assert any(n == "coverage.resume"
                   for _ts, n, _d in telemetry.REGISTRY.events())
    finally:
        telemetry.FLIGHT.set_dir(None)
        telemetry.FLIGHT.min_interval_s = saved


def test_stall_needs_full_window_of_history():
    """Startup must never read as a plateau: a fresh tracker with no
    novelty yet stays un-stalled until a whole window has passed."""
    clock = _Clock()
    tr = CoverageTracker(time_fn=clock, stall_window_s=60.0,
                         stall_edges=1, interval_s=1.0)
    clock.advance(30.0)
    tr.tick(force=True)
    assert not tr.stalled()
    clock.advance(31.0)
    tr.tick(force=True)
    assert tr.stalled()
    # resume before leaving: the stalled gauge is process-shared
    # registry state, and a latched 1 would leak into later tests
    # (the live manager test asserts the un-stalled exposition).
    tr.note_novel("exploration", 1)
    assert not tr.stalled()


# -- knobs (envsafe semantics) ---------------------------------------------


def test_coverage_knobs_envsafe_and_registered(monkeypatch):
    from syzkaller_tpu.health.envsafe import KNOWN_TZ_VARS

    for name in ("TZ_COVERAGE_STALL_WINDOW_S",
                 "TZ_COVERAGE_STALL_EDGES", "TZ_COVERAGE_INTERVAL_S",
                 "TZ_COVERAGE_AUDIT_S", "TZ_COVERAGE_RING",
                 "TZ_MANAGER_HTTP"):
        assert name in KNOWN_TZ_VARS, name
    monkeypatch.setenv("TZ_COVERAGE_STALL_WINDOW_S", "42.5")
    monkeypatch.setenv("TZ_COVERAGE_STALL_EDGES", "nope")  # malformed
    tr = CoverageTracker(time_fn=_Clock())
    assert tr.stall_window_s == 42.5
    assert tr.stall_edges == 1  # degraded to the default, not a crash


# -- lane threading through the verdict path -------------------------------


def test_verdict_path_attribution_all_lanes(test_target):
    """check_new_signal_fn attributes confirmed novel edges to the
    workqueue lane + proc it was handed (the threading Proc.execute
    does), and ticks the detector on the no-news path."""
    from syzkaller_tpu.fuzzer import Fuzzer, WorkQueue

    fz = Fuzzer(test_target, wq=WorkQueue())
    base = {s: _counter_value(s) for s in SOURCES}
    rng = np.random.RandomState(2)
    for i, src in enumerate(SOURCES):
        edges = rng.randint(0, 1 << 26, size=8, dtype=np.uint32)
        news = fz.check_new_signal_fn(_prio_fn, [_Info(0, edges)],
                                      source=src, proc=i)
        assert news
        got = _counter_value(src) - base[src]
        assert got == sum(len(d) for _ci, d in news), src
    # replay: nothing new -> no attribution movement
    before = _counter_value("smash")
    assert fz.check_new_signal_fn(
        _prio_fn, [_Info(0, edges)], source="smash") == []
    assert _counter_value("smash") == before


def test_proc_lane_map_covers_execution_stats():
    """Every Stat an execution can carry maps into the bounded SOURCES
    label set (unknown stats fold to exploration in Proc.execute)."""
    from syzkaller_tpu.fuzzer.proc import _LANE_BY_STAT

    assert set(_LANE_BY_STAT.values()) <= set(SOURCES)
    from syzkaller_tpu.fuzzer.fuzzer import Stat

    assert _LANE_BY_STAT[Stat.CANDIDATE] == "candidate"
    assert _LANE_BY_STAT[Stat.SMASH] == "smash"
    assert _LANE_BY_STAT[Stat.GENERATE] == "exploration"


# -- the device analytics kernels ------------------------------------------


def test_device_popcount_bitexact_and_heat_regions():
    """Acceptance: the device occupancy popcount is bit-exact against
    np.count_nonzero on the host mirror, and the region histogram is
    the exact per-region breakdown."""
    jnp = pytest.importorskip("jax.numpy")
    from syzkaller_tpu.ops import signal as dsig

    rng = np.random.RandomState(11)
    mirror = np.zeros(dsig.PLANE_SIZE, dtype=np.uint8)
    idx = rng.randint(0, dsig.PLANE_SIZE, size=200_000)
    mirror[idx] = rng.randint(1, 5, size=idx.size).astype(np.uint8)
    occ_dev, regions_dev = dsig.coverage_stats(jnp.asarray(mirror))
    assert int(occ_dev) == int(np.count_nonzero(mirror))
    regions_np = np.count_nonzero(
        mirror.reshape(dsig.COVERAGE_REGIONS, -1), axis=1)
    assert np.array_equal(np.asarray(regions_dev), regions_np)
    assert int(occ_dev) == int(regions_np.sum())


def test_plane_drift_flags_injected_corruption():
    jnp = pytest.importorskip("jax.numpy")
    from syzkaller_tpu.ops import signal as dsig

    rng = np.random.RandomState(12)
    mirror = np.zeros(dsig.PLANE_SIZE, dtype=np.uint8)
    mirror[rng.randint(0, dsig.PLANE_SIZE, size=5000)] = 3
    clean = jnp.asarray(mirror)
    assert int(dsig.plane_drift(clean, jnp.asarray(mirror))) == 0
    corrupt = mirror.copy()
    flips = np.unique(rng.randint(0, dsig.PLANE_SIZE, size=257))
    corrupt[flips] ^= 1  # silent bit damage
    assert int(dsig.plane_drift(jnp.asarray(corrupt),
                                jnp.asarray(mirror))) == flips.size


# -- the triage engine's flush-cadence wiring ------------------------------


def test_engine_analytics_exact_occupancy_and_drift(test_target):
    """The exact-popcount satellite: occupancy is no longer tracked
    incrementally at merge time; one analytics pass makes the gauge
    bit-exact against the mirror (device or mirror path), and an
    injected plane corruption is caught by the audit, which drops the
    plane so the next flush re-uploads the authority mirror."""
    pytest.importorskip("jax")
    import jax.numpy as jnp

    from syzkaller_tpu.triage import TriageEngine

    eng = TriageEngine(batch=8, max_edges=64)
    rng = np.random.RandomState(4)
    eng._merge_edges(
        rng.randint(0, 1 << 32, size=4096, dtype=np.uint32), 3)
    assert eng._occupancy == 0  # stale by design until the cadence
    r = eng.run_analytics(audit=True)  # mirror path: no device plane
    want = int(np.count_nonzero(eng._mirror))
    assert r["occupancy"] == want == eng._occupancy
    assert r["drift"] == 0
    eng.share_plane()  # materialize; backlog applied
    r = eng.run_analytics(audit=True)  # device path now
    assert r["occupancy"] == want
    assert r["drift"] == 0
    snap = eng.snapshot()
    assert snap["plane_occupancy"] == want
    assert snap["fold_false_negative_rate"] == pytest.approx(
        want / (1 << 26))
    # Injected corruption: flip buckets the mirror does not hold.
    events0 = sum(1 for _ts, n, _d in telemetry.REGISTRY.events()
                  if n == "coverage.drift")
    eng._plane_dev = eng._plane_dev.at[np.arange(7)].set(
        jnp.uint8(9))
    r = eng.run_analytics(audit=True)
    assert r["drift"] == 7
    assert eng._plane_dev is None, \
        "detected drift must drop the plane for a mirror re-upload"
    assert sum(1 for _ts, n, _d in telemetry.REGISTRY.events()
               if n == "coverage.drift") == events0 + 1
    # the rebuild restores a clean plane
    eng.share_plane()
    assert eng.run_analytics(audit=True)["drift"] == 0


def test_engine_analytics_feeds_tracker(test_target):
    pytest.importorskip("jax")
    from syzkaller_tpu.triage import TriageEngine

    eng = TriageEngine(batch=8, max_edges=64)
    rng = np.random.RandomState(5)
    eng._merge_edges(
        rng.randint(0, 1 << 32, size=64, dtype=np.uint32), 2)
    r = eng.run_analytics()
    snap = telemetry.COVERAGE.snapshot()
    assert snap["occupancy"] == r["occupancy"]
    assert telemetry.REGISTRY.gauge(
        "tz_coverage_occupancy").value == r["occupancy"]
