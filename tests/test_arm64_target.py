"""linux/arm64 target: the same descriptions compile against arm64's
own syscall-number table (VERDICT r4 ask #3 second half).

The arm64 const file is produced by the two-pass extraction in
sys/extract.py (host kernel-ABI values + asm-generic override pass);
legacy x86-only syscalls must compile DISABLED, everything else keeps
working through the generic-table numbers (reference analog: per-arch
sys/linux/*.const + gen/arm64.go)."""

from __future__ import annotations

import pytest

from syzkaller_tpu.models.target import get_target


@pytest.fixture(scope="module")
def arm64():
    return get_target("linux", "arm64")


def test_compiles_with_own_nr_table(arm64):
    amd64 = get_target("linux", "amd64")
    names64 = {s.name: s for s in amd64.syscalls}
    namesa = {s.name: s for s in arm64.syscalls}
    # substantial shared surface, numbered differently
    shared = set(names64) & set(namesa)
    assert len(shared) > 1700
    differing = [n for n in shared
                 if not n.startswith("syz_")
                 and names64[n].nr != namesa[n].nr]
    # nearly every real syscall renumbers on the generic table
    assert len(differing) > 1000, f"only {len(differing)} renumbered"
    assert namesa["openat"].nr == 56  # generic table anchor


def test_legacy_x86_calls_disabled(arm64):
    names = {s.name for s in arm64.syscalls}
    for legacy in ("open", "epoll_create", "inotify_init", "mkdir",
                   "readlink", "unlink", "rename", "pipe", "dup2",
                   "arch_prctl"):
        assert legacy not in names, f"{legacy} must be absent on arm64"
    # their modern replacements stay — including the __ARCH_WANT_*
    # selections arm64's uapi asm/unistd.h makes (renameat, fstat,
    # getrlimit live behind those macros in the generic table)
    for modern in ("openat", "epoll_create1", "inotify_init1", "mkdirat",
                   "readlinkat", "unlinkat", "renameat", "renameat2",
                   "pipe2", "dup3", "fstat", "getrlimit", "setrlimit"):
        assert modern in names, f"{modern} missing on arm64"


def test_pseudo_calls_survive(arm64):
    names = {s.name for s in arm64.syscalls}
    assert "syz_open_dev" in names
    assert any(n.startswith("syz_mount_image$") for n in names)


def test_generation_works_on_arm64(arm64):
    from syzkaller_tpu.models.generation import generate_prog
    from syzkaller_tpu.models.rand import RandGen

    p = generate_prog(arm64, RandGen(arm64, 7), 8)
    assert 1 <= len(p.calls) <= 8
