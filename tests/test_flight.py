"""Flight recorder (telemetry/flight.py, ISSUE 6): bounded rings,
incident dumps + triggers (breaker-open, DeviceWedged, SIGTERM),
the race-fixed snapshot under a concurrent increment hammer, the
attempt journal, and the Prometheus exposition validator
(telemetry/promcheck.py).  Host-only; the on-pipeline DeviceWedged
incident test shares the warm rig in test_health_faults.py."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry.flight import FlightRecorder, append_attempt
from syzkaller_tpu.telemetry.registry import Registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk(tmp_path, size=64):
    reg = Registry()
    fr = FlightRecorder(registry=reg, size=size)
    fr.set_dir(str(tmp_path))
    fr.min_interval_s = 0.0
    return reg, fr


# -- rings --------------------------------------------------------------


def test_span_ring_is_bounded(tmp_path):
    _reg, fr = _mk(tmp_path, size=32)
    for i in range(100):
        fr.note_span("pipeline.drain", 0.001 * i)
    snap = fr.snapshot()
    assert len(snap["spans"]) == 32
    assert snap["spans"][-1][2] == pytest.approx(0.099)  # newest kept


def test_gauge_history_samples_watch_gauges(tmp_path):
    reg, fr = _mk(tmp_path)
    reg.gauge("tz_pipeline_queue_depth").set(5)
    for _ in range(64):
        fr.note_span("proc.exec", 0.001)
    snap = fr.snapshot()
    assert snap["queue_depths"]
    assert snap["queue_depths"][-1]["tz_pipeline_queue_depth"] == 5


# -- dumps --------------------------------------------------------------


def test_dump_disarmed_returns_none():
    fr = FlightRecorder(registry=Registry())
    fr.min_interval_s = 0.0
    assert not fr.armed()
    assert fr.dump("breaker_open") is None


def test_dump_writes_structured_incident(tmp_path):
    reg, fr = _mk(tmp_path)
    reg.counter("tz_pipeline_batches_total").inc(7)
    reg.gauge("tz_pipeline_queue_depth").set(2)
    reg.record_event("breaker.open", "after 4 failures")
    reg.record_event("watchdog.wedge", "device.launch 0.3s")
    for _ in range(40):
        fr.note_span("pipeline.drain", 0.01)
    path = fr.dump("device_wedged", "device.launch hung")
    assert path is not None and os.path.exists(path)
    incident = json.loads(open(path).read())
    assert incident["reason"] == "device_wedged"
    assert incident["detail"] == "device.launch hung"
    assert incident["spans"] and incident["queue_depths"]
    names = [n for _ts, n, _d in incident["breaker_timeline"]]
    assert names == ["breaker.open", "watchdog.wedge"]
    assert incident["registry"]["counters"][
        "tz_pipeline_batches_total"] == 7


def test_dump_rate_limited_per_reason(tmp_path):
    _reg, fr = _mk(tmp_path)
    fr.min_interval_s = 60.0
    assert fr.dump("breaker_open") is not None
    assert fr.dump("breaker_open") is None  # limited
    assert fr.dump("device_wedged") is not None  # other reason free


def test_dump_uses_race_fixed_snapshot_under_hammer(tmp_path):
    """ISSUE 6 satellite: the dump path reads the registry through
    Registry.snapshot() (one lock acquisition for the metric list,
    per-metric locks for values — the grab_stats race-fix shape), not
    a live-counter walk.  Hammer a counter from worker threads while
    dumping continuously: every dump parses, and the recorded values
    are monotone and conserved."""
    reg, fr = _mk(tmp_path)
    c = reg.counter("tz_hammer_total")
    per_thread, nthreads = 5000, 4
    stop = threading.Event()

    def worker():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(nthreads)]
    for t in threads:
        t.start()
    values = []
    while any(t.is_alive() for t in threads):
        path = fr.dump("on_demand")
        if path:
            values.append(json.loads(open(path).read())
                          ["registry"]["counters"]["tz_hammer_total"])
    for t in threads:
        t.join()
    final = json.loads(open(fr.dump("on_demand")).read())
    values.append(final["registry"]["counters"]["tz_hammer_total"])
    assert values[-1] == per_thread * nthreads  # conserved
    assert all(a <= b for a, b in zip(values, values[1:]))  # monotone


# -- automatic triggers -------------------------------------------------


def test_breaker_open_triggers_dump(tmp_path):
    from syzkaller_tpu.health import CircuitBreaker

    telemetry.FLIGHT.set_dir(str(tmp_path))
    saved = telemetry.FLIGHT.min_interval_s
    telemetry.FLIGHT.min_interval_s = 0.0
    try:
        br = CircuitBreaker(failure_threshold=1, backoff_initial=60.0)
        br.record_failure()
        path = os.path.join(
            tmp_path, f"tz_flight_breaker_open_{os.getpid()}.json")
        assert os.path.exists(path)
        incident = json.loads(open(path).read())
        assert incident["reason"] == "breaker_open"
    finally:
        telemetry.FLIGHT.set_dir(None)
        telemetry.FLIGHT.min_interval_s = saved


def test_device_wedged_triggers_dump(tmp_path):
    from syzkaller_tpu.health import DeviceWedged, Watchdog

    telemetry.FLIGHT.set_dir(str(tmp_path))
    saved = telemetry.FLIGHT.min_interval_s
    telemetry.FLIGHT.min_interval_s = 0.0
    hang = threading.Event()
    try:
        wd = Watchdog(deadline_s=0.05)
        with pytest.raises(DeviceWedged):
            wd.call(hang.wait, "device.launch")
        path = os.path.join(
            tmp_path, f"tz_flight_device_wedged_{os.getpid()}.json")
        assert os.path.exists(path)
        incident = json.loads(open(path).read())
        assert "device.launch" in incident["detail"]
        assert any(n == "watchdog.wedge"
                   for _ts, n, _d in incident["breaker_timeline"])
    finally:
        hang.set()
        telemetry.FLIGHT.set_dir(None)
        telemetry.FLIGHT.min_interval_s = saved


def test_sigterm_dumps_incident(tmp_path):
    """SIGTERM is the supervisor killing a possibly-mid-incident
    process: the handler dumps the black box, then delivers the
    default disposition (the process still dies of SIGTERM)."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {str(REPO_ROOT)!r})\n"
        "from syzkaller_tpu import telemetry\n"
        "from syzkaller_tpu.telemetry import flight\n"
        f"telemetry.FLIGHT.set_dir({str(tmp_path)!r})\n"
        "telemetry.FLIGHT.min_interval_s = 0.0\n"
        "telemetry.counter('tz_sig_probe_total').inc(3)\n"
        "assert flight.install_signal_handler()\n"
        "print('READY', flush=True)\n"
        "time.sleep(30)\n")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGTERM  # default delivered
    path = os.path.join(tmp_path,
                        f"tz_flight_sigterm_{proc.pid}.json")
    assert os.path.exists(path)
    incident = json.loads(open(path).read())
    assert incident["reason"] == "sigterm"
    assert incident["registry"]["counters"][
        "tz_sig_probe_total"] == 3


# -- the attempt journal ------------------------------------------------


def test_append_attempt_accumulates_and_bounds(tmp_path):
    path = str(tmp_path / "inc.json")
    for i in range(12):
        append_attempt(path, {"kind": "timeout", "reason": f"r{i}",
                              "attempt": i})
    payload = json.loads(open(path).read())
    assert len(payload["attempts"]) == 12
    assert payload["attempts"][-1]["reason"] == "r11"
    assert payload["attempts"][-1]["ts"] > 0
    # the bound, without paying 300 JSON rewrites: seed an oversized
    # journal and append once
    payload["attempts"] = [{"kind": "timeout", "reason": "old"}] * 400
    with open(path, "w") as f:
        json.dump(payload, f)
    append_attempt(path, {"kind": "timeout", "reason": "new"})
    payload = json.loads(open(path).read())
    assert len(payload["attempts"]) == 256  # bounded
    assert payload["attempts"][-1]["reason"] == "new"


# -- the exposition validator (telemetry/promcheck.py) ------------------


def test_promcheck_accepts_registry_output():
    from syzkaller_tpu.telemetry.promcheck import validate_exposition

    reg = Registry()
    reg.counter("tz_c_total", "a counter").inc(3)
    reg.gauge("tz_g_depth").set(1.5)
    reg.gauge("tz_fam_ms_per_batch", labels={"kernel": "mutate"}).set(2)
    reg.gauge("tz_fam_ms_per_batch", labels={"kernel": "novel"}).set(3)
    reg.histogram("tz_h_seconds").observe(0.01)
    assert validate_exposition(reg.render_prometheus()) == []


def test_promcheck_flags_malformations():
    from syzkaller_tpu.telemetry.promcheck import validate_exposition

    assert any("unknown TYPE" in p for p in validate_exposition(
        "# TYPE tz_x_total banana\ntz_x_total 1\n"))
    assert any("duplicate TYPE" in p for p in validate_exposition(
        "# TYPE tz_x_total counter\n# TYPE tz_x_total counter\n"
        "tz_x_total 1\n"))
    assert any("malformed sample" in p for p in validate_exposition(
        "tz x total 1\n"))
    assert any("malformed label" in p for p in validate_exposition(
        'tz_x_total{kernel=mutate} 1\n'))
    assert any("le label" in p for p in validate_exposition(
        "# TYPE tz_h_seconds histogram\n"
        'tz_h_seconds_bucket{kernel="x"} 1\n'))
    assert any("+Inf" in p for p in validate_exposition(
        "# TYPE tz_h_seconds histogram\n"
        'tz_h_seconds_bucket{le="1"} 1\n'))
    assert any("cumulative" in p for p in validate_exposition(
        "# TYPE tz_h_seconds histogram\n"
        'tz_h_seconds_bucket{le="1"} 5\n'
        'tz_h_seconds_bucket{le="2"} 3\n'
        'tz_h_seconds_bucket{le="+Inf"} 5\n'))
