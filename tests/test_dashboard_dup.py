"""Cross-namespace dup management + the reporting-config matrix
(VERDICT r4 ask #7; reference: dashboard/app/reporting.go:1-731
upstream reporting chains + incomingCommand dup/undup).

Done-when contract verified here: two namespaces sharing a crash
title dedup to ONE upstream bug, and the email flow round-trips
#syz dup / #syz undup."""

from __future__ import annotations

from email.message import EmailMessage

import pytest

from syzkaller_tpu.dashboard.app import (
    ACCESS_ADMIN,
    ACCESS_PUBLIC,
    STATUS_DUP,
    STATUS_REPORTED,
    Dashboard,
    ReportingStage,
)
from syzkaller_tpu.email import EmailReporting, Mailbox, parse_email


@pytest.fixture
def dash(tmp_path):
    return Dashboard(
        str(tmp_path),
        clients={
            "stable-mgr": {"key": "k1", "namespace": "stable"},
            "android-mgr": {"key": "k2", "namespace": "android"},
            "up-mgr": {"key": "k3", "namespace": "upstream"},
        },
        reporting={
            "stable": [ReportingStage("stable-public", ACCESS_PUBLIC,
                                      0.0, email_to="stable@lists")],
            "android": [ReportingStage("android-public", ACCESS_PUBLIC,
                                       0.0, email_to="android@lists")],
            "upstream": [ReportingStage("upstream-public",
                                        ACCESS_PUBLIC, 0.0,
                                        email_to="lkml@lists")],
        },
        upstream_ns="upstream")


def _crash(dash, client, key, title):
    return dash.report_crash({
        "client": client, "key": key, "manager": client,
        "title": title, "log": "log", "report": "rep",
    })["bug_id"]


def test_two_namespaces_dedup_to_one_upstream_bug(dash):
    title = "KASAN: use-after-free in shared_path"
    b_stable = _crash(dash, "stable-mgr", "k1", title)
    b_android = _crash(dash, "android-mgr", "k2", title)
    assert b_stable != b_android  # per-namespace bugs at first

    # both namespaces exhaust their ladder -> upstreaming
    assert dash.upstream_bug(b_stable)
    assert dash.upstream_bug(b_android)

    up_ids = {dash.bugs[b_stable].dup_of, dash.bugs[b_android].dup_of}
    assert len(up_ids) == 1, "must converge on ONE upstream bug"
    up = dash.bugs[up_ids.pop()]
    assert up.namespace == "upstream"
    assert up.title == title
    assert dash.bugs[b_stable].status == STATUS_DUP
    assert dash.bugs[b_android].status == STATUS_DUP
    # crash evidence folded upstream
    assert up.num_crashes >= 2

    # upstream bug reports through the upstream namespace's stage
    reports = dash.poll_reports("upstream")
    assert [r["id"] for r in reports] == [up.id]
    assert reports[0]["email_to"] == "lkml@lists"


def test_upstream_ns_is_terminal(dash):
    title = "BUG: terminal"
    up_direct = _crash(dash, "up-mgr", "k3", title)
    # already in the upstream namespace: no further upstreaming
    assert not dash.upstream_bug(up_direct)


def test_dup_by_title_crosses_namespaces(dash):
    t1 = "WARNING: odd state in foo"
    t2 = "WARNING: odd state in foo (stable flavor)"
    b_up = _crash(dash, "up-mgr", "k3", t1)
    b_stable = _crash(dash, "stable-mgr", "k1", t2)
    dash.update_bug(b_stable, dup_of=t1)  # by TITLE, other namespace
    assert dash.bugs[b_stable].status == STATUS_DUP
    assert dash.bugs[b_stable].dup_of == b_up

    # dup chains resolve to the canonical end
    b_android = _crash(dash, "android-mgr", "k2", "third flavor")
    dash.update_bug(b_android, dup_of=t2)
    assert dash.bugs[b_android].dup_of == b_up


def test_reporting_config_matrix(dash):
    """Each namespace x stage carries its own access/delay/email
    destination."""
    assert dash.stages_for("stable")[0].email_to == "stable@lists"
    assert dash.stages_for("android")[0].email_to == "android@lists"
    assert dash.stages_for("upstream")[0].email_to == "lkml@lists"
    assert dash.stages_for("stable")[0].access == ACCESS_PUBLIC


def _reply(reporting, commands):
    rep = parse_email(reporting.mailbox.outgoing[-1])
    m = EmailMessage()
    m["Subject"] = "Re: " + rep.subject
    m["From"] = "maintainer@kernel.org"
    m["To"] = rep.from_addr
    m["In-Reply-To"] = rep.msg_id
    m["Message-ID"] = f"<r{len(reporting.mailbox.outgoing)}@k.org>"
    m.set_content(commands + "\n")
    reporting.mailbox.deliver(bytes(m))


def test_email_round_trips_dup_and_undup(dash):
    mbox = Mailbox()
    reporting = EmailReporting(dash, mbox)
    canonical = "BUG: canonical crash"
    flavor = "BUG: crash flavor two"
    b_can = _crash(dash, "up-mgr", "k3", canonical)
    b_dup = _crash(dash, "up-mgr", "k3", flavor)
    assert reporting.poll_and_send() == 2

    # the last-sent report is the flavor bug; mark it a dup by title
    _reply(reporting, f"#syz dup: {canonical}")
    assert reporting.process_incoming() == 1
    assert dash.bugs[b_dup].status == STATUS_DUP
    assert dash.bugs[b_dup].dup_of == b_can

    # and undo it
    _reply(reporting, "#syz undup")
    assert reporting.process_incoming() == 1
    assert dash.bugs[b_dup].status == STATUS_REPORTED
    assert dash.bugs[b_dup].dup_of == ""
