"""Pipeline on-path overhead bound (VERDICT r4 ask #2).

The device engine's cost to the exec loop is the time Proc spends
inside ``mutator.next()``.  In the supply-rich regime — the chip
regime, where the prefetch queue is never empty — a draw is a queue
pop plus stash bookkeeping.  This test bounds that on-path cost at
<5% of a sim-kernel execution, which is the break-even condition the
BENCH_AB artifacts state: once supply outruns demand, the engine's
residual tax is the draw cost, and it must stay a rounding error
against the exec it feeds.

Reference analog for the measurement shape: equal-budget comparisons
in tools/syz-benchcmp (/root/reference/tools/syz-benchcmp/benchcmp.go:4-36).
"""

from __future__ import annotations

import time

from syzkaller_tpu.fuzzer import Fuzzer, FuzzerConfig, Proc, WorkQueue
from syzkaller_tpu.fuzzer.fuzzer import Stat
from syzkaller_tpu.fuzzer.proc import PipelineMutator
from syzkaller_tpu.ipc.env import make_env
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.ops.pipeline import DevicePipeline
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.signal.cover import Cover


def _seeds(target, n, length=6):
    return [generate_prog(target, RandGen(target, 42 + i), length)
            for i in range(n)]


def test_supply_rich_draw_cost_under_5pct_of_exec():
    target = get_target("test", "64")
    cfg = FuzzerConfig(program_length=8, generate_period=100,
                       smash_mutants=2, fault_nth_max=2,
                       minimize_attempts=1)
    fuzzer = Fuzzer(target, wq=WorkQueue(), cfg=cfg)
    for i, p in enumerate(_seeds(target, 16)):
        fuzzer.add_input_to_corpus(p, Signal({i: 1}), Cover())

    pl = DevicePipeline(target, capacity=128, batch_size=256)
    mutator = PipelineMutator(pl, drain_timeout=120.0)
    mutator._sync_corpus(fuzzer)
    env = make_env(pid=0, sim=True, signal=True)
    try:
        # Warm: compile both carried signatures, then give the prefetch
        # worker a head start so measured draws never wait on compute.
        pl.next_batch(timeout=600)
        pl.next_batch(timeout=600)
        time.sleep(0.5)

        rng = RandGen(target, 7)
        n_draws = 200
        # One throwaway draw absorbs stash paths.
        mutator.next(fuzzer, rng)
        # Classify per-draw cost by op class: squash/splice draws are
        # reference-ladder CPU mutation work that BOTH engines pay
        # (prog/mutation.go:19-131 analog); the device engine's own
        # on-path tax is the "device" draws — a prefetch-queue pop.
        mutator.ops_journal = []
        device_costs, got = [], 0
        for _ in range(n_draws):
            mark = len(mutator.ops_journal)
            t0 = time.perf_counter()
            m = mutator.next(fuzzer, rng)
            dt = time.perf_counter() - t0
            if m is not None:
                got += 1
            ops = mutator.ops_journal[mark:]
            if ops == ["device"]:
                device_costs.append(dt)
        assert got > n_draws // 2, \
            f"supply collapsed mid-measurement ({got}/{n_draws} draws)"
        assert len(device_costs) >= 20, \
            f"too few device draws to measure ({len(device_costs)})"
        # Median, not mean: a draw that lands on a prefetch refill
        # blocks on batch compute — that's supply (bounded by chip
        # rate, absent in the supply-rich regime this test models),
        # not per-draw on-path cost.
        device_costs.sort()
        draw_us = 1e6 * device_costs[len(device_costs) // 2]

        # Mean sim-kernel execution cost through the same Proc path.
        proc = Proc(fuzzer, pid=0, env=env, mutator=None)
        progs = _seeds(target, 8)
        proc.execute(proc.exec_opts, progs[0], Stat.FUZZ)  # warm
        n_execs = 60
        t0 = time.perf_counter()
        for i in range(n_execs):
            proc.execute(proc.exec_opts, progs[i % len(progs)], Stat.FUZZ)
        exec_us = 1e6 * (time.perf_counter() - t0) / n_execs
    finally:
        pl.stop()
        env.close()

    ratio = draw_us / exec_us
    assert ratio < 0.05, (
        f"supply-rich draw cost {draw_us:.0f}us is {100 * ratio:.1f}% of "
        f"a {exec_us:.0f}us sim exec — pipeline overhead bound (5%) "
        f"violated")
