"""One-command full-stack demo (VERDICT r3 item #3): manager + local
VM pool + real fuzzer subprocesses + sim-kernel executor run until
the workdir holds all five artifacts — grown corpus.db, a detected
crash, an extracted repro.prog, an emitted repro.c, and a bug filed
in the live dashboard (reference shape: RunManager -> vmLoop ->
saveCrash -> repro.Run -> saveRepro,
/root/reference/syz-manager/manager.go:141-534,736)."""

from __future__ import annotations

import pytest

from syzkaller_tpu.tools.demo import run_demo


@pytest.mark.slow
def test_demo_produces_all_artifacts(tmp_path):
    status = run_demo(str(tmp_path / "work"), minutes=12.0,
                      engine="cpu", vms=2, procs=2,
                      log=lambda *a: None)
    assert status["corpus.db"], status
    assert status["crash"], status
    assert status["repro.prog"], status
    assert status["repro.c"], status
    assert status["dashboard_bug"], status
