// Wire formats shared between the executor and the host IPC layer.
//
// Two protocols meet here:
//  1. the exec program stream (uint64 words) produced by
//     syzkaller_tpu/models/encodingexec.py (and by the TPU engine's
//     batched emitter) — constants must match that file exactly;
//  2. the control protocol over the command pipes + the result layout
//     in the output shmem, parsed by syzkaller_tpu/ipc/env.py.
//
// Design follows the role of the reference executor protocol
// (reference: executor/executor.h:117-144, prog/encodingexec.go:7-51)
// but is a fresh layout: fixed little-endian structs, no gob/go types.

#ifndef TZ_EXECUTOR_WIRE_H
#define TZ_EXECUTOR_WIRE_H

#include <stdint.h>

namespace tz {

// ---- exec program stream (encodingexec.py contract) ----------------

constexpr uint64_t kMask64 = ~0ull;
constexpr uint64_t kInstrEOF = kMask64;
constexpr uint64_t kInstrCopyin = kMask64 - 1;
constexpr uint64_t kInstrCopyout = kMask64 - 2;

constexpr uint64_t kArgConst = 0;
constexpr uint64_t kArgResult = 1;
constexpr uint64_t kArgData = 2;
constexpr uint64_t kArgCsum = 3;

constexpr uint64_t kCsumInet = 0;
constexpr uint64_t kCsumChunkData = 0;
constexpr uint64_t kCsumChunkConst = 1;

constexpr uint64_t kNoCopyout = kMask64;

// const-arg meta word: size | be<<8 | bf_off<<16 | bf_len<<24 |
// pid_stride<<32
inline uint64_t meta_size(uint64_t m) { return m & 0xff; }
inline bool meta_be(uint64_t m) { return (m >> 8) & 1; }
inline uint64_t meta_bf_off(uint64_t m) { return (m >> 16) & 0xff; }
inline uint64_t meta_bf_len(uint64_t m) { return (m >> 24) & 0xff; }
inline uint64_t meta_pid_stride(uint64_t m) { return m >> 32; }

// ---- limits (reference: executor/executor.h:25-28, ipc.go:54-55) ----

constexpr uint64_t kInShmemSize = 2 << 20;    // program stream
constexpr uint64_t kOutShmemSize = 16 << 20;  // results
constexpr int kMaxCalls = 64;
constexpr int kMaxThreads = 16;
constexpr int kMaxCopyout = 256;
constexpr int kMaxCommands = 4096;

// ---- control protocol (pipes) ---------------------------------------

constexpr uint64_t kHandshakeReqMagic = 0x745a6878616e6401ull;  // 'tZhxand1'
constexpr uint64_t kHandshakeRepMagic = 0x745a6878616e6402ull;
constexpr uint64_t kExecuteReqMagic = 0x745a65786563710aull;
constexpr uint64_t kExecuteRepMagic = 0x745a65786563720bull;

// env flags (per-process, set at handshake;
// host side: syzkaller_tpu/ipc/env.py EnvFlags)
constexpr uint64_t kEnvDebug = 1 << 0;
constexpr uint64_t kEnvSignal = 1 << 1;     // collect edge signal
constexpr uint64_t kEnvSandboxNone = 1 << 2;
constexpr uint64_t kEnvSandboxSetuid = 1 << 3;
constexpr uint64_t kEnvSandboxNamespace = 1 << 4;
constexpr uint64_t kEnvSimOS = 1 << 5;      // simulated kernel backend
constexpr uint64_t kEnvOptionalCover = 1 << 6;
// fork a fresh child per program: a program that _exits/crashes its
// process cannot take the fork-server down (reference process model:
// executor/common_linux.h:1931-2040 loop()/fork per program)
constexpr uint64_t kEnvForkProg = 1 << 7;
// real-OS environment features (reference: common_linux.h:332 TUN,
// 1075 cgroups); each is best-effort — missing kernel facilities
// degrade to a debug note, not a failure
constexpr uint64_t kEnvEnableTun = 1 << 8;
constexpr uint64_t kEnvEnableCgroups = 1 << 9;

// ---- pseudo-syscalls -------------------------------------------------
// syz_* calls are executor-implemented helpers, not kernel syscalls
// (reference: executor/common_linux.h:1041+ syz_open_dev & friends).
// They occupy a reserved NR range; the same values appear in
// sys/descriptions/linux/pseudo_amd64.const so the compiler pins them.

constexpr uint32_t kPseudoNrBase = 0x81000000u;
constexpr uint32_t kPseudoOpenDev = kPseudoNrBase + 0;
constexpr uint32_t kPseudoOpenProcfs = kPseudoNrBase + 1;
constexpr uint32_t kPseudoOpenPts = kPseudoNrBase + 2;
constexpr uint32_t kPseudoEmitEthernet = kPseudoNrBase + 3;
constexpr uint32_t kPseudoExtractTcpRes = kPseudoNrBase + 4;
constexpr uint32_t kPseudoGenetlinkFamily = kPseudoNrBase + 5;
constexpr uint32_t kPseudoMountImage = kPseudoNrBase + 6;
constexpr uint32_t kPseudoReadPartTable = kPseudoNrBase + 7;
constexpr uint32_t kPseudoKvmSetupCpu = kPseudoNrBase + 8;
constexpr uint32_t kPseudoFuseMount = kPseudoNrBase + 9;
constexpr uint32_t kPseudoFuseblkMount = kPseudoNrBase + 10;
constexpr uint32_t kPseudoInitNetSocket = kPseudoNrBase + 11;
constexpr uint32_t kPseudoNrLast = kPseudoInitNetSocket;

// exec flags (per-request)
constexpr uint64_t kExecCollectCover = 1 << 0;
constexpr uint64_t kExecDedupCover = 1 << 1;
constexpr uint64_t kExecCollectComps = 1 << 2;
constexpr uint64_t kExecThreaded = 1 << 3;
constexpr uint64_t kExecCollide = 1 << 4;
constexpr uint64_t kExecFault = 1 << 5;

struct HandshakeReq {
  uint64_t magic;
  uint64_t env_flags;
  uint64_t pid;  // proc index: drives ProcType value striding
};

struct HandshakeRep {
  uint64_t magic;
};

struct ExecuteReq {
  uint64_t magic;
  uint64_t exec_flags;
  uint64_t prog_words;  // number of uint64 words in the in-shmem
  uint64_t fault_call;  // call index for fault injection, -1 = none
  uint64_t fault_nth;   // fail the nth "allocation" within that call
};

struct ExecuteRep {
  uint64_t magic;
  uint64_t status;  // 0 ok; nonzero = executor-detected failure
  uint64_t ncalls;  // completed calls written to out shmem
};

// magic exit statuses recognized by the host
// (reference: pkg/ipc/ipc.go:57-59)
constexpr int kStatusFail = 67;   // executor-level failure, retriable
constexpr int kStatusError = 68;  // program-level error
constexpr int kStatusRetry = 69;  // transient, respawn

// ---- output shmem layout --------------------------------------------
//
//   OutHeader { ncalls }
//   per call: CallResult header followed by
//     uint32 signal[signal_len]; uint32 cover[cover_len];
//     uint64 comps[2*comps_len]  (op1, op2 pairs)

struct OutHeader {
  uint32_t ncalls;
  uint32_t completed;  // all calls ran (no hang/short-circuit)
};

constexpr uint32_t kCallFlagExecuted = 1 << 0;
constexpr uint32_t kCallFlagFinished = 1 << 1;
constexpr uint32_t kCallFlagBlocked = 1 << 2;
constexpr uint32_t kCallFlagFaultInjected = 1 << 3;

struct CallResult {
  uint32_t call_index;  // position in the program
  uint32_t call_id;     // syscall table id
  uint32_t errno_;
  uint32_t flags;
  uint32_t signal_len;
  uint32_t cover_len;
  uint32_t comps_len;
  uint32_t reserved;
};

}  // namespace tz

#endif  // TZ_EXECUTOR_WIRE_H
