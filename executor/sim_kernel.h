// Simulated kernel: a deterministic in-process "OS" the executor can
// run programs against with no kernel, no VM and no risk.  It is the
// executable counterpart of the hermetic fake `test` target — where
// the reference validates its executor against a real kernel only
// (reference: executor runs syscalls for real; sys/test exists only on
// the Go side), the TPU build makes the whole execution stack testable
// end-to-end by giving the executor a fake kernel with *real fuzzing
// gradients*:
//
//   * coverage: each call deterministically yields edge PCs derived
//     from (call_id, coarse arg buckets), so novel argument shapes
//     discover novel edges;
//   * dataflow: values previously returned by calls become "live
//     handles"; passing one back yields bonus edges — rewarding
//     resource-correct programs the way real fd reuse does;
//   * comparisons: every arg is "compared" against per-call magic
//     constants, emitted as CMP records; matching a magic unlocks
//     extra edges — giving MutateWithHints a real signal to climb;
//   * crashes: a two-stage magic sequence triggers a synthetic oops on
//     stderr and abort — exercising crash detection, dedup and repro;
//   * fault injection: the nth simulated allocation fails when armed.

#ifndef TZ_EXECUTOR_SIM_KERNEL_H
#define TZ_EXECUTOR_SIM_KERNEL_H

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include <atomic>
#include <chrono>
#include <set>
#include <thread>

namespace tz {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// Coarse value bucket: collapses the argument space so coverage is a
// function of value *shape*, not exact value (log2 magnitude + low
// bits), mirroring how kernel branches discriminate sizes/flags.
inline uint32_t value_bucket(uint64_t v) {
  uint32_t log2 = 0;
  while (log2 < 63 && (v >> (log2 + 1))) log2++;
  return (log2 << 4) | (uint32_t)(v & 0xf);
}

struct SimCmp {
  uint64_t op1, op2;
};

struct SimResult {
  uint32_t errno_;
  uint64_t ret;
  bool fault_injected;
  bool crashed;
};

class SimKernel {
 public:
  explicit SimKernel(uint64_t pid) : pid_(pid) {}

  // ---- race window (collide-mode target) ----------------------------
  // Two deterministic call-id families form a provocable race: a
  // "prepare" call opens a short window on a handle, a "trigger" call
  // crashes iff it observes the window OPEN — which sequential
  // execution can never do (prepare closes the window before
  // returning), while collide mode's concurrent re-issue can.  These
  // calls touch ONLY the race_window_ atomic, so the executor runs
  // them without the global sim lock (the lock would serialize the
  // pair and make collide meaningless — VERDICT r1/r2 weak item).
  static constexpr uint32_t kRacePrepareTag = 5;
  static constexpr uint32_t kRaceTriggerTag = 9;

  static uint32_t race_tag(uint32_t call_id) {
    return (uint32_t)(splitmix64(call_id * 0x10001ull + 1) & 31);
  }
  static bool lockless(uint32_t call_id) {
    uint32_t t = race_tag(call_id);
    return t == kRacePrepareTag || t == kRaceTriggerTag;
  }

  // Lock-free execution path for the racy call families.  The window
  // is held open only on collide re-issues: sequential execution can
  // never observe it anyway, and an unconditional spin would tax
  // every 32nd sim call with 1.5ms of stall.
  SimResult exec_lockless(uint32_t call_id, const uint64_t* args, int nargs,
                          uint32_t* cov, int cov_max, int* cov_len,
                          bool hold_window) {
    SimResult res{};
    *cov_len = 0;
    uint64_t h = splitmix64(call_id * 0x10001ull + 1);
    if (*cov_len < cov_max) cov[(*cov_len)++] = (uint32_t)splitmix64(h);
    uint64_t key = (nargs > 0 ? args[0] : 0) | 1;
    if (race_tag(call_id) == kRacePrepareTag) {
      race_window_.store(key, std::memory_order_release);
      if (hold_window) {
        // Yielding wait, so the sibling thread gets scheduled even on
        // a throttled single-core box (wall-clock, not lock-clock).
        auto until = std::chrono::steady_clock::now() +
                     std::chrono::microseconds(1500);
        while (std::chrono::steady_clock::now() < until)
          std::this_thread::yield();
      }
      race_window_.store(0, std::memory_order_release);
      res.errno_ = 0;
    } else {
      if (race_window_.load(std::memory_order_acquire) == key) {
        fprintf(stderr,
                "BUG: sim-kernel: data race on handle 0x%llx in "
                "sim_call_%u\n"
                "Call Trace:\n sim_call_%u+0x%llx\n sim_race+0x22\n",
                (unsigned long long)key, call_id, call_id,
                (unsigned long long)(h & 0xfff));
        fflush(stderr);
        res.crashed = true;
        return res;
      }
      res.errno_ = 0;
    }
    return res;
  }

  // Arm fault injection: the nth (1-based) allocation from now fails.
  void arm_fault(uint64_t nth) {
    fault_armed_ = true;
    fault_left_ = nth;
  }
  // Called between programs so an armed-but-unfired fault (nth beyond
  // the call's allocation count) cannot leak into unrelated calls.
  void disarm_fault() {
    fault_armed_ = false;
    fault_left_ = 0;
  }

  // Execute one call. Appends edge PCs to cov (up to cov_max) and CMP
  // records to cmps (up to cmps_max); returns result.
  SimResult exec(uint32_t call_id, const uint64_t* args, int nargs,
                 uint32_t* cov, int cov_max, int* cov_len, SimCmp* cmps,
                 int cmps_max, int* cmps_len) {
    SimResult res{};
    *cov_len = 0;
    *cmps_len = 0;
    uint64_t h = splitmix64(call_id * 0x10001ull + 1);

    auto emit = [&](uint64_t seed) {
      if (*cov_len < cov_max) cov[(*cov_len)++] = (uint32_t)splitmix64(seed);
    };

    // entry edge: every call has one
    emit(h);

    int magic_hits = 0;
    int handle_hits = 0;
    for (int i = 0; i < nargs; i++) {
      uint64_t a = args[i];
      // branch on the coarse shape of the argument
      emit(h ^ splitmix64((uint64_t)i << 32 | value_bucket(a)));
      // the "kernel" compares the arg against a per-(call,arg) magic
      uint64_t magic = splitmix64(h + 0x1111 * (i + 1)) & 0xffffffffull;
      if (*cmps_len < cmps_max) cmps[(*cmps_len)++] = SimCmp{a, magic};
      if (a == magic) {
        magic_hits++;
        // unlocked path: edges others can't reach without the magic
        emit(h ^ splitmix64(0xabcd0000ull + i));
        emit(h ^ splitmix64(0xabcd1000ull + i + (magic & 0xff)));
      }
      if (handles_.count(a)) {
        handle_hits++;
        emit(h ^ splitmix64(0xfeed0000ull + i));  // valid-handle path
      }
    }

    // deeper state-dependent paths when dataflow is right
    if (handle_hits >= 2) emit(h ^ 0x10);
    if (handle_hits >= 1 && magic_hits >= 1) emit(h ^ 0x11);

    // simulated allocations: 1-3 per call; honored fault injection
    int allocs = 1 + (int)(h % 3);
    for (int i = 0; i < allocs; i++) {
      if (fault_armed_) {
        fault_left_--;
        if (fault_left_ == 0) {
          fault_armed_ = false;
          res.fault_injected = true;
          res.errno_ = 12;  // ENOMEM
          return res;
        }
      }
    }

    // two-stage crash trigger: arg0 and arg1 must both hit dedicated
    // crash magics on a "crashy" call (1 in 8 call ids)
    if ((h & 7) == 3 && nargs >= 2) {
      uint64_t c0 = splitmix64(h ^ 0xc0de0000ull) & 0xffffffffull;
      uint64_t c1 = splitmix64(h ^ 0xc0de0001ull) & 0xffffffffull;
      if (*cmps_len < cmps_max) cmps[(*cmps_len)++] = SimCmp{args[0], c0};
      if (args[0] == c0) {
        emit(h ^ 0xdead0);
        if (*cmps_len < cmps_max) cmps[(*cmps_len)++] = SimCmp{args[1], c1};
        if (args[1] == c1) {
          fprintf(stderr,
                  "BUG: sim-kernel: use-after-free in sim_call_%u\n"
                  "Call Trace:\n sim_call_%u+0x%llx\n sim_dispatch+0x11\n",
                  call_id, call_id, (unsigned long long)(h & 0xfff));
          fflush(stderr);
          res.crashed = true;
          return res;
        }
      }
    }

    // "ctor" calls (1 in 4) return a new live handle on success
    if ((h & 3) == 1) {
      uint64_t handle = 0x1000 + (handles_.size() * 4 + pid_) % 0xfffff;
      handles_.insert(handle);
      res.ret = handle;
      res.errno_ = 0;
    } else {
      // calls that want handles fail without them (EBADF-ish)
      bool wants_handle = (h & 3) == 2 && nargs > 0;
      if (wants_handle && handle_hits == 0) {
        res.errno_ = 9;  // EBADF
      } else {
        res.errno_ = 0;
        res.ret = 0;
      }
    }
    return res;
  }

 private:
  uint64_t pid_;
  std::set<uint64_t> handles_;
  bool fault_armed_ = false;
  uint64_t fault_left_ = 0;
  std::atomic<uint64_t> race_window_{0};
};

}  // namespace tz

#endif  // TZ_EXECUTOR_SIM_KERNEL_H
