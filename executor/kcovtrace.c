/* kcovtrace: strace-like per-command KCOV tracer.
 *
 * Runs a command under KCOV and prints every covered kernel PC —
 * quick answer to "which kernel code does this program reach?"
 * (reference: tools/kcovtrace/kcovtrace.c).
 *
 * Build: gcc -O2 -o kcovtrace kcovtrace.c
 * Usage: kcovtrace <command> [args...]
 */
#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define KCOV_INIT_TRACE _IOR('c', 1, unsigned long)
#define KCOV_ENABLE _IO('c', 100)
#define KCOV_DISABLE _IO('c', 101)
#define KCOV_TRACE_PC 0
#define COVER_SIZE (64 << 10)

int main(int argc, char** argv)
{
  if (argc < 2) {
    fprintf(stderr, "usage: kcovtrace <command> [args...]\n");
    return 1;
  }
  int fd = open("/sys/kernel/debug/kcov", O_RDWR);
  if (fd == -1) {
    perror("open /sys/kernel/debug/kcov");
    return 1;
  }
  if (ioctl(fd, KCOV_INIT_TRACE, COVER_SIZE)) {
    perror("KCOV_INIT_TRACE");
    return 1;
  }
  uint64_t* cover = (uint64_t*)mmap(NULL, COVER_SIZE * sizeof(uint64_t),
                                    PROT_READ | PROT_WRITE, MAP_SHARED,
                                    fd, 0);
  if (cover == MAP_FAILED) {
    perror("mmap");
    return 1;
  }
  pid_t pid = fork();
  if (pid < 0) {
    perror("fork");
    return 1;
  }
  if (pid == 0) {
    /* child: enable tracing for this task, exec the command */
    if (ioctl(fd, KCOV_ENABLE, KCOV_TRACE_PC)) {
      perror("KCOV_ENABLE");
      _exit(1);
    }
    __atomic_store_n(&cover[0], 0, __ATOMIC_RELAXED);
    execvp(argv[1], argv + 1);
    perror("execvp");
    _exit(1);
  }
  int status;
  waitpid(pid, &status, 0);
  uint64_t n = __atomic_load_n(&cover[0], __ATOMIC_RELAXED);
  if (n > COVER_SIZE - 1) n = COVER_SIZE - 1;
  for (uint64_t i = 0; i < n; i++)
    printf("0x%llx\n", (unsigned long long)cover[i + 1]);
  return WIFEXITED(status) ? WEXITSTATUS(status) : 1;
}
