// BSD-backend pseudo-syscall layer.
//
// The FreeBSD/NetBSD description trees declare no syz_* pseudo calls
// (sys/descriptions/freebsd, sys/descriptions/netbsd), so this layer
// is a clean ENOSYS fallback that keeps the dispatch contract of
// pseudo_linux.h's execute_pseudo: any pseudo NR that reaches a BSD
// executor answers -ENOSYS instead of being thrown at syscall(2)
// (where the 0x81000000 NR range would be meaningless).  Environment
// hooks are no-ops: no netns/TUN/cgroup analog is set up — the BSD
// sandbox story is the setuid drop in executor.cc's
// apply_sandbox_and_env (reference analog: executor/common_bsd.h,
// which is similarly thin next to common_linux.h).

#ifndef TZ_EXECUTOR_PSEUDO_BSD_H
#define TZ_EXECUTOR_PSEUDO_BSD_H

#if defined(TZ_BSD)

#include <errno.h>

namespace tz {

static long execute_pseudo(uint32_t nr, const uint64_t* args, int nargs) {
  (void)args;
  (void)nargs;
  debugf("pseudo: nr 0x%x unsupported on BSD backend\n", nr);
  return -ENOSYS;
}

static void pseudo_cleanup() {}
static void pseudo_parent_sweep() {}
static void pseudo_init_mount_root() {}

}  // namespace tz

#endif  // TZ_BSD
#endif  // TZ_EXECUTOR_PSEUDO_BSD_H
