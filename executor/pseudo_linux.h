// Linux-backend environment setup + syz_* pseudo-syscalls.
//
// Fills the role of the reference's common_linux.h environment layer:
// namespace sandbox (reference: common_linux.h:1375 sandbox_namespace),
// TUN-based packet injection (common_linux.h:332-560), cgroup setup
// (common_linux.h:1075-1170), loop-device images (syz_mount_image /
// syz_read_part_table), and the executor-implemented syz_* pseudo
// syscalls (common_linux.h:1041+), including a compact
// syz_kvm_setup_cpu (common_kvm_amd64.h).  Everything is best-effort:
// a kernel facility that is absent (no /dev/net/tun, no /dev/kvm, ro
// cgroupfs, no CAP_SYS_ADMIN) degrades to a debug note and ENOSYS/
// ENODEV for the calls that need it, never an executor failure —
// containers and CI hosts stay usable.
//
// This header is linux-only and included from executor.cc.

#ifndef TZ_EXECUTOR_PSEUDO_LINUX_H
#define TZ_EXECUTOR_PSEUDO_LINUX_H

#if defined(__linux__) && !defined(TZ_OS_FREEBSD)

#include <arpa/inet.h>
#include <fcntl.h>
#include <linux/if.h>
#include <linux/if_tun.h>
#include <linux/loop.h>
#include <net/if_arp.h>
#include <sched.h>
#include <stdio.h>
#include <string.h>
#include <sys/ioctl.h>
#include <sys/mount.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/sysmacros.h>
#include <sys/types.h>
#include <unistd.h>

#include <mutex>

namespace tz {

// Included from executor.cc after its guest()/debugf() definitions;
// both are visible here.

// ---- namespace sandbox ----------------------------------------------

static bool write_file_str(const char* path, const char* data) {
  int fd = open(path, O_WRONLY);
  if (fd < 0) return false;
  ssize_t len = (ssize_t)strlen(data);
  bool ok = write(fd, data, len) == len;
  close(fd);
  return ok;
}

// unshare into fresh user/mount/net/ipc/uts namespaces and map the
// current uid to root inside.  Each step is best-effort: partial
// isolation is still isolation (reference: common_linux.h:1375-1460
// does this with clone flags at process creation; we sandbox the
// already-running fork-server, which the fork-per-program children
// then inherit).
static void sandbox_namespace() {
  uid_t uid = geteuid();
  gid_t gid = getegid();
  if (unshare(CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET |
              CLONE_NEWIPC | CLONE_NEWUTS)) {
    // no user namespaces (kernel.unprivileged_userns_clone=0 or
    // seccomp): try without NEWUSER (works when already root)
    if (unshare(CLONE_NEWNS | CLONE_NEWNET | CLONE_NEWIPC |
                CLONE_NEWUTS)) {
      debugf("sandbox: unshare failed: %d\n", errno);
      return;
    }
  } else {
    char buf[64];
    write_file_str("/proc/self/setgroups", "deny");
    snprintf(buf, sizeof(buf), "0 %d 1", (int)uid);
    if (!write_file_str("/proc/self/uid_map", buf))
      debugf("sandbox: uid_map write failed: %d\n", errno);
    snprintf(buf, sizeof(buf), "0 %d 1", (int)gid);
    if (!write_file_str("/proc/self/gid_map", buf))
      debugf("sandbox: gid_map write failed: %d\n", errno);
  }
  // private mount propagation + a scratch tmpfs workdir
  if (mount(nullptr, "/", nullptr, MS_REC | MS_PRIVATE, nullptr))
    debugf("sandbox: MS_PRIVATE remount failed: %d\n", errno);
  if (mount("none", "/tmp", "tmpfs", 0, nullptr) == 0)
    (void)chdir("/tmp");
  // bring up loopback in the fresh netns so sockets work
  int sock = socket(AF_INET, SOCK_DGRAM, 0);
  if (sock >= 0) {
    struct ifreq ifr;
    memset(&ifr, 0, sizeof(ifr));
    strncpy(ifr.ifr_name, "lo", IFNAMSIZ - 1);
    if (ioctl(sock, SIOCGIFFLAGS, &ifr) == 0) {
      ifr.ifr_flags |= IFF_UP | IFF_RUNNING;
      ioctl(sock, SIOCSIFFLAGS, &ifr);
    }
    close(sock);
  }
}

// ---- TUN packet injection -------------------------------------------
// A tap device gives programs an L2 injection point:
// syz_emit_ethernet writes raw frames, syz_extract_tcp_res reads the
// kernel's reply to learn live seq/ack numbers
// (reference: common_linux.h:332-560, sys/linux/vnet.txt).

static int g_tun_fd = -1;

static void setup_tun(uint64_t pid) {
  // Per-proc addressing is one byte wide (172.20.<pid>.1, MAC byte
  // 5): the mask keeps the octet valid for pids >255.  Procs 256
  // apart therefore share a subnet — accepted, since proc counts
  // stay far below 256 (reference uses the same single-octet scheme).
  pid &= 0xff;
  g_tun_fd = open("/dev/net/tun", O_RDWR | O_NONBLOCK);
  if (g_tun_fd < 0) {
    debugf("tun: /dev/net/tun unavailable: %d\n", errno);
    return;
  }
  struct ifreq ifr;
  memset(&ifr, 0, sizeof(ifr));
  snprintf(ifr.ifr_name, IFNAMSIZ, "tz_tun%d", (int)pid);
  ifr.ifr_flags = IFF_TAP | IFF_NO_PI;
  if (ioctl(g_tun_fd, TUNSETIFF, &ifr)) {
    debugf("tun: TUNSETIFF failed: %d\n", errno);
    close(g_tun_fd);
    g_tun_fd = -1;
    return;
  }
  int sock = socket(AF_INET, SOCK_DGRAM, 0);
  if (sock >= 0) {
    // deterministic MAC (aa:aa:aa:aa:aa:pid) + 172.20.<pid>.1/24, up
    struct ifreq ifr2;
    memset(&ifr2, 0, sizeof(ifr2));
    memcpy(ifr2.ifr_name, ifr.ifr_name, IFNAMSIZ);
    ifr2.ifr_hwaddr.sa_family = ARPHRD_ETHER;
    memset(ifr2.ifr_hwaddr.sa_data, 0xaa, 6);
    ifr2.ifr_hwaddr.sa_data[5] = (char)pid;
    ioctl(sock, SIOCSIFHWADDR, &ifr2);
    auto* sin = (struct sockaddr_in*)&ifr2.ifr_addr;
    sin->sin_family = AF_INET;
    sin->sin_addr.s_addr = htonl(0xAC140001 | ((uint32_t)pid << 8));
    ioctl(sock, SIOCSIFADDR, &ifr2);
    ioctl(sock, SIOCGIFFLAGS, &ifr2);
    ifr2.ifr_flags |= IFF_UP | IFF_RUNNING;
    ioctl(sock, SIOCSIFFLAGS, &ifr2);
    close(sock);
  }
  debugf("tun: %s ready fd=%d\n", ifr.ifr_name, g_tun_fd);
}

// ---- cgroups --------------------------------------------------------

static void setup_cgroups(uint64_t pid) {
  // one subtree per proc under whichever cgroup fs is writable
  // (reference: common_linux.h:1075-1170 creates /syzcgroup/{unified,
  // cpu,net}; we reuse the host mount which is what containers allow)
  const char* roots[] = {"/sys/fs/cgroup", "/sys/fs/cgroup/unified"};
  for (const char* root : roots) {
    char dir[128];
    snprintf(dir, sizeof(dir), "%s/tz%d", root, (int)pid);
    if (mkdir(dir, 0777) == 0 || errno == EEXIST) {
      char procs[160];
      snprintf(procs, sizeof(procs), "%s/cgroup.procs", dir);
      char self[32];
      snprintf(self, sizeof(self), "%d", (int)getpid());
      if (write_file_str(procs, self)) {
        debugf("cgroups: joined %s\n", dir);
        return;
      }
    }
  }
  debugf("cgroups: no writable cgroup fs (ok)\n");
}

// ---- guest strings --------------------------------------------------

static void read_guest_str(uint64_t addr, char* out, size_t cap) {
  // Bounded by the arena end: a mutated string whose NUL was
  // overwritten near the arena edge must fail THIS call (empty path →
  // ENOENT), not failf-exit the whole fork server via guest().
  out[0] = 0;
  if (addr == 0 || addr < g_arena_base ||
      addr >= g_arena_base + g_arena_size)
    return;
  uint64_t remain = g_arena_base + g_arena_size - addr;
  size_t max = cap - 1;
  if (remain < (uint64_t)max) max = (size_t)remain;
  const char* src = (const char*)(g_arena + (addr - g_arena_base));
  size_t i = 0;
  for (; i < max && src[i]; i++) out[i] = src[i];
  out[i] = 0;
}

// ---- loop devices ---------------------------------------------------

static int loop_attach(int img_fd) {
  int ctl = open("/dev/loop-control", O_RDWR);
  if (ctl < 0) return -1;
  int idx = ioctl(ctl, LOOP_CTL_GET_FREE);
  close(ctl);
  if (idx < 0) return -1;
  char path[32];
  snprintf(path, sizeof(path), "/dev/loop%d", idx);
  int lfd = open(path, O_RDWR);
  if (lfd < 0) return -1;
  if (ioctl(lfd, LOOP_SET_FD, img_fd)) {
    close(lfd);
    return -1;
  }
  return lfd;
}

static void loop_detach(int lfd) {
  if (lfd >= 0) {
    ioctl(lfd, LOOP_CLR_FD, 0);
    close(lfd);
  }
}

// build a temp image file from (offset, size, data-ptr) segments
struct ImgSegment {   // guest layout used by syz_mount_image/
  uint64_t addr;      // read_part_table: {data ptr, size, offset}
  uint64_t size;
  uint64_t offset;
};

static int build_image(uint64_t size, uint64_t nsegs, uint64_t segs_addr) {
  char tmpl[] = "/tmp/tz_img_XXXXXX";
  int fd = mkstemp(tmpl);
  if (fd < 0) return -1;
  unlink(tmpl);
  if (size > (64ull << 20)) size = 64ull << 20;
  if (ftruncate(fd, (off_t)size)) {
    close(fd);
    return -1;
  }
  if (nsegs > 64) nsegs = 64;
  for (uint64_t i = 0; i < nsegs; i++) {
    ImgSegment seg;
    memcpy(&seg, guest(segs_addr + i * sizeof(seg), sizeof(seg)),
           sizeof(seg));
    if (seg.size > (1 << 20) || seg.offset > size) continue;
    if (seg.offset + seg.size > size) seg.size = size - seg.offset;
    if (pwrite(fd, guest(seg.addr, seg.size), seg.size,
               (off_t)seg.offset) < 0)
      debugf("image: segment write failed: %d\n", errno);
  }
  return fd;
}

// ---- KVM ------------------------------------------------------------
// Compact syz_kvm_setup_cpu: map the program-provided user memory into
// the VM, install a minimal real-mode or long-mode register state, and
// copy the text blob to the entry point (reference:
// executor/common_kvm_amd64.h + kvm.S do a far more elaborate staging;
// the ioctl-level contract — vmfd/cpufd resources set up so a
// following ioctl$KVM_RUN executes the text — is the same).

#if defined(__has_include)
#if __has_include(<linux/kvm.h>)
#include <linux/kvm.h>
#define TZ_HAVE_KVM 1
#endif
#endif

#ifdef TZ_HAVE_KVM

struct KvmTextSeg {  // guest layout of the text array arg
  uint64_t typ;      // 0 = real16, 1 = prot32, 2 = long64
  uint64_t text_addr;
  uint64_t text_len;
};

static constexpr uint64_t kKvmGuestMemSize = 24 << 12;  // 24 pages

// Real-mode trampoline executed by the guest itself: lgdt/lidt from
// guest descriptor tables, CR4.PAE, CR3, EFER.LME (wrmsr), CR0.PG|PE,
// far jump through the 64-bit GDT descriptor into the user text at
// 0x8000 (the real->long staging the reference does in kvm.S).
static const uint8_t kKvmTramp[] = {
    0xfa,                                      // cli
    0x66, 0x0f, 0x01, 0x16, 0x80, 0x70,        // lgdtl [0x7080]
    0x66, 0x0f, 0x01, 0x1e, 0x88, 0x70,        // lidtl [0x7088]
    0x0f, 0x20, 0xe0,                          // mov eax, cr4
    0x0c, 0x20,                                // or  al, 0x20 (PAE)
    0x0f, 0x22, 0xe0,                          // mov cr4, eax
    0x66, 0xb8, 0x00, 0x30, 0x00, 0x00,        // mov eax, 0x3000
    0x0f, 0x22, 0xd8,                          // mov cr3, eax
    0x66, 0xb9, 0x80, 0x00, 0x00, 0xc0,        // mov ecx, 0xc0000080
    0x0f, 0x32,                                // rdmsr
    0x66, 0x0d, 0x00, 0x01, 0x00, 0x00,        // or  eax, 0x100 (LME)
    0x0f, 0x30,                                // wrmsr
    0x0f, 0x20, 0xc0,                          // mov eax, cr0
    0x66, 0x0d, 0x01, 0x00, 0x00, 0x80,        // or  eax, PG|PE
    0x0f, 0x22, 0xc0,                          // mov cr0, eax
    0x66, 0xea, 0x00, 0x78, 0x00, 0x00,        // ljmpl 0x08:0x7800
    0x08, 0x00,                                //   (long-mode prologue)
};

// Stage the full long-mode bring-up image into a guest-memory buffer.
// Pure memory writes — no KVM fds — so tests can verify every
// descriptor table byte-exactly without /dev/kvm (the
// --dump-kvm-stage CLI below drives exactly this function).
//
// Guest layout (reference: executor/common_kvm_amd64.h:1-812 + kvm.S
// stage the same real->protected->long transition with their own
// table layout):
//   0x1000 IDT: 256 x 16-byte present interrupt gates -> ISR @0x7F00
//   0x2000 GDT: null | 0x08 code64 | 0x10 data | 0x18 code32 |
//               0x20 TSS64 desc (16b) | 0x30 code16 | 0x38 data16 |
//               0x40 user code64 (DPL3) | 0x48 user data (DPL3)
//   0x3000 PML4  0x4000 PDPT  0x5000 PD (4 x 2MB identity = 8MB)
//   0x6000 TSS64 (104 bytes: rsp0=0xE000, IST1=0xE800)
//   0x7000 real-mode trampoline + GDTR/IDTR operands @0x7080/0x7088
//   0x7800 long-mode prologue: ltr, data-segment loads, jmp text
//   0x7F00 ISR stub (hlt loop)
//   0x8000 user text        0xF000 initial stack top
static void kvm_stage_long(uint8_t* host_mem, const uint8_t* text,
                           uint64_t text_len) {
  auto w64 = [&](uint64_t gpa, uint64_t val) {
    memcpy(host_mem + gpa, &val, 8);
  };
  // page tables: identity-map 8MB through 4 2MB PD entries
  w64(0x3000, 0x4000 | 3);
  w64(0x4000, 0x5000 | 3);
  for (uint64_t i = 0; i < 4; i++)
    w64(0x5000 + 8 * i, (i << 21) | 0x83);  // present|rw|ps
  // GDT
  w64(0x2000 + 0x00, 0);
  w64(0x2000 + 0x08, 0x00209A0000000000ull);  // L=1 kernel code
  w64(0x2000 + 0x10, 0x00CF92000000FFFFull);  // flat data
  w64(0x2000 + 0x18, 0x00CF9A000000FFFFull);  // 32-bit code
  // 64-bit TSS descriptor (16 bytes): base 0x6000, limit 0x67, type 9
  w64(0x2000 + 0x20, 0x0000890060000067ull);
  w64(0x2000 + 0x28, 0);
  w64(0x2000 + 0x30, 0x00009A000000FFFFull);  // 16-bit code
  w64(0x2000 + 0x38, 0x000092000000FFFFull);  // 16-bit data
  w64(0x2000 + 0x40, 0x0020FA0000000000ull);  // user code64 DPL3
  w64(0x2000 + 0x48, 0x00CFF2000000FFFFull);  // user data DPL3
  // TSS: rsp0 at +4, IST1 at +36, iomap base = sizeof(tss)
  memset(host_mem + 0x6000, 0, 0x68);
  w64(0x6000 + 4, 0xE000);
  w64(0x6000 + 36, 0xE800);
  host_mem[0x6000 + 102] = 0x68;
  // IDT: every vector a present DPL0 interrupt gate to the ISR stub
  for (int v = 0; v < 256; v++) {
    uint8_t* g = host_mem + 0x1000 + 16 * v;
    memset(g, 0, 16);
    g[0] = 0x00;  // offset 15:0 = 0x7F00
    g[1] = 0x7F;
    g[2] = 0x08;  // selector: kernel code64
    g[3] = 0x00;
    g[4] = 0x00;  // IST 0
    g[5] = 0x8E;  // present, type E (interrupt gate)
  }
  // ISR stub: hlt; jmp $-1 (vcpu parks on any exception/interrupt)
  host_mem[0x7F00] = 0xF4;
  host_mem[0x7F01] = 0xEB;
  host_mem[0x7F02] = 0xFD;
  // user text
  memset(host_mem + 0x8000, 0xf4, 0x1000);
  memcpy(host_mem + 0x8000, text, text_len);
  // real-mode trampoline + its GDTR/IDTR operands
  memcpy(host_mem + 0x7000, kKvmTramp, sizeof(kKvmTramp));
  host_mem[0x7080] = 0x4F;  // GDT limit: through user data
  host_mem[0x7081] = 0x00;
  uint32_t gdt_base = 0x2000;
  memcpy(host_mem + 0x7082, &gdt_base, 4);
  host_mem[0x7088] = 0xFF;  // IDT limit: full 256 gates
  host_mem[0x7089] = 0x0F;
  uint32_t idt_base = 0x1000;
  memcpy(host_mem + 0x708a, &idt_base, 4);
  // long-mode prologue at 0x7800 (the trampoline far-jumps here):
  //   mov ax, 0x20 ; ltr ax        -- hardware task register
  //   mov ax, 0x10 ; mov ds/es/ss/fs/gs, ax
  //   mov rsp, 0xF000
  //   mov rax, 0x8000 ; jmp rax    -- into the user text
  static const uint8_t prologue[] = {
      0x66, 0xb8, 0x20, 0x00,              // mov ax, 0x20
      0x0f, 0x00, 0xd8,                    // ltr ax
      0x66, 0xb8, 0x10, 0x00,              // mov ax, 0x10
      0x8e, 0xd8, 0x8e, 0xc0, 0x8e, 0xd0,  // mov ds/es/ss, ax
      0x8e, 0xe0, 0x8e, 0xe8,              // mov fs/gs, ax
      0x48, 0xc7, 0xc4, 0x00, 0xf0, 0x00, 0x00,  // mov rsp, 0xf000
      0x48, 0xc7, 0xc0, 0x00, 0x80, 0x00, 0x00,  // mov rax, 0x8000
      0xff, 0xe0,                          // jmp rax
  };
  memcpy(host_mem + 0x7800, prologue, sizeof(prologue));
}

static long kvm_setup_cpu(int vmfd, int cpufd, uint64_t usermem,
                          uint64_t text_addr, uint64_t ntext,
                          uint64_t flags) {
  (void)flags;
  if (ntext == 0) return -EINVAL;
  KvmTextSeg seg;
  memcpy(&seg, guest(text_addr, sizeof(seg)), sizeof(seg));
  if (seg.text_len > 0x1000) seg.text_len = 0x1000;

  struct kvm_userspace_memory_region mem;
  memset(&mem, 0, sizeof(mem));
  mem.slot = 0;
  mem.guest_phys_addr = 0;
  mem.memory_size = kKvmGuestMemSize;
  mem.userspace_addr = (uint64_t)(uintptr_t)guest(usermem,
                                                  kKvmGuestMemSize);
  if (ioctl(vmfd, KVM_SET_USER_MEMORY_REGION, &mem))
    return -errno;

  // text at guest phys 0x1000
  uint8_t* host_mem = guest(usermem, kKvmGuestMemSize);
  memset(host_mem, 0xf4, 0x2000);  // hlt-fill the first pages
  memcpy(host_mem + 0x1000, guest(seg.text_addr, seg.text_len),
         seg.text_len);

  struct kvm_sregs sregs;
  if (ioctl(cpufd, KVM_GET_SREGS, &sregs))
    return -errno;
  struct kvm_regs regs;
  memset(&regs, 0, sizeof(regs));
  regs.rflags = 2;
  if (seg.typ == 2) {
    // Long mode via REAL staging: the vcpu starts in real mode at
    // the trampoline, which performs the architectural bring-up
    // itself (lgdt/lidt, CR4.PAE, CR3, EFER.LME, CR0.PG|PE), far-
    // jumps into the long-mode prologue (ltr + segment loads), and
    // lands in the user text.  All tables staged by kvm_stage_long.
    kvm_stage_long(host_mem, guest(seg.text_addr, seg.text_len),
                   seg.text_len);
    // real-mode start at the trampoline; all data segs base 0 so the
    // lgdt/lidt disp16 operands address guest-physical directly
    sregs.cs.base = 0x7000;
    sregs.cs.selector = 0x700;
    sregs.ds.base = sregs.es.base = sregs.ss.base = 0;
    sregs.ds.selector = sregs.es.selector = sregs.ss.selector = 0;
    regs.rip = 0;
    regs.rsp = 0xf000;
  } else if (seg.typ == 1) {
    // protected 32-bit, flat segments, no paging
    sregs.cr0 |= 1;  // PE
    struct kvm_segment cs;
    memset(&cs, 0, sizeof(cs));
    cs.base = 0;
    cs.limit = 0xffffffff;
    cs.selector = 0x8;
    cs.type = 11;
    cs.present = 1;
    cs.s = 1;
    cs.db = 1;
    cs.g = 1;
    sregs.cs = cs;
    struct kvm_segment ds = cs;
    ds.type = 3;
    ds.selector = 0x10;
    sregs.ds = sregs.es = sregs.ss = ds;
    regs.rip = 0x1000;
    regs.rsp = 0x2000;
  } else {
    // real mode: run text at 0100:0000 (= phys 0x1000)
    sregs.cs.base = 0x1000;
    sregs.cs.selector = 0x100;
    regs.rip = 0;
    regs.rsp = 0xf000;
  }
  if (ioctl(cpufd, KVM_SET_SREGS, &sregs))
    return -errno;
  if (ioctl(cpufd, KVM_SET_REGS, &regs))
    return -errno;
  return 0;
}
#else
static long kvm_setup_cpu(int, int, uint64_t, uint64_t, uint64_t,
                          uint64_t) {
  return -ENOSYS;  // no <linux/kvm.h> on this build host
}
#endif  // TZ_HAVE_KVM

// ---- pseudo-syscall dispatch ----------------------------------------

static long pseudo_open_dev(uint64_t name_addr, uint64_t id,
                            uint64_t flags) {
  // '#' in the path is replaced by the id (reference semantics:
  // common_linux.h syz_open_dev)
  char path[256];
  read_guest_str(name_addr, path, sizeof(path) - 16);
  char final_path[272];
  char* hash = strchr(path, '#');
  if (hash != nullptr) {
    *hash = 0;
    snprintf(final_path, sizeof(final_path), "%s%d%s", path, (int)id,
             hash + 1);
  } else {
    snprintf(final_path, sizeof(final_path), "%s", path);
  }
  long fd = open(final_path, (int)flags, 0666);
  return fd < 0 ? -errno : fd;
}

static long pseudo_open_procfs(uint64_t pid, uint64_t file_addr) {
  char file[128];
  read_guest_str(file_addr, file, sizeof(file));
  char path[160];
  if (pid == 0)
    snprintf(path, sizeof(path), "/proc/self/%s", file);
  else
    snprintf(path, sizeof(path), "/proc/%d/%s", (int)pid, file);
  long fd = open(path, O_RDWR);
  if (fd < 0) fd = open(path, O_RDONLY);
  return fd < 0 ? -errno : fd;
}

static long pseudo_open_pts(uint64_t master_fd, uint64_t flags) {
  int ptyno = 0;
  if (ioctl((int)master_fd, TIOCGPTN, &ptyno))
    return -errno;
  char path[32];
  snprintf(path, sizeof(path), "/dev/pts/%d", ptyno);
  long fd = open(path, (int)flags);
  return fd < 0 ? -errno : fd;
}

static long pseudo_emit_ethernet(uint64_t len, uint64_t packet_addr) {
  if (g_tun_fd < 0) return -ENODEV;
  if (len > (1 << 16)) return -EINVAL;
  ssize_t w = write(g_tun_fd, guest(packet_addr, len), len);
  return w < 0 ? -errno : w;
}

struct TcpResults {  // guest layout of syz_extract_tcp_res result
  uint32_t seq;
  uint32_t ack;
};

static long pseudo_extract_tcp_res(uint64_t res_addr, uint64_t seq_inc,
                                   uint64_t ack_inc) {
  if (g_tun_fd < 0) return -ENODEV;
  uint8_t pkt[2048];
  ssize_t n = read(g_tun_fd, pkt, sizeof(pkt));
  if (n < 0) return -errno;
  // eth(14) + ipv4(ihl) + tcp: pull seq/ack out of the reply
  if (n < 14 + 20 + 20) return -EBADMSG;
  uint16_t ethertype = (uint16_t)((pkt[12] << 8) | pkt[13]);
  int ip_off = 14;
  if (ethertype != 0x0800) return -EBADMSG;
  int ihl = (pkt[ip_off] & 0xf) * 4;
  if (pkt[ip_off + 9] != 6 /*TCP*/ || n < ip_off + ihl + 20)
    return -EBADMSG;
  int tcp = ip_off + ihl;
  TcpResults res;
  memcpy(&res.seq, pkt + tcp + 4, 4);
  memcpy(&res.ack, pkt + tcp + 8, 4);
  res.seq = htonl(ntohl(res.seq) + (uint32_t)seq_inc);
  res.ack = htonl(ntohl(res.ack) + (uint32_t)ack_inc);
  memcpy(guest(res_addr, sizeof(res)), &res, sizeof(res));
  return 0;
}

static long pseudo_genetlink_family(uint64_t name_addr) {
  // generic-netlink CTRL_CMD_GETFAMILY by name
  int sock = socket(AF_NETLINK, SOCK_RAW, 16 /*NETLINK_GENERIC*/);
  if (sock < 0) return -errno;
  char name[64];
  read_guest_str(name_addr, name, sizeof(name));
  struct {
    uint32_t len;
    uint16_t type, flags;
    uint32_t seq, pid;
    uint8_t cmd, version;
    uint16_t reserved;
    uint16_t attr_len, attr_type;
    char attr[64];
  } __attribute__((packed)) req;
  memset(&req, 0, sizeof(req));
  req.type = 0x10;  // GENL_ID_CTRL
  req.flags = 1;    // NLM_F_REQUEST
  req.cmd = 3;      // CTRL_CMD_GETFAMILY
  req.version = 1;
  req.attr_type = 2;  // CTRL_ATTR_FAMILY_NAME
  size_t name_len = strlen(name) + 1;
  memcpy(req.attr, name, name_len);
  req.attr_len = (uint16_t)(4 + name_len);
  req.len = (uint32_t)(20 + ((req.attr_len + 3) & ~3u));
  long ret = -1;
  if (send(sock, &req, req.len, 0) >= 0) {
    uint8_t buf[4096];
    ssize_t got = recv(sock, buf, sizeof(buf), 0);
    // walk attrs of the reply genlmsg for CTRL_ATTR_FAMILY_ID (1)
    if (got >= 24) {
      size_t off = 20;
      while (off + 4 <= (size_t)got) {
        uint16_t alen, atype;
        memcpy(&alen, buf + off, 2);
        memcpy(&atype, buf + off + 2, 2);
        if (alen < 4) break;
        if (atype == 1 && alen >= 6) {
          uint16_t id;
          memcpy(&id, buf + off + 4, 2);
          ret = id;
          break;
        }
        off += (alen + 3) & ~3u;
      }
    }
  }
  int saved = errno;
  close(sock);
  return ret >= 0 ? ret : -(saved ? saved : ENOENT);
}

// Mounts made by syz_mount_image within the current program; torn
// down by pseudo_cleanup() at end-of-program (the reference unmounts
// between programs via its per-program namespace teardown,
// common_linux.h remove_dir; we unmount explicitly because the
// fork-server shares one mount namespace with its children).  All
// mount points live under a per-proc root so the PARENT of a
// fork-per-program child can sweep stragglers even when the child
// died (exit_group mid-program, timeout SIGKILL) before its own
// pseudo_cleanup ran — child-local bookkeeping dies with the child,
// the mount namespace does not.  Calls run on worker-pool threads, so
// the registry is mutex-guarded.
static constexpr int kMaxMounts = 8;
static char g_mounts[kMaxMounts][160];
static int g_nmounts = 0;
static std::mutex g_mounts_mu;
static char g_mount_root[64];

// Initialized in the fork SERVER before any program runs, so parent
// and every child agree on the same root path.  The process also
// chdirs into the root: programs mount at relative paths ("./file0")
// and then operate on them by the same relative path, so the mount
// point they see and the confined path the parent sweeps are the same
// directory (the reference gives each proc its own cwd the same way).
static void pseudo_init_mount_root() {
  snprintf(g_mount_root, sizeof(g_mount_root), "/tmp/tz_mnt_%d",
           (int)getpid());
  mkdir(g_mount_root, 0777);
  if (chdir(g_mount_root))
    debugf("chdir %s failed: %d\n", g_mount_root, errno);
}

static const char* mount_root() {
  if (!g_mount_root[0]) pseudo_init_mount_root();  // non-fork path
  return g_mount_root;
}

// Register a successful mount for end-of-program teardown; returns
// false when the table is full.
static bool register_mount(const char* dir) {
  std::lock_guard<std::mutex> lk(g_mounts_mu);
  if (g_nmounts >= kMaxMounts) return false;
  snprintf(g_mounts[g_nmounts++], sizeof(g_mounts[0]), "%s", dir);
  return true;
}

static long pseudo_mount_image(uint64_t fs_addr, uint64_t dir_addr,
                               uint64_t size, uint64_t nsegs,
                               uint64_t segs_addr, uint64_t flags,
                               uint64_t opts_addr) {
  char fs[64], reqdir[64], dir[160], opts[256];
  read_guest_str(fs_addr, fs, sizeof(fs));
  read_guest_str(dir_addr, reqdir, sizeof(reqdir));
  read_guest_str(opts_addr, opts, sizeof(opts));
  // confine the mount point under the per-proc root: use only the
  // basename of the requested dir
  const char* base = strrchr(reqdir, '/');
  base = base ? base + 1 : reqdir;
  snprintf(dir, sizeof(dir), "%s/%s", mount_root(),
           base[0] ? base : "m");
  int img = build_image(size, nsegs, segs_addr);
  if (img < 0) return -errno;
  int lfd = loop_attach(img);
  close(img);
  if (lfd < 0) return -ENODEV;
  // AUTOCLEAR: the kernel releases the loop device when its last user
  // (the mount, or our fd below) goes away — no leak on any path.
  struct loop_info64 info;
  memset(&info, 0, sizeof(info));
  long res = -EINVAL;
  if (ioctl(lfd, LOOP_GET_STATUS64, &info) == 0) {
    info.lo_flags |= LO_FLAGS_AUTOCLEAR;
    ioctl(lfd, LOOP_SET_STATUS64, &info);
    mkdir(dir, 0777);
    char ldev[32];
    snprintf(ldev, sizeof(ldev), "/dev/loop%d", (int)info.lo_number);
    res = mount(ldev, dir, fs, flags, opts[0] ? opts : nullptr);
    if (res < 0) res = -errno;
  } else {
    res = -errno;
    // AUTOCLEAR was never set: detach explicitly or the loop device
    // (and its unlinked backing file) leaks for the rest of the run.
    ioctl(lfd, LOOP_CLR_FD, 0);
  }
  close(lfd);  // mount (if any) holds the loop device from here
  if (res < 0) return res;
  // register for end-of-program unmount; hand back an fd to the root
  // so the program can operate on the mounted fs
  if (!register_mount(dir)) {
    umount2(dir, MNT_DETACH);
    return -EMFILE;
  }
  long dfd = open(dir, O_RDONLY | O_DIRECTORY);
  return dfd < 0 ? -errno : dfd;
}

// end-of-program teardown (called from execute_program)
static void pseudo_cleanup() {
  std::lock_guard<std::mutex> lk(g_mounts_mu);
  for (int i = g_nmounts - 1; i >= 0; i--)
    if (umount2(g_mounts[i], MNT_DETACH))
      debugf("umount %s failed: %d\n", g_mounts[i], errno);
  g_nmounts = 0;
}

// Parent-side sweep after reaping a fork-per-program child: unmount
// anything still mounted under the per-proc root (the child's own
// registry died with it).
static void pseudo_parent_sweep() {
  const char* root = mount_root();
  size_t rootlen = strlen(root);
  for (int pass = 0; pass < 4; pass++) {
    FILE* f = fopen("/proc/self/mounts", "r");
    if (f == nullptr) return;
    char line[512];
    bool any = false;
    while (fgets(line, sizeof(line), f)) {
      // format: dev mountpoint fstype opts ...
      char* sp1 = strchr(line, ' ');
      if (sp1 == nullptr) continue;
      char* mp = sp1 + 1;
      char* sp2 = strchr(mp, ' ');
      if (sp2 == nullptr) continue;
      *sp2 = 0;
      // path-boundary match: /tmp/tz_mnt_12 must not sweep
      // /tmp/tz_mnt_123's live mounts
      if (strncmp(mp, root, rootlen) == 0 &&
          (mp[rootlen] == '/' || mp[rootlen] == 0)) {
        if (umount2(mp, MNT_DETACH) == 0) any = true;
      }
    }
    fclose(f);
    if (!any) return;  // nothing (left) to do
  }
}

// syz_init_net_socket: create a socket inside the INIT network
// namespace — some families (bluetooth HCI/SCO/L2CAP) refuse to
// exist in the per-proc sandbox netns.  Implementation differs from
// the reference's pre-opened-fd scheme (common_linux.h kInitNetNsFd):
// we enter /proc/1/ns/net for the one socket() call and hop back.
// Requires CAP_SYS_ADMIN in the init userns; degrades to a plain
// socket() when the hop fails (still a valid socket for fuzzing).
static long pseudo_init_net_socket(uint64_t family, uint64_t type,
                                   uint64_t proto) {
  int self_ns = open("/proc/self/ns/net", O_RDONLY);
  int init_ns = open("/proc/1/ns/net", O_RDONLY);
  bool hopped = false;
  if (self_ns >= 0 && init_ns >= 0 && setns(init_ns, CLONE_NEWNET) == 0)
    hopped = true;
  long fd = socket((int)family, (int)type, (int)proto);
  long err = fd < 0 ? errno : 0;
  if (hopped && setns(self_ns, CLONE_NEWNET))
    debugf("init_net_socket: failed to return to proc netns: %d\n",
           errno);
  if (self_ns >= 0) close(self_ns);
  if (init_ns >= 0) close(init_ns);
  return fd < 0 ? -err : fd;
}

// Build the fuse mount option string shared by both fuse mounts.
// mode mixes rootmode type bits with option bits 1/2 (the kernel
// wants rootmode as octal file-type bits; 1 and 2 select the
// default_permissions / allow_other options).
static void fuse_opts(char* buf, size_t cap, int fd, uint64_t mode,
                      uint64_t uid, uint64_t gid, uint64_t maxread,
                      uint64_t blksize) {
  size_t n = (size_t)snprintf(
      buf, cap, "fd=%d,user_id=%lu,group_id=%lu,rootmode=0%o", fd,
      (unsigned long)uid, (unsigned long)gid,
      (unsigned)mode & ~3u);
  if (maxread && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",max_read=%lu",
                          (unsigned long)maxread);
  if (blksize && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",blksize=%lu",
                          (unsigned long)blksize);
  if ((mode & 1) && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",default_permissions");
  if ((mode & 2) && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",allow_other");
}

// Confine a caller-supplied path under the per-proc root (basename
// only); optionally mkdir it (mount targets yes, device nodes no).
static void confine_mount_dir(uint64_t dir_addr, char* dir,
                              size_t cap, bool make_dir = true) {
  char reqdir[64];
  read_guest_str(dir_addr, reqdir, sizeof(reqdir));
  const char* base = strrchr(reqdir, '/');
  base = base ? base + 1 : reqdir;
  snprintf(dir, cap, "%s/%s", mount_root(), base[0] ? base : "m");
  if (make_dir) mkdir(dir, 0777);
}

// syz_fuse_mount: open /dev/fuse and mount a filesystem driven by
// that fd.  The mount is attempted best-effort — the fd alone is
// useful to the fuzzer (reads pending requests, FUSE_DEV_IOC_CLONE,
// write$fuse replies), matching reference behavior
// (executor/common_linux.h syz_fuse_mount: "Ignore errors").
static long pseudo_fuse_mount(uint64_t target_addr, uint64_t mode,
                              uint64_t uid, uint64_t gid,
                              uint64_t maxread, uint64_t flags) {
  int fd = open("/dev/fuse", O_RDWR);
  if (fd < 0) return -errno;
  char dir[160];
  confine_mount_dir(target_addr, dir, sizeof(dir));
  char opts[256];
  fuse_opts(opts, sizeof(opts), fd, mode, uid, gid, maxread, 0);
  if (mount("", dir, "fuse", flags, opts) == 0 &&
      !register_mount(dir))
    umount2(dir, MNT_DETACH);  // table full: do not leak the mount
  return fd;
}

// syz_fuseblk_mount: same, but a block-device-backed fuseblk mount.
// The node is created under the per-proc root at loop device 199 —
// an index the image pipeline never allocates, so a stray fuseblk
// daemonless mount cannot collide with syz_mount_image loops.
static long pseudo_fuseblk_mount(uint64_t target_addr,
                                 uint64_t blkdev_addr, uint64_t mode,
                                 uint64_t uid, uint64_t gid,
                                 uint64_t maxread, uint64_t blksize,
                                 uint64_t flags) {
  int fd = open("/dev/fuse", O_RDWR);
  if (fd < 0) return -errno;
  char blkdev[160];
  confine_mount_dir(blkdev_addr, blkdev, sizeof(blkdev),
                    /*make_dir=*/false);
  if (mknod(blkdev, S_IFBLK | 0600, makedev(7, 199)) && errno != EEXIST)
    return fd;  // fd is still useful without the mount
  char dir[160];
  confine_mount_dir(target_addr, dir, sizeof(dir));
  char opts[256];
  fuse_opts(opts, sizeof(opts), fd, mode, uid, gid, maxread, blksize);
  if (mount(blkdev, dir, "fuseblk", flags, opts) == 0 &&
      !register_mount(dir))
    umount2(dir, MNT_DETACH);
  return fd;
}

static long pseudo_read_part_table(uint64_t size, uint64_t nsegs,
                                   uint64_t segs_addr) {
  int img = build_image(size, nsegs, segs_addr);
  if (img < 0) return -errno;
  int lfd = loop_attach(img);
  close(img);
  if (lfd < 0) return -ENODEV;
  long res = ioctl(lfd, BLKRRPART, 0);
  if (res < 0) res = -errno;
  loop_detach(lfd);
  return res;
}

// Returns the pseudo-syscall result following the raw-syscall
// convention (negative errno on failure).
static long execute_pseudo(uint32_t nr, const uint64_t* a, int nargs) {
  (void)nargs;
  switch (nr) {
    case kPseudoOpenDev:
      return pseudo_open_dev(a[0], a[1], a[2]);
    case kPseudoOpenProcfs:
      return pseudo_open_procfs(a[0], a[1]);
    case kPseudoOpenPts:
      return pseudo_open_pts(a[0], a[1]);
    case kPseudoEmitEthernet:
      return pseudo_emit_ethernet(a[0], a[1]);
    case kPseudoExtractTcpRes:
      return pseudo_extract_tcp_res(a[0], a[1], a[2]);
    case kPseudoGenetlinkFamily:
      return pseudo_genetlink_family(a[0]);
    case kPseudoMountImage:
      return pseudo_mount_image(a[0], a[1], a[2], a[3], a[4], a[5], a[6]);
    case kPseudoReadPartTable:
      return pseudo_read_part_table(a[0], a[1], a[2]);
    case kPseudoKvmSetupCpu:
      return kvm_setup_cpu((int)a[0], (int)a[1], a[2], a[3], a[4], a[5]);
    case kPseudoFuseMount:
      return pseudo_fuse_mount(a[0], a[1], a[2], a[3], a[4], a[5]);
    case kPseudoFuseblkMount:
      return pseudo_fuseblk_mount(a[0], a[1], a[2], a[3], a[4], a[5],
                                  a[6], a[7]);
    case kPseudoInitNetSocket:
      return pseudo_init_net_socket(a[0], a[1], a[2]);
    default:
      return -ENOSYS;
  }
}

}  // namespace tz

#endif  // __linux__ && !TZ_OS_FREEBSD
#endif  // TZ_EXECUTOR_PSEUDO_LINUX_H
