// tz-executor: the native in-VM program executor.
//
// A fresh TPU-framework design filling the role of the reference's
// syz-executor (reference: executor/executor.h, executor/executor.cc,
// executor/executor_linux.cc): it speaks the exec uint64 wire format
// emitted by the host/TPU mutation plane, runs each program's calls on
// a pool of worker threads with a per-call timeout, computes deduped
// edge signal, captures comparison operands, supports collide mode and
// fault injection, and writes per-call results into an output shmem
// region parsed by syzkaller_tpu/ipc/env.py.
//
// Backends:
//   * sim (kEnvSimOS): deterministic in-process fake kernel
//     (sim_kernel.h) — hermetic, used by all tests and local stress;
//   * linux: raw syscall(2) execution with optional KCOV coverage —
//     the real-kernel path, selected by the VM-side fuzzer.
//
// Process model: fork-server.  The host spawns this binary once per
// proc; handshake over stdin/stdout, then one ExecuteReq/ExecuteRep
// round per program.  Crashes of the simulated kernel print an oops to
// stderr and kill the process — the host treats that exactly like a
// VM console oops + lost connection.

#include <errno.h>
#include <signal.h>
#include <stdarg.h>
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <set>
#include <thread>
#include <vector>

#include "sim_kernel.h"
#include "wire.h"

// ---- OS backend selection -------------------------------------------
// TZ_OS_FREEBSD can be forced from the build line to compile-check the
// BSD code path on a non-BSD host (see Makefile freebsd-check): the
// path uses only POSIX surface that glibc also declares, so a host
// -fsyntax-only pass type-checks it; a real FreeBSD toolchain selects
// it naturally via __FreeBSD__ (reference analog: per-OS executor
// builds driven by sys/targets cflags, reference Makefile:139-144).
#if defined(TZ_OS_FREEBSD) || defined(__FreeBSD__) || defined(__NetBSD__)
#define TZ_BSD 1
#elif defined(__linux__)
#define TZ_LINUX 1
#endif

#if defined(TZ_LINUX)
#include <sys/ioctl.h>
#include <sys/syscall.h>
#elif defined(TZ_BSD)
#include <sys/syscall.h>
#endif

#if defined(TZ_LINUX) || defined(TZ_BSD)
namespace tz {
// 64-bit-clean raw syscall.  FreeBSD's syscall(2) is declared
// `int syscall(int, ...)` — returning mmap addresses or lseek offsets
// through it would truncate; __syscall is the 64-bit variant there.
// Linux (and the host compile-check) declare syscall() as long.
static inline long raw_syscall(long nr, uint64_t a0, uint64_t a1,
                               uint64_t a2, uint64_t a3, uint64_t a4,
                               uint64_t a5) {
#if defined(__FreeBSD__)
  return (long)__syscall((int64_t)nr, a0, a1, a2, a3, a4, a5);
#else
  return syscall(nr, a0, a1, a2, a3, a4, a5);
#endif
}
}  // namespace tz
#endif

namespace tz {

// ---- globals set at handshake ---------------------------------------

static uint64_t g_env_flags;
static uint64_t g_pid;
static bool g_debug;
static uint64_t* g_in;      // program stream
static uint8_t* g_out;      // results
static uint8_t* g_arena;    // guest data region
static uint64_t g_arena_base = 0x20000000ull;
static uint64_t g_arena_size = 16ull << 20;
static int g_call_timeout_ms = 25;

static void debugf(const char* fmt, ...) {
  if (!g_debug) return;
  va_list args;
  va_start(args, fmt);
  vfprintf(stderr, fmt, args);
  va_end(args);
}

[[noreturn]] static void failf(const char* fmt, ...) {
  va_list args;
  va_start(args, fmt);
  vfprintf(stderr, fmt, args);
  va_end(args);
  fprintf(stderr, "\n");
  _exit(kStatusFail);
}

// ---- guest memory ----------------------------------------------------

static uint8_t* guest(uint64_t addr, uint64_t size) {
  if (addr < g_arena_base || addr + size > g_arena_base + g_arena_size ||
      addr + size < addr)
    failf("executor: copy outside arena: addr=0x%llx size=%llu",
          (unsigned long long)addr, (unsigned long long)size);
  return g_arena + (addr - g_arena_base);
}

static uint64_t swap_bytes(uint64_t v, uint64_t size) {
  uint64_t r = __builtin_bswap64(v);
  return r >> (64 - 8 * size);
}

// copyin with bitfield read-modify-write + endianness + pid striding
// (reference: executor/executor.h:708-749 copyin semantics)
static void copyin_const(uint64_t addr, uint64_t val, uint64_t meta) {
  uint64_t size = meta_size(meta);
  uint64_t bf_off = meta_bf_off(meta);
  uint64_t bf_len = meta_bf_len(meta);
  val += meta_pid_stride(meta) * g_pid;
  if (meta_be(meta)) val = swap_bytes(val, size);
  uint8_t* p = guest(addr, size);
  if (bf_len == 0) {
    memcpy(p, &val, size);
    return;
  }
  uint64_t cur = 0;
  memcpy(&cur, p, size);
  uint64_t mask = (bf_len == 64 ? ~0ull : ((1ull << bf_len) - 1)) << bf_off;
  cur = (cur & ~mask) | ((val << bf_off) & mask);
  memcpy(p, &cur, size);
}

static uint64_t read_guest_int(uint64_t addr, uint64_t size) {
  uint64_t v = 0;
  memcpy(&v, guest(addr, size), size);
  return v;
}

}  // namespace tz

// Environment features + syz_* pseudo-syscalls for the real-OS
// backend (needs guest()/debugf() above).
#if defined(TZ_BSD)
#include "pseudo_bsd.h"
#else
#include "pseudo_linux.h"
#endif

namespace tz {

// ---- inet checksum ---------------------------------------------------

static uint16_t csum_fold(uint64_t sum) {
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return (uint16_t)~sum;
}

static uint64_t csum_acc(const uint8_t* data, uint64_t len, uint64_t sum) {
  for (uint64_t i = 0; i + 1 < len; i += 2)
    sum += (uint16_t)(data[i] | (data[i + 1] << 8));
  if (len & 1) sum += data[len - 1];
  return sum;
}

// ---- signal (edge hash + dedup) -------------------------------------
// signal = pc ^ hash(prev_pc), deduped in a small open-addressing
// table per call (reference: executor/executor.h:492-528,677-706).

struct SignalBuilder {
  static constexpr int kTableBits = 13;  // 8192 entries
  uint32_t table[1 << kTableBits];
  int n = 0;

  SignalBuilder() { memset(table, 0, sizeof(table)); }

  static uint32_t hash(uint32_t pc) {
    uint64_t h = splitmix64(pc);
    return (uint32_t)(h ^ (h >> 32));
  }

  // returns true if sig was new
  bool add(uint32_t sig, std::vector<uint32_t>* out) {
    uint32_t slot = sig & ((1 << kTableBits) - 1);
    for (int probe = 0; probe < 8; probe++) {
      uint32_t idx = (slot + probe) & ((1 << kTableBits) - 1);
      if (table[idx] == sig) return false;
      if (table[idx] == 0) {
        table[idx] = sig;
        out->push_back(sig);
        return true;
      }
    }
    out->push_back(sig);  // table pressure: accept possible dup
    return true;
  }

  void build(const uint32_t* cov, int len, std::vector<uint32_t>* out) {
    uint32_t prev = 0;
    for (int i = 0; i < len; i++) {
      add(cov[i] ^ hash(prev), out);
      prev = cov[i];
    }
  }
};

// ---- KCOV (linux real-kernel mode) ----------------------------------

#if defined(TZ_LINUX)
struct Kcov {
  static constexpr unsigned long kInitTrace = 0x80086301;
  static constexpr unsigned long kEnable = 0x6364;
  static constexpr unsigned long kDisable = 0x6365;
  static constexpr unsigned long kTracePc = 0;
  static constexpr unsigned long kTraceCmp = 1;
  // 256K entries per thread (reference: executor/executor.h:25).
  static constexpr int kCoverSize = 256 << 10;
  int fd = -1;
  uint64_t* area = nullptr;

  bool open_() {
    fd = open("/sys/kernel/debug/kcov", O_RDWR);
    if (fd < 0) return false;
    if (ioctl(fd, kInitTrace, kCoverSize)) return close_();
    area = (uint64_t*)mmap(nullptr, kCoverSize * 8, PROT_READ | PROT_WRITE,
                           MAP_SHARED, fd, 0);
    if (area == MAP_FAILED) return close_();
    return true;
  }
  bool close_() {
    if (fd >= 0) close(fd);
    fd = -1;
    return false;
  }
  void enable(bool cmps) {
    if (area) {
      __atomic_store_n(&area[0], 0, __ATOMIC_RELAXED);
      ioctl(fd, kEnable, cmps ? kTraceCmp : kTracePc);
    }
  }
  int disable(uint32_t* cov, int max) {
    if (!area) return 0;
    ioctl(fd, kDisable, 0);
    uint64_t n = __atomic_load_n(&area[0], __ATOMIC_RELAXED);
    int out = 0;
    for (uint64_t i = 0; i < n && out < max; i++)
      cov[out++] = (uint32_t)area[i + 1];
    return out;
  }
  // KCOV_TRACE_CMP records: 4 words each (type, arg1, arg2, ip);
  // operands are masked to the compare width.  When the CONST flag
  // (type bit 0) is set, arg1 is a compile-time constant: only the
  // (program-value, constant) direction can ever be a useful hint.
  // Otherwise both orders are emitted since the kernel side doesn't
  // know which operand came from the program
  // (reference: executor_linux.cc:221-253).
  int disable_cmps(SimCmp* out, int max) {
    if (!area) return 0;
    ioctl(fd, kDisable, 0);
    uint64_t n = __atomic_load_n(&area[0], __ATOMIC_RELAXED);
    int cnt = 0;
    for (uint64_t i = 0; i < n && cnt + 1 < max; i++) {
      uint64_t type = area[1 + 4 * i];
      uint64_t a1 = area[2 + 4 * i];
      uint64_t a2 = area[3 + 4 * i];
      int size = 1 << ((type >> 1) & 3);
      uint64_t mask = size == 8 ? ~0ull : ((1ull << (8 * size)) - 1);
      a1 &= mask;
      a2 &= mask;
      if (a1 == a2) continue;  // useless as a hint
      if (type & 1) {          // KCOV_CMP_CONST: arg1 is the constant
        out[cnt++] = SimCmp{a2, a1};
      } else {
        out[cnt++] = SimCmp{a1, a2};
        out[cnt++] = SimCmp{a2, a1};
      }
    }
    return cnt;
  }
};
#endif

// ---- call execution --------------------------------------------------

constexpr int kMaxCov = 4 << 10;
constexpr int kMaxCmps = 512;

struct CallJob {
  // inputs
  uint32_t call_index;
  uint32_t call_id;  // table id: sim dispatch + result attribution
  uint32_t nr;       // kernel syscall number (real-OS backend)
  uint64_t args[8];
  int nargs;
  bool collect_cover;
  bool collect_comps;
  bool collide_reissue = false;  // concurrent re-issue (collide mode)
  // outputs — written by the worker only at completion, under its
  // mutex, so the main thread may read them freely once wait()
  // succeeded; a timed-out job is marked abandoned and then owned
  // (and eventually freed) by the worker alone.
  uint32_t errno_;
  uint64_t ret;
  uint32_t flags;
  std::vector<uint32_t> signal;
  std::vector<uint32_t> cover;
  std::vector<SimCmp> comps;
  bool crashed = false;
  bool abandoned = false;
};

class Worker {
 public:
  Worker(SimKernel* sim, std::mutex* sim_mu) : sim_(sim), sim_mu_(sim_mu) {
    th_ = std::thread([this] { loop(); });
  }

  bool busy() const { return busy_.load(); }

  void submit(CallJob* job) {
    std::unique_lock<std::mutex> lk(mu_);
    job_ = job;
    busy_.store(true);
    cv_.notify_one();
  }

  // Plain wait for completion; false on timeout (job stays owned by
  // the caller — used when waiting for pool capacity).
  bool wait(int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    return done_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                             [this] { return !busy_.load(); });
  }

  // Wait for completion; returns false on timeout, after which the
  // job is no longer the caller's: if it was still queued it is
  // dequeued and freed here, if it is running the worker frees it at
  // completion.  The caller must not touch the job after false.
  bool wait_or_abandon(int timeout_ms, CallJob* job) {
    std::unique_lock<std::mutex> lk(mu_);
    bool done = done_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  [this] { return !busy_.load(); });
    if (!done) {
      if (job_ == job) {
        // never picked up: dequeue so the worker can't run it later
        job_ = nullptr;
        busy_.store(false);
        done_cv_.notify_all();
        delete job;
      } else if (cur_ != nullptr) {
        cur_->abandoned = true;
      }
    }
    return done;
  }

 private:
  void loop() {
    for (;;) {
      CallJob* job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return job_ != nullptr; });
        job = job_;
        job_ = nullptr;
        cur_ = job;
      }
      Output out{};
      run(job, &out);
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (job->abandoned) {
          delete job;
        } else {
          job->errno_ = out.errno_;
          job->ret = out.ret;
          job->flags |= out.flags;
          job->crashed = out.crashed;
          job->signal = std::move(out.signal);
          job->cover = std::move(out.cover);
          job->comps = std::move(out.comps);
        }
        cur_ = nullptr;
        busy_.store(false);
        done_cv_.notify_all();
      }
    }
  }

  struct Output {
    uint32_t errno_;
    uint64_t ret;
    uint32_t flags;
    bool crashed;
    std::vector<uint32_t> signal;
    std::vector<uint32_t> cover;
    std::vector<SimCmp> comps;
  };

  void run(CallJob* j, Output* o) {
    static thread_local uint32_t cov[kMaxCov];
    static thread_local SimCmp cmps[kMaxCmps];
    int cov_len = 0, cmps_len = 0;
    if (g_env_flags & kEnvSimOS) {
      SimResult r;
      if (SimKernel::lockless(j->call_id)) {
        // Race-window calls run WITHOUT the sim lock so collide mode
        // can actually interleave them (sim_kernel.h race families).
        r = sim_->exec_lockless(j->call_id, j->args, j->nargs, cov,
                                kMaxCov, &cov_len, j->collide_reissue);
      } else {
        std::lock_guard<std::mutex> lk(*sim_mu_);
        r = sim_->exec(j->call_id, j->args, j->nargs, cov, kMaxCov, &cov_len,
                       cmps, kMaxCmps, &cmps_len);
      }
      if (r.crashed) {
        o->crashed = true;
        return;
      }
      o->errno_ = r.errno_;
      o->ret = r.ret;
      if (r.fault_injected) o->flags |= kCallFlagFaultInjected;
    } else {
#if defined(TZ_LINUX) || defined(TZ_BSD)
      // Shared real-OS dispatch; only the coverage wrapping is
      // per-OS: Linux uses KCOV when available, the BSD backend has
      // no kernel coverage interface wired up and degrades to one
      // synthetic edge per (call, errno) — the sim backend's no-KCOV
      // scheme — so triage/corpus still function.
#if defined(TZ_LINUX)
      static thread_local Kcov kcov;
      static thread_local bool kcov_ok = kcov.open_();
      bool want_cmps = j->collect_comps;
      if (kcov_ok) kcov.enable(want_cmps);
#endif
      long res;
      if (j->nr >= kPseudoNrBase) {
        // executor-implemented syz_* helper; returns -errno on failure
        res = execute_pseudo(j->nr, j->args, j->nargs);
        if (res < 0) {
          o->errno_ = (uint32_t)-res;
          o->ret = 0;
        } else {
          o->errno_ = 0;
          o->ret = (uint64_t)res;
        }
      } else {
        res = raw_syscall(j->nr, j->args[0], j->args[1], j->args[2],
                          j->args[3], j->args[4], j->args[5]);
        o->errno_ = res == -1 ? errno : 0;
        o->ret = res == -1 ? 0 : (uint64_t)res;
      }
#if defined(TZ_LINUX)
      if (kcov_ok) {
        if (want_cmps)
          cmps_len = kcov.disable_cmps(cmps, kMaxCmps);
        else
          cov_len = kcov.disable(cov, kMaxCov);
      }
#endif
      if (cov_len == 0) {
        // no KCOV (or a comps run): one synthetic edge per
        // (call, errno) so signal still flows
        cov[0] = (uint32_t)splitmix64(j->nr * 1000ull + o->errno_);
        cov_len = 1;
      }
#else
      o->errno_ = 38;  // ENOSYS
#endif
    }
    if (g_env_flags & kEnvSignal) {
      SignalBuilder sb;
      sb.build(cov, cov_len, &o->signal);
    }
    if (j->collect_cover) o->cover.assign(cov, cov + cov_len);
    if (j->collect_comps) {
      std::set<std::pair<uint64_t, uint64_t>> uniq;
      for (int i = 0; i < cmps_len; i++)
        uniq.emplace(cmps[i].op1, cmps[i].op2);
      for (auto& c : uniq) o->comps.push_back(SimCmp{c.first, c.second});
    }
    o->flags |= kCallFlagExecuted | kCallFlagFinished;
  }

  SimKernel* sim_;
  std::mutex* sim_mu_;
  std::thread th_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  std::atomic<bool> busy_{false};
  CallJob* job_ = nullptr;  // submitted, not yet picked up
  CallJob* cur_ = nullptr;  // being executed; abandoned under mu_
};

struct WorkerPool {
  std::vector<Worker*> workers;
  SimKernel* sim;
  std::mutex sim_mu;

  Worker* get() {
    for (auto* w : workers)
      if (!w->busy()) return w;
    if ((int)workers.size() >= kMaxThreads) return nullptr;
    workers.push_back(new Worker(sim, &sim_mu));
    return workers.back();
  }
};

// ---- program interpretation -----------------------------------------

struct Interp {
  uint64_t* words;
  uint64_t nwords;
  uint64_t pos = 0;
  uint64_t copyout_vals[kMaxCopyout];
  bool copyout_done[kMaxCopyout] = {};

  uint64_t next() {
    if (pos >= nwords) failf("executor: truncated program at word %llu",
                             (unsigned long long)pos);
    return words[pos++];
  }

  // Decode one arg; performs const/result resolution.  For data args
  // writes payload at addr (0 = call-arg position: payload ignored).
  uint64_t read_arg(uint64_t addr) {
    uint64_t kind = next();
    switch (kind) {
      case kArgConst: {
        uint64_t meta = next();
        uint64_t val = next();
        if (addr) {
          copyin_const(addr, val, meta);
          return 0;
        }
        val += meta_pid_stride(meta) * g_pid;
        if (meta_be(meta)) val = swap_bytes(val, meta_size(meta));
        return val;
      }
      case kArgResult: {
        uint64_t size = next();
        uint64_t idx = next();
        uint64_t op_div = next();
        uint64_t op_add = next();
        uint64_t def = next();
        if (size > 8) failf("executor: result arg size %llu",
                            (unsigned long long)size);
        if (idx >= kMaxCopyout) failf("executor: copyout idx %llu",
                                      (unsigned long long)idx);
        uint64_t val = copyout_done[idx] ? copyout_vals[idx] : def;
        if (op_div) val /= op_div;
        val += op_add;
        if (addr) memcpy(guest(addr, size), &val, size);
        return val;
      }
      case kArgData: {
        // Low 32 bits: payload length.  High 32 bits: region capacity
        // (0 = len) — the device engine emits cap-padded regions so
        // mutated lengths never reshape the stream.
        uint64_t lenword = next();
        uint64_t len = lenword & 0xFFFFFFFFull;
        uint64_t cap = lenword >> 32;
        if (cap < len) cap = len;
        uint64_t padded = (cap + 7) / 8;
        if (pos + padded > nwords) failf("executor: truncated data arg");
        if (addr) memcpy(guest(addr, len), &words[pos], len);
        pos += padded;
        return 0;
      }
      case kArgCsum: {
        uint64_t size = next();
        uint64_t ckind = next();
        if (ckind != kCsumInet) failf("executor: bad csum kind");
        uint64_t nchunks = next();
        uint64_t sum = 0;
        for (uint64_t i = 0; i < nchunks; i++) {
          uint64_t chunk_kind = next();
          uint64_t v = next();
          uint64_t csize = next();
          if (chunk_kind == kCsumChunkData) {
            sum = csum_acc(guest(v, csize), csize, sum);
          } else {
            // constant chunk, little-endian bytes of v
            if (csize > 8) failf("executor: csum const chunk size %llu",
                                 (unsigned long long)csize);
            sum = csum_acc((const uint8_t*)&v, csize, sum);
          }
        }
        uint16_t folded = csum_fold(sum);
        if (addr) memcpy(guest(addr, size < 2 ? size : 2), &folded,
                         size < 2 ? size : 2);
        return folded;
      }
      default:
        failf("executor: bad arg kind %llu at word %llu",
              (unsigned long long)kind, (unsigned long long)(pos - 1));
    }
    return 0;
  }
};

struct PendingCall {
  CallJob* job;  // owned by main unless abandoned to the worker, in
                 // which case it is replaced by a blocked stub
  Worker* worker;
  uint64_t copyout_idx;  // of ret; kNoCopyout if none
  // Copies of the job's identity: after an abandon the job pointer
  // must not be dereferenced (the worker may free it concurrently).
  uint32_t call_index;
  uint32_t call_id;
  std::vector<std::array<uint64_t, 3>> copyouts;  // idx, addr, size
};

static void execute_program(const ExecuteReq& req, ExecuteRep* rep,
                            WorkerPool* pool) {
  Interp in;
  in.words = g_in;
  in.nwords = req.prog_words;

  bool threaded = req.exec_flags & kExecThreaded;
  bool collide = req.exec_flags & kExecCollide;

  std::vector<PendingCall> calls;

  auto finish_call = [&](PendingCall& pc) {
    if (pc.worker != nullptr) {
      bool done = pc.worker->wait_or_abandon(g_call_timeout_ms, pc.job);
      if (!done) {
        // the job is gone (freed by the worker or the dequeue);
        // report the call through a stub
        auto* stub = new CallJob{};
        stub->call_index = pc.call_index;
        stub->call_id = pc.call_id;
        stub->flags = kCallFlagBlocked;
        pc.job = stub;
        pc.worker = nullptr;
        return;
      }
      pc.worker = nullptr;
    }
    if (pc.job->crashed) _exit(kStatusError);
    // persist ret + memory copyouts for later result args
    if (pc.copyout_idx != kNoCopyout &&
        (pc.job->flags & kCallFlagFinished) && pc.job->errno_ == 0) {
      in.copyout_vals[pc.copyout_idx] = pc.job->ret;
      in.copyout_done[pc.copyout_idx] = true;
    }
    for (auto& co : pc.copyouts) {
      if ((pc.job->flags & kCallFlagFinished) && pc.job->errno_ == 0) {
        in.copyout_vals[co[0]] = read_guest_int(co[1], co[2]);
        in.copyout_done[co[0]] = true;
      }
    }
    pc.copyouts.clear();
  };

  int ncommands = 0;
  for (;;) {
    if (++ncommands > kMaxCommands) failf("executor: too many commands");
    uint64_t w = in.next();
    if (w == kInstrEOF) break;
    if (w == kInstrCopyin) {
      uint64_t addr = in.next();
      in.read_arg(addr);
      continue;
    }
    if (w == kInstrCopyout) {
      uint64_t idx = in.next();
      uint64_t addr = in.next();
      uint64_t size = in.next();
      if (idx >= kMaxCopyout) failf("executor: copyout idx %llu",
                                    (unsigned long long)idx);
      if (size == 0 || size > 8) failf("executor: copyout size %llu",
                                       (unsigned long long)size);
      if (calls.empty()) failf("executor: copyout before any call");
      calls.back().copyouts.push_back({idx, addr, size});
      // in sequential mode the call already completed; re-finish to
      // pick up this copyout now (result args may need it next)
      if (!threaded) finish_call(calls.back());
      continue;
    }
    // call instruction
    if ((int)calls.size() >= kMaxCalls) failf("executor: too many calls");
    auto* job = new CallJob{};
    job->call_index = (uint32_t)calls.size();
    job->call_id = (uint32_t)w;
    job->nr = (uint32_t)(w >> 32);
    job->collect_cover = req.exec_flags & kExecCollectCover;
    job->collect_comps = req.exec_flags & kExecCollectComps;
    uint64_t copyout_idx = in.next();
    uint64_t nargs = in.next();
    if (nargs > 8) failf("executor: %llu args", (unsigned long long)nargs);
    for (uint64_t i = 0; i < nargs; i++) job->args[i] = in.read_arg(0);
    job->nargs = (int)nargs;

    // fault injection arms the sim allocator before the chosen call
    if ((req.exec_flags & kExecFault) && req.fault_call == calls.size()) {
      std::lock_guard<std::mutex> lk(pool->sim_mu);
      pool->sim->arm_fault(req.fault_nth);
    }

    Worker* worker = pool->get();
    if (worker == nullptr) {
      // thread budget exhausted: wait for a worker to free up
      worker = pool->workers[0];
      worker->wait(10 * g_call_timeout_ms);
      worker = pool->get();
      if (worker == nullptr) failf("executor: no free workers");
    }
    worker->submit(job);
    calls.push_back(PendingCall{job, worker, copyout_idx,
                                job->call_index, job->call_id, {}});
    if (!threaded) finish_call(calls.back());
  }
  for (auto& pc : calls) finish_call(pc);

  // collide mode: re-issue adjacent pairs without waiting in between
  // to provoke races (reference: executor/executor.h:409-453)
  if (collide) {
    auto reissue = [&](CallJob* src) -> std::pair<Worker*, CallJob*> {
      Worker* w = pool->get();
      if (w == nullptr) return {nullptr, nullptr};
      auto* copy = new CallJob(*src);
      copy->collide_reissue = true;
      w->submit(copy);
      return {w, copy};
    };
    for (size_t i = 0; i + 1 < calls.size(); i += 2) {
      auto a = reissue(calls[i].job);
      auto b = reissue(calls[i + 1].job);
      bool crashed = false;
      if (a.first && a.first->wait_or_abandon(g_call_timeout_ms, a.second)) {
        crashed |= a.second->crashed;
        delete a.second;
      }
      if (b.first && b.first->wait_or_abandon(g_call_timeout_ms, b.second)) {
        crashed |= b.second->crashed;
        delete b.second;
      }
      // A race provoked during collide is a kernel crash like any
      // other (the oops is already on stderr).
      if (crashed) _exit(kStatusError);
    }
  }

  // ---- write results ----
  uint8_t* p = g_out;
  uint8_t* end = g_out + kOutShmemSize;
  auto* hdr = (OutHeader*)p;
  p += sizeof(OutHeader);
  uint32_t written = 0;
  bool all_finished = true;
  for (auto& pc : calls) {
    CallJob* job = pc.job;
    uint64_t need = sizeof(CallResult) + 4ull * job->signal.size() +
                    4ull * job->cover.size() + 16ull * job->comps.size();
    if (p + need > end) {
      all_finished = false;  // truncated: host must not trust this run
      break;
    }
    auto* cr = (CallResult*)p;
    p += sizeof(CallResult);
    cr->call_index = job->call_index;
    cr->call_id = job->call_id;
    cr->errno_ = job->errno_;
    cr->flags = job->flags;
    cr->signal_len = (uint32_t)job->signal.size();
    cr->cover_len = (uint32_t)job->cover.size();
    cr->comps_len = (uint32_t)job->comps.size();
    cr->reserved = 0;
    memcpy(p, job->signal.data(), 4 * job->signal.size());
    p += 4 * job->signal.size();
    memcpy(p, job->cover.data(), 4 * job->cover.size());
    p += 4 * job->cover.size();
    for (auto& c : job->comps) {
      memcpy(p, &c.op1, 8);
      memcpy(p + 8, &c.op2, 8);
      p += 16;
    }
    if (!(job->flags & kCallFlagFinished)) all_finished = false;
    written++;
  }
  hdr->ncalls = written;
  hdr->completed = all_finished ? 1 : 0;
  rep->ncalls = written;
  rep->status = 0;
  for (auto& pc : calls) delete pc.job;  // stubs or completed jobs
#if defined(TZ_LINUX) || defined(TZ_BSD)
  pseudo_cleanup();  // unmount syz_mount_image mounts of this program
#endif
  {
    // Don't leak an unfired fault onward; abandoned jobs may still be
    // in sim->exec, so take the sim lock.
    std::lock_guard<std::mutex> lk(pool->sim_mu);
    pool->sim->disarm_fault();
  }
}

// ---- sandbox ---------------------------------------------------------

// Ordering contract (reference: common_linux.h does the same dance):
// namespace unshare FIRST (so the tap device lives in the sandbox
// netns), then privileged env setup (TUN needs CAP_NET_ADMIN, cgroups
// need write access), then the setuid privilege drop LAST.
static void apply_sandbox_and_env() {
#if defined(TZ_LINUX)
  if (g_env_flags & kEnvSandboxNamespace)
    sandbox_namespace();  // fresh user/mount/net/ipc/uts ns, uid 0 in
  if (!(g_env_flags & kEnvSimOS)) {
    if (g_env_flags & kEnvEnableTun) setup_tun(g_pid);
    if (g_env_flags & kEnvEnableCgroups) setup_cgroups(g_pid);
  }
  if (g_env_flags & kEnvSandboxSetuid) {
    // drop to nobody best-effort (reference: common_linux.h:1216)
    if (setgid(65534)) debugf("setgid failed: %d\n", errno);
    if (setuid(65534)) debugf("setuid failed: %d\n", errno);
  }
#elif defined(TZ_BSD)
  // No namespace/TUN/cgroup analog on the BSD backend; the setuid
  // drop is the whole sandbox (BSD's "nobody" is also 65534).  A
  // host-requested namespace sandbox must NOT silently run
  // unsandboxed — it degrades to the strongest thing we have.
  if (g_env_flags & (kEnvSandboxSetuid | kEnvSandboxNamespace)) {
    if (g_env_flags & kEnvSandboxNamespace)
      fprintf(stderr, "executor: namespace sandbox unsupported on BSD; "
                      "falling back to setuid drop\n");
    if (setgid(65534)) debugf("setgid failed: %d\n", errno);
    if (setuid(65534)) debugf("setuid failed: %d\n", errno);
  }
#endif
  // the sim backend doesn't touch the host, so "none" is safe there.
}

// ---- main loop -------------------------------------------------------

static void read_exact(int fd, void* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, (char*)buf + got, n - got);
    if (r <= 0) _exit(kStatusRetry);  // host went away
    got += (size_t)r;
  }
}

static void write_exact(int fd, const void* buf, size_t n) {
  size_t put = 0;
  while (put < n) {
    ssize_t r = write(fd, (const char*)buf + put, n - put);
    if (r <= 0) _exit(kStatusRetry);
    put += (size_t)r;
  }
}

static void* map_file(const char* path, uint64_t size, bool writable) {
  int fd = open(path, writable ? O_RDWR : O_RDONLY);
  if (fd < 0) failf("executor: cannot open %s: %d", path, errno);
  if (writable && ftruncate(fd, (off_t)size))
    failf("executor: ftruncate %s: %d", path, errno);
  void* p = mmap(nullptr, size, PROT_READ | (writable ? PROT_WRITE : 0),
                 MAP_SHARED, fd, 0);
  if (p == MAP_FAILED) failf("executor: mmap %s: %d", path, errno);
  close(fd);
  return p;
}

#if defined(TZ_LINUX)
// Self-contained proof that the staged long-mode KVM setup executes
// guest text: stage a vcpu via kvm_setup_cpu (the same code the
// syz_kvm_setup_cpu pseudo-syscall runs), KVM_RUN it, and print the
// exit reason + rbx so the caller can verify a marker instruction
// actually ran.  Usage: tz-executor --selftest-kvm <hex-text>
static int kvm_selftest(const char* hex) {
#ifndef TZ_HAVE_KVM
  fprintf(stderr, "kvm-selftest: built without <linux/kvm.h>\n");
  return 2;
#else
  // private arena for guest() translation
  g_arena = (uint8_t*)mmap(nullptr, g_arena_size, PROT_READ | PROT_WRITE,
                           MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (g_arena == MAP_FAILED) failf("kvm-selftest: arena mmap");
  size_t text_len = strlen(hex) / 2;
  if (text_len == 0 || text_len > 0x1000)
    failf("kvm-selftest: bad text length %zu", text_len);
  uint64_t usermem = g_arena_base + 0x100000;
  uint64_t seg_gaddr = g_arena_base + 0x100;
  uint64_t text_gaddr = g_arena_base + 0x200;
  uint8_t* text = guest(text_gaddr, text_len);
  for (size_t i = 0; i < text_len; i++) {
    unsigned v = 0;
    if (sscanf(hex + 2 * i, "%2x", &v) != 1)
      failf("kvm-selftest: bad hex");
    text[i] = (uint8_t)v;
  }
  KvmTextSeg seg{2 /* long64 */, text_gaddr, text_len};
  memcpy(guest(seg_gaddr, sizeof(seg)), &seg, sizeof(seg));

  int kvm = open("/dev/kvm", O_RDWR);
  if (kvm < 0) {
    fprintf(stderr, "kvm-selftest: no /dev/kvm: %d\n", errno);
    return 3;
  }
  int vmfd = ioctl(kvm, KVM_CREATE_VM, 0);
  int cpufd = vmfd >= 0 ? ioctl(vmfd, KVM_CREATE_VCPU, 0) : -1;
  if (vmfd < 0 || cpufd < 0) failf("kvm-selftest: create vm/vcpu");
  long res = kvm_setup_cpu(vmfd, cpufd, usermem, seg_gaddr, 1, 0);
  if (res != 0) failf("kvm-selftest: setup_cpu: %ld", res);
  int run_size = ioctl(kvm, KVM_GET_VCPU_MMAP_SIZE, 0);
  auto* run = (struct kvm_run*)mmap(nullptr, run_size,
                                    PROT_READ | PROT_WRITE, MAP_SHARED,
                                    cpufd, 0);
  if (run == MAP_FAILED) failf("kvm-selftest: run mmap");
  if (ioctl(cpufd, KVM_RUN, 0)) failf("kvm-selftest: KVM_RUN: %d", errno);
  struct kvm_regs regs;
  if (ioctl(cpufd, KVM_GET_REGS, &regs))
    failf("kvm-selftest: KVM_GET_REGS: %d", errno);
  printf("kvm-selftest: exit=%u rip=0x%llx rbx=0x%llx\n",
         run->exit_reason, (unsigned long long)regs.rip,
         (unsigned long long)regs.rbx);
  return 0;
#endif
}
#endif  // TZ_LINUX

#if defined(TZ_LINUX) && defined(TZ_HAVE_KVM)
// Byte-exact staging dump: run kvm_stage_long into an anonymous
// buffer (no /dev/kvm involved) and hex-dump it so a unit test can
// verify the GDT/IDT/page-table/TSS/trampoline bytes.
// Usage: tz-executor --dump-kvm-stage <hex-text>
static int kvm_stage_dump(const char* hex) {
  size_t text_len = strlen(hex) / 2;
  if (text_len == 0 || text_len > 0x1000)
    failf("dump-kvm-stage: bad text length %zu", text_len);
  std::vector<uint8_t> text(text_len);
  for (size_t i = 0; i < text_len; i++) {
    unsigned v = 0;
    if (sscanf(hex + 2 * i, "%2x", &v) != 1)
      failf("dump-kvm-stage: bad hex");
    text[i] = (uint8_t)v;
  }
  std::vector<uint8_t> mem(kKvmGuestMemSize, 0);
  kvm_stage_long(mem.data(), text.data(), text_len);
  // dump 0x1000..0x9000 (IDT..user text) as hex lines of 32 bytes
  for (uint64_t off = 0x1000; off < 0x9000; off += 32) {
    printf("%06llx ", (unsigned long long)off);
    for (int i = 0; i < 32; i++) printf("%02x", mem[off + i]);
    printf("\n");
  }
  return 0;
}
#endif

static int executor_main(int argc, char** argv) {
#if defined(TZ_LINUX)
  if (argc >= 3 && strcmp(argv[1], "--selftest-kvm") == 0)
    return kvm_selftest(argv[2]);
#ifdef TZ_HAVE_KVM
  if (argc >= 3 && strcmp(argv[1], "--dump-kvm-stage") == 0)
    return kvm_stage_dump(argv[2]);
#endif
#endif
  if (argc < 3) failf("usage: tz-executor <in-file> <out-file>");
  g_in = (uint64_t*)map_file(argv[1], kInShmemSize, false);
  g_out = (uint8_t*)map_file(argv[2], kOutShmemSize, true);

  HandshakeReq hs;
  read_exact(0, &hs, sizeof(hs));
  if (hs.magic != kHandshakeReqMagic)
    failf("executor: bad handshake magic 0x%llx",
          (unsigned long long)hs.magic);
  g_env_flags = hs.env_flags;
  g_pid = hs.pid;
  g_debug = g_env_flags & kEnvDebug;

  // guest arena at the fixed data offset every target compiles
  // pointers against
  g_arena = (uint8_t*)mmap((void*)g_arena_base, g_arena_size,
                           PROT_READ | PROT_WRITE,
                           MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0);
  if (g_arena == MAP_FAILED) {
    // fixed mapping unavailable (ASLR collision): fall back to any
    // address; guest() translates so semantics are unchanged
    g_arena = (uint8_t*)mmap(nullptr, g_arena_size, PROT_READ | PROT_WRITE,
                             MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (g_arena == MAP_FAILED) failf("executor: arena mmap failed");
  }

  apply_sandbox_and_env();

  HandshakeRep hr{kHandshakeRepMagic};
  write_exact(1, &hr, sizeof(hr));

  bool fork_prog = g_env_flags & kEnvForkProg;
#if defined(TZ_LINUX) || defined(TZ_BSD)
  if (!(g_env_flags & kEnvSimOS))
    pseudo_init_mount_root();  // parent + children share the root
#endif
  // In fork mode the parent stays single-threaded and pool-less:
  // every program gets a fresh child with its own pool + sim state
  // (reference process model: common_linux.h:1931-2040).
  SimKernel* sim = nullptr;
  WorkerPool* pool = nullptr;
  if (!fork_prog) {
    sim = new SimKernel(g_pid);
    pool = new WorkerPool;
    pool->sim = sim;
  }

  for (;;) {
    ExecuteReq req;
    read_exact(0, &req, sizeof(req));
    if (req.magic != kExecuteReqMagic)
      failf("executor: bad execute magic 0x%llx",
            (unsigned long long)req.magic);
    if (req.prog_words * 8 > kInShmemSize)
      failf("executor: program too large");
    memset(g_out, 0, sizeof(OutHeader));
    ExecuteRep rep{kExecuteRepMagic, 0, 0};
    if (!fork_prog) {
      execute_program(req, &rep, pool);
      write_exact(1, &rep, sizeof(rep));
      continue;
    }

    pid_t child = fork();
    if (child < 0) failf("executor: fork: %d", errno);
    if (child == 0) {
      // Child: fresh kernel state + worker pool; results land in the
      // MAP_SHARED out region; sim crashes exit kStatusError which
      // the parent propagates (host contract: crash = dead executor
      // + oops on the console).
      SimKernel csim(g_pid);
      WorkerPool cpool;
      cpool.sim = &csim;
      ExecuteRep crep{kExecuteRepMagic, 0, 0};
      execute_program(req, &crep, &cpool);
      _exit(0);
    }
    // Parent: bounded wait, then reap; a child that _exits mid-run
    // (or is killed by a stray program syscall) must not take the
    // fork-server down.
    int prog_timeout_ms = g_call_timeout_ms * (kMaxCalls + 8);
    int waited = 0;
    int status = 0;
    pid_t got = 0;
    while (waited < prog_timeout_ms) {
      got = waitpid(child, &status, WNOHANG);
      if (got == child) break;
      usleep(1000);
      waited += 1;
    }
    if (got != child) {
      kill(child, SIGKILL);
      waitpid(child, &status, 0);
    }
    // Only the SIM backend can legitimately exit kStatusError (a
    // simulated oops) — propagate it to preserve the crash contract.
    // On the real-OS backend the program itself controls the child's
    // exit code (exit_group is described), so treating any status as
    // meaningful would let fuzzed programs forge crash verdicts or
    // kill the fork server; those runs are contained as partial.
    if ((g_env_flags & kEnvSimOS) && WIFEXITED(status) &&
        WEXITSTATUS(status) == kStatusError)
      _exit(kStatusError);
    if (WIFEXITED(status) && WEXITSTATUS(status) == kStatusFail)
      fprintf(stderr, "executor: child reported executor-level failure "
                      "(contained; run marked partial)\n");
    auto* hdr = (OutHeader*)g_out;
    if (got != child || !WIFEXITED(status) || WEXITSTATUS(status) != 0)
      hdr->completed = 0;  // partial or killed: host must not trust
#if defined(TZ_LINUX) || defined(TZ_BSD)
    // A child that died before its own pseudo_cleanup (exit_group
    // mid-program, timeout SIGKILL) leaves its mounts behind in the
    // shared mount namespace; sweep them here.
    if (!(g_env_flags & kEnvSimOS)) pseudo_parent_sweep();
#endif
    rep.ncalls = hdr->ncalls;
    rep.status = 0;
    write_exact(1, &rep, sizeof(rep));
  }
}

}  // namespace tz

int main(int argc, char** argv) { return tz::executor_main(argc, argv); }
