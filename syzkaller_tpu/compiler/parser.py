"""Parser for the syzlang description language.

A hand-written line-oriented lexer + recursive-descent parser producing
compiler/ast.py nodes.  Grammar follows the reference language
(reference: pkg/ast/parser.go, docs/syscall_descriptions_syntax.md):

  top       := include | incdir | define | resource | typedef |
               flags | strflags | struct | union | call
  include   := "include" "<" path ">"
  resource  := "resource" ident "[" type "]" [":" intlist]
  typedef   := "type" ident ["[" identlist "]"] (type | structbody)
  flags     := ident "=" int ("," int)*
  strflags  := ident "=" string ("," string)*
  struct    := ident "{" NL (field NL)* "}" [attrs]
  union     := ident "[" NL (field NL)* "]" [attrs]
  call      := ident "(" [field ("," field)*] ")" [type]
  type      := ident ["[" typearg ("," typearg)* "]"] [":" intval]
  typearg   := type | intval | range | string
  intval    := dec | 0xhex | 'c' | ident
  range     := intval ":" intval

Errors are collected (not raised) so a whole file reports all problems
at once, matching the reference's ErrorHandler style.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from syzkaller_tpu.compiler.ast import (
    Call,
    Comment,
    Define,
    Description,
    Field,
    Include,
    Incdir,
    IntFlags,
    IntValue,
    Pos,
    RangeValue,
    Resource,
    StrFlags,
    StrValue,
    Struct,
    TypeDef,
    TypeExpr,
)


class ParseError(Exception):
    pass


_IDENT_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_$]*")
_INT_RE = re.compile(r"-?(0x[0-9a-fA-F]+|[0-9]+)")
_INT_TYPE_RE = re.compile(r"^(int(8|16|32|64)(be)?|intptr)$")


@dataclass
class _Line:
    text: str
    num: int


class Parser:
    def __init__(self, src: str, filename: str = "<src>"):
        self.filename = filename
        self.lines = [_Line(t, i + 1) for i, t in enumerate(src.split("\n"))]
        self.li = 0  # current line index
        self.text = ""
        self.col = 0
        self.errors: list[str] = []

    # -- line/character machinery ---------------------------------------

    def _pos(self) -> Pos:
        num = self.lines[self.li].num if self.li < len(self.lines) else 0
        return Pos(self.filename, num, self.col + 1)

    def _error(self, msg: str) -> None:
        self.errors.append(f"{self._pos()}: {msg}")

    def _next_line(self) -> bool:
        while self.li < len(self.lines):
            line = self.lines[self.li].text
            self.text = line
            self.col = 0
            return True
        return False

    def _advance_line(self) -> None:
        self.li += 1

    def _skip_ws(self) -> None:
        while self.col < len(self.text) and self.text[self.col] in " \t":
            self.col += 1

    def _at_end(self) -> bool:
        self._skip_ws()
        return self.col >= len(self.text) or self.text[self.col] == "#"

    def _peek(self) -> str:
        self._skip_ws()
        return self.text[self.col] if self.col < len(self.text) else ""

    def _eat(self, ch: str) -> bool:
        if self._peek() == ch:
            self.col += 1
            return True
        return False

    def _expect(self, ch: str) -> bool:
        if not self._eat(ch):
            self._error(f"expected {ch!r}, got {self._peek()!r}")
            return False
        return True

    def _ident(self) -> Optional[str]:
        self._skip_ws()
        m = _IDENT_RE.match(self.text, self.col)
        if not m:
            return None
        self.col = m.end()
        return m.group()

    def _int_value(self) -> Optional[IntValue]:
        self._skip_ws()
        pos = self._pos()
        if self.col < len(self.text) and self.text[self.col] == "'":
            # char literal 'x'
            if self.col + 2 < len(self.text) and self.text[self.col + 2] == "'":
                ch = self.text[self.col + 1]
                self.col += 3
                return IntValue(pos=pos, raw=f"'{ch}'", value=ord(ch))
            self._error("malformed char literal")
            return None
        m = _INT_RE.match(self.text, self.col)
        if m:
            self.col = m.end()
            raw = m.group()
            val = int(raw, 0)
            return IntValue(pos=pos, raw=raw, value=val & ((1 << 64) - 1))
        name = self._ident()
        if name is not None:
            return IntValue(pos=pos, raw=name, ident=name)
        return None

    def _string(self) -> Optional[StrValue]:
        self._skip_ws()
        pos = self._pos()
        if self._peek() != '"':
            return None
        self.col += 1
        out = []
        while self.col < len(self.text):
            c = self.text[self.col]
            if c == '"':
                self.col += 1
                return StrValue(pos=pos, value="".join(out))
            if c == "\\" and self.col + 1 < len(self.text):
                nxt = self.text[self.col + 1]
                out.append({"n": "\n", "t": "\t", '"': '"',
                            "\\": "\\", "0": "\0"}.get(nxt, nxt))
                self.col += 2
                continue
            out.append(c)
            self.col += 1
        self._error("unterminated string")
        return None

    # -- type expressions ------------------------------------------------

    def _type_expr(self) -> Optional[TypeExpr]:
        pos = self._pos()
        name = self._ident()
        if name is None:
            self._error(f"expected type, got {self._peek()!r}")
            return None
        t = TypeExpr(pos=pos, name=name)
        if self._eat("["):
            while True:
                arg = self._type_arg()
                if arg is None:
                    return None
                t.args.append(arg)
                if self._eat(","):
                    continue
                break
            if not self._expect("]"):
                return None
        if self._eat(":"):
            iv = self._int_value()
            if iv is None:
                self._error("expected bitfield width after ':'")
                return None
            t.colon = iv
        return t

    def _type_arg(self):
        self._skip_ws()
        c = self._peek()
        if c == '"':
            return self._string()
        if c == "'" or c.isdigit() or c == "-":
            iv = self._int_value()
            if iv is None:
                return None
            if self._peek() == ":":
                self.col += 1
                hi = self._int_value()
                if hi is None:
                    self._error("expected range end after ':'")
                    return None
                return RangeValue(pos=iv.pos, lo=iv, hi=hi)
            return iv
        # identifier: could be a nested type (with args), a bare name,
        # or a symbolic range (CONST:CONST).  _type_expr consumes the
        # ':' as a bitfield suffix; reinterpret it as a range unless the
        # head is an int type (where `int32:4` really is a bitfield).
        t = self._type_expr()
        if t is None:
            return None
        if not t.args and t.colon is not None \
                and not _INT_TYPE_RE.match(t.name):
            lo = IntValue(pos=t.pos, raw=t.name, ident=t.name)
            return RangeValue(pos=t.pos, lo=lo, hi=t.colon)
        return t

    # -- declarations ----------------------------------------------------

    def _parse_include(self, kind: str):
        pos = self._pos()
        if not self._expect("<"):
            return None
        end = self.text.find(">", self.col)
        if end < 0:
            self._error("expected '>'")
            return None
        path = self.text[self.col:end]
        self.col = end + 1
        return Include(pos=pos, file=path) if kind == "include" else \
            Incdir(pos=pos, dir=path)

    def _parse_define(self):
        pos = self._pos()
        name = self._ident()
        if name is None:
            self._error("expected define name")
            return None
        self._skip_ws()
        value = self.text[self.col:].strip()
        if "#" in value:
            value = value[:value.index("#")].strip()
        self.col = len(self.text)
        if not value:
            self._error("expected define value")
            return None
        return Define(pos=pos, name=name, value=value)

    def _parse_resource(self):
        pos = self._pos()
        name = self._ident()
        if name is None or not self._expect("["):
            self._error("malformed resource")
            return None
        base = self._type_expr()
        if base is None or not self._expect("]"):
            return None
        values: list[IntValue] = []
        if self._eat(":"):
            while True:
                v = self._int_value()
                if v is None:
                    self._error("expected resource value")
                    return None
                values.append(v)
                if not self._eat(","):
                    break
        return Resource(pos=pos, name=name, base=base, values=values)

    def _parse_typedef(self):
        pos = self._pos()
        name = self._ident()
        if name is None:
            self._error("expected type name")
            return None
        params: list[str] = []
        if self._eat("["):
            while True:
                p = self._ident()
                if p is None:
                    self._error("expected template parameter")
                    return None
                params.append(p)
                if not self._eat(","):
                    break
            if not self._expect("]"):
                return None
        c = self._peek()
        if c == "{":
            st = self._parse_struct_body(name, is_union=False)
            if st is None:
                return None
            return TypeDef(pos=pos, name=name, params=params, struct=st)
        if c == "[" and self._looks_like_union_body():
            st = self._parse_struct_body(name, is_union=True)
            if st is None:
                return None
            return TypeDef(pos=pos, name=name, params=params, struct=st)
        t = self._type_expr()
        if t is None:
            return None
        return TypeDef(pos=pos, name=name, params=params, type=t)

    def _looks_like_union_body(self) -> bool:
        # `type t [ \n` opens a union body; `type t [varlen] int32`-style
        # cannot occur, so a '[' followed by line end means union.
        save = self.col
        assert self._eat("[")
        at_end = self._at_end()
        self.col = save
        return at_end

    def _parse_flags(self, name: str, pos: Pos):
        # after "name ="
        if self._peek() == '"':
            vals_s: list[StrValue] = []
            while True:
                s = self._string()
                if s is None:
                    return None
                vals_s.append(s)
                if not self._eat(","):
                    break
            return StrFlags(pos=pos, name=name, values=vals_s)
        vals: list[IntValue] = []
        while True:
            v = self._int_value()
            if v is None:
                self._error("expected flag value")
                return None
            vals.append(v)
            if not self._eat(","):
                break
        return IntFlags(pos=pos, name=name, values=vals)

    def _parse_struct_body(self, name: str, is_union: bool) -> Optional[Struct]:
        pos = self._pos()
        opener, closer = ("[", "]") if is_union else ("{", "}")
        if not self._expect(opener):
            return None
        if not self._at_end():
            self._error(f"expected end of line after {opener!r}")
        st = Struct(pos=pos, name=name, is_union=is_union)
        while True:
            self._advance_line()
            if not self._next_line():
                self._error(f"unterminated {'union' if is_union else 'struct'}")
                return None
            if self._at_end():
                continue
            if self._peek() == closer:
                self.col += 1
                break
            fpos = self._pos()
            fname = self._ident()
            if fname is None:
                self._error("expected field name")
                return None
            ft = self._type_expr()
            if ft is None:
                return None
            st.fields.append(Field(pos=fpos, name=fname, type=ft))
            if not self._at_end():
                self._error("unexpected trailing tokens after field")
                return None
        # trailing attributes
        if self._eat("["):
            while True:
                a = self._type_expr()
                if a is None:
                    return None
                st.attrs.append(a)
                if not self._eat(","):
                    break
            if not self._expect("]"):
                return None
        return st

    def _parse_call(self, name: str, pos: Pos) -> Optional[Call]:
        call = Call(pos=pos, name=name)
        if not self._expect("("):
            return None
        if not self._eat(")"):
            while True:
                apos = self._pos()
                aname = self._ident()
                if aname is None:
                    self._error("expected argument name")
                    return None
                at = self._type_expr()
                if at is None:
                    return None
                call.args.append(Field(pos=apos, name=aname, type=at))
                if self._eat(","):
                    continue
                if not self._expect(")"):
                    return None
                break
        if not self._at_end():
            ret = self._type_expr()
            if ret is None:
                return None
            call.ret = ret
        return call

    # -- driver ----------------------------------------------------------

    def parse(self) -> Description:
        desc = Description()
        while self._next_line():
            if not self._at_end():
                d = self._parse_top()
                if d is not None:
                    desc.decls.append(d)
                    if not self._at_end():
                        self._error("unexpected trailing tokens")
            self._advance_line()
        return desc

    def _parse_top(self):
        pos = self._pos()
        save = self.col
        name = self._ident()
        if name is None:
            self._error(f"unexpected character {self._peek()!r}")
            self.col = len(self.text)
            return None
        if name in ("include", "incdir"):
            return self._parse_include(name)
        if name == "define":
            return self._parse_define()
        if name == "resource":
            return self._parse_resource()
        if name == "type":
            return self._parse_typedef()
        c = self._peek()
        if c == "=":
            self.col += 1
            return self._parse_flags(name, pos)
        if c == "(":
            return self._parse_call(name, pos)
        if c == "{":
            return self._parse_struct_body(name, is_union=False)
        if c == "[" and self._looks_like_union_body():
            return self._parse_struct_body(name, is_union=True)
        self.col = save
        self._error(f"unexpected declaration starting with {name!r}")
        self.col = len(self.text)
        return None


def parse(src: str, filename: str = "<src>") -> Description:
    """Parse a description; raises ParseError listing every error."""
    p = Parser(src, filename)
    desc = p.parse()
    if p.errors:
        raise ParseError("\n".join(p.errors))
    return desc


def parse_glob(paths) -> Description:
    """Parse and concatenate several description files
    (reference: pkg/ast ParseGlob used by sysgen.go:39)."""
    merged = Description()
    errors = []
    for path in paths:
        with open(path) as f:
            src = f.read()
        p = Parser(src, str(path))
        d = p.parse()
        errors += p.errors
        merged.decls += d.decls
    if errors:
        raise ParseError("\n".join(errors))
    return merged
