"""Syscall-description pipeline: layout engine, syzlang parser and
target compiler (reference: pkg/ast, pkg/compiler, sys/syz-sysgen)."""
