"""Description compiler: syzlang AST → Target tables.

Four stages mirroring the reference compile pipeline (reference:
pkg/compiler/compiler.go:19-33 — assignSyscallNumbers, patchConsts,
check, gen), lowered onto the TargetBuilder backend (sys/builder.py)
instead of generated Go source:

  1. const patching (compiler/consts.py) — disables calls whose consts
     are missing on this arch;
  2. typedef instantiation — builtin + user aliases and templates are
     expanded by argument substitution at each use site
     (reference: pkg/compiler/types.go typedefs);
  3. check — duplicate/unknown names, arg sanity, ret-type rules;
  4. gen — builder declarations and Target build.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field as dc_field
from typing import Optional, Union

from syzkaller_tpu.compiler import ast as A
from syzkaller_tpu.compiler.consts import patch_consts
from syzkaller_tpu.compiler.parser import parse
from syzkaller_tpu.models.types import CsumKind, Dir, TextKind
from syzkaller_tpu.sys import builder as B


class CompileError(Exception):
    pass


class UnresolvedConst(Exception):
    """A symbolic constant with no value on this arch was needed in an
    int position; the enclosing syscall gets disabled."""

    def __init__(self, name: str):
        super().__init__(name)
        self.name = name


@dataclass
class CompileResult:
    target: object = None
    disabled_calls: list[str] = dc_field(default_factory=list)
    warnings: list[str] = dc_field(default_factory=list)


# Builtin type aliases/templates (reference:
# docs/syscall_descriptions_syntax.md "builtin type aliases").
_BUILTINS = """\
type bool8 int8[0:1]
type bool16 int16[0:1]
type bool32 int32[0:1]
type bool64 int64[0:1]
type boolptr intptr[0:1]
type buffer[DIR] ptr[DIR, array[int8]]
type optional[T] [
\tval\tT
\tvoid\tvoid
] [varlen]
"""

_INT_SIZES = {"int8": 1, "int16": 2, "int32": 4, "int64": 8}

_TEXT_KINDS = {
    "x86_real": TextKind.X86_REAL,
    "x86_16": TextKind.X86_16,
    "x86_32": TextKind.X86_32,
    "x86_64": TextKind.X86_64,
    "arm64": TextKind.ARM64,
}

_DIRS = {"in": Dir.IN, "out": Dir.OUT, "inout": Dir.INOUT}


def _fmt(t: A.TypeExpr) -> str:
    return t.format()


class Compiler:
    def __init__(self, desc: A.Description, consts: dict[str, int],
                 os: str, arch: str, ptr_size: int = 8,
                 page_size: int = 4096, num_pages: int = 4096,
                 auto_nr_base: int = 0, strict_nr: bool = False):
        self.desc = desc
        self.consts = dict(consts)
        self.os = os
        self.arch = arch
        self.errors: list[str] = []
        self.warnings: list[str] = []
        self.b = B.TargetBuilder(os=os, arch=arch, ptr_size=ptr_size,
                                 page_size=page_size, num_pages=num_pages)
        self.typedefs: dict[str, A.TypeDef] = {}
        self.structs: dict[str, A.Struct] = {}
        self.resources: dict[str, A.Resource] = {}
        self.intflags: dict[str, A.IntFlags] = {}
        self.strflags: dict[str, A.StrFlags] = {}
        self.calls: list[A.Call] = []
        self.auto_nr = auto_nr_base
        # strict_nr: the const set is a real kernel syscall-number
        # table — a missing __NR_ means the arch lacks the syscall and
        # the call must be disabled (arm64 vs x86 legacy calls), not
        # auto-numbered.  Hermetic description sets (test/dsl targets,
        # unit tests) keep auto-numbering for NR-less calls.
        self.strict_nr = strict_nr
        self._instantiating: set[str] = set()
        self._declared: set[str] = set()
        self.disabled: list[str] = []

    def _error(self, pos: A.Pos, msg: str) -> None:
        self.errors.append(f"{pos}: {msg}")

    # -- stage 1+2: collect ---------------------------------------------

    def collect(self) -> list[str]:
        disabled = patch_consts(self.desc, self.consts)
        for d in parse(_BUILTINS, "<builtin>").decls:
            assert isinstance(d, A.TypeDef)
            self.typedefs[d.name] = d
        tables = [self.typedefs, self.structs, self.resources,
                  self.intflags, self.strflags]
        for d in self.desc.decls:
            if isinstance(d, (A.Include, A.Incdir, A.Define, A.Comment)):
                continue
            if isinstance(d, A.Call):
                self.calls.append(d)
                continue
            name = d.name
            if any(name in t for t in tables):
                self._error(d.pos, f"duplicate declaration {name!r}")
                continue
            if isinstance(d, A.TypeDef):
                self.typedefs[name] = d
            elif isinstance(d, A.Struct):
                self.structs[name] = d
            elif isinstance(d, A.Resource):
                self.resources[name] = d
            elif isinstance(d, A.IntFlags):
                self.intflags[name] = d
            elif isinstance(d, A.StrFlags):
                self.strflags[name] = d
        seen_calls = set()
        for c in self.calls:
            if c.name in seen_calls:
                self._error(c.pos, f"duplicate syscall {c.name}")
            seen_calls.add(c.name)
        return disabled

    # -- typedef substitution -------------------------------------------

    def _substitute(self, t: A.TypeExpr,
                    env: dict[str, A.TypeArg]) -> A.TypeExpr:
        if t.is_bare_ident() and t.name in env:
            rep = env[t.name]
            if isinstance(rep, A.TypeExpr):
                return copy.deepcopy(rep)
            if isinstance(rep, A.StrValue):
                # A string literal has no meaning as a standalone type
                # (only inside string[...]/stringnoz[...] args, handled
                # by the arg loop below); report it precisely.
                self._error(t.pos, f"string template argument "
                                   f"{rep.value!r} used in type position")
                return A.TypeExpr(pos=t.pos, name="void")
            # An int parameter used in type position is only valid where
            # the consumer expects an int; wrap for the lowerer to unpack.
            out = A.TypeExpr(pos=t.pos, name="__intparam__")
            out.args = [copy.deepcopy(rep)]
            return out
        out = A.TypeExpr(pos=t.pos, name=t.name, colon=copy.deepcopy(t.colon))
        if out.name in env:
            rep = env[out.name]
            if isinstance(rep, A.TypeExpr) and rep.is_bare_ident():
                out.name = rep.name
        for a in t.args:
            if isinstance(a, A.TypeExpr):
                if a.is_bare_ident() and a.name in env \
                        and isinstance(env[a.name], A.StrValue):
                    # string-literal template arg (e.g. fs_opt["uid"])
                    # must stay a StrValue for string[...] lowering
                    out.args.append(copy.deepcopy(env[a.name]))
                else:
                    out.args.append(self._substitute(a, env))
            elif isinstance(a, A.IntValue) and a.ident and a.ident in env:
                rep = env[a.ident]
                if isinstance(rep, A.IntValue):
                    out.args.append(copy.deepcopy(rep))
                elif isinstance(rep, A.TypeExpr) and rep.is_bare_ident():
                    out.args.append(A.IntValue(pos=a.pos, raw=rep.name,
                                               ident=rep.name))
                else:
                    self._error(a.pos, f"template arg {a.ident!r} used as "
                                       "int but bound to a type")
                    out.args.append(copy.deepcopy(a))
            else:
                out.args.append(copy.deepcopy(a))
        if out.colon is not None and out.colon.ident and out.colon.ident in env:
            rep = env[out.colon.ident]
            if isinstance(rep, A.IntValue):
                out.colon = copy.deepcopy(rep)
        return out

    def _expand_typedef(self, t: A.TypeExpr) -> Optional[Union[A.TypeExpr, str]]:
        """If t's head is a typedef, expand it.  Returns a TypeExpr for
        alias expansion, a struct name (str) for struct-template
        instantiation, or None if t is not a typedef use."""
        td = self.typedefs.get(t.name)
        if td is None:
            return None
        if len(t.args) != len(td.params):
            self._error(t.pos, f"type {td.name} expects "
                               f"{len(td.params)} args, got {len(t.args)}")
            return None
        env: dict[str, A.TypeArg] = dict(zip(td.params, t.args))
        if td.type is not None:
            expanded = self._substitute(td.type, env)
            if t.colon is not None:
                expanded.colon = t.colon
            return expanded
        # struct/union template: instantiate under the printed name
        inst_name = _fmt(t)
        if inst_name not in self.structs:
            if t.name in self._instantiating:
                self._error(t.pos, f"recursive template {t.name}")
                return None
            self._instantiating.add(t.name)
            st = A.Struct(pos=td.pos, name=inst_name,
                          is_union=td.struct.is_union,
                          attrs=copy.deepcopy(td.struct.attrs))
            for f in td.struct.fields:
                st.fields.append(A.Field(pos=f.pos, name=f.name,
                                         type=self._substitute(f.type, env)))
            self.structs[inst_name] = st
            self._declare_struct(st)
            self._instantiating.discard(t.name)
        return inst_name

    # -- int base types --------------------------------------------------

    def _int_base(self, t: A.TypeExpr) -> Optional[tuple[int, bool, int]]:
        """Parse an integer base type: (size, big_endian, bitfield_len),
        or None if t is not an int type."""
        name = t.name
        be = False
        if name.endswith("be") and name[:-2] in _INT_SIZES:
            be = True
            name = name[:-2]
        if name == "intptr":
            size = self.b.ptr_size
        elif name in _INT_SIZES:
            size = _INT_SIZES[name]
        else:
            return None
        bits = 0
        if t.colon is not None:
            if t.colon.value is None:
                self._error(t.pos, "unresolved bitfield width")
                return None
            bits = t.colon.value
        return size, be, bits

    def _int_arg(self, t: A.TypeExpr, a: A.TypeArg, what: str) -> int:
        if isinstance(a, A.TypeExpr) and a.name == "__intparam__":
            a = a.args[0]
        if isinstance(a, A.IntValue):
            if a.value is None:
                raise UnresolvedConst(a.ident)
            return a.value
        if isinstance(a, A.TypeExpr) and a.is_bare_ident():
            raise UnresolvedConst(a.name)
        self._error(t.pos, f"expected {what} (int), got {a.format()!r}")
        return 0

    def _range_arg(self, a: A.TypeArg) -> Optional[tuple[int, int]]:
        if isinstance(a, A.RangeValue):
            return (a.lo.value or 0, a.hi.value or 0)
        if isinstance(a, A.IntValue):
            v = a.value or 0
            return (v, v)
        return None

    # -- stage 4: type lowering -----------------------------------------

    def _lower(self, t: A.TypeExpr, in_struct: bool) -> B.TypeSpec:
        """Lower a TypeExpr to a builder TypeSpec."""
        # `opt` may appear as the trailing arg of most types.
        args = list(t.args)
        is_opt = False
        if args and isinstance(args[-1], A.TypeExpr) \
                and args[-1].is_bare_ident() and args[-1].name == "opt":
            is_opt = True
            args = args[:-1]
        spec = self._lower_inner(t, args, in_struct)
        if is_opt and not isinstance(spec, str):
            spec = B.opt(spec)
        elif is_opt and isinstance(spec, str):
            named = spec

            def named_opt(b, d, fname, memo):
                ty = b._instantiate(named, d, fname, memo)
                ty.optional = True
                return ty

            spec = named_opt
        return spec

    def _lower_inner(self, t: A.TypeExpr, args: list[A.TypeArg],
                     in_struct: bool) -> B.TypeSpec:
        name = t.name
        pos = t.pos

        def err(msg: str) -> B.TypeSpec:
            self._error(pos, msg)
            return B.intptr()

        # integer types (size already ptr_size-aware via _int_base)
        base = self._int_base(t)
        if base is not None:
            size, be, bits = base
            rng = self._range_arg(args[0]) if args else None
            if args and rng is None:
                return err(f"bad int range {args[0].format()!r}")
            kw = dict(be=be, bits=bits)
            if rng is not None:
                kw["range"] = rng
            iname = "intptr" if name.startswith("intptr") else ""
            return B._int_spec(size, name=iname, **kw)

        if name == "fileoff":
            # fileoff[BASE] or bare fileoff (intptr-sized)
            size = self.b.ptr_size
            be = False
            if args and isinstance(args[0], A.TypeExpr):
                b2 = self._int_base(args[0])
                if b2 is None:
                    return err("fileoff base must be an int type")
                size, be, _ = b2
            return B._int_spec(size, be=be, fileoff=True)

        if name == "const":
            if not args:
                return err("const needs a value")
            val = self._int_arg(t, args[0], "const value")
            size, be, bits = 8, False, 0
            if len(args) >= 2 and isinstance(args[1], A.TypeExpr):
                b2 = self._int_base(args[1])
                if b2 is None:
                    return err("const base must be an int type")
                size, be, bits = b2
            elif in_struct:
                return err("const in struct needs a base type")
            return B.const(val, size=size, be=be, bits=bits)

        if name == "flags":
            if not args or not isinstance(args[0], A.TypeExpr) \
                    or not args[0].is_bare_ident():
                return err("flags needs a flags-set name")
            fname = args[0].name
            size, be, bits = 8, False, 0
            if len(args) >= 2 and isinstance(args[1], A.TypeExpr):
                b2 = self._int_base(args[1])
                if b2 is None:
                    return err("flags base must be an int type")
                size, be, bits = b2
            elif in_struct:
                return err("flags in struct needs a base type")
            if fname in self.strflags:
                return B.string(fname)
            if fname not in self.intflags:
                return err(f"unknown flags {fname!r}")
            if not self.intflags[fname].values:
                # Every member const was undefined on this arch
                # (dropped by patch_consts): disable dependent calls.
                raise UnresolvedConst(f"flags {fname} (no defined values)")
            return B.flags(fname, size=size, be=be, bits=bits)

        if name in ("len", "bytesize", "bitsize") or \
                (name.startswith("bytesize") and name[8:].isdigit()):
            if not args or not isinstance(args[0], A.TypeExpr) \
                    or not args[0].is_bare_ident():
                return err(f"{name} needs a target field name")
            path = args[0].name
            size, be, bits = 8, False, 0
            if len(args) >= 2 and isinstance(args[1], A.TypeExpr):
                b2 = self._int_base(args[1])
                if b2 is None:
                    return err(f"{name} base must be an int type")
                size, be, bits = b2
            elif in_struct:
                return err(f"{name} in struct needs a base type")
            if name == "len":
                return B.len_of(path, size=size, be=be, bits=bits)
            if name == "bitsize":
                return B.bitsize_of(path, size=size, be=be)
            unit = int(name[8:]) if len(name) > 8 else 1
            return B.bytesize_of(path, size=size, unit=unit, be=be)

        if name in ("ptr", "ptr64"):
            if len(args) < 2 or not isinstance(args[0], A.TypeExpr) \
                    or args[0].name not in _DIRS:
                return err("ptr needs [dir, type]")
            d = _DIRS[args[0].name]
            elem = self._lower(args[1], in_struct=True) \
                if isinstance(args[1], A.TypeExpr) else None
            if elem is None:
                return err("bad ptr element")
            return B.ptr(d, elem)

        if name == "array":
            if not args or not isinstance(args[0], A.TypeExpr):
                return err("array needs an element type")
            elem = self._lower(args[0], in_struct=True)
            count = None
            if len(args) >= 2:
                rng = self._range_arg(args[1])
                if rng is None:
                    return err("bad array count")
                count = rng[0] if rng[0] == rng[1] else rng
            return B.array(elem, count)

        if name in ("string", "stringnoz"):
            no_z = name == "stringnoz"
            values = None
            size = 0
            sub_kind = ""
            rest = args
            if rest and isinstance(rest[0], A.StrValue):
                values = (rest[0].value.encode(),)
                rest = rest[1:]
            elif rest and isinstance(rest[0], A.TypeExpr) \
                    and rest[0].is_bare_ident():
                sname = rest[0].name
                rest = rest[1:]
                if sname == "filename":
                    return B.filename(no_z=no_z)
                if sname not in self.strflags:
                    return err(f"unknown string flags {sname!r}")
                values = sname
            if rest:
                size = self._int_arg(t, rest[0], "string size")
                rest = rest[1:]
            return B.string(values, size=size, no_z=no_z, sub_kind=sub_kind)

        if name == "filename":
            return B.filename()

        if name in ("vma", "vma64"):
            rng = None
            if args:
                rng = self._range_arg(args[0])
                if rng is None:
                    return err("bad vma range")
            return B.vma(range=rng)

        if name == "proc":
            if len(args) < 2:
                return err("proc needs [start, per-proc]")
            start = self._int_arg(t, args[0], "proc start")
            per = self._int_arg(t, args[1], "proc per-proc count")
            size = 8
            if len(args) >= 3 and isinstance(args[2], A.TypeExpr):
                b2 = self._int_base(args[2])
                if b2 is None:
                    return err("proc base must be an int type")
                size = b2[0]
            elif in_struct:
                return err("proc in struct needs a base type")
            return B.proc(start, per, size=size)

        if name == "csum":
            # csum[buf, inet|pseudo, (proto,)? base]
            if len(args) < 3 or not isinstance(args[0], A.TypeExpr) \
                    or not isinstance(args[1], A.TypeExpr):
                return err("csum needs [buf, kind, base]")
            buf = args[0].name
            kind_s = args[1].name
            if kind_s == "inet":
                kind, proto, bi = CsumKind.INET, 0, 2
            elif kind_s == "pseudo":
                if len(args) < 4:
                    return err("pseudo csum needs a protocol")
                kind, proto, bi = CsumKind.PSEUDO, \
                    self._int_arg(t, args[2], "protocol"), 3
            else:
                return err(f"unknown csum kind {kind_s!r}")
            size = 2
            if len(args) > bi and isinstance(args[bi], A.TypeExpr):
                b2 = self._int_base(args[bi])
                if b2 is not None:
                    size = b2[0]
            return B.csum(buf, kind=kind, protocol=proto, size=size)

        if name == "text":
            if not args or not isinstance(args[0], A.TypeExpr) \
                    or args[0].name not in _TEXT_KINDS:
                return err("text needs a known text kind")
            return B.text(_TEXT_KINDS[args[0].name])

        if name == "void":
            return B.void()

        if name == "__intparam__":
            # An int template param in type position has no meaning.
            return err("int template argument used in type position")

        # typedef?
        if name in self.typedefs:
            expanded = self._expand_typedef(t)
            if expanded is None:
                return B.intptr()
            if isinstance(expanded, str):
                return expanded  # instantiated struct name
            return self._lower(expanded, in_struct)

        # named resource / struct / union
        if name in self.resources:
            return B.res(name)
        if name in self.structs:
            self._declare_struct(self.structs[name])
            return name
        return err(f"unknown type {name!r}")

    # -- declarations ----------------------------------------------------

    def _declare_flags(self) -> None:
        for fl in self.intflags.values():
            vals = tuple((v.value or 0) for v in fl.values)
            self.b.flag_set(fl.name, *vals)
        for sf in self.strflags.values():
            self.b.string_set(sf.name, *(v.value for v in sf.values))

    def _resource_base(self, r: A.Resource,
                       seen: set[str]) -> tuple[int, Optional[str]]:
        """Returns (base_size, parent_resource_or_None)."""
        base = r.base
        ib = self._int_base(base)
        if ib is not None:
            return ib[0], None
        if base.name in self.resources:
            if base.name in seen:
                self._error(r.pos, f"recursive resource {r.name}")
                return 8, None
            parent = self.resources[base.name]
            size, _ = self._resource_base(parent, seen | {base.name})
            return size, base.name
        self._error(r.pos, f"unknown resource base {base.name!r}")
        return 8, None

    def _declare_resources(self) -> None:
        declared: set[str] = set()

        def declare(r: A.Resource) -> None:
            if r.name in declared:
                return
            size, parent = self._resource_base(r, {r.name})
            if parent is not None and parent not in declared:
                declare(self.resources[parent])
            values = tuple((v.value or 0) for v in r.values) or (0,)
            self.b.resource(r.name, size, values=values, parent=parent)
            declared.add(r.name)

        for r in self.resources.values():
            declare(r)

    def _declare_struct(self, st: A.Struct) -> None:
        if st.name in self._declared:
            return
        self._declared.add(st.name)
        packed = False
        align = 0
        size: Optional[int] = None
        varlen = False
        for a in st.attrs:
            if a.name == "packed":
                packed = True
            elif a.name.startswith("align_"):
                align = int(a.name[6:])
            elif a.name == "varlen":
                varlen = True
            elif a.name == "size" and a.args:
                size = self._int_arg(a, a.args[0], "size attribute")
            else:
                self._error(a.pos, f"unknown attribute {a.name!r} "
                                   f"on {st.name}")
        fields = [(f.name, self._lower(f.type, in_struct=True))
                  for f in st.fields]
        if st.is_union:
            if packed or align:
                self._error(st.pos, f"union {st.name} cannot be packed/aligned")
            self.b.union(st.name, fields, varlen=varlen, size=size)
        else:
            if varlen:
                self._error(st.pos, f"struct {st.name} cannot be varlen")
            self.b.struct(st.name, fields, packed=packed, align=align,
                          size=size)

    def _declare_calls(self) -> None:
        for c in self.calls:
            nr = self.consts.get(f"__NR_{c.call_name}")
            if nr is None:
                if self.strict_nr and not c.call_name.startswith("syz_"):
                    self.disabled.append(c.name)
                    self.warnings.append(
                        f"{c.pos}: {c.name} disabled: no __NR_"
                        f"{c.call_name} on this arch")
                    continue
                nr = self.auto_nr
                self.auto_nr += 1
            try:
                args = [(f.name, self._lower(f.type, in_struct=False))
                        for f in c.args]
            except UnresolvedConst as e:
                self.disabled.append(c.name)
                self.warnings.append(
                    f"{c.pos}: {c.name} disabled: missing const {e.name!r}")
                continue
            ret: Optional[str] = None
            if c.ret is not None:
                if not c.ret.is_bare_ident() or c.ret.name not in self.resources:
                    self._error(c.ret.pos,
                                f"return type of {c.name} must be a resource")
                else:
                    ret = c.ret.name
            self.b.syscall(c.name, args, ret=ret, nr=nr)

    # -- driver ----------------------------------------------------------

    def compile(self, register: bool = True) -> CompileResult:
        self.disabled = self.collect()
        try:
            self._declare_flags()
            self._declare_resources()
            # structs are declared lazily on first use so that template
            # instantiations land before dependents; force the rest now
            for st in list(self.structs.values()):
                self._declare_struct(st)
            self._declare_calls()
        except UnresolvedConst as e:
            # missing const in a struct/resource: unusable by every call
            raise CompileError(f"undefined constant {e.name!r}") from None
        if self.errors:
            raise CompileError("\n".join(self.errors))
        target = self.b.build(register=register)
        return CompileResult(target=target, disabled_calls=self.disabled,
                             warnings=self.warnings)


def compile_description(src: Union[str, A.Description],
                        consts: Optional[dict[str, int]] = None,
                        os: str = "dsl", arch: str = "64",
                        filename: str = "<src>", register: bool = False,
                        **target_kw) -> CompileResult:
    """Compile syzlang source text (or a parsed Description) into a
    registered Target (reference: pkg/compiler/compiler.go:47 Compile)."""
    desc = parse(src, filename) if isinstance(src, str) else src
    c = Compiler(desc, consts or {}, os, arch, **target_kw)
    return c.compile(register=register)
