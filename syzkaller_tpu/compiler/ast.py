"""AST for the syzlang syscall-description language.

Node semantics follow the reference description language (reference:
pkg/ast/ast.go, docs/syscall_descriptions_syntax.md): top-level
declarations are includes/incdirs/defines, resources, int/string flag
sets, type aliases/templates, structs/unions and syscalls.  Types are a
uniform head + bracketed argument list (`ptr[in, array[int8]]`), with
an optional `:colon` suffix used for bitfields (`int8:3`).

Unlike the reference this AST is consumed only by our compiler
(compiler/compile.py) — there is no separate formatter tool, but every
node knows how to print itself back to canonical source, which the
tests use for parse round-trips.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


@dataclass(frozen=True)
class Pos:
    file: str = ""
    line: int = 0
    col: int = 0

    def __str__(self) -> str:
        return f"{self.file}:{self.line}:{self.col}"


@dataclass
class Node:
    pos: Pos = field(default_factory=Pos)


@dataclass
class Comment(Node):
    text: str = ""

    def format(self) -> str:
        return f"#{self.text}"


@dataclass
class Include(Node):
    file: str = ""

    def format(self) -> str:
        return f"include <{self.file}>"


@dataclass
class Incdir(Node):
    dir: str = ""

    def format(self) -> str:
        return f"incdir <{self.dir}>"


@dataclass
class Define(Node):
    name: str = ""
    # The value expression, kept as raw source; evaluated by
    # compiler/consts.py with the current const environment.
    value: str = ""

    def format(self) -> str:
        return f"define {self.name} {self.value}"


@dataclass
class IntValue(Node):
    """An integer-valued token: literal, hex, char, or symbolic const.
    After const patching, `value` is set for symbolic names too."""

    raw: str = ""
    value: Optional[int] = None
    ident: str = ""  # non-empty if symbolic

    def format(self) -> str:
        return self.ident if self.ident else self.raw


@dataclass
class RangeValue(Node):
    lo: IntValue = field(default_factory=IntValue)
    hi: IntValue = field(default_factory=IntValue)

    def format(self) -> str:
        return f"{self.lo.format()}:{self.hi.format()}"


@dataclass
class StrValue(Node):
    value: str = ""

    def format(self) -> str:
        return '"' + self.value.replace("\\", "\\\\").replace('"', '\\"') + '"'


# A type argument: nested type, int, range, or string.
TypeArg = Union["TypeExpr", IntValue, RangeValue, StrValue]


@dataclass
class TypeExpr(Node):
    name: str = ""
    args: list[TypeArg] = field(default_factory=list)
    colon: Optional[IntValue] = None  # bitfield suffix: int8:3

    def format(self) -> str:
        s = self.name
        if self.args:
            s += "[" + ", ".join(a.format() for a in self.args) + "]"
        if self.colon is not None:
            s += ":" + self.colon.format()
        return s

    def is_bare_ident(self) -> bool:
        return not self.args and self.colon is None


@dataclass
class Field(Node):
    name: str = ""
    type: TypeExpr = field(default_factory=TypeExpr)

    def format(self) -> str:
        return f"{self.name}\t{self.type.format()}"


@dataclass
class Resource(Node):
    name: str = ""
    base: TypeExpr = field(default_factory=TypeExpr)
    values: list[IntValue] = field(default_factory=list)

    def format(self) -> str:
        s = f"resource {self.name}[{self.base.format()}]"
        if self.values:
            s += ": " + ", ".join(v.format() for v in self.values)
        return s


@dataclass
class IntFlags(Node):
    name: str = ""
    values: list[IntValue] = field(default_factory=list)

    def format(self) -> str:
        return f"{self.name} = " + ", ".join(v.format() for v in self.values)


@dataclass
class StrFlags(Node):
    name: str = ""
    values: list[StrValue] = field(default_factory=list)

    def format(self) -> str:
        return f"{self.name} = " + ", ".join(v.format() for v in self.values)


@dataclass
class Struct(Node):
    name: str = ""
    fields: list[Field] = field(default_factory=list)
    attrs: list[TypeExpr] = field(default_factory=list)
    is_union: bool = False

    def format(self) -> str:
        o, c = ("[", "]") if self.is_union else ("{", "}")
        lines = [f"{self.name} {o}"]
        lines += ["\t" + f.format() for f in self.fields]
        tail = c
        if self.attrs:
            tail += " [" + ", ".join(a.format() for a in self.attrs) + "]"
        lines.append(tail)
        return "\n".join(lines)


@dataclass
class TypeDef(Node):
    """`type name[ARGS] <type-or-struct>` — alias when params empty,
    template otherwise (reference: pkg/ast/ast.go TypeDef)."""

    name: str = ""
    params: list[str] = field(default_factory=list)
    type: Optional[TypeExpr] = None
    struct: Optional[Struct] = None

    def format(self) -> str:
        head = f"type {self.name}"
        if self.params:
            head += "[" + ", ".join(self.params) + "]"
        if self.type is not None:
            return f"{head} {self.type.format()}"
        assert self.struct is not None
        body = self.struct.format()
        return f"{head} {body[body.index(' ') + 1:]}"


@dataclass
class Call(Node):
    name: str = ""  # full name incl. $variant
    args: list[Field] = field(default_factory=list)
    ret: Optional[TypeExpr] = None
    nr: int = -1  # syscall number; assigned by the compiler

    @property
    def call_name(self) -> str:
        return self.name.split("$")[0]

    def format(self) -> str:
        s = f"{self.name}(" + ", ".join(
            f"{a.name} {a.type.format()}" for a in self.args) + ")"
        if self.ret is not None:
            s += " " + self.ret.format()
        return s


Decl = Union[Include, Incdir, Define, Resource, IntFlags, StrFlags,
             Struct, TypeDef, Call, Comment]


@dataclass
class Description:
    decls: list[Decl] = field(default_factory=list)

    def format(self) -> str:
        return "\n".join(d.format() for d in self.decls) + "\n"

    def walk_types(self):
        """Yield every TypeExpr in the description (pre-order)."""

        def rec(t: TypeExpr):
            yield t
            for a in t.args:
                if isinstance(a, TypeExpr):
                    yield from rec(a)

        for d in self.decls:
            if isinstance(d, Resource):
                yield from rec(d.base)
            elif isinstance(d, Struct):
                for f in d.fields:
                    yield from rec(f.type)
            elif isinstance(d, TypeDef):
                if d.type is not None:
                    yield from rec(d.type)
                elif d.struct is not None:
                    for f in d.struct.fields:
                        yield from rec(f.type)
            elif isinstance(d, Call):
                for f in d.args:
                    yield from rec(f.type)
                if d.ret is not None:
                    yield from rec(d.ret)
