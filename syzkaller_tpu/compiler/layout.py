"""Struct/union/array layout: bitfield grouping, padding insertion,
alignment and size computation.

Follows the reference compiler's layout pass
(reference: pkg/compiler/gen.go:76-385): bitfields of equal storage
size pack into one unit; non-packed structs get C-like natural
alignment padding; explicit size/align attributes override; sizes of
recursive structures converge via a fixpoint since recursion can only
pass through fixed-size pointers.
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    Type,
    UnionType,
    VmaType,
)

SIZE_UNASSIGNED = -1


def gen_pad(size: int) -> ConstType:
    return ConstType(name="pad", field_name="", type_size=size, dir=Dir.IN,
                     is_pad=True)


def mark_bitfields(fields: list[Type]) -> None:
    """Group consecutive bitfields sharing a storage unit
    (reference: pkg/compiler/gen.go:233-249)."""
    bf_offset = 0
    for i, f in enumerate(fields):
        if f.bitfield_length() == 0:
            continue
        off, middle = bf_offset, True
        bf_offset += f.bitfield_length()
        last = (i == len(fields) - 1
                or fields[i + 1].bitfield_length() == 0
                or fields[i + 1].size() != f.size()
                or bf_offset + fields[i + 1].bitfield_length() > f.size() * 8)
        if last:
            middle = False
            bf_offset = 0
        f.bitfield_off = off  # type: ignore[attr-defined]
        f.bitfield_mdl = middle  # type: ignore[attr-defined]


class LayoutAttrs:
    """Per-struct attributes carried from the description."""

    def __init__(self, packed: bool = False, align: int = 0,
                 size: Optional[int] = None, varlen_attr: bool = False):
        self.packed = packed
        self.align = align
        self.size = size
        self.varlen_attr = varlen_attr  # unions only


def type_align(t: Type, attrs_of) -> int:
    """(reference: pkg/compiler/gen.go:337-374)"""
    if isinstance(t, (IntType, ConstType, LenType, FlagsType, ProcType,
                      CsumType, PtrType, VmaType, ResourceType)):
        return t.type_size
    if isinstance(t, BufferType):
        return 1
    if isinstance(t, ArrayType):
        assert t.elem is not None
        return type_align(t.elem, attrs_of)
    if isinstance(t, StructType):
        attrs: LayoutAttrs = attrs_of(t)
        if attrs.align:
            return attrs.align
        if attrs.packed:
            return 1
        return max((type_align(f, attrs_of) for f in t.fields), default=0)
    if isinstance(t, UnionType):
        return max((type_align(f, attrs_of) for f in t.fields), default=0)
    raise TypeError(f"unknown type {t}")


def add_alignment(fields: list[Type], varlen: bool, packed: bool,
                  align_attr: int, attrs_of) -> list[Type]:
    """Insert pad fields (reference: pkg/compiler/gen.go:268-335)."""
    if packed:
        new_fields = list(fields)
        if not varlen and align_attr != 0:
            size = sum(f.size() for f in fields if not f.bitfield_middle())
            tail = size % align_attr
            if tail:
                new_fields.append(gen_pad(align_attr - tail))
        return new_fields
    new_fields: list[Type] = []
    align = 0
    off = 0
    for i, f in enumerate(fields):
        if i == 0 or not fields[i - 1].bitfield_middle():
            a = type_align(f, attrs_of)
            if align < a:
                align = a
            if a and off % a != 0:
                pad = a - off % a
                off += pad
                new_fields.append(gen_pad(pad))
        new_fields.append(f)
        if not f.bitfield_middle() and (i != len(fields) - 1 or not f.varlen):
            off += f.size()
    if align_attr != 0:
        align = align_attr
    if align != 0 and off % align != 0 and not varlen:
        pad = align - off % align
        off += pad
        new_fields.append(gen_pad(pad))
    return new_fields


_DEFAULT_ATTRS = LayoutAttrs()


class LayoutEngine:
    """Runs the padding/size fixpoint over all types reachable from a
    syscall list (reference: pkg/compiler/gen.go:76-205)."""

    def __init__(self, attrs: dict[str, LayoutAttrs]):
        # attrs maps struct/union name -> LayoutAttrs
        self.attrs = attrs
        self.padded: set[int] = set()

    def attrs_of(self, t: Type) -> LayoutAttrs:
        return self.attrs.get(t.name, _DEFAULT_ATTRS)

    def _size_known(self, t: Type) -> bool:
        return t.varlen or t.type_size != SIZE_UNASSIGNED

    def run(self, syscalls: list[Syscall]) -> None:
        while True:
            start = len(self.padded)
            for c in syscalls:
                for a in c.args:
                    self._rec(a)
                if c.ret is not None:
                    self._rec(c.ret)
            if start == len(self.padded):
                break

    def _rec(self, t: Type) -> None:
        if isinstance(t, PtrType):
            assert t.elem is not None
            self._rec(t.elem)
        elif isinstance(t, ArrayType):
            if id(t) in self.padded:
                return
            assert t.elem is not None
            self._rec(t.elem)
            if not self._size_known(t.elem):
                return  # inner struct not padded yet
            self.padded.add(id(t))
            t.type_size = 0
            if t.kind == ArrayKind.RANGE_LEN and t.range_begin == t.range_end \
                    and not t.elem.varlen:
                t.type_size = t.range_begin * t.elem.size()
                t.varlen = False
            else:
                t.varlen = True
        elif isinstance(t, StructType):
            if not self._check_struct(t):
                return
            varlen = any(f.varlen for f in t.fields)
            mark_bitfields(t.fields)
            attrs = self.attrs_of(t)
            t.fields = add_alignment(t.fields, varlen, attrs.packed,
                                     attrs.align, self.attrs_of)
            t.align_attr = attrs.align
            t.varlen = varlen
            t.type_size = 0
            if not varlen:
                t.type_size = sum(f.size() for f in t.fields
                                  if not f.bitfield_middle())
                if attrs.size is not None:
                    assert t.type_size <= attrs.size, (
                        f"struct {t.name} has size attr {attrs.size} < "
                        f"computed size {t.type_size}")
                    pad = attrs.size - t.type_size
                    if pad:
                        t.fields.append(gen_pad(pad))
                    t.type_size = attrs.size
        elif isinstance(t, UnionType):
            if not self._check_struct(t):
                return
            attrs = self.attrs_of(t)
            t.varlen = attrs.varlen_attr
            t.type_size = 0
            if not attrs.varlen_attr:
                for f in t.fields:
                    sz = f.size()
                    if attrs.size is not None:
                        assert sz <= attrs.size, (
                            f"union {t.name} size attr {attrs.size} < "
                            f"field {f.name} size {sz}")
                    t.type_size = max(t.type_size, sz)
                if attrs.size is not None:
                    t.type_size = attrs.size

    def _check_struct(self, t) -> bool:
        if id(t) in self.padded:
            return False
        self.padded.add(id(t))
        for f in t.fields:
            self._rec(f)
            if not self._size_known(f):
                # An inner struct is not padded yet; retry next iteration.
                self.padded.discard(id(t))
                return False
        return True
