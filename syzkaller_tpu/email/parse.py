"""Inbound mail parsing: commands, patches, quoting
(reference: pkg/email/parser.go + patch.go).

Recognized commands (lines beginning '#syz', anywhere in the
unquoted body; reference command grammar: pkg/email/parser.go
extractCommand):

  #syz fix: <commit title>      mark fixed by commit
  #syz dup: <bug title>         mark duplicate of another bug
  #syz invalid                  close as invalid
  #syz undup                    undo a dup
  #syz test: <repo> <branch>    patch-test job (patch from the body)
  #syz upstream                 advance to the next reporting stage
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from email import message_from_bytes
from email.utils import getaddresses, parseaddr
from typing import Optional


@dataclass
class Command:
    name: str  # fix | dup | invalid | undup | test | upstream
    args: str = ""


@dataclass
class Email:
    msg_id: str = ""
    in_reply_to: str = ""
    subject: str = ""
    from_addr: str = ""
    to: list[str] = field(default_factory=list)
    cc: list[str] = field(default_factory=list)
    body: str = ""  # text/plain, quoting stripped
    raw_body: str = ""
    patch: str = ""  # unified diff found in the body, if any
    commands: list[Command] = field(default_factory=list)


_CMD_RE = re.compile(r"^#syz\s+([a-z-]+):?\s*(.*)$")
# A unified diff starts at 'diff --git' or a '--- ' header followed by
# '+++ ' (reference: pkg/email/patch.go ParsePatch).
_DIFF_START = re.compile(r"^(diff --git |Index: |--- )")


def _text_body(msg) -> str:
    if msg.is_multipart():
        for part in msg.walk():
            if part.get_content_type() == "text/plain":
                payload = part.get_payload(decode=True)
                if payload is not None:
                    return payload.decode("utf-8", "replace")
        return ""
    payload = msg.get_payload(decode=True)
    if payload is None:
        return str(msg.get_payload())
    return payload.decode("utf-8", "replace")


def _strip_quoting(body: str) -> str:
    out = []
    for line in body.splitlines():
        if line.startswith(">"):
            continue
        if line.startswith("On ") and line.rstrip().endswith("wrote:"):
            continue
        out.append(line)
    return "\n".join(out)


def _extract_patch(body: str) -> str:
    """First unified diff in the body through its last hunk line
    (reference: pkg/email/patch.go)."""
    lines = body.splitlines()
    start = None
    for i, line in enumerate(lines):
        if _DIFF_START.match(line):
            if line.startswith("--- ") and \
                    (i + 1 >= len(lines)
                     or not lines[i + 1].startswith("+++ ")):
                continue
            start = i
            break
    if start is None:
        return ""
    end = start
    for j in range(start, len(lines)):
        line = lines[j]
        if line.startswith(("diff ", "Index: ", "--- ", "+++ ", "@@ ",
                            "+", "-", " ")) or not line:
            end = j
        else:
            break
    return "\n".join(lines[start:end + 1]).strip("\n")


def parse_email(raw: bytes) -> Email:
    msg = message_from_bytes(raw)
    body = _text_body(msg)
    unquoted = _strip_quoting(body)
    commands = []
    for line in unquoted.splitlines():
        m = _CMD_RE.match(line.strip())
        if m:
            commands.append(Command(name=m.group(1),
                                    args=m.group(2).strip()))
    return Email(
        msg_id=(msg.get("Message-ID") or "").strip(),
        in_reply_to=(msg.get("In-Reply-To") or "").strip(),
        subject=msg.get("Subject", ""),
        from_addr=parseaddr(msg.get("From", ""))[1],
        to=[a for _, a in getaddresses(msg.get_all("To", []))],
        cc=[a for _, a in getaddresses(msg.get_all("Cc", []))],
        body=unquoted,
        raw_body=body,
        patch=_extract_patch(unquoted),
        commands=commands,
    )
