"""Outbound report mail rendering (reference: dashboard/app
mail_bug.txt template + pkg/email formatting)."""

from __future__ import annotations

from email.message import EmailMessage

REPORT_FOOTER = """\
---
This bug report was generated automatically.
Reply to this email to communicate with the bot:

#syz fix: exact-commit-title         when the bug is fixed
#syz dup: exact-subject-of-another-report   to mark a duplicate
#syz invalid                          to close an invalid report
#syz test: git://repo/address.git branch    to test a patch
(attach the patch inline to the reply)
"""


def render_report(bug: dict, from_addr: str, to: list[str],
                  msg_id: str) -> bytes:
    """One bug report mail; msg_id threads all future replies back to
    the bug (reference: reporting.go sendMailReport)."""
    m = EmailMessage()
    m["Subject"] = bug["title"]
    m["From"] = from_addr
    m["To"] = ", ".join(to)
    m["Message-ID"] = msg_id
    body = [
        "Hello,",
        "",
        f"The fuzzer hit the following crash ({bug.get('num_crashes', 1)}"
        f" occurrences):",
        "",
        f"    {bug['title']}",
        "",
    ]
    if bug.get("repro_prog"):
        body += ["Reproducer program:", "", bug["repro_prog"], ""]
    if bug.get("report"):
        body += ["Console report:", "", bug["report"], ""]
    body.append(REPORT_FOOTER)
    m.set_content("\n".join(body))
    return bytes(m)
