"""Email reporting workflow (reference: pkg/email +
dashboard/app/reporting.go).

parse.py turns inbound mail into commands + patches, render.py
produces the syzbot-style bug report mails, reporting.py binds both to
the Dashboard's bug lifecycle (new -> reported -> fixed/invalid/dup,
plus '#syz test' patch jobs).
"""

from syzkaller_tpu.email.parse import Email, parse_email
from syzkaller_tpu.email.render import render_report
from syzkaller_tpu.email.reporting import EmailReporting, Mailbox

__all__ = ["Email", "parse_email", "render_report", "EmailReporting",
           "Mailbox"]
